"""Stdlib-only HTTP telemetry plane for the proving service.

Three read-only endpoints over `http.server.ThreadingHTTPServer` (no
third-party dependency — the container may not have a metrics stack,
and the endpoint must cost nothing when unused):

  /metrics   Prometheus text exposition (version 0.0.4) of the
             telemetry sampler's registry — `telemetry.*` time-series
             gauges (device memory, live buffers, queue depth, lane
             occupancy, in-flight count) plus any counters — with
             metric names sanitized to `boojum_tpu_*`.
  /healthz   liveness JSON: status, uptime, sampler tick count, plus
             whatever the owner's health callback reports (served /
             failed / queue depth for the proving service).
  /slo       the per-request SLO aggregation of `report.slo_summary`
             over the service's report artifact — the same numbers
             `scripts/prove_report.py --slo` prints, live.

The server binds 127.0.0.1 by default (scrape-agent posture; an
operator who wants it exposed passes host="0.0.0.0" explicitly) and
port 0 picks a free port — `start()` returns the bound one. Request
handling is threaded so a slow scrape never blocks the worker loop, and
every handler is exception-safe: a probe must never take the prover
down.
"""

from __future__ import annotations

import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")


def _prom_name(name: str, prefix: str = "boojum_tpu") -> str:
    return _NAME_RE.sub("_", f"{prefix}_{name}")


def prometheus_text(metrics: dict, prefix: str = "boojum_tpu") -> str:
    """Render a {counters: {...}, gauges: {...}} metrics dict (the
    MetricsRegistry.to_dict shape) as Prometheus text exposition."""
    lines: list[str] = []
    for kind, prom_type in (("counters", "counter"), ("gauges", "gauge")):
        for name, value in sorted((metrics.get(kind) or {}).items()):
            if not isinstance(value, (int, float)) or value != value:
                continue
            pname = _prom_name(name, prefix)
            lines.append(f"# TYPE {pname} {prom_type}")
            lines.append(f"{pname} {value}")
    return "\n".join(lines) + "\n" if lines else "\n"


class MetricsPlane:
    """One HTTP server exposing a telemetry sampler + owner callbacks.

    `sampler` provides the registry behind /metrics; `health_fn` and
    `slo_fn` are optional zero-arg callables returning JSON-able dicts
    for /healthz and /slo. All endpoints stay up (with partial data)
    when a callback raises — observability must degrade, not crash."""

    def __init__(
        self,
        sampler,
        health_fn=None,
        slo_fn=None,
        host: str = "127.0.0.1",
        port: int = 0,
    ):
        self.sampler = sampler
        self.health_fn = health_fn
        self.slo_fn = slo_fn
        self.host = host
        self.port = int(port)
        self._server: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None
        self._t0 = None

    # ---- lifecycle -------------------------------------------------------
    def start(self) -> int:
        """Bind + serve on a daemon thread; returns the bound port."""
        import time

        if self._server is not None:
            return self.port
        plane = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # quiet by default
                pass

            def _send(self, code: int, body: bytes, ctype: str):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 — http.server API
                try:
                    path = self.path.split("?", 1)[0].rstrip("/") or "/"
                    out = plane.handle_get(path)
                    if out is None:
                        self._send(404, b'{"error":"not found"}',
                                   "application/json")
                    else:
                        self._send(*out)
                except (BrokenPipeError, ConnectionError):
                    pass  # client went away: not a server error
                except Exception as e:  # noqa: BLE001 — a probe must
                    # never crash the serving process; the failure is
                    # counted (service.http.errors) and answered with a
                    # 500 body instead of a dropped connection
                    plane.count_error()
                    try:
                        self._send(
                            500,
                            json.dumps({"error": repr(e)}).encode(),
                            "application/json",
                        )
                    except Exception:
                        pass

        self._server = ThreadingHTTPServer((self.host, self.port), _Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._t0 = time.perf_counter()
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="boojum-metrics-http",
            daemon=True,
        )
        self._thread.start()
        return self.port

    def stop(self):
        srv = self._server
        if srv is None:
            return
        self._server = None
        srv.shutdown()
        srv.server_close()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
        self._thread = None

    def url(self, path: str = "") -> str:
        return f"http://{self.host}:{self.port}{path}"

    # ---- routing (shared with the gateway's composed server) -------------
    PROM_CTYPE = "text/plain; version=0.0.4; charset=utf-8"

    def handle_get(self, path: str):
        """Route one read-plane GET: (code, body_bytes, content_type) or
        None for an unknown path. The gateway (service/gateway.py)
        composes its write plane with this read plane by falling back
        here, so /metrics, /healthz and /slo are identical whether the
        plane runs standalone or under the gateway's server."""
        if path == "/metrics":
            return 200, self.render_metrics().encode(), self.PROM_CTYPE
        if path == "/healthz":
            return (
                200, json.dumps(self.render_health()).encode(),
                "application/json",
            )
        if path == "/slo":
            return (
                200, json.dumps(self.render_slo()).encode(),
                "application/json",
            )
        return None

    def count_error(self):
        """Charge one handler failure to the `service.http.errors`
        counter on the sampler's registry (rides /metrics as
        boojum_tpu_service_http_errors) — a 500 the operator can see
        beats a silently dropped connection."""
        try:
            self.sampler.registry.count("service.http.errors")
        except Exception:  # noqa: BLE001 — error accounting must never
            pass           # itself become the error

    # ---- endpoint bodies (pure, unit-testable without sockets) -----------
    def render_metrics(self) -> str:
        """Prometheus text of the sampler's registry MERGED with the
        process-global default metrics registry (ISSUE 12 satellite):
        the prove counters — `ici.*`, `limb.*`, `aot.*`, `quotient.*`,
        `fri.*`, `transfer.*`, `cost.*` — accumulate on the flight
        recorder's registry, not the sampler's, so without the merge
        /metrics only ever showed `telemetry.*`. Scoped (per-request)
        registries stay per-line by design; sampler values win a name
        collision (they are the fresher snapshot)."""
        merged: dict = {"counters": {}, "gauges": {}}
        try:
            from ..utils import metrics as _metrics

            # the process-global DEFAULT registry only: this handler
            # thread's context never carries a request-scoped registry,
            # and per-request collectors belong to their report lines
            reg = _metrics.current_registry()
            if reg is not None:
                snap = reg.to_dict()
                merged["counters"].update(snap.get("counters") or {})
                merged["gauges"].update(snap.get("gauges") or {})
        except Exception:  # noqa: BLE001 — a prove-registry probe must
            pass           # never take the metrics endpoint down
        snap = self.sampler.registry.to_dict()
        merged["counters"].update(snap.get("counters") or {})
        merged["gauges"].update(snap.get("gauges") or {})
        return prometheus_text(merged)

    def render_health(self) -> dict:
        import time

        out = {
            "status": "ok",
            "uptime_s": (
                round(time.perf_counter() - self._t0, 3)
                if self._t0 is not None else 0.0
            ),
            "telemetry_ticks": self.sampler.ticks,
            "telemetry_interval_s": self.sampler.interval_s,
        }
        if self.health_fn is not None:
            try:
                out.update(self.health_fn())
            except Exception as e:
                out["health_fn_error"] = repr(e)
        return out

    def render_slo(self) -> dict:
        if self.slo_fn is None:
            return {"requests": 0, "note": "no SLO source configured"}
        try:
            return self.slo_fn()
        except Exception as e:
            return {"requests": 0, "error": repr(e)}
