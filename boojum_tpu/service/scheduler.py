"""Placement scheduler: shard-parallel vs proof-parallel, per request.

DIZK's conclusion (PAPERS.md) is that distributed proving throughput is
a scheduling problem as much as a kernel problem: the same mesh can run
ONE proof spread across every chip (the PR 5 `shard_sweep` path —
minimum latency for a big trace, but collectives + per-chip variants for
work that may not fill the mesh) or MANY independent proofs packed one
per chip / sub-mesh (maximum throughput for small traces — zero
interconnect traffic, each chip runs the meshless kernel library).

The decision inputs are exactly what the admission queue exposes:

- **trace size**: a trace at/above `shard_threshold_rows` (default 2^17;
  `BOOJUM_TPU_SERVICE_SHARD_ROWS`) wants the whole mesh — a 2^20
  recursive job on one chip would monopolize it for the wall-clock the
  mesh exists to divide, and may not even fit one chip's HBM.
- **bucket occupancy**: several queued same-shape small jobs pack
  proof-parallel (they share one warmed meshless kernel library); even a
  LONE small job stays meshless — mesh collectives cost more than they
  parallelize at small n, and dispatching the `_sm` kernel variants
  would compile a second library for no win.

Packing is UNCONDITIONAL on the recording state: the flight recorder's
collectors are contextvars-scoped (ISSUE 9), so every packed request
records its own spans/metrics/checkpoints concurrently — the historical
"max_inflight > 1 requires recording off" restriction is gone.

`warm_for_placement` then warms exactly the kernel-library variant the
chosen placement dispatches (`precompile.enumerate_kernels(mesh_shape=)`
enumerates only the dispatched set), so admission-time compile work
never builds variants the prove won't run.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass

from ..utils.profiling import current_compile_ledger, log as _log
from ..utils.spans import span as _span

SHARD_PARALLEL = "shard_parallel"
PROOF_PARALLEL = "proof_parallel"
PLACEMENTS = (SHARD_PARALLEL, PROOF_PARALLEL)

DEFAULT_SHARD_THRESHOLD_ROWS = 1 << 17


@dataclass
class Placement:
    """One scheduling decision: how a request runs on the mesh."""

    kind: str                  # SHARD_PARALLEL | PROOF_PARALLEL
    mesh: object | None        # the Mesh a shard-parallel prove spans
    pack: int = 1              # proof-parallel: how many requests the
    #                            drain batch packs concurrently (1 = serial)
    total_devices: int = 1     # the service's chip count (occupancy
    #                            denominator — proof-parallel placements
    #                            carry mesh=None, so it rides here)
    reason: str = ""
    trace_id: str | None = None  # the trace this decision serves (set
    #                              when every request in the drain batch
    #                              shares one — ISSUE 17): batch-level
    #                              warm spans stamp it so the timeline
    #                              stitcher can join them to the trace

    @property
    def occupancy(self) -> float:
        """Fraction of the service's chips this placement lights up per
        proof — the per-request SLO record's occupancy field."""
        if self.kind == SHARD_PARALLEL:
            return 1.0
        return 1.0 / max(self.total_devices, 1)


def _mesh_devices(mesh) -> int:
    if mesh is None:
        return 1
    try:
        return int(mesh.devices.size)
    except Exception:
        return 1


def shard_threshold_rows() -> int:
    """BOOJUM_TPU_SERVICE_SHARD_ROWS: trace row count at/above which a
    request runs shard-parallel across the whole mesh (default 2^17)."""
    v = os.environ.get("BOOJUM_TPU_SERVICE_SHARD_ROWS", "").strip()
    if not v:
        return DEFAULT_SHARD_THRESHOLD_ROWS
    rows = int(v)
    if rows < 1:
        raise ValueError(
            f"BOOJUM_TPU_SERVICE_SHARD_ROWS={v!r}: must be >= 1"
        )
    return rows


def choose_placement(
    bucket,
    occupancy: int,
    mesh,
    max_inflight: int = 1,
    threshold_rows: int | None = None,
    trace_id: str | None = None,
) -> Placement:
    """Pick the placement for one request (or drain batch) of `bucket`.

    `occupancy` is the bucket's queued-request count (admission queue),
    `mesh` the service's mesh (None on a single chip — everything is
    proof-parallel then). `trace_id` threads the batch's propagated
    trace context through the decision (rides the Placement so the warm
    span downstream can stamp it)."""
    if threshold_rows is None:
        threshold_rows = shard_threshold_rows()
    n_dev = _mesh_devices(mesh)
    if mesh is not None and bucket.trace_len >= threshold_rows:
        return Placement(
            SHARD_PARALLEL, mesh, total_devices=n_dev,
            reason=(
                f"trace 2^{bucket.log_n} >= shard threshold "
                f"{threshold_rows} rows: one proof across {n_dev} chips"
            ),
            trace_id=trace_id,
        )
    pack = max(1, min(occupancy, max_inflight, n_dev))
    return Placement(
        PROOF_PARALLEL, None, pack=pack, total_devices=n_dev,
        reason=(
            f"trace 2^{bucket.log_n} below shard threshold; "
            f"bucket occupancy {occupancy}: meshless proofs"
            + (f" packed {pack}-wide" if pack > 1 else "")
        ),
        trace_id=trace_id,
    )


class VariantWarmer:
    """Warm exactly the kernel-library variant a placement dispatches.

    One warm per (bucket key, placement kind) per service lifetime:
    `precompile.enumerate_kernels(mesh_shape=)` derives the `_sm` set for
    shard-parallel placements and the meshless set otherwise, and
    `precompile()` pushes it through the persistent cache on a thread
    pool. `mode` = "full" (lower + backend compile), "lower" (trace-only
    — the CPU-test posture: validates enumeration, skips the compile
    bill), or "off".

    AOT artifacts: in "full" mode with BOOJUM_TPU_AOT_DIR set, the
    warmer first consults the artifact store (prover/aot.py) for the
    bundle matching (bucket, placement variant) — a hit installs +
    deserializes the pre-built executables (O(seconds)) instead of
    compiling; only on a miss does the warm fall back to the
    precompile sweep."""

    def __init__(self, mode: str = "full", max_workers: int = 8):
        if mode not in ("full", "lower", "off"):
            raise ValueError(
                f"precompile mode {mode!r}: use full | lower | off"
            )
        self.mode = mode
        self.max_workers = max_workers
        self._warmed: set[tuple] = set()

    def reset(self) -> int:
        """Forget every (bucket, placement) warm — the hot AOT-bundle
        reload verb (`POST /admin/reload-artifacts`): the next batch of
        each bucket re-runs the artifact-store consult + warm against
        whatever is in BOOJUM_TPU_AOT_DIR NOW, without dropping queued
        work. Returns how many warm keys were forgotten."""
        n = len(self._warmed)
        self._warmed.clear()
        return n

    def warm(self, bucket, assembly, config, placement: Placement) -> bool:
        if self.mode == "off":
            return False
        key = (bucket.key, placement.kind)
        if key in self._warmed:
            return False
        self._warmed.add(key)
        from ..prover.precompile import precompile

        mesh_shape = (
            placement.mesh if placement.kind == SHARD_PARALLEL else None
        )
        t0 = time.perf_counter()
        # batch-level work runs OUTSIDE any request's scoped recorder;
        # the explicit trace attr is how a warm span recorded by a
        # process-global recorder still joins the batch's trace in the
        # stitched timeline (report._timeline_line_events)
        warm_attrs = {"shape": bucket.key, "placement": placement.kind}
        if placement.trace_id:
            warm_attrs["trace"] = placement.trace_id
        with _span("service_warm_variant", **warm_attrs):
            aot_stats = None
            if self.mode == "full":
                from ..prover import aot as _aot

                root = _aot.aot_dir()
                if root is not None:
                    aot_stats = _aot.load_and_warm(
                        root, assembly, config, mesh_shape=mesh_shape,
                        ledger=current_compile_ledger(),
                    )
            if aot_stats is not None and aot_stats.get("aborted"):
                # systematic key mismatch: the serial warm bailed out —
                # the parallel sweep recompiles (warmed kernels re-hit)
                aot_stats = None
            if aot_stats is None:
                precompile(
                    assembly, config,
                    max_workers=self.max_workers,
                    ledger=current_compile_ledger(),
                    lower_only=self.mode == "lower",
                    mesh_shape=mesh_shape,
                )
        _log(
            f"service: warmed {placement.kind} variant of {bucket.key} "
            f"in {time.perf_counter() - t0:.1f}s "
            + (
                f"(aot: {aot_stats.get('aot_hits', '?')} artifact hits)"
                if aot_stats is not None
                else f"({self.mode})"
            )
        )
        return True
