"""Tenant model for the network admission plane (gateway.py).

Three concerns, deliberately tiny and stdlib-only:

- **TenantSpec**: one tenant's identity and entitlements — the shared
  secret `token` the gateway maps to a tenant id at the front door, the
  DRR `weight` the admission queue schedules the tenant at inside its
  lane, and the per-window byte/compute quotas the ledger enforces.
- **parse_tenant_specs**: the operator surface. Accepts either a JSON
  list (inline or `@file.json`) or the compact
  `id:token[:weight[:quota_bytes[:quota_compute_s]]]` comma form that
  fits in one env var (`BOOJUM_TPU_GATEWAY_TENANTS`).
- **QuotaLedger**: fixed-window byte + compute accounting, charged from
  the per-request flight-recorder records the service already produces
  (transfer counters + prove wall + proof bytes — PR 8 made these free).
  Exhaustion is a **429 + Retry-After** decision at admission, never a
  mid-prove kill: `admit()` answers before work is accepted, `charge()`
  settles after the prove so the NEXT window boundary is when an
  exhausted tenant gets service again. The ledger also feeds the
  `service.tenant.*` telemetry axis (snapshot() is registered as a
  sampler provider by the gateway, so per-tenant usage rides /metrics
  and every report line's `telemetry` record).

Quotas are per fixed window (default 60 s) rather than token-bucket:
a prover's unit of work is seconds long, so sub-window smoothing buys
nothing, and the fixed window gives an exact, explainable Retry-After.
"""

from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass


@dataclass(frozen=True)
class TenantSpec:
    """One tenant's identity + entitlements (see module docstring)."""

    id: str
    token: str
    weight: float = 1.0          # DRR quantum inside each lane (queue.py)
    quota_bytes: int | None = None       # per-window byte budget (None = ∞)
    quota_compute_s: float | None = None  # per-window prove-wall budget
    admin: bool = False          # may call the /admin/* verbs

    def __post_init__(self):
        if not self.id or not self.token:
            raise ValueError("tenant needs a non-empty id and token")
        if not (self.weight > 0):
            raise ValueError(
                f"tenant {self.id!r}: weight must be > 0, got {self.weight}"
            )


def parse_tenant_specs(text: str) -> list[TenantSpec]:
    """Parse the operator's tenant table (BOOJUM_TPU_GATEWAY_TENANTS).

    Forms:
      '@/path/tenants.json'      — JSON list loaded from a file
      '[{"id": ..., "token": ...}, ...]' — inline JSON list
      'id:token[:weight[:quota_bytes[:quota_compute_s]]],id2:tok2'
                                 — compact env-var form; an 'admin' flag
                                   rides as a trailing ':admin'
    """
    text = (text or "").strip()
    if not text:
        return []
    if text.startswith("@"):
        with open(text[1:]) as f:
            text = f.read().strip()
    if text.startswith("["):
        out = []
        for entry in json.loads(text):
            out.append(TenantSpec(
                id=entry["id"],
                token=entry["token"],
                weight=float(entry.get("weight", 1.0)),
                quota_bytes=(
                    None if entry.get("quota_bytes") is None
                    else int(entry["quota_bytes"])
                ),
                quota_compute_s=(
                    None if entry.get("quota_compute_s") is None
                    else float(entry["quota_compute_s"])
                ),
                admin=bool(entry.get("admin", False)),
            ))
        return out
    out = []
    for item in text.split(","):
        item = item.strip()
        if not item:
            continue
        parts = item.split(":")
        if len(parts) < 2:
            raise ValueError(
                f"tenant entry {item!r}: want id:token[:weight[...]]"
            )
        admin = False
        # the trailing flag is only a flag PAST the mandatory id:token
        # prefix — a tenant whose shared secret is literally "admin"
        # ('ops:admin') keeps its token
        if len(parts) > 2 and parts[-1].strip().lower() == "admin":
            admin = True
            parts = parts[:-1]
        tid, token = parts[0], parts[1]
        weight = float(parts[2]) if len(parts) > 2 and parts[2] else 1.0
        qb = int(parts[3]) if len(parts) > 3 and parts[3] else None
        qc = float(parts[4]) if len(parts) > 4 and parts[4] else None
        out.append(TenantSpec(
            id=tid, token=token, weight=weight,
            quota_bytes=qb, quota_compute_s=qc, admin=admin,
        ))
    return out


class QuotaLedger:
    """Fixed-window per-tenant byte + compute accounting.

    `admit()` is the 429 decision at the front door; `charge()` settles
    a served request's bill from its flight-recorder numbers. Unknown
    tenants (no spec) are unlimited but still metered, so the telemetry
    axis covers them too. All methods take an optional `now` (monotonic
    seconds) so window math is unit-testable without sleeping."""

    def __init__(self, specs=(), window_s: float = 60.0):
        if not (window_s > 0):
            raise ValueError(f"window_s must be > 0, got {window_s}")
        self.window_s = float(window_s)
        self._specs: dict[str, TenantSpec] = {s.id: s for s in specs}
        self._lock = threading.Lock()
        # tenant -> {"start": window_start, "bytes": int, "compute_s": f}
        self._usage: dict[str, dict] = {}
        self.throttled: dict[str, int] = {}

    def spec(self, tenant_id: str) -> TenantSpec | None:
        return self._specs.get(tenant_id)

    def _window(self, tenant_id: str, now: float) -> dict:
        # caller holds self._lock
        u = self._usage.get(tenant_id)
        if u is None or now - u["start"] >= self.window_s:
            u = {"start": now, "bytes": 0, "compute_s": 0.0}
            self._usage[tenant_id] = u
        return u

    def admit(self, tenant_id: str, now: float | None = None):
        """(ok, retry_after_s): may this tenant enqueue more work NOW?
        Exhausted -> (False, seconds until the window resets) and the
        rejection is tallied on `throttled` (the 429 count)."""
        now = time.monotonic() if now is None else now
        spec = self._specs.get(tenant_id)
        with self._lock:
            u = self._window(tenant_id, now)
            over = spec is not None and (
                (spec.quota_bytes is not None
                 and u["bytes"] >= spec.quota_bytes)
                or (spec.quota_compute_s is not None
                    and u["compute_s"] >= spec.quota_compute_s)
            )
            if not over:
                return True, 0.0
            self.throttled[tenant_id] = self.throttled.get(tenant_id, 0) + 1
            return False, max(0.0, u["start"] + self.window_s - now)

    def charge(
        self,
        tenant_id: str,
        nbytes: int,
        compute_s: float,
        now: float | None = None,
    ) -> dict:
        """Settle one served request's bill; returns the per-line
        `tenant` record (prove_report.py --check validates it)."""
        now = time.monotonic() if now is None else now
        nbytes = max(0, int(nbytes))
        compute_s = max(0.0, float(compute_s))
        with self._lock:
            u = self._window(tenant_id, now)
            u["bytes"] += nbytes
            u["compute_s"] += compute_s
            return {
                "id": tenant_id,
                "charged_bytes": nbytes,
                "charged_compute_s": round(compute_s, 6),
                "window_used_bytes": u["bytes"],
                "window_used_compute_s": round(u["compute_s"], 6),
            }

    def snapshot(self) -> dict:
        """Flat {<tenant>.<axis>: value} dict — registered as a sampler
        provider ('service.tenant') so per-tenant usage rides /metrics
        (`telemetry.service.tenant.*` gauges) and the report lines'
        `telemetry` records."""
        with self._lock:
            out: dict[str, float] = {}
            for tid, u in self._usage.items():
                out[f"{tid}.used_bytes"] = float(u["bytes"])
                out[f"{tid}.used_compute_s"] = round(u["compute_s"], 6)
            for tid, n in self.throttled.items():
                out[f"{tid}.throttled"] = float(n)
            return out
