"""Shape-bucketed admission queue: priority lanes, weighted-fair tenants.

Requests are grouped by the SAME shape-bucket key the precompile pass
and the compile ledger use (`prover/shape_key.py`) — same key means same
kernel library, shared domain/twiddle caches and a setup that can stay
device-resident across the batch. The scheduler reads bucket occupancy
to pick a placement (one big shard-parallel proof vs. packing
proof-parallel ones), so the queue's job is to keep same-shape work
adjacent without letting heavy lanes — or heavy TENANTS — starve the
rest.

Lanes are strict-priority: "interactive" drains before "batch" drains
before "bulk" (a recursive 2^20 aggregation job belongs in bulk; a
wallet-facing proof in interactive). WITHIN a lane, tenants are served
by **deficit round robin** (ISSUE 11): each tenant keeps a per-lane
deficit counter topped up by its configured weight as the round-robin
ring rotates past it, and a tenant is served only while its deficit
covers the work (one request = one unit). A tenant that drains a large
same-bucket batch borrows against its deficit (the counter goes
negative) and is skipped for proportionally many rounds after — the
debt survives even an emptied backlog while the lane stays contended
(only CREDIT dies with the backlog; all fairness state clears when the
whole lane goes idle) — so long-run service inside a lane converges to
the weight ratios no matter how bursty any one tenant is, while
same-shape batching (the warmed-state amortizer) is preserved. Per-tenant order is FIFO across buckets
and within a bucket; `pop_batch` gathers FOLLOWERS of the head's shape
bucket from the SAME (lane, tenant), so a drain amortizes warmed state
without reordering more than one batch deep.

Admission is bounded: above `capacity` the queue REJECTS
(`QueueFullError`) instead of buffering unboundedly — the caller sheds
load or retries, and the rejection is charged to the
`service.queue.rejects` counter. This is deliberate backpressure, not a
failure mode: an unbounded queue turns overload into latency for every
tenant, a bounded one turns it into an explicit signal for the few.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict

from ..utils import metrics as _metrics

# strict-priority lane order (drain left to right)
LANES = ("interactive", "batch", "bulk")

DEFAULT_WEIGHT = 1.0


class QueueFullError(RuntimeError):
    """Admission rejected: the bounded queue is at capacity (the
    backpressure signal — retry later or shed load)."""


class AdmissionQueue:
    def __init__(self, capacity: int = 64, weights: dict | None = None):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        # lane -> OrderedDict[tenant -> OrderedDict[bucket_key -> list]].
        # The tenant OrderedDict IS the DRR ring: its key order is the
        # round-robin rotation, its head the tenant currently in
        # service. Bucket order preserves FIFO across buckets (insertion
        # order) and within a bucket (list append order), per tenant.
        self._lanes: dict[str, OrderedDict] = {
            lane: OrderedDict() for lane in LANES
        }
        # (lane, tenant) -> DRR deficit (may go negative: borrowing)
        self._deficit: dict[tuple[str, str], float] = {}
        self.weights: dict[str, float] = {}
        for tenant, w in (weights or {}).items():
            self.set_weight(tenant, w)
        self._depth = 0
        self.rejects = 0
        self.admitted = 0
        # tenant -> served request count (fairness introspection)
        self.served: dict[str, int] = {}

    # ---- fairness configuration -----------------------------------------
    def set_weight(self, tenant: str, weight: float) -> None:
        """Configure a tenant's DRR weight (its per-rotation quantum,
        in requests). Unconfigured tenants weigh DEFAULT_WEIGHT."""
        if not (float(weight) > 0):
            raise ValueError(
                f"tenant {tenant!r}: weight must be > 0, got {weight}"
            )
        with self._lock:
            self.weights[tenant] = float(weight)

    def _quantum(self, tenant: str) -> float:
        return self.weights.get(tenant, DEFAULT_WEIGHT)

    @staticmethod
    def _tenant_of(request) -> str:
        return getattr(request, "tenant", None) or "default"

    # ---- admission -------------------------------------------------------
    def submit(self, request) -> None:
        """Admit one request (request.priority names the lane,
        request.bucket_key the shape bucket, request.tenant the DRR
        class — absent/empty means "default"). Raises QueueFullError at
        capacity."""
        lane = request.priority
        if lane not in self._lanes:
            raise ValueError(
                f"unknown priority lane {lane!r}: use one of {LANES}"
            )
        tenant = self._tenant_of(request)
        with self._lock:
            if self._depth >= self.capacity:
                self.rejects += 1
                _metrics.count("service.queue.rejects")
                raise QueueFullError(
                    f"admission queue at capacity ({self.capacity}); "
                    f"{self.rejects} rejects so far"
                )
            request.admit_ts = time.perf_counter()
            # trace-context admission stamp (ISSUE 17): the depth this
            # request queued BEHIND — the queue.wait span's key attr,
            # turning "the wait was long" into "the wait was long
            # because N requests were ahead"
            request.admit_depth = self._depth
            tenants = self._lanes[lane]
            if tenant not in tenants:
                # a newly-active tenant joins at the ring's TAIL with
                # zero deficit: no join-with-burst advantage
                tenants[tenant] = OrderedDict()
            buckets = tenants[tenant]
            if request.bucket_key not in buckets:
                buckets[request.bucket_key] = []
            buckets[request.bucket_key].append(request)
            self._depth += 1
            self.admitted += 1
            _metrics.gauge_service("queue.depth", self._depth)
            self._not_empty.notify()

    # ---- draining --------------------------------------------------------
    def _drr_pick(self, lane: str, tenants: OrderedDict) -> str:
        """The deficit-round-robin decision for one lane: rotate the
        tenant ring, topping each visited tenant's deficit up by its
        weight, until the head tenant can afford one request. Caller
        holds the lock. Terminates because every quantum is > 0 (a lone
        tenant still pays off any borrowed deficit here, a few rotations
        of its one-element ring, so joining competitors never face an
        incumbent with banked credit or unbounded debt)."""
        while True:
            tenant = next(iter(tenants))
            key = (lane, tenant)
            if self._deficit.get(key, 0.0) >= 1.0:
                return tenant
            self._deficit[key] = (
                self._deficit.get(key, 0.0) + self._quantum(tenant)
            )
            tenants.move_to_end(tenant)

    def pop_batch(self, limit: int | None = None) -> list:
        """Remove and return the DRR-chosen tenant's head request plus
        up to `limit - 1` same-bucket followers from the same (lane,
        tenant) — highest-priority nonempty lane first. The whole batch
        is charged against the tenant's deficit (which may go negative:
        a big batch is borrowed against future rounds). Empty list when
        the queue is empty."""
        with self._lock:
            for lane in LANES:
                tenants = self._lanes[lane]
                if not tenants:
                    continue
                tenant = self._drr_pick(lane, tenants)
                buckets = tenants[tenant]
                key, reqs = next(iter(buckets.items()))
                take = len(reqs) if limit is None else min(limit, len(reqs))
                batch = reqs[:take]
                del reqs[:take]
                if not reqs:
                    del buckets[key]
                dkey = (lane, tenant)
                self._deficit[dkey] = (
                    self._deficit.get(dkey, 0.0) - len(batch)
                )
                if not buckets:
                    del tenants[tenant]
                    # an idle tenant must not bank CREDIT while away —
                    # but borrowed DEBT survives the empty backlog, or a
                    # bursty tenant could drain a big batch, go briefly
                    # idle, and rejoin at zero to lap its siblings
                    # (resubmit-after-drain would evade the weight
                    # ratios entirely)
                    if self._deficit[dkey] >= 0.0:
                        del self._deficit[dkey]
                    if not tenants:
                        # the LANE going idle ends the contention the
                        # deficits arbitrate: clear its fairness state
                        # so a tenant returning much later isn't starved
                        # over debts nobody was waiting behind
                        for k in [
                            k for k in self._deficit if k[0] == lane
                        ]:
                            del self._deficit[k]
                self._depth -= len(batch)
                self.served[tenant] = (
                    self.served.get(tenant, 0) + len(batch)
                )
                _metrics.gauge_service("queue.depth", self._depth)
                return batch
            return []

    def wait_nonempty(self, timeout: float | None = None) -> bool:
        """Block until at least one request is queued (worker-loop idle
        wait); True when work is available."""
        with self._lock:
            if self._depth:
                return True
            return self._not_empty.wait_for(
                lambda: self._depth > 0, timeout=timeout
            )

    # ---- introspection (the scheduler's inputs) --------------------------
    def depth(self) -> int:
        with self._lock:
            return self._depth

    def occupancy(self, bucket_key: str) -> int:
        """How many queued requests share this shape bucket (across all
        lanes and tenants) — the scheduler's proof-parallel packing
        signal."""
        with self._lock:
            return sum(
                len(buckets.get(bucket_key, ()))
                for tenants in self._lanes.values()
                for buckets in tenants.values()
            )

    def bucket_depths(self) -> dict[str, int]:
        """bucket_key -> queued request count, across lanes/tenants."""
        with self._lock:
            out: dict[str, int] = {}
            for tenants in self._lanes.values():
                for buckets in tenants.values():
                    for key, reqs in buckets.items():
                        out[key] = out.get(key, 0) + len(reqs)
            return out

    def lane_depths(self) -> dict[str, int]:
        """lane -> queued request count — the telemetry sampler's lane
        occupancy axis (utils/telemetry.py): a bulk lane filling while
        interactive stays drained is healthy, the reverse is an SLO
        fire."""
        with self._lock:
            return {
                lane: sum(
                    len(reqs)
                    for buckets in tenants.values()
                    for reqs in buckets.values()
                )
                for lane, tenants in self._lanes.items()
            }

    def tenant_depths(self) -> dict[str, int]:
        """tenant -> queued request count across lanes — the fairness
        axis of the telemetry plane (gateway dashboards watch a heavy
        tenant's backlog grow while its siblings stay drained)."""
        with self._lock:
            out: dict[str, int] = {}
            for tenants in self._lanes.values():
                for tenant, buckets in tenants.items():
                    out[tenant] = out.get(tenant, 0) + sum(
                        len(reqs) for reqs in buckets.values()
                    )
            return out
