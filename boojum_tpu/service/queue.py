"""Shape-bucketed admission queue with priority lanes and backpressure.

Requests are grouped by the SAME shape-bucket key the precompile pass
and the compile ledger use (`prover/shape_key.py`) — same key means same
kernel library, shared domain/twiddle caches and a setup that can stay
device-resident across the batch. The scheduler reads bucket occupancy
to pick a placement (one big shard-parallel proof vs. packing
proof-parallel ones), so the queue's job is to keep same-shape work
adjacent without letting heavy lanes starve interactive ones.

Lanes are strict-priority: "interactive" drains before "batch" drains
before "bulk" (a recursive 2^20 aggregation job belongs in bulk; a
wallet-facing proof in interactive). Within a lane, order is FIFO —
except that `pop_batch` gathers FOLLOWERS of the head's shape bucket
from the SAME lane, so a drain amortizes warmed state across every
queued same-shape request without reordering across buckets more than
one batch deep.

Admission is bounded: above `capacity` the queue REJECTS
(`QueueFullError`) instead of buffering unboundedly — the caller sheds
load or retries, and the rejection is charged to the
`service.queue.rejects` counter. This is deliberate backpressure, not a
failure mode: an unbounded queue turns overload into latency for every
tenant, a bounded one turns it into an explicit signal for the few.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict

from ..utils import metrics as _metrics

# strict-priority lane order (drain left to right)
LANES = ("interactive", "batch", "bulk")


class QueueFullError(RuntimeError):
    """Admission rejected: the bounded queue is at capacity (the
    backpressure signal — retry later or shed load)."""


class AdmissionQueue:
    def __init__(self, capacity: int = 64):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        # lane -> OrderedDict[bucket_key -> list[request]] preserves both
        # FIFO order across buckets (insertion order of the OrderedDict)
        # and within a bucket (list append order)
        self._lanes: dict[str, OrderedDict] = {
            lane: OrderedDict() for lane in LANES
        }
        self._depth = 0
        self.rejects = 0
        self.admitted = 0

    # ---- admission -------------------------------------------------------
    def submit(self, request) -> None:
        """Admit one request (request.priority names the lane,
        request.bucket_key the shape bucket). Raises QueueFullError at
        capacity."""
        lane = request.priority
        if lane not in self._lanes:
            raise ValueError(
                f"unknown priority lane {lane!r}: use one of {LANES}"
            )
        with self._lock:
            if self._depth >= self.capacity:
                self.rejects += 1
                _metrics.count("service.queue.rejects")
                raise QueueFullError(
                    f"admission queue at capacity ({self.capacity}); "
                    f"{self.rejects} rejects so far"
                )
            request.admit_ts = time.perf_counter()
            buckets = self._lanes[lane]
            if request.bucket_key not in buckets:
                buckets[request.bucket_key] = []
            buckets[request.bucket_key].append(request)
            self._depth += 1
            self.admitted += 1
            _metrics.gauge_service("queue.depth", self._depth)
            self._not_empty.notify()

    # ---- draining --------------------------------------------------------
    def pop_batch(self, limit: int | None = None) -> list:
        """Remove and return the head request plus up to `limit - 1`
        same-bucket followers from the head's lane (highest-priority
        nonempty lane first). Empty list when the queue is empty."""
        with self._lock:
            for lane in LANES:
                buckets = self._lanes[lane]
                if not buckets:
                    continue
                key, reqs = next(iter(buckets.items()))
                take = len(reqs) if limit is None else min(limit, len(reqs))
                batch = reqs[:take]
                del reqs[:take]
                if not reqs:
                    del buckets[key]
                self._depth -= len(batch)
                _metrics.gauge_service("queue.depth", self._depth)
                return batch
            return []

    def wait_nonempty(self, timeout: float | None = None) -> bool:
        """Block until at least one request is queued (worker-loop idle
        wait); True when work is available."""
        with self._lock:
            if self._depth:
                return True
            return self._not_empty.wait_for(
                lambda: self._depth > 0, timeout=timeout
            )

    # ---- introspection (the scheduler's inputs) --------------------------
    def depth(self) -> int:
        with self._lock:
            return self._depth

    def occupancy(self, bucket_key: str) -> int:
        """How many queued requests share this shape bucket (across all
        lanes) — the scheduler's proof-parallel packing signal."""
        with self._lock:
            return sum(
                len(buckets.get(bucket_key, ()))
                for buckets in self._lanes.values()
            )

    def bucket_depths(self) -> dict[str, int]:
        """bucket_key -> queued request count, across lanes."""
        with self._lock:
            out: dict[str, int] = {}
            for buckets in self._lanes.values():
                for key, reqs in buckets.items():
                    out[key] = out.get(key, 0) + len(reqs)
            return out

    def lane_depths(self) -> dict[str, int]:
        """lane -> queued request count — the telemetry sampler's lane
        occupancy axis (utils/telemetry.py): a bulk lane filling while
        interactive stays drained is healthy, the reverse is an SLO
        fire."""
        with self._lock:
            return {
                lane: sum(len(reqs) for reqs in buckets.values())
                for lane, buckets in self._lanes.items()
            }
