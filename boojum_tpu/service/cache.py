"""Device-resident cache manager: pin amortizable state across requests.

ICICLE's deployment model (PAPERS.md) keeps setup/twiddle/table state
device-resident across proof requests instead of rebuilding per proof;
this manager is that layer for the proving service. Two classes of
state, treated differently because they free differently:

- **Per-setup residency** (the big, evictable items): the sigma column
  stack, grand-product x powers, non-residues and lookup tables that
  `prover._dev_cached` parks on the setup/assembly objects (~8·Ct·n
  bytes for sigma alone — ~0.5 GB at 2^20). The manager holds the only
  long-lived references, measures ACTUAL resident bytes from the
  `_dev_cache` dicts after each request, and evicts least-recently-used
  entries (clearing those dicts, so the buffers free and the next
  request re-uploads on miss) when the byte cap is exceeded.
- **Per-geometry tables** (small, global, shared): twiddle/domain
  contexts (`ntt.warm_domain_caches`), brev-domain constants and FRI
  fold/1-over-x tables live in module `lru_cache`s keyed by
  (log_n, rate) — already shared by every same-shape request and not
  individually evictable. The manager WARMS them at admission (so the
  first request of a bucket pays the build outside a transcript
  barrier) and reports their estimated footprint, but the byte cap
  applies only to the evictable class.

Hits/misses/evictions are charged through
`utils.metrics.count_service_cache` (`service.cache.*`), pinned bytes to
the `service.cache.pinned_bytes` gauge — the `prove_report.py --check`
gate validates the schema.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field

from ..utils import metrics as _metrics
from ..utils.profiling import log as _log


def _dev_cache_bytes(obj) -> int:
    """Actual resident bytes of one host object's `_dev_cache` (the
    prover's device-upload cache seam)."""
    cache = getattr(obj, "_dev_cache", None)
    if not cache:
        return 0
    total = 0
    for v in cache.values():
        for leaf in v if isinstance(v, (tuple, list)) else (v,):
            try:
                total += int(leaf.size) * leaf.dtype.itemsize
            except Exception:
                pass
    return total


@dataclass
class PinnedEntry:
    """One pinned (assembly, setup) residency, keyed by the request's
    shape-bucket key plus the setup's identity (two different circuits
    can share a shape bucket but never a setup)."""

    bucket_key: str
    assembly: object
    setup: object
    bytes: int = 0
    hits: int = 0
    pinned_ts: float = field(default_factory=time.perf_counter)

    def measure(self) -> int:
        self.bytes = _dev_cache_bytes(self.setup) + _dev_cache_bytes(
            self.assembly
        )
        return self.bytes

    def release(self):
        """Drop the device residency: clearing the `_dev_cache` dicts
        releases the manager's references so the buffers free; the next
        prove of this setup transparently re-uploads (a cache MISS, not
        an error)."""
        for obj in (self.setup, self.assembly):
            cache = getattr(obj, "_dev_cache", None)
            if cache:
                cache.clear()


class DeviceCacheManager:
    """Byte-capped LRU over pinned per-setup device residency, plus
    geometry-table warming. Thread-safe; all accounting no-op-cheap when
    no metrics registry is installed."""

    def __init__(self, capacity_bytes: int = 2 << 30):
        self.capacity_bytes = int(capacity_bytes)
        self._lock = threading.Lock()
        # key -> PinnedEntry, most-recently-used LAST
        self._entries: OrderedDict[tuple, PinnedEntry] = OrderedDict()
        self._warmed_geometries: set[tuple] = set()
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.evicted_bytes = 0

    # ---- geometry tables -------------------------------------------------
    def warm_geometry(self, bucket) -> bool:
        """Populate the per-geometry transform caches of one shape bucket
        (twiddles for rates L and Q, domain constants, FRI fold tables) —
        idempotent and enqueue-only, exactly the set the prover's round-0
        prefetch touches. Returns True when this call did the warming."""
        from ..field.spec import active_field, is_babybear

        key = (
            bucket.log_n, bucket.lde_factor, bucket.quotient_degree,
            bucket.fri_final_degree, bucket.fri_schedule, bucket.lookups,
            # field backend (ISSUE 20): a geometry warmed under goldilocks
            # holds u64 twiddles — the same bucket under babybear needs
            # its own u32 table set, so the field is part of the key
            active_field(),
        )
        with self._lock:
            if key in self._warmed_geometries:
                return False
            self._warmed_geometries.add(key)
        if is_babybear():
            # the babybear full prover (prover/prover_bb.py) consumes the
            # plane-free u32 table set — bb_ntt twiddles/scale tables at
            # trace size and both full-domain rates, the coset domain
            # constants, and the FRI fold-challenge tables; warm exactly
            # that set, nothing limb- or u64-shaped
            from ..field import babybear as _bb
            from ..ntt import bb_ntt as BN
            from ..prover import bb_kernels as BK
            from ..prover import stages_bb as SBB

            shift = int(_bb.SPEC.multiplicative_generator)
            log_L = bucket.lde_factor.bit_length() - 1
            log_Q = bucket.quotient_degree.bit_length() - 1
            for lg in (
                bucket.log_n, bucket.log_n + log_L, bucket.log_n + log_Q,
            ):
                BN._twiddles(lg, False)
                BN._twiddles(lg, True)
            BN._lde_scale_table(bucket.log_n, bucket.lde_factor, shift)
            BN._lde_scale_table(bucket.log_n, bucket.quotient_degree, shift)
            BK.domain_xs_bb(bucket.log_n, bucket.lde_factor, shift)
            BK.domain_xs_bb(bucket.log_n, bucket.quotient_degree, shift)
            BK.zh_inv_bb(bucket.log_n, bucket.quotient_degree, shift)
            SBB.l0_lde_bb(bucket.log_n, bucket.quotient_degree, shift)
            log_full = bucket.log_n + log_L
            num_rounds = (
                bucket.trace_len // bucket.fri_final_degree
            ).bit_length() - 1
            if num_rounds >= 1:
                BK.fri_fold_tables_bb(log_full, shift, num_rounds)
            return True
        from ..prover.pallas_sweep import limb_resident_enabled

        if limb_resident_enabled():
            # the resident prove consumes the PLANE table set (ISSUE 10)
            # — warm exactly what it will touch, nothing u64
            from ..prover import resident as RES
            from ..ntt import limb_ntt as LN
            from ..field import gl as _gl

            # plane twiddle contexts for trace size and both full-domain
            # rates (the warm_domain_caches twin)
            LN.PlaneNTTContext(bucket.log_n)
            LN.PlaneNTTContext(
                bucket.log_n + (bucket.lde_factor.bit_length() - 1)
            )
            LN.PlaneNTTContext(
                bucket.log_n + (bucket.quotient_degree.bit_length() - 1)
            )
            RES.domain_xs_brev_p(bucket.log_n, bucket.lde_factor)
            RES.domain_xs_brev_p(bucket.log_n, bucket.quotient_degree)
            RES.l0_brev_p(bucket.log_n, bucket.quotient_degree)
            RES.vanishing_inv_brev_p(bucket.log_n, bucket.quotient_degree)
            RES.omega_powers_p(bucket.log_n)
            LN._lde_scale_planes(
                bucket.log_n, bucket.lde_factor,
                int(_gl.MULTIPLICATIVE_GENERATOR),
            )
            LN._lde_scale_planes(
                bucket.log_n, bucket.quotient_degree,
                int(_gl.MULTIPLICATIVE_GENERATOR),
            )
            if bucket.lookups:
                RES.inv_xs_brev_p(bucket.log_n, bucket.lde_factor)
            from ..prover.fri import fold_challenge_tables_p, fold_schedule

            log_full = bucket.log_n + (bucket.lde_factor.bit_length() - 1)
            num_folds = sum(
                fold_schedule(
                    bucket.trace_len, bucket.fri_final_degree,
                    list(bucket.fri_schedule) or None,
                )
            )
            fold_challenge_tables_p(log_full, num_folds)
            return True
        from ..ntt.ntt import warm_domain_caches
        from ..prover.fri import fold_challenge_tables, fold_schedule
        from ..prover.prover import _inv_xs_brev

        warm_domain_caches(bucket.log_n, bucket.lde_factor)
        warm_domain_caches(bucket.log_n, bucket.quotient_degree)
        if bucket.lookups:
            _inv_xs_brev(bucket.log_n, bucket.lde_factor)
        log_full = bucket.log_n + (bucket.lde_factor.bit_length() - 1)
        num_folds = sum(
            fold_schedule(
                bucket.trace_len, bucket.fri_final_degree,
                list(bucket.fri_schedule) or None,
            )
        )
        fold_challenge_tables(log_full, num_folds)
        return True

    # ---- per-setup residency --------------------------------------------
    def pin(self, bucket_key: str, assembly, setup) -> bool:
        """Mark one (assembly, setup) pair resident for the request being
        served. Returns True on a HIT (this setup was already pinned —
        its device buffers survive from an earlier request); False on a
        MISS (newly pinned; the prove will upload into the residency).
        Accounting goes to service.cache.hits/misses."""
        key = (bucket_key, id(setup))
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                entry.hits += 1
                self._entries.move_to_end(key)
                self.hits += 1
                hit = True
            else:
                self._entries[key] = PinnedEntry(bucket_key, assembly, setup)
                self.misses += 1
                hit = False
        _metrics.count_service_cache("hit" if hit else "miss")
        return hit

    def after_request(self):
        """Re-measure resident bytes (uploads happen DURING the prove,
        so sizes are only known afterwards) and evict LRU entries above
        the byte cap. Called by the worker loop after each request."""
        evicted: list[PinnedEntry] = []
        with self._lock:
            total = 0
            for entry in self._entries.values():
                total += entry.measure()
            while total > self.capacity_bytes and len(self._entries) > 1:
                _key, entry = self._entries.popitem(last=False)
                total -= entry.bytes
                if entry.bytes > 0:
                    # a zero-byte entry holds no residency (e.g. its
                    # request failed before uploading) — dropping it is
                    # not an EVICTION, and counting one with a zero byte
                    # gauge would fail the report validator's
                    # evictions-imply-evicted-bytes consistency check
                    self.evictions += 1
                    self.evicted_bytes += entry.bytes
                evicted.append(entry)
            pinned = total
        for entry in evicted:
            # released OUTSIDE the lock: freeing device buffers can call
            # into the backend
            if entry.bytes > 0:
                _metrics.count_service_cache("evict", entry.bytes)
                _log(
                    f"service cache: evicted {entry.bucket_key} "
                    f"({entry.bytes / 2**20:.1f} MiB, {entry.hits} hits)"
                )
            entry.release()
        _metrics.gauge_service("cache.pinned_bytes", pinned)
        return pinned

    # ---- introspection ---------------------------------------------------
    def pinned_bytes(self) -> int:
        with self._lock:
            return sum(e.bytes for e in self._entries.values())

    def stats(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._entries),
                "pinned_bytes": sum(
                    e.bytes for e in self._entries.values()
                ),
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "evicted_bytes": self.evicted_bytes,
                "warmed_geometries": len(self._warmed_geometries),
            }
