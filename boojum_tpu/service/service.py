"""ProvingService: the multi-tenant batch-proving layer over the mesh.

The repo's perf work (PRs 1-5) made one `prove()` fast; this service
makes MANY of them cheap by owning the mesh and amortizing everything
amortizable across requests:

- admission through the shape-bucketed, priority-laned, bounded queue
  (`service/queue.py` — backpressure via QueueFullError);
- device-resident caches pinned across requests with byte-capped LRU
  eviction (`service/cache.py`);
- per-batch placement between shard-parallel (one proof across the
  whole mesh, the PR 5 shard_map path) and proof-parallel (independent
  meshless proofs, packable one-per-chip), with the kernel-library
  variant of the CHOSEN placement warmed through the precompile pass
  (`service/scheduler.py`);
- per-request SLO records — queue latency, prove wall, placement,
  occupancy, proofs/sec, plus the full flight-recorder axis (spans,
  `ici.*` bytes, digest checkpoints) — appended as ProveReport JSONL
  lines that `scripts/prove_report.py --check/--slo` validate.

Proof bytes and digest-checkpoint streams are bit-identical to direct
`prove()` calls regardless of placement: the service only picks WHICH
validated execution mode runs (meshless vs. the PR 5 mesh path, both
pinned bit-identical by tests/test_mesh_parity.py) and never touches
the transcript.

Concurrency contract: requests are served one batch at a time by ONE
worker loop (the mesh is one resource). Proof-parallel packing runs up
to `max_inflight` same-bucket requests concurrently on distinct chips,
RECORDING INCLUDED: each packed request binds its own contextvars-scoped
flight recorder (utils/report.flight_recording(scoped=True)) on its pool
thread, so every request — packed or sequential — gets a complete
ProveReport line with its own spans, counters and digest-checkpoint
stream, and interleaved recording can no longer corrupt a neighbor's.
Cross-host proof-parallelism composes through
`parallel.multihost.distribute_proofs` (see scripts/multihost_worker).

Live telemetry plane (ISSUE 9): a background sampler
(utils/telemetry.py) snapshots device memory, the live-buffer census,
queue depth / lane occupancy and the in-flight count on a fixed cadence,
and `run_worker` exposes its registry over a stdlib HTTP endpoint
(service/http_metrics.py: /metrics Prometheus text, /healthz, /slo)
when `metrics_port` is configured. Per-request `capture_trace=True`
(or an armed BOOJUM_TPU_XPROF budget) records a jax.profiler trace
attributable to the request via the report line's `trace` record.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field

from ..prover.shape_key import shape_bucket
from ..utils import metrics as _metrics
from ..utils import report as _report
from ..utils import spans as _spans
from ..utils.profiling import log as _log
from ..utils.spans import span as _span
from .cache import DeviceCacheManager
from .queue import LANES, AdmissionQueue, QueueFullError  # noqa: F401
from .scheduler import (
    PROOF_PARALLEL,
    SHARD_PARALLEL,
    Placement,
    VariantWarmer,
    choose_placement,
)

REQUEST_SCHEMA = 1


def _env_int(name: str, default: int) -> int:
    v = os.environ.get(name, "").strip()
    if not v:
        return default
    try:
        return int(float(v))
    except ValueError:
        raise ValueError(f"{name}={v!r}: not a number") from None


@dataclass
class ServiceConfig:
    """Knobs of one ProvingService (env: BOOJUM_TPU_SERVICE_*)."""

    queue_capacity: int = 64       # BOOJUM_TPU_SERVICE_QUEUE_CAP
    cache_bytes: int = 2 << 30     # BOOJUM_TPU_SERVICE_CACHE_BYTES
    max_inflight: int = 1          # BOOJUM_TPU_SERVICE_MAX_INFLIGHT
    # kernel-library warm mode per (bucket, placement):
    #   full = lower + backend compile (production), lower = trace only
    #   (CPU-test posture), off = compile at first dispatch
    precompile: str = "full"       # BOOJUM_TPU_SERVICE_PRECOMPILE
    # shard threshold rides BOOJUM_TPU_SERVICE_SHARD_ROWS (scheduler.py)
    shard_threshold_rows: int | None = None
    report_path: str | None = None  # default: BOOJUM_TPU_REPORT
    mesh: object | str | None = "auto"  # "auto" | Mesh | None (meshless)
    # live telemetry plane: None = no HTTP endpoint; 0 = any free port
    # (bound port comes back from start_telemetry / the worker loop log)
    metrics_port: int | None = None   # BOOJUM_TPU_SERVICE_METRICS_PORT
    # sampler cadence; None = BOOJUM_TPU_TELEMETRY_INTERVAL (default 1s)
    telemetry_interval_s: float | None = None

    @classmethod
    def from_env(cls) -> "ServiceConfig":
        port = _env_int("BOOJUM_TPU_SERVICE_METRICS_PORT", -1)
        return cls(
            queue_capacity=_env_int("BOOJUM_TPU_SERVICE_QUEUE_CAP", 64),
            cache_bytes=_env_int(
                "BOOJUM_TPU_SERVICE_CACHE_BYTES", 2 << 30
            ),
            max_inflight=_env_int("BOOJUM_TPU_SERVICE_MAX_INFLIGHT", 1),
            precompile=os.environ.get(
                "BOOJUM_TPU_SERVICE_PRECOMPILE", ""
            ).strip().lower() or "full",
            metrics_port=None if port < 0 else port,
        )


@dataclass
class ProveRequest:
    """One admitted proving job. `result()` blocks for the proof; the
    `slo` dict mirrors the request record the report line carries."""

    assembly: object
    setup: object
    config: object
    id: str
    priority: str = "batch"
    tenant: str = "default"
    capture_trace: bool = False    # record a jax.profiler trace of the
    #                                prove (report line carries the dir)
    gateway: bool = False          # admitted over HTTP (service/gateway.py):
    #                                the report line must carry a tenant
    #                                record (--check enforces it)
    bucket: object = None          # ShapeBucket, stamped at submit
    bucket_key: str = ""
    submit_ts: float = 0.0
    admit_ts: float = 0.0
    admit_depth: int = 0           # queue depth waited behind (queue.py)
    trace: dict | None = None      # propagated trace context (ISSUE 17):
    #                                {"trace_id", "parent_span_id"?} —
    #                                minted at submit unless the gateway
    #                                handed one down
    proof: object = None
    error: BaseException | None = None
    slo: dict = field(default_factory=dict)
    _done: threading.Event = field(default_factory=threading.Event)

    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: float | None = None):
        if not self._done.wait(timeout):
            raise TimeoutError(f"request {self.id} still queued/running")
        if self.error is not None:
            raise self.error
        return self.proof


class ProvingService:
    def __init__(self, config: ServiceConfig | None = None):
        import jax

        self.config = config or ServiceConfig.from_env()
        self.queue = AdmissionQueue(self.config.queue_capacity)
        self.cache = DeviceCacheManager(self.config.cache_bytes)
        self.warmer = VariantWarmer(self.config.precompile)
        self.devices = list(jax.devices())
        mesh = self.config.mesh
        if mesh == "auto":
            # one process, >1 chip: own the whole mesh. Multi-process
            # deployments keep per-host services meshless and scale
            # proof-parallel across hosts (multihost.distribute_proofs);
            # a DCN-spanning shard-parallel mesh is opt-in via an
            # explicit Mesh (e.g. multihost.hybrid_mesh()).
            multi = False
            try:
                multi = jax.process_count() > 1
            except Exception:
                pass
            if len(self.devices) > 1 and not multi:
                from ..parallel.sharding import make_mesh

                mesh = make_mesh(self.devices)
            else:
                mesh = None
        self.mesh = mesh
        self.report_path = (
            self.config.report_path
            if self.config.report_path is not None
            else _report.default_report_path()
        )
        self._ids = itertools.count(1)
        self._serve_lock = threading.Lock()
        # packed requests append report lines from pool threads; one
        # writer at a time keeps the JSONL artifact line-atomic
        self._report_lock = threading.Lock()
        self._inflight = 0
        # live telemetry plane: sampler built eagerly (providers close
        # over the queue/stats), started by run_worker/start_telemetry
        from ..utils import telemetry as _telemetry

        self.sampler = _telemetry.TelemetrySampler(
            interval_s=self.config.telemetry_interval_s
        )
        self.sampler.add_provider("service.queue.depth", self.queue.depth)
        self.sampler.add_provider(
            "service.queue.lane", self.queue.lane_depths
        )
        self.sampler.add_provider(
            "service.inflight", lambda: self._inflight
        )
        self.sampler.add_provider(
            "service.cache.pinned_bytes",
            lambda: self.cache.stats().get("pinned_bytes", 0),
        )
        self.sampler.add_provider(
            "service.queue.tenant", self.queue.tenant_depths
        )
        # roofline attribution of the most recent prove (ISSUE 12):
        # per-stage achieved GFLOP/s / GB/s / efficiency ride /metrics
        # and the telemetry record as telemetry.cost.* gauges
        from ..utils import costmodel as _costmodel

        self.sampler.add_provider("cost", _costmodel.telemetry_provider)
        # per-tenant byte/compute quota accounting (tenant.QuotaLedger);
        # installed by the gateway — None keeps in-process submit()
        # admission unmetered, exactly as before ISSUE 11
        self.quota = None
        self.metrics_plane = None
        self._owns_sampler_install = False
        # service-lifetime prove-counter registry: per-request scoped
        # recorders are torn down with their report line, so /metrics
        # would never show the ici./limb./aot./quotient./fri./transfer./
        # cost. families without an accumulator the plane's merge can
        # read (installed as the process-global default by
        # start_telemetry; _serve_one folds each request in)
        from ..utils import metrics as _metrics

        self.prove_registry = _metrics.MetricsRegistry()
        self._owns_registry_install = False
        # packed proof-parallel mode mutates these from pool threads
        self._stats_lock = threading.Lock()
        self.stats = {
            "served": 0,
            "failed": 0,
            "batches": 0,
            "placements": {SHARD_PARALLEL: 0, PROOF_PARALLEL: 0},
            "prove_wall_s": 0.0,
            "queue_latency_s": 0.0,
        }

    # ---- admission -------------------------------------------------------
    def submit(
        self,
        assembly,
        setup,
        config,
        priority: str = "batch",
        tenant: str = "default",
        request_id: str | None = None,
        capture_trace: bool = False,
        gateway: bool = False,
        trace: dict | None = None,
    ) -> ProveRequest:
        """Admit one job (raises QueueFullError at the queue bound —
        the caller's backpressure signal). Shape bucketing happens here,
        with the SAME key the precompile pass and compile ledger use.
        `capture_trace=True` records a jax.profiler trace of this
        request's prove (profiling.maybe_trace_capture); the trace dir
        rides the request's report line and SLO record."""
        req = ProveRequest(
            assembly=assembly,
            setup=setup,
            config=config,
            id=request_id or f"req-{next(self._ids):04d}",
            priority=priority,
            tenant=tenant,
            capture_trace=capture_trace,
            gateway=gateway,
        )
        # trace context from admission onward (ISSUE 17): adopt the
        # caller's context (the gateway minted one at POST /prove, the
        # fleet worker read one from its spool file) or mint a fresh
        # root trace — every request is stitchable either way
        if isinstance(trace, dict) and _spans.valid_trace_id(
            trace.get("trace_id")
        ):
            req.trace = {"trace_id": trace["trace_id"]}
            psid = trace.get("parent_span_id")
            if _spans.valid_span_id(psid):
                req.trace["parent_span_id"] = psid
        else:
            req.trace = {"trace_id": _spans.new_trace_id()}
        req.bucket = shape_bucket(assembly, config)
        req.bucket_key = req.bucket.key
        req.submit_ts = time.perf_counter()
        self.queue.submit(req)  # stamps admit_ts + admit_depth
        return req

    # ---- serving ---------------------------------------------------------
    def process_once(self) -> int:
        """Drain ONE same-bucket batch: schedule, warm, prove, record.
        Returns the number of requests served (0 = queue empty)."""
        with self._serve_lock:
            batch = self.queue.pop_batch(
                limit=max(self.config.max_inflight, 1)
                if self.config.max_inflight > 1
                else None
            )
            if not batch:
                return 0
            return self._serve_batch(batch)

    def run_worker(
        self, stop: threading.Event | None = None, idle_wait_s: float = 0.0
    ) -> dict:
        """The worker loop: drain the queue until empty (idle_wait_s=0)
        or until `stop` is set (a serving daemon passes idle_wait_s > 0
        to block for new work). Returns the service stats summary.

        Starts the live telemetry plane for the loop's lifetime: the
        background sampler always runs (its samples ride every report
        line as the `telemetry` record), and with `metrics_port`
        configured the HTTP endpoint serves /metrics, /healthz and /slo
        while the loop drains. Components the caller already started
        (start_telemetry) are left running on exit; anything THIS call
        started — including an endpoint bound over a caller-started
        sampler — is stopped (start_telemetry is idempotent per
        component, ownership is tracked per component too)."""
        owns_sampler = not self.sampler.running()
        had_plane = self.metrics_plane is not None
        self.start_telemetry(self.config.metrics_port)
        # black-box forensics (ISSUE 15): with BOOJUM_TPU_BLACKBOX /
        # BOOJUM_TPU_STALL_S armed, a wedged worker loop dumps
        # all-thread stacks into the report artifact instead of idling
        # silently until the pod is recycled
        try:
            from ..utils import blackbox as _blackbox

            _blackbox.ensure_started(
                label="service_worker", report_path=self.report_path
            )
            _blackbox.set_phase("service_worker")
        except Exception:
            pass
        t0 = time.perf_counter()
        try:
            while stop is None or not stop.is_set():
                served = self.process_once()
                if served:
                    continue
                if idle_wait_s <= 0:
                    break
                self.queue.wait_nonempty(timeout=idle_wait_s)
                if (
                    not self.queue.depth()
                    and stop is not None
                    and stop.is_set()
                ):
                    break
            return self.summary(wall_s=time.perf_counter() - t0)
        finally:
            if owns_sampler:
                self.stop_telemetry()
            elif not had_plane and self.metrics_plane is not None:
                # the caller owned the sampler but WE bound the
                # endpoint: release the port, keep their sampler
                self.metrics_plane.stop()
                self.metrics_plane = None

    # ---- telemetry plane -------------------------------------------------
    def start_telemetry(
        self,
        metrics_port: int | None = None,
        sampler_only: bool = False,
    ) -> int | None:
        """Start the background sampler (installed process-wide so
        report lines pick up the `telemetry` record) and, with a port
        (0 = any free port; None falls back to the config's
        metrics_port), the HTTP metrics plane. `sampler_only=True`
        never binds a standalone plane regardless of the config port —
        the gateway posture, where /metrics rides the composed server.
        Returns the bound port or None. Idempotent; a bind failure logs
        and degrades to sampler-only — observability must never take
        the prover down."""
        from ..utils import telemetry as _telemetry

        if metrics_port is None:
            metrics_port = self.config.metrics_port
        if sampler_only:
            metrics_port = None
        if not self.sampler.running():
            # only adopt the process-wide slot if nobody else (a bench
            # harness, another service) owns it
            if _telemetry.current_sampler() is None:
                _telemetry.install_sampler(self.sampler)
                self._owns_sampler_install = True
            self.sampler.start()
        # same adoption rule for the default metrics registry: the
        # plane's /metrics merge reads current_registry(), and unrecorded
        # requests (no report_path) then count straight into it
        from ..utils import metrics as _metrics

        if _metrics.current_registry() is None:
            _metrics.install_registry(self.prove_registry)
            self._owns_registry_install = True
        if metrics_port is not None and self.metrics_plane is None:
            from .http_metrics import MetricsPlane

            plane = MetricsPlane(
                self.sampler,
                health_fn=self._telemetry_health,
                slo_fn=self._telemetry_slo,
                port=metrics_port,
            )
            try:
                port = plane.start()
            except Exception as e:  # noqa: BLE001 — e.g. EADDRINUSE;
                # leave metrics_plane None so a later call can retry
                _log(
                    f"service: telemetry endpoint failed to bind "
                    f":{metrics_port}: {e!r} (sampler stays up)"
                )
                return None
            self.metrics_plane = plane
            _log(
                f"service: telemetry plane up on :{port} "
                f"(/metrics /healthz /slo)"
            )
            return port
        return (
            self.metrics_plane.port if self.metrics_plane is not None
            else None
        )

    def stop_telemetry(self):
        """Stop the sampler + HTTP plane (idempotent)."""
        from ..utils import telemetry as _telemetry

        if self.metrics_plane is not None:
            self.metrics_plane.stop()
            self.metrics_plane = None
        self.sampler.stop()
        if self._owns_sampler_install:
            if _telemetry.current_sampler() is self.sampler:
                _telemetry.install_sampler(None)
            self._owns_sampler_install = False
        if self._owns_registry_install:
            from ..utils import metrics as _metrics

            if _metrics.current_registry() is self.prove_registry:
                _metrics.install_registry(None)
            self._owns_registry_install = False

    def _telemetry_health(self) -> dict:
        with self._stats_lock:
            served = self.stats["served"]
            failed = self.stats["failed"]
            inflight = self._inflight
        return {
            "served": served,
            "failed": failed,
            "inflight": inflight,
            "queue_depth": self.queue.depth(),
            "queue_rejects": self.queue.rejects,
        }

    def _telemetry_slo(self) -> dict:
        """The /slo endpoint body: report.slo_summary over this
        service's report artifact (live view of what
        `prove_report.py --slo` prints post-hoc). Memoized on the
        artifact's (size, mtime): a scrape agent polling at 1 Hz must
        not re-parse an ever-growing JSONL file on every probe."""
        if not self.report_path or not os.path.exists(self.report_path):
            return {"requests": 0, "note": "no report artifact yet"}
        st = os.stat(self.report_path)
        key = (st.st_size, st.st_mtime_ns)
        cached = getattr(self, "_slo_cache", None)
        if cached is not None and cached[0] == key:
            return cached[1]
        summary = _report.slo_summary(
            _report.load_reports(self.report_path)
        )
        self._slo_cache = (key, summary)
        return summary

    # ---- internals -------------------------------------------------------
    def _serve_batch(self, batch: list) -> int:
        bucket = batch[0].bucket
        occupancy = len(batch) + self.queue.occupancy(bucket.key)
        # the batch's trace context reaches the placement decision when
        # every request shares one trace (the common single-request
        # drain; a mixed batch stays trace-less at batch level — each
        # request still records under its own trace)
        batch_tids = {
            (req.trace or {}).get("trace_id") for req in batch
        }
        batch_tid = (
            batch_tids.pop() if len(batch_tids) == 1 else None
        )
        placement = choose_placement(
            bucket,
            occupancy,
            self.mesh,
            max_inflight=self.config.max_inflight,
            threshold_rows=self.config.shard_threshold_rows,
            trace_id=batch_tid,
        )
        _log(
            f"service: batch of {len(batch)} x {bucket.key} -> "
            f"{placement.kind} ({placement.reason})"
        )
        # warm OUTSIDE the per-request recording window: compile bill is
        # service state, not a request's SLO (the ledger keeps per-shape
        # attribution), and the geometry tables are bucket-level
        self.warmer.warm(bucket, batch[0].assembly, batch[0].config,
                         placement)
        self.cache.warm_geometry(bucket)

        pack = placement.pack if placement.kind == PROOF_PARALLEL else 1
        batch_t0 = time.perf_counter()
        if pack > 1 and len(batch) > 1:
            # packing no longer cares about the recording state: each
            # packed request scopes its own flight-recorder collectors
            # via contextvars (_serve_one), so concurrent requests
            # record complete, disjoint report lines
            served = self._serve_packed(batch, placement)
        else:
            served = 0
            for req in batch:
                served += self._serve_one(req, placement)
        batch_wall = time.perf_counter() - batch_t0
        with self._stats_lock:
            self.stats["batches"] += 1
            self.stats["placements"][placement.kind] += len(batch)
        if served and batch_wall > 0:
            _metrics.gauge_service(
                "batch_proofs_per_sec", served / batch_wall
            )
        self.cache.after_request()
        return served

    def _serve_one(
        self,
        req: ProveRequest,
        placement: Placement,
        packed: int = 1,
        device=None,
    ) -> int:
        """Serve one request with full flight recording when a report
        path is configured. The collectors are contextvars-SCOPED, so
        packed siblings running this concurrently on pool threads each
        record their own complete line. (A bare BOOJUM_TPU_REPORT was
        already resolved into self.report_path at construction —
        __init__ via default_report_path — so the service's scoped path
        owns recording and prove()'s process-global fallback never
        fires under packing.)"""
        path = self.report_path
        # bind the request's propagated trace to THIS execution context
        # before any recorder exists: the scoped SpanRecorder the
        # flight_recording below constructs adopts it, so the line's
        # trace_ctx and every span id chain back to the gateway's
        # admission span (ISSUE 17)
        trace_tok = _spans.set_inbound_trace(req.trace)
        try:
            if not path:
                ok = self._run_request(req, placement, packed=packed,
                                       device=device)
                # quota is settled even without a report artifact — a
                # metered tenant's window must fill either way
                self._charge_quota(req)
                return ok
            with _report.flight_recording(
                label=f"service:{req.id}", scoped=True
            ) as rec:
                # the queue.wait span (ISSUE 17 satellite): the
                # admission→dispatch gap as a REAL backdated span, not
                # just the queue_latency_s scalar — recorded here, not
                # in _run_request, so it anchors the line even when the
                # prove itself fails early
                if req.admit_ts:
                    sp = rec.spans.open(
                        "queue.wait",
                        start_at=req.admit_ts,
                        request=req.id,
                        lane=req.priority,
                        depth=req.admit_depth,
                    )
                    rec.spans.close(sp)
                try:
                    ok = self._run_request(req, placement, packed=packed,
                                           device=device)
                finally:
                    # the request record rides the ProveReport line even
                    # when the prove raised — a failed request's partial
                    # spans + SLO fields are the post-mortem
                    try:
                        extra = {"request": dict(req.slo)}
                        tenant_rec = self._charge_quota(req, rec)
                        if tenant_rec is not None:
                            extra["tenant"] = tenant_rec
                        line = _report.build_report(rec, extra=extra)
                        # the request line must carry THIS service's time
                        # series (queue depth, lane occupancy, in-flight) —
                        # build_report read the process-global sampler slot,
                        # which a bench harness may own with a provider-less
                        # sampler of its own. Only rebuild when the slot is
                        # foreign/empty; in the normal posture build_report
                        # already snapshotted this very sampler.
                        from ..utils import telemetry as _telemetry

                        if (
                            self.sampler.ticks
                            and _telemetry.current_sampler()
                            is not self.sampler
                        ):
                            line["telemetry"] = self.sampler.snapshot()
                        with self._report_lock:
                            _report.append_jsonl(path, line)
                    except Exception as e:  # noqa: BLE001 — recording must
                        # never turn a served proof into a failure
                        _log(f"service: report write failed: {e!r}")
                    try:
                        # the scoped registry dies with this block: fold it
                        # into the service-lifetime one so /metrics keeps
                        # the prove counter families
                        self.prove_registry.fold(rec.metrics)
                    except Exception:  # noqa: BLE001
                        pass
        finally:
            _spans.reset_inbound_trace(trace_tok)
        return ok

    def _charge_quota(self, req: ProveRequest, rec=None) -> dict | None:
        """Settle one request's per-tenant quota bill (tenant.QuotaLedger,
        installed by the gateway) from the numbers the flight recorder
        already collected: explicit host<->device transfer bytes plus the
        serialized proof size on the byte axis, prove wall on the compute
        axis. Returns the per-line `tenant` record, or None when the
        service is unmetered. Charging must never fail a served proof."""
        if self.quota is None:
            return None
        try:
            nbytes = 0
            if rec is not None:
                counters = rec.metrics.to_dict().get("counters") or {}
                nbytes += int(counters.get("transfer.h2d_bytes", 0))
                nbytes += int(counters.get("transfer.d2h_bytes", 0))
            if req.proof is not None:
                try:
                    nbytes += len(req.proof.to_json())
                except Exception:  # noqa: BLE001
                    pass
            compute_s = req.slo.get("prove_wall_s") or 0.0
            return self.quota.charge(req.tenant, nbytes, compute_s)
        except Exception as e:  # noqa: BLE001
            _log(f"service: quota charge failed for {req.id}: {e!r}")
            return None

    def _serve_packed(self, batch: list, placement: Placement) -> int:
        """Proof-parallel packing: same-bucket requests run concurrently,
        each pinned to its own chip via jax.default_device, each with its
        own contextvars-scoped flight recorder (so per-request report
        lines are written exactly as in the sequential path)."""
        devices = (
            list(self.mesh.devices.ravel()) if self.mesh is not None
            else self.devices
        )
        width = min(placement.pack, len(batch), len(devices))

        def run(i_req):
            i, req = i_req
            return self._serve_one(
                req, placement, packed=width, device=devices[i % width]
            )

        with ThreadPoolExecutor(max_workers=width) as pool:
            served = sum(pool.map(run, enumerate(batch)))
        return served

    def _run_request(
        self,
        req: ProveRequest,
        placement: Placement,
        packed: int = 1,
        device=None,
    ) -> int:
        import contextlib

        from ..prover.prover import prove
        from ..utils import profiling as _prof

        serve_ts = time.perf_counter()
        queue_latency = serve_ts - req.submit_ts
        hit = self.cache.pin(req.bucket_key, req.assembly, req.setup)
        _metrics.gauge_service("occupancy", placement.occupancy)
        req.slo = {
            "schema": REQUEST_SCHEMA,
            "id": req.id,
            "tenant": req.tenant,
            "priority": req.priority,
            "bucket": req.bucket_key,
            "placement": placement.kind,
            "packed": packed,
            "occupancy": round(placement.occupancy, 4),
            "queue_latency_s": round(queue_latency, 6),
            "cache_hit": hit,
        }
        if isinstance(req.trace, dict) and req.trace.get("trace_id"):
            req.slo["trace_id"] = req.trace["trace_id"]
        if req.gateway:
            # gateway-admitted: --check requires the line to carry a
            # tenant record alongside this flag
            req.slo["gateway"] = True
        if device is not None:
            import jax

            device_ctx = jax.default_device(device)
        else:
            device_ctx = contextlib.nullcontext()
        with self._stats_lock:
            self._inflight += 1
        _metrics.gauge_service("inflight", self._inflight)
        t0 = time.perf_counter()
        try:
            with _span(
                "service_request", request=req.id, placement=placement.kind
            ), _prof.maybe_trace_capture(
                f"req_{req.id}", force=req.capture_trace
            ) as trace_dir, device_ctx:
                if trace_dir:
                    req.slo["trace_dir"] = trace_dir
                    rec = _report.current_flight_recorder()
                    if rec is not None:
                        rec.trace_dir = trace_dir
                proof = prove(
                    req.assembly, req.setup, req.config,
                    mesh=placement.mesh,
                )
                wall = time.perf_counter() - t0
        except BaseException as e:
            req.error = e
            req.slo["error"] = repr(e)
            req.slo["prove_wall_s"] = round(
                time.perf_counter() - t0, 6
            )
            with self._stats_lock:
                self._inflight -= 1
                self.stats["failed"] += 1
                self.stats["queue_latency_s"] += queue_latency
            req._done.set()
            if isinstance(e, (KeyboardInterrupt, SystemExit)):
                raise
            _log(f"service: request {req.id} failed: {e!r}")
            return 0
        req.proof = proof
        req.slo["prove_wall_s"] = round(wall, 6)
        req.slo["proofs_per_sec"] = round(packed / wall, 6) if wall else None
        with self._stats_lock:
            self._inflight -= 1
            self.stats["served"] += 1
            self.stats["prove_wall_s"] += wall
            self.stats["queue_latency_s"] += queue_latency
        req._done.set()
        return 1

    # ---- reporting -------------------------------------------------------
    def summary(self, wall_s: float | None = None) -> dict:
        with self._stats_lock:
            stats = dict(self.stats)
        served = stats["served"]
        out = {
            "served": served,
            "failed": stats["failed"],
            "batches": stats["batches"],
            "placements": dict(stats["placements"]),
            "queue": {
                "depth": self.queue.depth(),
                "admitted": self.queue.admitted,
                "rejects": self.queue.rejects,
                "capacity": self.queue.capacity,
            },
            "cache": self.cache.stats(),
            "mean_prove_wall_s": (
                round(stats["prove_wall_s"] / served, 4)
                if served else None
            ),
            "mean_queue_latency_s": (
                round(stats["queue_latency_s"] / served, 4)
                if served else None
            ),
        }
        if wall_s is not None:
            out["wall_s"] = round(wall_s, 4)
            if served and wall_s > 0:
                out["proofs_per_sec"] = round(served / wall_s, 4)
        out["telemetry"] = {
            "ticks": self.sampler.ticks,
            "interval_s": self.sampler.interval_s,
            "metrics_port": (
                self.metrics_plane.port
                if self.metrics_plane is not None else None
            ),
        }
        return out
