"""ProvingService: the multi-tenant batch-proving layer over the mesh.

The repo's perf work (PRs 1-5) made one `prove()` fast; this service
makes MANY of them cheap by owning the mesh and amortizing everything
amortizable across requests:

- admission through the shape-bucketed, priority-laned, bounded queue
  (`service/queue.py` — backpressure via QueueFullError);
- device-resident caches pinned across requests with byte-capped LRU
  eviction (`service/cache.py`);
- per-batch placement between shard-parallel (one proof across the
  whole mesh, the PR 5 shard_map path) and proof-parallel (independent
  meshless proofs, packable one-per-chip), with the kernel-library
  variant of the CHOSEN placement warmed through the precompile pass
  (`service/scheduler.py`);
- per-request SLO records — queue latency, prove wall, placement,
  occupancy, proofs/sec, plus the full flight-recorder axis (spans,
  `ici.*` bytes, digest checkpoints) — appended as ProveReport JSONL
  lines that `scripts/prove_report.py --check/--slo` validate.

Proof bytes and digest-checkpoint streams are bit-identical to direct
`prove()` calls regardless of placement: the service only picks WHICH
validated execution mode runs (meshless vs. the PR 5 mesh path, both
pinned bit-identical by tests/test_mesh_parity.py) and never touches
the transcript.

Concurrency contract: requests are served one batch at a time by ONE
worker loop (the mesh is one resource). Proof-parallel packing runs up
to `max_inflight` same-bucket requests concurrently on distinct chips
— but only when flight recording is OFF, because the recorder's
span/metrics/checkpoint collectors are process-global and interleaved
recording would corrupt the per-request checkpoint streams; with
recording on, packing degrades to sequential (the SLO record notes
`packed: 1`). Cross-host proof-parallelism composes through
`parallel.multihost.distribute_proofs` (see scripts/multihost_worker).
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field

from ..prover.shape_key import shape_bucket
from ..utils import metrics as _metrics
from ..utils import report as _report
from ..utils.profiling import log as _log
from ..utils.spans import span as _span
from .cache import DeviceCacheManager
from .queue import LANES, AdmissionQueue, QueueFullError  # noqa: F401
from .scheduler import (
    PROOF_PARALLEL,
    SHARD_PARALLEL,
    Placement,
    VariantWarmer,
    choose_placement,
)

REQUEST_SCHEMA = 1


def _env_int(name: str, default: int) -> int:
    v = os.environ.get(name, "").strip()
    if not v:
        return default
    try:
        return int(float(v))
    except ValueError:
        raise ValueError(f"{name}={v!r}: not a number") from None


@dataclass
class ServiceConfig:
    """Knobs of one ProvingService (env: BOOJUM_TPU_SERVICE_*)."""

    queue_capacity: int = 64       # BOOJUM_TPU_SERVICE_QUEUE_CAP
    cache_bytes: int = 2 << 30     # BOOJUM_TPU_SERVICE_CACHE_BYTES
    max_inflight: int = 1          # BOOJUM_TPU_SERVICE_MAX_INFLIGHT
    # kernel-library warm mode per (bucket, placement):
    #   full = lower + backend compile (production), lower = trace only
    #   (CPU-test posture), off = compile at first dispatch
    precompile: str = "full"       # BOOJUM_TPU_SERVICE_PRECOMPILE
    # shard threshold rides BOOJUM_TPU_SERVICE_SHARD_ROWS (scheduler.py)
    shard_threshold_rows: int | None = None
    report_path: str | None = None  # default: BOOJUM_TPU_REPORT
    mesh: object | str | None = "auto"  # "auto" | Mesh | None (meshless)

    @classmethod
    def from_env(cls) -> "ServiceConfig":
        return cls(
            queue_capacity=_env_int("BOOJUM_TPU_SERVICE_QUEUE_CAP", 64),
            cache_bytes=_env_int(
                "BOOJUM_TPU_SERVICE_CACHE_BYTES", 2 << 30
            ),
            max_inflight=_env_int("BOOJUM_TPU_SERVICE_MAX_INFLIGHT", 1),
            precompile=os.environ.get(
                "BOOJUM_TPU_SERVICE_PRECOMPILE", ""
            ).strip().lower() or "full",
        )


@dataclass
class ProveRequest:
    """One admitted proving job. `result()` blocks for the proof; the
    `slo` dict mirrors the request record the report line carries."""

    assembly: object
    setup: object
    config: object
    id: str
    priority: str = "batch"
    tenant: str = "default"
    bucket: object = None          # ShapeBucket, stamped at submit
    bucket_key: str = ""
    submit_ts: float = 0.0
    admit_ts: float = 0.0
    proof: object = None
    error: BaseException | None = None
    slo: dict = field(default_factory=dict)
    _done: threading.Event = field(default_factory=threading.Event)

    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: float | None = None):
        if not self._done.wait(timeout):
            raise TimeoutError(f"request {self.id} still queued/running")
        if self.error is not None:
            raise self.error
        return self.proof


class ProvingService:
    def __init__(self, config: ServiceConfig | None = None):
        import jax

        self.config = config or ServiceConfig.from_env()
        self.queue = AdmissionQueue(self.config.queue_capacity)
        self.cache = DeviceCacheManager(self.config.cache_bytes)
        self.warmer = VariantWarmer(self.config.precompile)
        self.devices = list(jax.devices())
        mesh = self.config.mesh
        if mesh == "auto":
            # one process, >1 chip: own the whole mesh. Multi-process
            # deployments keep per-host services meshless and scale
            # proof-parallel across hosts (multihost.distribute_proofs);
            # a DCN-spanning shard-parallel mesh is opt-in via an
            # explicit Mesh (e.g. multihost.hybrid_mesh()).
            multi = False
            try:
                multi = jax.process_count() > 1
            except Exception:
                pass
            if len(self.devices) > 1 and not multi:
                from ..parallel.sharding import make_mesh

                mesh = make_mesh(self.devices)
            else:
                mesh = None
        self.mesh = mesh
        self.report_path = (
            self.config.report_path
            if self.config.report_path is not None
            else _report.default_report_path()
        )
        self._ids = itertools.count(1)
        self._serve_lock = threading.Lock()
        # packed proof-parallel mode mutates these from pool threads
        self._stats_lock = threading.Lock()
        self.stats = {
            "served": 0,
            "failed": 0,
            "batches": 0,
            "placements": {SHARD_PARALLEL: 0, PROOF_PARALLEL: 0},
            "prove_wall_s": 0.0,
            "queue_latency_s": 0.0,
        }

    # ---- admission -------------------------------------------------------
    def submit(
        self,
        assembly,
        setup,
        config,
        priority: str = "batch",
        tenant: str = "default",
        request_id: str | None = None,
    ) -> ProveRequest:
        """Admit one job (raises QueueFullError at the queue bound —
        the caller's backpressure signal). Shape bucketing happens here,
        with the SAME key the precompile pass and compile ledger use."""
        req = ProveRequest(
            assembly=assembly,
            setup=setup,
            config=config,
            id=request_id or f"req-{next(self._ids):04d}",
            priority=priority,
            tenant=tenant,
        )
        req.bucket = shape_bucket(assembly, config)
        req.bucket_key = req.bucket.key
        req.submit_ts = time.perf_counter()
        self.queue.submit(req)  # stamps admit_ts
        return req

    # ---- serving ---------------------------------------------------------
    def process_once(self) -> int:
        """Drain ONE same-bucket batch: schedule, warm, prove, record.
        Returns the number of requests served (0 = queue empty)."""
        with self._serve_lock:
            batch = self.queue.pop_batch(
                limit=max(self.config.max_inflight, 1)
                if self.config.max_inflight > 1
                else None
            )
            if not batch:
                return 0
            return self._serve_batch(batch)

    def run_worker(
        self, stop: threading.Event | None = None, idle_wait_s: float = 0.0
    ) -> dict:
        """The worker loop: drain the queue until empty (idle_wait_s=0)
        or until `stop` is set (a serving daemon passes idle_wait_s > 0
        to block for new work). Returns the service stats summary."""
        t0 = time.perf_counter()
        while stop is None or not stop.is_set():
            served = self.process_once()
            if served:
                continue
            if idle_wait_s <= 0:
                break
            self.queue.wait_nonempty(timeout=idle_wait_s)
            if (
                not self.queue.depth()
                and stop is not None
                and stop.is_set()
            ):
                break
        return self.summary(wall_s=time.perf_counter() - t0)

    # ---- internals -------------------------------------------------------
    def _serve_batch(self, batch: list) -> int:
        bucket = batch[0].bucket
        occupancy = len(batch) + self.queue.occupancy(bucket.key)
        placement = choose_placement(
            bucket,
            occupancy,
            self.mesh,
            max_inflight=self.config.max_inflight,
            threshold_rows=self.config.shard_threshold_rows,
        )
        _log(
            f"service: batch of {len(batch)} x {bucket.key} -> "
            f"{placement.kind} ({placement.reason})"
        )
        # warm OUTSIDE the per-request recording window: compile bill is
        # service state, not a request's SLO (the ledger keeps per-shape
        # attribution), and the geometry tables are bucket-level
        self.warmer.warm(bucket, batch[0].assembly, batch[0].config,
                         placement)
        self.cache.warm_geometry(bucket)

        recording = bool(self.report_path) or bool(
            os.environ.get("BOOJUM_TPU_REPORT")
        )
        pack = placement.pack if placement.kind == PROOF_PARALLEL else 1
        batch_t0 = time.perf_counter()
        if pack > 1 and len(batch) > 1 and not recording:
            served = self._serve_packed(batch, placement)
        else:
            if pack > 1:
                # recording ON: the flight recorder's collectors are
                # process-global, so packing degrades to sequential to
                # keep per-request checkpoint streams uncorrupted
                placement = Placement(
                    placement.kind, placement.mesh, pack=1,
                    total_devices=placement.total_devices,
                    reason=placement.reason + " (sequential: recording on)",
                )
            served = 0
            for req in batch:
                served += self._serve_one(req, placement)
        batch_wall = time.perf_counter() - batch_t0
        with self._stats_lock:
            self.stats["batches"] += 1
            self.stats["placements"][placement.kind] += len(batch)
        if served and batch_wall > 0:
            _metrics.gauge_service(
                "batch_proofs_per_sec", served / batch_wall
            )
        self.cache.after_request()
        return served

    def _serve_one(self, req: ProveRequest, placement: Placement) -> int:
        """Serve one request sequentially, with full flight recording
        when a report path is configured."""
        if not self.report_path:
            return self._run_request(req, placement)
        with _report.flight_recording(label=f"service:{req.id}") as rec:
            try:
                ok = self._run_request(req, placement)
            finally:
                # the request record rides the ProveReport line even
                # when the prove raised — a failed request's partial
                # spans + SLO fields are the post-mortem
                try:
                    _report.append_jsonl(
                        self.report_path,
                        _report.build_report(
                            rec, extra={"request": dict(req.slo)}
                        ),
                    )
                except Exception as e:  # noqa: BLE001 — recording must
                    # never turn a served proof into a failure
                    _log(f"service: report write failed: {e!r}")
        return ok

    def _serve_packed(self, batch: list, placement: Placement) -> int:
        """Proof-parallel packing: same-bucket requests run concurrently,
        each pinned to its own chip via jax.default_device. Only reached
        with recording off (see class docstring), so no report lines are
        written; each request's `slo` dict still carries its SLO fields."""
        import jax

        devices = (
            list(self.mesh.devices.ravel()) if self.mesh is not None
            else self.devices
        )
        width = min(placement.pack, len(batch), len(devices))

        def run(i_req):
            i, req = i_req
            with jax.default_device(devices[i % width]):
                return self._run_request(req, placement, packed=width)

        with ThreadPoolExecutor(max_workers=width) as pool:
            served = sum(pool.map(run, enumerate(batch)))
        return served

    def _run_request(
        self, req: ProveRequest, placement: Placement, packed: int = 1
    ) -> int:
        from ..prover.prover import prove

        serve_ts = time.perf_counter()
        queue_latency = serve_ts - req.submit_ts
        hit = self.cache.pin(req.bucket_key, req.assembly, req.setup)
        _metrics.gauge_service("occupancy", placement.occupancy)
        req.slo = {
            "schema": REQUEST_SCHEMA,
            "id": req.id,
            "tenant": req.tenant,
            "priority": req.priority,
            "bucket": req.bucket_key,
            "placement": placement.kind,
            "packed": packed,
            "occupancy": round(placement.occupancy, 4),
            "queue_latency_s": round(queue_latency, 6),
            "cache_hit": hit,
        }
        t0 = time.perf_counter()
        try:
            with _span(
                "service_request", request=req.id, placement=placement.kind
            ):
                proof = prove(
                    req.assembly, req.setup, req.config,
                    mesh=placement.mesh,
                )
                wall = time.perf_counter() - t0
        except BaseException as e:
            req.error = e
            req.slo["error"] = repr(e)
            req.slo["prove_wall_s"] = round(
                time.perf_counter() - t0, 6
            )
            with self._stats_lock:
                self.stats["failed"] += 1
                self.stats["queue_latency_s"] += queue_latency
            req._done.set()
            if isinstance(e, (KeyboardInterrupt, SystemExit)):
                raise
            _log(f"service: request {req.id} failed: {e!r}")
            return 0
        req.proof = proof
        req.slo["prove_wall_s"] = round(wall, 6)
        req.slo["proofs_per_sec"] = round(packed / wall, 6) if wall else None
        with self._stats_lock:
            self.stats["served"] += 1
            self.stats["prove_wall_s"] += wall
            self.stats["queue_latency_s"] += queue_latency
        req._done.set()
        return 1

    # ---- reporting -------------------------------------------------------
    def summary(self, wall_s: float | None = None) -> dict:
        with self._stats_lock:
            stats = dict(self.stats)
        served = stats["served"]
        out = {
            "served": served,
            "failed": stats["failed"],
            "batches": stats["batches"],
            "placements": dict(stats["placements"]),
            "queue": {
                "depth": self.queue.depth(),
                "admitted": self.queue.admitted,
                "rejects": self.queue.rejects,
                "capacity": self.queue.capacity,
            },
            "cache": self.cache.stats(),
            "mean_prove_wall_s": (
                round(stats["prove_wall_s"] / served, 4)
                if served else None
            ),
            "mean_queue_latency_s": (
                round(stats["queue_latency_s"] / served, 4)
                if served else None
            ),
        }
        if wall_s is not None:
            out["wall_s"] = round(wall_s, 4)
            if served and wall_s > 0:
                out["proofs_per_sec"] = round(served / wall_s, 4)
        return out
