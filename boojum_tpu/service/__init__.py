"""Proving-as-a-service: the multi-tenant batch scheduler over the mesh.

Public surface:

- `ProvingService` / `ServiceConfig` / `ProveRequest` — the service
  itself (service.py): shape-bucketed admission, device-resident cache
  manager, shard-vs-proof-parallel placement, per-request SLO records.
- `AdmissionQueue` / `QueueFullError` / `LANES` — the bounded priority
  queue (queue.py).
- `DeviceCacheManager` — byte-capped LRU over pinned device state
  (cache.py).
- `choose_placement` / `Placement` / `SHARD_PARALLEL` / `PROOF_PARALLEL`
  — the scheduler (scheduler.py).
- `MetricsPlane` — the stdlib HTTP telemetry endpoint
  (http_metrics.py: /metrics Prometheus text, /healthz, /slo), started
  by the worker loop when `ServiceConfig.metrics_port` is set.

Driver CLI: `scripts/prove_service.py`; bench integration:
`bench.py --service`.
"""

from .cache import DeviceCacheManager
from .http_metrics import MetricsPlane
from .queue import LANES, AdmissionQueue, QueueFullError
from .scheduler import (
    PROOF_PARALLEL,
    SHARD_PARALLEL,
    Placement,
    choose_placement,
)
from .service import ProveRequest, ProvingService, ServiceConfig

__all__ = [
    "AdmissionQueue",
    "DeviceCacheManager",
    "LANES",
    "MetricsPlane",
    "Placement",
    "PROOF_PARALLEL",
    "ProveRequest",
    "ProvingService",
    "QueueFullError",
    "SHARD_PARALLEL",
    "ServiceConfig",
    "choose_placement",
]
