"""Proving-as-a-service: the multi-tenant batch scheduler over the mesh.

Public surface:

- `ProvingService` / `ServiceConfig` / `ProveRequest` — the service
  itself (service.py): shape-bucketed admission, device-resident cache
  manager, shard-vs-proof-parallel placement, per-request SLO records.
- `AdmissionQueue` / `QueueFullError` / `LANES` — the bounded priority
  queue (queue.py).
- `DeviceCacheManager` — byte-capped LRU over pinned device state
  (cache.py).
- `choose_placement` / `Placement` / `SHARD_PARALLEL` / `PROOF_PARALLEL`
  — the scheduler (scheduler.py).
- `MetricsPlane` — the stdlib HTTP telemetry endpoint
  (http_metrics.py: /metrics Prometheus text, /healthz, /slo), started
  by the worker loop when `ServiceConfig.metrics_port` is set.
- `Gateway` / `GatewayConfig` — the network admission plane
  (gateway.py, ISSUE 11): POST /prove with tenant auth + idempotency
  keys, job status/proof download, graceful drain, hot AOT reload and
  telemetry-driven load-shed, composed with the read plane under one
  server.
- `TenantSpec` / `QuotaLedger` / `parse_tenant_specs` — tenant
  identity, DRR weights and per-window byte/compute quotas (tenant.py).

Driver CLI: `scripts/prove_service.py` (`--gateway` serves the front
door); bench integration: `bench.py --service`.
"""

from .cache import DeviceCacheManager
from .gateway import Gateway, GatewayConfig, GatewayJob, read_spool
from .http_metrics import MetricsPlane
from .queue import LANES, AdmissionQueue, QueueFullError
from .scheduler import (
    PROOF_PARALLEL,
    SHARD_PARALLEL,
    Placement,
    choose_placement,
)
from .service import ProveRequest, ProvingService, ServiceConfig
from .tenant import QuotaLedger, TenantSpec, parse_tenant_specs

__all__ = [
    "AdmissionQueue",
    "DeviceCacheManager",
    "Gateway",
    "GatewayConfig",
    "GatewayJob",
    "LANES",
    "MetricsPlane",
    "Placement",
    "PROOF_PARALLEL",
    "ProveRequest",
    "ProvingService",
    "QueueFullError",
    "QuotaLedger",
    "SHARD_PARALLEL",
    "ServiceConfig",
    "TenantSpec",
    "choose_placement",
    "parse_tenant_specs",
    "read_spool",
]
