"""Network admission plane: the HTTP front door of the proving service.

ISSUE 11's tentpole. Until now admission was an in-process Python
`submit()`; millions of users hit a port. This module puts a
stdlib-only HTTP **write plane** in front of `service/queue.py`,
composed with the existing read-only plane (`service/http_metrics.py`)
under ONE server:

  POST /prove                submit a job spec (JSON body). Auth is a
                             shared-secret bearer token mapped to a
                             tenant id; an `Idempotency-Key` header
                             makes the submit replay-safe — a replay
                             returns the ORIGINAL ticket/proof from the
                             gateway's ledger and never re-proves.
                             Responses: 202 ticket, 200 replay,
                             401 bad token, 400 bad spec, 429 quota
                             exhausted (Retry-After = window reset),
                             503 queue full / draining / bulk load-shed.
  GET  /jobs/<id>            ticket status (+ the request's SLO record
                             once served). Tenants see only their own
                             jobs; admin tokens see all.
  GET  /jobs/<id>/proof      the proof bytes, streamed in 64 KiB chunks.
  POST /admin/drain          graceful drain: stop admitting (503),
                             finish in-flight work, flush report lines,
                             stop the worker loop; the `drained` event
                             lets a serving CLI exit.
  POST /admin/reload-artifacts
                             hot AOT-bundle reload: forget every warm
                             key so the next batch per bucket re-runs
                             the artifact-store load (prover/aot.py)
                             against the CURRENT bundle dir — without
                             dropping the queue.
  GET  /metrics /healthz /slo  delegated to MetricsPlane.handle_get —
                             identical bodies to the standalone plane.

Fairness + quotas ride the components ISSUE 11 added around this
module: the gateway configures per-tenant DRR weights on the admission
queue (`queue.py`), installs a `tenant.QuotaLedger` on the service
(charged from each request's flight-recorder record), and registers the
ledger's snapshot as a telemetry provider so `service.tenant.*` usage
rides /metrics and every report line's `telemetry` record. Load-shed is
telemetry-driven: bulk-lane work is rejected while queue depth or the
device-memory high-water gauge is above the configured thresholds.

Rejected admissions (429/shed) append a minimal report line carrying a
`tenant` record with `rejected` set — `prove_report.py --check`
enforces that such lines never carry a prove wall, and `--slo` counts
them per tenant.

A `spool_dir` turns the gateway into DIZK-style work distribution: bulk
jobs are written as one JSON file per request into the spool instead of
being proved locally, and `scripts/multihost_worker.py` "proofs" mode
feeds each worker its `distribute_proofs` slice of the spool — the
horizontal tier's feed path from this front door.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..utils import report as _report
from ..utils import spans as _spans
from ..utils.profiling import log as _log
from .http_metrics import MetricsPlane
from .queue import LANES, QueueFullError
from .tenant import QuotaLedger, TenantSpec, parse_tenant_specs

# streamed-download chunk: large enough to amortize syscalls, small
# enough that a slow client never pins a proof-sized buffer per write
STREAM_CHUNK = 64 * 1024


def _env_opt_int(name: str) -> int | None:
    v = os.environ.get(name, "").strip()
    if not v:
        return None
    return int(float(v))


@dataclass
class GatewayConfig:
    """Knobs of one Gateway (env: BOOJUM_TPU_GATEWAY_*)."""

    tenants: list = field(default_factory=list)  # list[TenantSpec]
    host: str = "127.0.0.1"       # loopback posture, like the read plane
    port: int = 0                 # BOOJUM_TPU_GATEWAY_PORT (0 = any free)
    admin_token: str | None = None  # BOOJUM_TPU_GATEWAY_ADMIN_TOKEN
    quota_window_s: float = 60.0  # BOOJUM_TPU_GATEWAY_QUOTA_WINDOW_S
    # telemetry-driven load-shed thresholds (None = axis disabled):
    # bulk-lane admissions are rejected 503 while crossed
    shed_queue_depth: int | None = None   # BOOJUM_TPU_GATEWAY_SHED_DEPTH
    shed_mem_bytes: int | None = None     # BOOJUM_TPU_GATEWAY_SHED_MEM_BYTES
    # bulk-lane spool directory (None = prove bulk work locally)
    spool_dir: str | None = None  # BOOJUM_TPU_GATEWAY_SPOOL
    # ticket/idempotency ledger bound: above it the oldest FINISHED
    # jobs (and their idempotency keys) are evicted — a long-running
    # front door must not retain every proof ever served. Each
    # finished ticket pins its PROOF for replay/download (assembly/
    # setup refs are shared with the resolver's memoized parts), so
    # size this to proofs-worth-of-RAM you can hold: 2048 × a ~1 MB
    # proof ≈ 2 GiB ceiling at the default
    max_jobs: int = 2048          # BOOJUM_TPU_GATEWAY_MAX_JOBS
    drain_timeout_s: float = 600.0
    worker_idle_wait_s: float = 0.2

    @classmethod
    def from_env(cls) -> "GatewayConfig":
        return cls(
            tenants=parse_tenant_specs(
                os.environ.get("BOOJUM_TPU_GATEWAY_TENANTS", "")
            ),
            port=_env_opt_int("BOOJUM_TPU_GATEWAY_PORT") or 0,
            admin_token=(
                os.environ.get("BOOJUM_TPU_GATEWAY_ADMIN_TOKEN") or None
            ),
            quota_window_s=float(
                os.environ.get("BOOJUM_TPU_GATEWAY_QUOTA_WINDOW_S") or 60.0
            ),
            shed_queue_depth=_env_opt_int("BOOJUM_TPU_GATEWAY_SHED_DEPTH"),
            shed_mem_bytes=_env_opt_int("BOOJUM_TPU_GATEWAY_SHED_MEM_BYTES"),
            spool_dir=os.environ.get("BOOJUM_TPU_GATEWAY_SPOOL") or None,
            max_jobs=_env_opt_int("BOOJUM_TPU_GATEWAY_MAX_JOBS") or 2048,
        )


@dataclass
class GatewayJob:
    """One admitted ticket: the gateway's unit of idempotency and
    status. `req` is None for spooled (farmed-out) jobs."""

    id: str
    tenant: str
    spec: dict
    req: object = None            # ProveRequest | None
    idem_key: str | None = None
    spooled: bool = False
    created_ts: float = 0.0
    trace_id: str | None = None   # the trace minted (or honored from
    #                               X-Boojum-Trace) at POST /prove
    admit_span_id: str | None = None  # the admission root span — the
    #                               parent every downstream span chains to

    def status(self) -> str:
        if self.spooled:
            return "spooled"
        if self.req is None or not self.req.done():
            return "queued"
        return "failed" if self.req.error is not None else "done"


def read_spool(spool_dir: str) -> list:
    """[(filename, spec_dict), ...] over the gateway spool, sorted by
    filename (admission order: names embed the monotonically-increasing
    job id). Partial/corrupt files — a gateway mid-write crashed — are
    skipped; the atomic tmp+rename on the write side makes that rare.
    Shared with scripts/multihost_worker.py "proofs" mode."""
    out = []
    for fname in sorted(os.listdir(spool_dir)):
        if not fname.endswith(".json"):
            continue
        try:
            with open(os.path.join(spool_dir, fname)) as f:
                out.append((fname, json.load(f)))
        except (OSError, ValueError):
            continue
    return out


class Gateway:
    """The HTTP admission plane over one ProvingService.

    `resolver(spec) -> (assembly, setup, config)` turns a job spec into
    prove parts — the CLI (scripts/prove_service.py) passes its circuit
    catalog; tests pass a registry of prebuilt parts. The gateway owns
    the worker loop (a daemon thread draining the service) for its
    lifetime, so `start()` is the whole deployment: bind, drain, serve.
    """

    def __init__(self, service, config: GatewayConfig, resolver):
        self.service = service
        self.config = config
        self.resolver = resolver
        by_token = {}
        for t in config.tenants:
            if t.token in by_token:
                raise ValueError(
                    f"tenants {by_token[t.token].id!r} and {t.id!r} share "
                    f"a token — tokens must be unique"
                )
            by_token[t.token] = t
        self._by_token: dict[str, TenantSpec] = by_token
        # per-tenant fairness + quotas onto the service's components
        service.quota = QuotaLedger(
            config.tenants, window_s=config.quota_window_s
        )
        for t in config.tenants:
            service.queue.set_weight(t.id, t.weight)
        service.sampler.add_provider(
            "service.tenant", service.quota.snapshot
        )
        # read plane: rendering only — never start()ed; its endpoints are
        # served by THIS gateway's server via handle_get
        self.read_plane = MetricsPlane(
            service.sampler,
            health_fn=service._telemetry_health,
            slo_fn=service._telemetry_slo,
        )
        self._jobs: dict[str, GatewayJob] = {}
        self._idem: dict[tuple[str, str], str] = {}
        self._ids = itertools.count(1)
        self._lock = threading.Lock()
        self._drain_lock = threading.Lock()
        self._draining = threading.Event()
        self.drained = threading.Event()
        self._stop_worker = threading.Event()
        self._worker: threading.Thread | None = None
        self._server: ThreadingHTTPServer | None = None
        self._http_thread: threading.Thread | None = None
        self.port: int | None = None
        if config.spool_dir:
            os.makedirs(config.spool_dir, exist_ok=True)

    # ---- lifecycle -------------------------------------------------------
    def start(self) -> int:
        """Start telemetry + the worker loop + the HTTP server; returns
        the bound port."""
        if self._server is not None:
            return self.port
        # sampler only — no standalone plane even when the service
        # config carries a metrics_port: /metrics rides THIS server
        self.service.start_telemetry(sampler_only=True)
        # black-box forensics (ISSUE 15): a gateway whose worker wedges
        # mid-request leaves heartbeat + stack-dump forensics behind
        try:
            from ..utils import blackbox as _blackbox

            _blackbox.ensure_started(
                label="gateway",
                report_path=self.service.report_path,
            )
            _blackbox.set_phase("gateway")
        except Exception:
            pass
        self._stop_worker.clear()
        self._worker = threading.Thread(
            target=self._worker_main, name="boojum-gateway-worker",
            daemon=True,
        )
        self._worker.start()
        gw = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # quiet by default
                pass

            def _send(self, code, body, ctype, extra_headers=None):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                for k, v in (extra_headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                # streamed write: proof downloads go out in chunks so a
                # multi-MB proof never sits behind one giant write
                for i in range(0, len(body), STREAM_CHUNK):
                    self.wfile.write(body[i:i + STREAM_CHUNK])

            def _dispatch(self, method):
                try:
                    length = int(self.headers.get("Content-Length") or 0)
                    body = self.rfile.read(length) if length else b""
                    out = gw.handle(method, self.path, self.headers, body)
                    self._send(*out)
                except (BrokenPipeError, ConnectionError):
                    pass  # client went away: not a server error
                except Exception as e:  # noqa: BLE001 — an admission
                    # failure must be a 500 body + a counted error, never
                    # a dropped connection or a dead server
                    gw.read_plane.count_error()
                    try:
                        self._send(
                            500,
                            json.dumps({"error": repr(e)}).encode(),
                            "application/json",
                        )
                    except Exception:
                        pass

            def do_GET(self):   # noqa: N802 — http.server API
                self._dispatch("GET")

            def do_POST(self):  # noqa: N802
                self._dispatch("POST")

        self._server = ThreadingHTTPServer(
            (self.config.host, self.config.port), _Handler
        )
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._http_thread = threading.Thread(
            target=self._server.serve_forever,
            name="boojum-gateway-http", daemon=True,
        )
        self._http_thread.start()
        _log(
            f"gateway: admission plane up on "
            f"http://{self.config.host}:{self.port} "
            f"({len(self._by_token)} tenants)"
        )
        return self.port

    def _worker_main(self):
        try:
            self.service.run_worker(
                stop=self._stop_worker,
                idle_wait_s=self.config.worker_idle_wait_s,
            )
        except Exception as e:  # noqa: BLE001 — keep the port answering
            _log(f"gateway: worker loop died: {e!r}")

    def stop(self):
        """Tear everything down (idempotent); drain() is the graceful
        path — this one just stops."""
        srv = self._server
        if srv is not None:
            self._server = None
            srv.shutdown()
            srv.server_close()
            if self._http_thread is not None:
                self._http_thread.join(timeout=5.0)
                self._http_thread = None
        self._stop_worker.set()
        if self._worker is not None:
            self._worker.join(timeout=10.0)
            self._worker = None
        self.service.stop_telemetry()

    def url(self, path: str = "") -> str:
        return f"http://{self.config.host}:{self.port}{path}"

    # ---- routing (socket-free: unit-testable) ----------------------------
    def handle(self, method, path, headers, body):
        """Route one request: (code, body_bytes, ctype[, extra_headers]).
        Pure of sockets so tests can drive the plane without binding."""
        path = path.split("?", 1)[0].rstrip("/") or "/"
        if method == "GET":
            out = self.read_plane.handle_get(path)
            if out is not None:
                return out
            if path.startswith("/jobs/"):
                return self._get_job(path, headers)
            return self._json(404, {"error": "not found"})
        if method == "POST":
            if path == "/prove":
                return self._post_prove(headers, body)
            if path == "/admin/drain":
                return self._admin(headers, self._drain_locked)
            if path == "/admin/reload-artifacts":
                return self._admin(headers, self._admin_reload)
            return self._json(404, {"error": "not found"})
        return self._json(405, {"error": f"method {method} not allowed"})

    @staticmethod
    def _json(code, obj, extra_headers=None):
        body = json.dumps(obj).encode()
        if extra_headers:
            return code, body, "application/json", extra_headers
        return code, body, "application/json"

    @staticmethod
    def _token(headers) -> str:
        tok = headers.get("X-Boojum-Token") or ""
        if not tok:
            auth = headers.get("Authorization") or ""
            if auth.startswith("Bearer "):
                tok = auth[len("Bearer "):].strip()
        return tok

    def _auth(self, headers) -> TenantSpec | None:
        tok = self._token(headers)
        return self._by_token.get(tok) if tok else None

    def _is_admin(self, headers, tenant: TenantSpec | None) -> bool:
        """Admin = a tenant carrying the admin flag, or the standalone
        BOOJUM_TPU_GATEWAY_ADMIN_TOKEN (which needs no tenant row)."""
        if tenant is not None and tenant.admin:
            return True
        admin_tok = self.config.admin_token
        return admin_tok is not None and self._token(headers) == admin_tok

    def _count(self, name: str, n: int = 1):
        """Gateway counters live on the sampler's registry so they ride
        /metrics (boojum_tpu_service_gateway_*)."""
        try:
            self.service.sampler.registry.count(name, n)
        except Exception:  # noqa: BLE001
            pass

    # ---- POST /prove -----------------------------------------------------
    def _post_prove(self, headers, body):
        tenant = self._auth(headers)
        if tenant is None:
            self._count("service.gateway.auth_failures")
            return self._json(401, {"error": "unknown or missing token"})
        # the trace is minted HERE, at the system's front door (ISSUE
        # 17): an inbound X-Boojum-Trace header ("<trace_id>" or
        # "<trace_id>:<parent_span_id>", ids as in BASELINE.md "Trace
        # protocol") is honored so an external driver can stitch our
        # timeline into its own; anything malformed is replaced, never
        # propagated. The admission span id becomes the parent of every
        # downstream span — queue wait, prove stages, spool write.
        hdr = str(headers.get("X-Boojum-Trace") or "")
        in_tid, _, in_psid = hdr.partition(":")
        trace_id = (
            in_tid if _spans.valid_trace_id(in_tid)
            else _spans.new_trace_id()
        )
        admit_span_id = _spans.new_span_id()
        trace_ctx = {"trace_id": trace_id, "parent_span_id": admit_span_id}
        # idempotency FIRST: a replay is a LEDGER READ — it must return
        # the original ticket before draining/quotas/shedding get a
        # chance to answer differently, and must never re-prove. The
        # check and the reservation happen under ONE lock acquisition:
        # two concurrent POSTs with the same key race to reserve, the
        # loser replays the winner's (possibly still queued) ticket —
        # never a second prove or a double quota charge.
        idem = headers.get("Idempotency-Key") or None
        with self._lock:
            if idem is not None:
                existing = self._idem.get((tenant.id, idem))
                if existing is not None and existing in self._jobs:
                    job = self._jobs[existing]
                    if job.req is None and not job.spooled:
                        # the winner is still BETWEEN reservation and
                        # admission — its checks may yet roll the
                        # reservation back, so a 200 here could hand
                        # out a ticket that then evaporates. Tell the
                        # duplicate to retry instead.
                        return self._json(
                            409,
                            {
                                "error": "original request with this "
                                         "key is still being admitted",
                                "retry_after_s": 1,
                            },
                            {"Retry-After": "1"},
                        )
                    self._count("service.gateway.replays")
                    return self._json(
                        200, dict(self._ticket(job), replay=True)
                    )
            job_id = f"gw-{next(self._ids):06d}"
            job = GatewayJob(
                id=job_id, tenant=tenant.id, spec={}, idem_key=idem,
                created_ts=time.time(),
                trace_id=trace_id, admit_span_id=admit_span_id,
            )
            self._jobs[job_id] = job
            if idem is not None:
                self._idem[(tenant.id, idem)] = job_id
        # every path below either fills the reservation in (202) or
        # rolls it back (_unreserve) so a rejected key can be retried
        if self._draining.is_set():
            self._unreserve(job)
            return self._json(
                503, {"error": "draining", "retry_after_s": 30},
                {"Retry-After": "30"},
            )
        try:
            spec = json.loads(body.decode() or "{}")
            if not isinstance(spec, dict):
                raise ValueError("job spec must be a JSON object")
        except ValueError as e:
            self._unreserve(job)
            return self._json(400, {"error": f"bad job spec: {e}"})
        priority = spec.get("priority", "batch")
        if priority not in LANES:
            self._unreserve(job)
            return self._json(
                400,
                {"error": f"unknown priority {priority!r}: use {LANES}"},
            )

        ok, retry_after = self.service.quota.admit(tenant.id)
        if not ok:
            self._unreserve(job)
            self._count("service.gateway.throttled")
            self._reject_line(
                tenant.id, "throttled", 429, retry_after, trace_ctx
            )
            return self._json(
                429,
                {
                    "error": "quota exhausted",
                    "tenant": tenant.id,
                    "retry_after_s": round(retry_after, 3),
                },
                {"Retry-After": str(max(1, int(retry_after + 0.999)))},
            )
        if priority == "bulk" and self._should_shed():
            self._unreserve(job)
            self._count("service.gateway.shed")
            self._reject_line(tenant.id, "shed", 503, None, trace_ctx)
            return self._json(
                503,
                {"error": "bulk lane shedding load", "tenant": tenant.id},
                {"Retry-After": "30"},
            )

        if priority == "bulk" and self.config.spool_dir:
            admit_parent = (
                in_psid if _spans.valid_span_id(in_psid) else None
            )
            nbytes = self._spool_job(
                job, tenant, spec, trace_ctx, admit_parent
            )
            # spooled work never reaches _serve_one's settle, so the
            # byte quota is charged HERE (spool-file bytes; the fleet
            # owns the compute) — without this a quota tenant could
            # fill the spool disk unmetered
            try:
                self.service.quota.charge(tenant.id, nbytes, 0.0)
            except Exception:  # noqa: BLE001
                pass
            self._count("service.gateway.spooled")
            self._gc_jobs()
            return self._json(
                202, self._ticket(job), {"X-Boojum-Trace": trace_id}
            )
        try:
            asm, setup, cfg = self.resolver(spec)
        except Exception as e:  # noqa: BLE001 — a spec the resolver
            # rejects is the CLIENT's error
            self._unreserve(job)
            return self._json(400, {"error": f"unresolvable spec: {e!r}"})
        try:
            req = self.service.submit(
                asm, setup, cfg,
                priority=priority,
                tenant=tenant.id,
                request_id=job_id,
                capture_trace=bool(spec.get("capture_trace")),
                gateway=True,
                trace=trace_ctx,
            )
        except QueueFullError:
            self._unreserve(job)
            return self._json(
                503,
                {"error": "admission queue full", "retry_after_s": 5},
                {"Retry-After": "5"},
            )
        with self._lock:
            job.spec = spec
            job.req = req
        self._count("service.gateway.admitted")
        self._gc_jobs()
        return self._json(
            202, self._ticket(job), {"X-Boojum-Trace": trace_id}
        )

    def _unreserve(self, job: GatewayJob):
        """Roll a rejected admission's ticket/idempotency reservation
        back so the client can retry the same key later."""
        with self._lock:
            self._jobs.pop(job.id, None)
            if job.idem_key is not None:
                key = (job.tenant, job.idem_key)
                if self._idem.get(key) == job.id:
                    del self._idem[key]

    def _gc_jobs(self):
        """Bound the ticket ledger above max_jobs, oldest first (dict
        insertion order is admission order). FINISHED tickets
        (done/failed) go first; only if the ledger is still over does
        it fall back to the oldest SPOOLED tickets (their record of
        truth is the spool file / the fleet's result line, and keeping
        them forever would be the unbounded-growth hole this GC
        exists to close). Locally-queued tickets are NEVER evicted."""
        with self._lock:
            excess = len(self._jobs) - self.config.max_jobs
            if excess <= 0:
                return

            def evict(statuses):
                nonlocal excess
                for job_id in list(self._jobs):
                    if excess <= 0:
                        return
                    job = self._jobs[job_id]
                    if job.status() not in statuses:
                        continue
                    del self._jobs[job_id]
                    if job.idem_key is not None:
                        key = (job.tenant, job.idem_key)
                        if self._idem.get(key) == job_id:
                            del self._idem[key]
                    excess -= 1

            evict(("done", "failed"))
            evict(("spooled",))

    def _ticket(self, job: GatewayJob) -> dict:
        out = {
            "job": job.id,
            "tenant": job.tenant,
            "status": job.status(),
            "priority": job.spec.get("priority", "batch"),
        }
        if job.trace_id:
            out["trace"] = job.trace_id
        if job.req is not None and job.req.done():
            out["request"] = dict(job.req.slo)
            if job.req.error is not None:
                out["error"] = repr(job.req.error)
        return out

    def _spool_job(self, job, tenant, spec, trace_ctx, admit_parent=None):
        """Farm a bulk job out to the worker fleet: one JSON file per
        request in the spool dir (atomic tmp+rename), named by job id so
        spool order is admission order. The record carries the trace
        context so a fleet worker's prove joins the gateway's trace
        instead of orphaning (ISSUE 17 / ROADMAP item 3), and the write
        itself is recorded as a span in a gateway report line — the
        spooled job's footprint in THIS host's artifact."""
        record = dict(spec)
        record["job"] = job.id
        record["tenant"] = tenant.id
        record["trace"] = dict(trace_ctx)
        path = os.path.join(self.config.spool_dir, f"{job.id}.json")
        tmp = path + ".tmp"
        payload = json.dumps(record)
        t0 = time.perf_counter()
        with open(tmp, "w") as f:
            f.write(payload)
        os.replace(tmp, path)
        wall = round(time.perf_counter() - t0, 6)
        with self._lock:
            job.spec = spec
            job.spooled = True
        self._spool_line(job, tenant, payload, wall, trace_ctx, admit_parent)
        return len(payload)

    def _spool_line(
        self, job, tenant, payload, wall, trace_ctx, admit_parent
    ):
        """One gateway report line per spooled job: the admission root
        span (the id every downstream span chains to) with the
        spool-write as its child."""
        rpath = self.service.report_path
        if not rpath:
            return
        admit_span = {
            "name": "gateway.admit",
            "start_s": 0.0,
            "wall_s": wall,
            "span_id": job.admit_span_id or _spans.new_span_id(),
            "trace_id": trace_ctx["trace_id"],
            "children": [],
            "attrs": {"job": job.id, "tenant": tenant.id, "spooled": True},
        }
        if admit_parent:
            admit_span["parent_span_id"] = admit_parent
        admit_span["children"].append(
            {
                "name": "gateway.spool_write",
                "start_s": 0.0,
                "wall_s": wall,
                "span_id": _spans.new_span_id(),
                "parent_span_id": admit_span["span_id"],
                "children": [],
                "attrs": {"job": job.id, "bytes": len(payload)},
            }
        )
        line = {
            "kind": _report.REPORT_KIND,
            "schema": _report.REPORT_SCHEMA,
            "label": "gateway:spool",
            "unix_ts": round(time.time(), 3),
            "wall_s": wall,
            "spans": [admit_span],
            "metrics": {
                "counters": {"service.gateway.spooled": 1},
                "gauges": {},
            },
            "checkpoints": [],
            # the LINE's context is the external one: this line contains
            # the admission span itself, so its parent is the inbound
            # header's span (if any), not the admission span
            "trace_ctx": (
                {"trace_id": trace_ctx["trace_id"],
                 "parent_span_id": admit_parent}
                if admit_parent
                else {"trace_id": trace_ctx["trace_id"]}
            ),
            "tenant": {"id": tenant.id, "charged_bytes": len(payload)},
        }
        try:
            with self.service._report_lock:
                _report.append_jsonl(rpath, line)
        except Exception as e:  # noqa: BLE001
            _log(f"gateway: spool line write failed: {e!r}")

    def _should_shed(self) -> bool:
        """Telemetry-driven load-shed: bulk work is rejected while queue
        depth or the device-memory high-water gauge is above threshold."""
        cfg = self.config
        if (
            cfg.shed_queue_depth is not None
            and self.service.queue.depth() >= cfg.shed_queue_depth
        ):
            return True
        if cfg.shed_mem_bytes is not None:
            gauges = (
                self.service.sampler.registry.to_dict().get("gauges") or {}
            )
            high_water = max(
                gauges.get("telemetry.device_bytes_in_use_high_water", 0),
                gauges.get("telemetry.live_bytes_high_water", 0),
            )
            if high_water >= cfg.shed_mem_bytes:
                return True
        return False

    def _reject_line(self, tenant_id, reason, code, retry_after,
                     trace_ctx=None):
        """Append a minimal report line for a rejected admission so the
        artifact carries the 429/shed history `--slo` aggregates. The
        line has NO request record (nothing was proved — --check
        enforces that a rejected line never carries a prove wall) but
        DOES carry the trace context: a throttled request is part of
        its trace's story, and --check fails a gateway line without
        one."""
        path = self.service.report_path
        if not path:
            return
        tenant_rec = {"id": tenant_id, "rejected": code, "reason": reason}
        if retry_after is not None:
            tenant_rec["retry_after_s"] = round(max(0.0, retry_after), 3)
        line = {
            "kind": _report.REPORT_KIND,
            "schema": _report.REPORT_SCHEMA,
            "label": f"gateway:{reason}",
            "unix_ts": round(time.time(), 3),
            "wall_s": 0.0,
            "spans": [],
            "metrics": {
                "counters": {f"service.gateway.{reason}": 1},
                "gauges": {},
            },
            "checkpoints": [],
            "tenant": tenant_rec,
        }
        if isinstance(trace_ctx, dict) and trace_ctx.get("trace_id"):
            line["trace_ctx"] = dict(trace_ctx)
        try:
            with self.service._report_lock:
                _report.append_jsonl(path, line)
        except Exception as e:  # noqa: BLE001
            _log(f"gateway: reject line write failed: {e!r}")

    # ---- GET /jobs/<id>[/proof] ------------------------------------------
    def _get_job(self, path, headers):
        tenant = self._auth(headers)
        is_admin = self._is_admin(headers, tenant)
        if tenant is None and not is_admin:
            self._count("service.gateway.auth_failures")
            return self._json(401, {"error": "unknown or missing token"})
        parts = path.split("/")  # ['', 'jobs', '<id>'(, 'proof')]
        job_id = parts[2] if len(parts) > 2 else ""
        want_proof = len(parts) > 3 and parts[3] == "proof"
        with self._lock:
            job = self._jobs.get(job_id)
        if job is None or (
            not is_admin and (tenant is None or job.tenant != tenant.id)
        ):
            # a foreign tenant's ticket is indistinguishable from a
            # nonexistent one: no cross-tenant job-id probing
            return self._json(404, {"error": f"no job {job_id!r}"})
        if not want_proof:
            return self._json(200, self._ticket(job))
        status = job.status()
        if status != "done":
            code = 500 if status == "failed" else 409
            return self._json(code, self._ticket(job))
        proof_bytes = job.req.proof.to_json().encode()
        return (
            200, proof_bytes, "application/json",
            {"X-Boojum-Job": job.id},
        )

    # ---- admin verbs -----------------------------------------------------
    def _admin(self, headers, verb):
        tenant = self._auth(headers)
        if not self._is_admin(headers, tenant):
            # a KNOWN tenant probing admin verbs is an authorization
            # denial, not a credential failure — keep the bad-token
            # alarm (auth_failures) clean of it
            self._count(
                "service.gateway.admin_denied" if tenant is not None
                else "service.gateway.auth_failures"
            )
            return self._json(403, {"error": "admin token required"})
        return verb()

    def drain(self) -> dict:
        """Public graceful-drain entry (the /admin/drain verb and the
        CLI's SIGINT path both land here; serialized so a concurrent
        pair can't double-join the worker). Returns the drain body."""
        return json.loads(self._drain_locked()[1])

    def _drain_locked(self):
        with self._drain_lock:
            return self._admin_drain()

    def job(self, job_id: str) -> GatewayJob | None:
        """Ticket lookup by id (public: harness/bench surface)."""
        with self._lock:
            return self._jobs.get(job_id)

    def wait_jobs(self, job_ids, timeout_s: float | None = None,
                  poll_s: float = 0.2) -> list:
        """Block until every listed LOCALLY-PROVED job finishes;
        returns their ProveRequests in job_ids order. Spooled jobs
        (proved by the fleet) raise ValueError — the gateway never
        learns their completion. TimeoutError past timeout_s."""
        deadline = (
            None if timeout_s is None else time.monotonic() + timeout_s
        )
        out = []
        for job_id in job_ids:
            job = self.job(job_id)
            if job is None:
                raise KeyError(f"no job {job_id!r}")
            if job.spooled:
                raise ValueError(
                    f"job {job_id!r} was spooled to the fleet"
                )
            while job.req is None or not job.req.done():
                if deadline is not None and time.monotonic() > deadline:
                    raise TimeoutError(
                        f"job {job_id!r} still {job.status()}"
                    )
                time.sleep(poll_s)
            out.append(job.req)
        return out

    def _admin_drain(self):
        """Graceful drain: stop admitting, finish in-flight work, flush
        the report artifact, stop the worker loop. Blocks until drained
        (or the timeout), then sets `drained` so a serving CLI exits."""
        self._draining.set()
        self._count("service.gateway.drains")
        deadline = time.monotonic() + self.config.drain_timeout_s
        svc = self.service

        def busy():
            # _serve_lock covers the whole batch — including the window
            # between pop_batch (queue depth already 0) and the first
            # _inflight increment (scheduling + variant warming), which
            # depth/inflight alone would misread as idle
            return (
                svc.queue.depth() > 0
                or svc._inflight > 0
                or svc._serve_lock.locked()
            )

        while busy() and time.monotonic() < deadline:
            time.sleep(0.05)
        timed_out = busy()
        self._stop_worker.set()
        if self._worker is not None:
            self._worker.join(timeout=30.0)
            if self._worker.is_alive():
                # the worker outlived its join budget: mid-prove work is
                # still running and the summary below is provisional —
                # never report that as a clean drain
                timed_out = True
        # report lines are appended with open/write/close per line, so
        # the artifact is already on disk; this is the explicit fsync a
        # deploy's preStop hook wants before the pod goes away
        if svc.report_path and os.path.exists(svc.report_path):
            try:
                fd = os.open(svc.report_path, os.O_RDONLY)
                try:
                    os.fsync(fd)
                finally:
                    os.close(fd)
            except OSError:
                pass
        summary = svc.summary()
        self.drained.set()
        return self._json(
            200,
            {
                "drained": not timed_out,
                "timed_out": timed_out,
                "summary": summary,
                "report_path": svc.report_path,
            },
        )

    def _admin_reload(self):
        """Hot AOT-bundle reload: clear the warmer's dedup set (next
        batch per bucket re-consults BOOJUM_TPU_AOT_DIR) and drop jax's
        persistent-cache singleton so a swapped cache dir is re-read —
        queued work is untouched."""
        cleared = self.service.warmer.reset()
        aot_root = None
        try:
            from ..prover import aot as _aot

            aot_root = _aot.aot_dir()
            _aot._reset_persistent_cache()
        except Exception as e:  # noqa: BLE001 — reload is best-effort;
            # the warmer reset alone already forces a fresh consult
            _log(f"gateway: persistent-cache reset failed: {e!r}")
        self._count("service.gateway.reloads")
        return self._json(
            200,
            {
                "reloaded": True,
                "warm_keys_cleared": cleared,
                "aot_dir": aot_root,
            },
        )
