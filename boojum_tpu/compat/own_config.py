"""Verifier gate configuration for circuits built by THIS framework.

The reference-dialect verifier (`compat.verifier._verify_impl`) consumes a
`config` dict of gate evaluators in the compat adapter shape (`num_terms`,
`per_chunk`, `num_repetitions(geom)`, `load_shared`, `evaluate_once`) — the
same shape `era_main_vm_verifier_config` hand-writes for the golden Era
main-VM artifacts. For OWN circuits the gate set is known exactly: this
module wraps each `boojum_tpu.cs.gates.Gate` instance in that adapter shape
by re-running its single `evaluate(ops, row, dst)` definition over
`ExtScalarOps` (the verifier-side face of the field-like contract) — so the
reference-dialect prover and verifier agree on term order by construction.

Counterpart: the reference's `GateConstraintEvaluator` instances recovered
from a `Verifier` (`/root/reference/src/cs/implementations/verifier.rs:130`
`new_from_parameters`).
"""

from __future__ import annotations

from ..cs.field_like import ExtScalarOps
from ..cs.gates.base import RowView, TermsCollector


class _GeomShim:
    def __init__(self, geom_dict):
        self.num_columns_under_copy_permutation = geom_dict[
            "num_columns_under_copy_permutation"
        ]
        self.num_witness_columns = geom_dict["num_witness_columns"]
        self.num_constant_columns = geom_dict["num_constant_columns"]


class OwnGateAdapter:
    """Compat-verifier evaluator over one of this framework's gates.

    `per_chunk` constants are 0: this framework shares a row's gate
    constants across instance chunks (the verifier's `const(i)` then
    resolves relative to the selector-path offset for every repetition,
    matching `prover.verifier._ZRowView`).
    """

    def __init__(self, gate):
        self.gate = gate
        self.num_terms = gate.num_terms
        self.per_chunk = (gate.principal_width, gate.witness_width, 0)

    def num_repetitions(self, geom):
        return self.gate.num_repetitions(_GeomShim(geom))

    @staticmethod
    def load_shared(const):
        return None

    def evaluate_once(self, var, wit, const, shared, push):
        row = RowView(var, wit, const)
        dst = TermsCollector()
        self.gate.evaluate(ExtScalarOps, row, dst)
        assert len(dst.terms) == self.num_terms, self.gate.name
        for term in dst.terms:
            push(term)


def verifier_config_for_assembly(assembly) -> dict:
    """Reference-dialect verifier config for an own assembly.

    All of this framework's gates place general-purpose (specialized
    columns are used only by lookups, which the verifier handles
    separately), so `specialized_gates` is empty and the general-purpose
    list is the assembly's gate list in selector-tree order (gate index i
    == position i, the same indexing `setup.build_selector_tree` uses).
    Zero-term gates (nop, public-input, lookup markers) get a `None`
    evaluator exactly like the reference's nop row.
    """
    gp = []
    for g in assembly.gates:
        if g.num_terms == 0:
            gp.append((g.name, None))
        else:
            if g.witness_width:
                # the compat verifier's wit() accessor carries no per-rep
                # offset (mirroring the reference closure), so witness-
                # column gates must occupy the row alone
                assert g.num_repetitions(assembly.geometry) == 1, g.name
            gp.append((g.name, OwnGateAdapter(g)))
    return {"general_purpose_gates": gp, "specialized_gates": []}
