"""Export own proofs/VKs into the reference's serde-JSON schema.

Counterparts: `/root/reference/src/cs/implementations/proof.rs:121` (Proof
serde layout), `verifier.rs:31` (VerificationKey), `setup.rs:1374`
(selectors_placement serde enum). The exported JSON loads with
`compat.serde.load_vk/load_proof` (the same loaders used on the golden
artifacts) and round-trips through this module's importers back into the
framework's own `Proof`/`VerificationKey`, closing a byte-level schema loop
on OWN circuits: prove -> export -> reload -> full verification (including
the quotient identity at z) passes, tampering fails.

Dialect note (documented, deliberate): the reference's TRANSCRIPT dialect
differs from this framework's in three structural ways — storage
enumeration (natural coset-major vs our bit-reversed domain), stage-2/
quotient openings (one extension value per ext poly vs our per-base-column
pair), and challenge partition order (lookup/specialized/general/copy vs
our general/copy/lookup). A proof byte-identical to the reference CPU
prover therefore requires proving in that dialect end-to-end, not a
serialization shim; the schema exported here is the reference's, the
transcript dialect is ours. `compat.verifier.verify_reference_proof`
replays the REFERENCE dialect and is used against the golden artifacts;
own proofs are verified by `prover.verifier.verify` (full identity) after
a schema round-trip through the loaders.
"""

from __future__ import annotations

import json

import numpy as np

from ..prover.setup import build_selector_tree
from ..field import gl


def _ext(v) -> dict:
    return {"coeffs": [str(int(v[0])), str(int(v[1]))]}


def _cap_json(cap):
    return [[str(int(x)) for x in digest] for digest in cap]


def export_vk(vk, gates, total_tables_len: int | None = None) -> dict:
    """Own VerificationKey -> reference vk.json schema (verifier.rs:31).

    `gates` are the assembly's gate instances (the selector tree is
    reconstructed exactly as generate_setup built it)."""
    geom = vk.geometry
    tree, paths = build_selector_tree(gates)
    assert [list(p) for p in paths] == [list(p) for p in vk.selector_paths], (
        "selector tree reconstruction diverged from the VK's paths"
    )
    lp = vk.lookup_params
    if lp is None or not lp.is_enabled:
        lookup_json = "NoLookup"
        table_ids_column_idxes = []
    elif lp.use_specialized_columns:
        lookup_json = {
            "UseSpecializedColumnsWithTableIdAsConstant": {
                "width": lp.width,
                "num_repetitions": lp.num_repetitions,
                "share_table_id": bool(getattr(lp, "share_table_id", True)),
            }
        }
        # the dedicated table-id constant column sits after the base
        # constants (setup.py build order: K = base + 1, tid last)
        table_ids_column_idxes = [geom.num_constant_columns]
    else:
        # reference cs/mod.rs:233: TableIdAsConstant{width, share_table_id}
        # only — no num_repetitions field on this variant
        lookup_json = {
            "TableIdAsConstant": {
                "width": lp.width,
                "share_table_id": bool(getattr(lp, "share_table_id", True)),
            }
        }
        # general mode: the table id is the lookup marker row's first gate
        # constant, i.e. constant column len(marker selector path)
        # (prover.py/verifier.py tid_col; reference setup.rs:954)
        mk_gid = next(
            (
                i for i, g in enumerate(gates)
                if getattr(g, "is_lookup_marker", False)
            ),
            None,
        )
        assert mk_gid is not None, "general-mode VK without a marker gate"
        table_ids_column_idxes = [len(vk.selector_paths[mk_gid])]
    # this framework places selector-path constants INSIDE the declared
    # geometry.num_constant_columns (setup.py asserts they fit), so the
    # reference's extra_constant_polys_for_selectors (= constants used
    # beyond the declared count, reference setup.rs:1212) is zero
    extra_constant_polys = 0
    return {
        "fixed_parameters": {
            "parameters": {
                "num_columns_under_copy_permutation": (
                    geom.num_columns_under_copy_permutation
                ),
                "num_witness_columns": geom.num_witness_columns,
                "num_constant_columns": geom.num_constant_columns,
                "max_allowed_constraint_degree": (
                    geom.max_allowed_constraint_degree
                ),
            },
            "lookup_parameters": lookup_json,
            "domain_size": str(vk.trace_len),
            "total_tables_len": str(int(total_tables_len or 0)),
            "public_inputs_locations": [
                [int(c), int(r)] for (c, r) in vk.public_input_locations
            ],
            "extra_constant_polys_for_selectors": extra_constant_polys,
            "table_ids_column_idxes": table_ids_column_idxes,
            "quotient_degree": int(vk.effective_quotient_degree()),
            "selectors_placement": tree.to_json(),
            "fri_lde_factor": int(vk.fri_lde_factor),
            "cap_size": int(vk.cap_size),
        },
        "setup_merkle_tree_cap": _cap_json(vk.setup_merkle_cap),
    }


def _query_json(q) -> dict:
    return {
        "leaf_elements": [str(int(x)) for x in q.leaf_values],
        "proof": [[str(int(x)) for x in d] for d in q.path],
    }


def export_proof(proof, security_level: int = 100) -> dict:
    """Own Proof -> reference proof.json schema (proof.rs:121)."""
    cfg = proof.config
    return {
        "proof_config": {
            "fri_lde_factor": int(cfg["fri_lde_factor"]),
            "merkle_tree_cap_size": int(cfg["merkle_tree_cap_size"]),
            "fri_folding_schedule": None,
            "security_level": int(security_level),
            "pow_bits": int(cfg["pow_bits"]),
        },
        "public_inputs": [str(int(v)) for v in proof.public_inputs],
        "witness_oracle_cap": _cap_json(proof.witness_cap),
        "stage_2_oracle_cap": _cap_json(proof.stage2_cap),
        "quotient_oracle_cap": _cap_json(proof.quotient_cap),
        "final_fri_monomials": [
            [str(int(c0)) for (c0, _c1) in proof.final_fri_monomials],
            [str(int(c1)) for (_c0, c1) in proof.final_fri_monomials],
        ],
        "values_at_z": [_ext(v) for v in proof.values_at_z],
        "values_at_z_omega": [_ext(v) for v in proof.values_at_z_omega],
        "values_at_0": [_ext(v) for v in proof.values_at_0],
        "fri_base_oracle_cap": _cap_json(proof.fri_caps[0]),
        "fri_intermediate_oracles_caps": [
            _cap_json(c) for c in proof.fri_caps[1:]
        ],
        "queries_per_fri_repetition": [
            {
                "witness_query": _query_json(q.witness),
                "stage_2_query": _query_json(q.stage2),
                "quotient_query": _query_json(q.quotient),
                "setup_query": _query_json(q.setup),
                "fri_queries": [_query_json(f) for f in q.fri],
            }
            for q in proof.queries
        ],
        "pow_challenge": str(int(proof.pow_challenge)),
        # own-dialect extras the reference schema has no slot for; loaders
        # ignore unknown keys, importers round-trip them
        "_boojum_tpu": {
            "quotient_degree": int(cfg["quotient_degree"]),
            "num_queries": int(cfg["num_queries"]),
            "fri_final_degree": int(cfg["fri_final_degree"]),
        },
    }


def import_proof(obj: dict):
    """Reference-schema JSON (as exported above) -> own Proof."""
    from ..prover.proof import OracleQuery, Proof, SingleRoundQueries

    def q(d):
        return OracleQuery(
            leaf_values=[int(x) for x in d["leaf_elements"]],
            path=[tuple(int(x) for x in lvl) for lvl in d["proof"]],
        )

    def cap(d):
        return [tuple(int(x) for x in digest) for digest in d]

    extra = obj.get("_boojum_tpu", {})
    pc = obj["proof_config"]
    m0, m1 = obj["final_fri_monomials"]
    return Proof(
        public_inputs=[int(v) for v in obj["public_inputs"]],
        witness_cap=cap(obj["witness_oracle_cap"]),
        stage2_cap=cap(obj["stage_2_oracle_cap"]),
        quotient_cap=cap(obj["quotient_oracle_cap"]),
        values_at_z=[
            (int(v["coeffs"][0]), int(v["coeffs"][1]))
            for v in obj["values_at_z"]
        ],
        values_at_z_omega=[
            (int(v["coeffs"][0]), int(v["coeffs"][1]))
            for v in obj["values_at_z_omega"]
        ],
        values_at_0=[
            (int(v["coeffs"][0]), int(v["coeffs"][1]))
            for v in obj["values_at_0"]
        ],
        fri_caps=[cap(obj["fri_base_oracle_cap"])]
        + [cap(c) for c in obj["fri_intermediate_oracles_caps"]],
        final_fri_monomials=[
            (int(a), int(b)) for a, b in zip(m0, m1)
        ],
        queries=[
            SingleRoundQueries(
                witness=q(d["witness_query"]),
                stage2=q(d["stage_2_query"]),
                quotient=q(d["quotient_query"]),
                setup=q(d["setup_query"]),
                fri=[q(f) for f in d["fri_queries"]],
            )
            for d in obj["queries_per_fri_repetition"]
        ],
        pow_challenge=int(obj["pow_challenge"]),
        config={
            "fri_lde_factor": int(pc["fri_lde_factor"]),
            "merkle_tree_cap_size": int(pc["merkle_tree_cap_size"]),
            "pow_bits": int(pc["pow_bits"]),
            "quotient_degree": int(extra.get("quotient_degree", 0)),
            "num_queries": int(
                extra.get(
                    "num_queries", len(obj["queries_per_fri_repetition"])
                )
            ),
            "fri_final_degree": int(
                extra.get("fri_final_degree", len(m0))
            ),
        },
    )


def export_proof_json(proof, **kw) -> str:
    return json.dumps(export_proof(proof, **kw))


def import_proof_json(s: str):
    return import_proof(json.loads(s))
