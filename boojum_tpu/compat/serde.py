"""Deserialization of the reference's serde-JSON proof/VK artifacts.

Counterparts: `/root/reference/src/cs/implementations/proof.rs:121` (Proof),
`verifier.rs:31` (VerificationKey), `verifier.rs:66`
(VerificationKeyCircuitGeometry), `setup.rs:1374` (TreeNode/GateDescription).
Extension values serialize as `{"coeffs": [c0, c1]}`; caps as lists of
4-element digests; the selector placement tree as nested
`{"Fork": {...}}`/`{"GateOnly": {...}}`/`"Empty"` serde-enum JSON.
"""

from __future__ import annotations

import json
from dataclasses import dataclass

from ..cs.selector_tree import GateDescription, TreeNode

__all__ = [
    "GateDescription",
    "TreeNode",
    "ReferenceVk",
    "ReferenceProof",
    "load_vk",
    "load_proof",
]


@dataclass
class LookupParametersRef:
    mode: str  # serde variant name
    width: int
    num_repetitions: int
    share_table_id: bool

    @classmethod
    def from_json(cls, obj) -> "LookupParametersRef":
        if obj == "NoLookup":
            return cls("NoLookup", 0, 0, False)
        (mode, body), = obj.items()
        return cls(
            mode,
            int(body.get("width", 0)),
            int(body.get("num_repetitions", 0)),
            bool(body.get("share_table_id", False)),
        )

    @property
    def is_lookup(self) -> bool:
        return self.mode != "NoLookup"

    def specialized_columns_per_subargument(self) -> int:
        """Variable columns one specialized sub-argument occupies
        (reference cs/mod.rs LookupParameters)."""
        if self.mode == "UseSpecializedColumnsWithTableIdAsConstant":
            return self.width
        if self.mode == "UseSpecializedColumnsWithTableIdAsVariable":
            return self.width + 1
        raise ValueError("not a specialized-columns mode")


@dataclass
class ReferenceVk:
    # geometry (CSGeometry)
    num_columns_under_copy_permutation: int
    num_witness_columns: int
    num_constant_columns: int
    max_allowed_constraint_degree: int
    # the rest of VerificationKeyCircuitGeometry
    lookup_parameters: LookupParametersRef
    domain_size: int
    total_tables_len: int
    public_inputs_locations: list  # [(column, row)]
    extra_constant_polys_for_selectors: int
    table_ids_column_idxes: list
    quotient_degree: int
    selectors_placement: TreeNode
    fri_lde_factor: int
    cap_size: int
    setup_merkle_tree_cap: list  # [[4 ints]]


def _ext(obj):
    return (int(obj["coeffs"][0]), int(obj["coeffs"][1]))


@dataclass
class OracleQueryRef:
    leaf_elements: list
    proof: list  # list of 4-int digests


@dataclass
class QueriesRef:
    witness: OracleQueryRef
    stage_2: OracleQueryRef
    quotient: OracleQueryRef
    setup: OracleQueryRef
    fri: list  # [OracleQueryRef]


@dataclass
class ReferenceProof:
    proof_config: dict
    public_inputs: list
    witness_oracle_cap: list
    stage_2_oracle_cap: list
    quotient_oracle_cap: list
    final_fri_monomials: tuple  # (list c0, list c1)
    values_at_z: list  # [(c0, c1)]
    values_at_z_omega: list
    values_at_0: list
    fri_base_oracle_cap: list
    fri_intermediate_oracles_caps: list
    queries_per_fri_repetition: list  # [QueriesRef]
    pow_challenge: int


def _query(obj) -> OracleQueryRef:
    return OracleQueryRef(
        leaf_elements=[int(x) for x in obj["leaf_elements"]],
        proof=[tuple(int(x) for x in d) for d in obj["proof"]],
    )


def _cap(obj):
    return [tuple(int(x) for x in d) for d in obj]


def load_vk(path: str) -> ReferenceVk:
    raw = json.load(open(path))
    fp = raw["fixed_parameters"]
    geo = fp["parameters"]
    return ReferenceVk(
        num_columns_under_copy_permutation=geo[
            "num_columns_under_copy_permutation"
        ],
        num_witness_columns=geo["num_witness_columns"],
        num_constant_columns=geo["num_constant_columns"],
        max_allowed_constraint_degree=geo["max_allowed_constraint_degree"],
        lookup_parameters=LookupParametersRef.from_json(
            fp["lookup_parameters"]
        ),
        domain_size=int(fp["domain_size"]),
        total_tables_len=int(fp["total_tables_len"]),
        public_inputs_locations=[
            (int(c), int(r)) for c, r in fp["public_inputs_locations"]
        ],
        extra_constant_polys_for_selectors=int(
            fp["extra_constant_polys_for_selectors"]
        ),
        table_ids_column_idxes=[int(i) for i in fp["table_ids_column_idxes"]],
        quotient_degree=int(fp["quotient_degree"]),
        selectors_placement=TreeNode.from_json(fp["selectors_placement"]),
        fri_lde_factor=int(fp["fri_lde_factor"]),
        cap_size=int(fp["cap_size"]),
        setup_merkle_tree_cap=_cap(raw["setup_merkle_tree_cap"]),
    )


def load_proof(path: str) -> ReferenceProof:
    raw = json.load(open(path))
    return ReferenceProof(
        proof_config=raw["proof_config"],
        public_inputs=[int(x) for x in raw["public_inputs"]],
        witness_oracle_cap=_cap(raw["witness_oracle_cap"]),
        stage_2_oracle_cap=_cap(raw["stage_2_oracle_cap"]),
        quotient_oracle_cap=_cap(raw["quotient_oracle_cap"]),
        final_fri_monomials=(
            [int(x) for x in raw["final_fri_monomials"][0]],
            [int(x) for x in raw["final_fri_monomials"][1]],
        ),
        values_at_z=[_ext(v) for v in raw["values_at_z"]],
        values_at_z_omega=[_ext(v) for v in raw["values_at_z_omega"]],
        values_at_0=[_ext(v) for v in raw["values_at_0"]],
        fri_base_oracle_cap=_cap(raw["fri_base_oracle_cap"]),
        fri_intermediate_oracles_caps=[
            _cap(c) for c in raw["fri_intermediate_oracles_caps"]
        ],
        queries_per_fri_repetition=[
            QueriesRef(
                witness=_query(q["witness_query"]),
                stage_2=_query(q["stage_2_query"]),
                quotient=_query(q["quotient_query"]),
                setup=_query(q["setup_query"]),
                fri=[_query(f) for f in q["fri_queries"]],
            )
            for q in raw["queries_per_fri_repetition"]
        ],
        pow_challenge=int(raw["pow_challenge"]),
    )
