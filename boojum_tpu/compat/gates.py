"""Reference-dialect gate verification evaluators (host, extension field).

Each class mirrors one reference `GateConstraintEvaluator`'s `evaluate_once`
EXACTLY — same variable/constant indices, same term emission order — so the
verifier's per-term challenge alignment matches the Rust bytes. Values are
extension elements as (c0, c1) int tuples; `var(i)`/`wit(i)`/`const(i)` are
accessor callables honoring the caller's chunk offsets.

Citations (all under /root/reference/src/cs/gates/):
ConstantsAllocator constant_allocator.rs:107; Fma
fma_gate_without_constant.rs:96; U8x4FMA u32_fma.rs (evaluate_once);
DotProduct dot_product_gate.rs; ZeroCheck zero_check.rs; UIntXAdd
uintx_add.rs; Selection selection_gate.rs; ParallelSelection
parallel_selection.rs; Reduction reduction_gate.rs; Boolean
boolean_allocator.rs; Poseidon2Flattened poseidon2.rs (evaluate_once,
num_terms at :422).
"""

from __future__ import annotations

from ..field import gl
from ..field import extension as ext
from ..hashes import poseidon2_params as p2p
from ..hashes.poseidon2 import _external_mds_s

# Extension scalars are (c0, c1) int tuples over GF(p)[x]/(x^2-7), same as
# the reference's GoldilocksExt2. The host ops live in field/extension.py;
# the aliases keep the verifier code close to the Rust naming.
ONE = ext.ONE_S
ZERO = ext.ZERO_S
e_add = ext.add_s
e_sub = ext.sub_s
e_mul = ext.mul_s
e_mul_base = ext.mul_by_base_s
e_pow = ext.pow_s
e_inv = ext.inv_s


def _from_base(c: int):
    return (int(c) % gl.P, 0)


class ConstantsAllocator:
    """var0 = const0; 1 term, deg 1, principal width 1, constants advance by
    1 per repetition; reps = min(num_constant_columns, copy columns)."""

    num_terms = 1
    per_chunk = (1, 0, 1)  # (vars, wits, consts)

    @staticmethod
    def num_repetitions(geom):
        return min(
            geom["num_constant_columns"],
            geom["num_columns_under_copy_permutation"],
        )

    @staticmethod
    def load_shared(const):
        return None

    @staticmethod
    def evaluate_once(var, wit, const, shared, push):
        push(e_sub(var(0), const(0)))


class Fma:
    """q*a*b + l*c - d = 0; shared constants (q, l); width 4."""

    num_terms = 1
    per_chunk = (4, 0, 0)

    @staticmethod
    def num_repetitions(geom):
        return geom["num_columns_under_copy_permutation"] // 4

    @staticmethod
    def load_shared(const):
        return (const(0), const(1))

    @staticmethod
    def evaluate_once(var, wit, const, shared, push):
        q, l = shared
        contribution = e_mul(var(2), l)
        contribution = e_add(contribution, e_mul(q, e_mul(var(0), var(1))))
        push(e_sub(contribution, var(3)))


class U8x4Fma:
    """u8x4 long-multiplication FMA; 2 terms, width 26 (u32_fma.rs)."""

    num_terms = 2
    per_chunk = (26, 0, 0)

    SH8 = 1 << 8
    SH16 = 1 << 16
    SH24 = 1 << 24
    SH32 = 1 << 32
    SH40 = 1 << 40

    @staticmethod
    def num_repetitions(geom):
        return geom["num_columns_under_copy_permutation"] // 26

    @staticmethod
    def load_shared(const):
        return None

    @classmethod
    def evaluate_once(cls, var, wit, const, shared, push):
        a = [var(i) for i in range(4)]
        b = [var(4 + i) for i in range(4)]
        c = [var(8 + i) for i in range(4)]
        carry = [var(12 + i) for i in range(4)]
        low = [var(16 + i) for i in range(4)]
        high = [var(20 + i) for i in range(4)]
        pc0, pc1 = var(24), var(25)

        def acc(dst, x, k):
            return e_add(dst, e_mul_base(x, k % gl.P))

        contribution = c[0]
        contribution = acc(contribution, c[1], cls.SH8)
        contribution = acc(contribution, c[2], cls.SH16)
        contribution = acc(contribution, c[3], cls.SH24)
        contribution = e_add(contribution, carry[0])
        contribution = acc(contribution, carry[1], cls.SH8)
        contribution = acc(contribution, carry[2], cls.SH16)
        contribution = acc(contribution, carry[3], cls.SH24)
        contribution = acc(contribution, low[0], gl.P - 1)
        contribution = acc(contribution, low[1], gl.P - cls.SH8)
        contribution = acc(contribution, low[2], gl.P - cls.SH16)
        contribution = acc(contribution, low[3], gl.P - cls.SH24)
        contribution = e_add(contribution, e_mul(a[0], b[0]))
        tmp = e_mul(a[1], b[0])
        tmp = e_add(tmp, e_mul(a[0], b[1]))
        contribution = acc(contribution, tmp, cls.SH8)
        tmp = e_mul(a[2], b[0])
        tmp = e_add(tmp, e_mul(a[1], b[1]))
        tmp = e_add(tmp, e_mul(a[0], b[2]))
        contribution = acc(contribution, tmp, cls.SH16)
        tmp = e_mul(a[3], b[0])
        tmp = e_add(tmp, e_mul(a[2], b[1]))
        tmp = e_add(tmp, e_mul(a[1], b[2]))
        tmp = e_add(tmp, e_mul(a[0], b[3]))
        contribution = acc(contribution, tmp, cls.SH24)
        contribution = acc(contribution, pc0, gl.P - cls.SH32 % gl.P)
        contribution = acc(contribution, pc1, gl.P - cls.SH40 % gl.P)
        push(contribution)

        contribution = pc0
        contribution = acc(contribution, pc1, cls.SH8)
        contribution = acc(contribution, high[0], gl.P - 1)
        contribution = acc(contribution, high[1], gl.P - cls.SH8)
        contribution = acc(contribution, high[2], gl.P - cls.SH16)
        contribution = acc(contribution, high[3], gl.P - cls.SH24)
        tmp = e_mul(a[3], b[1])
        tmp = e_add(tmp, e_mul(a[2], b[2]))
        tmp = e_add(tmp, e_mul(a[1], b[3]))
        contribution = e_add(contribution, tmp)
        tmp = e_mul(a[3], b[2])
        tmp = e_add(tmp, e_mul(a[2], b[3]))
        contribution = acc(contribution, tmp, cls.SH8)
        tmp = e_mul(a[3], b[3])
        contribution = acc(contribution, tmp, cls.SH16)
        push(contribution)


class DotProduct4:
    num_terms = 1
    per_chunk = (9, 0, 0)

    @staticmethod
    def num_repetitions(geom):
        return geom["num_columns_under_copy_permutation"] // 9

    @staticmethod
    def load_shared(const):
        return None

    @staticmethod
    def evaluate_once(var, wit, const, shared, push):
        contribution = ZERO
        for idx in range(4):
            contribution = e_add(
                contribution, e_mul(var(2 * idx), var(2 * idx + 1))
            )
        push(e_sub(contribution, var(8)))


class ZeroCheck:
    """flag + input*inv - 1 = 0 and input*flag = 0 (variable-inversion
    variant, use_witness_column_for_inversion = false)."""

    num_terms = 2
    per_chunk = (3, 0, 0)

    @staticmethod
    def num_repetitions(geom):
        return geom["num_columns_under_copy_permutation"] // 3

    @staticmethod
    def load_shared(const):
        return None

    @staticmethod
    def evaluate_once(var, wit, const, shared, push):
        inp, flag, inv_w = var(0), var(1), var(2)
        contribution = e_add(flag, e_mul(inp, inv_w))
        push(e_sub(contribution, ONE))
        push(e_mul(inp, flag))


class UIntXAdd:
    """a + b + carry_in - c - shift*carry_out = 0; carry_out boolean.
    Shared constant (shift = 2^WIDTH) read from the trace."""

    num_terms = 2
    per_chunk = (5, 0, 0)

    @staticmethod
    def num_repetitions(geom):
        return geom["num_columns_under_copy_permutation"] // 5

    @staticmethod
    def load_shared(const):
        return (const(0),)

    @staticmethod
    def evaluate_once(var, wit, const, shared, push):
        (shift,) = shared
        a, b, carry_in, c, carry_out = (var(i) for i in range(5))
        contribution = e_add(e_add(a, b), carry_in)
        contribution = e_sub(contribution, c)
        contribution = e_sub(contribution, e_mul(shift, carry_out))
        push(contribution)
        push(e_sub(e_mul(carry_out, carry_out), carry_out))


class Selection:
    num_terms = 1
    per_chunk = (4, 0, 0)

    @staticmethod
    def num_repetitions(geom):
        return geom["num_columns_under_copy_permutation"] // 4

    @staticmethod
    def load_shared(const):
        return None

    @staticmethod
    def evaluate_once(var, wit, const, shared, push):
        a, b, sel, result = (var(i) for i in range(4))
        contribution = e_mul(a, sel)
        contribution = e_add(contribution, e_mul(e_sub(ONE, sel), b))
        push(e_sub(contribution, result))


class ParallelSelection4:
    num_terms = 4
    per_chunk = (13, 0, 0)

    @staticmethod
    def num_repetitions(geom):
        return geom["num_columns_under_copy_permutation"] // 13

    @staticmethod
    def load_shared(const):
        return None

    @staticmethod
    def evaluate_once(var, wit, const, shared, push):
        sel = var(0)
        for i in range(4):
            a, b, result = var(3 * i + 1), var(3 * i + 2), var(3 * i + 3)
            contribution = e_mul(a, sel)
            contribution = e_add(contribution, e_mul(e_sub(ONE, sel), b))
            push(e_sub(contribution, result))


class Reduction4:
    num_terms = 1
    per_chunk = (5, 0, 0)

    @staticmethod
    def num_repetitions(geom):
        return geom["num_columns_under_copy_permutation"] // 5

    @staticmethod
    def load_shared(const):
        return tuple(const(i) for i in range(4))

    @staticmethod
    def evaluate_once(var, wit, const, shared, push):
        contribution = ZERO
        for i in range(4):
            contribution = e_add(contribution, e_mul(var(i), shared[i]))
        push(e_sub(contribution, var(4)))


class Boolean:
    """x^2 - x = 0 (boolean_allocator.rs); specialized-columns in the Era
    config (1 repetition, share_constants=false)."""

    num_terms = 1
    per_chunk = (1, 0, 0)

    @staticmethod
    def num_repetitions(geom):
        return geom["num_columns_under_copy_permutation"]

    @staticmethod
    def load_shared(const):
        return None

    @staticmethod
    def evaluate_once(var, wit, const, shared, push):
        x = var(0)
        push(e_sub(e_mul(x, x), x))


def _external_matrix():
    """12x12 external-MDS coefficients, derived column-by-column from the
    structural host implementation (same matrix the permutation uses)."""
    cols = []
    for j in range(12):
        unit = [0] * 12
        unit[j] = 1
        cols.append(_external_mds_s(unit))
    # cols[j][i] = M[i][j]
    return [[cols[j][i] for j in range(12)] for i in range(12)]


_EXT_MATRIX = _external_matrix()
_INNER_MATRIX = [
    [
        (p2p.M_I_DIAGONAL[i] + 1) % gl.P if i == j else 1
        for j in range(12)
    ]
    for i in range(12)
]
_RC_ROWS = [
    p2p.ALL_ROUND_CONSTANTS[12 * r : 12 * r + 12] for r in range(30)
]
_FULL_ROUND_CONSTANTS = _RC_ROWS[0:4] + _RC_ROWS[26:30]
_PARTIAL_ROUND_CONSTANTS = [_RC_ROWS[4 + r][0] for r in range(22)]

SW = 12
HALF_FULL = 4
NUM_PARTIAL = 22


class Poseidon2Flattened:
    """Whole Poseidon2 permutation inscribed per row (poseidon2.rs
    evaluate_once): 118 terms, 118 copiable columns, degree 7."""

    num_terms = (HALF_FULL - 1) * SW + NUM_PARTIAL + (HALF_FULL - 1) * SW + SW + SW
    # in(12) + out(12) + first-half sboxes(36) + partial sboxes(22) +
    # second-half sboxes(48): every second-half round resets all 12 elements
    COLUMNS = 2 * SW + (HALF_FULL - 1) * SW + NUM_PARTIAL + HALF_FULL * SW
    per_chunk = (COLUMNS, 0, 0)

    @classmethod
    def num_repetitions(cls, geom):
        return geom["num_columns_under_copy_permutation"] // cls.COLUMNS

    @staticmethod
    def load_shared(const):
        return None

    @classmethod
    def evaluate_once(cls, var, wit, const, shared, push):
        def mat_mul(state, matrix):
            out = []
            for i in range(SW):
                tmp = ZERO
                for src, coeff in zip(state, matrix[i]):
                    tmp = e_add(tmp, e_mul_base(src, coeff))
                out.append(tmp)
            return out

        state = [var(i) for i in range(SW)]
        offset = SW
        output = [var(offset + i) for i in range(SW)]
        offset += SW

        for rnd in range(HALF_FULL):
            if rnd != 0:
                for i in range(SW):
                    sbox_out = var(offset)
                    offset += 1
                    push(e_sub(state[i], sbox_out))
                    state[i] = sbox_out
            else:
                state = mat_mul(state, _EXT_MATRIX)
            for i in range(SW):
                state[i] = e_pow(
                    e_add(state[i], _from_base(_FULL_ROUND_CONSTANTS[rnd][i])),
                    7,
                )
            state = mat_mul(state, _EXT_MATRIX)

        for rnd in range(NUM_PARTIAL):
            state[0] = e_add(
                state[0], _from_base(_PARTIAL_ROUND_CONSTANTS[rnd])
            )
            sbox_out = var(offset)
            offset += 1
            push(e_sub(state[0], sbox_out))
            state[0] = e_pow(sbox_out, 7)
            state = mat_mul(state, _INNER_MATRIX)

        for rnd_idx in range(HALF_FULL):
            rnd = HALF_FULL + rnd_idx
            for i in range(SW):
                sbox_out = var(offset)
                offset += 1
                push(e_sub(state[i], sbox_out))
                state[i] = sbox_out
            for i in range(SW):
                state[i] = e_pow(
                    e_add(state[i], _from_base(_FULL_ROUND_CONSTANTS[rnd][i])),
                    7,
                )
            state = mat_mul(state, _EXT_MATRIX)

        for src, dst in zip(state, output):
            push(e_sub(dst, src))
