"""Reference Fiat-Shamir transcript, bit-for-bit.

`boojum_tpu.transcript.Poseidon2Transcript` / `BitSource` already implement
the reference semantics (`GoldilocksPoisedon2Transcript`,
/root/reference/src/cs/implementations/transcript.rs:48, and `BoolsBuffer`,
:369); the golden-artifact tests pin them to the Rust bytes, so the compat
layer aliases them under the reference names rather than keeping a second
copy of security-critical Fiat-Shamir code.
"""

from __future__ import annotations

from ..transcript import BitSource, Poseidon2Transcript

ReferenceTranscript = Poseidon2Transcript


class BoolsBuffer(BitSource):
    """Reference-named view of BitSource (`available` alias included for
    parity with the Rust field names)."""

    def __init__(self, max_needed: int):
        super().__init__(max_needed)

    @property
    def available(self):
        return self.bits


def u64_from_lsb_first_bits(bits) -> int:
    out = 0
    for shift, bit in enumerate(bits):
        out |= int(bool(bit)) << shift
    return out
