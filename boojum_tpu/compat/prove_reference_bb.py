"""Pure-NumPy reference leg of the BabyBear prover (ISSUE 19).

`compat.prove_reference` closes the transcript-DIALECT loop against the
Rust reference; this module closes the KERNEL loop for the new field
backend: `NumpyBackendBB` re-implements every device kernel the BabyBear
prover dispatches (iNTT, coset LDE, fused quotient sweep, DEEP
accumulation, FRI fold, Merkle commit) as plain vectorized numpy, then
runs the SAME `prover.bb_prover.prove_babybear` flow — same transcript,
same challenge schedule, same checkpoint stream.

Because the prover core is shared, `prove_babybear(pub, cfg,
NumpyBackendBB())` must produce a bit-identical proof and an identical
Fiat–Shamir checkpoint digest sequence to the device backend; any
divergence localizes to exactly one kernel twin. This is the BabyBear
counterpart of the golden-parity harness the Goldilocks leg already has.

No device dispatch anywhere on this path: jax is still *imported*
transitively (the shared host-table module decorates its kernels), but
every array op the reference leg executes is numpy.
"""

from __future__ import annotations

import numpy as np

from ..field import babybear as bb
from ..hashes import poseidon2_bb as p2bb
from ..ntt import bb_ntt
from ..prover import bb_kernels as K
from ..prover.bb_prover import BBProof, BBProofConfig, prove_babybear


def _ext_cols(v) -> tuple:
    """(4,) u32 challenge vector -> ext 4-tuple of numpy scalars."""
    a = np.asarray(v, dtype=np.uint32)
    return tuple(a[k] for k in range(4))


def _base_minus_ext_np(base_arr, e):
    shape = base_arr.shape
    p = np.uint32(bb.P)
    return (
        bb.sub_np(base_arr, np.broadcast_to(e[0], shape)),
        np.broadcast_to((p - e[1]) % p, shape),
        np.broadcast_to((p - e[2]) % p, shape),
        np.broadcast_to((p - e[3]) % p, shape),
    )


class NumpyBackendBB:
    """The numpy twin of DeviceBackendBB: same np-in/np-out method seam,
    kernels replaced by their host reference implementations."""

    def intt(self, values):
        return bb_ntt.ntt_np(np.asarray(values, dtype=np.uint32),
                             inverse=True)

    def lde(self, mono, log_n, lde_factor, shift):
        return bb_ntt.lde_np(np.asarray(mono, dtype=np.uint32),
                             lde_factor, shift)

    def coset_sweep(self, w_lde, alpha, cfg: BBProofConfig, pub: int):
        args = (cfg.log_n, cfg.lde_factor, cfg.shift)
        w_lde = np.asarray(w_lde, dtype=np.uint32)
        wg = np.roll(w_lde, -cfg.lde_factor)
        trans = bb.sub_np(
            wg,
            bb.add_np(bb.mul_np(w_lde, w_lde),
                      np.uint32(cfg.square_c % bb.P)),
        )
        qt = bb.mul_np(bb.mul_np(trans, K.last_row_term_bb(*args)),
                       K.zh_inv_bb(*args))
        qb = bb.mul_np(bb.sub_np(w_lde, np.uint32(pub % bb.P)),
                       K.boundary_inv_bb(*args))
        a = [np.uint32(c) for c in alpha]
        out = [bb.add_np(qt, bb.mul_np(qb, a[0]))]
        out += [bb.mul_np(qb, a[k]) for k in range(1, 4)]
        return np.stack(out)

    def deep(self, w_lde, q_cols, xs, z, gz, wz, wgz, qz, gammas):
        w_lde = np.asarray(w_lde, dtype=np.uint32)
        q_cols = np.asarray(q_cols, dtype=np.uint32)
        xs = np.asarray(xs, dtype=np.uint32)
        g = [_ext_cols(gm) for gm in gammas]
        num = bb.ext_mul_np(
            g[0], _base_minus_ext_np(w_lde, _ext_cols(wz))
        )
        for k in range(4):
            num = bb.ext_add_np(
                num,
                bb.ext_mul_np(
                    g[2 + k],
                    _base_minus_ext_np(q_cols[k], _ext_cols(qz[k])),
                ),
            )
        d1 = bb.ext_mul_np(
            num, bb.ext_inv_np(_base_minus_ext_np(xs, _ext_cols(z)))
        )
        d2 = bb.ext_mul_np(
            bb.ext_mul_np(
                g[1], _base_minus_ext_np(w_lde, _ext_cols(wgz))
            ),
            bb.ext_inv_np(_base_minus_ext_np(xs, _ext_cols(gz))),
        )
        return np.stack(bb.ext_add_np(d1, d2))

    def fold(self, codeword, beta, inv2x):
        codeword = np.asarray(codeword, dtype=np.uint32)
        inv2x = np.asarray(inv2x, dtype=np.uint32)
        half = codeword.shape[-1] // 2
        a = tuple(codeword[k, :half] for k in range(4))
        b = tuple(codeword[k, half:] for k in range(4))
        inv2 = np.uint32(K.INV2)
        even = tuple(
            bb.mul_np(bb.add_np(x, y), inv2) for x, y in zip(a, b)
        )
        odd = tuple(
            bb.mul_np(bb.sub_np(x, y), inv2x) for x, y in zip(a, b)
        )
        out = bb.ext_add_np(
            even, bb.ext_mul_np(_ext_cols(beta), odd)
        )
        return np.stack(out)

    def commit(self, cols, cap_size: int) -> K.BBMerkleTree:
        cols = np.asarray(cols, dtype=np.uint32)
        digests = p2bb.leaf_hash_bb_np(cols.T)
        layers = [digests]
        while layers[-1].shape[0] > cap_size:
            cur = layers[-1]
            layers.append(p2bb.node_hash_bb_np(cur[0::2], cur[1::2]))
        return K.BBMerkleTree(layers, cap_size)


def prove_babybear_reference(
    pub: int, cfg: BBProofConfig | None = None
) -> BBProof:
    """Run the shared BabyBear prover over the numpy kernel twins."""
    return prove_babybear(pub, cfg, backend=NumpyBackendBB())


class NumpyBackendBBFull:
    """Numpy twin of `prover.prover_bb.DeviceBackendBBFull` — the FULL
    PLONKish prover's kernel seam (ISSUE 20): stage-2 grand product,
    lookup polys, the fused gate/cp/lookup quotient sweep and the
    multi-oracle DEEP all run the SAME `stages_bb` cores over the numpy
    lib. A proof from this backend must be byte-identical to the device
    backend's; divergence localizes to one kernel twin."""

    name = "numpy"

    def intt(self, values):
        return bb_ntt.ntt_np(
            np.asarray(values, dtype=np.uint32), inverse=True
        )

    def lde(self, mono, rate, shift=31):
        return bb_ntt.lde_np(
            np.asarray(mono, dtype=np.uint32), rate, shift
        )

    def commit(self, cols, cap_size: int) -> K.BBMerkleTree:
        cols = np.asarray(cols, dtype=np.uint32)
        digests = p2bb.leaf_hash_bb_np(cols.T)
        layers = [digests]
        while layers[-1].shape[0] > cap_size:
            cur = layers[-1]
            layers.append(p2bb.node_hash_bb_np(cur[0::2], cur[1::2]))
        return K.BBMerkleTree(layers, cap_size)

    def stage2(self, copy_vals, sigma_vals, ks, xs, beta, gamma, chunks):
        from ..prover import stages_bb as S

        return S.stage2_z_partials_np(
            np.asarray(copy_vals, np.uint32),
            np.asarray(sigma_vals, np.uint32),
            tuple(int(k) for k in ks), np.asarray(xs, np.uint32),
            beta, gamma, tuple(tuple(c) for c in chunks),
        )

    def lookup_polys(
        self, lookup_cols, tid_col, table_cols, mults, lkb, lkg, R, width
    ):
        from ..prover import stages_bb as S

        return S.lookup_polys_np(
            np.asarray(lookup_cols, np.uint32),
            np.asarray(tid_col, np.uint32),
            np.asarray(table_cols, np.uint32),
            np.asarray(mults, np.uint32), lkb, lkg, R, width,
        )

    def sweep(self, assembly, sweep_ctx, arrays):
        from ..prover import stages_bb as S

        gates, selector_paths, geometry, lk_ctx, non_residues = sweep_ctx
        return S.full_sweep_np(
            gates, selector_paths, geometry, lk_ctx, non_residues,
            *[np.asarray(a, np.uint32) for a in arrays],
        )

    def deep(self, all_lde, zw_cols, lk_cols, pi_cols, xs, z4, zw4,
             ch_tbl, at_z_const, y_zw, y_lk, pi_vals, pi_inv,
             num_lk, num_pi):
        from ..prover import stages_bb as S

        return np.asarray(
            S.deep_full_np(
                np.asarray(all_lde, np.uint32),
                np.asarray(zw_cols, np.uint32),
                np.asarray(lk_cols, np.uint32),
                np.asarray(pi_cols, np.uint32),
                np.asarray(xs, np.uint32),
                np.asarray(z4, np.uint32), np.asarray(zw4, np.uint32),
                np.asarray(ch_tbl, np.uint32),
                np.asarray(at_z_const, np.uint32),
                np.asarray(y_zw, np.uint32), np.asarray(y_lk, np.uint32),
                np.asarray(pi_vals, np.uint32),
                np.asarray(pi_inv, np.uint32),
                num_lk, num_pi,
            )
        )

    def fri_fold(self, codeword, beta4, inv2x):
        codeword = np.asarray(codeword, dtype=np.uint32)
        inv2x = np.asarray(inv2x, dtype=np.uint32)
        half = codeword.shape[-1] // 2
        a = tuple(codeword[k, :half] for k in range(4))
        b = tuple(codeword[k, half:] for k in range(4))
        inv2 = np.uint32(K.INV2)
        even = tuple(
            bb.mul_np(bb.add_np(x, y), inv2) for x, y in zip(a, b)
        )
        odd = tuple(
            bb.mul_np(bb.sub_np(x, y), inv2x) for x, y in zip(a, b)
        )
        out = bb.ext_add_np(
            even, bb.ext_mul_np(_ext_cols(beta4), odd)
        )
        return np.stack(out)


def prove_full_babybear_reference(assembly, setup, config):
    """Run the shared FULL BabyBear prover over the numpy kernel twins
    (same transcript, challenges, checkpoints and proof assembly as the
    device leg — the core is `prover_bb.prove_full_babybear` itself)."""
    from ..prover.prover_bb import prove_full_babybear

    return prove_full_babybear(
        assembly, setup, config, backend=NumpyBackendBBFull()
    )
