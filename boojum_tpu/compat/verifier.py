"""Full reference-dialect verifier (host).

A faithful reimplementation of `Verifier::verify`
(`/root/reference/src/cs/implementations/verifier.rs:888-2520`) over the
parsed artifacts: transcript replay, challenge derivation, the quotient
identity at z (lookup + specialized + general-purpose gate terms + copy
permutation), DEEP quotening, FRI fold simulation with the reference's
folding schedule (`prover.rs:2281`), Merkle/cap checks, and final monomial
evaluation. Gate term order comes from `compat.gates`; the selector paths
come from the VK's `selectors_placement` tree.
"""

from __future__ import annotations

from ..field import gl
from .gates import (
    Boolean,
    ConstantsAllocator,
    DotProduct4,
    Fma,
    ONE,
    ParallelSelection4,
    Poseidon2Flattened,
    Reduction4,
    Selection,
    U8x4Fma,
    UIntXAdd,
    ZERO,
    ZeroCheck,
    e_add,
    e_inv,
    e_mul,
    e_mul_base,
    e_pow,
    e_sub,
)
from .serde import ReferenceProof, ReferenceVk
from .transcript import (
    BoolsBuffer,
    ReferenceTranscript,
    u64_from_lsb_first_bits,
)
from ..hashes.poseidon2 import Poseidon2SpongeHost


def era_main_vm_verifier_config():
    """Gate configuration of the Era main-VM circuit the golden artifacts
    belong to. The general-purpose order is pinned by the golden VK's
    selector tree (gate_idx -> (num_constants, degree) uniquely identifies
    each gate; see /root/reference/vk.json selectors_placement and the gate
    set reconstructed in recursive_verifier.rs:2290-2460)."""
    return {
        "general_purpose_gates": [
            ("constants_allocator", ConstantsAllocator),
            ("u8x4_fma", U8x4Fma),
            ("poseidon2_flattened", Poseidon2Flattened),
            ("dot_product_4", DotProduct4),
            ("zero_check", ZeroCheck),
            ("fma", Fma),
            ("uintx_add_32", UIntXAdd),
            ("selection", Selection),
            ("parallel_selection_4", ParallelSelection4),
            ("nop", None),
            ("reduction_4", Reduction4),
        ],
        # (name, evaluator, num_repetitions, share_constants); order matters
        # for specialized column offsets and challenge consumption. The
        # lookup's specialized columns always come first.
        "specialized_gates": [("boolean", Boolean, 1, False)],
    }


import functools


@functools.lru_cache(maxsize=None)
def make_non_residues(num: int, domain_size: int) -> tuple[int, ...]:
    """Reference utils.rs:636 — successive integers that are quadratic
    non-residues and lie in distinct multiplicative cosets of the domain.
    Cached: the reference-dialect prover hits this once per quotient-coset
    point through `t_accumulator_at`."""
    out: list[int] = []
    current = 1
    legendre_exp = (gl.P - 1) // 2
    while len(out) < num:
        current += 1
        if gl.pow_(current, legendre_exp) != gl.P - 1:
            continue
        tmp = gl.pow_(current, domain_size)
        if tmp == 1:
            continue
        if any(gl.pow_(t, domain_size) == tmp for t in out):
            continue
        out.append(current)
    return tuple(out)


def non_residues_for_copy_permutation(domain_size: int, num_columns: int):
    return [1] + list(make_non_residues(num_columns - 1, domain_size))


def pow_seed_challenges(t):
    """Transcript challenges seeding the Blake2s PoW (verifier.rs:1960):
    256/CHAR_BITS = 4 challenges, plus one because 4 % CHAR_BITS != 0 — a
    reference quirk kept for byte parity. Shared by the verifier and the
    reference-dialect prover so the two transcripts cannot desynchronize."""
    num_chal = 256 // 64
    if num_chal % 64 != 0:
        num_chal += 1
    return t.get_multiple_challenges(num_chal)


def compute_fri_schedule(
    security_bits: int,
    cap_size: int,
    pow_bits: int,
    rate_log_two: int,
    initial_degree_log_two: int,
):
    """Reference prover.rs:2281 — (new_pow_bits, num_queries, schedule,
    final_expected_degree)."""
    assert security_bits > pow_bits
    raw = security_bits - pow_bits
    new_pow_bits = pow_bits
    if raw % rate_log_two != 0:
        deficit = rate_log_two - (raw % rate_log_two)
        if new_pow_bits >= deficit:
            new_pow_bits -= deficit
    raw = security_bits - new_pow_bits
    num_queries = raw // rate_log_two + (1 if raw % rate_log_two else 0)
    candidate = cap_size >> rate_log_two
    folding_stop_degree = max(1, candidate)
    stop_log2 = folding_stop_degree.bit_length() - 1
    degree = initial_degree_log_two
    cap_log2 = cap_size.bit_length() - 1
    schedule = []
    while degree > stop_log2:
        if degree + rate_log_two <= cap_log2:
            break
        if degree - stop_log2 >= 3:
            degree -= 3
            schedule.append(3)
        elif degree - stop_log2 == 2:
            degree -= 2
            schedule.append(2)
        else:
            degree -= 1
            schedule.append(1)
            break
        if degree + rate_log_two <= cap_log2:
            break
    assert degree + rate_log_two >= cap_log2
    return new_pow_bits, num_queries, schedule, 1 << degree


def _verify_merkle_path(leaf_elements, path, cap, idx):
    cur = tuple(Poseidon2SpongeHost.hash_leaf(leaf_elements))
    i = idx
    for sib in path:
        if i & 1 == 0:
            cur = tuple(Poseidon2SpongeHost.hash_node(cur, sib))
        else:
            cur = tuple(Poseidon2SpongeHost.hash_node(sib, cur))
        i >>= 1
    return cur == tuple(cap[i])


def _compute_selector_subpath_at_z(path, buffer, constants):
    """verifier.rs:278 — product over path prefixes of c_b / (1-c_b)."""
    key = tuple(path)
    if key in buffer or not path:
        return
    idx = len(path) - 1
    if len(path) == 1:
        poly = constants[idx]
        buffer[key] = poly if path[0] else e_sub(ONE, poly)
        return
    parent = path[:-1]
    _compute_selector_subpath_at_z(parent, buffer, constants)
    prefix = buffer[tuple(parent)]
    other = constants[idx]
    if path[-1]:
        buffer[key] = e_mul(other, prefix)
    else:
        buffer[key] = e_mul(e_sub(ONE, other), prefix)


def _quotening(acc, sources, values_at, domain_element, at, challenges):
    """(sum of ch_i*(f_i - y_i)) / (x - at) added to acc
    (verifier.rs:2498 quotening_operation)."""
    assert len(sources) == len(values_at) == len(challenges)
    denom = e_inv(e_sub((domain_element % gl.P, 0), at))
    local = ZERO
    for poly_value, value_at, ch in zip(sources, values_at, challenges):
        local = e_add(local, e_mul(ch, e_sub(poly_value, value_at)))
    return e_add(acc, e_mul(local, denom))


def verify_reference_proof(
    vk: ReferenceVk,
    proof: ReferenceProof,
    config=None,
    check_quotient_identity: bool = True,
) -> bool:
    """Run the reference verification algorithm over parsed golden artifacts.

    With ``check_quotient_identity=False`` the algebraic quotient identity at
    z (the only step needing the CIRCUIT's gate configuration, which lives in
    the external era-zkevm_circuits crate, not in the VK) is skipped; all
    byte-level checks still run: transcript replay and challenge derivation,
    lookup sumcheck, proof-shape checks against the VK, FRI schedule
    reproduction, per-query Merkle/cap verification of all oracles, DEEP
    quotening consistency, FRI fold simulation, and final monomial
    evaluation. The gate configuration in `era_main_vm_verifier_config` is a
    best-effort reconstruction pinned by the VK's selector tree; the repo's
    own reconstruction (recursive_verifier.rs:2290) names a gate set whose
    selector tree would differ from this VK's, so the artifacts predate it.

    Malformed/hostile proofs are rejected with False, never an exception.
    """
    try:
        return _verify_impl(vk, proof, config, check_quotient_identity)
    except (KeyError, IndexError, ValueError, TypeError, AssertionError):
        # attacker-controlled JSON with missing fields or bad shapes must
        # reject, not crash the verifier
        return False


def derive_counts(vk, config):
    """Poly/term counts the reference derives from VK + gate config
    (verifier.rs:888 locals). Shared between `_verify_impl` and the
    reference-dialect prover (`compat.prove_reference`) so both sides
    agree on leaf widths, opening counts and challenge partition sizes."""
    lp = vk.lookup_parameters
    num_lookup_subarguments = lp.num_repetitions if lp.is_lookup else 0
    num_multiplicities_polys = 1 if lp.is_lookup else 0
    total_num_lookup_argument_terms = (
        num_lookup_subarguments + num_multiplicities_polys
    )
    lookup_specialized_vars = (
        lp.specialized_columns_per_subargument() * lp.num_repetitions
        if lp.is_lookup
        else 0
    )
    spec_gates = config["specialized_gates"]
    spec_gate_vars = sum(
        g.per_chunk[0] * reps for (_n, g, reps, _s) in spec_gates
    )
    total_vars_specialized = lookup_specialized_vars + spec_gate_vars
    num_variable_polys = (
        vk.num_columns_under_copy_permutation + total_vars_specialized
    )
    num_witness_polys = vk.num_witness_columns
    spec_gate_constants = sum(
        (0 if share else g.per_chunk[2] * reps)
        for (_n, g, reps, share) in spec_gates
    )
    # specialized lookup w/ table id as constant contributes 1 constant col
    lookup_specialized_constants = (
        1
        if (lp.mode == "UseSpecializedColumnsWithTableIdAsConstant")
        else 0
    )
    num_constant_polys = (
        vk.num_constant_columns
        + vk.extra_constant_polys_for_selectors
        + lookup_specialized_constants
        + spec_gate_constants
    )
    quotient_degree = vk.quotient_degree
    num_copy_permutation_polys = num_variable_polys
    c = num_copy_permutation_polys
    num_intermediate = 0
    if c > quotient_degree:
        num_intermediate = (
            c // quotient_degree + (1 if c % quotient_degree else 0) - 1
        )

    geom = {
        "num_columns_under_copy_permutation": (
            vk.num_columns_under_copy_permutation
        ),
        "num_witness_columns": vk.num_witness_columns,
        "num_constant_columns": vk.num_constant_columns,
    }
    gp_gates = config["general_purpose_gates"]
    gp_term_counts = [
        (g.num_terms * g.num_repetitions(geom)) if g is not None else 0
        for (_n, g) in gp_gates
    ]
    total_gp_terms = sum(gp_term_counts)
    spec_term_counts = [
        g.num_terms * reps for (_n, g, reps, _s) in spec_gates
    ]
    total_spec_terms = sum(spec_term_counts)

    total_num_terms = (
        total_num_lookup_argument_terms
        + total_spec_terms
        + total_gp_terms
        + 1
        + 1
        + num_intermediate
    )
    expected_lookup_polys_total = (
        (
            num_lookup_subarguments
            + num_multiplicities_polys * 2
            + lp.width
            + 1
        )
        if lp.is_lookup
        else 0
    )
    num_poly_values_at_z = (
        num_variable_polys
        + num_witness_polys
        + num_constant_polys
        + num_copy_permutation_polys
        + 1
        + num_intermediate
        + expected_lookup_polys_total
        + quotient_degree
    )
    return {
        "num_lookup_subarguments": num_lookup_subarguments,
        "num_multiplicities_polys": num_multiplicities_polys,
        "total_num_lookup_argument_terms": total_num_lookup_argument_terms,
        "lookup_specialized_vars": lookup_specialized_vars,
        "lookup_specialized_constants": lookup_specialized_constants,
        "num_variable_polys": num_variable_polys,
        "num_witness_polys": num_witness_polys,
        "num_constant_polys": num_constant_polys,
        "num_copy_permutation_polys": num_copy_permutation_polys,
        "num_intermediate": num_intermediate,
        "quotient_degree": quotient_degree,
        "geom": geom,
        "total_gp_terms": total_gp_terms,
        "total_spec_terms": total_spec_terms,
        "total_num_terms": total_num_terms,
        "expected_lookup_polys_total": expected_lookup_polys_total,
        "num_poly_values_at_z": num_poly_values_at_z,
    }


def split_alpha_powers(alpha, counts):
    """[1, a, a^2, ...] partitioned lookup | specialized | general | rest
    (copy-permutation) — the reference challenge consumption order."""
    powers = [ONE]
    for _ in range(1, counts["total_num_terms"]):
        powers.append(e_mul(powers[-1], alpha))
    tl = counts["total_num_lookup_argument_terms"]
    ts = counts["total_spec_terms"]
    tg = counts["total_gp_terms"]
    return {
        "lookup": powers[:tl],
        "specialized": powers[tl : tl + ts],
        "general": powers[tl + ts : tl + ts + tg],
        "remaining": powers[tl + ts + tg :],
    }


def t_accumulator_at(point, opened, ch, vk, config, counts):
    """The quotient-identity numerator T(x) at one evaluation point
    (verifier.rs:1242-1650): lookup terms, specialized-gate terms,
    general-purpose gate terms (selector-gated), and the copy-permutation
    terms, each weighted by its alpha-power partition.

    `point`: ext (c0, c1) evaluation point (z for the verifier; quotient-
    coset points for the reference-dialect prover).
    `opened`: dict of poly values at `point` — keys variables, witness,
    constants, sigmas, copy_z, copy_z_shifted, intermediates,
    multiplicities, lookup_a, mult_encoding, tables (lists of ext tuples).
    `ch`: dict with beta, gamma, lookup_beta, lookup_gamma and the alpha
    partitions from `split_alpha_powers`.
    """
    lp = vk.lookup_parameters
    spec_gates = config["specialized_gates"]
    gp_gates = config["general_purpose_gates"]
    geom = counts["geom"]
    quotient_degree = counts["quotient_degree"]
    num_lookup_subarguments = counts["num_lookup_subarguments"]

    variables_polys_values = opened["variables"]
    witness_polys_values = opened["witness"]
    constant_poly_values = opened["constants"]
    sigmas_values = opened["sigmas"]
    copy_permutation_z_at_z = opened["copy_z"]
    copy_permutation_z_at_z_omega = opened["copy_z_shifted"]
    grand_product_intermediate_polys = opened["intermediates"]
    multiplicities_polys_values = opened["multiplicities"]
    lookup_witness_encoding_polys_values = opened["lookup_a"]
    multiplicities_encoding_polys_values = opened["mult_encoding"]
    lookup_tables_columns = opened["tables"]

    t_accumulator = ZERO

    selectors_buffer = {}
    for gate_idx, (_name, g) in enumerate(gp_gates):
        path = vk.selectors_placement.output_placement(gate_idx)
        if path is not None:
            _compute_selector_subpath_at_z(
                path, selectors_buffer, constant_poly_values
            )
        else:
            assert g is None or g.num_terms == 0, _name

    if lp.is_lookup:
        lookup_beta = ch["lookup_beta"]
        lookup_gamma = ch["lookup_gamma"]
        assert lp.mode.startswith("UseSpecializedColumns"), (
            "only the specialized-columns lookup mode is implemented"
        )
        col_per_subarg = lp.specialized_columns_per_subargument()
        capacity = col_per_subarg + (
            1 if len(vk.table_ids_column_idxes) == 1 else 0
        )
        powers_of_gamma = [ONE]
        for _ in range(1, capacity):
            powers_of_gamma.append(
                e_mul(powers_of_gamma[-1], lookup_gamma)
            )
        lookup_table_columns_aggregated = lookup_beta
        for gpow, column in zip(powers_of_gamma, lookup_tables_columns):
            lookup_table_columns_aggregated = e_add(
                lookup_table_columns_aggregated, e_mul(gpow, column)
            )
        ch_it = iter(ch["lookup"])
        base = vk.num_columns_under_copy_permutation
        variables_for_lookup = variables_polys_values[
            base : base + col_per_subarg * num_lookup_subarguments
        ]
        table_id = (
            [constant_poly_values[vk.table_ids_column_idxes[0]]]
            if vk.table_ids_column_idxes
            else []
        )
        for i, a_poly in enumerate(lookup_witness_encoding_polys_values):
            cols = variables_for_lookup[
                i * col_per_subarg : (i + 1) * col_per_subarg
            ]
            contribution = lookup_beta
            for gpow, column in zip(powers_of_gamma, list(cols) + table_id):
                contribution = e_add(contribution, e_mul(gpow, column))
            contribution = e_mul(contribution, a_poly)
            contribution = e_sub(contribution, ONE)
            contribution = e_mul(contribution, next(ch_it))
            t_accumulator = e_add(t_accumulator, contribution)
        for b_poly, mult in zip(
            multiplicities_encoding_polys_values, multiplicities_polys_values
        ):
            contribution = e_mul(lookup_table_columns_aggregated, b_poly)
            contribution = e_sub(contribution, mult)
            contribution = e_mul(contribution, next(ch_it))
            t_accumulator = e_add(t_accumulator, contribution)

    constants_for_gp = (
        vk.num_constant_columns + vk.extra_constant_polys_for_selectors
    )

    # specialized gates (each with selector ONE, own column subranges)
    ch_off = 0
    var_off = (
        vk.num_columns_under_copy_permutation
        + counts["lookup_specialized_vars"]
    )
    const_off = constants_for_gp + counts["lookup_specialized_constants"]
    for (_name, g, reps, share) in spec_gates:
        vw, ww, cw = g.per_chunk
        gate_acc = ZERO
        term_i = 0
        for rep in range(reps):
            vo = var_off + rep * vw
            co = const_off + (0 if share else rep * cw)

            def var(i, _vo=vo):
                return variables_polys_values[_vo + i]

            def wit(i):
                return witness_polys_values[i]

            def const(i, _co=co):
                return constant_poly_values[_co + i]

            terms = []
            g.evaluate_once(var, wit, const, g.load_shared(const), terms.append)
            for term in terms:
                gate_acc = e_add(
                    gate_acc,
                    e_mul(term, ch["specialized"][ch_off + term_i]),
                )
                term_i += 1
        t_accumulator = e_add(t_accumulator, gate_acc)
        ch_off += g.num_terms * reps
        var_off += vw * reps
        const_off += 0 if share else cw * reps
    assert ch_off == counts["total_spec_terms"]

    # general purpose gates
    ch_off = 0
    for gate_idx, (_name, g) in enumerate(gp_gates):
        if g is None or g.num_terms == 0:
            continue
        path = vk.selectors_placement.output_placement(gate_idx)
        selector = selectors_buffer.pop(tuple(path))
        constant_placement_offset = len(path)
        reps = g.num_repetitions(geom)
        vw, _ww, cw = g.per_chunk

        def const_shared(i, _o=constant_placement_offset):
            return constant_poly_values[_o + i]

        shared = g.load_shared(const_shared)
        gate_acc = ZERO
        term_i = 0
        for rep in range(reps):
            vo = rep * vw
            co = constant_placement_offset + rep * cw

            def var(i, _vo=vo):
                return variables_polys_values[_vo + i]

            def wit(i):
                return witness_polys_values[i]

            def const(i, _co=co):
                return constant_poly_values[_co + i]

            terms = []
            g.evaluate_once(var, wit, const, shared, terms.append)
            assert len(terms) == g.num_terms, _name
            for term in terms:
                gate_acc = e_add(
                    gate_acc, e_mul(term, ch["general"][ch_off + term_i])
                )
                term_i += 1
        # destination.advance(): accumulator *= selector, once per gate
        t_accumulator = e_add(t_accumulator, e_mul(gate_acc, selector))
        ch_off += g.num_terms * reps
    assert ch_off == counts["total_gp_terms"]

    # copy permutation
    beta = ch["beta"]
    gamma = ch["gamma"]
    non_residues = non_residues_for_copy_permutation(
        vk.domain_size, counts["num_variable_polys"]
    )
    z_in_domain_size = e_pow(point, vk.domain_size)
    vanishing_at_z = e_sub(z_in_domain_size, ONE)
    ch_it = iter(ch["remaining"])
    # z(1) == 1 via unnormalized L1
    unnorm_l1_inv_at_z = e_mul(vanishing_at_z, e_inv(e_sub(point, ONE)))
    contribution = e_sub(copy_permutation_z_at_z, ONE)
    contribution = e_mul(contribution, unnorm_l1_inv_at_z)
    contribution = e_mul(contribution, next(ch_it))
    t_accumulator = e_add(t_accumulator, contribution)

    lhs_seq = grand_product_intermediate_polys + [
        copy_permutation_z_at_z_omega
    ]
    rhs_seq = [copy_permutation_z_at_z] + grand_product_intermediate_polys

    def chunks(seq, k):
        return [seq[i : i + k] for i in range(0, len(seq), k)]

    for lhs, rhs, chal, nr_chunk, var_chunk, sigma_chunk in zip(
        lhs_seq,
        rhs_seq,
        ch_it,
        chunks(non_residues, quotient_degree),
        chunks(variables_polys_values, quotient_degree),
        chunks(sigmas_values, quotient_degree),
    ):
        lhs_acc = lhs
        for variable, sigma in zip(var_chunk, sigma_chunk):
            subres = e_mul(sigma, beta)
            subres = e_add(subres, variable)
            subres = e_add(subres, gamma)
            lhs_acc = e_mul(lhs_acc, subres)
        rhs_acc = rhs
        for non_res, variable in zip(nr_chunk, var_chunk):
            subres = e_mul_base(point, non_res)
            subres = e_mul(subres, beta)
            subres = e_add(subres, variable)
            subres = e_add(subres, gamma)
            rhs_acc = e_mul(rhs_acc, subres)
        contribution = e_mul(e_sub(lhs_acc, rhs_acc), chal)
        t_accumulator = e_add(t_accumulator, contribution)
    return t_accumulator


def _verify_impl(vk, proof, config, check_quotient_identity):
    if config is None:
        config = era_main_vm_verifier_config()

    lp = vk.lookup_parameters
    pc = proof.proof_config
    if vk.cap_size != pc["merkle_tree_cap_size"]:
        return False
    if vk.fri_lde_factor != pc["fri_lde_factor"]:
        return False
    if vk.cap_size != len(vk.setup_merkle_tree_cap):
        return False
    if len(proof.public_inputs) != len(vk.public_inputs_locations):
        return False

    t = ReferenceTranscript()
    t.witness_merkle_tree_cap(vk.setup_merkle_tree_cap)
    public_inputs_with_values = []
    for (column, row), value in zip(
        vk.public_inputs_locations, proof.public_inputs
    ):
        public_inputs_with_values.append((column, row, value))
        t.witness_field_elements([value])
    if vk.cap_size != len(proof.witness_oracle_cap):
        return False
    t.witness_merkle_tree_cap(proof.witness_oracle_cap)
    beta = (t.get_challenge(), t.get_challenge())
    gamma = (t.get_challenge(), t.get_challenge())
    if lp.is_lookup:
        lookup_beta = (t.get_challenge(), t.get_challenge())
        lookup_gamma = (t.get_challenge(), t.get_challenge())
    if vk.cap_size != len(proof.stage_2_oracle_cap):
        return False
    t.witness_merkle_tree_cap(proof.stage_2_oracle_cap)
    alpha = (t.get_challenge(), t.get_challenge())

    counts = derive_counts(vk, config)
    num_lookup_subarguments = counts["num_lookup_subarguments"]
    num_multiplicities_polys = counts["num_multiplicities_polys"]
    total_num_lookup_argument_terms = counts[
        "total_num_lookup_argument_terms"
    ]
    num_variable_polys = counts["num_variable_polys"]
    num_witness_polys = counts["num_witness_polys"]
    num_constant_polys = counts["num_constant_polys"]
    num_copy_permutation_polys = counts["num_copy_permutation_polys"]
    num_intermediate = counts["num_intermediate"]
    quotient_degree = counts["quotient_degree"]
    alpha_partitions = split_alpha_powers(alpha, counts)

    if vk.cap_size != len(proof.quotient_oracle_cap):
        return False
    t.witness_merkle_tree_cap(proof.quotient_oracle_cap)
    z = (t.get_challenge(), t.get_challenge())
    for v in proof.values_at_z:
        t.witness_field_elements(v)
    for v in proof.values_at_z_omega:
        t.witness_field_elements(v)
    for v in proof.values_at_0:
        t.witness_field_elements(v)

    omega = gl.omega(vk.domain_size.bit_length() - 1)
    # public input opening tuples grouped by opening point
    public_input_opening_tuples = []
    for column, row, value in public_inputs_with_values:
        open_at = gl.pow_(omega, row)
        for el in public_input_opening_tuples:
            if el[0] == open_at:
                el[1].append((column, value))
                break
        else:
            public_input_opening_tuples.append([open_at, [(column, value)]])

    if len(proof.values_at_z) != counts["num_poly_values_at_z"]:
        return False
    if len(proof.values_at_z_omega) != 1:
        return False
    if len(proof.values_at_0) != total_num_lookup_argument_terms:
        return False

    # ---- quotient identity at z ------------------------------------------
    it = iter(proof.values_at_z)

    def take(n):
        return [next(it) for _ in range(n)]

    opened = {
        "variables": take(num_variable_polys),
        "witness": take(num_witness_polys),
        "constants": take(num_constant_polys),
        "sigmas": take(num_copy_permutation_polys),
        "copy_z": take(1)[0],
        "intermediates": take(num_intermediate),
        "multiplicities": take(num_multiplicities_polys),
        "lookup_a": take(num_lookup_subarguments),
        "mult_encoding": take(num_multiplicities_polys),
        "tables": take((lp.width + 1) if lp.is_lookup else 0),
        "copy_z_shifted": proof.values_at_z_omega[0],
    }
    quotient_chunks = list(it)
    assert len(quotient_chunks) == quotient_degree

    if lp.is_lookup:
        # sumcheck: sum A_i(0) == sum B(0)
        a_sum = ZERO
        for v in proof.values_at_0[:num_lookup_subarguments]:
            a_sum = e_add(a_sum, v)
        b_sum = ZERO
        for v in proof.values_at_0[num_lookup_subarguments:]:
            b_sum = e_add(b_sum, v)
        if a_sum != b_sum:
            return False

    challenges = dict(alpha_partitions)
    challenges["beta"] = beta
    challenges["gamma"] = gamma
    if lp.is_lookup:
        challenges["lookup_beta"] = lookup_beta
        challenges["lookup_gamma"] = lookup_gamma
    t_accumulator = t_accumulator_at(z, opened, challenges, vk, config, counts)

    z_in_domain_size = e_pow(z, vk.domain_size)
    vanishing_at_z = e_sub(z_in_domain_size, ONE)
    t_from_chunks = ZERO
    pow_acc = ONE
    for el in quotient_chunks:
        t_from_chunks = e_add(t_from_chunks, e_mul(el, pow_acc))
        pow_acc = e_mul(pow_acc, z_in_domain_size)
    t_from_chunks = e_mul(t_from_chunks, vanishing_at_z)
    if check_quotient_identity and t_accumulator != t_from_chunks:
        return False

    # ---- DEEP + FRI -------------------------------------------------------
    c0 = t.get_challenge()
    c1 = t.get_challenge()
    total_num_challenges = (
        len(proof.values_at_z)
        + len(proof.values_at_z_omega)
        + len(proof.values_at_0)
        + sum(len(s[1]) for s in public_input_opening_tuples)
    )
    deep_challenges = [ONE, (c0, c1)]
    cur = (c0, c1)
    for _ in range(2, total_num_challenges):
        cur = e_mul(cur, (c0, c1))
        deep_challenges.append(cur)
    deep_challenges = deep_challenges[:total_num_challenges]

    rate_log_two = vk.fri_lde_factor.bit_length() - 1
    new_pow_bits, num_queries, schedule, final_expected_degree = (
        compute_fri_schedule(
            pc["security_level"],
            pc["merkle_tree_cap_size"],
            pc["pow_bits"],
            rate_log_two,
            vk.domain_size.bit_length() - 1,
        )
    )
    if new_pow_bits != pc["pow_bits"]:
        return False

    expected_degree = vk.domain_size
    fri_intermediate_challenges = []
    if vk.cap_size != len(proof.fri_base_oracle_cap):
        return False
    t.witness_merkle_tree_cap(proof.fri_base_oracle_cap)
    c0 = t.get_challenge()
    c1 = t.get_challenge()
    chs = [(c0, c1)]
    cur = (c0, c1)
    for _ in range(1, schedule[0]):
        cur = e_mul(cur, cur)
        chs.append(cur)
    fri_intermediate_challenges.append(chs)
    expected_degree >>= schedule[0]

    if len(schedule[1:]) != len(proof.fri_intermediate_oracles_caps):
        return False
    for deg_log2, cap in zip(
        schedule[1:], proof.fri_intermediate_oracles_caps
    ):
        if vk.cap_size != len(cap):
            return False
        t.witness_merkle_tree_cap(cap)
        c0 = t.get_challenge()
        c1 = t.get_challenge()
        chs = [(c0, c1)]
        cur = (c0, c1)
        for _ in range(1, deg_log2):
            cur = e_mul(cur, cur)
            chs.append(cur)
        fri_intermediate_challenges.append(chs)
        expected_degree >>= deg_log2
    if final_expected_degree != expected_degree:
        return False
    if expected_degree != len(proof.final_fri_monomials[0]):
        return False
    if expected_degree != len(proof.final_fri_monomials[1]):
        return False
    t.witness_field_elements(proof.final_fri_monomials[0])
    t.witness_field_elements(proof.final_fri_monomials[1])

    if new_pow_bits != 0:
        challenges = pow_seed_challenges(t)
        # Blake2s PoW runner semantics (pow.rs:8,93): seed = challenges as
        # LE bytes; digest's first LE u64 needs pow_bits trailing zeros
        import hashlib

        seed = b"".join(int(c).to_bytes(8, "little") for c in challenges)
        digest = hashlib.blake2s(
            seed + int(proof.pow_challenge).to_bytes(8, "little")
        ).digest()
        word = int.from_bytes(digest[:8], "little")
        if word & ((1 << pc["pow_bits"]) - 1) != 0:
            return False
        low = proof.pow_challenge & 0xFFFFFFFF
        high = proof.pow_challenge >> 32
        t.witness_field_elements([low, high])

    lde_domain_size = vk.domain_size * vk.fri_lde_factor
    max_needed_bits = lde_domain_size.bit_length() - 1
    bools_buffer = BoolsBuffer(max_needed=max_needed_bits)
    num_bits_for_in_coset_index = max_needed_bits - rate_log_two
    base_tree_index_shift = vk.domain_size.bit_length() - 1
    assert num_bits_for_in_coset_index == base_tree_index_shift

    precomputed_powers = []
    precomputed_powers_inversed = []
    for i in range(lde_domain_size.bit_length()):
        w = gl.omega(i) if i else 1
        precomputed_powers.append(w)
        precomputed_powers_inversed.append(gl.inv(w))

    # interpolation steps: [1, w4^-1, w8^-1, w4^-1 * w8^-1]
    interpolation_steps = [1, 1, 1, 1]
    for idx in (1, 3):
        interpolation_steps[idx] = gl.mul(
            interpolation_steps[idx], precomputed_powers_inversed[2]
        )
    for idx in (2, 3):
        interpolation_steps[idx] = gl.mul(
            interpolation_steps[idx], precomputed_powers_inversed[3]
        )

    if num_queries != len(proof.queries_per_fri_repetition):
        return False

    base_oracle_depth = (
        lde_domain_size.bit_length() - 1 - (vk.cap_size.bit_length() - 1)
    )
    witness_leaf_size = (
        num_variable_polys + num_witness_polys + num_multiplicities_polys
    )
    stage_2_leaf_size = (
        1
        + num_intermediate
        + num_lookup_subarguments
        + num_multiplicities_polys
    ) * 2
    quotient_leaf_size = quotient_degree * 2
    setup_leaf_size = (
        num_copy_permutation_polys
        + num_constant_polys
        + ((lp.width + 1) if lp.is_lookup else 0)
    )

    z_polys_offset = 0
    intermediate_polys_offset = 2
    lookup_witness_encoding_polys_offset = (
        intermediate_polys_offset + num_intermediate * 2
    )
    lookup_multiplicities_encoding_polys_offset = (
        lookup_witness_encoding_polys_offset + num_lookup_subarguments * 2
    )
    constants_offset = num_copy_permutation_polys
    lookup_tables_values_offset = (
        num_copy_permutation_polys + num_constant_polys
    )
    lookup_multiplicities_offset = num_variable_polys + num_witness_polys
    base_coset_inverse = gl.inv(gl.MULTIPLICATIVE_GENERATOR)

    def cast_base(els):
        return [(int(e) % gl.P, 0) for e in els]

    def cast_ext(els):
        assert len(els) % 2 == 0
        return [
            (int(els[i]) % gl.P, int(els[i + 1]) % gl.P)
            for i in range(0, len(els), 2)
        ]

    z_omega = e_mul_base(z, omega)

    for q in proof.queries_per_fri_repetition:
        bits = bools_buffer.get_bits(t, max_needed_bits)
        inner_idx = u64_from_lsb_first_bits(
            bits[:num_bits_for_in_coset_index]
        )
        coset_idx = u64_from_lsb_first_bits(
            bits[num_bits_for_in_coset_index:]
        )
        base_tree_idx = (coset_idx << base_tree_index_shift) + inner_idx

        if len(q.witness.leaf_elements) != witness_leaf_size:
            return False
        if len(q.witness.proof) != base_oracle_depth:
            return False
        if not _verify_merkle_path(
            q.witness.leaf_elements,
            q.witness.proof,
            proof.witness_oracle_cap,
            base_tree_idx,
        ):
            return False
        if len(q.stage_2.leaf_elements) != stage_2_leaf_size:
            return False
        if len(q.stage_2.proof) != base_oracle_depth:
            return False
        if not _verify_merkle_path(
            q.stage_2.leaf_elements,
            q.stage_2.proof,
            proof.stage_2_oracle_cap,
            base_tree_idx,
        ):
            return False
        if len(q.quotient.leaf_elements) != quotient_leaf_size:
            return False
        if len(q.quotient.proof) != base_oracle_depth:
            return False
        if not _verify_merkle_path(
            q.quotient.leaf_elements,
            q.quotient.proof,
            proof.quotient_oracle_cap,
            base_tree_idx,
        ):
            return False
        if len(q.setup.leaf_elements) != setup_leaf_size:
            return False
        if len(q.setup.proof) != base_oracle_depth:
            return False
        if not _verify_merkle_path(
            q.setup.leaf_elements,
            q.setup.proof,
            vk.setup_merkle_tree_cap,
            base_tree_idx,
        ):
            return False

        # domain element from LSB-first bits
        domain_element = 1
        for a, b in zip(bits, precomputed_powers[1:]):
            if a:
                domain_element = gl.mul(domain_element, b)

        power_chunks = []
        skip_highest_powers = 0
        for deg_log2 in schedule:
            el = 1
            pairs = list(
                zip(
                    bits[skip_highest_powers:],
                    precomputed_powers_inversed[1:],
                )
            )[deg_log2:]
            for a, b in pairs:
                if a:
                    el = gl.mul(el, b)
            skip_highest_powers += deg_log2
            power_chunks.append(el)

        domain_element_for_quotiening = gl.mul(
            domain_element, gl.MULTIPLICATIVE_GENERATOR
        )
        domain_element_for_interpolation = domain_element_for_quotiening

        simulated = ZERO
        challenge_offset = 0
        sources = []
        sources += cast_base(
            q.witness.leaf_elements[:num_variable_polys]
        )
        sources += cast_base(
            q.witness.leaf_elements[
                num_variable_polys : num_variable_polys + num_witness_polys
            ]
        )
        sources += cast_base(
            q.setup.leaf_elements[
                constants_offset : constants_offset + num_constant_polys
            ]
        )
        sources += cast_base(
            q.setup.leaf_elements[:num_copy_permutation_polys]
        )
        sources += cast_ext(
            q.stage_2.leaf_elements[
                z_polys_offset:lookup_witness_encoding_polys_offset
            ]
        )
        if lp.is_lookup:
            sources += cast_base(
                q.witness.leaf_elements[
                    lookup_multiplicities_offset : lookup_multiplicities_offset
                    + num_multiplicities_polys
                ]
            )
            sources += cast_ext(
                q.stage_2.leaf_elements[
                    lookup_witness_encoding_polys_offset:
                ]
            )
            sources += cast_base(
                q.setup.leaf_elements[
                    lookup_tables_values_offset : lookup_tables_values_offset
                    + lp.width
                    + 1
                ]
            )
        sources += cast_ext(q.quotient.leaf_elements)
        assert len(sources) == len(proof.values_at_z)
        simulated = _quotening(
            simulated,
            sources,
            proof.values_at_z,
            domain_element_for_quotiening,
            z,
            deep_challenges[
                challenge_offset : challenge_offset + len(sources)
            ],
        )
        challenge_offset += len(sources)

        sources_zw = cast_ext(
            q.stage_2.leaf_elements[z_polys_offset:intermediate_polys_offset]
        )
        simulated = _quotening(
            simulated,
            sources_zw,
            proof.values_at_z_omega,
            domain_element_for_quotiening,
            z_omega,
            deep_challenges[
                challenge_offset : challenge_offset + len(sources_zw)
            ],
        )
        challenge_offset += len(sources_zw)

        if lp.is_lookup:
            sources_0 = cast_ext(
                q.stage_2.leaf_elements[
                    lookup_witness_encoding_polys_offset:
                ]
            )
            simulated = _quotening(
                simulated,
                sources_0,
                proof.values_at_0,
                domain_element_for_quotiening,
                ZERO,
                deep_challenges[
                    challenge_offset : challenge_offset + len(sources_0)
                ],
            )
            challenge_offset += len(sources_0)

        for open_at, subset in public_input_opening_tuples:
            srcs = []
            vals = []
            for column, expected in subset:
                srcs.append(
                    (int(q.witness.leaf_elements[column]) % gl.P, 0)
                )
                vals.append((int(expected) % gl.P, 0))
            simulated = _quotening(
                simulated,
                srcs,
                vals,
                domain_element_for_quotiening,
                (open_at, 0),
                deep_challenges[
                    challenge_offset : challenge_offset + len(srcs)
                ],
            )
            challenge_offset += len(srcs)
        assert challenge_offset == len(deep_challenges)

        current_folded_value = simulated
        subidx = base_tree_idx
        coset_inverse = base_coset_inverse
        if len(schedule) != len(q.fri):
            return False
        expected_fri_query_len = base_oracle_depth
        for idx, (deg_log2, fri_query) in enumerate(zip(schedule, q.fri)):
            expected_fri_query_len -= deg_log2
            interpolation_degree = 1 << deg_log2
            subidx_in_leaf = subidx % interpolation_degree
            tree_idx = subidx >> deg_log2
            if (
                current_folded_value[0]
                != int(fri_query.leaf_elements[subidx_in_leaf]) % gl.P
                or current_folded_value[1]
                != int(
                    fri_query.leaf_elements[
                        interpolation_degree + subidx_in_leaf
                    ]
                )
                % gl.P
            ):
                return False
            cap = (
                proof.fri_base_oracle_cap
                if idx == 0
                else proof.fri_intermediate_oracles_caps[idx - 1]
            )
            if len(fri_query.leaf_elements) != interpolation_degree * 2:
                return False
            if len(fri_query.proof) != expected_fri_query_len:
                return False
            if not _verify_merkle_path(
                fri_query.leaf_elements, fri_query.proof, cap, tree_idx
            ):
                return False

            # leaf layout: interpolation_degree c0s then as many c1s
            elements = [
                (
                    int(fri_query.leaf_elements[i]) % gl.P,
                    int(fri_query.leaf_elements[interpolation_degree + i])
                    % gl.P,
                )
                for i in range(interpolation_degree)
            ]
            challenges = fri_intermediate_challenges[idx]
            assert len(challenges) == deg_log2
            base_pow = power_chunks[idx]
            for ch in challenges:
                nxt = []
                for i in range(len(elements) // 2):
                    a = elements[2 * i]
                    b = elements[2 * i + 1]
                    result = e_add(a, b)
                    diff = e_mul(e_sub(a, b), ch)
                    powv = gl.mul(
                        gl.mul(base_pow, interpolation_steps[i]),
                        coset_inverse,
                    )
                    diff = e_mul_base(diff, powv)
                    nxt.append(e_add(result, diff))
                elements = nxt
                base_pow = gl.mul(base_pow, base_pow)
                coset_inverse = gl.mul(coset_inverse, coset_inverse)
            for _ in range(deg_log2):
                domain_element_for_interpolation = gl.mul(
                    domain_element_for_interpolation,
                    domain_element_for_interpolation,
                )
            subidx = tree_idx
            current_folded_value = elements[0]

        # evaluate final monomials by Horner at the interpolation point
        result_from_monomial = ZERO
        for mc0, mc1 in zip(
            reversed(proof.final_fri_monomials[0]),
            reversed(proof.final_fri_monomials[1]),
        ):
            result_from_monomial = e_mul_base(
                result_from_monomial, domain_element_for_interpolation
            )
            result_from_monomial = e_add(
                result_from_monomial, (int(mc0) % gl.P, int(mc1) % gl.P)
            )
        if result_from_monomial != current_folded_value:
            return False

    return True
