"""Byte-level compatibility layer with the reference (Rust) Boojum dialect.

`serde` parses the reference's `proof.json` / `vk.json` artifacts, `transcript`
replays its Fiat-Shamir transcript bit-for-bit, and `verifier` runs the
reference verification algorithm (`/root/reference/src/cs/implementations/
verifier.rs:888`) on host. Verifying the repo's golden Era main-VM proof pins
Poseidon2, sponge/transcript byte order, Merkle/cap hashing, BoolsBuffer query
drawing, FRI folding schedules, and DEEP quotening to the Rust implementation.
The gate-constraint evaluators in `gates` follow the reference gate sources
but are NOT pinned by the golden artifacts: the quotient identity at z needs
the external era-zkevm_circuits gate configuration (see verifier docstring).
"""

from .serde import ReferenceProof, ReferenceVk, load_proof, load_vk
from .transcript import BoolsBuffer, ReferenceTranscript
from .verifier import (
    compute_fri_schedule,
    era_main_vm_verifier_config,
    make_non_residues,
    verify_reference_proof,
)

__all__ = [
    "ReferenceProof",
    "ReferenceVk",
    "load_proof",
    "load_vk",
    "BoolsBuffer",
    "ReferenceTranscript",
    "compute_fri_schedule",
    "era_main_vm_verifier_config",
    "make_non_residues",
    "verify_reference_proof",
]
