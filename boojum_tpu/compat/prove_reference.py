"""Reference-DIALECT prover for circuits built by this framework.

`compat.export` closes the SCHEMA loop (own proofs serialized in the
reference's serde layout, verified by this framework's own verifier). This
module closes the DIALECT loop: it produces proofs in the reference's
*transcript dialect* — the reference's challenge partition order, single
ext-value openings for stage-2/quotient polynomials, its small-QNR
copy-permutation non-residues, quotient-degree-sized grand-product chunks,
unnormalized-L1 boundary term, c0s-then-c1s FRI leaves and
`compute_fri_schedule`-derived folding — so the finished proof passes
`compat.verifier.verify_reference_proof` (the byte-level reimplementation of
`/root/reference/src/cs/implementations/verifier.rs:888` that also verifies
the golden Era artifacts) INCLUDING the full quotient identity at z.

Counterpart: `/root/reference/src/cs/implementations/prover.rs:153`
(`prove_cpu_basic`). This is a host-side parity prover for small circuits —
the performance path stays `prover.prove`; what this buys is an executable
bit-level contract with the reference protocol on circuits whose gate
configuration is fully known (unlike the external Era main-VM circuit).

Shared machinery (already pinned to the Rust bytes by the golden tests):
`ReferenceTranscript`/`BoolsBuffer` Fiat-Shamir, `MerkleTreeWithCap`
(enumeration proven identical to the reference's full-domain bit-reversed
tree indexing), `t_accumulator_at`/`derive_counts`/`split_alpha_powers`
(extracted from `_verify_impl`), and the NTT/LDE kernels.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass

import numpy as np

from ..field import gl
from ..field import extension as ext
from ..merkle import MerkleTreeWithCap
from ..prover.setup import (
    build_selector_tree,
    build_constant_columns,
    compute_sigma_values,
)
from .own_config import verifier_config_for_assembly
from .serde import LookupParametersRef, ReferenceVk
from .transcript import BoolsBuffer, ReferenceTranscript
from .verifier import (
    compute_fri_schedule,
    derive_counts,
    non_residues_for_copy_permutation,
    pow_seed_challenges,
    split_alpha_powers,
    t_accumulator_at,
)
# flight-recorder digest checkpoints (no-op unless recording): the SAME
# (round, label) stream as prover/prover.py, so a bit-parity break between
# the TPU prover and this CPU reference localizes to the first diverging
# Fiat–Shamir round via scripts/prove_report.py --diff
from ..utils.report import checkpoint as _checkpoint

ONE = ext.ONE_S
ZERO = ext.ZERO_S
W_EXT = (0, 1)  # extension generator (x^2 = 7)
e_add = ext.add_s
e_sub = ext.sub_s
e_mul = ext.mul_s
e_mul_base = ext.mul_by_base_s
e_pow = ext.pow_s
e_inv = ext.inv_s


# ---------------------------------------------------------------------------
# small host helpers
# ---------------------------------------------------------------------------


def _batch_inv_ext(values):
    """Montgomery batch inversion over ext tuples."""
    prefix = [ONE]
    for v in values:
        prefix.append(e_mul(prefix[-1], v))
    inv_all = e_inv(prefix[-1])
    out = [None] * len(values)
    for i in range(len(values) - 1, -1, -1):
        out[i] = e_mul(prefix[i], inv_all)
        inv_all = e_mul(inv_all, values[i])
    return out


def _pow_table(base: int, count: int):
    out = [1] * count
    cur = 1
    for i in range(count):
        out[i] = cur
        cur = gl.mul(cur, base)
    return out


def _brev(i: int, bits: int) -> int:
    out = 0
    for b in range(bits):
        out |= ((i >> b) & 1) << (bits - 1 - b)
    return out


def _eval_plane_at_ext(coeffs, z):
    """Horner evaluation of a base-coefficient poly at an ext point."""
    acc = ZERO
    for c in reversed(coeffs):
        acc = e_add(e_mul(acc, z), (int(c), 0))
    return acc


def _to_mono(values_2d):
    """(cols, n) natural-row-order host values -> host monomial coeffs."""
    import jax.numpy as jnp
    from ..ntt import monomial_from_values

    return np.asarray(monomial_from_values(jnp.asarray(values_2d)))


def _lde(mono_2d, L):
    """(cols, n) monomials -> (cols, L*n) LDE in reference enumeration
    (full-domain bit-reversed tree indexing; proven identical to
    `lde_from_monomial`'s (cols, L, n) layout flattened coset-major)."""
    import jax.numpy as jnp
    from ..ntt import lde_from_monomial

    out = np.asarray(lde_from_monomial(jnp.asarray(mono_2d), L))
    return out.reshape(out.shape[0], -1)


def _eval_planes_on_coset(mono_2d, D, offset):
    """(cols, n) monomials -> (cols, D) values at offset*w_D^brev_D(t)."""
    import jax.numpy as jnp
    from ..ntt import fft_natural_to_bitreversed

    cols, n = mono_2d.shape
    offs = np.array(_pow_table(offset, n), dtype=np.uint64)
    scaled = _np_mod_mul(mono_2d, offs[None, :])
    padded = np.zeros((cols, D), dtype=np.uint64)
    padded[:, :n] = scaled
    return np.asarray(fft_natural_to_bitreversed(jnp.asarray(padded)))


def _interp_from_coset(values_2d, offset_inv):
    """(cols, D) values at offset*w_D^brev_D(t) -> (cols, D) monomials."""
    import jax.numpy as jnp
    from ..ntt import ifft_bitreversed_to_natural

    cols, D = values_2d.shape
    coeffs = np.asarray(ifft_bitreversed_to_natural(jnp.asarray(values_2d)))
    offs = np.array(_pow_table(offset_inv, D), dtype=np.uint64)
    return _np_mod_mul(coeffs, offs[None, :])


def _np_mod_mul(a, b):
    from ..prover.setup import _np_mod_mul as f

    return f(np.asarray(a, dtype=np.uint64), np.asarray(b, dtype=np.uint64))


# ---------------------------------------------------------------------------
# the prover
# ---------------------------------------------------------------------------


@dataclass
class ReferenceDialectArtifacts:
    vk: ReferenceVk
    proof: object  # ReferenceProof (via serde loaders in to_json round trip)
    vk_json: dict
    proof_json: dict
    config: dict  # verifier gate config (own_config adapters)


class _VkShim:
    """Duck-typed stand-in for ReferenceVk during proving (the real one is
    constructed at the end, once the setup cap exists)."""


def prove_reference_dialect(
    assembly,
    *,
    fri_lde_factor: int = 4,
    cap_size: int = 8,
    security_level: int = 40,
    pow_bits: int = 0,
) -> ReferenceDialectArtifacts:
    n = assembly.trace_len
    log_n = n.bit_length() - 1
    L = fri_lde_factor
    rate_log = L.bit_length() - 1
    N = n * L
    log_full = log_n + rate_log
    geom = assembly.geometry
    lookups = assembly.lookups_enabled
    if lookups:
        assert assembly.lookup_mode == "specialized", (
            "reference-dialect proving covers the specialized-columns "
            "lookup mode (the compat verifier's identity implements only "
            "UseSpecializedColumns*, matching lookup_placement.rs:21)"
        )
    config = verifier_config_for_assembly(assembly)

    # ---- setup in the reference dialect ----------------------------------
    tree, selector_paths = build_selector_tree(assembly.gates)
    tree_degree, tree_constants = tree.compute_stats()
    degree_bound = max(
        tree_degree, geom.max_allowed_constraint_degree + 1, 1
    )
    Q = 1 << (degree_bound - 1).bit_length()

    full_placement = np.concatenate(
        [assembly.copy_placement, assembly.lookup_placement], axis=0
    )
    Ct = full_placement.shape[0]  # all columns under copy permutation
    Cg = assembly.copy_placement.shape[0]
    Wn = assembly.wit_placement.shape[0]
    ref_nr = non_residues_for_copy_permutation(n, Ct)
    sigma = compute_sigma_values(full_placement, n, non_residues=ref_nr)
    consts = build_constant_columns(assembly, selector_paths)
    lp = assembly.lookup_params if lookups else None
    if lookups:
        assert assembly.lookup_table_id_col is not None
        consts = np.concatenate(
            [consts, assembly.lookup_table_id_col[None, :]], axis=0
        )
        table_cols = assembly.stacked_table_columns(lp.width)
    else:
        table_cols = np.zeros((0, n), dtype=np.uint64)
    K = consts.shape[0]
    TW = table_cols.shape[0]
    setup_cols = np.concatenate([sigma, consts, table_cols], axis=0)
    setup_mono = _to_mono(setup_cols)
    setup_flat = _lde(setup_mono, L)  # (Ct+K+TW, N)
    import jax.numpy as jnp

    setup_tree = MerkleTreeWithCap(jnp.asarray(setup_flat.T), cap_size)
    setup_cap = setup_tree.get_cap()

    # ---- VK shim for shared count/identity helpers -----------------------
    vk = _VkShim()
    vk.num_columns_under_copy_permutation = Cg
    vk.num_witness_columns = Wn
    vk.num_constant_columns = geom.num_constant_columns
    vk.max_allowed_constraint_degree = geom.max_allowed_constraint_degree
    vk.domain_size = n
    vk.quotient_degree = Q
    vk.selectors_placement = tree
    vk.fri_lde_factor = L
    vk.cap_size = cap_size
    vk.extra_constant_polys_for_selectors = 0
    vk.setup_merkle_tree_cap = setup_cap
    vk.public_inputs_locations = [
        (c, r) for (c, r, _v) in assembly.public_inputs
    ]
    if lookups:
        vk.lookup_parameters = LookupParametersRef(
            "UseSpecializedColumnsWithTableIdAsConstant",
            lp.width,
            lp.num_repetitions,
            bool(getattr(lp, "share_table_id", True)),
        )
        # dedicated table-id constant column sits after the base constants
        vk.table_ids_column_idxes = [geom.num_constant_columns]
    else:
        vk.lookup_parameters = LookupParametersRef("NoLookup", 0, 0, False)
        vk.table_ids_column_idxes = []
    counts = derive_counts(vk, config)
    assert counts["num_variable_polys"] == Ct, (
        counts["num_variable_polys"],
        Ct,
    )
    assert counts["num_constant_polys"] == K

    # ---- transcript round 1: witness commit ------------------------------
    t = ReferenceTranscript()
    t.witness_merkle_tree_cap(setup_cap)
    _checkpoint(0, "setup_cap", setup_cap)
    pi_values = [int(v) for (_c, _r, v) in assembly.public_inputs]
    for v in pi_values:
        t.witness_field_elements([v])
    _checkpoint(0, "public_inputs", pi_values)

    host_cols = [np.asarray(assembly.copy_cols_values)]
    if Ct > Cg:
        host_cols.append(np.asarray(assembly.lookup_cols_values))
    if Wn:
        host_cols.append(np.asarray(assembly.wit_cols_values))
    M = 1 if lookups else 0
    if M:
        host_cols.append(np.asarray(assembly.multiplicities)[None, :])
    wit_vals = np.concatenate(host_cols, axis=0)  # (Ct+Wn+M, n)
    wit_mono = _to_mono(wit_vals)
    wit_flat = _lde(wit_mono, L)
    wit_tree = MerkleTreeWithCap(jnp.asarray(wit_flat.T), cap_size)
    t.witness_merkle_tree_cap(wit_tree.get_cap())
    _checkpoint(1, "witness_cap", wit_tree.get_cap())
    beta = (t.get_challenge(), t.get_challenge())
    gamma = (t.get_challenge(), t.get_challenge())
    r1_challenges = [beta, gamma]
    if lookups:
        lookup_beta = (t.get_challenge(), t.get_challenge())
        lookup_gamma = (t.get_challenge(), t.get_challenge())
        r1_challenges += [lookup_beta, lookup_gamma]
    _checkpoint(1, "challenges", r1_challenges)

    # ---- stage 2: grand product + lookup polys (reference chunking) ------
    # z(w^{j+1}) = z(w^j) * prod_cols (v + b*x*nr + g)/(v + b*sigma + g);
    # intermediates are the after-chunk partial states, chunk size = Q
    # (prover.rs compute_copy_permutation_aggregates; verifier.rs:1560).
    omega = gl.omega(log_n)
    xs = _pow_table(omega, n)
    col_chunks = [
        list(range(i, min(i + Q, Ct))) for i in range(0, Ct, Q)
    ]
    num_intermediate = counts["num_intermediate"]
    assert len(col_chunks) - 1 == num_intermediate

    dens = []  # (row, chunk) denominators, flattened row-major
    for j in range(n):
        for chunk in col_chunks:
            d = ONE
            for c in chunk:
                term = e_add(
                    e_add(
                        e_mul_base(beta, int(sigma[c, j])),
                        (int(wit_vals[c, j]), 0),
                    ),
                    gamma,
                )
                d = e_mul(d, term)
            dens.append(d)
    den_invs = _batch_inv_ext(dens)

    z_rows = [ONE] * n
    interm_rows = [[ONE] * n for _ in range(num_intermediate)]
    cur = ONE
    for j in range(n):
        z_rows[j] = cur
        state = cur
        for k, chunk in enumerate(col_chunks):
            num = ONE
            for c in chunk:
                kx = gl.mul(ref_nr[c], xs[j])
                term = e_add(
                    e_add(
                        e_mul_base(beta, kx), (int(wit_vals[c, j]), 0)
                    ),
                    gamma,
                )
                num = e_mul(num, term)
            state = e_mul(
                e_mul(state, num), den_invs[j * len(col_chunks) + k]
            )
            if k < num_intermediate:
                interm_rows[k][j] = state
        cur = state
    assert cur == ONE, "copy-permutation grand product does not close"

    s2_planes = [
        np.array([v[0] for v in z_rows], dtype=np.uint64),
        np.array([v[1] for v in z_rows], dtype=np.uint64),
    ]
    for rows in interm_rows:
        s2_planes.append(np.array([v[0] for v in rows], dtype=np.uint64))
        s2_planes.append(np.array([v[1] for v in rows], dtype=np.uint64))

    R = counts["num_lookup_subarguments"]
    if lookups:
        # A_i = 1/(lb + sum g^j col_j + g^w tid), B = mult/(lb + sum g^j t_j)
        # (log-derivative argument, lookup.rs; verifier.rs:1242)
        width = lp.width
        gpow = [ONE]
        for _ in range(width + 1):
            gpow.append(e_mul(gpow[-1], lookup_gamma))
        tid_col = consts[-1]
        denoms = []
        for i in range(R):
            for j in range(n):
                d = lookup_beta
                for w in range(width):
                    d = e_add(
                        d,
                        e_mul_base(
                            gpow[w], int(wit_vals[Cg + i * width + w, j])
                        ),
                    )
                d = e_add(d, e_mul_base(gpow[width], int(tid_col[j])))
                denoms.append(d)
        for j in range(n):
            d = lookup_beta
            for w in range(width + 1):
                d = e_add(d, e_mul_base(gpow[w], int(table_cols[w, j])))
            denoms.append(d)
        inv = _batch_inv_ext(denoms)
        mults = np.asarray(assembly.multiplicities)
        for i in range(R):
            a_rows = inv[i * n : (i + 1) * n]
            s2_planes.append(
                np.array([v[0] for v in a_rows], dtype=np.uint64)
            )
            s2_planes.append(
                np.array([v[1] for v in a_rows], dtype=np.uint64)
            )
        b_rows = [
            e_mul_base(inv[R * n + j], int(mults[j])) for j in range(n)
        ]
        s2_planes.append(np.array([v[0] for v in b_rows], dtype=np.uint64))
        s2_planes.append(np.array([v[1] for v in b_rows], dtype=np.uint64))

    s2_vals = np.stack(s2_planes)  # (2*(1+I+R+M), n)
    s2_mono = _to_mono(s2_vals)
    s2_flat = _lde(s2_mono, L)
    s2_tree = MerkleTreeWithCap(jnp.asarray(s2_flat.T), cap_size)
    t.witness_merkle_tree_cap(s2_tree.get_cap())
    _checkpoint(2, "stage2_cap", s2_tree.get_cap())
    alpha = (t.get_challenge(), t.get_challenge())
    _checkpoint(2, "alpha", alpha)
    challenges = split_alpha_powers(alpha, counts)
    challenges["beta"] = beta
    challenges["gamma"] = gamma
    if lookups:
        challenges["lookup_beta"] = lookup_beta
        challenges["lookup_gamma"] = lookup_gamma

    # ---- stage 3: quotient -----------------------------------------------
    # T(x) is evaluated pointwise over a disjoint coset of size 2*Q*n with
    # THE SAME `t_accumulator_at` the verifier replays at z, then divided by
    # the vanishing x^n - 1 in coefficient space (exact; nonzero remainder
    # means an unsatisfied circuit) and split into Q chunks of n.
    D = 2 * Q * n
    log_D = D.bit_length() - 1
    gq = gl.MULTIPLICATIVE_GENERATOR
    # z(w x) plane monomials: coeff_k * w^k
    zsh_mono = _np_mod_mul(
        s2_mono[0:2], np.array(_pow_table(omega, n), dtype=np.uint64)[None]
    )
    wit_q = _eval_planes_on_coset(wit_mono, D, gq)
    setup_q = _eval_planes_on_coset(setup_mono, D, gq)
    s2_q = _eval_planes_on_coset(s2_mono, D, gq)
    zsh_q = _eval_planes_on_coset(zsh_mono, D, gq)

    I = num_intermediate

    def _ext_cols(arr, base, count):
        return [
            (int(arr[base + 2 * i, tt]), int(arr[base + 2 * i + 1, tt]))
            for i in range(count)
        ]

    t0 = np.zeros(D, dtype=np.uint64)
    t1 = np.zeros(D, dtype=np.uint64)
    wD = gl.omega(log_D)
    for tt in range(D):
        x = gl.mul(gq, gl.pow_(wD, _brev(tt, log_D)))
        opened = {
            "variables": [(int(wit_q[i, tt]), 0) for i in range(Ct)],
            "witness": [
                (int(wit_q[Ct + i, tt]), 0) for i in range(Wn)
            ],
            "constants": [
                (int(setup_q[Ct + i, tt]), 0) for i in range(K)
            ],
            "sigmas": [(int(setup_q[i, tt]), 0) for i in range(Ct)],
            "copy_z": (int(s2_q[0, tt]), int(s2_q[1, tt])),
            "copy_z_shifted": (int(zsh_q[0, tt]), int(zsh_q[1, tt])),
            "intermediates": _ext_cols(s2_q, 2, I),
            "multiplicities": [
                (int(wit_q[Ct + Wn, tt]), 0)
            ]
            if M
            else [],
            "lookup_a": _ext_cols(s2_q, 2 + 2 * I, R),
            "mult_encoding": _ext_cols(s2_q, 2 + 2 * I + 2 * R, M),
            "tables": [
                (int(setup_q[Ct + K + i, tt]), 0) for i in range(TW)
            ],
        }
        acc = t_accumulator_at((x, 0), opened, challenges, vk, config, counts)
        t0[tt] = acc[0]
        t1[tt] = acc[1]

    t_mono = _interp_from_coset(np.stack([t0, t1]), gl.inv(gq))
    # exact division by x^n - 1:  a[k] = q[k-n] - q[k]
    q_planes = np.zeros((2, D), dtype=np.uint64)
    for p in range(2):
        a = t_mono[p]
        qq = [0] * (D + n)
        for k in range(D - 1, n - 1, -1):
            qq[k - n] = gl.add(int(a[k]), qq[k])
        for k in range(n):  # remainder must vanish on a satisfied circuit
            assert gl.add(int(a[k]), qq[k]) == 0, (
                "quotient remainder nonzero: circuit not satisfied"
            )
        q_planes[p, : len(qq) - n] = np.array(qq[:D], dtype=np.uint64)
    assert not q_planes[:, Q * n :].any(), "quotient degree overflow"
    # interleaved chunk planes: [q0.c0, q0.c1, q1.c0, ...]
    q_cols = np.zeros((2 * Q, n), dtype=np.uint64)
    for i in range(Q):
        q_cols[2 * i] = q_planes[0, i * n : (i + 1) * n]
        q_cols[2 * i + 1] = q_planes[1, i * n : (i + 1) * n]
    q_flat = _lde(q_cols, L)
    q_tree = MerkleTreeWithCap(jnp.asarray(q_flat.T), cap_size)
    t.witness_merkle_tree_cap(q_tree.get_cap())
    _checkpoint(3, "quotient_cap", q_tree.get_cap())
    z = (t.get_challenge(), t.get_challenge())
    _checkpoint(3, "z", z)

    # ---- evaluations at z, z*omega, 0 ------------------------------------
    def ext_poly_at(base_idx, mono, at):
        p0 = _eval_plane_at_ext(mono[base_idx], at)
        p1 = _eval_plane_at_ext(mono[base_idx + 1], at)
        return e_add(p0, e_mul(p1, W_EXT))

    # reference opening order: vars+wits, constants, sigmas, stage-2, ...
    values_at_z = []
    for i in range(Ct + Wn):
        values_at_z.append(_eval_plane_at_ext(wit_mono[i], z))
    for i in range(K):
        values_at_z.append(_eval_plane_at_ext(setup_mono[Ct + i], z))
    for i in range(Ct):
        values_at_z.append(_eval_plane_at_ext(setup_mono[i], z))
    values_at_z.append(ext_poly_at(0, s2_mono, z))  # copy z
    for i in range(I):
        values_at_z.append(ext_poly_at(2 + 2 * i, s2_mono, z))
    if M:
        values_at_z.append(_eval_plane_at_ext(wit_mono[Ct + Wn], z))
        for i in range(R):
            values_at_z.append(ext_poly_at(2 + 2 * I + 2 * i, s2_mono, z))
        values_at_z.append(ext_poly_at(2 + 2 * I + 2 * R, s2_mono, z))
        for i in range(TW):
            values_at_z.append(
                _eval_plane_at_ext(setup_mono[Ct + K + i], z)
            )
    for i in range(Q):
        values_at_z.append(ext_poly_at(2 * i, q_cols, z))
    zw = e_mul_base(z, omega)
    values_at_z_omega = [ext_poly_at(0, s2_mono, zw)]
    values_at_0 = []
    if M:
        for i in range(R):
            values_at_0.append(
                (int(s2_mono[2 + 2 * I + 2 * i, 0]),
                 int(s2_mono[2 + 2 * I + 2 * i + 1, 0]))
            )
        values_at_0.append(
            (int(s2_mono[2 + 2 * I + 2 * R, 0]),
             int(s2_mono[2 + 2 * I + 2 * R + 1, 0]))
        )
    assert len(values_at_z) == counts["num_poly_values_at_z"]
    for v in values_at_z:
        t.witness_field_elements(v)
    for v in values_at_z_omega:
        t.witness_field_elements(v)
    for v in values_at_0:
        t.witness_field_elements(v)
    _checkpoint(
        4, "evaluations", [values_at_z, values_at_z_omega, values_at_0]
    )

    # ---- DEEP ------------------------------------------------------------
    c0 = t.get_challenge()
    c1 = t.get_challenge()
    _checkpoint(4, "deep_challenge", (c0, c1))
    public_input_opening_tuples = []
    for (col, row, value) in assembly.public_inputs:
        open_at = gl.pow_(omega, row)
        for el in public_input_opening_tuples:
            if el[0] == open_at:
                el[1].append((col, int(value)))
                break
        else:
            public_input_opening_tuples.append([open_at, [(col, int(value))]])
    total_num_challenges = (
        len(values_at_z)
        + len(values_at_z_omega)
        + len(values_at_0)
        + sum(len(s[1]) for s in public_input_opening_tuples)
    )
    deep_challenges = [ONE, (c0, c1)]
    cur = (c0, c1)
    for _ in range(2, total_num_challenges):
        cur = e_mul(cur, (c0, c1))
        deep_challenges.append(cur)
    deep_challenges = deep_challenges[:total_num_challenges]

    # x array over the LDE domain (reference tree enumeration) + inverses
    W_full = gl.omega(log_full)
    x_arr = [
        gl.mul(gl.MULTIPLICATIVE_GENERATOR, gl.pow_(W_full, _brev(i, log_full)))
        for i in range(N)
    ]
    inv_xz = _batch_inv_ext([e_sub((x, 0), z) for x in x_arr])
    inv_xzw = _batch_inv_ext([e_sub((x, 0), zw) for x in x_arr])
    inv_x = _batch_inv_ext([(x, 0) for x in x_arr])
    pi_invs = {
        open_at: _batch_inv_ext(
            [e_sub((x, 0), (open_at, 0)) for x in x_arr]
        )
        for open_at, _s in public_input_opening_tuples
    }

    # sources in the exact values_at_z order
    def src_at(tt):
        out = []
        for i in range(Ct + Wn):
            out.append((int(wit_flat[i, tt]), 0))
        for i in range(K):
            out.append((int(setup_flat[Ct + i, tt]), 0))
        for i in range(Ct):
            out.append((int(setup_flat[i, tt]), 0))
        out.append((int(s2_flat[0, tt]), int(s2_flat[1, tt])))
        for i in range(I):
            out.append(
                (int(s2_flat[2 + 2 * i, tt]), int(s2_flat[3 + 2 * i, tt]))
            )
        if M:
            out.append((int(wit_flat[Ct + Wn, tt]), 0))
            base = 2 + 2 * I
            for i in range(R + 1):
                out.append(
                    (
                        int(s2_flat[base + 2 * i, tt]),
                        int(s2_flat[base + 2 * i + 1, tt]),
                    )
                )
            for i in range(TW):
                out.append((int(setup_flat[Ct + K + i, tt]), 0))
        for i in range(Q):
            out.append(
                (int(q_flat[2 * i, tt]), int(q_flat[2 * i + 1, tt]))
            )
        return out

    h_vals = [ZERO] * N
    for tt in range(N):
        local = ZERO
        srcs = src_at(tt)
        off = 0
        for i, (s, v) in enumerate(zip(srcs, values_at_z)):
            local = e_add(
                local, e_mul(deep_challenges[off + i], e_sub(s, v))
            )
        acc = e_mul(local, inv_xz[tt])
        off += len(srcs)
        szw = (int(s2_flat[0, tt]), int(s2_flat[1, tt]))
        acc = e_add(
            acc,
            e_mul(
                e_mul(
                    deep_challenges[off], e_sub(szw, values_at_z_omega[0])
                ),
                inv_xzw[tt],
            ),
        )
        off += 1
        if M:
            local0 = ZERO
            base = 2 + 2 * I
            for i in range(R + 1):
                s = (
                    int(s2_flat[base + 2 * i, tt]),
                    int(s2_flat[base + 2 * i + 1, tt]),
                )
                local0 = e_add(
                    local0,
                    e_mul(
                        deep_challenges[off + i], e_sub(s, values_at_0[i])
                    ),
                )
            acc = e_add(acc, e_mul(local0, inv_x[tt]))
            off += R + 1
        for open_at, subset in public_input_opening_tuples:
            local_pi = ZERO
            for (col, expected) in subset:
                s = (int(wit_flat[col, tt]), 0)
                local_pi = e_add(
                    local_pi,
                    e_mul(
                        deep_challenges[off],
                        e_sub(s, (expected % gl.P, 0)),
                    ),
                )
                off += 1
            acc = e_add(acc, e_mul(local_pi, pi_invs[open_at][tt]))
        assert off == len(deep_challenges) if tt == 0 else True
        h_vals[tt] = acc

    # ---- FRI --------------------------------------------------------------
    new_pow_bits, num_queries, schedule, final_degree = compute_fri_schedule(
        security_level, cap_size, pow_bits, rate_log, log_n
    )
    x_inv = [gl.inv(x) for x in x_arr]

    fri_layer_values = []  # per oracle layer: list of ext values
    fri_trees = []
    fri_caps = []
    cur_vals = h_vals
    cur_xinv = x_inv
    fri_challenges_per_layer = []
    for li, deg_log2 in enumerate(schedule):
        blk = 1 << deg_log2
        num_leaves = len(cur_vals) // blk
        leaf_mat = np.zeros((num_leaves, 2 * blk), dtype=np.uint64)
        for leaf in range(num_leaves):
            for j in range(blk):
                v = cur_vals[leaf * blk + j]
                leaf_mat[leaf, j] = v[0]
                leaf_mat[leaf, blk + j] = v[1]
        treeo = MerkleTreeWithCap(jnp.asarray(leaf_mat), cap_size)
        fri_layer_values.append(cur_vals)
        fri_trees.append(treeo)
        fri_caps.append(treeo.get_cap())
        t.witness_merkle_tree_cap(treeo.get_cap())
        _checkpoint(5, f"fri_cap_{li}", treeo.get_cap())
        cc0 = t.get_challenge()
        cc1 = t.get_challenge()
        _checkpoint(5, f"fri_challenge_{li}", (cc0, cc1))
        chs = [(cc0, cc1)]
        for _ in range(1, deg_log2):
            chs.append(e_mul(chs[-1], chs[-1]))
        fri_challenges_per_layer.append(chs)
        for ch in chs:
            nxt = []
            nxt_xinv = []
            for i2 in range(len(cur_vals) // 2):
                a = cur_vals[2 * i2]
                b = cur_vals[2 * i2 + 1]
                res = e_add(a, b)
                diff = e_mul_base(e_mul(e_sub(a, b), ch), cur_xinv[2 * i2])
                nxt.append(e_add(res, diff))
                xsq = gl.mul(cur_xinv[2 * i2], cur_xinv[2 * i2])
                nxt_xinv.append(xsq)
            cur_vals = nxt
            cur_xinv = nxt_xinv

    # final monomials: interpolate the fully folded layer (size L*final_deg;
    # rate L is preserved by folding, so coeffs above final_degree vanish)
    F = sum(schedule)
    d_arr = len(cur_vals)
    assert d_arr == N >> F and final_degree == n >> F
    offset_f = gl.pow_(gl.MULTIPLICATIVE_GENERATOR, 1 << F)
    vals2 = np.zeros((2, d_arr), dtype=np.uint64)
    for i2, v in enumerate(cur_vals):
        vals2[0, i2] = v[0]
        vals2[1, i2] = v[1]
    fin_mono = _interp_from_coset(vals2, gl.inv(offset_f))
    assert not fin_mono[:, final_degree:].any(), "final degree overflow"
    final_fri_monomials = (
        [int(v) for v in fin_mono[0, :final_degree]],
        [int(v) for v in fin_mono[1, :final_degree]],
    )
    t.witness_field_elements(final_fri_monomials[0])
    t.witness_field_elements(final_fri_monomials[1])
    # interleaved (c0, c1) pairs — the SAME encoding prover/fri.py digests
    # (it checkpoints out.final_monomials, a list of pairs), so identical
    # values give identical digests across the two implementations
    _checkpoint(
        5, "fri_final_monomials",
        list(zip(final_fri_monomials[0], final_fri_monomials[1])),
    )

    # ---- PoW (blake2s runner, pow.rs:93) ---------------------------------
    pow_challenge = 0
    if new_pow_bits != 0:
        seed_words = pow_seed_challenges(t)
        seed = b"".join(int(c).to_bytes(8, "little") for c in seed_words)
        mask = (1 << new_pow_bits) - 1
        while True:
            digest = hashlib.blake2s(
                seed + pow_challenge.to_bytes(8, "little")
            ).digest()
            if int.from_bytes(digest[:8], "little") & mask == 0:
                break
            pow_challenge += 1
        t.witness_field_elements(
            [pow_challenge & 0xFFFFFFFF, pow_challenge >> 32]
        )
    _checkpoint(5, "pow_nonce", [pow_challenge])

    # ---- queries ----------------------------------------------------------
    max_needed_bits = log_full
    bools = BoolsBuffer(max_needed=max_needed_bits)
    query_idxs = []
    for _ in range(num_queries):
        bits = bools.get_bits(t, max_needed_bits)
        idx = 0
        for shift, bit in enumerate(bits):
            idx |= int(bool(bit)) << shift
        query_idxs.append(idx)
    _checkpoint(5, "query_indices", query_idxs)

    def oracle_query(flat, treeo, idx):
        return {
            "leaf_elements": [str(int(v)) for v in flat[:, idx]],
            "proof": [
                [str(int(x)) for x in d] for d in treeo.get_proof(idx)
            ],
        }

    queries_json = []
    for idx in query_idxs:
        fri_queries = []
        fidx = idx
        for li, deg_log2 in enumerate(schedule):
            blk = 1 << deg_log2
            leaf_idx = fidx >> deg_log2
            layer_vals = fri_layer_values[li]
            leaf_els = [
                str(int(layer_vals[leaf_idx * blk + j][0]))
                for j in range(blk)
            ] + [
                str(int(layer_vals[leaf_idx * blk + j][1]))
                for j in range(blk)
            ]
            fri_queries.append(
                {
                    "leaf_elements": leaf_els,
                    "proof": [
                        [str(int(x)) for x in d]
                        for d in fri_trees[li].get_proof(leaf_idx)
                    ],
                }
            )
            fidx = leaf_idx
        queries_json.append(
            {
                "witness_query": oracle_query(wit_flat, wit_tree, idx),
                "stage_2_query": oracle_query(s2_flat, s2_tree, idx),
                "quotient_query": oracle_query(q_flat, q_tree, idx),
                "setup_query": oracle_query(setup_flat, setup_tree, idx),
                "fri_queries": fri_queries,
            }
        )

    # ---- serde-JSON artifacts --------------------------------------------
    def _cap_json(cap):
        return [[str(int(x)) for x in d] for d in cap]

    def _ext_json(v):
        return {"coeffs": [str(int(v[0])), str(int(v[1]))]}

    if lookups:
        lookup_json = {
            "UseSpecializedColumnsWithTableIdAsConstant": {
                "width": lp.width,
                "num_repetitions": lp.num_repetitions,
                "share_table_id": bool(getattr(lp, "share_table_id", True)),
            }
        }
        total_tables_len = int(
            sum(len(tbl.content) for tbl in assembly.lookup_tables)
        )
    else:
        lookup_json = "NoLookup"
        total_tables_len = 0
    vk_json = {
        "fixed_parameters": {
            "parameters": {
                "num_columns_under_copy_permutation": Cg,
                "num_witness_columns": Wn,
                "num_constant_columns": geom.num_constant_columns,
                "max_allowed_constraint_degree": (
                    geom.max_allowed_constraint_degree
                ),
            },
            "lookup_parameters": lookup_json,
            "domain_size": str(n),
            "total_tables_len": str(total_tables_len),
            "public_inputs_locations": [
                [int(c), int(r)] for (c, r) in vk.public_inputs_locations
            ],
            "extra_constant_polys_for_selectors": 0,
            "table_ids_column_idxes": list(vk.table_ids_column_idxes),
            "quotient_degree": Q,
            "selectors_placement": tree.to_json(),
            "fri_lde_factor": L,
            "cap_size": cap_size,
        },
        "setup_merkle_tree_cap": _cap_json(setup_cap),
    }
    proof_json = {
        "proof_config": {
            "fri_lde_factor": L,
            "merkle_tree_cap_size": cap_size,
            "fri_folding_schedule": None,
            "security_level": security_level,
            # the ADJUSTED bits: compute_fri_schedule may lower the
            # requested pow_bits, and the verifier recomputes the schedule
            # from the recorded value (which must be its fixed point)
            "pow_bits": new_pow_bits,
        },
        "public_inputs": [str(v) for v in pi_values],
        "witness_oracle_cap": _cap_json(wit_tree.get_cap()),
        "stage_2_oracle_cap": _cap_json(s2_tree.get_cap()),
        "quotient_oracle_cap": _cap_json(q_tree.get_cap()),
        "final_fri_monomials": [
            [str(v) for v in final_fri_monomials[0]],
            [str(v) for v in final_fri_monomials[1]],
        ],
        "values_at_z": [_ext_json(v) for v in values_at_z],
        "values_at_z_omega": [_ext_json(v) for v in values_at_z_omega],
        "values_at_0": [_ext_json(v) for v in values_at_0],
        "fri_base_oracle_cap": _cap_json(fri_caps[0]),
        "fri_intermediate_oracles_caps": [
            _cap_json(c) for c in fri_caps[1:]
        ],
        "queries_per_fri_repetition": queries_json,
        "pow_challenge": str(pow_challenge),
    }

    # parse back through the golden-artifact loaders (schema loop)
    import json, tempfile, os

    with tempfile.TemporaryDirectory() as td:
        vp = os.path.join(td, "vk.json")
        pp = os.path.join(td, "proof.json")
        json.dump(vk_json, open(vp, "w"))
        json.dump(proof_json, open(pp, "w"))
        from .serde import load_proof, load_vk

        vk_ref = load_vk(vp)
        proof_ref = load_proof(pp)

    return ReferenceDialectArtifacts(
        vk=vk_ref,
        proof=proof_ref,
        vk_json=vk_json,
        proof_json=proof_json,
        config=config,
    )
