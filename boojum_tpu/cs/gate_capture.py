"""Symbolic gate-program capture — the offload-synthesizer seam.

Counterpart of `/root/reference/src/gpu_synthesizer/` (856 LoC):
`GpuSynthesizerFieldLike` (mod.rs:201) runs each gate's constraint evaluator
once over a fake field whose "values" are symbolic indices, recording every
arithmetic op as a `Relation` (mod.rs:169-190) so a device backend can replay
constraint evaluation without re-tracing the evaluator.

Here the same contract face (`zero/one/constant/add/sub/mul/neg/double`)
records a straight-line SSA program per gate. Two uses:
- inspection/debug: a portable, serializable description of every gate's
  constraint circuit (op counts, degree audits);
- replay: `GateProgram.evaluate_rows` interprets the program over any ops
  context (scalars or whole device arrays), byte-equivalent to running the
  evaluator directly — this is the seam a custom fused-kernel backend
  (e.g. a Pallas gate-sweep generator) consumes.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..field import gl
from .gates.base import RowView, TermsCollector


@dataclass(frozen=True)
class Sym:
    """A symbolic value: an SSA slot index."""

    idx: int


@dataclass
class GateProgram:
    """Straight-line program of one gate instance's constraint evaluation.

    Inputs are addressed as ('v', i) / ('w', i) / ('c', i) loads; every op is
    (opcode, dst_slot, src_a, src_b) with constants inlined by value.
    """

    gate_name: str = ""
    loads: list = field(default_factory=list)  # (slot, kind, index)
    consts: list = field(default_factory=list)  # (slot, value)
    ops: list = field(default_factory=list)  # (op, dst, a_slot, b_slot)
    terms: list = field(default_factory=list)  # slot per quotient term
    num_slots: int = 0

    # -- replay ------------------------------------------------------------

    def evaluate(self, ops_ctx, row: RowView):
        """Interpret over any field-like ops context + row view; returns the
        term values (same results as gate.evaluate, by construction)."""
        slots = [None] * self.num_slots
        for slot, kind, index in self.loads:
            slots[slot] = (
                row.v(index) if kind == "v"
                else row.w(index) if kind == "w"
                else row.c(index)
            )
        for slot, value in self.consts:
            slots[slot] = ops_ctx.constant(value)
        for op, dst, a, b in self.ops:
            if op == "add":
                slots[dst] = ops_ctx.add(slots[a], slots[b])
            elif op == "sub":
                slots[dst] = ops_ctx.sub(slots[a], slots[b])
            elif op == "mul":
                slots[dst] = ops_ctx.mul(slots[a], slots[b])
            elif op == "neg":
                slots[dst] = ops_ctx.neg(slots[a])
            elif op == "double":
                slots[dst] = ops_ctx.double(slots[a])
            else:
                raise ValueError(op)
        return [slots[t] for t in self.terms]

    def stats(self) -> dict:
        from collections import Counter

        c = Counter(op for (op, *_rest) in self.ops)
        return {
            "gate": self.gate_name,
            "loads": len(self.loads),
            "constants": len(self.consts),
            **dict(c),
            "terms": len(self.terms),
        }


class _CaptureOps:
    """The symbolic field-like ops face (GpuSynthesizerFieldLike analogue)."""

    def __init__(self, program: GateProgram):
        self.p = program

    def _new(self) -> int:
        s = self.p.num_slots
        self.p.num_slots += 1
        return s

    def zero(self):
        return self.constant(0)

    def one(self):
        return self.constant(1)

    def constant(self, v: int):
        s = self._new()
        self.p.consts.append((s, int(v) % gl.P))
        return Sym(s)

    def _bin(self, op, a: Sym, b: Sym):
        s = self._new()
        self.p.ops.append((op, s, a.idx, b.idx))
        return Sym(s)

    def add(self, a, b):
        return self._bin("add", a, b)

    def sub(self, a, b):
        return self._bin("sub", a, b)

    def mul(self, a, b):
        return self._bin("mul", a, b)

    def neg(self, a):
        s = self._new()
        self.p.ops.append(("neg", s, a.idx, a.idx))
        return Sym(s)

    def double(self, a):
        s = self._new()
        self.p.ops.append(("double", s, a.idx, a.idx))
        return Sym(s)


def capture_gate_program(gate, constants=()) -> GateProgram:
    """Run the gate's evaluator once over symbolic values, recording its
    constraint program (reference GPUDataCapture::from_evaluator,
    gpu_synthesizer/mod.rs:354)."""
    p = GateProgram(gate_name=gate.name)
    ops = _CaptureOps(p)

    def load(kind):
        def get(i):
            s = ops._new()
            p.loads.append((s, kind, i))
            return Sym(s)

        return get

    # memoize loads so repeated row.v(i) maps to one slot
    cache: dict = {}

    def memo(kind):
        raw = load(kind)

        def get(i):
            key = (kind, i)
            if key not in cache:
                cache[key] = raw(i)
            return cache[key]

        return get

    row = RowView(memo("v"), memo("w"), memo("c"))
    dst = TermsCollector()
    gate.evaluate(ops, row, dst)
    p.terms = [t.idx for t in dst.terms]
    return p


def capture_all(gates, constants_by_gate=None) -> dict:
    """Programs for a whole gate set (reference GatesSetForGPU,
    gpu_synthesizer/mod.rs:446)."""
    return {g.name: capture_gate_program(g) for g in gates}


# ---------------------------------------------------------------------------
# Scanned playback: O(1)-size compiled graphs for huge gate programs
# ---------------------------------------------------------------------------
# The prover's gate sweep normally traces gate.evaluate() directly, so the
# compiled graph grows with the evaluator's op count — for permutation-sized
# gates (the recursion circuit's flattened Poseidon2: thousands of field
# ops) XLA optimization time explodes super-linearly (the round-2 recursive
# prove never finished compiling). `pack_for_scan` register-allocates the
# SSA program (linear-scan liveness, so the live set stays near the gate's
# state width instead of one slot per op) and `scan_evaluate` replays it
# under ONE jax.lax.scan whose body is a single add/sub/mul switch — the
# graph size is constant in the program length. Bit-identical to direct
# tracing: same ops, same order, exact integer arithmetic.

from dataclasses import dataclass as _dataclass


@_dataclass
class PackedGateProgram:
    gate_name: str
    num_regs: int
    # ops: (T, 4) int32 [opcode(0=add,1=sub,2=mul), dst, a, b]
    ops_arr: object
    v_idx: tuple
    v_regs: tuple
    w_idx: tuple
    w_regs: tuple
    c_idx: tuple
    c_regs: tuple
    const_vals: tuple  # python ints
    const_regs: tuple
    term_regs: tuple
    num_ops: int


def pack_for_scan(prog: GateProgram) -> PackedGateProgram:
    """Lower a GateProgram to the register form scan_evaluate replays."""
    # prelower neg/double onto {add, sub, mul}; neg needs a zero constant
    consts = list(prog.consts)
    ops = []
    zero_slot = None
    for op, dst, a, b in prog.ops:
        if op == "neg":
            if zero_slot is None:
                zero_slot = prog.num_slots
                consts.append((zero_slot, 0))
            ops.append(("sub", dst, zero_slot, a))
        elif op == "double":
            ops.append(("add", dst, a, a))
        else:
            ops.append((op, dst, a, b))
    num_slots = prog.num_slots + (1 if zero_slot is not None else 0)

    # liveness: last position (op index) each slot is read; terms live forever
    last_use = [-1] * num_slots
    for t, (_op, _dst, a, b) in enumerate(ops):
        last_use[a] = t
        last_use[b] = t
    INF = len(ops) + 1
    for s in prog.terms:
        last_use[s] = INF

    # linear-scan allocation. Initial definitions (loads/consts) take regs
    # up front; an op's dst may reuse a reg freed at THIS op (operands are
    # read before the write in the scan body).
    reg_of = {}
    free: list = []
    next_reg = 0

    def alloc(slot):
        nonlocal next_reg
        if free:
            r = free.pop()
        else:
            r = next_reg
            next_reg += 1
        reg_of[slot] = r
        return r

    initial_defs = [s for (s, _k, _i) in prog.loads] + [
        s for (s, _v) in consts
    ]
    for s in initial_defs:
        alloc(s)
    # free initial defs never read at all (dead loads)
    for s in list(initial_defs):
        if last_use[s] < 0:
            free.append(reg_of[s])
    packed_ops = []
    for t, (op, dst, a, b) in enumerate(ops):
        ra, rb = reg_of[a], reg_of[b]
        # free operands whose last read is this op (dst may take the reg)
        for s in {a, b}:
            if last_use[s] == t:
                free.append(reg_of[s])
        rd = alloc(dst)
        if last_use[dst] < 0:  # dead op (term-less side effect): keep reg
            last_use[dst] = INF
        packed_ops.append(
            ({"add": 0, "sub": 1, "mul": 2}[op], rd, ra, rb)
        )

    import numpy as _np

    v_loads = [(i, reg_of[s]) for (s, k, i) in prog.loads if k == "v"]
    w_loads = [(i, reg_of[s]) for (s, k, i) in prog.loads if k == "w"]
    c_loads = [(i, reg_of[s]) for (s, k, i) in prog.loads if k == "c"]
    return PackedGateProgram(
        gate_name=prog.gate_name,
        num_regs=next_reg,
        ops_arr=_np.array(packed_ops, dtype=_np.int32).reshape(-1, 4),
        v_idx=tuple(i for i, _r in v_loads),
        v_regs=tuple(r for _i, r in v_loads),
        w_idx=tuple(i for i, _r in w_loads),
        w_regs=tuple(r for _i, r in w_loads),
        c_idx=tuple(i for i, _r in c_loads),
        c_regs=tuple(r for _i, r in c_loads),
        const_vals=tuple(v for (_s, v) in consts),
        const_regs=tuple(reg_of[s] for (s, _v) in consts),
        term_regs=tuple(reg_of[s] for s in prog.terms),
        num_ops=len(packed_ops),
    )


def scan_evaluate(packed: PackedGateProgram, row: RowView):
    """Replay a packed program over (n,)-array row values with lax.scan.

    Returns the term arrays, equal to gate.evaluate(ArrayOps, ...)."""
    import jax
    import jax.numpy as jnp

    from ..field import goldilocks as gf

    sample = None
    loads = []
    for idx, reg, getter in (
        [(i, r, row.v) for i, r in zip(packed.v_idx, packed.v_regs)]
        + [(i, r, row.w) for i, r in zip(packed.w_idx, packed.w_regs)]
        + [(i, r, row.c) for i, r in zip(packed.c_idx, packed.c_regs)]
    ):
        val = getter(idx)
        sample = val
        loads.append((reg, val))
    assert sample is not None, packed.gate_name
    n = sample.shape[-1]
    regs = jnp.zeros((packed.num_regs, n), jnp.uint64)
    if loads:
        regs = regs.at[jnp.asarray([r for r, _v in loads])].set(
            jnp.stack([jnp.broadcast_to(v, (n,)) for _r, v in loads])
        )
    if packed.const_vals:
        cvals = jnp.asarray(
            _np_array_u64(packed.const_vals)
        )
        regs = regs.at[jnp.asarray(packed.const_regs)].set(
            jnp.broadcast_to(cvals[:, None], (len(packed.const_vals), n))
        )

    ops_dev = jnp.asarray(packed.ops_arr)

    def step(regs, op):
        a = regs[op[2]]
        b = regs[op[3]]
        res = jax.lax.switch(
            op[0],
            (
                lambda x, y: gf.add(x, y),
                lambda x, y: gf.sub(x, y),
                lambda x, y: gf.mul(x, y),
            ),
            a,
            b,
        )
        regs = jax.lax.dynamic_update_index_in_dim(regs, res, op[1], 0)
        return regs, None

    regs, _ = jax.lax.scan(step, regs, ops_dev)
    return [regs[r] for r in packed.term_regs]


def _np_array_u64(vals):
    import numpy as _np

    return _np.array([int(v) % gl.P for v in vals], dtype=_np.uint64)


_PACKED_CACHE: dict = {}


def packed_program_for(gate, threshold: int | None = None):
    """The packed program for `gate` when its op count exceeds the scan
    threshold (BOOJUM_TPU_SCAN_GATE_THRESHOLD, default 256); None for small
    gates, which stay on the direct-trace path."""
    import os

    if threshold is None:
        threshold = int(
            os.environ.get("BOOJUM_TPU_SCAN_GATE_THRESHOLD", "256")
        )
    key = (gate.name, threshold)
    if key not in _PACKED_CACHE:
        prog = capture_gate_program(gate)
        _PACKED_CACHE[key] = (
            pack_for_scan(prog) if len(prog.ops) > threshold else None
        )
    return _PACKED_CACHE[key]
