"""Symbolic gate-program capture — the offload-synthesizer seam.

Counterpart of `/root/reference/src/gpu_synthesizer/` (856 LoC):
`GpuSynthesizerFieldLike` (mod.rs:201) runs each gate's constraint evaluator
once over a fake field whose "values" are symbolic indices, recording every
arithmetic op as a `Relation` (mod.rs:169-190) so a device backend can replay
constraint evaluation without re-tracing the evaluator.

Here the same contract face (`zero/one/constant/add/sub/mul/neg/double`)
records a straight-line SSA program per gate. Two uses:
- inspection/debug: a portable, serializable description of every gate's
  constraint circuit (op counts, degree audits);
- replay: `GateProgram.evaluate_rows` interprets the program over any ops
  context (scalars or whole device arrays), byte-equivalent to running the
  evaluator directly — this is the seam a custom fused-kernel backend
  (e.g. a Pallas gate-sweep generator) consumes.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..field import gl
from .gates.base import RowView, TermsCollector


@dataclass(frozen=True)
class Sym:
    """A symbolic value: an SSA slot index."""

    idx: int


@dataclass
class GateProgram:
    """Straight-line program of one gate instance's constraint evaluation.

    Inputs are addressed as ('v', i) / ('w', i) / ('c', i) loads; every op is
    (opcode, dst_slot, src_a, src_b) with constants inlined by value.
    """

    gate_name: str = ""
    loads: list = field(default_factory=list)  # (slot, kind, index)
    consts: list = field(default_factory=list)  # (slot, value)
    ops: list = field(default_factory=list)  # (op, dst, a_slot, b_slot)
    terms: list = field(default_factory=list)  # slot per quotient term
    num_slots: int = 0

    # -- replay ------------------------------------------------------------

    def evaluate(self, ops_ctx, row: RowView):
        """Interpret over any field-like ops context + row view; returns the
        term values (same results as gate.evaluate, by construction)."""
        slots = [None] * self.num_slots
        for slot, kind, index in self.loads:
            slots[slot] = (
                row.v(index) if kind == "v"
                else row.w(index) if kind == "w"
                else row.c(index)
            )
        for slot, value in self.consts:
            slots[slot] = ops_ctx.constant(value)
        for op, dst, a, b in self.ops:
            if op == "add":
                slots[dst] = ops_ctx.add(slots[a], slots[b])
            elif op == "sub":
                slots[dst] = ops_ctx.sub(slots[a], slots[b])
            elif op == "mul":
                slots[dst] = ops_ctx.mul(slots[a], slots[b])
            elif op == "neg":
                slots[dst] = ops_ctx.neg(slots[a])
            elif op == "double":
                slots[dst] = ops_ctx.double(slots[a])
            else:
                raise ValueError(op)
        return [slots[t] for t in self.terms]

    def stats(self) -> dict:
        from collections import Counter

        c = Counter(op for (op, *_rest) in self.ops)
        return {
            "gate": self.gate_name,
            "loads": len(self.loads),
            "constants": len(self.consts),
            **dict(c),
            "terms": len(self.terms),
        }


class _CaptureOps:
    """The symbolic field-like ops face (GpuSynthesizerFieldLike analogue)."""

    def __init__(self, program: GateProgram):
        self.p = program

    def _new(self) -> int:
        s = self.p.num_slots
        self.p.num_slots += 1
        return s

    def zero(self):
        return self.constant(0)

    def one(self):
        return self.constant(1)

    def constant(self, v: int):
        s = self._new()
        self.p.consts.append((s, int(v) % gl.P))
        return Sym(s)

    def _bin(self, op, a: Sym, b: Sym):
        s = self._new()
        self.p.ops.append((op, s, a.idx, b.idx))
        return Sym(s)

    def add(self, a, b):
        return self._bin("add", a, b)

    def sub(self, a, b):
        return self._bin("sub", a, b)

    def mul(self, a, b):
        return self._bin("mul", a, b)

    def neg(self, a):
        s = self._new()
        self.p.ops.append(("neg", s, a.idx, a.idx))
        return Sym(s)

    def double(self, a):
        s = self._new()
        self.p.ops.append(("double", s, a.idx, a.idx))
        return Sym(s)


def capture_gate_program(gate, constants=()) -> GateProgram:
    """Run the gate's evaluator once over symbolic values, recording its
    constraint program (reference GPUDataCapture::from_evaluator,
    gpu_synthesizer/mod.rs:354)."""
    p = GateProgram(gate_name=gate.name)
    ops = _CaptureOps(p)

    def load(kind):
        def get(i):
            s = ops._new()
            p.loads.append((s, kind, i))
            return Sym(s)

        return get

    # memoize loads so repeated row.v(i) maps to one slot
    cache: dict = {}

    def memo(kind):
        raw = load(kind)

        def get(i):
            key = (kind, i)
            if key not in cache:
                cache[key] = raw(i)
            return cache[key]

        return get

    row = RowView(memo("v"), memo("w"), memo("c"))
    dst = TermsCollector()
    gate.evaluate(ops, row, dst)
    p.terms = [t.idx for t in dst.terms]
    return p


def capture_all(gates, constants_by_gate=None) -> dict:
    """Programs for a whole gate set (reference GatesSetForGPU,
    gpu_synthesizer/mod.rs:446)."""
    return {g.name: capture_gate_program(g) for g in gates}
