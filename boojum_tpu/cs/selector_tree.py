"""Degree-aware selector placement tree.

Port of the reference's `TreeNode` optimizer
(`/root/reference/src/cs/implementations/setup.rs:486`
compute_selectors_and_constants_placement, `:1328`
try_find_placement_for_degree, `:1374` TreeNode/GateDescription): gates are
packed into a variable-depth binary selector tree so that high-degree /
constant-hungry gates sit near the root (short selector paths) and cheap
gates absorb depth. Selector path bits occupy the leading constant columns
along each row's path; the gate's own constants start at column
`len(path)`. The same JSON encoding as the reference's `selectors_placement`
VK field is used (`compat.serde` parses golden VKs with this class).
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass
class GateDescription:
    gate_idx: int
    num_constants: int
    degree: int
    needs_selector: bool
    is_lookup: bool

    def degree_at_depth(self, depth: int) -> int:
        if not self.is_lookup:
            return depth + self.degree
        # lookup marker: deg 2 on the A-poly side, depth on the selector side
        return max(depth, 2)


class TreeNode:
    """kind is one of 'Empty' | 'GateOnly' | 'Fork'."""

    def __init__(self, kind, gate=None, left=None, right=None):
        self.kind = kind
        self.gate = gate
        self.left = left
        self.right = right

    # -- (de)serialization (reference serde-enum JSON) ----------------------

    @classmethod
    def from_json(cls, obj) -> "TreeNode":
        if obj == "Empty":
            return cls("Empty")
        if "GateOnly" in obj:
            return cls("GateOnly", gate=GateDescription(**obj["GateOnly"]))
        if "Fork" in obj:
            f = obj["Fork"]
            return cls(
                "Fork",
                left=cls.from_json(f["left"]),
                right=cls.from_json(f["right"]),
            )
        raise ValueError(f"unknown TreeNode variant: {obj!r}")

    def to_json(self):
        if self.kind == "Empty":
            return "Empty"
        if self.kind == "GateOnly":
            return {"GateOnly": dict(self.gate.__dict__)}
        return {
            "Fork": {
                "left": self.left.to_json(),
                "right": self.right.to_json(),
            }
        }

    # -- queries ------------------------------------------------------------

    def output_placement(self, gate_idx: int):
        """Root-to-leaf bool path for the gate, True = left (setup.rs:1439)."""
        if self.kind == "Empty":
            return None
        if self.kind == "GateOnly":
            return [] if self.gate.gate_idx == gate_idx else None
        left = self.left.output_placement(gate_idx)
        if left is not None:
            return [True] + left
        right = self.right.output_placement(gate_idx)
        if right is not None:
            return [False] + right
        return None

    def compute_stats(self, depth: int = 0):
        """(max constraint degree incl. selector path, max constants used)
        — reference compute_stats_at_depth (setup.rs:1412)."""
        if self.kind == "Empty":
            assert depth == 0
            return (0, 0)
        if self.kind == "GateOnly":
            return (
                self.gate.degree_at_depth(depth),
                self.gate.num_constants + depth,
            )
        ls = self.left.compute_stats(depth + 1)
        rs = self.right.compute_stats(depth + 1)
        return (max(ls[0], rs[0]), max(ls[1], rs[1]))

    # -- construction (setup.rs:1466 try_add_gate) --------------------------

    def try_add_gate(
        self,
        gate: GateDescription,
        max_degree: int,
        max_constants: int,
        depth: int = 0,
    ):
        if self.kind == "Empty":
            if (
                gate.degree_at_depth(depth) > max_degree
                or gate.num_constants > max_constants
            ):
                return None
            return TreeNode("GateOnly", gate=gate)
        if self.kind == "GateOnly":
            for left, right in (
                (self.gate, gate),
                (gate, self.gate),
            ):
                candidate = TreeNode(
                    "Fork",
                    left=TreeNode("GateOnly", gate=left),
                    right=TreeNode("GateOnly", gate=right),
                )
                deg, consts = candidate.compute_stats(depth)
                if deg <= max_degree and consts <= max_constants:
                    return candidate
            return None
        new_left = self.left.try_add_gate(
            gate, max_degree, max_constants, depth + 1
        )
        if new_left is not None:
            return TreeNode("Fork", left=new_left, right=self.right)
        new_right = self.right.try_add_gate(
            gate, max_degree, max_constants, depth + 1
        )
        if new_right is not None:
            return TreeNode("Fork", left=self.left, right=new_right)
        return None


def try_find_placement_for_degree(
    gates, degree_bound: int, starting_num_constants: int
):
    """setup.rs:1328 — relax the constant budget a few times at fixed
    degree."""
    k = len(gates)
    upper = (max(k - 1, 1)).bit_length()
    for i in range(upper + 2):
        tree = TreeNode("Empty")
        ok = True
        for gate in gates:
            new = tree.try_add_gate(
                gate, degree_bound, starting_num_constants + i
            )
            if new is None:
                ok = False
                break
            tree = new
        if ok:
            return tree
    return None


def compute_selector_placement(descriptions) -> TreeNode:
    """Reference compute_selectors_and_constants_placement (setup.rs:486):
    stable-sort by (degree desc, constants desc), pick a power-of-two target
    degree from the max bare gate degree, insert greedily, doubling the
    target up to 4 times."""
    assert descriptions, "no gates to place"
    if len(descriptions) == 1:
        return TreeNode("GateOnly", gate=descriptions[0])
    gates = sorted(
        descriptions, key=lambda g: (-g.degree, -g.num_constants)
    )
    max_degree = max(g.degree_at_depth(0) for g in gates) - 1
    max_num_constants = max(g.num_constants for g in gates)
    target = max(1, max_degree)
    if target & (target - 1):
        target = 1 << target.bit_length()
    for _ in range(4):
        tree = try_find_placement_for_degree(
            gates, target, max_num_constants
        )
        if tree is not None:
            return tree
        target *= 2
    raise RuntimeError(
        f"cannot find a selector placement for target degree {target}"
    )
