"""Runtime lookup tables.

Counterpart of `/root/reference/src/cs/implementations/lookup_table.rs:10`
(`LookupTable<F, N>` content + key->row index map) without the width-generic
wrapper enums: a table is a dense (rows, width) numpy array plus a dict from
key tuple to row index. Table ids are allocated by the CS starting at 1
(`reference_cs.rs:23`), so id 0 never collides with the zero padding of the
table-id setup polynomial.
"""

from __future__ import annotations

import numpy as np

from ..field import gl


class LookupTable:
    def __init__(self, name: str, num_keys: int, num_values: int, rows):
        """rows: iterable of tuples of ints, each of width num_keys+num_values."""
        self.name = name
        self.num_keys = num_keys
        self.num_values = num_values
        self.width = num_keys + num_values
        content = np.array(
            [[int(v) % gl.P for v in row] for row in rows], dtype=np.uint64
        )
        assert content.ndim == 2 and content.shape[1] == self.width, (
            f"table {name}: rows must have width {self.width}"
        )
        self.content = content
        self._index = {
            tuple(int(v) for v in row[: num_keys]): i
            for i, row in enumerate(content)
        }
        assert len(self._index) == len(content), f"table {name}: duplicate keys"

    def __len__(self):
        return len(self.content)

    def row_index(self, vals) -> int:
        """Row index of a full (keys+values) tuple; keys alone also accepted."""
        key = tuple(int(v) for v in vals[: self.num_keys])
        idx = self._index[key]
        if len(vals) > self.num_keys:
            expect = tuple(int(v) for v in self.content[idx])
            assert tuple(int(v) for v in vals) == expect, (
                f"table {self.name}: tuple {vals} is not a table row"
            )
        return idx

    def lookup_values(self, keys) -> tuple:
        row = self.content[self._index[tuple(int(k) for k in keys)]]
        return tuple(int(v) for v in row[self.num_keys :])


# ---------------------------------------------------------------------------
# Common table builders (reference `src/gadgets/tables/`)
# ---------------------------------------------------------------------------


def binop_table(name: str, op) -> LookupTable:
    """8-bit binary op table: (a, b) -> op(a, b); 65536 rows."""
    a = np.arange(256, dtype=np.uint64).repeat(256)
    b = np.tile(np.arange(256, dtype=np.uint64), 256)
    return LookupTable(name, 2, 1, np.stack([a, b, op(a, b)], axis=1))


def and8_table() -> LookupTable:
    return binop_table("and8", lambda a, b: a & b)


def xor8_table() -> LookupTable:
    return binop_table("xor8", lambda a, b: a ^ b)


def or8_table() -> LookupTable:
    return binop_table("or8", lambda a, b: a | b)


def range_check_table(bits: int, name: str | None = None) -> LookupTable:
    """[0, 2^bits) membership table, one key column, zero value columns...
    represented as (x, 0) pairs (width-2) so the table is usable in width-2
    sub-arguments alongside other tables (reference range_check_16_bits.rs
    uses a 1-column table; we carry an explicit zero value column to keep all
    tables in one stacked layout)."""
    n = 1 << bits
    x = np.arange(n, dtype=np.uint64)
    z = np.zeros(n, dtype=np.uint64)
    return LookupTable(name or f"range_{bits}", 1, 1, np.stack([x, z], axis=1))
