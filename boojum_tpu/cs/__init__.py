from .types import (
    CSGeometry,
    Place,
    VAR,
    WIT,
    PLACEHOLDER,
    var,
    wit,
    is_var,
    is_wit,
    place_index,
)
