from .types import (
    CSGeometry,
    Place,
    VAR,
    WIT,
    PLACEHOLDER,
    var,
    wit,
    is_var,
    is_wit,
    place_index,
)
from .lookup_table import (
    LookupTable,
    and8_table,
    xor8_table,
    or8_table,
    binop_table,
    range_check_table,
)
