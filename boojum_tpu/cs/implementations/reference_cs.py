"""Synthesis-time constraint system and its frozen, provable assembly.

Counterpart of the reference `CSReferenceImplementation` /
`CSReferenceAssembly` (`/root/reference/src/cs/implementations/reference_cs.rs:26`,
placement logic in `implementations/cs.rs:63,112,427`, freeze at `:199-287`).

Design differences (TPU-first):
- placement data is dense numpy int64 arrays (column-major (cols, rows) of
  place ids, -1 = vacant) so the witness scatter at freeze time is one
  vectorized gather into device arrays — no per-cell objects;
- gate constants and selector encoding are NOT written into constant columns
  during synthesis; they are materialized at setup once the selector tree over
  the finally-used gate set is known (reference does the same split:
  setup.rs:486 + setup.rs:710);
- the witness "DAG" is the eager batched resolver in `boojum_tpu.dag`.
"""

from __future__ import annotations

import numpy as np

from ...field import gl
from ...field.active import field_p
from ...field.spec import active_field
from ..types import CSGeometry, CSConfig, DEV_CS_CONFIG, LookupParameters
from ...dag import NullResolver, make_resolver
from ..gates.base import Gate
from ..gates.simple import ConstantsAllocatorGate


class FieldCapacityError(ValueError):
    """A gadget's arithmetic does not fit the active field backend.

    Raised at SYNTHESIS time (not at prove time, where the broken witness
    would only surface as an unsatisfiable trace): e.g. sha256's u32
    decomposition gates need every 32-bit value to be a distinct field
    element, which BabyBear (p = 2^31 - 2^27 + 1) cannot represent."""


class ConstraintSystem:
    def __init__(
        self,
        geometry: CSGeometry,
        max_trace_len: int,
        config: CSConfig = DEV_CS_CONFIG,
        lookup_params: LookupParameters | None = None,
        resolver=None,
    ):
        self.geometry = geometry
        self.max_trace_len = max_trace_len
        self.config = config
        self.lookup_params = lookup_params or LookupParameters()
        # field backend binding (ISSUE 20): the circuit is synthesized OVER
        # a field — witness values, gate constants and resolver arithmetic
        # all reduce mod this prime, and the frozen assembly carries the
        # name so a prove under a different BOOJUM_TPU_FIELD fails loudly
        # instead of producing an unsatisfiable trace.
        self.field = active_field()
        self._field_p = field_p()
        if resolver is not None:
            self.resolver = resolver
        else:
            self.resolver = (
                make_resolver() if config.evaluate_witness else NullResolver()
            )
        self.next_var_idx = 0
        self.next_wit_idx = 0
        c = geometry.num_columns_under_copy_permutation
        w = geometry.num_witness_columns
        self.copy_placement = np.full((c, max_trace_len), -1, dtype=np.int64)
        self.wit_placement = np.full((w, max_trace_len), -1, dtype=np.int64)
        self.row_gate = np.full(max_trace_len, -1, dtype=np.int32)
        self.gates: list[Gate] = []
        self.gate_index: dict[str, int] = {}
        self.gate_constants: dict[int, tuple] = {}
        self.next_row = 0
        self._tooling: dict[tuple, list] = {}
        self.public_inputs: list[tuple[int, int]] = []
        self._zero_var = None
        self._one_var = None
        self._constants_cache: dict[int, int] = {}
        # lookups (specialized columns mode)
        self.lookup_tables = []  # list of LookupTable
        self._table_by_name = {}
        self.lookup_rows: list[list[int]] = []  # per sub-argument: row-major keys
        self.lookup_multiplicities: dict[int, int] | None = None

    # ------------------------------------------------------------------
    # allocation (reference implementations/cs.rs:63)
    # ------------------------------------------------------------------

    def alloc_variable_without_value(self) -> int:
        place = self.next_var_idx << 1
        self.next_var_idx += 1
        return place

    def alloc_multiple_variables_without_values(self, n: int) -> list[int]:
        base = self.next_var_idx
        self.next_var_idx += n
        return [(base + i) << 1 for i in range(n)]

    def alloc_witness_without_value(self) -> int:
        place = (self.next_wit_idx << 1) | 1
        self.next_wit_idx += 1
        return place

    def alloc_variable_with_value(self, value: int) -> int:
        p = self.alloc_variable_without_value()
        self.resolver.set_value(p, value % self._field_p)
        return p

    def set_values_with_dependencies(self, ins, outs, fn, native=None, table=None):
        """Register a witness closure (reference cs.rs:112). `native` is an
        optional typed-op descriptor for the native tape engine; `fn` remains
        the portable fallback."""
        self.resolver.add_resolution(ins, outs, fn, native=native, table=table)

    def get_value(self, place: int) -> int:
        return self.resolver.get_value(place)

    def require_field_bits(self, bits: int, feature: str) -> None:
        """Field-capacity guard (ISSUE 20): assert the active field can
        hold every value in [0, 2^bits) as a distinct element. Gadgets
        whose arithmetic assumes b-bit integers (u32 decompositions, byte
        tables) call this at synthesis so e.g. sha256-over-babybear fails
        with a clear error instead of a silently wrapped witness."""
        if (1 << bits) > self._field_p:
            raise FieldCapacityError(
                f"{feature} needs {bits}-bit values as single field "
                f"elements, but the active field backend "
                f"{self.field!r} has p = {self._field_p} "
                f"(< 2^{bits}); this circuit is only supported over a "
                f"larger field (e.g. goldilocks — unset BOOJUM_TPU_FIELD)"
            )

    # -- canonical constants ------------------------------------------------

    def zero_var(self) -> int:
        if self._zero_var is None:
            self._zero_var = self.allocate_constant(0)
        return self._zero_var

    def one_var(self) -> int:
        if self._one_var is None:
            self._one_var = self.allocate_constant(1)
        return self._one_var

    def allocate_constant(self, value: int) -> int:
        """Allocate (or reuse) a variable pinned to a constant. Same-value
        requests return the same variable — the copy-permutation makes reuse
        free, and hash gadgets re-request the same round constants heavily
        (the reference amortizes these per-row via tooling instead,
        constant_allocator.rs)."""
        value = value % self._field_p
        v = self._constants_cache.get(value)
        if v is None:
            v = ConstantsAllocatorGate.allocate_constant(self, value)
            self._constants_cache[value] = v
        return v

    def has_table(self, name: str) -> bool:
        return name in self._table_by_name

    def ensure_table(self, name: str, builder) -> int:
        """Register the table built by `builder()` unless already present;
        returns its table id."""
        if name not in self._table_by_name:
            self.add_lookup_table(builder())
        return self._table_by_name[name]

    # ------------------------------------------------------------------
    # gate placement (reference implementations/cs.rs:427)
    # ------------------------------------------------------------------

    def _register_gate(self, gate: Gate) -> int:
        gid = self.gate_index.get(gate.name)
        if gid is None:
            gid = len(self.gates)
            self.gates.append(gate)
            self.gate_index[gate.name] = gid
            # full check (path depth + constants) happens at setup time once
            # the selector tree is known
            assert gate.num_constants <= self.geometry.num_constant_columns
        return gid

    def place_gate(self, gate: Gate, var_places, constants=(), wit_places=()):
        """Place one instance; returns (first_column, row) of the instance."""
        gid = self._register_gate(gate)
        key = (gate.name, tuple(constants))
        reps = gate.num_repetitions(self.geometry)
        assert reps >= 1, f"gate {gate.name} does not fit geometry"
        tool = self._tooling.get(key)
        if tool is None or tool[1] >= reps:
            row = self.next_row
            assert row < self.max_trace_len, "trace overflow"
            self.next_row += 1
            self.row_gate[row] = gid
            if constants:
                self.gate_constants[row] = tuple(
                    int(c) % self._field_p for c in constants
                )
            tool = [row, 0]
            self._tooling[key] = tool
        row, used = tool
        off = used * gate.principal_width
        assert len(var_places) == gate.principal_width
        for i, p in enumerate(var_places):
            self.copy_placement[off + i, row] = p
        if gate.witness_width:
            woff = used * gate.witness_width
            assert len(wit_places) == gate.witness_width
            for i, p in enumerate(wit_places):
                self.wit_placement[woff + i, row] = p
        tool[1] = used + 1
        return off, row

    def set_public(self, column: int, row: int):
        self.public_inputs.append((column, row))

    # ------------------------------------------------------------------
    # lookups (specialized-columns, log-derivative; reference
    # lookup_placement.rs:112 + implementations/cs.rs:809)
    # ------------------------------------------------------------------

    def add_lookup_table(self, table) -> int:
        """Register a LookupTable; returns its table id (ids start at 1,
        reference reference_cs.rs:23)."""
        assert table.name not in self._table_by_name
        table_id = len(self.lookup_tables) + 1
        self.lookup_tables.append(table)
        self._table_by_name[table.name] = table_id
        if self.lookup_multiplicities is None:
            self.lookup_multiplicities = {}
        return table_id

    def get_table_id(self, name: str) -> int:
        return self._table_by_name[name]

    def get_table(self, table_id: int):
        return self.lookup_tables[table_id - 1]

    def enforce_lookup(self, table_id: int, keys: list[int]):
        """Constrain tuple of variable places `keys` to be a row of table.

        Placement into specialized lookup columns happens at freeze; here we
        record the tuple and bump multiplicity eagerly via the resolver.
        Tuples narrower than the argument width are padded with zero
        variables (tables are zero-column-padded to match at setup).
        """
        params = self.lookup_params
        assert params.is_enabled, "lookups not configured"
        table = self.get_table(table_id)
        assert len(keys) == table.width
        assert table.width <= params.width
        keys = list(keys)
        while len(keys) < params.width:
            keys.append(self.zero_var())
        if not params.use_specialized_columns:
            # general-purpose mode (reference
            # enforce_lookup_over_general_purpose_columns,
            # lookup_placement.rs:21): the tuple occupies general copy
            # columns on a lookup-marker row whose constant is the table id
            from ..gates.simple import LookupMarkerGate

            self.place_gate(
                LookupMarkerGate.instance(params.width),
                keys,
                (table_id,),
            )
        else:
            self.lookup_rows.append((table_id, keys))
        if self.config.evaluate_witness:

            def bump(vals, table=table, table_id=table_id):
                row_idx = table.row_index(tuple(vals[: table.width]))
                key = (table_id, row_idx)
                self.lookup_multiplicities[key] = (
                    self.lookup_multiplicities.get(key, 0) + 1
                )
                return []

            from ...native import OP_LOOKUP_BUMP

            self.resolver.add_resolution(
                list(keys[: table.width]), [], bump,
                native=(OP_LOOKUP_BUMP, (table_id,)), table=table,
            )

    def perform_lookup(self, table_id: int, key_places: list[int]) -> list[int]:
        """Allocate output variables = table lookup of key variables."""
        from ...native import OP_LOOKUP

        table = self.get_table(table_id)
        num_outs = table.num_values
        outs = self.alloc_multiple_variables_without_values(num_outs)

        def resolve(vals, table=table):
            return list(table.lookup_values(tuple(vals)))

        self.set_values_with_dependencies(
            list(key_places), outs, resolve,
            native=(OP_LOOKUP, (table_id,)), table=table,
        )
        self.enforce_lookup(table_id, list(key_places) + outs)
        return outs

    # ------------------------------------------------------------------
    # finalization / freeze (reference setup.rs:99 pad_and_shrink +
    # reference_cs.rs:257 into_assembly)
    # ------------------------------------------------------------------

    def pad_and_shrink(self):
        from ..gates.simple import NopGate

        # complete partially-filled gate rows with padding instances; padding
        # may itself allocate helper constants (zero/one vars -> new constant
        # rows), so iterate to a fixpoint
        while True:
            unfinished = [
                (key, tool)
                for key, tool in self._tooling.items()
                if tool[1]
                < self.gates[self.gate_index[key[0]]].num_repetitions(self.geometry)
            ]
            if not unfinished:
                break
            for (gname, constants), tool in unfinished:
                gate = self.gates[self.gate_index[gname]]
                reps = gate.num_repetitions(self.geometry)
                row, used = tool
                while used < reps:
                    off = used * gate.principal_width
                    pads = gate.padding_instance(self, constants)
                    for i, p in enumerate(pads):
                        self.copy_placement[off + i, row] = p
                    used += 1
                tool[1] = used
        # rows needed by the specialized lookup columns (R tuples per row,
        # grouped by table id since the id is a shared per-row constant)
        lookup_rows_needed = 0
        if self.lookup_rows:
            R = self.lookup_params.num_repetitions
            per_table: dict[int, int] = {}
            for tid, _ in self.lookup_rows:
                per_table[tid] = per_table.get(tid, 0) + 1
            lookup_rows_needed = sum(
                (cnt + R - 1) // R for cnt in per_table.values()
            )
        # total stacked table content must also fit the trace
        table_content_rows = sum(len(t) for t in self.lookup_tables)
        # round up to a power of two; vacant rows become NOP rows
        rows = max(self.next_row, lookup_rows_needed, table_content_rows, 1)
        n = 1 << max(3, (rows - 1).bit_length())
        assert n <= self.max_trace_len
        nop_gid = self._register_gate(NopGate.instance())
        self.row_gate[: n][self.row_gate[:n] < 0] = nop_gid
        self.trace_len = n
        return n

    def _place_lookups(self, n: int):
        """Pack recorded lookup tuples into the specialized columns.

        Returns (lookup_placement (R*w, n) int64, table_id_col (n,) uint64).
        Every row performs R lookups: vacant slots (and entirely vacant rows)
        are filled with a shared "padding tuple" per table — fresh variables
        resolving to the table's row 0 — whose multiplicity bumps are added
        here so the log-derivative sum stays balanced (the reference pads the
        same way: lookup_placement.rs:112).
        """
        params = self.lookup_params
        R = params.num_repetitions
        w = params.width
        placement = np.full((R * w, n), -1, dtype=np.int64)
        table_id_col = np.zeros(n, dtype=np.uint64)
        evaluating = self.config.evaluate_witness

        pad_tuples: dict[int, list[int]] = {}

        def padding_tuple(tid: int) -> list[int]:
            tup = pad_tuples.get(tid)
            if tup is None:
                table = self.get_table(tid)
                row0 = [int(v) for v in table.content[0]] + [0] * (
                    w - table.width
                )
                tup = self.alloc_multiple_variables_without_values(w)
                for p, v in zip(tup, row0):
                    self.resolver.set_value(p, v)
                pad_tuples[tid] = tup
            return tup

        def bump_padding(tid: int, times: int):
            if evaluating and times:
                key = (tid, 0)
                self.lookup_multiplicities[key] = (
                    self.lookup_multiplicities.get(key, 0) + times
                )

        by_table: dict[int, list[list[int]]] = {}
        for tid, places in self.lookup_rows:
            by_table.setdefault(tid, []).append(places)

        row = 0
        for tid in sorted(by_table):
            tuples = by_table[tid]
            for i in range(0, len(tuples), R):
                chunk = tuples[i : i + R]
                pad_count = R - len(chunk)
                if pad_count:
                    chunk = chunk + [padding_tuple(tid)] * pad_count
                    bump_padding(tid, pad_count)
                table_id_col[row] = tid
                for s, places in enumerate(chunk):
                    placement[s * w : (s + 1) * w, row] = places
                row += 1
        # entirely vacant rows: padding lookups into the first table
        if row < n and self.lookup_tables:
            tid = 1
            tup = padding_tuple(tid)
            table_id_col[row:] = tid
            for s in range(R):
                placement[s * w : (s + 1) * w, row:] = np.array(
                    tup, dtype=np.int64
                )[:, None]
            bump_padding(tid, (n - row) * R)
        return placement, table_id_col

    def into_assembly(self) -> "CSAssembly":
        n = getattr(self, "trace_len", None) or self.pad_and_shrink()
        lookups_on = bool(self.lookup_rows) or (
            self.lookup_params.is_enabled and bool(self.lookup_tables)
        )
        if lookups_on and self.lookup_params.use_specialized_columns:
            lookup_placement, table_id_col = self._place_lookups(n)
        else:
            # general-purpose mode: tuples live in the general copy columns
            # on lookup-marker rows; no specialized columns, no dedicated
            # table-id column (the id is the marker row's gate constant)
            lookup_placement = np.zeros((0, n), dtype=np.int64)
            table_id_col = None
        # AFTER padding/lookup placement (both may register resolutions):
        # force every pending resolution — incl. the native tape — to fire
        self.resolver.wait_till_resolved()
        num_places = 2 * max(self.next_var_idx, self.next_wit_idx) + 2
        arena = self.resolver.values
        if len(arena) < num_places:
            grown = np.zeros(num_places, dtype=np.uint64)
            grown[: len(arena)] = arena
            arena = grown

        def scatter(placement):
            pl = placement[:, :n]
            safe = np.where(pl >= 0, pl, 0)
            vals = arena[safe]
            vals[pl < 0] = 0
            return vals.astype(np.uint64)

        copy_cols = scatter(self.copy_placement)
        wit_cols = scatter(self.wit_placement)
        lookup_cols = scatter(lookup_placement)
        # multiplicity column over the stacked-table row space
        multiplicities = None
        table_offsets = {}
        if lookups_on:
            off = 0
            for tid in range(1, len(self.lookup_tables) + 1):
                table_offsets[tid] = off
                off += len(self.get_table(tid))
            assert off <= n, "stacked lookup tables exceed trace length"
            multiplicities = np.zeros(n, dtype=np.uint64)
            if self.config.evaluate_witness:
                for (tid, row_idx), cnt in self.lookup_multiplicities.items():
                    multiplicities[table_offsets[tid] + row_idx] = cnt
                # merge counters bumped by the native tape engine
                for tid in range(1, len(self.lookup_tables) + 1):
                    nm = self.resolver.native_multiplicities(tid)
                    if nm is not None:
                        off = table_offsets[tid]
                        multiplicities[off : off + len(nm)] += nm.astype(
                            np.uint64
                        )
        return CSAssembly(
            geometry=self.geometry,
            lookup_params=self.lookup_params,
            field=self.field,
            trace_len=n,
            gates=self.gates,
            row_gate=self.row_gate[:n].copy(),
            gate_constants=dict(self.gate_constants),
            copy_placement=self.copy_placement[:, :n],
            wit_placement=self.wit_placement[:, :n],
            copy_cols_values=copy_cols,
            wit_cols_values=wit_cols,
            public_inputs=[
                (c, r, self.get_value(int(self.copy_placement[c, r])))
                for (c, r) in self.public_inputs
            ]
            if self.config.evaluate_witness
            else [(c, r, 0) for (c, r) in self.public_inputs],
            lookup_tables=self.lookup_tables,
            lookup_rows=self.lookup_rows,
            lookup_multiplicities=self.lookup_multiplicities,
            lookup_placement=lookup_placement,
            lookup_cols_values=lookup_cols,
            lookup_table_id_col=table_id_col,
            multiplicities=multiplicities,
            table_offsets=table_offsets,
            resolver=self.resolver,
        )


class CSAssembly:
    """Frozen, provable CS (reference CSReferenceAssembly)."""

    def __init__(self, **kw):
        self.__dict__.update(kw)

    def __getstate__(self):
        # picklable snapshot (long synthesis runs checkpoint the frozen
        # assembly): the resolver holds ctypes handles into the native
        # engine and is only needed for post-freeze witness hooks — the
        # materialized columns/multiplicities below carry the proof inputs
        state = dict(self.__dict__)
        state["resolver"] = None
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)

    @property
    def num_copy_cols(self):
        """General-purpose copy columns (gates live here)."""
        return self.geometry.num_columns_under_copy_permutation

    @property
    def num_lookup_cols(self):
        """Specialized lookup copy columns, appended after the general ones."""
        return self.lookup_placement.shape[0]

    @property
    def num_copy_cols_total(self):
        """All columns under copy permutation (general + lookup)."""
        return self.num_copy_cols + self.num_lookup_cols

    @property
    def num_wit_cols(self):
        return self.geometry.num_witness_columns

    @property
    def lookup_mode(self) -> str:
        """'none' | 'specialized' | 'general' (reference LookupParameters
        placement families, cs/mod.rs:227)."""
        lp = self.lookup_params
        if lp is None or not lp.is_enabled or not self.lookup_tables:
            return "none"
        if lp.use_specialized_columns:
            return "specialized"
        # general mode with zero placed lookups has no marker gate and
        # therefore no lookup argument at all
        return "general" if self.lookup_marker_gid() is not None else "none"

    @property
    def lookups_enabled(self):
        return self.lookup_mode != "none"

    @property
    def num_lookup_subargs(self) -> int:
        """Log-derivative sub-arguments: configured repetitions in
        specialized mode; general columns // width in general mode
        (reference SizeCalculator::num_sublookup_arguments)."""
        mode = self.lookup_mode
        if mode == "specialized":
            return self.lookup_params.num_repetitions
        if mode == "general":
            return self.num_copy_cols // self.lookup_params.width
        return 0

    def lookup_marker_gid(self):
        for i, g in enumerate(self.gates):
            if getattr(g, "is_lookup_marker", False):
                return i
        return None

    def witness_vec(self) -> np.ndarray:
        """Flat resolver value arena for every allocated place (reference
        `WitnessVec`, witness.rs:32): the portable witness artifact for
        repeated proving."""
        if self.resolver is None:
            raise RuntimeError(
                "witness_vec() needs the live resolver, which pickled "
                "assembly checkpoints drop — call it before pickling and "
                "carry the vector alongside, or rebuild via "
                "with_external_witness"
            )
        num_places = int(
            max(
                self.copy_placement.max(initial=-1),
                self.wit_placement.max(initial=-1),
                self.lookup_placement.max(initial=-1),
            )
            + 1
        )
        return np.array(self.resolver.values[:num_places], dtype=np.uint64)

    def with_external_witness(self, witness_vec: np.ndarray) -> "CSAssembly":
        """New assembly with the same circuit but externally supplied witness
        values (reference `into_assembly_for_repeated_proving`,
        reference_cs.rs:271): columns are re-scattered from the flat vector
        and lookup multiplicities recounted from the placed tuples."""
        arena = np.asarray(witness_vec, dtype=np.uint64)

        def scatter(placement):
            pl = placement
            safe = np.where(pl >= 0, pl, 0)
            vals = arena[safe]
            vals[pl < 0] = 0
            return vals.astype(np.uint64)

        copy_cols = scatter(self.copy_placement)
        wit_cols = scatter(self.wit_placement)
        lookup_cols = scatter(self.lookup_placement)
        multiplicities = None
        if self.lookup_mode == "general":
            multiplicities = np.zeros(self.trace_len, dtype=np.uint64)
            lp = self.lookup_params
            w = lp.width
            mk_gid = self.lookup_marker_gid()
            marker = self.gates[mk_gid]
            reps = marker.num_repetitions(self.geometry)
            rows = np.nonzero(self.row_gate == mk_gid)[0]
            tids = np.array(
                [int(self.gate_constants[int(r)][0]) for r in rows],
                dtype=np.uint64,
            )
            # stack every marker slot's tuple: (1 + reps*w, num_rows)
            stacked = np.vstack(
                [tids[None, :]]
                + [copy_cols[s * w : (s + 1) * w, rows] for s in range(reps)]
            )
            uniq, ucounts = np.unique(stacked, axis=1, return_counts=True)
            for u in range(uniq.shape[1]):
                tid = int(uniq[0, u])
                assert tid != 0, (
                    "marker row with table id 0 while recounting "
                    "multiplicities from an external witness"
                )
                table = self.lookup_tables[tid - 1]
                col = uniq[1:, u]
                for s in range(reps):
                    tup = tuple(
                        int(col[s * w + j]) for j in range(table.width)
                    )
                    ridx = table.row_index(tup)
                    multiplicities[self.table_offsets[tid] + ridx] += int(
                        ucounts[u]
                    )
        elif self.lookups_enabled:
            multiplicities = np.zeros(self.trace_len, dtype=np.uint64)
            lp = self.lookup_params
            R, w = lp.num_repetitions, lp.width
            # dedup whole rows first (padding dominates large traces), then
            # count per unique row — same trick as the satisfiability checker
            stacked = np.vstack(
                [np.asarray(self.lookup_table_id_col, dtype=np.uint64)[None, :],
                 lookup_cols]
            )
            uniq, ucounts = np.unique(stacked, axis=1, return_counts=True)
            for u in range(uniq.shape[1]):
                tid = int(uniq[0, u])
                # tid 0 on a lookup row is a hard error everywhere else
                # (satisfiability checker rejects it); recounting must not
                # silently skip it and prove with inconsistent bookkeeping
                assert tid != 0, (
                    "lookup row with table id 0 while recounting "
                    "multiplicities from an external witness"
                )
                table = self.lookup_tables[tid - 1]
                col = uniq[1:, u]
                for s in range(R):
                    tup = tuple(
                        int(col[s * w + j]) for j in range(table.width)
                    )
                    ridx = table.row_index(tup)
                    multiplicities[self.table_offsets[tid] + ridx] += int(
                        ucounts[u]
                    )
        new = CSAssembly(**self.__dict__)
        new.copy_cols_values = copy_cols
        new.wit_cols_values = wit_cols
        new.lookup_cols_values = lookup_cols
        new.multiplicities = multiplicities
        new.public_inputs = [
            (c, r, int(arena[int(self.copy_placement[c, r])]))
            for (c, r, _v) in self.public_inputs
        ]
        new._gate_sweep_jit = None
        # CSAssembly(**self.__dict__) SHARES mutable attrs with self — the
        # prover's device-upload cache (witness columns, multiplicities)
        # must not leak to an assembly with different witness values, or
        # re-proving commits the OLD witness
        new._dev_cache = {}
        return new

    def stacked_table_columns(self, width: int) -> np.ndarray:
        """(width+1, n) setup polys: table columns zero-padded to `width`,
        plus the table-id column, stacked over all tables in id order
        (reference create_lookup_tables_columns_polys, setup.rs:892)."""
        n = self.trace_len
        cols = np.zeros((width + 1, n), dtype=np.uint64)
        off = 0
        for tid in range(1, len(self.lookup_tables) + 1):
            t = self.lookup_tables[tid - 1]
            rows = len(t)
            cols[: t.width, off : off + rows] = t.content.T
            cols[width, off : off + rows] = tid
            off += rows
        return cols
