"""Synthesis-time constraint system and its frozen, provable assembly.

Counterpart of the reference `CSReferenceImplementation` /
`CSReferenceAssembly` (`/root/reference/src/cs/implementations/reference_cs.rs:26`,
placement logic in `implementations/cs.rs:63,112,427`, freeze at `:199-287`).

Design differences (TPU-first):
- placement data is dense numpy int64 arrays (column-major (cols, rows) of
  place ids, -1 = vacant) so the witness scatter at freeze time is one
  vectorized gather into device arrays — no per-cell objects;
- gate constants and selector encoding are NOT written into constant columns
  during synthesis; they are materialized at setup once the selector tree over
  the finally-used gate set is known (reference does the same split:
  setup.rs:486 + setup.rs:710);
- the witness "DAG" is the eager batched resolver in `boojum_tpu.dag`.
"""

from __future__ import annotations

import numpy as np

from ...field import gl
from ..types import CSGeometry, CSConfig, DEV_CS_CONFIG, LookupParameters
from ...dag import WitnessResolver, NullResolver
from ..gates.base import Gate
from ..gates.simple import ConstantsAllocatorGate


class ConstraintSystem:
    def __init__(
        self,
        geometry: CSGeometry,
        max_trace_len: int,
        config: CSConfig = DEV_CS_CONFIG,
        lookup_params: LookupParameters | None = None,
    ):
        self.geometry = geometry
        self.max_trace_len = max_trace_len
        self.config = config
        self.lookup_params = lookup_params or LookupParameters()
        self.resolver = (
            WitnessResolver() if config.evaluate_witness else NullResolver()
        )
        self.next_var_idx = 0
        self.next_wit_idx = 0
        c = geometry.num_columns_under_copy_permutation
        w = geometry.num_witness_columns
        self.copy_placement = np.full((c, max_trace_len), -1, dtype=np.int64)
        self.wit_placement = np.full((w, max_trace_len), -1, dtype=np.int64)
        self.row_gate = np.full(max_trace_len, -1, dtype=np.int32)
        self.gates: list[Gate] = []
        self.gate_index: dict[str, int] = {}
        self.gate_constants: dict[int, tuple] = {}
        self.next_row = 0
        self._tooling: dict[tuple, list] = {}
        self.public_inputs: list[tuple[int, int]] = []
        self._zero_var = None
        self._one_var = None
        # lookups (specialized columns mode)
        self.lookup_tables = []  # list of LookupTable
        self._table_by_name = {}
        self.lookup_rows: list[list[int]] = []  # per sub-argument: row-major keys
        self.lookup_multiplicities: dict[int, int] | None = None

    # ------------------------------------------------------------------
    # allocation (reference implementations/cs.rs:63)
    # ------------------------------------------------------------------

    def alloc_variable_without_value(self) -> int:
        place = self.next_var_idx << 1
        self.next_var_idx += 1
        return place

    def alloc_multiple_variables_without_values(self, n: int) -> list[int]:
        base = self.next_var_idx
        self.next_var_idx += n
        return [(base + i) << 1 for i in range(n)]

    def alloc_witness_without_value(self) -> int:
        place = (self.next_wit_idx << 1) | 1
        self.next_wit_idx += 1
        return place

    def alloc_variable_with_value(self, value: int) -> int:
        p = self.alloc_variable_without_value()
        self.resolver.set_value(p, value % gl.P)
        return p

    def set_values_with_dependencies(self, ins, outs, fn):
        """Register a witness closure (reference cs.rs:112)."""
        self.resolver.add_resolution(ins, outs, fn)

    def get_value(self, place: int) -> int:
        return self.resolver.get_value(place)

    # -- canonical constants ------------------------------------------------

    def zero_var(self) -> int:
        if self._zero_var is None:
            self._zero_var = ConstantsAllocatorGate.allocate_constant(self, 0)
        return self._zero_var

    def one_var(self) -> int:
        if self._one_var is None:
            self._one_var = ConstantsAllocatorGate.allocate_constant(self, 1)
        return self._one_var

    def allocate_constant(self, value: int) -> int:
        return ConstantsAllocatorGate.allocate_constant(self, value)

    # ------------------------------------------------------------------
    # gate placement (reference implementations/cs.rs:427)
    # ------------------------------------------------------------------

    def _register_gate(self, gate: Gate) -> int:
        gid = self.gate_index.get(gate.name)
        if gid is None:
            gid = len(self.gates)
            self.gates.append(gate)
            self.gate_index[gate.name] = gid
            # full check (path depth + constants) happens at setup time once
            # the selector tree is known
            assert gate.num_constants <= self.geometry.num_constant_columns
        return gid

    def place_gate(self, gate: Gate, var_places, constants=(), wit_places=()):
        """Place one instance; returns (first_column, row) of the instance."""
        gid = self._register_gate(gate)
        key = (gate.name, tuple(constants))
        reps = gate.num_repetitions(self.geometry)
        assert reps >= 1, f"gate {gate.name} does not fit geometry"
        tool = self._tooling.get(key)
        if tool is None or tool[1] >= reps:
            row = self.next_row
            assert row < self.max_trace_len, "trace overflow"
            self.next_row += 1
            self.row_gate[row] = gid
            if constants:
                self.gate_constants[row] = tuple(int(c) % gl.P for c in constants)
            tool = [row, 0]
            self._tooling[key] = tool
        row, used = tool
        off = used * gate.principal_width
        assert len(var_places) == gate.principal_width
        for i, p in enumerate(var_places):
            self.copy_placement[off + i, row] = p
        if gate.witness_width:
            woff = used * gate.witness_width
            assert len(wit_places) == gate.witness_width
            for i, p in enumerate(wit_places):
                self.wit_placement[woff + i, row] = p
        tool[1] = used + 1
        return off, row

    def set_public(self, column: int, row: int):
        self.public_inputs.append((column, row))

    # ------------------------------------------------------------------
    # lookups (specialized-columns, log-derivative; reference
    # lookup_placement.rs:112 + implementations/cs.rs:809)
    # ------------------------------------------------------------------

    def add_lookup_table(self, table) -> int:
        """Register a LookupTable; returns its table id (ids start at 1,
        reference reference_cs.rs:23)."""
        assert table.name not in self._table_by_name
        table_id = len(self.lookup_tables) + 1
        self.lookup_tables.append(table)
        self._table_by_name[table.name] = table_id
        if self.lookup_multiplicities is None:
            self.lookup_multiplicities = {}
        return table_id

    def get_table_id(self, name: str) -> int:
        return self._table_by_name[name]

    def get_table(self, table_id: int):
        return self.lookup_tables[table_id - 1]

    def enforce_lookup(self, table_id: int, keys: list[int]):
        """Constrain tuple of variable places `keys` to be a row of table.

        Placement into specialized lookup columns happens at freeze; here we
        record the tuple and bump multiplicity eagerly via the resolver.
        """
        params = self.lookup_params
        assert params.is_enabled, "lookups not configured"
        assert len(keys) == params.width
        self.lookup_rows.append((table_id, list(keys)))
        if self.config.evaluate_witness:
            table = self.get_table(table_id)

            def bump(vals, table=table, table_id=table_id):
                row_idx = table.row_index(tuple(vals))
                key = (table_id, row_idx)
                self.lookup_multiplicities[key] = (
                    self.lookup_multiplicities.get(key, 0) + 1
                )
                return []

            self.resolver.add_resolution(list(keys), [], bump)

    def perform_lookup(self, table_id: int, key_places: list[int]) -> list[int]:
        """Allocate output variables = table lookup of key variables."""
        table = self.get_table(table_id)
        num_outs = table.num_values
        outs = self.alloc_multiple_variables_without_values(num_outs)

        def resolve(vals, table=table):
            return list(table.lookup_values(tuple(vals)))

        self.set_values_with_dependencies(list(key_places), outs, resolve)
        self.enforce_lookup(table_id, list(key_places) + outs)
        return outs

    # ------------------------------------------------------------------
    # finalization / freeze (reference setup.rs:99 pad_and_shrink +
    # reference_cs.rs:257 into_assembly)
    # ------------------------------------------------------------------

    def pad_and_shrink(self):
        from ..gates.simple import NopGate

        # complete partially-filled gate rows with padding instances; padding
        # may itself allocate helper constants (zero/one vars -> new constant
        # rows), so iterate to a fixpoint
        while True:
            unfinished = [
                (key, tool)
                for key, tool in self._tooling.items()
                if tool[1]
                < self.gates[self.gate_index[key[0]]].num_repetitions(self.geometry)
            ]
            if not unfinished:
                break
            for (gname, constants), tool in unfinished:
                gate = self.gates[self.gate_index[gname]]
                reps = gate.num_repetitions(self.geometry)
                row, used = tool
                while used < reps:
                    off = used * gate.principal_width
                    pads = gate.padding_instance(self, constants)
                    for i, p in enumerate(pads):
                        self.copy_placement[off + i, row] = p
                    used += 1
                tool[1] = used
        # round up to a power of two; vacant rows become NOP rows
        n = 1 << max(3, (max(self.next_row, 1) - 1).bit_length())
        assert n <= self.max_trace_len
        nop_gid = self._register_gate(NopGate.instance())
        self.row_gate[: n][self.row_gate[:n] < 0] = nop_gid
        self.trace_len = n
        return n

    def into_assembly(self) -> "CSAssembly":
        self.resolver.wait_till_resolved()
        n = getattr(self, "trace_len", None) or self.pad_and_shrink()
        num_places = 2 * max(self.next_var_idx, self.next_wit_idx) + 2
        arena = self.resolver.values
        if len(arena) < num_places:
            grown = np.zeros(num_places, dtype=np.uint64)
            grown[: len(arena)] = arena
            arena = grown

        def scatter(placement):
            pl = placement[:, :n]
            safe = np.where(pl >= 0, pl, 0)
            vals = arena[safe]
            vals[pl < 0] = 0
            return vals.astype(np.uint64)

        copy_cols = scatter(self.copy_placement)
        wit_cols = scatter(self.wit_placement)
        return CSAssembly(
            geometry=self.geometry,
            lookup_params=self.lookup_params,
            trace_len=n,
            gates=self.gates,
            row_gate=self.row_gate[:n].copy(),
            gate_constants=dict(self.gate_constants),
            copy_placement=self.copy_placement[:, :n],
            wit_placement=self.wit_placement[:, :n],
            copy_cols_values=copy_cols,
            wit_cols_values=wit_cols,
            public_inputs=[
                (c, r, self.get_value(int(self.copy_placement[c, r])))
                for (c, r) in self.public_inputs
            ]
            if self.config.evaluate_witness
            else [(c, r, 0) for (c, r) in self.public_inputs],
            lookup_tables=self.lookup_tables,
            lookup_rows=self.lookup_rows,
            lookup_multiplicities=self.lookup_multiplicities,
            resolver=self.resolver,
        )


class CSAssembly:
    """Frozen, provable CS (reference CSReferenceAssembly)."""

    def __init__(self, **kw):
        self.__dict__.update(kw)

    @property
    def num_copy_cols(self):
        return self.geometry.num_columns_under_copy_permutation

    @property
    def num_wit_cols(self):
        return self.geometry.num_witness_columns
