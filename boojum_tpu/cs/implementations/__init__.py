from .reference_cs import ConstraintSystem, CSAssembly
