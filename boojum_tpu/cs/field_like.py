"""Field-like op contexts: one gate definition, many execution contexts.

The reference achieves "write the constraint once, run it everywhere" with the
`PrimeFieldLike` trait (`/root/reference/src/field/traits/field_like.rs:24`):
the same gate evaluator runs over scalars (satisfiability checks), SIMD
vectors (prover sweep) and circuit variables (recursive verifier). Here the
same contract is a tiny duck-typed ops object:

- ScalarOps    : python ints, base field        (satisfiability checker)
- ArrayOps     : jnp uint64 arrays, base field  (prover quotient sweep — the
                 whole LDE domain at once; XLA vectorizes)
- LimbOps      : (lo, hi) uint32 limb pairs     (the Pallas limb-domain
                 sweep kernels, prover/pallas_sweep.py — Mosaic has no
                 64-bit integer datapath)
- ExtScalarOps : (int, int) tuples, GF(p^2)     (plain verifier at z)
- circuit ops  : gadget Nums (recursive verifier, later layer)

BabyBear twins (ISSUE 19) speak the same contract over one u32 lane per
element — no LimbOps analogue exists because BabyBear never splits:

- BBScalarOps    : python ints mod 2^31-2^27+1
- BBArrayOps     : jnp uint32 arrays, plane-free
- BBExtScalarOps : 4-tuples, GF(p^4) = GF(p)[w]/(w^4 - 11)
"""

import jax.numpy as jnp

from ..field import gl
from ..field import babybear as bb
from ..field import extension as ext_f
from ..field import goldilocks as gf
from ..field import limbs as _limbs


class ScalarOps:
    @staticmethod
    def zero():
        return 0

    @staticmethod
    def one():
        return 1

    @staticmethod
    def constant(v: int):
        return v % gl.P

    add = staticmethod(gl.add)
    sub = staticmethod(gl.sub)
    mul = staticmethod(gl.mul)
    neg = staticmethod(gl.neg)

    @staticmethod
    def double(a):
        return gl.add(a, a)


class ArrayOps:
    """Base-field ops over whole jnp arrays (vectorized across domain rows)."""

    @staticmethod
    def zero():
        return jnp.uint64(0)

    @staticmethod
    def one():
        return jnp.uint64(1)

    @staticmethod
    def constant(v: int):
        return jnp.uint64(v % gl.P)

    add = staticmethod(gf.add)
    sub = staticmethod(gf.sub)
    mul = staticmethod(gf.mul)
    neg = staticmethod(gf.neg)
    double = staticmethod(gf.double)


class LimbOps:
    """Base-field ops over (lo, hi) uint32 limb pairs — the SAME gate
    evaluators run inside Pallas kernels (and in interpret mode on CPU);
    exact mod p, bit-identical to ArrayOps after limbs.join."""

    @staticmethod
    def zero():
        return jnp.uint32(0), jnp.uint32(0)

    @staticmethod
    def one():
        return jnp.uint32(1), jnp.uint32(0)

    @staticmethod
    def constant(v: int):
        lo, hi = _limbs.const_pair(v)
        return jnp.uint32(lo), jnp.uint32(hi)

    add = staticmethod(_limbs.add)
    sub = staticmethod(_limbs.sub)
    mul = staticmethod(_limbs.mul)
    neg = staticmethod(_limbs.neg)
    double = staticmethod(_limbs.double)


class ExtScalarOps:
    @staticmethod
    def zero():
        return ext_f.ZERO_S

    @staticmethod
    def one():
        return ext_f.ONE_S

    @staticmethod
    def constant(v: int):
        return (v % gl.P, 0)

    add = staticmethod(ext_f.add_s)
    sub = staticmethod(ext_f.sub_s)
    mul = staticmethod(ext_f.mul_s)
    neg = staticmethod(ext_f.neg_s)

    @staticmethod
    def double(a):
        return ext_f.add_s(a, a)


class BBScalarOps:
    """BabyBear base-field ops over python ints (satisfiability checks
    of a circuit declared over the BabyBear backend)."""

    @staticmethod
    def zero():
        return 0

    @staticmethod
    def one():
        return 1

    @staticmethod
    def constant(v: int):
        return v % bb.P

    add = staticmethod(bb.add_s)
    sub = staticmethod(bb.sub_s)
    mul = staticmethod(bb.mul_s)
    neg = staticmethod(bb.neg_s)

    @staticmethod
    def double(a):
        return bb.add_s(a, a)


class BBArrayOps:
    """BabyBear base-field ops over whole jnp uint32 arrays — the
    plane-free twin of ArrayOps: one lane per element, no limb pairs
    anywhere, so the same gate evaluator vectorizes over the LDE domain
    at half the HBM bytes of the Goldilocks path."""

    @staticmethod
    def zero():
        return jnp.uint32(0)

    @staticmethod
    def one():
        return jnp.uint32(1)

    @staticmethod
    def constant(v: int):
        return jnp.uint32(v % bb.P)

    add = staticmethod(bb.add)
    sub = staticmethod(bb.sub)
    mul = staticmethod(bb.mul)
    neg = staticmethod(bb.neg)
    double = staticmethod(bb.double)


class BBNpArrayOps:
    """BabyBear base-field ops over numpy uint32 arrays — the host twin of
    BBArrayOps for the numpy reference backend's quotient sweep. Same gate
    evaluators, same reduction discipline, pure numpy."""

    @staticmethod
    def zero():
        import numpy as _np

        return _np.uint32(0)

    @staticmethod
    def one():
        import numpy as _np

        return _np.uint32(1)

    @staticmethod
    def constant(v: int):
        import numpy as _np

        return _np.uint32(v % bb.P)

    add = staticmethod(bb.add_np)
    sub = staticmethod(bb.sub_np)
    mul = staticmethod(bb.mul_np)

    @staticmethod
    def neg(a):
        import numpy as _np

        return bb.sub_np(_np.uint32(0), a)

    @staticmethod
    def double(a):
        return bb.add_np(a, a)


class BBExtScalarOps:
    """GF(p^4) ops over 4-tuples of python ints (BabyBear verifier at z)."""

    @staticmethod
    def zero():
        return bb.ZERO_S

    @staticmethod
    def one():
        return bb.ONE_S

    @staticmethod
    def constant(v: int):
        return bb.ext_from_base_s(v % bb.P)

    add = staticmethod(bb.ext_add_s)
    sub = staticmethod(bb.ext_sub_s)
    mul = staticmethod(bb.ext_mul_s)
    neg = staticmethod(bb.ext_neg_s)

    @staticmethod
    def double(a):
        return bb.ext_add_s(a, a)
