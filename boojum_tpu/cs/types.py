"""CS data model: places, geometry, configs.

Counterpart of the reference's `Place`/`Variable`/`Witness` (u48 + tag bits,
`/root/reference/src/cs/mod.rs:35,155,185`), `CSGeometry` (`:218`) and the
type-level `CSConfig` bundles (`src/config.rs:27`). Python-side synthesis is
hot (millions of allocations), so places are plain ints with a tag bit rather
than objects: variable k -> 2k, witness k -> 2k+1, placeholder -> -1.
"""

from dataclasses import dataclass, field

PLACEHOLDER = -1
VAR = 0
WIT = 1


def var(idx: int) -> int:
    return idx << 1


def wit(idx: int) -> int:
    return (idx << 1) | 1


def is_var(place: int) -> bool:
    return place >= 0 and (place & 1) == 0


def is_wit(place: int) -> bool:
    return place >= 0 and (place & 1) == 1


def place_index(place: int) -> int:
    assert place >= 0
    return place >> 1


Place = int  # alias for documentation


@dataclass(frozen=True)
class CSGeometry:
    """Trace shape (reference `CSGeometry`, src/cs/mod.rs:218)."""

    num_columns_under_copy_permutation: int
    num_witness_columns: int
    num_constant_columns: int
    max_allowed_constraint_degree: int


@dataclass(frozen=True)
class LookupParameters:
    """Lookup configuration (reference `LookupParameters`, src/cs/mod.rs:227).

    width = number of key-value columns per sub-argument (excluding the
    table-id column); num_repetitions = number of parallel sub-arguments
    (specialized mode); share_table_id = table id carried as a per-row
    constant; use_specialized_columns selects between dedicated lookup
    columns (reference lookup_placement.rs:112) and the general-purpose
    -columns mode where tuples live on selector-gated marker rows
    (lookup_placement.rs:21).
    """

    width: int = 0
    num_repetitions: int = 0
    share_table_id: bool = True
    use_specialized_columns: bool = True

    @property
    def is_enabled(self) -> bool:
        if not self.use_specialized_columns:
            # general-purpose mode: sub-arguments tile the general columns,
            # so only the tuple width configures it
            return self.width > 0
        return self.num_repetitions > 0

    @property
    def specialized_columns_per_subargument(self) -> int:
        return self.width + (0 if self.share_table_id else 1)


@dataclass
class CSConfig:
    """Runtime analogue of the reference's type-level config bundles.

    evaluate_witness: run witness resolution (off for setup-only synthesis);
    runtime_asserts: extra invariant checks during synthesis;
    keep_setup: retain placement data needed for setup/VK generation.
    """

    evaluate_witness: bool = True
    runtime_asserts: bool = True
    keep_setup: bool = True


DEV_CS_CONFIG = CSConfig(True, True, True)
PROVING_CS_CONFIG = CSConfig(True, False, False)
SETUP_CS_CONFIG = CSConfig(False, True, True)
