from .base import Gate, RowView, TermsCollector
from .simple import (
    FmaGate,
    ConstantsAllocatorGate,
    ExplicitConstantsAllocatorGate,
    BooleanConstraintGate,
    NopGate,
    PublicInputGate,
    ReductionGate,
    SelectionGate,
    BoundedGateWrapper,
    LookupMarkerGate,
    ZeroCheckGate,
    ZeroCheckWitnessGate,
    ParallelSelectionGate,
    ConditionalSwapGate,
    DotProductGate,
    QuadraticCombinationGate,
    ReductionByPowersGate,
    SimpleNonlinearityGate,
    MatrixMultiplicationGate,
)
from .u32 import U32AddGate, U32SubGate, U32FmaGate, U32TriAddCarryAsChunkGate, UIntXAddGate
from .ext_fma import ExtFmaGate
from .poseidon2_flat import Poseidon2FlattenedGate
from .poseidon_flat import PoseidonFlattenedGate
