"""Fixed-width integer gates (reference u32_add.rs, u32_sub.rs, u32_fma.rs,
u32_tri_add_carry_as_chunk.rs, uintx_add.rs).

Range correctness of the 32-bit limbs themselves comes from lookup-table range
checks at the gadget layer (as in the reference); these gates enforce the
carry arithmetic relations.
"""

from __future__ import annotations

from ...field import gl
from .base import Gate

SHIFT32 = 1 << 32


def _require_capacity(cs, bits: int, gate_name: str) -> None:
    """Field-capacity guard (ISSUE 20): these gates assume every `bits`-bit
    integer is a distinct field element (e.g. the u32 fma relation
    a·b + c + cin = low + 2^32·high needs 2^32 < p). Synthesizing them
    over a too-small backend (BabyBear, p ≈ 2^31) must fail loudly."""
    require = getattr(cs, "require_field_bits", None)
    if require is not None:
        require(bits, f"{gate_name} (fixed-width integer arithmetic)")


class U32AddGate(Gate):
    """a + b + carry_in = c + 2^32·carry_out; carry_out boolean."""

    name = "u32_add"
    principal_width = 5
    num_terms = 2
    max_degree = 2

    def evaluate(self, ops, row, dst):
        a, b, cin, c, cout = (row.v(i) for i in range(5))
        lhs = ops.add(ops.add(a, b), cin)
        rhs = ops.add(c, ops.mul(ops.constant(SHIFT32), cout))
        dst.push(ops.sub(lhs, rhs))
        dst.push(ops.sub(ops.mul(cout, cout), cout))

    @staticmethod
    def add(cs, a, b, carry_in):
        _require_capacity(cs, 32, "U32AddGate")
        c = cs.alloc_variable_without_value()
        cout = cs.alloc_variable_without_value()

        def resolve(vals):
            s = vals[0] + vals[1] + vals[2]
            return [s & 0xFFFFFFFF, s >> 32]

        from ...native import OP_U32_ADD

        cs.set_values_with_dependencies(
            [a, b, carry_in], [c, cout], resolve,
            native=(OP_U32_ADD, (32,)),
        )
        cs.place_gate(U32AddGate.instance(), [a, b, carry_in, c, cout], ())
        return c, cout

    _inst = None

    @classmethod
    def instance(cls):
        if cls._inst is None:
            cls._inst = cls()
        return cls._inst


class U32SubGate(Gate):
    """a − b − borrow_in = c − 2^32·borrow_out; borrow_out boolean."""

    name = "u32_sub"
    principal_width = 5
    num_terms = 2
    max_degree = 2

    def evaluate(self, ops, row, dst):
        a, b, bin_, c, bout = (row.v(i) for i in range(5))
        lhs = ops.sub(ops.sub(a, b), bin_)
        rhs = ops.sub(c, ops.mul(ops.constant(SHIFT32), bout))
        dst.push(ops.sub(lhs, rhs))
        dst.push(ops.sub(ops.mul(bout, bout), bout))

    @staticmethod
    def sub(cs, a, b, borrow_in):
        _require_capacity(cs, 32, "U32SubGate")
        c = cs.alloc_variable_without_value()
        bout = cs.alloc_variable_without_value()

        def resolve(vals):
            d = vals[0] - vals[1] - vals[2]
            if d < 0:
                return [d + SHIFT32, 1]
            return [d, 0]

        from ...native import OP_U32_SUB

        cs.set_values_with_dependencies(
            [a, b, borrow_in], [c, bout], resolve,
            native=(OP_U32_SUB, ()),
        )
        cs.place_gate(U32SubGate.instance(), [a, b, borrow_in, c, bout], ())
        return c, bout

    _inst = None

    @classmethod
    def instance(cls):
        if cls._inst is None:
            cls._inst = cls()
        return cls._inst


class U32FmaGate(Gate):
    """a·b + c + carry_in = low + 2^32·high, made sound in Goldilocks by
    splitting the operands into 16-bit halves so no single constraint can
    reach p (the naive one-liner maxes at 2^64-1 > p and admits a second
    witness shifted by p; the reference splits to 8-bit sub-words for the
    same reason, u32_fma.rs:73-130).

    Vars: [a, b, c, cin, a_lo, a_hi, b_lo, b_hi, low, high, k]; terms:
      (1) a − a_lo − 2^16·a_hi
      (2) b − b_lo − 2^16·b_hi
      (3) a_lo·b_lo + c + cin + 2^16·(a_lo·b_hi + a_hi·b_lo) − low − 2^32·k
          (max ≈ 2^50 < p; k is the bounded mid-carry, range-checked ≤ 2^20)
      (4) a_hi·b_hi + k − high              (max ≈ 2^32 + 2^20 < p)
    Halves/k are range-checked by the fma() helper; low/high by the caller.
    """

    name = "u32_fma"
    principal_width = 11
    num_terms = 4
    max_degree = 2

    def evaluate(self, ops, row, dst):
        a, b, c, cin, a_lo, a_hi, b_lo, b_hi, low, high, k = (
            row.v(i) for i in range(11)
        )
        sh16 = ops.constant(1 << 16)
        dst.push(
            ops.sub(a, ops.add(a_lo, ops.mul(sh16, a_hi)))
        )
        dst.push(
            ops.sub(b, ops.add(b_lo, ops.mul(sh16, b_hi)))
        )
        mid = ops.add(ops.mul(a_lo, b_hi), ops.mul(a_hi, b_lo))
        lhs = ops.add(ops.add(ops.mul(a_lo, b_lo), c), cin)
        lhs = ops.add(lhs, ops.mul(sh16, mid))
        rhs = ops.add(low, ops.mul(ops.constant(SHIFT32), k))
        dst.push(ops.sub(lhs, rhs))
        dst.push(ops.sub(ops.add(ops.mul(a_hi, b_hi), k), high))

    @staticmethod
    def fma(cs, a, b, c, carry_in):
        _require_capacity(cs, 32, "U32FmaGate")
        outs = cs.alloc_multiple_variables_without_values(7)
        a_lo, a_hi, b_lo, b_hi, low, high, k = outs

        def resolve(vals):
            av, bv, cv, cinv = vals
            s = av * bv + cv + cinv
            alo, ahi = av & 0xFFFF, av >> 16
            blo, bhi = bv & 0xFFFF, bv >> 16
            part = alo * blo + cv + cinv + ((alo * bhi + ahi * blo) << 16)
            return [
                alo, ahi, blo, bhi,
                s & 0xFFFFFFFF, s >> 32, part >> 32,
            ]

        from ...native import OP_U32_FMA

        cs.set_values_with_dependencies(
            [a, b, c, carry_in], list(outs), resolve,
            native=(OP_U32_FMA, ()),
        )
        cs.place_gate(
            U32FmaGate.instance(),
            [a, b, c, carry_in, a_lo, a_hi, b_lo, b_hi, low, high, k],
            (),
        )
        from ...gadgets.chunk_utils import decompose_and_check

        for half in (a_lo, a_hi, b_lo, b_hi):
            decompose_and_check(cs, half, 16)
        decompose_and_check(cs, k, 20)
        return low, high

    _inst = None

    @classmethod
    def instance(cls):
        if cls._inst is None:
            cls._inst = cls()
        return cls._inst


class U32TriAddCarryAsChunkGate(Gate):
    """a + b + c = low + 2^32·high, high in [0,2) ∪ {2} as a chunk
    (reference u32_tri_add_carry_as_chunk.rs; high range-checked via lookups)."""

    name = "u32_tri_add"
    principal_width = 5
    num_terms = 1
    max_degree = 1

    def evaluate(self, ops, row, dst):
        a, b, c, low, high = (row.v(i) for i in range(5))
        lhs = ops.add(ops.add(a, b), c)
        rhs = ops.add(low, ops.mul(ops.constant(SHIFT32), high))
        dst.push(ops.sub(lhs, rhs))

    @staticmethod
    def add(cs, a, b, c):
        _require_capacity(cs, 32, "U32TriAddCarryAsChunkGate")
        low = cs.alloc_variable_without_value()
        high = cs.alloc_variable_without_value()

        def resolve(vals):
            s = vals[0] + vals[1] + vals[2]
            return [s & 0xFFFFFFFF, s >> 32]

        from ...native import OP_TRIADD

        cs.set_values_with_dependencies(
            [a, b, c], [low, high], resolve, native=(OP_TRIADD, ())
        )
        cs.place_gate(U32TriAddCarryAsChunkGate.instance(), [a, b, c, low, high], ())
        return low, high

    _inst = None

    @classmethod
    def instance(cls):
        if cls._inst is None:
            cls._inst = cls()
        return cls._inst


class ByteTriAddGate(Gate):
    """Three u32 operands as LE byte chunks: Σ_i (a_i+b_i+x_i)·2^{8i} =
    Σ_i out_i·2^{8i} + 2^32·carry (the chunked form the reference gate
    u32_tri_add_carry_as_chunk.rs actually uses — operands never get
    composed; out bytes and the carry chunk are range-checked by the
    caller's follow-up lookups)."""

    name = "byte_tri_add"
    principal_width = 17
    num_terms = 1
    max_degree = 1

    def evaluate(self, ops, row, dst):
        acc = None
        for i in range(4):
            w = ops.constant(1 << (8 * i))
            s = ops.add(ops.add(row.v(i), row.v(4 + i)), row.v(8 + i))
            s = ops.sub(s, row.v(12 + i))
            term = ops.mul(w, s)
            acc = term if acc is None else ops.add(acc, term)
        acc = ops.sub(acc, ops.mul(ops.constant(SHIFT32), row.v(16)))
        dst.push(acc)

    @staticmethod
    def add(cs, a4, b4, x4):
        """(out4, carry): bytes of (a + b + x) mod 2^32 plus the carry chunk."""
        _require_capacity(cs, 32, "ByteTriAddGate")
        outs = cs.alloc_multiple_variables_without_values(4)
        carry = cs.alloc_variable_without_value()
        ins = list(a4) + list(b4) + list(x4)

        def resolve(vals):
            s = sum(v << (8 * i) for i, v in enumerate(vals[0:4]))
            s += sum(v << (8 * i) for i, v in enumerate(vals[4:8]))
            s += sum(v << (8 * i) for i, v in enumerate(vals[8:12]))
            return [(s >> (8 * i)) & 0xFF for i in range(4)] + [s >> 32]

        from ...native import OP_BYTE_TRIADD

        cs.set_values_with_dependencies(
            ins, list(outs) + [carry], resolve,
            native=(OP_BYTE_TRIADD, ()),
        )
        cs.place_gate(
            ByteTriAddGate.instance(), ins + list(outs) + [carry], ()
        )
        return list(outs), carry

    _inst = None

    @classmethod
    def instance(cls):
        if cls._inst is None:
            cls._inst = cls()
        return cls._inst


class UIntXAddGate(Gate):
    """Width-parameterized add: a + b + cin = c + 2^W·cout (reference
    uintx_add.rs, W ∈ {8, 16, 32})."""

    num_constants = 0
    num_terms = 2
    max_degree = 2
    principal_width = 5

    def __init__(self, width_bits: int):
        assert width_bits in (8, 16, 32)
        self.width_bits = width_bits
        self.name = f"uint{width_bits}_add"
        self.shift = 1 << width_bits

    def evaluate(self, ops, row, dst):
        a, b, cin, c, cout = (row.v(i) for i in range(5))
        lhs = ops.add(ops.add(a, b), cin)
        rhs = ops.add(c, ops.mul(ops.constant(self.shift), cout))
        dst.push(ops.sub(lhs, rhs))
        dst.push(ops.sub(ops.mul(cout, cout), cout))

    def add(self, cs, a, b, carry_in):
        _require_capacity(cs, self.width_bits, "UIntXAddGate")
        c = cs.alloc_variable_without_value()
        cout = cs.alloc_variable_without_value()
        mask = self.shift - 1
        bits = self.width_bits

        def resolve(vals):
            s = vals[0] + vals[1] + vals[2]
            return [s & mask, s >> bits]

        from ...native import OP_U32_ADD

        cs.set_values_with_dependencies(
            [a, b, carry_in], [c, cout], resolve,
            native=(OP_U32_ADD, (bits,)),
        )
        cs.place_gate(self, [a, b, carry_in, c, cout], ())
        return c, cout
