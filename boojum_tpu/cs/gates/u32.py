"""Fixed-width integer gates (reference u32_add.rs, u32_sub.rs, u32_fma.rs,
u32_tri_add_carry_as_chunk.rs, uintx_add.rs).

Range correctness of the 32-bit limbs themselves comes from lookup-table range
checks at the gadget layer (as in the reference); these gates enforce the
carry arithmetic relations.
"""

from __future__ import annotations

from ...field import gl
from .base import Gate

SHIFT32 = 1 << 32


class U32AddGate(Gate):
    """a + b + carry_in = c + 2^32·carry_out; carry_out boolean."""

    name = "u32_add"
    principal_width = 5
    num_terms = 2
    max_degree = 2

    def evaluate(self, ops, row, dst):
        a, b, cin, c, cout = (row.v(i) for i in range(5))
        lhs = ops.add(ops.add(a, b), cin)
        rhs = ops.add(c, ops.mul(ops.constant(SHIFT32), cout))
        dst.push(ops.sub(lhs, rhs))
        dst.push(ops.sub(ops.mul(cout, cout), cout))

    @staticmethod
    def add(cs, a, b, carry_in):
        c = cs.alloc_variable_without_value()
        cout = cs.alloc_variable_without_value()

        def resolve(vals):
            s = vals[0] + vals[1] + vals[2]
            return [s & 0xFFFFFFFF, s >> 32]

        cs.set_values_with_dependencies([a, b, carry_in], [c, cout], resolve)
        cs.place_gate(U32AddGate.instance(), [a, b, carry_in, c, cout], ())
        return c, cout

    _inst = None

    @classmethod
    def instance(cls):
        if cls._inst is None:
            cls._inst = cls()
        return cls._inst


class U32SubGate(Gate):
    """a − b − borrow_in = c − 2^32·borrow_out; borrow_out boolean."""

    name = "u32_sub"
    principal_width = 5
    num_terms = 2
    max_degree = 2

    def evaluate(self, ops, row, dst):
        a, b, bin_, c, bout = (row.v(i) for i in range(5))
        lhs = ops.sub(ops.sub(a, b), bin_)
        rhs = ops.sub(c, ops.mul(ops.constant(SHIFT32), bout))
        dst.push(ops.sub(lhs, rhs))
        dst.push(ops.sub(ops.mul(bout, bout), bout))

    @staticmethod
    def sub(cs, a, b, borrow_in):
        c = cs.alloc_variable_without_value()
        bout = cs.alloc_variable_without_value()

        def resolve(vals):
            d = vals[0] - vals[1] - vals[2]
            if d < 0:
                return [d + SHIFT32, 1]
            return [d, 0]

        cs.set_values_with_dependencies([a, b, borrow_in], [c, bout], resolve)
        cs.place_gate(U32SubGate.instance(), [a, b, borrow_in, c, bout], ())
        return c, bout

    _inst = None

    @classmethod
    def instance(cls):
        if cls._inst is None:
            cls._inst = cls()
        return cls._inst


class U32FmaGate(Gate):
    """a·b + c + carry_in = low + 2^32·high (reference u32_fma.rs;
    low/high range-checked at the gadget layer)."""

    name = "u32_fma"
    principal_width = 6
    num_terms = 1
    max_degree = 2

    def evaluate(self, ops, row, dst):
        a, b, c, cin, low, high = (row.v(i) for i in range(6))
        lhs = ops.add(ops.add(ops.mul(a, b), c), cin)
        rhs = ops.add(low, ops.mul(ops.constant(SHIFT32), high))
        dst.push(ops.sub(lhs, rhs))

    @staticmethod
    def fma(cs, a, b, c, carry_in):
        low = cs.alloc_variable_without_value()
        high = cs.alloc_variable_without_value()

        def resolve(vals):
            s = vals[0] * vals[1] + vals[2] + vals[3]
            return [s & 0xFFFFFFFF, s >> 32]

        cs.set_values_with_dependencies([a, b, c, carry_in], [low, high], resolve)
        cs.place_gate(U32FmaGate.instance(), [a, b, c, carry_in, low, high], ())
        return low, high

    _inst = None

    @classmethod
    def instance(cls):
        if cls._inst is None:
            cls._inst = cls()
        return cls._inst


class U32TriAddCarryAsChunkGate(Gate):
    """a + b + c = low + 2^32·high, high in [0,2) ∪ {2} as a chunk
    (reference u32_tri_add_carry_as_chunk.rs; high range-checked via lookups)."""

    name = "u32_tri_add"
    principal_width = 5
    num_terms = 1
    max_degree = 1

    def evaluate(self, ops, row, dst):
        a, b, c, low, high = (row.v(i) for i in range(5))
        lhs = ops.add(ops.add(a, b), c)
        rhs = ops.add(low, ops.mul(ops.constant(SHIFT32), high))
        dst.push(ops.sub(lhs, rhs))

    @staticmethod
    def add(cs, a, b, c):
        low = cs.alloc_variable_without_value()
        high = cs.alloc_variable_without_value()

        def resolve(vals):
            s = vals[0] + vals[1] + vals[2]
            return [s & 0xFFFFFFFF, s >> 32]

        cs.set_values_with_dependencies([a, b, c], [low, high], resolve)
        cs.place_gate(U32TriAddCarryAsChunkGate.instance(), [a, b, c, low, high], ())
        return low, high

    _inst = None

    @classmethod
    def instance(cls):
        if cls._inst is None:
            cls._inst = cls()
        return cls._inst


class UIntXAddGate(Gate):
    """Width-parameterized add: a + b + cin = c + 2^W·cout (reference
    uintx_add.rs, W ∈ {8, 16, 32})."""

    num_constants = 0
    num_terms = 2
    max_degree = 2
    principal_width = 5

    def __init__(self, width_bits: int):
        assert width_bits in (8, 16, 32)
        self.width_bits = width_bits
        self.name = f"uint{width_bits}_add"
        self.shift = 1 << width_bits

    def evaluate(self, ops, row, dst):
        a, b, cin, c, cout = (row.v(i) for i in range(5))
        lhs = ops.add(ops.add(a, b), cin)
        rhs = ops.add(c, ops.mul(ops.constant(self.shift), cout))
        dst.push(ops.sub(lhs, rhs))
        dst.push(ops.sub(ops.mul(cout, cout), cout))

    def add(self, cs, a, b, carry_in):
        c = cs.alloc_variable_without_value()
        cout = cs.alloc_variable_without_value()
        mask = self.shift - 1
        bits = self.width_bits

        def resolve(vals):
            s = vals[0] + vals[1] + vals[2]
            return [s & mask, s >> bits]

        cs.set_values_with_dependencies([a, b, carry_in], [c, cout], resolve)
        cs.place_gate(self, [a, b, carry_in, c, cout], ())
        return c, cout
