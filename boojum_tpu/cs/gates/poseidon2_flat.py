"""Poseidon2 flattened gate: one full width-12 permutation per instance.

Counterpart of `/root/reference/src/cs/gates/poseidon2.rs`
(`Poseidon2RoundFunctionFlattenedEvaluator::evaluate_once`, :180-404): the
permutation is inscribed across one row — 12 input + 12 output variables plus
one auxiliary "degree reset" variable at every point where the running state
expression would exceed the allowed constraint degree (after each full-round
s-box batch and each partial-round s-box input). Each reset contributes the
constraint `state_expr - aux = 0` (degree <= 7) and the traversal continues
from the fresh variable; the final external-MDS output is tied to the output
variables. Total: 12 + 12 + 106 aux = 130 columns — exactly the 130
copy-permutation columns of the Era recursion geometry (`vk.json`).

The SAME traversal (`flat_permutation`) drives the constraint evaluator (over
field-like ops) and the witness resolver (over scalars), so they cannot drift.
"""

from __future__ import annotations

from ...field import gl
from ...hashes import poseidon2_params as params
from .base import Gate

SW = 12
HALF_FULL = 4
NUM_PARTIAL = 22

_RC = [
    [int(c) for c in params.ALL_ROUND_CONSTANTS[12 * r : 12 * r + 12]]
    for r in range(30)
]
_DIAG = [int(d) for d in params.M_I_DIAGONAL]

NUM_AUX = (HALF_FULL - 1) * SW + NUM_PARTIAL + HALF_FULL * SW  # 106
WIDTH = 2 * SW + NUM_AUX  # 130


def _ext_mds(ops, s):
    """circ(2·M4, M4, M4) via the add/double chain (same schedule as
    boojum_tpu.hashes.poseidon2._external_mds)."""

    def block(x0, x1, x2, x3):
        t0 = ops.add(x0, x1)
        t1 = ops.add(x2, x3)
        t2 = ops.add(ops.double(x1), t1)
        t3 = ops.add(ops.double(x3), t0)
        t4 = ops.add(ops.double(ops.double(t1)), t3)
        t5 = ops.add(ops.double(ops.double(t0)), t2)
        return ops.add(t3, t5), t5, ops.add(t2, t4), t4

    blocks = [block(*s[4 * b : 4 * b + 4]) for b in range(3)]
    sums = [
        ops.add(ops.add(blocks[0][i], blocks[1][i]), blocks[2][i])
        for i in range(4)
    ]
    return [ops.add(blocks[b][i], sums[i]) for b in range(3) for i in range(4)]


def _int_mds(ops, s):
    total = s[0]
    for v in s[1:]:
        total = ops.add(total, v)
    return [
        ops.add(ops.mul(v, ops.constant(_DIAG[i])), total)
        for i, v in enumerate(s)
    ]


def _pow7(ops, x):
    x2 = ops.mul(x, x)
    x3 = ops.mul(x2, x)
    return ops.mul(ops.mul(x2, x2), x3)


def flat_permutation(ops, state, reset):
    """Poseidon2 permutation with a `reset(value) -> value` hook at every
    degree-reset point. Evaluator mode: reset pulls the next aux variable and
    emits `value - aux`; witness mode: reset records the value."""
    state = _ext_mds(ops, state)
    for r in range(HALF_FULL):
        if r != 0:
            state = [reset(v) for v in state]
        state = [
            _pow7(ops, ops.add(v, ops.constant(_RC[r][i])))
            for i, v in enumerate(state)
        ]
        state = _ext_mds(ops, state)
    for p in range(NUM_PARTIAL):
        s0 = ops.add(state[0], ops.constant(_RC[HALF_FULL + p][0]))
        state[0] = _pow7(ops, reset(s0))
        state = _int_mds(ops, state)
    for r in range(HALF_FULL):
        state = [reset(v) for v in state]
        rc = _RC[HALF_FULL + NUM_PARTIAL + r]
        state = [
            _pow7(ops, ops.add(v, ops.constant(rc[i])))
            for i, v in enumerate(state)
        ]
        state = _ext_mds(ops, state)
    return state


def _witness_trace(input_values):
    """(outputs, aux_values) of one permutation over scalars."""
    from ..field_like import ScalarOps

    aux = []

    def reset(v):
        aux.append(v)
        return v

    out = flat_permutation(ScalarOps, [v % gl.P for v in input_values], reset)
    return out, aux


class Poseidon2FlattenedGate(Gate):
    name = "poseidon2_flat"
    principal_width = WIDTH
    num_terms = NUM_AUX + SW
    max_degree = 7

    def evaluate(self, ops, row, dst):
        state = [row.v(i) for i in range(SW)]
        output = [row.v(SW + i) for i in range(SW)]
        cursor = [2 * SW]

        def reset(v):
            aux = row.v(cursor[0])
            cursor[0] += 1
            dst.push(ops.sub(v, aux))
            return aux

        state = flat_permutation(ops, state, reset)
        assert cursor[0] == WIDTH
        for s, o in zip(state, output):
            dst.push(ops.sub(o, s))

    def padding_instance(self, cs, constants=()):
        zero = cs.zero_var()
        ins = [zero] * SW
        outs, aux = _witness_trace([0] * SW)
        vals = outs + aux
        places = cs.alloc_multiple_variables_without_values(len(vals))
        cs.set_values_with_dependencies(
            [], list(places), lambda _, vals=vals: list(vals)
        )
        return ins + list(places)

    @staticmethod
    def permutation(cs, input_vars):
        """Allocate and constrain output = poseidon2(input); returns the 12
        output variables (the circuit round function's `compute_round_function`,
        reference poseidon2.rs + gadgets/poseidon2/mod.rs)."""
        assert len(input_vars) == SW
        outs = cs.alloc_multiple_variables_without_values(SW)
        auxs = cs.alloc_multiple_variables_without_values(NUM_AUX)

        def resolve(vals):
            out, aux = _witness_trace(list(vals))
            return out + aux

        from ...native import OP_POSEIDON2

        cs.set_values_with_dependencies(
            list(input_vars), list(outs) + list(auxs), resolve,
            native=(OP_POSEIDON2, ()),
        )
        cs.place_gate(
            Poseidon2FlattenedGate.instance(),
            list(input_vars) + list(outs) + list(auxs),
            (),
        )
        return list(outs)

    _inst = None

    @classmethod
    def instance(cls):
        if cls._inst is None:
            cls._inst = cls()
        return cls._inst
