"""Gate framework: evaluator contract + row views.

Counterpart of the reference `Gate`/`GateConstraintEvaluator` traits
(`/root/reference/src/cs/traits/gate.rs:72`, `traits/evaluator.rs:105`) and
the trace-source/destination views (`traits/trace_source.rs`,
`traits/destination_view.rs`). A gate subclass declares its geometry
(columns per instance, constants per row, quotient terms, max degree) and one
`evaluate(ops, row, dst)` over the field-like ops contract; that single
definition drives:

- the prover's quotient sweep (ArrayOps over the whole LDE domain, every
  instance chunk, masked by the gate's selector path),
- the satisfiability checker (ScalarOps per row),
- the plain verifier's reconstruction at z (ExtScalarOps over values-at-z),
- later, the recursive verifier (gadget ops).
"""

from __future__ import annotations


class RowView:
    """Access to one gate instance's cells, generic over backing storage.

    v(i): i-th copy-permutation column of the instance;
    w(i): i-th witness column of the instance;
    c(i): i-th gate constant of the row.
    """

    def __init__(self, var_get, wit_get, const_get):
        self.v = var_get
        self.w = wit_get
        self.c = const_get


class TermsCollector:
    def __init__(self):
        self.terms = []

    def push(self, value):
        self.terms.append(value)


class Gate:
    """Base gate. Subclasses set class attrs and implement evaluate()."""

    name: str = "?"
    principal_width: int = 0  # copy columns per instance
    witness_width: int = 0  # witness columns per instance
    num_constants: int = 0  # constant columns consumed per row
    num_terms: int = 0  # quotient terms per instance
    max_degree: int = 0  # max constraint degree over the trace polys

    def evaluate(self, ops, row: RowView, dst: TermsCollector):
        raise NotImplementedError

    def num_repetitions(self, geometry) -> int:
        """Instances packed into one general-purpose row."""
        if self.principal_width == 0:
            return 1
        per_copy = geometry.num_columns_under_copy_permutation // self.principal_width
        if self.witness_width:
            per_wit = geometry.num_witness_columns // self.witness_width
            per_copy = min(per_copy, per_wit)
        return per_copy

    def padding_instance(self, cs, constants=()) -> list:
        """Variable places filling one vacant instance so its terms vanish.

        Default: zeros everywhere (valid whenever the constraint has no
        affine offset). Gates that need a different filler override this.
        """
        zero = cs.zero_var()
        return [zero] * self.principal_width

    def __repr__(self):
        return f"<gate {self.name}>"
