"""Legacy Poseidon flattened gate: one full width-12 permutation per row.

Counterpart of `/root/reference/src/cs/gates/poseidon.rs:1249`
(`PoseidonFlattenedGate` — the whole LEGACY Poseidon permutation inscribed
across one row, used by legacy-recursion-mode circuits). Same degree-reset
construction as the Poseidon2 gate (`poseidon2_flat.py`): an auxiliary
variable is placed wherever the running state expression would exceed
degree 7, contributing `state_expr - aux = 0`, and the traversal resumes
from the fresh variable.

Legacy schedule (hashes/poseidon.py, Plonky2-compatible): NO initial
external MDS; 4 full rounds (RC + x^7 on all lanes + circulant MDS), 22
partial rounds (RC on all lanes, x^7 on lane 0, MDS), 4 full rounds.
Resets: all 12 lanes before full rounds 1..3 (36), lane 0's s-box input in
every partial round (22), all 12 lanes before each tail full round (48) —
106 aux, so the gate spans 12 + 12 + 106 = 130 copy columns, the same
occupancy as the Poseidon2 gate (and the Era recursion geometry).

The SAME traversal drives the constraint evaluator and the witness
resolver, so they cannot drift.
"""

from __future__ import annotations

from ...field import gl
from ...hashes import poseidon2_params as params
from ...hashes.poseidon import MDS_MATRIX_EXPS
from .base import Gate
from .poseidon2_flat import _pow7

SW = 12
HALF_FULL = 4
NUM_PARTIAL = 22

_RC = [
    [int(c) for c in params.ALL_ROUND_CONSTANTS[12 * r : 12 * r + 12]]
    for r in range(30)
]

NUM_AUX = (HALF_FULL - 1) * SW + NUM_PARTIAL + HALF_FULL * SW  # 106
WIDTH = 2 * SW + NUM_AUX  # 130


def _circulant_mds(ops, s):
    """M·s with the power-of-two circulant (suggested_mds.rs:11): constant
    multiplications keep the constraint degree unchanged."""
    out = []
    for r in range(SW):
        acc = None
        for c in range(SW):
            term = ops.mul(
                s[c], ops.constant(1 << MDS_MATRIX_EXPS[(c - r) % SW])
            )
            acc = term if acc is None else ops.add(acc, term)
        out.append(acc)
    return out


def legacy_flat_permutation(ops, state, reset):
    """Legacy Poseidon permutation with a `reset(value) -> value` hook at
    every degree-reset point (see module docstring for the schedule)."""
    for r in range(HALF_FULL):
        if r != 0:
            state = [reset(v) for v in state]
        state = [
            _pow7(ops, ops.add(v, ops.constant(_RC[r][i])))
            for i, v in enumerate(state)
        ]
        state = _circulant_mds(ops, state)
    for p in range(NUM_PARTIAL):
        rc = _RC[HALF_FULL + p]
        state = [
            ops.add(v, ops.constant(rc[i])) for i, v in enumerate(state)
        ]
        state[0] = _pow7(ops, reset(state[0]))
        state = _circulant_mds(ops, state)
    for r in range(HALF_FULL):
        state = [reset(v) for v in state]
        rc = _RC[HALF_FULL + NUM_PARTIAL + r]
        state = [
            _pow7(ops, ops.add(v, ops.constant(rc[i])))
            for i, v in enumerate(state)
        ]
        state = _circulant_mds(ops, state)
    return state


def _witness_trace(input_values):
    """(outputs, aux_values) of one legacy permutation over scalars."""
    from ..field_like import ScalarOps

    aux = []

    def reset(v):
        aux.append(v)
        return v

    out = legacy_flat_permutation(
        ScalarOps, [v % gl.P for v in input_values], reset
    )
    return out, aux


class PoseidonFlattenedGate(Gate):
    name = "poseidon_flat"
    principal_width = WIDTH
    num_terms = NUM_AUX + SW
    max_degree = 7

    def evaluate(self, ops, row, dst):
        state = [row.v(i) for i in range(SW)]
        output = [row.v(SW + i) for i in range(SW)]
        cursor = [2 * SW]

        def reset(v):
            aux = row.v(cursor[0])
            cursor[0] += 1
            dst.push(ops.sub(v, aux))
            return aux

        state = legacy_flat_permutation(ops, state, reset)
        assert cursor[0] == WIDTH
        for s, o in zip(state, output):
            dst.push(ops.sub(o, s))

    def padding_instance(self, cs, constants=()):
        zero = cs.zero_var()
        ins = [zero] * SW
        outs, aux = _witness_trace([0] * SW)
        vals = outs + aux
        places = cs.alloc_multiple_variables_without_values(len(vals))
        cs.set_values_with_dependencies(
            [], list(places), lambda _, vals=vals: list(vals)
        )
        return ins + list(places)

    @staticmethod
    def permutation(cs, input_vars):
        """Allocate and constrain output = legacy_poseidon(input); returns
        the 12 output variables (the legacy round function's circuit form,
        reference poseidon.rs:1249 + gadgets/poseidon/mod.rs)."""
        assert len(input_vars) == SW
        outs = cs.alloc_multiple_variables_without_values(SW)
        auxs = cs.alloc_multiple_variables_without_values(NUM_AUX)

        def resolve(vals):
            out, aux = _witness_trace(list(vals))
            return out + aux

        cs.set_values_with_dependencies(
            list(input_vars), list(outs) + list(auxs), resolve
        )
        cs.place_gate(
            PoseidonFlattenedGate.instance(),
            list(input_vars) + list(outs) + list(auxs),
            (),
        )
        return list(outs)

    _inst = None

    @classmethod
    def instance(cls):
        if cls._inst is None:
            cls._inst = cls()
        return cls._inst
