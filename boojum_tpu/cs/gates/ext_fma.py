"""FMA gate over the quadratic extension.

Counterpart of `/root/reference/src/cs/gates/fma_gate_in_extension_without_constant.rs`
(`compute_fma_in_extension` :368, inversion constraint :427): the relation
`c0·a·b + c1·c = d` over GF(p²) = GF(p)[w]/(w²−7), with a,b,c,d carried as
(c0, c1) base-variable pairs and the coefficients as four per-row constants.
Two quotient terms (the result's two coordinates), degree 3.
"""

from __future__ import annotations

from ...field import gl
from ...field import extension as ext_host
from .base import Gate

NON_RESIDUE = 7


def _ext_mul_ops(ops, a, b):
    """(a0 + a1·w)(b0 + b1·w) over base field-like ops."""
    c0 = ops.add(
        ops.mul(a[0], b[0]),
        ops.mul(ops.constant(NON_RESIDUE), ops.mul(a[1], b[1])),
    )
    c1 = ops.add(ops.mul(a[0], b[1]), ops.mul(a[1], b[0]))
    return (c0, c1)


class ExtFmaGate(Gate):
    name = "ext_fma"
    principal_width = 8  # a0 a1 b0 b1 c0 c1 d0 d1
    num_constants = 4  # coeff_ab (2), coeff_c (2)
    num_terms = 2
    max_degree = 3

    def evaluate(self, ops, row, dst):
        a = (row.v(0), row.v(1))
        b = (row.v(2), row.v(3))
        c = (row.v(4), row.v(5))
        d = (row.v(6), row.v(7))
        k0 = (row.c(0), row.c(1))
        k1 = (row.c(2), row.c(3))
        t = _ext_mul_ops(ops, _ext_mul_ops(ops, k0, a), b)
        u = _ext_mul_ops(ops, k1, c)
        dst.push(ops.sub(ops.add(t[0], u[0]), d[0]))
        dst.push(ops.sub(ops.add(t[1], u[1]), d[1]))

    @staticmethod
    def fma(cs, a, b, c, coeff_ab=(1, 0), coeff_c=(1, 0)):
        """Allocate and constrain d = coeff_ab·a·b + coeff_c·c; all operands
        are (var, var) extension pairs, coefficients host (int, int) pairs."""
        k0 = (coeff_ab[0] % gl.P, coeff_ab[1] % gl.P)
        k1 = (coeff_c[0] % gl.P, coeff_c[1] % gl.P)
        d0 = cs.alloc_variable_without_value()
        d1 = cs.alloc_variable_without_value()

        def resolve(vals):
            av, bv, cv = (vals[0], vals[1]), (vals[2], vals[3]), (vals[4], vals[5])
            t = ext_host.mul_s(ext_host.mul_s(k0, av), bv)
            u = ext_host.mul_s(k1, cv)
            r = ext_host.add_s(t, u)
            return [r[0], r[1]]

        cs.set_values_with_dependencies(
            [a[0], a[1], b[0], b[1], c[0], c[1]], [d0, d1], resolve
        )
        cs.place_gate(
            ExtFmaGate.instance(),
            [a[0], a[1], b[0], b[1], c[0], c[1], d0, d1],
            k0 + k1,
        )
        return (d0, d1)

    @staticmethod
    def inversion(cs, a):
        """Witness ext inverse with a·a_inv = 1 enforced through this gate
        (reference create_inversion_constraint)."""
        iv0 = cs.alloc_variable_without_value()
        iv1 = cs.alloc_variable_without_value()

        def resolve(vals):
            r = ext_host.inv_s((vals[0], vals[1]))
            return [r[0], r[1]]

        cs.set_values_with_dependencies([a[0], a[1]], [iv0, iv1], resolve)
        one = cs.one_var()
        zero = cs.zero_var()
        # place: coeff_ab·a·inv + 0·c = (1, 0), with d pinned to constants
        cs.place_gate(
            ExtFmaGate.instance(),
            [a[0], a[1], iv0, iv1, zero, zero, one, zero],
            (1, 0, 0, 0),
        )
        return (iv0, iv1)

    _inst = None

    @classmethod
    def instance(cls):
        if cls._inst is None:
            cls._inst = cls()
        return cls._inst
