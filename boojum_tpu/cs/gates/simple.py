"""Core gate library (arithmetic / selection / structural gates).

Each gate mirrors the constraint of its reference counterpart in
`/root/reference/src/cs/gates/` (file noted per class) but is re-expressed as
one vectorizable evaluator over the field-like ops contract. `add_to_cs`-style
helpers live on the classes as static constructors that allocate outputs,
register witness closures with the dataflow resolver, and place the instance.
"""

from __future__ import annotations

from ...field.active import field_p as _p, scalar_field as _fl
from .base import Gate, RowView, TermsCollector


class FmaGate(Gate):
    """c0·a·b + c1·c = d (reference fma_gate_without_constant.rs:138)."""

    name = "fma"
    principal_width = 4
    num_constants = 2
    num_terms = 1
    max_degree = 3

    def evaluate(self, ops, row, dst):
        a, b, c, d = row.v(0), row.v(1), row.v(2), row.v(3)
        c0, c1 = row.c(0), row.c(1)
        t = ops.mul(c0, ops.mul(a, b))
        t = ops.add(t, ops.mul(c1, c))
        dst.push(ops.sub(t, d))

    @staticmethod
    def fma(cs, a, b, c, coeff_ab=1, coeff_c=1):
        """Allocate and constrain d = coeff_ab·a·b + coeff_c·c."""
        d = cs.alloc_variable_without_value()
        ca, cc = coeff_ab % _p(), coeff_c % _p()

        def resolve(vals):
            f = _fl()
            av, bv, cv = vals
            return [f.add(f.mul(ca, f.mul(av, bv)), f.mul(cc, cv))]

        from ...native import OP_FMA

        cs.set_values_with_dependencies(
            [a, b, c], [d], resolve, native=(OP_FMA, (ca, cc))
        )
        cs.place_gate(FmaGate.instance(), [a, b, c, d], (ca, cc))
        return d

    @staticmethod
    def enforce_fma(cs, a, b, c, d, coeff_ab=1, coeff_c=1):
        """Constrain coeff_ab·a·b + coeff_c·c = d over EXISTING variables
        (the reference's gate-with-rhs_part form, fma_gate_without_constant.rs)."""
        ca, cc = coeff_ab % _p(), coeff_c % _p()
        cs.place_gate(FmaGate.instance(), [a, b, c, d], (ca, cc))

    _inst = None

    @classmethod
    def instance(cls):
        if cls._inst is None:
            cls._inst = cls()
        return cls._inst


class ConstantsAllocatorGate(Gate):
    """v = const (reference constant_allocator.rs); one constant per row,
    amortized across all copy columns by the placement tooling."""

    name = "constant"
    principal_width = 1
    num_constants = 1
    num_terms = 1
    max_degree = 1

    def evaluate(self, ops, row, dst):
        dst.push(ops.sub(row.v(0), row.c(0)))

    def padding_instance(self, cs, constants=()):
        from ...native import OP_CONST

        c = constants[0] if constants else 0
        v = cs.alloc_variable_without_value()
        cs.set_values_with_dependencies(
            [], [v], lambda _: [c], native=(OP_CONST, (c,))
        )
        return [v]

    @staticmethod
    def allocate_constant(cs, value: int):
        from ...native import OP_CONST

        value = value % _p()
        v = cs.alloc_variable_without_value()
        cs.set_values_with_dependencies(
            [], [v], lambda _, value=value: [value],
            native=(OP_CONST, (value,)),
        )
        cs.place_gate(ConstantsAllocatorGate.instance(), [v], (value,))
        return v

    _inst = None

    @classmethod
    def instance(cls):
        if cls._inst is None:
            cls._inst = cls()
        return cls._inst


class BooleanConstraintGate(Gate):
    """x^2 = x (reference boolean_allocator.rs)."""

    name = "boolean"
    principal_width = 1
    num_terms = 1
    max_degree = 2

    def evaluate(self, ops, row, dst):
        x = row.v(0)
        dst.push(ops.sub(ops.mul(x, x), x))

    @staticmethod
    def enforce(cs, v):
        cs.place_gate(BooleanConstraintGate.instance(), [v], ())
        return v

    @staticmethod
    def allocate(cs, witness_fn=None, ins=()):
        v = cs.alloc_variable_without_value()
        if witness_fn is not None:
            cs.set_values_with_dependencies(list(ins), [v], witness_fn)
        BooleanConstraintGate.enforce(cs, v)
        return v

    _inst = None

    @classmethod
    def instance(cls):
        if cls._inst is None:
            cls._inst = cls()
        return cls._inst


class NopGate(Gate):
    """Row filler (reference nop_gate.rs); padding rows carry this gate."""

    name = "nop"
    principal_width = 0
    num_terms = 0
    max_degree = 0

    def evaluate(self, ops, row, dst):
        pass

    _inst = None

    @classmethod
    def instance(cls):
        if cls._inst is None:
            cls._inst = cls()
        return cls._inst


class PublicInputGate(Gate):
    """Exposes a variable as a public input (reference public_input.rs).

    No quotient term: the opening is enforced in the DEEP phase as an extra
    (w_col(x) − value)/(x − ω^row) term, as the reference prover does
    (prover.rs:1805 public_input_opening_tuples).
    """

    name = "public_input"
    principal_width = 1
    num_terms = 0
    max_degree = 0

    def evaluate(self, ops, row, dst):
        pass

    @staticmethod
    def place(cs, v):
        col, row = cs.place_gate(PublicInputGate.instance(), [v], ())
        cs.set_public(col, row)
        return v

    _inst = None

    @classmethod
    def instance(cls):
        if cls._inst is None:
            cls._inst = cls()
        return cls._inst


class ReductionGate(Gate):
    """sum_i coeff_i·x_i = out, N=4 terms (reference reduction_gate.rs)."""

    name = "reduction4"
    principal_width = 5
    num_constants = 4
    num_terms = 1
    max_degree = 1

    def evaluate(self, ops, row, dst):
        acc = ops.zero()
        for i in range(4):
            acc = ops.add(acc, ops.mul(row.v(i), row.c(i)))
        dst.push(ops.sub(acc, row.v(4)))

    @staticmethod
    def reduce(cs, vars4, coeffs4):
        assert len(vars4) == 4 and len(coeffs4) == 4
        out = cs.alloc_variable_without_value()
        cf = [c % _p() for c in coeffs4]

        def resolve(vals):
            f = _fl()
            acc = 0
            for v, c in zip(vals, cf):
                acc = f.add(acc, f.mul(v, c))
            return [acc]

        from ...native import OP_REDUCTION

        cs.set_values_with_dependencies(
            list(vars4), [out], resolve, native=(OP_REDUCTION, tuple(cf))
        )
        cs.place_gate(ReductionGate.instance(), list(vars4) + [out], tuple(cf))
        return out

    @staticmethod
    def enforce_reduce(cs, vars4, coeffs4, out):
        """Constrain sum coeff_i·x_i = out over EXISTING variables."""
        cf = [c % _p() for c in coeffs4]
        cs.place_gate(ReductionGate.instance(), list(vars4) + [out], tuple(cf))

    _inst = None

    @classmethod
    def instance(cls):
        if cls._inst is None:
            cls._inst = cls()
        return cls._inst


class ReductionByPowersGate(Gate):
    """sum_i c^i·x_i = out (reference reduction_by_powers_gate.rs)."""

    name = "reduction_by_powers4"
    principal_width = 5
    num_constants = 1
    num_terms = 1
    max_degree = 1

    def evaluate(self, ops, row, dst):
        c = row.c(0)
        acc = row.v(0)
        cp = c
        for i in range(1, 4):
            acc = ops.add(acc, ops.mul(row.v(i), cp))
            cp = ops.mul(cp, c)
        dst.push(ops.sub(acc, row.v(4)))

    @staticmethod
    def reduce(cs, vars4, base):
        out = cs.alloc_variable_without_value()
        b = base % _p()

        def resolve(vals):
            f = _fl()
            acc, cp = 0, 1
            for v in vals:
                acc = f.add(acc, f.mul(v, cp))
                cp = f.mul(cp, b)
            return [acc]

        cs.set_values_with_dependencies(list(vars4), [out], resolve)
        cs.place_gate(ReductionByPowersGate.instance(), list(vars4) + [out], (b,))
        return out

    _inst = None

    @classmethod
    def instance(cls):
        if cls._inst is None:
            cls._inst = cls()
        return cls._inst


class SelectionGate(Gate):
    """out = sel ? a : b  ==  sel·(a−b) + b − out (reference selection_gate.rs)."""

    name = "selection"
    principal_width = 4
    num_terms = 1
    max_degree = 2

    def evaluate(self, ops, row, dst):
        a, b, sel, out = row.v(0), row.v(1), row.v(2), row.v(3)
        t = ops.mul(sel, ops.sub(a, b))
        dst.push(ops.sub(ops.add(t, b), out))

    @staticmethod
    def select(cs, sel, a, b):
        out = cs.alloc_variable_without_value()

        def resolve(vals):
            av, bv, sv = vals
            return [av if sv == 1 else bv]

        cs.set_values_with_dependencies([a, b, sel], [out], resolve)
        cs.place_gate(SelectionGate.instance(), [a, b, sel, out], ())
        return out

    _inst = None

    @classmethod
    def instance(cls):
        if cls._inst is None:
            cls._inst = cls()
        return cls._inst


class ParallelSelectionGate(Gate):
    """Shared-selector 4-wide select (reference parallel_selection.rs)."""

    name = "parallel_selection4"
    principal_width = 13  # sel + 4*(a,b,out)
    num_terms = 4
    max_degree = 2

    def evaluate(self, ops, row, dst):
        sel = row.v(0)
        for i in range(4):
            a, b, out = row.v(1 + 3 * i), row.v(2 + 3 * i), row.v(3 + 3 * i)
            t = ops.mul(sel, ops.sub(a, b))
            dst.push(ops.sub(ops.add(t, b), out))

    @staticmethod
    def select(cs, sel, a_list, b_list):
        assert len(a_list) == 4 and len(b_list) == 4
        outs = [cs.alloc_variable_without_value() for _ in range(4)]

        def resolve(vals):
            sv = vals[0]
            avs, bvs = vals[1:5], vals[5:9]
            return [a if sv == 1 else b for a, b in zip(avs, bvs)]

        cs.set_values_with_dependencies(
            [sel] + list(a_list) + list(b_list), outs, resolve
        )
        flat = [sel]
        for a, b, o in zip(a_list, b_list, outs):
            flat += [a, b, o]
        cs.place_gate(ParallelSelectionGate.instance(), flat, ())
        return outs

    _inst = None

    @classmethod
    def instance(cls):
        if cls._inst is None:
            cls._inst = cls()
        return cls._inst


class ConditionalSwapGate(Gate):
    """(x, y) = sel ? (b, a) : (a, b) (reference conditional_swap.rs)."""

    name = "conditional_swap"
    principal_width = 5
    num_terms = 2
    max_degree = 2

    def evaluate(self, ops, row, dst):
        sel, a, b, x, y = (row.v(i) for i in range(5))
        d = ops.mul(sel, ops.sub(b, a))
        dst.push(ops.sub(ops.add(a, d), x))  # x = a + sel(b-a)
        dst.push(ops.add(ops.sub(b, d), ops.neg(y)))  # y = b - sel(b-a)

    @staticmethod
    def swap(cs, sel, a, b):
        x = cs.alloc_variable_without_value()
        y = cs.alloc_variable_without_value()

        def resolve(vals):
            sv, av, bv = vals
            return ([bv, av] if sv == 1 else [av, bv])

        cs.set_values_with_dependencies([sel, a, b], [x, y], resolve)
        cs.place_gate(ConditionalSwapGate.instance(), [sel, a, b, x, y], ())
        return x, y

    _inst = None

    @classmethod
    def instance(cls):
        if cls._inst is None:
            cls._inst = cls()
        return cls._inst


class DotProductGate(Gate):
    """sum of 4 products = out (reference dot_product_gate.rs)."""

    name = "dot_product4"
    principal_width = 9
    num_terms = 1
    max_degree = 2

    def evaluate(self, ops, row, dst):
        acc = ops.zero()
        for i in range(4):
            acc = ops.add(acc, ops.mul(row.v(2 * i), row.v(2 * i + 1)))
        dst.push(ops.sub(acc, row.v(8)))

    @staticmethod
    def dot(cs, pairs):
        assert len(pairs) == 4
        out = cs.alloc_variable_without_value()
        flat = [v for p in pairs for v in p]

        def resolve(vals):
            f = _fl()
            acc = 0
            for i in range(4):
                acc = f.add(acc, f.mul(vals[2 * i], vals[2 * i + 1]))
            return [acc]

        cs.set_values_with_dependencies(flat, [out], resolve)
        cs.place_gate(DotProductGate.instance(), flat + [out], ())
        return out

    _inst = None

    @classmethod
    def instance(cls):
        if cls._inst is None:
            cls._inst = cls()
        return cls._inst


class QuadraticCombinationGate(Gate):
    """sum of 4 products = 0 (reference quadratic_combination.rs)."""

    name = "quadratic_combination4"
    principal_width = 8
    num_terms = 1
    max_degree = 2

    def evaluate(self, ops, row, dst):
        acc = ops.zero()
        for i in range(4):
            acc = ops.add(acc, ops.mul(row.v(2 * i), row.v(2 * i + 1)))
        dst.push(acc)

    @staticmethod
    def enforce(cs, pairs):
        assert len(pairs) == 4
        flat = [v for p in pairs for v in p]
        cs.place_gate(QuadraticCombinationGate.instance(), flat, ())

    _inst = None

    @classmethod
    def instance(cls):
        if cls._inst is None:
            cls._inst = cls()
        return cls._inst


class ZeroCheckGate(Gate):
    """out = (x == 0), with witness inverse aux (reference zero_check.rs).

    Constraints: x·out = 0 and 1 − out − x·aux = 0 (aux = x^{-1} when x≠0).
    """

    name = "zero_check"
    principal_width = 3
    num_terms = 2
    max_degree = 2

    def evaluate(self, ops, row, dst):
        x, out, aux = row.v(0), row.v(1), row.v(2)
        dst.push(ops.mul(x, out))
        one = ops.one()
        dst.push(ops.sub(ops.sub(one, out), ops.mul(x, aux)))

    def padding_instance(self, cs, constants=()):
        return [cs.zero_var(), cs.one_var(), cs.zero_var()]

    @staticmethod
    def is_zero(cs, x):
        out = cs.alloc_variable_without_value()
        aux = cs.alloc_variable_without_value()

        def resolve(vals):
            (xv,) = vals
            if xv == 0:
                return [1, 0]
            return [0, _fl().inv(xv)]

        cs.set_values_with_dependencies([x], [out, aux], resolve)
        cs.place_gate(ZeroCheckGate.instance(), [x, out, aux], ())
        return out

    _inst = None

    @classmethod
    def instance(cls):
        if cls._inst is None:
            cls._inst = cls()
        return cls._inst


class ZeroCheckWitnessGate(Gate):
    """out = (x == 0) with the inverse aux in a WITNESS column (reference
    zero_check.rs `use_witness_column_for_inversion = true`, :591): same two
    constraints as ZeroCheckGate but the aux value lives outside the
    copy-permutation — it is never wired to anything, so a witness column
    (no sigma poly, no copy chain) carries it for free.
    """

    name = "zero_check_wit"
    principal_width = 2
    witness_width = 1
    num_terms = 2
    max_degree = 2

    def evaluate(self, ops, row, dst):
        x, out, aux = row.v(0), row.v(1), row.w(0)
        dst.push(ops.mul(x, out))
        one = ops.one()
        dst.push(ops.sub(ops.sub(one, out), ops.mul(x, aux)))

    def padding_instance(self, cs, constants=()):
        # x=0, out=1; the padded witness cell scatters to 0: 0*1 = 0 and
        # 1 - 1 - 0*0 = 0
        return [cs.zero_var(), cs.one_var()]

    @staticmethod
    def is_zero(cs, x):
        out = cs.alloc_variable_without_value()
        aux = cs.alloc_witness_without_value()

        def resolve(vals):
            (xv,) = vals
            if xv == 0:
                return [1, 0]
            return [0, _fl().inv(xv)]

        cs.set_values_with_dependencies([x], [out, aux], resolve)
        cs.place_gate(
            ZeroCheckWitnessGate.instance(), [x, out], (), wit_places=[aux]
        )
        return out

    _inst = None

    @classmethod
    def instance(cls):
        if cls._inst is None:
            cls._inst = cls()
        return cls._inst


class BoundedGateWrapper(Gate):
    """Row-capping newtype around an inner gate (reference
    BoundedGateWrapper, bounded_wrapper.rs:145, and the Bounded* allocator
    variants): placement through the wrapper counts the rows the inner gate
    occupies and refuses to exceed the cap — the circuit-builder contract
    for budgeted regions. Constraint semantics are the inner gate's own.
    """

    def __init__(self, inner: Gate, max_rows: int):
        self.inner = inner
        self.max_rows = max_rows
        # distinct gate identity: the wrapper gets its OWN rows/tooling and
        # selector-tree slot, so unbounded placements of the same inner gate
        # never share (or silently consume) budgeted rows
        self.name = f"bounded_{inner.name}"
        self.principal_width = inner.principal_width
        self.witness_width = inner.witness_width
        self.num_constants = inner.num_constants
        self.num_terms = inner.num_terms
        self.max_degree = inner.max_degree
        self._rows_used: set = set()

    def evaluate(self, ops, row, dst):
        return self.inner.evaluate(ops, row, dst)

    def padding_instance(self, cs, constants=()):
        return self.inner.padding_instance(cs, constants)

    def place(self, cs, var_places, constants=(), wit_places=()):
        """Place one instance, enforcing the row budget BEFORE mutating
        the constraint system."""
        tool = cs._tooling.get((self.name, tuple(constants)))
        opens_new_row = (
            tool is None or tool[1] >= self.num_repetitions(cs.geometry)
        )
        if opens_new_row and len(self._rows_used) >= self.max_rows:
            raise RuntimeError(
                f"bounded gate {self.inner.name}: row budget "
                f"{self.max_rows} exceeded"
            )
        off, row = cs.place_gate(self, var_places, constants, wit_places)
        self._rows_used.add(row)
        return off, row


class LookupMarkerGate(Gate):
    """Formal marker for general-purpose-columns lookups (reference
    LookupFormalGate, lookup_marker.rs:39): rows holding this gate carry
    lookup tuples in the general copy columns, the table id in the row's
    first gate-constant column, and the gate's SELECTOR gates the lookup
    argument's A relations. No quotient terms of its own.

    principal_width is configured at registration time from the lookup
    parameters (width columns per tuple, table id as constant)."""

    name = "lookup_marker"
    num_constants = 1  # the table id
    num_terms = 0
    max_degree = 0
    is_lookup_marker = True

    def __init__(self, width: int):
        self.principal_width = width

    def evaluate(self, ops, row, dst):
        return  # marker: the lookup argument supplies the relations

    def padding_instance(self, cs, constants=()):
        """Fill a vacant instance with the table's row 0 (and bump its
        multiplicity so the log-derivative sum stays balanced)."""
        tid = int(constants[0])
        table = cs.get_table(tid)
        row0 = [int(v) for v in table.content[0]] + [0] * (
            self.principal_width - table.width
        )
        pads = []
        for v in row0:
            p = cs.alloc_variable_without_value()
            cs.resolver.set_value(p, v)
            pads.append(p)
        if cs.config.evaluate_witness:
            key = (tid, 0)
            cs.lookup_multiplicities[key] = (
                cs.lookup_multiplicities.get(key, 0) + 1
            )
        return pads

    _by_width: dict = {}

    @classmethod
    def instance(cls, width: int = 0):
        g = cls._by_width.get(width)
        if g is None:
            g = cls(width)
            cls._by_width[width] = g
        return g


class SimpleNonlinearityGate(Gate):
    """y = x^7 + c (reference simple_non_linearity_with_constant.rs)."""

    name = "nonlinearity7"
    principal_width = 2
    num_constants = 1
    num_terms = 1
    max_degree = 7

    def evaluate(self, ops, row, dst):
        x, y = row.v(0), row.v(1)
        x2 = ops.mul(x, x)
        x3 = ops.mul(x2, x)
        x4 = ops.mul(x2, x2)
        x7 = ops.mul(x4, x3)
        dst.push(ops.sub(ops.add(x7, row.c(0)), y))

    def padding_instance(self, cs, constants=()):
        c = constants[0] if constants else 0
        y = cs.alloc_variable_without_value()
        cs.set_values_with_dependencies([], [y], lambda _, c=c: [c])
        return [cs.zero_var(), y]

    @staticmethod
    def apply(cs, x, c: int):
        y = cs.alloc_variable_without_value()
        c = c % _p()

        def resolve(vals):
            f = _fl()
            return [f.add(f.pow_(vals[0], 7), c)]

        cs.set_values_with_dependencies([x], [y], resolve)
        cs.place_gate(SimpleNonlinearityGate.instance(), [x, y], (c,))
        return y

    _inst = None

    @classmethod
    def instance(cls):
        if cls._inst is None:
            cls._inst = cls()
        return cls._inst


class MatrixMultiplicationGate(Gate):
    """out = M·in for a compile-time N×N matrix (reference
    matrix_multiplication_gate.rs; used for Poseidon2 MDS layers).

    The matrix is a gate *parameter* (not placed in constant columns); gates
    with different matrices are distinct gate types.
    """

    num_constants = 0
    max_degree = 1

    def __init__(self, name: str, matrix):
        self.name = f"matmul_{name}"
        self.matrix = [[int(v) % _p() for v in r] for r in matrix]
        n = len(self.matrix)
        self.n = n
        self.principal_width = 2 * n
        self.num_terms = n

    def evaluate(self, ops, row, dst):
        n = self.n
        for i in range(n):
            acc = ops.zero()
            for j in range(n):
                m = self.matrix[i][j]
                if m == 0:
                    continue
                acc = ops.add(acc, ops.mul(ops.constant(m), row.v(j)))
            dst.push(ops.sub(acc, row.v(n + i)))

    def apply(self, cs, ins):
        assert len(ins) == self.n
        outs = [cs.alloc_variable_without_value() for _ in range(self.n)]
        mat = self.matrix

        def resolve(vals):
            f = _fl()
            return [
                sum(f.mul(mat[i][j], vals[j]) for j in range(self.n)) % f.P
                for i in range(self.n)
            ]

        cs.set_values_with_dependencies(list(ins), outs, resolve)
        cs.place_gate(self, list(ins) + outs, ())
        return outs


class ExplicitConstantsAllocatorGate(Gate):
    """Constants allocated purely as baked-literal constraints — no constant
    COLUMNS consumed (reference
    constants_allocator_as_explicit_constraint.rs: always adds 0, 1 and -1,
    plus an arbitrary set; per-set instances carry a unique name the way the
    reference carries unique_identifier)."""

    witness_width = 0
    num_constants = 0
    max_degree = 1

    def __init__(self, constants_set=()):
        consts = [0, 1, _p() - 1] + [int(c) % _p() for c in constants_set]
        self.constants = consts
        self.principal_width = len(consts)
        self.num_terms = len(consts)
        self.name = (
            "explicit_constants["
            + ",".join(str(c) for c in consts[3:])
            + "]"
        )

    def evaluate(self, ops, row, dst):
        for i, c in enumerate(self.constants):
            dst.push(ops.sub(row.v(i), ops.constant(c)))

    def padding_instance(self, cs, constants=()):
        vals = list(self.constants)
        places = cs.alloc_multiple_variables_without_values(len(vals))
        cs.set_values_with_dependencies(
            [], list(places), lambda _, v=vals: list(v)
        )
        return list(places)

    @staticmethod
    def allocate(cs, constants_set=()):
        """Place one instance; returns {constant_value: variable} covering
        0, 1, p-1 and every value in constants_set."""
        gate = ExplicitConstantsAllocatorGate(constants_set)
        variables = []
        for c in gate.constants:
            v = cs.alloc_variable_without_value()
            cs.set_values_with_dependencies(
                [], [v], lambda _, c=c: [c]
            )
            variables.append(v)
        cs.place_gate(gate, list(variables), ())
        return dict(zip(gate.constants, variables))
