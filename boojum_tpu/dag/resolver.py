"""Witness-resolution dataflow engine (host side).

The reference resolves witness closures on a worker-thread pipeline overlapped
with synthesis (`/root/reference/src/dag/resolvers/mt/mod.rs:100`
MtCircuitResolver; single-threaded semantics in `resolvers/st.rs`). The
TPU-native design keeps resolution on the host but *eager and batched*:
closures run immediately when their inputs are already known (the common case
— gadget code computes forward), otherwise they are parked on their missing
inputs and flushed by the dependency that arrives last. Gadget helpers
register ONE closure for a whole vector of allocations (`set_values_batch`),
which is what makes python-side witness generation scale — the analogue of
the reference Guide's span batching (`src/dag/guide.rs:129`).

Values live in a growable numpy uint64 arena; the device witness scatter
reads it zero-copy at freeze time.
"""

from __future__ import annotations

import numpy as np

from ..cs.types import is_var, is_wit, place_index


class WitnessResolver:
    def __init__(self, capacity: int = 1 << 16):
        self.values = np.zeros(capacity, dtype=np.uint64)
        self.resolved = np.zeros(capacity, dtype=bool)
        # place -> list of closure records waiting on it
        self._waiters: dict[int, list] = {}
        self._num_pending = 0

    # -- storage ------------------------------------------------------------

    def _ensure(self, idx: int):
        if idx >= len(self.values):
            new_cap = max(len(self.values) * 2, idx + 1)
            new_values = np.zeros(new_cap, dtype=np.uint64)
            new_values[: len(self.values)] = self.values
            new_resolved = np.zeros(new_cap, dtype=bool)
            new_resolved[: len(self.resolved)] = self.resolved
            self.values = new_values
            self.resolved = new_resolved

    def is_resolved(self, place: int) -> bool:
        idx = place
        return idx < len(self.resolved) and bool(self.resolved[idx])

    def get_value(self, place: int) -> int:
        assert self.is_resolved(place), f"place {place} unresolved"
        return int(self.values[place])

    def set_value(self, place: int, value: int):
        self._ensure(place)
        assert not self.resolved[place], f"place {place} set twice"
        self.values[place] = value
        self.resolved[place] = True
        waiters = self._waiters.pop(place, None)
        if waiters:
            for rec in waiters:
                rec[0] -= 1
                if rec[0] == 0:
                    self._num_pending -= 1
                    self._run(rec[1], rec[2], rec[3])

    # -- resolutions --------------------------------------------------------

    def add_resolution(self, ins: list, outs: list, fn):
        """Register fn(list_of_input_ints) -> list_of_output_ints.

        Runs immediately if all inputs are resolved (the hot path).
        """
        missing = [p for p in ins if not self.is_resolved(p)]
        if not missing:
            self._run(ins, outs, fn)
            return
        rec = [len(missing), ins, outs, fn]
        self._num_pending += 1
        for p in missing:
            self._waiters.setdefault(p, []).append(rec)

    def _run(self, ins, outs, fn):
        in_vals = [int(self.values[p]) for p in ins]
        out_vals = fn(in_vals)
        assert len(out_vals) == len(outs), "resolver arity mismatch"
        for p, v in zip(outs, out_vals):
            self.set_value(p, int(v))

    def wait_till_resolved(self):
        """All registered resolutions must have fired (reference
        `wait_till_resolved`, dag/resolvers/mt/mod.rs)."""
        if self._num_pending:
            unresolved = [p for p, w in self._waiters.items() if w]
            raise RuntimeError(
                f"{self._num_pending} witness resolutions never fired; "
                f"first unresolved places: {unresolved[:10]}"
            )

    # -- bulk views ---------------------------------------------------------

    def values_flat(self, count: int) -> np.ndarray:
        """Dense value vector for places [0, count) (vars+wits interleaved)."""
        assert self.resolved[:count].all(), "unresolved places in flat dump"
        return self.values[:count]


class NullResolver(WitnessResolver):
    """Setup-mode no-op resolver (reference NullCircuitResolver,
    dag/resolvers/null.rs): accepts registrations, stores nothing."""

    def __init__(self):
        super().__init__(capacity=1)

    def set_value(self, place: int, value: int):
        pass

    def add_resolution(self, ins, outs, fn):
        pass

    def is_resolved(self, place: int) -> bool:
        return False

    def wait_till_resolved(self):
        pass
