"""Witness-resolution dataflow engine (host side).

The reference resolves witness closures on a worker-thread pipeline overlapped
with synthesis (`/root/reference/src/dag/resolvers/mt/mod.rs:100`
MtCircuitResolver; single-threaded semantics in `resolvers/st.rs`). The
TPU-native design keeps resolution on the host but *eager and batched*:
closures run immediately when their inputs are already known (the common case
— gadget code computes forward), otherwise they are parked on their missing
inputs and flushed by the dependency that arrives last. Gadget helpers
register ONE closure for a whole vector of allocations (`set_values_batch`),
which is what makes python-side witness generation scale — the analogue of
the reference Guide's span batching (`src/dag/guide.rs:129`).

Values live in a growable numpy uint64 arena; the device witness scatter
reads it zero-copy at freeze time.
"""

from __future__ import annotations

import numpy as np

from ..cs.types import is_var, is_wit, place_index


class WitnessResolver:
    def __init__(self, capacity: int = 1 << 16):
        self.values = np.zeros(capacity, dtype=np.uint64)
        self.resolved = np.zeros(capacity, dtype=bool)
        # place -> list of closure records waiting on it
        self._waiters: dict[int, list] = {}
        self._num_pending = 0
        # record/playback (reference mt/sorters/sorter_live.rs): when
        # recording, every registered resolution gets a sequential id and
        # the record lists ids in EXECUTION order
        self._record: list[int] | None = None
        self._reg_counter = 0

    # -- record / playback ---------------------------------------------------

    def start_recording(self):
        """Record the resolution execution order for deterministic replay
        (reference ResolutionRecord, dag/resolvers/mt/sorters/)."""
        assert self._reg_counter == 0, "recording must start before synthesis"
        self._record = []

    def resolution_record(self) -> list[int]:
        assert self._record is not None, "recording was not enabled"
        return list(self._record)

    def _log_execution(self, reg_id: int):
        if self._record is not None:
            self._record.append(reg_id)

    # -- storage ------------------------------------------------------------

    def _ensure(self, idx: int):
        if idx >= len(self.values):
            new_cap = max(len(self.values) * 2, idx + 1)
            new_values = np.zeros(new_cap, dtype=np.uint64)
            new_values[: len(self.values)] = self.values
            new_resolved = np.zeros(new_cap, dtype=bool)
            new_resolved[: len(self.resolved)] = self.resolved
            self.values = new_values
            self.resolved = new_resolved

    def is_resolved(self, place: int) -> bool:
        idx = place
        return idx < len(self.resolved) and bool(self.resolved[idx])

    def get_value(self, place: int) -> int:
        assert self.is_resolved(place), f"place {place} unresolved"
        return int(self.values[place])

    def set_value(self, place: int, value: int):
        self._ensure(place)
        assert not self.resolved[place], f"place {place} set twice"
        self.values[place] = value
        self.resolved[place] = True
        waiters = self._waiters.pop(place, None)
        if waiters:
            for rec in waiters:
                rec[0] -= 1
                if rec[0] == 0:
                    self._num_pending -= 1
                    self._log_execution(rec[4])
                    self._run(rec[1], rec[2], rec[3])

    # -- resolutions --------------------------------------------------------

    def add_resolution(self, ins: list, outs: list, fn, native=None, table=None):
        """Register fn(list_of_input_ints) -> list_of_output_ints.

        Runs immediately if all inputs are resolved (the hot path). `native`
        (a typed-op descriptor) and `table` are accepted for signature parity
        with NativeTapeResolver and ignored here.
        """
        reg_id = self._reg_counter
        self._reg_counter += 1
        missing = [p for p in ins if not self.is_resolved(p)]
        if not missing:
            self._log_execution(reg_id)
            self._run(ins, outs, fn)
            return
        rec = [len(missing), ins, outs, fn, reg_id]
        self._num_pending += 1
        for p in missing:
            self._waiters.setdefault(p, []).append(rec)

    def native_multiplicities(self, table_id: int):
        """Lookup-multiplicity bumps executed natively (none here)."""
        return None

    def _run(self, ins, outs, fn):
        in_vals = [int(self.values[p]) for p in ins]
        out_vals = fn(in_vals)
        assert len(out_vals) == len(outs), "resolver arity mismatch"
        for p, v in zip(outs, out_vals):
            self.set_value(p, int(v))

    def wait_till_resolved(self):
        """All registered resolutions must have fired (reference
        `wait_till_resolved`, dag/resolvers/mt/mod.rs)."""
        if self._num_pending:
            unresolved = [p for p, w in self._waiters.items() if w]
            raise RuntimeError(
                f"{self._num_pending} witness resolutions never fired; "
                f"first unresolved places: {unresolved[:10]}"
            )

    # -- bulk views ---------------------------------------------------------

    def values_flat(self, count: int) -> np.ndarray:
        """Dense value vector for places [0, count) (vars+wits interleaved)."""
        assert self.resolved[:count].all(), "unresolved places in flat dump"
        return self.values[:count]


class NativeTapeResolver(WitnessResolver):
    """Witness resolver backed by the C++ typed-op tape engine
    (`boojum_tpu.native`): gadget helpers that provide a typed descriptor are
    recorded on a tape and executed natively in batches; anything else runs
    through the python-closure path. Flushes happen lazily — on the first
    read of a tape-pending place, when a python closure needs one, or at
    `wait_till_resolved`.

    This is the host-side analogue of the reference's compiled resolver
    pipeline (dag/resolvers/mt/resolution_window.rs): same dataflow
    semantics, with the "worker" being one vectorized native pass instead of
    a thread pool.
    """

    # tape batches at or above this launch on the worker thread DURING
    # synthesis (the ctypes execute releases the GIL, so native resolution
    # overlaps python gate placement — the TPU-side answer to the
    # reference's synthesis-parallel ResolutionWindow workers,
    # mt/resolution_window.rs:111)
    ASYNC_THRESHOLD = 8192

    def __init__(self, lib, capacity: int = 1 << 16):
        super().__init__(capacity=capacity)
        from ..native import NativeTape

        self._tape = NativeTape(lib)
        self._pending: set[int] = set()
        self._max_place = -1
        self._poison: Exception | None = None
        self._executor = None
        self._inflight: list = []  # [(future, out_places_list)]
        self._inflight_places: set[int] = set()

    def _available(self, place: int) -> bool:
        return (
            (place < len(self.resolved) and bool(self.resolved[place]))
            or place in self._pending
            or place in self._inflight_places
        )

    def _ensure(self, idx: int):
        # the worker writes into self.values in place: a reallocation while
        # a batch is in flight would strand its writes in the old buffer
        if idx >= len(self.values) and self._inflight:
            self._join()
        super()._ensure(idx)

    def flush_async(self):
        """Detach the current tape batch and execute it on the worker
        thread; synthesis keeps running. Batches are FIFO on one worker, so
        a later batch always sees the values an earlier one wrote."""
        snap = self._tape.take_snapshot()
        if snap is None:
            return
        self._ensure(self._max_place)
        if self._executor is None:
            from concurrent.futures import ThreadPoolExecutor

            self._executor = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="boojum-tape"
            )
        out_places = snap[7]
        fut = self._executor.submit(
            self._tape.run_snapshot, self.values, snap
        )
        self._inflight.append((fut, out_places))
        self._inflight_places.update(out_places)
        self._pending.difference_update(out_places)

    def _join(self):
        """Wait for every in-flight batch and publish its results."""
        inflight, self._inflight = self._inflight, []
        self._inflight_places.clear()
        for fut, out_places in inflight:
            try:
                fut.result()
            except Exception as e:
                # a failed batch cannot be re-executed (partial execution
                # would double-bump lookup multiplicities): poison the
                # resolver so later reads surface THIS error instead of a
                # misleading 'place unresolved' assert.
                self._pending.clear()
                self._poison = e
                raise
            self.resolved[np.array(out_places, dtype=np.int64)] = True

    def flush(self):
        if len(self._tape):
            self.flush_async()
        if self._inflight:
            self._join()
        self._pending.clear()
        # fire python waiters parked on natively-resolved places
        if self._waiters:
            fired = [
                p
                for p in self._waiters
                if p < len(self.resolved) and self.resolved[p]
            ]
            for p in fired:
                for rec in self._waiters.pop(p):
                    rec[0] -= 1
                    if rec[0] == 0:
                        self._num_pending -= 1
                        self._log_execution(rec[4])
                        self._run(rec[1], rec[2], rec[3])

    def _check_poison(self):
        if self._poison is not None:
            raise RuntimeError(
                "witness resolution incomplete because an earlier native "
                "resolution batch failed"
            ) from self._poison

    def wait_till_resolved(self):
        self.flush()
        self._check_poison()
        super().wait_till_resolved()

    def values_flat(self, count: int) -> np.ndarray:
        self.flush()
        self._check_poison()
        return super().values_flat(count)

    def is_resolved(self, place: int) -> bool:
        if place in self._pending or place in self._inflight_places:
            self.flush()
        return super().is_resolved(place)

    def get_value(self, place: int) -> int:
        if place in self._pending or place in self._inflight_places:
            self.flush()
        if self._poison is not None and not super().is_resolved(place):
            raise RuntimeError(
                "witness place unresolved because an earlier native "
                "resolution batch failed"
            ) from self._poison
        return super().get_value(place)

    def add_resolution(self, ins, outs, fn, native=None, table=None):
        if native is not None and all(self._available(p) for p in ins):
            # tape ops execute in append order at flush time: log now
            reg_id = self._reg_counter
            self._reg_counter += 1
            self._log_execution(reg_id)
            kind, params = native
            if table is not None:
                tid = int(params[0])
                if self._inflight and not self._tape.has_table(tid):
                    # registering a table resizes the C engine's table
                    # vector, which an in-flight execute_tape dereferences:
                    # drain the worker before mutating engine state
                    self._join()
                self._tape.ensure_table(tid, table)
                params = (self._tape.slot_of(tid),)
            self._tape.append(kind, params, ins, outs)
            if outs:
                self._pending.update(outs)
                m = max(outs)
                if m > self._max_place:
                    self._max_place = m
            if len(self._tape) >= self.ASYNC_THRESHOLD:
                self.flush_async()
            return
        if native is not None:
            # inputs not all available natively: fall back to the closure
            # path, flushing first so tape-pending inputs materialize
            if any(
                p in self._pending or p in self._inflight_places
                for p in ins
            ):
                self.flush()
        super().add_resolution(ins, outs, fn)

    def native_multiplicities(self, table_id: int):
        # engine-side counters bump during execution: drain everything first
        self.flush()
        return self._tape.multiplicities_of(table_id)


class PlaybackResolver(WitnessResolver):
    """Deterministic re-run driven by a prior run's resolution record
    (reference `mt/sorters/sorter_playback.rs`): resolutions execute in
    exactly the recorded order with no dependency tracking — each one's
    inputs must already be resolved when its turn comes, otherwise the
    synthesis diverged from the recorded run and playback raises."""

    def __init__(self, record, capacity: int = 1 << 16):
        super().__init__(capacity=capacity)
        self._playback = list(record)
        self._cursor = 0
        self._parked: dict[int, tuple] = {}

    def _drain(self):
        while (
            self._cursor < len(self._playback)
            and self._playback[self._cursor] in self._parked
        ):
            nid = self._playback[self._cursor]
            self._cursor += 1
            pins, pouts, pfn = self._parked.pop(nid)
            for p in pins:
                if not self.is_resolved(p):
                    raise RuntimeError(
                        f"playback divergence: resolution {nid} input {p} "
                        "not resolved at its recorded slot"
                    )
            self._run(pins, pouts, pfn)

    def add_resolution(self, ins, outs, fn, native=None, table=None):
        assert fn is not None, "playback needs the portable closure"
        reg_id = self._reg_counter
        self._reg_counter += 1
        self._parked[reg_id] = (ins, outs, fn)
        self._drain()

    def get_value(self, place: int) -> int:
        if not self.is_resolved(place):
            self._drain()
        return super().get_value(place)

    def wait_till_resolved(self):
        if self._cursor != len(self._playback) or self._parked:
            raise RuntimeError(
                "playback divergence: "
                f"{len(self._playback) - self._cursor} recorded resolutions "
                f"never ran, {len(self._parked)} registrations unmatched"
            )


def make_resolver(capacity: int = 1 << 16) -> WitnessResolver:
    """The default witness resolver: native tape engine when the C++ library
    is available (BOOJUM_TPU_NO_NATIVE=1 opts out), else pure python.

    The native tape computes in GOLDILOCKS (its typed ops hardwire the
    2^64-2^32+1 reduction), so any other active field backend (ISSUE 20:
    BOOJUM_TPU_FIELD=babybear) takes the portable python resolver, whose
    closures dispatch through field/active.py."""
    from ..field.spec import active_field

    if active_field() != "goldilocks":
        return WitnessResolver(capacity=capacity)
    from ..native import get_lib

    lib = get_lib()
    if lib is not None:
        return NativeTapeResolver(lib, capacity=capacity)
    return WitnessResolver(capacity=capacity)


class NullResolver(WitnessResolver):
    """Setup-mode no-op resolver (reference NullCircuitResolver,
    dag/resolvers/null.rs): accepts registrations, stores nothing."""

    def __init__(self):
        super().__init__(capacity=1)

    def set_value(self, place: int, value: int):
        pass

    def add_resolution(self, ins, outs, fn, native=None, table=None):
        pass

    def is_resolved(self, place: int) -> bool:
        return False

    def wait_till_resolved(self):
        pass
