from .resolver import (
    NativeTapeResolver,
    NullResolver,
    WitnessResolver,
    make_resolver,
)
