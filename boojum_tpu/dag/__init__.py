from .resolver import WitnessResolver, NullResolver
