"""Poseidon2 permutation as a fused Pallas TPU kernel over u32 limb planes.

The TPU counterpart of the reference's AVX-512 Poseidon2 state
(`/root/reference/src/implementations/poseidon2/state_avx512.rs`): where that
packs the width-12 state into 512-bit registers and keeps a whole permutation
in-register, this kernel keeps a (12, TILE, 128) tile of states resident in
VMEM for all 30 rounds — one HBM read and one write per permutation batch,
instead of one round-trip per round (what the staged XLA version pays when the
fused graph exceeds the fusion horizon).

Layout: the batch axis is tiled (rows x 128 lanes); the state axis (12) and
the limb axis (2) are leading dims, so every field op is an elementwise VPU op
over (TILE, 128) tiles. Round constants live in SMEM as u32 limb pairs and are
broadcast per round inside `fori_loop`s (4 full / 22 partial / 4 full — the
same phase structure as `poseidon2.py`).

Used by `poseidon2.py:poseidon2_permutation` when running on TPU (env
BOOJUM_TPU_PALLAS=0 disables); bit-parity with the XLA path is asserted in
tests/test_pallas_kernels.py (interpret mode on CPU + real kernels on TPU).
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np

from ..field import limbs
from . import poseidon2_params as params

_DIAG_ROW = 30

from functools import lru_cache as _lru_cache  # noqa: E402


@_lru_cache(maxsize=None)
def rc_diag_table(layout: str = "lohi24") -> np.ndarray:
    """RC/DIAG limb constants in one kernel-variant-keyed spec cache.

    (30, 12) limb pairs -> (30, 24) u32: [lo(12) | hi(12)] per round, plus
    a 31st row carrying the M_I diagonal in the same [lo | hi] layout —
    pallas kernels cannot close over array constants, so the diagonal
    rides the same SMEM table as the round constants. Built at first
    kernel build (NOT import time) and keyed by the variant's constant
    layout, so the resident and converting kernel variants can never
    share a stale layout (ISSUE 10 satellite)."""
    assert layout == "lohi24", layout
    rc = np.array(params.ALL_ROUND_CONSTANTS, dtype=np.uint64).reshape(30, 12)
    diag = np.array(params.M_I_DIAGONAL, dtype=np.uint64)
    return np.concatenate(
        [
            np.concatenate(limbs.split_np(rc), axis=1),
            np.concatenate(limbs.split_np(diag[None, :]), axis=1),
        ],
        axis=0,
    )


def _sbox7(x):
    x2 = limbs.sqr(x)
    x3 = limbs.mul(x2, x)
    x4 = limbs.sqr(x2)
    return limbs.mul(x4, x3)


# The whole permutation is VECTORIZED over the state axis: every step is a
# limbs op on stacked (12, T, 128) (or (3, T, 128) group) planes. A
# per-element formulation traced ~800 jaxpr eqns PER ROUND BODY, and every
# graph that inlines a commit re-traced it — minutes of pure tracing per
# fresh process. Element order and add association match the per-element
# form exactly; field ops are exact mod p, so results are bit-identical.


def _external_mds_planes(lo, hi):
    """M_E on stacked (12, T, 128) limb planes: 3 groups x the width-4 M4
    block, then the cross-group sums (same 4b+i element order as the
    reference's per-element loop)."""
    add, dbl = limbs.add, limbs.double
    tail = lo.shape[1:]
    glo = lo.reshape((3, 4) + tail)
    ghi = hi.reshape((3, 4) + tail)
    X = [(glo[:, i], ghi[:, i]) for i in range(4)]  # (3, T, 128) pairs
    t0 = add(X[0], X[1])
    t1 = add(X[2], X[3])
    t2 = add(dbl(X[1]), t1)
    t3 = add(dbl(X[3]), t0)
    t4 = add(dbl(dbl(t1)), t3)
    t5 = add(dbl(dbl(t0)), t2)
    B = [add(t3, t5), t5, add(t2, t4), t4]  # block outputs per position
    out_lo, out_hi = [], []
    for i in range(4):
        blo, bhi = B[i]
        s = add(add((blo[0], bhi[0]), (blo[1], bhi[1])), (blo[2], bhi[2]))
        o = add(B[i], s)  # (3,T,128) + (T,128) broadcast
        out_lo.append(o[0])
        out_hi.append(o[1])
    olo = jnp.stack(out_lo, axis=1).reshape((12,) + tail)
    ohi = jnp.stack(out_hi, axis=1).reshape((12,) + tail)
    return olo, ohi


def _internal_mds_planes(rc_ref, lo, hi):
    """M_I = all-ones + diag(d) on stacked planes."""
    total = (lo[0], hi[0])
    for i in range(1, 12):
        total = limbs.add(total, (lo[i], hi[i]))
    scaled = limbs.mul((lo, hi), _rc_row(rc_ref, _DIAG_ROW, lo[0]))
    return limbs.add(scaled, total)  # (12,T,128) + (T,128) broadcast


def _rc_row(rc_ref, r, like):
    """Row-r constants from SMEM as (12, T, 128) planes (stacked full
    tiles: Mosaic rejects reshaping a 1-D vector into broadcastable 3-D)."""
    rlo = jnp.stack(
        [jnp.full_like(like, rc_ref[r, i]) for i in range(12)]
    )
    rhi = jnp.stack(
        [jnp.full_like(like, rc_ref[r, 12 + i]) for i in range(12)]
    )
    return rlo, rhi


def _permutation_planes_stacked(rc_ref, lo, hi):
    """All 30 rounds on stacked (12, T, 128) limb planes."""
    carry = _external_mds_planes(lo, hi)

    def full_round(r, carry):
        lo, hi = carry
        s = limbs.add((lo, hi), _rc_row(rc_ref, r, lo[0]))
        return _external_mds_planes(*_sbox7(s))

    def partial_round(r, carry):
        lo, hi = carry
        rc0 = (
            jnp.full_like(lo[0], rc_ref[r, 0]),
            jnp.full_like(hi[0], rc_ref[r, 12]),
        )
        el = _sbox7(limbs.add((lo[0], hi[0]), rc0))
        lo = jnp.concatenate([el[0][None], lo[1:]], axis=0)
        hi = jnp.concatenate([el[1][None], hi[1:]], axis=0)
        return _internal_mds_planes(rc_ref, lo, hi)

    carry = jax.lax.fori_loop(0, 4, full_round, carry)
    carry = jax.lax.fori_loop(4, 26, partial_round, carry)
    carry = jax.lax.fori_loop(26, 30, full_round, carry)
    return carry


def _perm_kernel(rc_ref, lo_ref, hi_ref, out_lo_ref, out_hi_ref):
    lo, hi = _permutation_planes_stacked(rc_ref, lo_ref[:], hi_ref[:])
    out_lo_ref[:] = lo
    out_hi_ref[:] = hi


def _sponge_kernel(num_chunks: int, rc_ref, vlo_ref, vhi_ref, olo_ref, ohi_ref):
    """Overwrite-mode sponge over (L, T, 128) leaf-value planes -> (4, T, 128).

    L is padded to 8*num_chunks with zeros by the wrapper; each chunk
    overwrites the rate portion (state[0:8]) then permutes.

    The chunk loop is a fori_loop with a dynamic leading-axis slice into
    the value refs: a Python-unrolled loop would trace num_chunks copies
    of the whole permutation — for wide leaves that is tens of thousands
    of jaxpr eqns PER GRAPH that inlines this kernel, minutes of pure
    tracing in every fresh process (the round-3 'compile bill' mystery)."""
    import jax.lax as lax

    zero12 = jnp.zeros((12,) + vlo_ref.shape[1:], jnp.uint32)

    def chunk_body(c, carry):
        lo, hi = carry
        # i32 offset arithmetic: under the global x64 flag a bare 8*c is
        # i64 and Mosaic's muli verifier rejects the mixed-width product
        off = jnp.int32(8) * c
        rlo = vlo_ref[pl.ds(off, 8)]
        rhi = vhi_ref[pl.ds(off, 8)]
        lo = jnp.concatenate([rlo, lo[8:]], axis=0)
        hi = jnp.concatenate([rhi, hi[8:]], axis=0)
        return _permutation_planes_stacked(rc_ref, lo, hi)

    lo, hi = lax.fori_loop(
        jnp.int32(0), jnp.int32(num_chunks), chunk_body, (zero12, zero12)
    )
    olo_ref[:] = lo[:4]
    ohi_ref[:] = hi[:4]


from jax.experimental import pallas as pl  # noqa: E402
from jax.experimental.pallas import tpu as pltpu  # noqa: E402

from ..utils.pallas_util import imap32  # noqa: E402

# wide-leaf sponge tiles exceed the default 16 MiB scoped-vmem budget
from ..utils.pallas_util import tpu_compiler_params  # noqa: E402

_CP = tpu_compiler_params(64 * 1024 * 1024)


def _smem_spec():
    # explicit block + index map: the default index map traces i64 under the
    # global x64 flag, which Mosaic cannot legalize
    return pl.BlockSpec(
        (31, 24), imap32(lambda *_: (0, 0)), memory_space=pltpu.SMEM
    )


@partial(jax.jit, static_argnums=(2, 3))
def _permute_planes(lo, hi, tile_rows: int, interpret: bool):
    """(12, R, 128) u32 limb planes -> permuted, grid over R tiles."""
    R = lo.shape[1]
    grid = (R // tile_rows,)
    spec = pl.BlockSpec(
        (12, tile_rows, 128),
        imap32(lambda r: (0, r, 0)),
        memory_space=pltpu.VMEM,
    )
    out_shape = jax.ShapeDtypeStruct((12, R, 128), jnp.uint32)
    return pl.pallas_call(
        _perm_kernel,
        grid=grid,
        out_shape=[out_shape, out_shape],
        in_specs=[_smem_spec(), spec, spec],
        out_specs=[spec, spec],
        interpret=interpret,
        compiler_params=None if interpret else _CP,
    )(jnp.asarray(rc_diag_table()), lo, hi)


@partial(jax.jit, static_argnums=(2, 3, 4))
def _sponge_planes(vlo, vhi, num_chunks: int, tile_rows: int, interpret: bool):
    """(8*chunks, R, 128) value planes -> (4, R, 128) digest planes."""
    L, R, _ = vlo.shape
    grid = (R // tile_rows,)
    in_spec = pl.BlockSpec(
        (L, tile_rows, 128),
        imap32(lambda r: (0, r, 0)),
        memory_space=pltpu.VMEM,
    )
    out_spec = pl.BlockSpec(
        (4, tile_rows, 128),
        imap32(lambda r: (0, r, 0)),
        memory_space=pltpu.VMEM,
    )
    out_shape = jax.ShapeDtypeStruct((4, R, 128), jnp.uint32)
    return pl.pallas_call(
        partial(_sponge_kernel, num_chunks),
        grid=grid,
        out_shape=[out_shape, out_shape],
        in_specs=[_smem_spec(), in_spec, in_spec],
        out_specs=[out_spec, out_spec],
        interpret=interpret,
        compiler_params=None if interpret else _CP,
    )(jnp.asarray(rc_diag_table()), vlo, vhi)


# tile legality (divisor-of-R, multiple-of-8 sublane rule) is shared with
# the limb-sweep kernel family
from ..utils.pallas_util import pick_tile as _pick_tile  # noqa: E402


_LANE = 128
_MIN_BATCH = 1024  # below this the XLA path wins (kernel launch overhead)


def batch_fits(n: int) -> bool:
    # n % 1024 guarantees a row count with a legal sublane tile (multiple
    # of 8) whenever the batch exceeds the per-step VMEM budget
    return n >= _MIN_BATCH and n % (8 * _LANE) == 0


# The kernels' NATIVE interface takes (lo, hi) u32 planes directly (ISSUE
# 10: the former u64 wrappers' split/join at every call were the interior
# boundary tax the resident mode deletes); `permutation`/`sponge_hash`
# survive as thin u64 conversion shims for the converting path.


def permutation_planes(state_p, interpret: bool = False):
    """Batched Poseidon2 permutation on (N, 12) u32 limb planes."""
    slo, shi = state_p
    n = slo.shape[0]
    assert n % _LANE == 0
    R = n // _LANE
    # (N, 12) -> (12, R, 128) plane layout
    lo = slo.T.reshape(12, R, _LANE)
    hi = shi.T.reshape(12, R, _LANE)
    tile = _pick_tile(R, 16)
    olo, ohi = _permute_planes(lo, hi, tile, interpret)
    return olo.reshape(12, n).T, ohi.reshape(12, n).T


def sponge_hash_planes(values_p, interpret: bool = False):
    """(N, L) leaf-value planes -> (N, 4) digest planes (overwrite mode)."""
    vlo0, vhi0 = values_p
    n, L = vlo0.shape
    assert n % _LANE == 0
    num_chunks = max(1, (L + 7) // 8)
    R = n // _LANE
    vlo = vlo0.T.reshape(L, R, _LANE)
    vhi = vhi0.T.reshape(L, R, _LANE)
    if L < 8 * num_chunks:
        pad = jnp.zeros((8 * num_chunks - L, R, _LANE), jnp.uint32)
        vlo = jnp.concatenate([vlo, pad], axis=0)
        vhi = jnp.concatenate([vhi, pad], axis=0)
    # VMEM budget: (L + out + temps) * tile * 128 * 4B * 2 planes. Floor at
    # 8 (the minimum legal sublane tile): wide leaves simply use more VMEM
    # per step — the raised compiler vmem cap covers L up to ~1024, and the
    # leaf_hash dispatcher falls back to XLA beyond that.
    budget = max(8, (2 << 20) // max(8 * num_chunks * _LANE * 8, 1))
    tile = _pick_tile(R, budget)
    olo, ohi = _sponge_planes(vlo, vhi, num_chunks, tile, interpret)
    return olo.reshape(4, n).T, ohi.reshape(4, n).T


def permutation(state: jax.Array, interpret: bool = False) -> jax.Array:
    """u64 shim over `permutation_planes` (converting path only)."""
    out = permutation_planes(limbs.split(state), interpret)
    return limbs.join(out)


def sponge_hash(values: jax.Array, interpret: bool = False) -> jax.Array:
    """u64 shim over `sponge_hash_planes` (converting path only)."""
    out = sponge_hash_planes(limbs.split(values), interpret)
    return limbs.join(out)
