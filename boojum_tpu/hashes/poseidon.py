"""Legacy Poseidon permutation (Goldilocks, t=12, x^7) — batched device +
host scalar.

Counterpart of `/root/reference/src/implementations/poseidon_goldilocks.rs`
(+ `poseidon_goldilocks_naive.rs`, `suggested_mds.rs`): the ORIGINAL Poseidon
round function the reference keeps alongside Poseidon2 (Plonky2-compatible —
same MDS and round constants, so proofs interoperate with Plonky2-era
tooling). Parameters: width 12 (rate 8 / capacity 4), S-box x^7, 4 full +
22 partial + 4 full rounds, every round = add-constants -> S-box (all lanes
in full rounds, lane 0 in partial) -> MDS.

The MDS matrix is the circulant of powers of two with exponents
[0,0,1,0,3,5,1,8,12,3,16,10] (suggested_mds.rs:11 MDS_MATRIX_EXPS):
M[r][c] = 2^exps[(c - r) mod 12]. Round constants are the shared Plonky2
table (`poseidon2_params.ALL_ROUND_CONSTANTS` — Poseidon2 reuses them,
reference poseidon2/params.rs). On device the MDS row sums run as 12
shift-multiplied modular adds over whole (..., 12) batches; the reference's
precomputed-round "optimized" variant is a pure CPU scheduling trick whose
outputs equal the naive spec (its own test_valid_transformation asserts so),
so this implements the spec form.

Sponge semantics (rate 8 / cap 4, overwrite mode) match the Poseidon2
sponge so either permutation can drive transcripts and tree hashing.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..field import gl
from ..field import goldilocks as gf
from . import poseidon2_params as params
from .poseidon2 import Poseidon2SpongeHost, _sponge_hash_device

_RC = np.array(params.ALL_ROUND_CONSTANTS, dtype=np.uint64).reshape(30, 12)
MDS_MATRIX_EXPS = [0, 0, 1, 0, 3, 5, 1, 8, 12, 3, 16, 10]


def _sbox7(x):
    x2 = gf.sqr(x)
    x3 = gf.mul(x2, x)
    return gf.mul(gf.sqr(x2), x3)


def _mds_mul(state):
    """(..., 12) -> M · state with the power-of-two circulant."""
    cols = [state[..., i] for i in range(12)]
    out = []
    for r in range(12):
        acc = None
        for c in range(12):
            term = gf.mul_small(cols[c], 1 << MDS_MATRIX_EXPS[(c - r) % 12])
            acc = term if acc is None else gf.add(acc, term)
        out.append(acc)
    return jnp.stack(out, axis=-1)


@jax.jit
def poseidon_permutation(state: jax.Array) -> jax.Array:
    """Batched legacy Poseidon permutation on (..., 12) uint64 arrays."""
    rc = jnp.asarray(_RC)

    def full_round(r, s):
        s = gf.add(s, rc[r])
        s = _sbox7(s)
        return _mds_mul(s)

    def partial_round(r, s):
        s = gf.add(s, rc[r])
        el0 = _sbox7(s[..., 0])
        s = jnp.concatenate([el0[..., None], s[..., 1:]], axis=-1)
        return _mds_mul(s)

    state = jax.lax.fori_loop(0, 4, full_round, state)
    state = jax.lax.fori_loop(4, 26, partial_round, state)
    state = jax.lax.fori_loop(26, 30, full_round, state)
    return state


# ---------------------------------------------------------------------------
# Host scalar mirror (python ints) — transcripts & verification
# ---------------------------------------------------------------------------


def _sbox7_s(x):
    x2 = gl.sqr(x)
    return gl.mul(gl.sqr(x2), gl.mul(x2, x))


def _mds_mul_s(s):
    out = []
    for r in range(12):
        acc = 0
        for c in range(12):
            acc = gl.add(
                acc, gl.mul(s[c], 1 << MDS_MATRIX_EXPS[(c - r) % 12])
            )
        out.append(acc)
    return out


def poseidon_permutation_host(state: list) -> list:
    s = [int(v) for v in state]
    for r in range(30):
        s = [gl.add(v, int(_RC[r, i])) for i, v in enumerate(s)]
        if 4 <= r < 26:
            s[0] = _sbox7_s(s[0])
        else:
            s = [_sbox7_s(v) for v in s]
        s = _mds_mul_s(s)
    return s


class PoseidonSpongeHost(Poseidon2SpongeHost):
    """Overwrite-mode sponge (rate 8 / cap 4) over the legacy permutation —
    same absorb/finalize semantics, permutation swapped via the hook."""

    _PERMUTATION = staticmethod(poseidon_permutation_host)


@jax.jit
def leaf_hash(values: jax.Array) -> jax.Array:
    """Hash (..., L) field values into (..., 4) digests (legacy Poseidon
    overwrite-mode sponge — the device twin of PoseidonSpongeHost)."""
    return _sponge_hash_device(values, poseidon_permutation)
