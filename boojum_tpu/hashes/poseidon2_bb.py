"""Poseidon2 BabyBear (t=16, x^7) — batched device + Pallas twin + host scalar.

The BOOJUM_TPU_FIELD=babybear sponge (ISSUE 19). Same shape as the
Goldilocks module (`poseidon2.py`): pre-multiply by the external matrix
circ(2*M4, M4, M4, M4), 4 full rounds, 13 partial rounds with the internal
all-ones+diag matrix, 4 full rounds — but over bare u32 lanes, so there are
no (lo, hi) planes anywhere: one leaf row is HALF the bytes of its
Goldilocks twin.

Three implementations of the one round function:
  - XLA (`poseidon2_permutation_bb_xla`): canonical-domain u32 ops, muls
    widen to u64 inside the graph (field/babybear.py ops);
  - Pallas (`_permutation_bb_block`): u32-ONLY Montgomery arithmetic —
    Mosaic has no 64-bit datapath, so in-kernel muls are 16-bit-split
    32x32->64 products + REDC folds (the BabyBear counterpart of the
    Goldilocks limb kernels, one u32 lane instead of two);
  - host (`poseidon2_permutation_bb_host`): python ints for the
    transcript/verifier.

Digests are 8 lanes (8 x 31 bits); leaves absorb rate-8 overwrite-mode
chunks, nodes compress by truncated permutation (left ‖ right fills the
full width, one permutation, take the first 8) — the standard 2-to-1
compression at digest = rate.
"""

import jax
import jax.numpy as jnp
import numpy as np

from ..field import babybear as bb
from ..field.limbs import mul32_wide
from . import poseidon2_params as params

P = params.BB_P
WIDTH = params.BB_STATE_WIDTH
RATE = params.BB_RATE
DIGEST = 8

_RC_EXT = np.array(params.BB_EXTERNAL_ROUND_CONSTANTS, dtype=np.uint32)
_RC_INT = np.array(params.BB_INTERNAL_ROUND_CONSTANTS, dtype=np.uint32)
_DIAG = np.array(params.BB_M_I_DIAGONAL, dtype=np.uint32)

# Montgomery constants for the Pallas kernel (R = 2^32)
_MONT_R2 = np.uint32((1 << 64) % P)
_MONT_MU = np.uint32((-pow(P, -1, 1 << 32)) % (1 << 32))
_MONT_ONE = np.uint32((1 << 32) % P)


def _to_mont_np(x):
    return ((x.astype(np.uint64) << np.uint64(32)) % np.uint64(P)).astype(
        np.uint32
    )


_RC_EXT_MONT = _to_mont_np(_RC_EXT)
_RC_INT_MONT = _to_mont_np(_RC_INT)
_DIAG_MONT = _to_mont_np(_DIAG)


def _sbox7(x, mul):
    x2 = mul(x, x)
    x3 = mul(x2, x)
    x4 = mul(x2, x2)
    return mul(x4, x3)


def _block_m4(x0, x1, x2, x3, add, double):
    """M4 = [[5,7,1,3],[4,6,1,1],[1,3,5,7],[1,1,4,6]] via add/double chain
    (same chain as the Goldilocks module)."""
    t0 = add(x0, x1)
    t1 = add(x2, x3)
    t2 = add(double(x1), t1)
    t3 = add(double(x3), t0)
    t4 = add(double(double(t1)), t3)
    t5 = add(double(double(t0)), t2)
    t6 = add(t3, t5)
    t7 = add(t2, t4)
    return t6, t5, t7, t4


def _external_cols(cols, add, double):
    """circ(2*M4, M4, M4, M4) over 16 per-lane columns."""
    blocks = [
        _block_m4(*cols[4 * b : 4 * b + 4], add, double) for b in range(4)
    ]
    sums = []
    for i in range(4):
        s = add(add(blocks[0][i], blocks[1][i]), add(blocks[2][i], blocks[3][i]))
        sums.append(s)
    out = []
    for b in range(4):
        for i in range(4):
            out.append(add(blocks[b][i], sums[i]))
    return out


def _internal_cols(cols, add, mul, diag):
    """M_I = all-ones + diag(d): out_i = d_i*x_i + sum_j x_j."""
    total = cols[0]
    for c in cols[1:]:
        total = add(total, c)
    return [add(mul(c, d), total) for c, d in zip(cols, diag)]


# ---------------------------------------------------------------------------
# XLA path (canonical domain)
# ---------------------------------------------------------------------------


def _external_mds_bb(state):
    """state (..., 16) -> circ(2*M4, M4, M4, M4) · state."""
    cols = [state[..., i] for i in range(WIDTH)]
    return jnp.stack(_external_cols(cols, bb.add, bb.double), axis=-1)


@jax.jit
def poseidon2_permutation_bb_xla(state: jax.Array) -> jax.Array:
    """Batched permutation on (..., 16) uint32 arrays. Rounds run under
    `lax.fori_loop` for the same reason the Goldilocks module loops: one
    round body per phase keeps XLA compile time flat (an unrolled 21-round
    graph measured 2min+ of CPU compile)."""
    rc_ext = jnp.asarray(_RC_EXT)
    rc_int = jnp.asarray(_RC_INT)
    diag = jnp.asarray(_DIAG)

    def full_round(r, s):
        s = bb.add(s, rc_ext[r])
        s = _sbox7(s, bb.mul)
        return _external_mds_bb(s)

    def partial_round(r, s):
        el0 = _sbox7(bb.add(s[..., 0], rc_int[r]), bb.mul)
        s = jnp.concatenate([el0[..., None], s[..., 1:]], axis=-1)
        # lane sum: widen once — 16 summands of < 2^31 fit u64 exactly
        total = (jnp.sum(s.astype(jnp.uint64), axis=-1) % jnp.uint64(P)).astype(
            jnp.uint32
        )
        return bb.add(bb.mul(s, diag), total[..., None])

    state = _external_mds_bb(state)
    state = jax.lax.fori_loop(0, 4, full_round, state)
    state = jax.lax.fori_loop(
        0, params.BB_NUM_PARTIAL_ROUNDS, partial_round, state
    )
    state = jax.lax.fori_loop(4, 8, full_round, state)
    return state


# ---------------------------------------------------------------------------
# Pallas path: u32-only Montgomery round function
# ---------------------------------------------------------------------------


def _mont_mul(a, b):
    """a*b*R^-1 mod p with u32 ops only (REDC). a, b < p."""
    t_lo, t_hi = mul32_wide(a, b)
    m = t_lo * jnp.uint32(_MONT_MU)  # wrapping low product
    _mp_lo, mp_hi = mul32_wide(m, jnp.full_like(m, np.uint32(P)))
    # t_lo + mp_lo == 0 mod 2^32 by construction: carry = (t_lo != 0)
    carry = (t_lo != 0).astype(jnp.uint32)
    u = t_hi + mp_hi + carry  # < 2p
    return jnp.where(u >= jnp.uint32(P), u - jnp.uint32(P), u)


def _mont_add(a, b):
    s = a + b
    return jnp.where(s >= jnp.uint32(P), s - jnp.uint32(P), s)


def _mont_double(a):
    return _mont_add(a, a)


def _permutation_bb_stack(s, rc_ext, rc_int, diag):
    """The full 21-round permutation on a (16, T) Montgomery-domain u32
    stack — the Pallas kernel core (also runs as plain jnp in interpret
    mode on CPU). Constant tables arrive as kernel inputs (Pallas rejects
    captured device constants): rc_ext (8, 16), rc_int (13, 1),
    diag (16, 1), all Montgomery-form. Rounds loop under fori_loop, same
    compile-time posture as the XLA path."""

    def ext_mds(s):
        cols = [s[i] for i in range(WIDTH)]
        return jnp.stack(_external_cols(cols, _mont_add, _mont_double))

    def full_round(r, s):
        s = _mont_add(s, rc_ext[r][:, None])
        s = _sbox7(s, _mont_mul)
        return ext_mds(s)

    def partial_round(r, s):
        c0 = _sbox7(_mont_add(s[0], rc_int[r, 0]), _mont_mul)
        s = jnp.concatenate([c0[None], s[1:]], axis=0)
        # lane sum as a 4-level _mont_add tree (no u64 in a Pallas body)
        t = _mont_add(s[:8], s[8:])
        t = _mont_add(t[:4], t[4:])
        t = _mont_add(t[:2], t[2:])
        total = _mont_add(t[0], t[1])
        return _mont_add(_mont_mul(s, diag), total[None])

    s = ext_mds(s)
    s = jax.lax.fori_loop(0, 4, full_round, s)
    s = jax.lax.fori_loop(0, params.BB_NUM_PARTIAL_ROUNDS, partial_round, s)
    s = jax.lax.fori_loop(4, 8, full_round, s)
    return s


def _perm_kernel(x_ref, rce_ref, rci_ref, diag_ref, o_ref):
    x = x_ref[...]  # (16, T) canonical u32
    r2 = jnp.full_like(x, _MONT_R2)
    s = _mont_mul(x, r2)  # to Montgomery
    s = _permutation_bb_stack(s, rce_ref[...], rci_ref[...], diag_ref[...])
    o_ref[...] = _mont_mul(s, jnp.ones_like(s))  # from Montgomery


def poseidon2_permutation_bb_pallas(state, interpret=None):
    """(N, 16) canonical u32 -> (N, 16), tiled (16, T) blocks through one
    pallas_call. Interpret mode off-TPU (the CPU correctness twin)."""
    from jax.experimental import pallas as pl

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    n = state.shape[0]
    T = min(512, max(8, n))
    pad = (-n) % T
    x = jnp.pad(state, ((0, pad), (0, 0))).T  # (16, n+pad)
    total = n + pad
    out = pl.pallas_call(
        _perm_kernel,
        grid=(total // T,),
        in_specs=[
            pl.BlockSpec((WIDTH, T), lambda i: (0, i)),
            pl.BlockSpec((8, WIDTH), lambda i: (0, 0)),
            pl.BlockSpec((params.BB_NUM_PARTIAL_ROUNDS, 1), lambda i: (0, 0)),
            pl.BlockSpec((WIDTH, 1), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((WIDTH, T), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((WIDTH, total), jnp.uint32),
        interpret=interpret,
    )(
        x,
        jnp.asarray(_RC_EXT_MONT),
        jnp.asarray(_RC_INT_MONT)[:, None],
        jnp.asarray(_DIAG_MONT)[:, None],
    )
    return out.T[:n]


def _pallas_ready(n: int) -> bool:
    from ..utils.pallas_util import pallas_enabled

    if not pallas_enabled():
        return False
    return n >= 8


def poseidon2_permutation_bb(state: jax.Array) -> jax.Array:
    """Dispatch: Pallas on TPU for 2-D batches, XLA otherwise."""
    if state.ndim == 2 and _pallas_ready(state.shape[0]):
        return poseidon2_permutation_bb_pallas(state)
    return poseidon2_permutation_bb_xla(state)


# ---------------------------------------------------------------------------
# Sponge / Merkle hashing (rate 8, digest 8, overwrite mode)
# ---------------------------------------------------------------------------


def _sponge_hash_bb(values: jax.Array, permutation) -> jax.Array:
    """Overwrite-mode sponge over (..., L) -> (..., 8): each full rate-8
    chunk overwrites the rate lanes then permutes; a trailing partial
    chunk is zero-padded (same finalize semantics as the Goldilocks
    sponge)."""
    lead = values.shape[:-1]
    L = values.shape[-1]
    state = jnp.zeros(lead + (WIDTH,), jnp.uint32)
    full = L // RATE

    def _absorb(c, st):
        chunk = jax.lax.dynamic_slice_in_dim(values, RATE * c, RATE, axis=-1)
        st = jnp.concatenate([chunk, st[..., RATE:]], axis=-1)
        return permutation(st)

    if full > 0:
        state = jax.lax.fori_loop(0, full, _absorb, state)
    rem = L - RATE * full
    if rem > 0:
        chunk = values[..., RATE * full :]
        pad = jnp.zeros(lead + (RATE - rem,), jnp.uint32)
        state = jnp.concatenate([chunk, pad, state[..., RATE:]], axis=-1)
        state = permutation(state)
    return state[..., :DIGEST]


@jax.jit
def leaf_hash_bb_xla(values: jax.Array) -> jax.Array:
    """Hash (..., L) BabyBear values into (..., 8) leaf digests."""
    return _sponge_hash_bb(values, poseidon2_permutation_bb_xla)


def leaf_hash_bb(values: jax.Array) -> jax.Array:
    if values.ndim == 2 and _pallas_ready(values.shape[0]):
        return _sponge_hash_bb(values, poseidon2_permutation_bb_pallas)
    return leaf_hash_bb_xla(values)


@jax.jit
def node_hash_bb_xla(left: jax.Array, right: jax.Array) -> jax.Array:
    """Truncated-permutation 2-to-1 compression: (..., 8) x (..., 8) ->
    (..., 8). left ‖ right fills the full width — one permutation."""
    state = jnp.concatenate([left, right], axis=-1)
    return poseidon2_permutation_bb_xla(state)[..., :DIGEST]


def node_hash_bb(left: jax.Array, right: jax.Array) -> jax.Array:
    if left.ndim == 2 and _pallas_ready(left.shape[0]):
        state = jnp.concatenate([left, right], axis=-1)
        return poseidon2_permutation_bb_pallas(state)[..., :DIGEST]
    return node_hash_bb_xla(left, right)


# ---------------------------------------------------------------------------
# NumPy batch twin (compat/prove_reference_bb.py) — vectorized host, no jax
# ---------------------------------------------------------------------------


def poseidon2_permutation_bb_np(states: np.ndarray) -> np.ndarray:
    """(T, 16) uint32 -> (T, 16), bit-identical to the device paths —
    the reference prover's Merkle workhorse (the scalar host mirror below
    is transcript-scale only)."""
    states = np.asarray(states, dtype=np.uint32)

    def ext_mds(cols):
        return _external_cols(cols, bb.add_np, lambda x: bb.add_np(x, x))

    cols = [states[:, i].copy() for i in range(WIDTH)]
    cols = ext_mds(cols)
    for r in range(4):
        cols = [bb.add_np(c, np.uint32(rc)) for c, rc in zip(cols, _RC_EXT[r])]
        cols = [_sbox7(c, bb.mul_np) for c in cols]
        cols = ext_mds(cols)
    diag = [np.uint32(d) for d in _DIAG]
    for r in range(params.BB_NUM_PARTIAL_ROUNDS):
        cols[0] = _sbox7(bb.add_np(cols[0], np.uint32(_RC_INT[r])), bb.mul_np)
        total = (
            np.sum(np.stack(cols).astype(np.uint64), axis=0) % np.uint64(P)
        ).astype(np.uint32)
        cols = [bb.add_np(bb.mul_np(c, d), total) for c, d in zip(cols, diag)]
    for r in range(4, 8):
        cols = [bb.add_np(c, np.uint32(rc)) for c, rc in zip(cols, _RC_EXT[r])]
        cols = [_sbox7(c, bb.mul_np) for c in cols]
        cols = ext_mds(cols)
    return np.stack(cols, axis=-1)


def leaf_hash_bb_np(values: np.ndarray) -> np.ndarray:
    """(T, L) uint32 -> (T, 8) digests (overwrite-mode sponge, numpy)."""
    values = np.asarray(values, dtype=np.uint32)
    T, L = values.shape
    state = np.zeros((T, WIDTH), dtype=np.uint32)
    for c in range(0, L, RATE):
        chunk = values[:, c : c + RATE]
        if chunk.shape[1] < RATE:
            chunk = np.pad(chunk, ((0, 0), (0, RATE - chunk.shape[1])))
        state = np.concatenate([chunk, state[:, RATE:]], axis=1)
        state = poseidon2_permutation_bb_np(state)
    return state[:, :DIGEST]


def node_hash_bb_np(left: np.ndarray, right: np.ndarray) -> np.ndarray:
    state = np.concatenate([left, right], axis=-1).astype(np.uint32)
    return poseidon2_permutation_bb_np(state)[:, :DIGEST]


# ---------------------------------------------------------------------------
# Host scalar mirror (transcript / verifier)
# ---------------------------------------------------------------------------


def poseidon2_permutation_bb_host(state):
    """Python-int permutation, bit-identical to the device paths."""
    assert len(state) == WIDTH
    cols = [int(x) % P for x in state]

    def add(a, b):
        return bb.add_s(a, b)

    def double(a):
        return bb.add_s(a, a)

    def mul(a, b):
        return bb.mul_s(a, b)

    def sbox(x):
        return _sbox7(x, mul)

    cols = _external_cols(cols, add, double)
    for r in range(4):
        cols = [add(c, int(rc)) for c, rc in zip(cols, _RC_EXT[r])]
        cols = [sbox(c) for c in cols]
        cols = _external_cols(cols, add, double)
    for r in range(params.BB_NUM_PARTIAL_ROUNDS):
        cols = [sbox(add(cols[0], int(_RC_INT[r])))] + cols[1:]
        cols = _internal_cols(cols, add, mul, [int(d) for d in _DIAG])
    for r in range(4, 8):
        cols = [add(c, int(rc)) for c, rc in zip(cols, _RC_EXT[r])]
        cols = [sbox(c) for c in cols]
        cols = _external_cols(cols, add, double)
    return cols


def leaf_hash_bb_host(values) -> list:
    """Host sponge over a python int sequence -> 8-element digest list."""
    state = [0] * WIDTH
    vals = [int(v) % P for v in values]
    full = len(vals) // RATE
    for c in range(full):
        state[:RATE] = vals[RATE * c : RATE * (c + 1)]
        state = poseidon2_permutation_bb_host(state)
    rem = len(vals) - RATE * full
    if rem > 0:
        chunk = vals[RATE * full :] + [0] * (RATE - rem)
        state[:RATE] = chunk
        state = poseidon2_permutation_bb_host(state)
    return state[:DIGEST]


def node_hash_bb_host(left, right) -> list:
    state = [int(x) % P for x in list(left) + list(right)]
    return poseidon2_permutation_bb_host(state)[:DIGEST]
