from . import poseidon2_params
from .poseidon2 import (
    poseidon2_permutation,
    poseidon2_permutation_host,
    leaf_hash,
    node_hash,
    Poseidon2SpongeHost,
)
