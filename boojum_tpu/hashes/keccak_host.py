"""Host (python-int) Keccak-f[1600] and Keccak-256 — original 0x01 padding.

Used by the byte-oriented Keccak256 transcript/PoW backends
(counterpart of the reference's `keccak256` uses in transcript.rs:369 and
pow.rs:140) and as the parity reference for the Keccak-256 gadget tests.
"""

from __future__ import annotations

_ROT = [
    [0, 36, 3, 41, 18],
    [1, 44, 10, 45, 2],
    [62, 6, 43, 15, 61],
    [28, 55, 25, 21, 56],
    [27, 20, 39, 8, 14],
]

_RC = [
    0x0000000000000001, 0x0000000000008082, 0x800000000000808A,
    0x8000000080008000, 0x000000000000808B, 0x0000000080000001,
    0x8000000080008081, 0x8000000000008009, 0x000000000000008A,
    0x0000000000000088, 0x0000000080008009, 0x000000008000000A,
    0x000000008000808B, 0x800000000000008B, 0x8000000000008089,
    0x8000000000008003, 0x8000000000008002, 0x8000000000000080,
    0x000000000000800A, 0x800000008000000A, 0x8000000080008081,
    0x8000000000008080, 0x0000000080000001, 0x8000000080008008,
]

_M = (1 << 64) - 1


def _rol(x, r):
    r %= 64
    return ((x << r) | (x >> (64 - r))) & _M


def keccak_f1600(a):
    """In-place-style permutation over a 5x5 list-of-lists of u64."""
    for rc in _RC:
        c = [a[x][0] ^ a[x][1] ^ a[x][2] ^ a[x][3] ^ a[x][4] for x in range(5)]
        d = [c[(x - 1) % 5] ^ _rol(c[(x + 1) % 5], 1) for x in range(5)]
        for x in range(5):
            for y in range(5):
                a[x][y] ^= d[x]
        b = [[0] * 5 for _ in range(5)]
        for x in range(5):
            for y in range(5):
                b[y][(2 * x + 3 * y) % 5] = _rol(a[x][y], _ROT[x][y])
        for x in range(5):
            for y in range(5):
                a[x][y] = b[x][y] ^ ((~b[(x + 1) % 5][y]) & b[(x + 2) % 5][y])
        a[0][0] ^= rc
    return a


def keccak256(data: bytes) -> bytes:
    """Ethereum-style Keccak-256 (0x01 domain padding, rate 136)."""
    rate = 136
    padlen = rate - len(data) % rate
    if padlen == 1:
        data = data + b"\x81"
    else:
        data = data + b"\x01" + b"\x00" * (padlen - 2) + b"\x80"
    a = [[0] * 5 for _ in range(5)]
    for off in range(0, len(data), rate):
        block = data[off : off + rate]
        for w in range(rate // 8):
            x, y = w % 5, w // 5
            a[x][y] ^= int.from_bytes(block[w * 8 : (w + 1) * 8], "little")
        a = keccak_f1600(a)
    out = b""
    for w in range(4):
        x, y = w % 5, w // 5
        out += a[x][y].to_bytes(8, "little")
    return out
