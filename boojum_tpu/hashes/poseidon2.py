"""Poseidon2 permutation (Goldilocks, t=12, x^7) — batched device + host scalar.

Algorithm per the Poseidon2 paper (eprint 2023/323), parameter-compatible with
the reference implementation (`/root/reference/src/implementations/poseidon2/
state_generic_impl.rs:222` poseidon2_permutation: pre-multiply by the external
matrix, 4 full rounds, 22 partial rounds with the internal matrix, 4 full
rounds). The external matrix is circ(2·M4, M4, M4); we evaluate it with the
shift-free add/double chain so the whole permutation is VPU-friendly modular
adds + the x^7 sbox muls, batched over an arbitrary leading leaf axis.

Sponge semantics (rate 8 / capacity 4, overwrite mode) follow
`/root/reference/src/algebraic_props/sponge.rs` so leaf/node/transcript hashing
is bit-compatible with the reference tree hasher.
"""

import jax
import jax.numpy as jnp
import numpy as np

from ..field import gl
from ..field import goldilocks as gf
from . import poseidon2_params as params

_RC = np.array(params.ALL_ROUND_CONSTANTS, dtype=np.uint64).reshape(30, 12)
_DIAG = np.array(params.M_I_DIAGONAL, dtype=np.uint64)


def _sbox7(x):
    x2 = gf.sqr(x)
    x3 = gf.mul(x2, x)
    x4 = gf.sqr(x2)
    return gf.mul(x4, x3)


def _block_m4(x0, x1, x2, x3):
    """M4 = [[5,7,1,3],[4,6,1,1],[1,3,5,7],[1,1,4,6]] via add/double chain."""
    t0 = gf.add(x0, x1)
    t1 = gf.add(x2, x3)
    t2 = gf.add(gf.double(x1), t1)
    t3 = gf.add(gf.double(x3), t0)
    t4 = gf.add(gf.double(gf.double(t1)), t3)
    t5 = gf.add(gf.double(gf.double(t0)), t2)
    t6 = gf.add(t3, t5)
    t7 = gf.add(t2, t4)
    return t6, t5, t7, t4


def _external_mds(state):
    """state (..., 12) -> circ(2*M4, M4, M4) · state."""
    cols = [state[..., i] for i in range(12)]
    blocks = []
    for b in range(3):
        blocks.append(_block_m4(*cols[4 * b : 4 * b + 4]))
    out = []
    for i in range(4):
        s = gf.add(gf.add(blocks[0][i], blocks[1][i]), blocks[2][i])
        out.append(s)
    new_cols = []
    for b in range(3):
        for i in range(4):
            new_cols.append(gf.add(blocks[b][i], out[i]))
    return jnp.stack(new_cols, axis=-1)


def _internal_mds(state):
    """M_I = all-ones + diag(d): out_i = d_i·x_i + sum_j x_j."""
    total = state[..., 0]
    for i in range(1, 12):
        total = gf.add(total, state[..., i])
    scaled = gf.mul(state, jnp.asarray(_DIAG))
    return gf.add(scaled, total[..., None])


@jax.jit
def poseidon2_permutation_xla(state: jax.Array) -> jax.Array:
    """Batched Poseidon2 permutation on (..., 12) uint64 arrays.

    Rounds run under `lax.fori_loop` (compiler-friendly control flow): the
    compiled graph is one round body per phase instead of 30 unrolled rounds,
    which keeps XLA compile time flat while the loop itself is negligible
    next to the field ops."""
    rc = jnp.asarray(_RC)

    def full_round(r, s):
        s = gf.add(s, rc[r])
        s = _sbox7(s)
        return _external_mds(s)

    def partial_round(r, s):
        el0 = _sbox7(gf.add(s[..., 0], rc[r, 0]))
        s = jnp.concatenate([el0[..., None], s[..., 1:]], axis=-1)
        return _internal_mds(s)

    state = _external_mds(state)
    state = jax.lax.fori_loop(0, 4, full_round, state)
    state = jax.lax.fori_loop(4, 26, partial_round, state)
    state = jax.lax.fori_loop(26, 30, full_round, state)
    return state


# ---------------------------------------------------------------------------
# Device sponge helpers (rate 8, cap 4, overwrite mode)
# ---------------------------------------------------------------------------


def _sponge_hash_device(values: jax.Array, permutation) -> jax.Array:
    """Overwrite-mode sponge over (..., L) -> (..., 4) for any width-12
    permutation: each full 8-chunk overwrites the rate portion then
    permutes; a trailing partial chunk is zero-padded (finalize semantics
    of the reference sponge)."""
    lead = values.shape[:-1]
    L = values.shape[-1]
    state = jnp.zeros(lead + (12,), jnp.uint64)
    full = L // 8
    # fori_loop + dynamic slice: an unrolled chunk loop would trace the
    # permutation `full` times in every graph that inlines this sponge
    # (see the pallas kernel's identical note)

    def _absorb(c, st):
        chunk = jax.lax.dynamic_slice_in_dim(values, 8 * c, 8, axis=-1)
        st = jnp.concatenate([chunk, st[..., 8:]], axis=-1)
        return permutation(st)

    if full > 0:  # fori traces the body even for a 0-trip count
        state = jax.lax.fori_loop(0, full, _absorb, state)
    rem = L - 8 * full
    if rem > 0:
        chunk = values[..., 8 * full :]
        pad = jnp.zeros(lead + (8 - rem,), jnp.uint64)
        state = jnp.concatenate([chunk, pad, state[..., 8:]], axis=-1)
        state = permutation(state)
    return state[..., :4]


@jax.jit
def leaf_hash_xla(values: jax.Array) -> jax.Array:
    """Hash (..., L) field values into (..., 4) leaf digests."""
    return _sponge_hash_device(values, poseidon2_permutation_xla)


@jax.jit
def node_hash_xla(left: jax.Array, right: jax.Array) -> jax.Array:
    """Hash two (..., 4) digests into a (..., 4) parent digest."""
    state = jnp.concatenate(
        [left, right, jnp.zeros(left.shape[:-1] + (4,), jnp.uint64)], axis=-1
    )
    return poseidon2_permutation_xla(state)[..., :4]


# ---------------------------------------------------------------------------
# Limb-plane forms (ISSUE 10): the SAME sponge semantics over (lo, hi) u32
# plane pairs in the u64 layouts — the resident prover's hashing never
# leaves the plane representation. The XLA bodies reuse the fused kernel's
# limb round functions (pallas_poseidon2._permutation_planes_stacked) as
# plain jnp, so there is exactly one limb implementation of the rounds.
# ---------------------------------------------------------------------------


@jax.jit
def poseidon2_permutation_planes_xla(state_p):
    """Batched permutation on (..., 12) limb planes (XLA path)."""
    from . import pallas_poseidon2 as pp2

    rc = jnp.asarray(pp2.rc_diag_table())
    lo = jnp.moveaxis(state_p[0], -1, 0)
    hi = jnp.moveaxis(state_p[1], -1, 0)
    olo, ohi = pp2._permutation_planes_stacked(rc, lo, hi)
    return jnp.moveaxis(olo, 0, -1), jnp.moveaxis(ohi, 0, -1)


def _sponge_hash_planes_device(values_p, permutation_p):
    """Overwrite-mode sponge over (..., L) planes -> (..., 4) planes
    (the `_sponge_hash_device` twin, same chunk/finalize semantics)."""
    vlo, vhi = values_p
    lead = vlo.shape[:-1]
    L = vlo.shape[-1]
    state = (
        jnp.zeros(lead + (12,), jnp.uint32),
        jnp.zeros(lead + (12,), jnp.uint32),
    )
    full = L // 8

    def _absorb(c, st):
        clo = jax.lax.dynamic_slice_in_dim(vlo, 8 * c, 8, axis=-1)
        chi = jax.lax.dynamic_slice_in_dim(vhi, 8 * c, 8, axis=-1)
        st = (
            jnp.concatenate([clo, st[0][..., 8:]], axis=-1),
            jnp.concatenate([chi, st[1][..., 8:]], axis=-1),
        )
        return permutation_p(st)

    if full > 0:
        state = jax.lax.fori_loop(0, full, _absorb, state)
    rem = L - 8 * full
    if rem > 0:
        pad = jnp.zeros(lead + (8 - rem,), jnp.uint32)
        state = (
            jnp.concatenate(
                [vlo[..., 8 * full :], pad, state[0][..., 8:]], axis=-1
            ),
            jnp.concatenate(
                [vhi[..., 8 * full :], pad, state[1][..., 8:]], axis=-1
            ),
        )
        state = permutation_p(state)
    return state[0][..., :4], state[1][..., :4]


@jax.jit
def leaf_hash_planes_xla(values_p):
    return _sponge_hash_planes_device(
        values_p, poseidon2_permutation_planes_xla
    )


@jax.jit
def node_hash_planes_xla(left_p, right_p):
    z = jnp.zeros(left_p[0].shape[:-1] + (4,), jnp.uint32)
    state = (
        jnp.concatenate([left_p[0], right_p[0], z], axis=-1),
        jnp.concatenate([left_p[1], right_p[1], z], axis=-1),
    )
    out = poseidon2_permutation_planes_xla(state)
    return out[0][..., :4], out[1][..., :4]


def poseidon2_permutation_planes(state_p):
    """Plane twin of `poseidon2_permutation` (fused kernel on TPU)."""
    if state_p[0].ndim == 2 and _pallas_ready(state_p[0].shape[0]):
        from . import pallas_poseidon2 as pp2

        return pp2.permutation_planes(state_p)
    return poseidon2_permutation_planes_xla(state_p)


def leaf_hash_planes(values_p):
    """Plane twin of `leaf_hash`: (N, L) planes -> (N, 4) digest planes."""
    vlo = values_p[0]
    if (
        vlo.ndim == 2
        and vlo.shape[1] <= 1024
        and _pallas_ready(vlo.shape[0])
    ):
        from . import pallas_poseidon2 as pp2

        return pp2.sponge_hash_planes(values_p)
    return leaf_hash_planes_xla(values_p)


def node_hash_planes(left_p, right_p):
    """Plane twin of `node_hash`."""
    if left_p[0].ndim == 2 and _pallas_ready(left_p[0].shape[0]):
        from . import pallas_poseidon2 as pp2

        return pp2.sponge_hash_planes(
            (
                jnp.concatenate([left_p[0], right_p[0]], axis=-1),
                jnp.concatenate([left_p[1], right_p[1]], axis=-1),
            )
        )
    return node_hash_planes_xla(left_p, right_p)


# ---------------------------------------------------------------------------
# Dispatchers: fused Pallas kernels on TPU, XLA everywhere else. Results are
# bit-identical (tests/test_pallas_kernels.py asserts parity).
# ---------------------------------------------------------------------------


def _pallas_ready(n: int) -> bool:
    from ..utils.pallas_util import pallas_enabled

    if not pallas_enabled():
        return False
    from . import pallas_poseidon2 as pp2

    return pp2.batch_fits(n)


def poseidon2_permutation(state: jax.Array) -> jax.Array:
    """Batched Poseidon2 permutation on (..., 12) uint64 arrays."""
    if state.ndim == 2 and _pallas_ready(state.shape[0]):
        from . import pallas_poseidon2 as pp2

        return pp2.permutation(state)
    return poseidon2_permutation_xla(state)


def leaf_hash(values: jax.Array) -> jax.Array:
    """Hash (..., L) field values into (..., 4) leaf digests."""
    # width cap: beyond ~1024 columns the kernel's minimum (8-row) tile no
    # longer fits the raised VMEM budget; such commits keep the XLA sponge
    if (
        values.ndim == 2
        and values.shape[1] <= 1024
        and _pallas_ready(values.shape[0])
    ):
        from . import pallas_poseidon2 as pp2

        return pp2.sponge_hash(values)
    return leaf_hash_xla(values)


def node_hash(left: jax.Array, right: jax.Array) -> jax.Array:
    """Hash two (..., 4) digests into a (..., 4) parent digest."""
    if left.ndim == 2 and _pallas_ready(left.shape[0]):
        from . import pallas_poseidon2 as pp2

        return pp2.sponge_hash(jnp.concatenate([left, right], axis=-1))
    return node_hash_xla(left, right)


# ---------------------------------------------------------------------------
# Host scalar mirror (python ints) — transcript & proof verification
# ---------------------------------------------------------------------------


def _sbox7_s(x):
    x2 = gl.sqr(x)
    x3 = gl.mul(x2, x)
    return gl.mul(gl.sqr(x2), x3)


def _block_m4_s(x0, x1, x2, x3):
    t0 = gl.add(x0, x1)
    t1 = gl.add(x2, x3)
    t2 = gl.add(gl.add(x1, x1), t1)
    t3 = gl.add(gl.add(x3, x3), t0)
    t4 = gl.add(gl.add(gl.add(t1, t1), gl.add(t1, t1)), t3)
    t5 = gl.add(gl.add(gl.add(t0, t0), gl.add(t0, t0)), t2)
    return gl.add(t3, t5), t5, gl.add(t2, t4), t4


def _external_mds_s(s):
    blocks = [_block_m4_s(*s[4 * b : 4 * b + 4]) for b in range(3)]
    sums = [
        gl.add(gl.add(blocks[0][i], blocks[1][i]), blocks[2][i]) for i in range(4)
    ]
    return [gl.add(blocks[b][i], sums[i]) for b in range(3) for i in range(4)]


def _internal_mds_s(s):
    total = 0
    for v in s:
        total = gl.add(total, v)
    return [gl.add(gl.mul(s[i], params.M_I_DIAGONAL[i]), total) for i in range(12)]


def poseidon2_permutation_host(state: list) -> list:
    s = _external_mds_s(list(state))
    for r in range(4):
        s = [gl.add(v, int(_RC[r, i])) for i, v in enumerate(s)]
        s = [_sbox7_s(v) for v in s]
        s = _external_mds_s(s)
    for r in range(4, 26):
        s[0] = _sbox7_s(gl.add(s[0], int(_RC[r, 0])))
        s = _internal_mds_s(s)
    for r in range(26, 30):
        s = [gl.add(v, int(_RC[r, i])) for i, v in enumerate(s)]
        s = [_sbox7_s(v) for v in s]
        s = _external_mds_s(s)
    return s


class Poseidon2SpongeHost:
    """Overwrite-mode sponge over python ints (transcripts, path
    verification). Subclasses swap the permutation via _PERMUTATION."""

    RATE = 8
    CAPACITY = 4
    _PERMUTATION = staticmethod(poseidon2_permutation_host)

    def __init__(self):
        self.state = [0] * 12
        self.buffer = []

    def absorb(self, values):
        self.buffer.extend(int(v) for v in values)
        while len(self.buffer) >= 8:
            chunk, self.buffer = self.buffer[:8], self.buffer[8:]
            self.state[:8] = chunk
            self.state = self._PERMUTATION(self.state)

    def finalize(self, n=4):
        if self.buffer:
            self.state[: len(self.buffer)] = self.buffer
            for i in range(len(self.buffer), 8):
                self.state[i] = 0
            self.state = self._PERMUTATION(self.state)
            self.buffer = []
        return self.state[:n]

    @classmethod
    def hash_leaf(cls, values, n=4):
        sp = cls()
        sp.absorb(values)
        return sp.finalize(n)

    @classmethod
    def hash_node(cls, left, right):
        sp = cls()
        sp.absorb(list(left) + list(right))
        return sp.finalize(4)
