"""Poseidon/Poseidon2 round constants and parameters (Goldilocks, width 12).

These are public protocol parameters, identical to Plonky2's and the
reference's (/root/reference/src/implementations/poseidon_goldilocks_params.rs)
so that sponges, Merkle caps, and transcripts are cross-compatible.
"""

RATE = 8
CAPACITY = 4
STATE_WIDTH = 12
HALF_NUM_FULL_ROUNDS = 4
NUM_FULL_ROUNDS_TOTAL = 8
NUM_PARTIAL_ROUNDS = 22
TOTAL_NUM_ROUNDS = 30

# Poseidon2 internal-matrix diagonal (M_I = all-ones + diag(d)); entries are
# powers of two (reference: state_generic_impl.rs:73 M_I_DIAGONAL_ELEMENTS_MINUS_ONE).
M_I_DIAGONAL = [
    1 << 4, 1 << 14, 1 << 11, 1 << 8, 1 << 0, 1 << 5,
    1 << 2, 1 << 9, 1 << 13, 1 << 6, 1 << 3, 1 << 12,
]

# 30 rounds x 12 lanes of round constants (Plonky2-compatible).
ALL_ROUND_CONSTANTS = [
    0xb585f767417ee042, 0x7746a55f77c10331, 0xb2fb0d321d356f7a, 0x0f6760a486f1621f,
    0xe10d6666b36abcdf, 0x8cae14cb455cc50b, 0xd438539cf2cee334, 0xef781c7d4c1fd8b4,
    0xcdc4a23a0aca4b1f, 0x277fa208d07b52e3, 0xe17653a300493d38, 0xc54302f27c287dc1,
    0x8628782231d47d10, 0x59cd1a8a690b49f2, 0xc3b919ad9efec0b0, 0xa484c4c637641d97,
    0x308bbd23f191398b, 0x6e4a40c1bf713cf1, 0x9a2eedb7510414fb, 0xe360c6e111c2c63b,
    0xd5c771901d4d89aa, 0xc35eae076e7d6b2f, 0x849c2656d0a09cad, 0xc0572c8c5cf1df2b,
    0xe9fa634a883b8bf3, 0xf56f6d4900fb1fdd, 0xf7d713e872a72a1b, 0x8297132b6ba47612,
    0xad6805e12ee8af1c, 0xac51d9f6485c22b9, 0x502ad7dc3bd56bf8, 0x57a1550c3761c577,
    0x66bbd30e99d311da, 0x0da2abef5e948f87, 0xf0612750443f8e94, 0x28b8ec3afb937d8c,
    0x92a756e6be54ca18, 0x70e741ec304e925d, 0x019d5ee2b037c59f, 0x6f6f2ed7a30707d1,
    0x7cf416d01e8c169c, 0x61df517bb17617df, 0x85dc499b4c67dbaa, 0x4b959b48dad27b23,
    0xe8be3e5e0dd779a0, 0xf5c0bc1e525ed8e6, 0x40b12cbf263cf853, 0xa637093f13e2ea3c,
    0x3cc3f89232e3b0c8, 0x2e479dc16bfe86c0, 0x6f49de07d6d39469, 0x213ce7beecc232de,
    0x5b043134851fc00a, 0xa2de45784a861506, 0x7103aaf97bed8dd5, 0x5326fc0dbb88a147,
    0xa9ceb750364cb77a, 0x27f8ec88cc9e991f, 0xfceb4fda8c93fb83, 0xfac6ff13b45b260e,
    0x7131aa455813380b, 0x93510360d5d68119, 0xad535b24fb96e3db, 0x4627f5c6b7efc045,
    0x645cf794e4da78a9, 0x241c70ed1ac2877f, 0xacb8e076b009e825, 0x3737e9db6477bd9d,
    0xe7ea5e344cd688ed, 0x90dee4a009214640, 0xd1b1edf7c77e74af, 0x0b65481bab42158e,
    0x99ad1aab4b4fe3e7, 0x438a7c91f1a360cd, 0xb60de3bd159088bf, 0xc99cab6b47a3e3bb,
    0x69a5ed92d5677cef, 0x5e7b329c482a9396, 0x5fc0ac0829f893c9, 0x32db82924fb757ea,
    0x0ade699c5cf24145, 0x7cc5583b46d7b5bb, 0x85df9ed31bf8abcb, 0x6604df501ad4de64,
    0xeb84f60941611aec, 0xda60883523989bd4, 0x8f97fe40bf3470bf, 0xa93f485ce0ff2b32,
    0x6704e8eebc2afb4b, 0xcee3e9ac788ad755, 0x510d0e66062a270d, 0xf6323f48d74634a0,
    0x0b508cdf04990c90, 0xf241708a4ef7ddf9, 0x60e75c28bb368f82, 0xa6217d8c3f0f9989,
    0x7159cd30f5435b53, 0x839b4e8fe97ec79f, 0x0d3f3e5e885db625, 0x8f7d83be1daea54b,
    0x780f22441e8dbc04, 0xeb9158465aedacd3, 0xd19e120d826c1b6c, 0x016ee53a7f007110,
    0xcb5fd54ed22dd1ca, 0xacb84178c58de144, 0x9c22190c2c463227, 0x5d693c1bcc98406d,
    0xdcef0798235f321a, 0x3d639263f55e0b1e, 0xe273fd977edb8fda, 0x418f027049d10fe7,
    0x8c25fda3f253a284, 0x2cbaed4dc25a884e, 0x5f58e6aff78dc2af, 0x284650ac6fb9d206,
    0x635b337f1391c13c, 0x9f9a036f1ac6361f, 0xb93e260cff6747b4, 0xb0a7eae8c7272e33,
    0xd0762cbce7da0a9f, 0x34c6efb829c754d6, 0x40bf0ab6166855c1, 0xb6b570fccc46a242,
    0x5a27b90055549545, 0xb1a5b166048b306f, 0x8722e0ad24f1006d, 0x788ee3b3b315049a,
    0x14a726661e5b0351, 0x98b7672fe1c3f13e, 0xbb93ae77bdc3aa8f, 0x28fd3b04756fc222,
    0x30a46805a86d7109, 0x337dc00c7844a0e7, 0xd5eca245253c861b, 0x77626382990d8546,
    0xc1e434bf33c3ae7a, 0x0299351a54dbf35e, 0xb2d456e4fb620184, 0x3e9ed1fdc00265ea,
    0x2972a92bb672e8db, 0x20216dd789f333ec, 0xadffe8cf746494a1, 0x1c4dbb1c5889d420,
    0x15a16a8a8c9972f5, 0x388a128b98960e26, 0x2300e5d6ca3e5589, 0x2f63aa865c9ceb9f,
    0xf1c36ce8d894420f, 0x271811252953f84a, 0xe5840293d5466a8e, 0x4d9bbc3e24e5f20e,
    0xea35bc29cfa2794b, 0x18e21b4bf59e2d28, 0x1e3b9fc632ef6adb, 0x25d643627a05e678,
    0x5a3f1bb1ecb63263, 0xdb7f0238ca031e31, 0xb462065960bfc4c4, 0x49c24ae463c280f4,
    0xd793862c6f7b901a, 0xaadd1106bdce475e, 0xc43b6e0eed8ad58f, 0xe29024c1f2060cb7,
    0x5e50c2755efbe17a, 0x10383f20ac183625, 0x38e8ee9d8a8a435d, 0xdd511837bcc52452,
    0x7750059861a7da6a, 0x86ab99b518d1dbef, 0xb1204f608ccfe33b, 0xef61ac84d8dfca49,
    0x1bbcd90f1f4eff36, 0x0cd1dabd9be9850a, 0x11a3ae5bf354bb11, 0xf755bfef11bb5516,
    0xa3b832506e2f3adb, 0x516306f4b617e6ba, 0xddb4ac4a2aeead3a, 0x64bb6dec62af4430,
    0xf9cc95c29895a152, 0x08d37f75632771b9, 0xeec49b619cee6b56, 0xf143933b56b3711a,
    0xe4c5dd82b9f6570c, 0xe7ad775756eefdc4, 0x92c2318bc834ef78, 0x739c25f93007aa0a,
    0x5636caca1725f788, 0xdd8f909af47cd0b6, 0xc6401fe16bc24d4e, 0x8ad97b342e6b3a3c,
    0x0c49366bb7be8ce2, 0x0784d3d2f4b39fb5, 0x530fb67ec5d77a58, 0x41049229b8221f3b,
    0x139542347cb606a3, 0x9cb0bd5ee62e6438, 0x02e3f615c4d3054a, 0x985d4f4adefb64a0,
    0x775b9feb32053cde, 0x304265a64d6c1ba6, 0x593664c3be7acd42, 0x4f0a2e5fd2bd6718,
    0xdd611f10619bf1da, 0xd8185f9b3e74f9a4, 0xef87139d126ec3b3, 0x3ba71336dd67f99b,
    0x7d3a455d8d808091, 0x660d32e15cbdecc7, 0x297a863f5af2b9ff, 0x90e0a736e6b434df,
    0x549f80ce7a12182e, 0x0f73b29235fb5b84, 0x16bf1f74056e3a01, 0x6d1f5a593019a39f,
    0x02ff876fa73f6305, 0xc5cb72a2fb9a5bd7, 0x8470f39d674dfaa3, 0x25abb3f1e41aea30,
    0x23eb8cc9c32951c7, 0xd687ba56242ac4ea, 0xda8d9e915d2de6b7, 0xe3cbdc7d938d8f1e,
    0xb9a8c9b4001efad6, 0xc0d28a5c64f2285c, 0x45d7ac9b878575b8, 0xeeb76e39d8da283e,
    0x3d06c8bd2fc7daac, 0x9c9c9820c13589f5, 0x65700b51db40bae3, 0x911f451579044242,
    0x7ae6849ff1fee8cc, 0x3bb340ebba896ae5, 0xb46e9d8bb71f0b4b, 0x8dcf22f9e1bde2a3,
    0x77bdaeda8cc55427, 0xf19e400ababa0e12, 0xc368a34939eb5c7f, 0x9ef1cd612c03bc5e,
    0xe89cd8553b94bbd8, 0x5cd377dcb4550713, 0xa7b0fb78cd4c5665, 0x7684403ef76c7128,
    0x5fa3f06f79c4f483, 0x8df57ac159dbade6, 0x2db01efa321b2625, 0x54846de4cfd58cb6,
    0xba674538aa20f5cd, 0x541d4963699f9777, 0xe9096784dadaa548, 0xdfe8992458bf85ff,
    0xece5a71e74a35593, 0x5ff98fd5ff1d14fd, 0x83e89419524c06e1, 0x5922040b6ef03286,
    0xf97d750eab002858, 0x5080d4c2dba7b3ec, 0xa7de115ba038b508, 0x6a9242acb5f37ec0,
    0xf7856ef865619ed0, 0x2265fc930dbd7a89, 0x17dfc8e5022c723b, 0x9001a64248f2d676,
    0x90004c13b0b8b50e, 0xb932b7cfc63485b0, 0xa0b1df81fd4c2bc5, 0x8ef1dd26b594c383,
    0x0541a4f9d20ba562, 0x9e611061be0a3c5b, 0xb3767e80e1e1624a, 0x0098d57820a88c6b,
    0x31d191cd71e01691, 0x410fefafbf90a57a, 0xbdf8f2433633aea8, 0x9e8cd55b9cc11c28,
    0xde122bec4acb869f, 0x4d001fd5b0b03314, 0xca66370067416209, 0x2f2339d6399888c6,
    0x6d1a7918f7c98a13, 0xdf9a493995f688f3, 0xebc2151f4ded22ca, 0x03cc2ba8a2bab82f,
    0xd341d03844ad9a9b, 0x387cb5d273ab3f58, 0xbba2515f74a7a221, 0x7248fe7737f37d9c,
    0x4d61e56a7437f6b9, 0x262e963c9e54bef8, 0x59e89b097477d296, 0x055d5b52b9e47452,
    0x82b27eb36e430708, 0xd30094caf3080f94, 0xcf5cb38227c2a3be, 0xfeed4db701262c7c,
    0x41703f5391dd0154, 0x5eeea9412666f57b, 0x4cd1f1b196abdbc4, 0x4a20358594b3662b,
    0x1478d361e4b47c26, 0x6f02dc0801d2c79f, 0x296a202eeb03c4b6, 0x2afd6799aec20c38,
    0x7acfd96f3050383d, 0x6798ba0c380dfdd3, 0x34c6f57b3de02c88, 0x5736e1baf82eb8a0,
    0x20057d2a0e58b8de, 0x3dea5bd5eb6e1404, 0x16e50d89874a6a98, 0x29bff3eccbfba19a,
    0x475cd3207974793c, 0x18a42105cde34cfa, 0x023e7414b0618331, 0x151471081b52594b,
    0xe4a3dff23bdeb0f3, 0x01a8d1a588c232ef, 0x11b4c74ee221d621, 0xe587cc0dce129c8c,
    0x1ff7327025a65080, 0x594e29c44b8602b1, 0xf6f31db1f5a56fd3, 0xc02ac5e4c7258a5e,
    0xe70201e9c5dc598f, 0x6f90ff3b9b3560b2, 0x42747a7262faf016, 0xd1f507e496927d26,
    0x1c86d265fdd24cd9, 0x3996ce73f6b5266e, 0x8e7fba02d68a061e, 0xba0dec71548b7546,
    0x9e9cbd785b8d8f40, 0xdae86459f6b3828c, 0xdebe08541314f71d, 0xa49229d29501358f,
    0x7be5ba0010c4df7c, 0xa3c95eaf09ecc39c, 0x0230bca8f5d457cd, 0x4135c2bedc68cdf9,
    0x166fc0cc4d5b20cc, 0x3762b59aa3236e6e, 0xe8928a4ceed163d2, 0x2a440b51b71223d9,
    0x80cefd2bb5f48e46, 0xbb9879c738328b71, 0x6e7c8f1ab47cced0, 0x164bb2de257ffc0a,
    0xf3c12fe5b800ea30, 0x40b9e92309e8c7e1, 0x551f5b0fe3b8d017, 0x25032aa7d4fc7aba,
    0xaaed340795de0a0a, 0x8ffd96bc38c8ba0f, 0x70fc91eb8aa58833, 0x7f795e2a97566d73,
    0x4543d9df72c4831d, 0xf172d73e69f20739, 0xdfd1c4ff1eb3d868, 0xbc8dfb62d26376f7,
]

ROUND_CONSTANTS_PER_ROUND = [
    ALL_ROUND_CONSTANTS[r * 12 : (r + 1) * 12] for r in range(TOTAL_NUM_ROUNDS)
]


# ---------------------------------------------------------------------------
# Poseidon2 BabyBear, width 16 (ISSUE 19 — the BOOJUM_TPU_FIELD=babybear
# backend's sponge). p = 2^31 - 2^27 + 1; x^7 sbox (gcd(7, p-1) = 1);
# external matrix circ(2*M4, M4, M4, M4); internal all-ones + diag; 4 + 13
# + 4 rounds (width-16 BabyBear round counts per the Poseidon2 paper's
# 128-bit instantiations). Unlike the Goldilocks table above there is no
# upstream implementation these must be bit-compatible with — the BabyBear
# leg defines its own protocol, verified by its own verifier — so the
# constants are PROTOCOL-DEFINING here: derived once by deterministic
# bias-free rejection sampling from blake2b(domain-tag ‖ counter), which
# both the device kernels and the NumPy reference prover read from this
# module. Changing them is a protocol break, same as editing the Goldilocks
# table.
# ---------------------------------------------------------------------------

BB_P = (1 << 31) - (1 << 27) + 1  # 2013265921
BB_STATE_WIDTH = 16
BB_RATE = 8
BB_CAPACITY = 8
BB_HALF_NUM_FULL_ROUNDS = 4
BB_NUM_FULL_ROUNDS_TOTAL = 8
BB_NUM_PARTIAL_ROUNDS = 13
BB_TOTAL_NUM_ROUNDS = 21


def _bb_sample(tag: str, count: int) -> list:
    """Deterministic bias-free field elements: 4-byte LE words from a
    blake2b counter stream, rejecting w >= 2p (floor(2^32/p) = 2, so
    accepting w < 2p and folding w mod p is exactly uniform)."""
    import hashlib

    out: list = []
    ctr = 0
    bound = 2 * BB_P
    while len(out) < count:
        h = hashlib.blake2b(
            f"boojum_tpu.poseidon2.babybear.{tag}.{ctr}".encode(),
            digest_size=32,
        ).digest()
        ctr += 1
        for i in range(0, 32, 4):
            w = int.from_bytes(h[i : i + 4], "little")
            if w < bound:
                out.append(w % BB_P)
                if len(out) == count:
                    break
    return out


# 8 full rounds x 16 lanes; partial rounds add a constant to lane 0 only.
BB_EXTERNAL_ROUND_CONSTANTS = [
    _bb_sample("external", BB_NUM_FULL_ROUNDS_TOTAL * BB_STATE_WIDTH)[
        r * BB_STATE_WIDTH : (r + 1) * BB_STATE_WIDTH
    ]
    for r in range(BB_NUM_FULL_ROUNDS_TOTAL)
]
BB_INTERNAL_ROUND_CONSTANTS = _bb_sample("internal", BB_NUM_PARTIAL_ROUNDS)

# Internal-matrix diagonal (M_I = all-ones + diag(d)); sampled from the
# same stream, with d_i != 0 and d_i != p-1 enforced (either would zero a
# diagonal term of M_I - J + I's spectrum trivially).
BB_M_I_DIAGONAL = [
    d for d in _bb_sample("diagonal", 4 * BB_STATE_WIDTH)
    if d not in (0, BB_P - 1)
][:BB_STATE_WIDTH]
assert len(BB_M_I_DIAGONAL) == BB_STATE_WIDTH
