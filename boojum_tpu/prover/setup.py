"""Setup / verification-key generation.

Counterpart of `/root/reference/src/cs/implementations/setup.rs`
(`create_permutation_polys` :401, `compute_selectors_and_constants_placement`
:486, `create_constant_setup_polys` :710, `get_full_setup` :1255).

TPU-first differences:
- sigma construction is a single vectorized numpy pass (stable argsort over
  the flattened placement + per-group rotation), not a per-cell cycle walk;
- selector encoding uses a balanced binary tree over the used gate set
  (variable-depth optimization as in the reference's TreeNode comes later);
  the path bits land in the leading constant columns, gate constants follow;
- all setup polynomials are low-degree-extended and Merkle-committed on
  device in one batch.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax.numpy as jnp
import numpy as np

from ..field import gl
from ..merkle import MerkleTreeWithCap
from ..ntt import lde_from_monomial, monomial_from_values


def build_selector_tree(gates):
    """Degree-aware selector placement (reference setup.rs:486 TreeNode
    optimizer): high-degree / constant-hungry gates get short selector
    paths. Returns (tree, per-gate paths as 0/1 lists)."""
    from ..cs.selector_tree import GateDescription, compute_selector_placement

    descriptions = [
        GateDescription(
            gate_idx=i,
            num_constants=g.num_constants,
            degree=g.max_degree,
            needs_selector=True,
            is_lookup=getattr(g, "is_lookup_marker", False),
        )
        for i, g in enumerate(gates)
    ]
    tree = compute_selector_placement(descriptions)
    paths = []
    for i in range(len(gates)):
        p = tree.output_placement(i)
        assert p is not None, f"gate {i} missing from selector tree"
        paths.append([int(b) for b in p])
    return tree, paths


def non_residues_for_copy_permutation(num_cols: int) -> list[int]:
    """Distinct coset representatives k_col = g^col (g the multiplicative
    generator); k_0 = 1 (reference utils.rs non-residues)."""
    out = [1]
    for _ in range(1, num_cols):
        out.append(gl.mul(out[-1], gl.MULTIPLICATIVE_GENERATOR))
    return out


def non_residues_for_copy_permutation_bb(num_cols: int) -> list[int]:
    """The BabyBear k_col = 31^col family (31 generates the full
    multiplicative group, so the cosets are distinct up to huge widths)."""
    from ..field import babybear as bb

    out = [1]
    for _ in range(1, num_cols):
        out.append(bb.mul_s(out[-1], 31))
    return out


def _sigma_cells(copy_placement: np.ndarray, trace_len: int) -> np.ndarray:
    """Field-independent half of the permutation-poly construction: the
    flat cell -> next-cell-in-cycle map (vacant cells fixed points)."""
    C, n = copy_placement.shape
    assert n == trace_len
    pl = copy_placement.reshape(-1)
    N = C * n
    order = np.argsort(pl, kind="stable")
    sorted_pl = pl[order]
    pos = np.arange(N)
    same_next = np.zeros(N, dtype=bool)
    same_next[:-1] = sorted_pl[1:] == sorted_pl[:-1]
    # group starts
    first = np.ones(N, dtype=bool)
    first[1:] = sorted_pl[1:] != sorted_pl[:-1]
    group_id = np.cumsum(first) - 1
    start_positions = np.nonzero(first)[0]
    starts_per_pos = start_positions[group_id]
    nxt = np.where(same_next, pos + 1, starts_per_pos)
    sigma_cell = np.empty(N, dtype=np.int64)
    sigma_cell[order] = order[nxt]
    # vacant cells: identity
    vacant = pl < 0
    sigma_cell[vacant] = np.nonzero(vacant)[0]
    return sigma_cell


def compute_sigma_values(
    copy_placement: np.ndarray, trace_len: int, non_residues=None
):
    """Vectorized permutation-polynomial construction.

    copy_placement: (C, n) int64 of place ids (-1 vacant). Cells holding the
    same variable form a cycle; sigma maps each cell to the next one in its
    cycle (vacant cells are fixed points). Returns (C, n) uint64 of
    sigma_col(w^row) = k_{col'} * w^{row'}.

    non_residues: per-column coset representatives k_col; defaults to this
    framework's g^col family (the reference-dialect prover passes the
    reference's small-QNR family instead).
    """
    C, n = copy_placement.shape
    sigma_cell = _sigma_cells(copy_placement, trace_len)
    # encode: cell -> k_col * w^row
    omega = gl.omega(n.bit_length() - 1)
    w_pows = np.zeros(n, dtype=np.uint64)
    cur = 1
    for i in range(n):
        w_pows[i] = cur
        cur = gl.mul(cur, omega)
    if non_residues is None:
        non_residues = non_residues_for_copy_permutation(C)
    ks = np.array([int(k) for k in non_residues], dtype=np.uint64)
    tgt_col = (sigma_cell // n).astype(np.int64)
    tgt_row = (sigma_cell % n).astype(np.int64)
    vals = _np_mod_mul(ks[tgt_col], w_pows[tgt_row])
    return vals.reshape(C, n)


def compute_sigma_values_bb(
    copy_placement: np.ndarray, trace_len: int, non_residues=None
):
    """BabyBear twin of compute_sigma_values: same vectorized cycle walk,
    encode over p = 2^31 - 2^27 + 1 with the 31^col non-residue family.
    Returns (C, n) uint32."""
    from ..field import babybear as bb

    C, n = copy_placement.shape
    sigma_cell = _sigma_cells(copy_placement, trace_len)
    w_pows = bb.powers_np(bb.omega(n.bit_length() - 1), n)
    if non_residues is None:
        non_residues = non_residues_for_copy_permutation_bb(C)
    ks = np.array([int(k) for k in non_residues], dtype=np.uint32)
    tgt_col = (sigma_cell // n).astype(np.int64)
    tgt_row = (sigma_cell % n).astype(np.int64)
    vals = bb.mul_np(ks[tgt_col], w_pows[tgt_row])
    return vals.reshape(C, n)


def _np_mod_mul(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Vectorized Goldilocks multiply on host uint64 arrays (the same
    EPSILON-reduction as field/goldilocks.py, in numpy — the python-object
    bigint path this replaces cost ~20 minutes for a 92x2^20 sigma)."""
    M32 = np.uint64(0xFFFFFFFF)
    a_lo = a & M32
    a_hi = a >> np.uint64(32)
    b_lo = b & M32
    b_hi = b >> np.uint64(32)
    with np.errstate(over="ignore"):
        ll = a_lo * b_lo
        lh = a_lo * b_hi
        hl = a_hi * b_lo
        hh = a_hi * b_hi
        mid = lh + hl
        mid_c = (mid < lh).astype(np.uint64)
        lo = ll + (mid << np.uint64(32))
        lo_c = (lo < ll).astype(np.uint64)
        hi = hh + (mid >> np.uint64(32)) + (mid_c << np.uint64(32)) + lo_c
        # reduce128: x = lo - hi_hi + hi_lo * EPSILON
        hi_hi = hi >> np.uint64(32)
        hi_lo = hi & M32
        t0 = lo - hi_hi
        t0 = np.where(lo < hi_hi, t0 - M32, t0)
        t1 = hi_lo * M32
        t2 = t0 + t1
        res = np.where(t2 < t0, t2 + M32, t2)
        return np.where(res >= np.uint64(gl.P), res - np.uint64(gl.P), res)


def build_constant_columns(assembly, selector_paths) -> np.ndarray:
    """(K, n) uint64 constant columns with variable-depth selector layout:
    on a row holding gate g, columns [0, len(path_g)) carry g's selector
    path bits and g's own constants start at column len(path_g) (reference
    create_constant_setup_polys, setup.rs:710)."""
    n = assembly.trace_len
    K = assembly.geometry.num_constant_columns
    for gid, g in enumerate(assembly.gates):
        used = len(selector_paths[gid]) + g.num_constants
        assert used <= K, (
            f"gate {g.name}: selector depth {len(selector_paths[gid])} + "
            f"constants {g.num_constants} exceed {K} constant columns"
        )
    cols = np.zeros((K, n), dtype=np.uint64)
    rg = assembly.row_gate
    max_depth = max((len(p) for p in selector_paths), default=0)
    if max_depth:
        # bits[g, d] = path bit (rows of shallower gates keep zeros beyond
        # their own path, which their selector product never reads)
        bits = np.zeros((len(selector_paths), max_depth), dtype=np.uint64)
        for gid, p in enumerate(selector_paths):
            bits[gid, : len(p)] = p
        cols[:max_depth, :] = bits[rg].T
    offsets = np.array(
        [len(p) for p in selector_paths], dtype=np.int64
    )
    for row, consts in assembly.gate_constants.items():
        off = int(offsets[rg[row]])
        for i, c in enumerate(consts):
            cols[off + i, row] = c
    return cols


@dataclass
class VerificationKey:
    """Fixed parameters + setup commitment (reference verifier.rs:31)."""

    geometry: object
    trace_len: int
    fri_lde_factor: int
    cap_size: int
    num_queries: int
    pow_bits: int
    fri_final_degree: int
    gate_names: list
    selector_paths: list
    public_input_locations: list  # [(col, row)]
    setup_merkle_cap: list
    num_copy_cols: int
    num_wit_cols: int
    lookup_params: object = None
    num_lookup_tables: int = 0
    fri_folding_schedule: list | None = None
    # quotient chunk count / sweep rate; None (legacy keys) = fri_lde_factor
    quotient_degree: int | None = None
    # Fiat-Shamir transcript kind the proof/verifier must replay
    transcript: str = "poseidon2"

    def effective_quotient_degree(self) -> int:
        return self.quotient_degree or self.fri_lde_factor

    def to_dict(self):
        from dataclasses import asdict

        d = {
            "trace_len": self.trace_len,
            "fri_lde_factor": self.fri_lde_factor,
            "quotient_degree": self.quotient_degree,
            "transcript": self.transcript,
            "cap_size": self.cap_size,
            "num_queries": self.num_queries,
            "pow_bits": self.pow_bits,
            "fri_final_degree": self.fri_final_degree,
            "gate_names": list(self.gate_names),
            "selector_paths": [list(p) for p in self.selector_paths],
            "public_input_locations": list(self.public_input_locations),
            "setup_merkle_cap": [list(c) for c in self.setup_merkle_cap],
            "num_copy_cols": self.num_copy_cols,
            "num_wit_cols": self.num_wit_cols,
            "num_lookup_tables": self.num_lookup_tables,
            "fri_folding_schedule": (
                None
                if self.fri_folding_schedule is None
                else list(self.fri_folding_schedule)
            ),
            "lookup_params": None
            if self.lookup_params is None
            else {
                "width": self.lookup_params.width,
                "num_repetitions": self.lookup_params.num_repetitions,
                "share_table_id": self.lookup_params.share_table_id,
                "use_specialized_columns": self.lookup_params.use_specialized_columns,
            },
            "geometry": {
                "num_columns_under_copy_permutation": self.geometry.num_columns_under_copy_permutation,
                "num_witness_columns": self.geometry.num_witness_columns,
                "num_constant_columns": self.geometry.num_constant_columns,
                "max_allowed_constraint_degree": self.geometry.max_allowed_constraint_degree,
            },
        }
        return d


@dataclass
class SetupData:
    """Everything the prover needs beyond the assembly's witness."""

    vk: VerificationKey
    sigma_cols: np.ndarray  # (C, n) host
    constant_cols: np.ndarray  # (K, n) host
    setup_monomials: object  # (C+K, n) device
    setup_lde: object  # (C+K, lde, n) device, or None in streamed mode
    setup_tree: MerkleTreeWithCap
    selector_paths: list
    non_residues: list


def generate_setup(assembly, config) -> SetupData:
    """Full setup: sigmas + constants -> monomial -> LDE -> Merkle -> VK.

    Setup column order: [sigma (C_total) | constants (K, + table-id col when
    lookups are on) | stacked table columns (width+1, lookups only)].
    """
    n = assembly.trace_len
    assert config.fri_final_degree < n, (
        "fri_final_degree must be below the trace length (at least one fold)"
    )
    tree, selector_paths = build_selector_tree(assembly.gates)
    # masked-constraint degree must fit the QUOTIENT evaluation domain
    # (quotient_degree cosets) — decoupled from the commitment rate
    # fri_lde_factor, reference prover.rs:230-259 quotient_degree_from_
    # gate_terms vs proof_config.fri_lde_factor. The degree-aware tree
    # keeps high-degree gates shallow so the bound is tight.
    tree_degree, tree_constants = tree.compute_stats()
    degree_bound = max(
        tree_degree,
        assembly.geometry.max_allowed_constraint_degree + 1,
        1,
    )
    derived_q = 1 << (degree_bound - 1).bit_length()  # next power of two
    quotient_degree = config.quotient_degree or derived_q
    assert tree_degree <= quotient_degree, (
        f"selector tree degree {tree_degree} exceeds quotient_degree "
        f"{quotient_degree}"
    )
    assert tree_constants <= assembly.geometry.num_constant_columns, (
        f"selector tree needs {tree_constants} constant columns, geometry "
        f"has {assembly.geometry.num_constant_columns}"
    )
    assert (
        assembly.geometry.max_allowed_constraint_degree + 1
        <= quotient_degree
    ), "copy-permutation chunk degree exceeds quotient_degree"
    full_placement = np.concatenate(
        [assembly.copy_placement, assembly.lookup_placement], axis=0
    )
    if getattr(assembly, "field", "goldilocks") == "babybear":
        return _generate_setup_babybear(
            assembly, config, full_placement, selector_paths,
            quotient_degree,
        )
    sigma = compute_sigma_values(full_placement, n)
    consts = build_constant_columns(assembly, selector_paths)
    if assembly.lookups_enabled:
        if assembly.lookup_table_id_col is not None:
            # specialized mode: dedicated table-id constant column
            consts = np.concatenate(
                [consts, assembly.lookup_table_id_col[None, :]], axis=0
            )
        table_cols = assembly.stacked_table_columns(assembly.lookup_params.width)
        setup_cols = np.concatenate([sigma, consts, table_cols], axis=0)
    else:
        table_cols = np.zeros((0, n), dtype=np.uint64)
        setup_cols = np.concatenate([sigma, consts], axis=0)
    dev = jnp.asarray(setup_cols)
    monomials = monomial_from_values(dev)
    del dev
    from .streaming import commit_streaming, use_streamed_lde

    if use_streamed_lde(setup_cols.shape[0], n * config.fri_lde_factor):
        # beyond the footprint threshold the setup LDE is never
        # materialized: the tree commits from streamed column blocks and
        # the prover regenerates blocks from the monomials (streaming.py)
        lde = None
        tree = commit_streaming(
            monomials, config.fri_lde_factor, config.merkle_tree_cap_size
        )
    else:
        lde = lde_from_monomial(monomials, config.fri_lde_factor)
        # same shape-keyed leaf-sponge + node-stack dispatches as the
        # prover's commit pipeline, so the setup commit shares executables
        # (and the precompile warm) with the proof oracles
        from ..merkle import commit_layers_device

        tree = MerkleTreeWithCap.from_layers(
            list(commit_layers_device(lde, config.merkle_tree_cap_size)),
            config.merkle_tree_cap_size,
        )
    vk = VerificationKey(
        geometry=assembly.geometry,
        trace_len=n,
        fri_lde_factor=config.fri_lde_factor,
        quotient_degree=quotient_degree,
        transcript=getattr(config, "transcript", "poseidon2"),
        cap_size=config.merkle_tree_cap_size,
        num_queries=config.num_queries,
        pow_bits=config.pow_bits,
        fri_final_degree=config.fri_final_degree,
        gate_names=[g.name for g in assembly.gates],
        selector_paths=selector_paths,
        public_input_locations=[(c, r) for (c, r, _v) in assembly.public_inputs],
        setup_merkle_cap=tree.get_cap(),
        num_copy_cols=sigma.shape[0],
        num_wit_cols=assembly.wit_placement.shape[0],
        lookup_params=assembly.lookup_params if assembly.lookups_enabled else None,
        num_lookup_tables=len(assembly.lookup_tables),
        fri_folding_schedule=getattr(config, "fri_folding_schedule", None),
    )
    return SetupData(
        vk=vk,
        sigma_cols=sigma,
        constant_cols=consts,
        setup_monomials=monomials,
        setup_lde=lde,
        setup_tree=tree,
        selector_paths=selector_paths,
        non_residues=non_residues_for_copy_permutation(sigma.shape[0]),
    )


_BB_TRANSCRIPTS = {
    "poseidon2": "poseidon2_babybear",
    "blake2s": "blake2s_babybear",
}


def _generate_setup_babybear(
    assembly, config, full_placement, selector_paths, quotient_degree
):
    """The BabyBear setup leg (ISSUE 20): u32 sigma/constant/table columns,
    HOST numpy monomials + coset-31 LDE, and a paired-leaf Poseidon2-BB
    Merkle commit — the same oracle layout the full prover's witness
    commits use, shared verbatim by the device and numpy prover backends
    (setup-cap parity is by construction)."""
    from ..field import babybear as bb
    from ..hashes import poseidon2_bb as p2bb
    from ..ntt import bb_ntt
    from .bb_kernels import BBMerkleTree

    n = assembly.trace_len
    L = config.fri_lde_factor
    half = (n * L) // 2
    non_residues = non_residues_for_copy_permutation_bb(
        full_placement.shape[0]
    )
    sigma = compute_sigma_values_bb(full_placement, n, non_residues)
    consts = build_constant_columns(assembly, selector_paths).astype(
        np.uint32
    )
    if assembly.lookups_enabled:
        assert assembly.lookup_table_id_col is not None, (
            "babybear backend supports specialized lookup columns only"
        )
        consts = np.concatenate(
            [consts, assembly.lookup_table_id_col[None, :].astype(np.uint32)],
            axis=0,
        )
        table_cols = assembly.stacked_table_columns(
            assembly.lookup_params.width
        ).astype(np.uint32)
        setup_cols = np.concatenate([sigma, consts, table_cols], axis=0)
    else:
        setup_cols = np.concatenate([sigma, consts], axis=0)
    monomials = bb_ntt.ntt_np(setup_cols, inverse=True)
    lde = bb_ntt.lde_np(monomials, L, 31)
    paired = np.concatenate([lde[:, :half], lde[:, half:]], axis=0)
    digests = p2bb.leaf_hash_bb_np(paired.T)
    layers = [digests]
    while layers[-1].shape[0] > config.merkle_tree_cap_size:
        cur = layers[-1]
        layers.append(p2bb.node_hash_bb_np(cur[0::2], cur[1::2]))
    tree = BBMerkleTree(layers, config.merkle_tree_cap_size)
    transcript = getattr(config, "transcript", "poseidon2")
    transcript = _BB_TRANSCRIPTS.get(transcript, transcript)
    assert transcript.endswith("babybear"), (
        f"transcript {transcript} has no babybear instantiation"
    )
    vk = VerificationKey(
        geometry=assembly.geometry,
        trace_len=n,
        fri_lde_factor=L,
        quotient_degree=quotient_degree,
        transcript=transcript,
        cap_size=config.merkle_tree_cap_size,
        num_queries=config.num_queries,
        pow_bits=config.pow_bits,
        fri_final_degree=config.fri_final_degree,
        gate_names=[g.name for g in assembly.gates],
        selector_paths=selector_paths,
        public_input_locations=[
            (c, r) for (c, r, _v) in assembly.public_inputs
        ],
        setup_merkle_cap=tree.get_cap(),
        num_copy_cols=sigma.shape[0],
        num_wit_cols=assembly.wit_placement.shape[0],
        lookup_params=(
            assembly.lookup_params if assembly.lookups_enabled else None
        ),
        num_lookup_tables=len(assembly.lookup_tables),
        fri_folding_schedule=getattr(config, "fri_folding_schedule", None),
    )
    return SetupData(
        vk=vk,
        sigma_cols=sigma,
        constant_cols=consts,
        setup_monomials=monomials,
        setup_lde=lde,
        setup_tree=tree,
        selector_paths=selector_paths,
        non_residues=non_residues,
    )
