"""One-shot convenience drivers.

Counterpart of `/root/reference/src/cs/implementations/convenience.rs`:
`prove_one_shot` (:34), `prepare_base_setup_with_precomputations_and_vk`
(:82), `prove_from_precomputations` (:119), `verify_circuit` (:198).
"""

from __future__ import annotations

from .config import ProofConfig
from .prover import prove
from .setup import SetupData, generate_setup
from .verifier import verify


def prove_one_shot(cs, config: ProofConfig | None = None):
    """Synthesized CS -> (assembly, setup, proof). The CS must have been
    built with witness evaluation on."""
    config = config or ProofConfig()
    assembly = cs.into_assembly()
    setup = generate_setup(assembly, config)
    proof = prove(assembly, setup, config)
    return assembly, setup, proof


def prepare_setup_and_vk(cs, config: ProofConfig | None = None):
    """(assembly, setup) for repeated proving (reference :82)."""
    config = config or ProofConfig()
    assembly = cs.into_assembly()
    return assembly, generate_setup(assembly, config)


def prove_from_precomputations(assembly, setup: SetupData, config: ProofConfig):
    """Re-prove with existing setup (reference :119)."""
    return prove(assembly, setup, config)


def verify_circuit(vk, proof, gates) -> bool:
    """Reference :198."""
    return verify(vk, proof, gates)
