"""Streamed commit-rate LDE: bound HBM by never materializing full LDE
storages.

The reference's long-trace posture is cache-friendly blocked processing
(SURVEY §5); on an accelerator the binding constraint is HBM: at 2^20 rows
the materialized rate-L storages (witness + setup + stage-2 + quotient)
exceed the chip even at the Era commit rate. This module streams them in
column blocks straight from the (always-resident) monomials:

- commit: blocks of <= 64 columns LDE-transform, transpose to rows, and
  absorb 8 columns at a time into a CARRIED sponge state (N, 12) — the
  digest stream feeds `MerkleTreeWithCap.from_digests`, so the full
  (N, total_cols) leaf matrix never exists. Absorption order equals
  `leaf_hash` over whole rows, so trees (and proofs) are BIT-IDENTICAL to
  the materialized path.
- DEEP / query gathers: the same block generator re-evaluates each column
  block at query time (one extra LDE pass each — FLOPs traded for the
  ~4 GB of residency the materialized path pins).

Streaming engages when the committed-storage footprint would exceed
BOOJUM_TPU_STREAM_LDE bytes (default 1.5 GiB; "1" forces on, "0" off) —
small traces keep the materialized fast path.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp

from ..merkle import MerkleTreeWithCap
from ..ntt import lde_from_monomial

# columns per streamed block (a multiple of the sponge rate 8)
COL_BLOCK = 32


def stream_threshold_bytes() -> float:
    v = os.environ.get("BOOJUM_TPU_STREAM_LDE", "").strip()
    if v == "0":
        return float("inf")
    if v == "1":
        return 0.0
    if v:
        try:
            return float(v)  # explicit byte threshold
        except ValueError:
            pass
    return float(1536 << 20)


def use_streamed_lde(total_cols: int, domain_size: int) -> bool:
    return total_cols * domain_size * 8 > stream_threshold_bytes()


class MonomialSource:
    """A committed oracle's columns, represented by monomials + rate.

    Stands in for the materialized (B, L*n) flat array in the DEEP and
    query phases; `blocks()` regenerates rate-L column blocks on demand."""

    def __init__(self, mono, L: int):
        self.mono = mono
        self.L = int(L)

    @property
    def shape(self):
        return (self.mono.shape[0], self.mono.shape[-1] * self.L)

    def blocks(self, per: int = COL_BLOCK):
        B = self.mono.shape[0]
        for i in range(0, B, per):
            lde = lde_from_monomial(self.mono[i : i + per], self.L)
            yield i, lde.reshape(lde.shape[0], -1)  # (b, N)

    def column(self, i: int):
        """One column's rate-L values (N,) — for the handful of single
        columns round 5 opens at shifted points."""
        lde = lde_from_monomial(self.mono[i : i + 1], self.L)
        return lde.reshape(-1)

    def gather_rows(self, idx_dev):
        """(B, num_queries) leaf-value gather, blockwise."""
        parts = [flat[:, idx_dev] for _, flat in self.blocks()]
        return jnp.concatenate(parts, axis=0)


@jax.jit
def _sponge_absorb8(state, chunk8):
    """Overwrite-absorb 8 columns into a carried (N, 12) sponge state."""
    from ..hashes.poseidon2 import poseidon2_permutation

    st = jnp.concatenate([chunk8, state[:, 8:]], axis=-1)
    return poseidon2_permutation(st)


def streamed_leaf_digests(mono, L: int):
    """(N, 4) leaf digests of the rate-L LDE of `mono`, block-streamed.

    Traceable (plain jnp + python loops): callable inside a fused-round jit
    so the whole commit is one dispatch. Bit-identical to leaf_hash over the
    materialized (N, B) leaf matrix: full 8-column chunks absorb in order,
    the trailing partial chunk zero-pads (the sponge finalize rule)."""
    n = mono.shape[-1]
    N = n * L
    state = jnp.zeros((N, 12), jnp.uint64)
    rem = None  # (N, r < 8) trailing columns
    for _, flat in MonomialSource(mono, L).blocks():
        cols = flat.T  # (N, b)
        if rem is not None:
            cols = jnp.concatenate([rem, cols], axis=1)
            rem = None
        b = cols.shape[1]
        for k in range(b // 8):
            state = _sponge_absorb8(state, cols[:, 8 * k : 8 * k + 8])
        if b % 8:
            rem = cols[:, (b // 8) * 8 :]
    if rem is not None:
        pad = jnp.zeros((N, 8 - rem.shape[1]), jnp.uint64)
        state = _sponge_absorb8(state, jnp.concatenate([rem, pad], axis=1))
    return state[:, :4]


def streamed_leaf_digests_blocks(mono, L: int):
    """Block-DISPATCHED form of streamed_leaf_digests: bit-identical
    digests, but each COL_BLOCK column block is its own top-level jit
    keyed only on (block, n, L) — so the expensive NTT+Poseidon2 graph is
    compiled ONCE and reused across every block of every streamed oracle,
    instead of re-tracing the whole B-column absorb chain into each
    oracle's private mega-graph (the round-3 `_commit_fused` compile
    bill, ISSUE 1). The per-block dynamic_slice start rides as an array
    argument, so block index never enters a cache key.

    With BOOJUM_TPU_OVERLAP (default on) the commit is DOUBLE-BUFFERED:
    the LDE transform and the carried-sponge absorb are separate
    dispatches, and block b+1's transform is enqueued before block b's
    absorb — the transforms carry no data dependence on the sponge chain,
    so the device pipelines them instead of draining between blocks. The
    absorb order (and therefore every digest) is unchanged."""
    from ..utils.transfer import overlap_enabled

    assert COL_BLOCK % 8 == 0
    n = mono.shape[-1]
    B = mono.shape[0]
    state = jnp.zeros((n * L, 12), jnp.uint64)
    if not overlap_enabled():
        for i in range(0, B, COL_BLOCK):
            b = min(COL_BLOCK, B - i)
            blk = jax.lax.dynamic_slice_in_dim(mono, i, b, axis=0)
            state = _absorb_lde_block(state, blk, L)
        return state[:, :4]

    def _lde(i):
        b = min(COL_BLOCK, B - i)
        blk = jax.lax.dynamic_slice_in_dim(mono, i, b, axis=0)
        return _lde_block_cols(blk, L)

    return double_buffered_absorb(
        state, range(0, B, COL_BLOCK), _lde
    )[:, :4]


def double_buffered_absorb(state, starts, produce_cols, absorb=None):
    """The double-buffered absorb loop shared by the meshless streamed
    commit above and the per-chip shard_map one
    (parallel/shard_sweep.streamed_leaf_digests_sm): block b+1's leaf
    columns (an LDE — and on the mesh, its pivot collective) are enqueued
    BEFORE block b's absorb, so the device pipelines transforms against
    the serial sponge chain. `produce_cols(start)` must return the (N, b)
    leaf columns for the block at `start`; absorb order — and therefore
    every digest — is identical to the sequential loop. `absorb` swaps
    the per-block absorb kernel (the limb-resident commit passes its
    plane twin); default is the u64 `_absorb_cols`."""
    from ..utils import metrics as _metrics

    if absorb is None:
        absorb = _absorb_cols
    starts = list(starts)
    nxt = produce_cols(starts[0])
    for k in range(len(starts)):
        cols, nxt = nxt, (
            produce_cols(starts[k + 1]) if k + 1 < len(starts) else None
        )
        _metrics.count("stream.double_buffered_blocks")
        state = absorb(state, cols)
    return state


from functools import partial as _partial


@_partial(jax.jit, static_argnums=(1,))
def _lde_block_cols(mono_blk, L: int):
    """One column block's rate-L leaf columns (N, b): the LDE half of
    `_absorb_lde_block`, split out so the double-buffered commit can
    dispatch block b+1's transform while block b absorbs. Keyed (b, n, L)
    like the fused form."""
    b = mono_blk.shape[0]
    lde = lde_from_monomial(mono_blk, L)
    return lde.reshape(b, -1).T  # (N, b)


@jax.jit
def _absorb_cols(state, cols):
    """Absorb an (N, b) leaf-column block into the carried sponge state —
    the absorb half of `_absorb_lde_block`, identical math (full 8-column
    chunks in order, trailing partial chunk zero-pads per the sponge
    finalize rule)."""
    b = cols.shape[1]
    for k in range(b // 8):
        state = _sponge_absorb8(state, cols[:, 8 * k : 8 * k + 8])
    rem = b % 8
    if rem:
        pad = jnp.zeros((cols.shape[0], 8 - rem), jnp.uint64)
        state = _sponge_absorb8(
            state, jnp.concatenate([cols[:, b - rem :], pad], axis=1)
        )
    return state


@_partial(jax.jit, static_argnums=(2,))
def _absorb_lde_block(state, mono_blk, L: int):
    """Absorb one column block's rate-L values into the carried sponge
    state: LDE-transform the (b, n) monomial block, transpose to rows and
    absorb 8 columns at a time. A trailing partial chunk (only ever the
    final block of an oracle — COL_BLOCK is a multiple of the sponge rate)
    zero-pads per the sponge finalize rule, matching leaf_hash exactly."""
    b = mono_blk.shape[0]
    lde = lde_from_monomial(mono_blk, L)
    cols = lde.reshape(b, -1).T  # (N, b)
    for k in range(b // 8):
        state = _sponge_absorb8(state, cols[:, 8 * k : 8 * k + 8])
    rem = b % 8
    if rem:
        pad = jnp.zeros((cols.shape[0], 8 - rem), jnp.uint64)
        state = _sponge_absorb8(
            state, jnp.concatenate([cols[:, b - rem :], pad], axis=1)
        )
    return state


# ---------------------------------------------------------------------------
# Limb-plane streamed commit (ISSUE 10): the double-buffered blocks carry
# (lo, hi) u32 planes end-to-end — LDE, pivot-to-rows and the carried
# sponge state never materialize u64. Digest values are identical.
# ---------------------------------------------------------------------------


class MonomialPlanesSource:
    """MonomialSource twin over plane monomials: stands in for a resident
    oracle's materialized (B, L*n) plane pair in the DEEP/query phases."""

    def __init__(self, mono_p, L: int):
        self.mono = mono_p
        self.L = int(L)

    @property
    def shape(self):
        return (self.mono[0].shape[0], self.mono[0].shape[-1] * self.L)

    def blocks(self, per: int = COL_BLOCK):
        from ..ntt.limb_ntt import lde_from_monomial_p

        B = self.mono[0].shape[0]
        for i in range(0, B, per):
            blk = (self.mono[0][i : i + per], self.mono[1][i : i + per])
            lde = lde_from_monomial_p(blk, self.L)
            b = lde[0].shape[0]
            yield i, (lde[0].reshape(b, -1), lde[1].reshape(b, -1))

    def column(self, i: int):
        from ..ntt.limb_ntt import lde_from_monomial_p

        blk = (self.mono[0][i : i + 1], self.mono[1][i : i + 1])
        lde = lde_from_monomial_p(blk, self.L)
        return lde[0].reshape(-1), lde[1].reshape(-1)

    def gather_rows(self, idx_dev):
        parts = [
            (flat[0][:, idx_dev], flat[1][:, idx_dev])
            for _, flat in self.blocks()
        ]
        return (
            jnp.concatenate([p[0] for p in parts], axis=0),
            jnp.concatenate([p[1] for p in parts], axis=0),
        )


@jax.jit
def _sponge_absorb8_p(state_p, chunk8_p):
    from ..hashes.poseidon2 import poseidon2_permutation_planes

    st = (
        jnp.concatenate([chunk8_p[0], state_p[0][:, 8:]], axis=-1),
        jnp.concatenate([chunk8_p[1], state_p[1][:, 8:]], axis=-1),
    )
    return poseidon2_permutation_planes(st)


@jax.jit
def _absorb_cols_p(state_p, cols_p):
    """Plane twin of _absorb_cols (same chunk/finalize semantics)."""
    b = cols_p[0].shape[1]
    for k in range(b // 8):
        state_p = _sponge_absorb8_p(
            state_p,
            (cols_p[0][:, 8 * k : 8 * k + 8], cols_p[1][:, 8 * k : 8 * k + 8]),
        )
    rem = b % 8
    if rem:
        pad = jnp.zeros((cols_p[0].shape[0], 8 - rem), jnp.uint32)
        state_p = _sponge_absorb8_p(
            state_p,
            (
                jnp.concatenate([cols_p[0][:, b - rem :], pad], axis=1),
                jnp.concatenate([cols_p[1][:, b - rem :], pad], axis=1),
            ),
        )
    return state_p


@_partial(jax.jit, static_argnums=(1,))
def _lde_block_cols_p(mono_blk_p, L: int):
    """Plane twin of _lde_block_cols: (b, n) monomial planes ->
    (N, b) leaf-column planes."""
    from ..ntt.limb_ntt import lde_from_monomial_p

    b = mono_blk_p[0].shape[0]
    lde = lde_from_monomial_p(mono_blk_p, L)
    return lde[0].reshape(b, -1).T, lde[1].reshape(b, -1).T


def streamed_leaf_digests_blocks_p(mono_p, L: int):
    """Plane twin of streamed_leaf_digests_blocks: (N, 4) digest planes,
    double-buffered under BOOJUM_TPU_OVERLAP exactly like the u64 form."""
    from ..utils.transfer import overlap_enabled

    assert COL_BLOCK % 8 == 0
    n = mono_p[0].shape[-1]
    B = mono_p[0].shape[0]
    state = (
        jnp.zeros((n * L, 12), jnp.uint32),
        jnp.zeros((n * L, 12), jnp.uint32),
    )

    def _blk(i):
        b = min(COL_BLOCK, B - i)
        return (
            jax.lax.dynamic_slice_in_dim(mono_p[0], i, b, axis=0),
            jax.lax.dynamic_slice_in_dim(mono_p[1], i, b, axis=0),
        )

    if not overlap_enabled():
        for i in range(0, B, COL_BLOCK):
            cols = _lde_block_cols_p(_blk(i), L)
            state = _absorb_cols_p(state, cols)
        return state[0][:, :4], state[1][:, :4]

    state = double_buffered_absorb(
        state,
        range(0, B, COL_BLOCK),
        lambda i: _lde_block_cols_p(_blk(i), L),
        absorb=_absorb_cols_p,
    )
    return state[0][:, :4], state[1][:, :4]


def commit_streaming(mono, L: int, cap_size: int) -> MerkleTreeWithCap:
    """Merkle-commit the rate-L LDE of `mono` without materializing it."""
    return MerkleTreeWithCap.from_digests(
        streamed_leaf_digests_blocks(mono, L), cap_size
    )


def deep_source_blocks(sources, per_bytes: int):
    """Yield (block (b, N), column_offset) across mixed sources: plain
    (B, N) arrays slice by a byte budget; MonomialSource regenerates."""
    off = 0
    for src in sources:
        if isinstance(src, MonomialSource):
            for i, flat in src.blocks():
                yield flat, off + i
            off += src.shape[0]
        else:
            B, N = src.shape
            per = max(1, per_bytes // (N * 8))
            for i in range(0, B, per):
                yield src[i : i + per], off + i
            off += B
