"""BabyBear mini-STARK verifier — the acceptance oracle for the `_bb`
prover leg (prover/bb_prover.py).

Pure host python ints: replays the Poseidon2 BabyBear transcript to
re-derive every challenge, checks the out-of-domain eval identity
Q(z) = Qt(z) + alpha * Qb(z) in GF(p^4), then per query walks the full
chain — Merkle paths for witness/quotient/FRI layers, DEEP recomputation
at both pair positions, the factor-2 fold recurrence down to the raw
final codeword — and finishes with the final-codeword low-degree check
(coset iNTT, high coefficients must vanish), PoW and index replay.

`check_babybear` returns (ok, reason) so tests can assert on the exact
failing stage; `verify_babybear` is the boolean wrapper.
"""

from __future__ import annotations

import numpy as np

from ..field import babybear as bb
from ..field.spec import BABYBEAR as SPEC
from ..ntt import bb_ntt
from ..transcript import BitSource, Poseidon2BabyBearTranscript
from .bb_kernels import verify_path_bb
from .bb_prover import BBProof, coset_descale
from .pow import blake2s_pow_verify


def _ext(v) -> tuple:
    return tuple(int(c) % bb.P for c in v)


_W = (0, 1, 0, 0)  # the ext generator w as a GF(p^4) element


def check_babybear(proof: BBProof):
    cfg = proof.config
    n, L, N = cfg.n, cfg.lde_factor, cfg.domain_len
    log_N = N.bit_length() - 1
    num_folds = cfg.num_folds
    pub = int(proof.pub) % bb.P

    # -- structural shape checks -------------------------------------------
    if len(proof.fri_caps) != num_folds - 1:
        return False, "fri cap count"
    if len(proof.final_codeword) != cfg.final_len:
        return False, "final codeword length"
    if len(proof.query_indices) != cfg.num_queries:
        return False, "query count"
    if any(not (0 <= int(i) < N) for i in proof.query_indices):
        return False, "query index range"

    # -- transcript replay: re-derive every challenge ----------------------
    t = Poseidon2BabyBearTranscript()
    t.witness_field_elements(cfg.params_list() + [pub])
    t.witness_merkle_tree_cap(proof.witness_cap)
    alpha = t.get_ext_challenge()
    t.witness_merkle_tree_cap(proof.quotient_cap)
    z = t.get_ext_challenge()
    wz = _ext(proof.evals["wz"])
    wgz = _ext(proof.evals["wgz"])
    qz = [_ext(e) for e in proof.evals["qz"]]
    t.witness_field_elements(
        [c for e in [wz, wgz] + qz for c in e]
    )
    gammas = [t.get_ext_challenge() for _ in range(6)]
    betas = []
    for r in range(num_folds):
        if r > 0:
            t.witness_merkle_tree_cap(proof.fri_caps[r - 1])
        betas.append(t.get_ext_challenge())
    final = [_ext(e) for e in proof.final_codeword]
    t.witness_field_elements([c for e in final for c in e])

    if not blake2s_pow_verify(t, cfg.pow_bits, proof.pow_nonce):
        return False, "pow"
    bits = BitSource(log_N, challenge_bits=SPEC.challenge_bits)
    idxs = [bits.get_index(t, log_N) for _ in range(cfg.num_queries)]
    if idxs != [int(i) for i in proof.query_indices]:
        return False, "query indices"

    # -- out-of-domain eval identity: Q(z) = Qt(z) + alpha * Qb(z) ---------
    g = bb.omega(cfg.log_n)
    g_last = bb.pow_s(g, n - 1)
    gz = bb.ext_scale_s(z, g)
    zn = bb.ext_pow_s(z, n)
    if zn == bb.ONE_S or z == bb.ONE_S:
        return False, "degenerate z"
    c_z = bb.ext_sub_s(
        wgz,
        bb.ext_add_s(bb.ext_mul_s(wz, wz), bb.ext_from_base_s(cfg.square_c)),
    )
    qt_z = bb.ext_mul_s(
        bb.ext_mul_s(c_z, bb.ext_sub_s(z, bb.ext_from_base_s(g_last))),
        bb.ext_inv_s(bb.ext_sub_s(zn, bb.ONE_S)),
    )
    qb_z = bb.ext_mul_s(
        bb.ext_sub_s(wz, bb.ext_from_base_s(pub)),
        bb.ext_inv_s(bb.ext_sub_s(z, bb.ONE_S)),
    )
    lhs = bb.ext_add_s(qt_z, bb.ext_mul_s(alpha, qb_z))
    rhs, wk = bb.ZERO_S, bb.ONE_S
    for k in range(4):
        rhs = bb.ext_add_s(rhs, bb.ext_mul_s(qz[k], wk))
        wk = bb.ext_mul_s(wk, _W)
    if lhs != rhs:
        return False, "eval identity"

    # -- final-codeword low-degree check -----------------------------------
    # domain of the final layer: shift^(2^num_folds) * <w_final_len>;
    # plain iNTT then coset descale, coefficients >= final_len / L must
    # vanish (the DEEP codeword has degree < N/L, halved per fold)
    sh_final = bb.pow_s(cfg.shift, 1 << num_folds)
    final_arr = np.array(final, dtype=np.uint32).T  # (4, final_len)
    mono = coset_descale(bb_ntt.ntt_np(final_arr, inverse=True), sh_final)
    if np.any(mono[:, cfg.final_len // L :]):
        return False, "final degree"

    # -- per-query chain ----------------------------------------------------
    w_n = bb.omega(log_N)

    def deep_at(j: int, w_j: int, q_j) -> tuple:
        x = bb.ext_from_base_s(bb.mul_s(cfg.shift, bb.pow_s(w_n, j)))
        num = bb.ext_mul_s(
            gammas[0], bb.ext_sub_s(bb.ext_from_base_s(w_j), wz)
        )
        for k in range(4):
            num = bb.ext_add_s(
                num,
                bb.ext_mul_s(
                    gammas[2 + k],
                    bb.ext_sub_s(bb.ext_from_base_s(q_j[k]), qz[k]),
                ),
            )
        d1 = bb.ext_mul_s(num, bb.ext_inv_s(bb.ext_sub_s(x, z)))
        d2 = bb.ext_mul_s(
            bb.ext_mul_s(
                gammas[1], bb.ext_sub_s(bb.ext_from_base_s(w_j), wgz)
            ),
            bb.ext_inv_s(bb.ext_sub_s(x, gz)),
        )
        return bb.ext_add_s(d1, d2)

    if len(proof.queries) != cfg.num_queries:
        return False, "opening count"
    for pos, opens in zip(idxs, proof.queries):
        if int(opens["pos"]) != pos:
            return False, "opening position"
        j0 = pos % (N // 2)
        pair_vals = []
        for half_idx, j in enumerate((j0, j0 + N // 2)):
            w_vals, w_path = opens["w"][half_idx]
            if len(w_vals) != 1 or not verify_path_bb(
                w_vals, w_path, proof.witness_cap, j
            ):
                return False, "witness path"
            q_vals, q_path = opens["q"][half_idx]
            if len(q_vals) != 4 or not verify_path_bb(
                q_vals, q_path, proof.quotient_cap, j
            ):
                return False, "quotient path"
            pair_vals.append(deep_at(j, int(w_vals[0]), q_vals))

        f0, f1 = pair_vals
        p = j0
        for r in range(num_folds):
            # fold the (p, p + M/2) pair of layer r at x = sh_r * w_M^p
            m_r = N >> r
            x = bb.mul_s(
                bb.pow_s(cfg.shift, 1 << r),
                bb.pow_s(bb.omega(m_r.bit_length() - 1), p),
            )
            even = bb.ext_scale_s(bb.ext_add_s(f0, f1), SPEC.half)
            odd = bb.ext_scale_s(
                bb.ext_sub_s(f0, f1), bb.inv_s(bb.mul_s(2, x))
            )
            folded = bb.ext_add_s(even, bb.ext_mul_s(betas[r], odd))
            if r + 1 == num_folds:
                if folded != final[p]:
                    return False, "final mismatch"
                break
            m_next = m_r // 2
            leaf_idx = p % (m_next // 2)
            leaf_vals, path = opens["fri"][r]
            if len(leaf_vals) != 8 or not verify_path_bb(
                leaf_vals, path, proof.fri_caps[r], leaf_idx
            ):
                return False, "fri path"
            lo = _ext(leaf_vals[0:4])
            hi = _ext(leaf_vals[4:8])
            if folded != (lo if p < m_next // 2 else hi):
                return False, "fold mismatch"
            f0, f1, p = lo, hi, leaf_idx

    return True, "ok"


def verify_babybear(proof: BBProof) -> bool:
    ok, _ = check_babybear(proof)
    return ok
