"""The geometry/shape-bucket key — ONE definition of "same shape".

Three subsystems bucket work by circuit shape and must never disagree:

- `prover/precompile.py` enumerates the shape-keyed kernel library of a
  (assembly, config) pair — every derived batch width below picks which
  executables a prove dispatches;
- the service admission queue (`service/queue.py`) groups requests into
  shape buckets so same-shape jobs share warmed caches and compiled
  kernels (and the scheduler reads bucket occupancy);
- the compile ledger (`utils/profiling.CompileLedger`) tags per-kernel
  entries with the shape they belong to, so a compile-bill regression is
  attributable to the bucket that paid it.

`shape_bucket(assembly, config)` derives everything from circuit
STRUCTURE only (placements, gates, geometry, lookup params) — witness
values and sigma columns are never read, so it runs before
`generate_setup` and is safe at admission time. The derivation mirrors
`prover._prove_impl` / `setup.generate_setup` exactly; `precompile.
enumerate_kernels` consumes the same `ShapeBucket` instance, which is
what makes divergence impossible rather than merely unlikely.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field


@dataclass(frozen=True)
class ShapeBucket:
    """Everything shape-keyed about one (assembly, config) pair.

    The identity fields (everything that feeds `key`) determine every
    derived batch width; two requests with equal keys dispatch the same
    kernel library, share domain/twiddle caches, and can pack into one
    admission bucket."""

    # -- trace / protocol geometry ----------------------------------------
    trace_len: int
    lde_factor: int            # L: FRI commit rate
    cap_size: int              # Merkle tree cap
    quotient_degree: int       # Q: resolved (config override or derived)
    num_queries: int
    fri_final_degree: int
    # explicit per-oracle fold counts, () = the derived greedy schedule
    # (the dispatched fri_fold_k* kernel set depends on it)
    fri_schedule: tuple
    transcript: str
    # -- column geometry ---------------------------------------------------
    num_copy_cols: int         # Cg
    num_lookup_cols: int       # LC
    num_wit_cols: int          # W
    num_constant_cols: int     # K (incl. the specialized table-id column)
    num_public_inputs: int
    # -- lookup argument ---------------------------------------------------
    lookups: bool
    lookup_mode: str | None
    lookup_subargs: int        # R_args
    lookup_width: int
    # -- gate set fingerprint (the sweep/stack graphs are per-gate-set) ----
    gates_fp: str
    # -- derived batch widths (functions of the fields above; carried so
    #    consumers never re-derive them differently) ------------------------
    num_chunks: int = field(compare=False)
    chunks: tuple = field(compare=False)
    max_degree: int = field(compare=False)

    # ---- derived accessors (shared shorthand of precompile/prover) -------
    @property
    def log_n(self) -> int:
        return self.trace_len.bit_length() - 1

    @property
    def domain_len(self) -> int:
        """N = n * L, the full LDE domain."""
        return self.trace_len * self.lde_factor

    @property
    def Ct(self) -> int:
        return self.num_copy_cols + self.num_lookup_cols

    @property
    def M(self) -> int:
        return 1 if self.lookups else 0

    @property
    def TW(self) -> int:
        return (self.lookup_width + 1) if self.lookups else 0

    @property
    def S(self) -> int:
        """Stage-2 oracle width: z + partials + lookup A_i/B columns."""
        return 2 * self.num_chunks + 2 * self.lookup_subargs + 2 * self.M

    @property
    def B_wit(self) -> int:
        return self.Ct + self.num_wit_cols + self.M

    @property
    def B_setup(self) -> int:
        return self.Ct + self.num_constant_cols + self.TW

    @property
    def B_q(self) -> int:
        return 2 * self.quotient_degree

    @property
    def B_all(self) -> int:
        return self.B_wit + self.B_setup + self.S + self.B_q

    @property
    def key(self) -> str:
        """Canonical compact bucket key, e.g.
        ``n2^10:L2:cap4:q2:Q4:f16:tposeidon2:c8+0+0:k6:pi1:nolk:g1a2f3``.
        Built from identity fields only — equal keys mean equal kernel
        shapes, shared caches, and one admission bucket."""
        lk = (
            f"lk{self.lookup_mode},{self.lookup_subargs}x{self.lookup_width}"
            if self.lookups
            else "nolk"
        )
        sched = (
            "s" + ",".join(str(k) for k in self.fri_schedule)
            if self.fri_schedule
            else "sderived"
        )
        # non-default field backends (ISSUE 19) suffix the key — their
        # kernel shapes/dtypes are disjoint, so they must never share a
        # cache or admission bucket with the Goldilocks set. Goldilocks
        # keys stay BYTE-IDENTICAL to every key minted before the field
        # seam existed (cached bundles/ledgers keep matching).
        from ..field.spec import active_field

        fld = active_field()
        field_sfx = f":F{fld}" if fld != "goldilocks" else ""
        return (
            f"n2^{self.log_n}:L{self.lde_factor}:cap{self.cap_size}"
            f":q{self.quotient_degree}:Q{self.num_queries}"
            f":f{self.fri_final_degree}:{sched}:t{self.transcript}"
            f":c{self.num_copy_cols}+{self.num_lookup_cols}"
            f"+{self.num_wit_cols}:k{self.num_constant_cols}"
            f":pi{self.num_public_inputs}:{lk}:g{self.gates_fp}"
            f"{field_sfx}"
        )

    @property
    def fingerprint(self) -> str:
        """Short stable digest of `key` for filesystem-safe naming
        (the AOT bundle store prefixes every bundle directory with it,
        so an operator can grep a bundle back to its shape bucket)."""
        return key_fingerprint(self.key)

    def __str__(self) -> str:
        return self.key


def key_fingerprint(key: str) -> str:
    """12-hex blake2s of a bucket key — the ONE fs-safe short form of
    "same shape" (prover/aot.py bundle dirs; anything else that needs a
    compact per-bucket name should use this, not its own hash)."""
    return hashlib.blake2s(key.encode(), digest_size=6).hexdigest()


def _gates_fingerprint(gates) -> str:
    """Short stable digest of the gate set IN PLACEMENT ORDER — the
    stage-2 stack and coset-sweep graphs are generated from the selector
    tree over exactly this sequence, so two circuits only share those
    executables when the sequence matches."""
    h = hashlib.blake2s(digest_size=6)
    for g in gates:
        h.update(type(g).__name__.encode())
        h.update(b"\x00")
    return h.hexdigest()


def derived_quotient_degree(assembly, config) -> int:
    """Q exactly as `setup.generate_setup` resolves it: the config
    override, else the next power of two covering the circuit's
    constraint-degree bound."""
    if config.quotient_degree is not None:
        return config.quotient_degree
    from .setup import build_selector_tree

    tree, _paths = build_selector_tree(assembly.gates)
    tree_degree, _consts = tree.compute_stats()
    degree_bound = max(
        tree_degree, assembly.geometry.max_allowed_constraint_degree + 1, 1
    )
    return 1 << (degree_bound - 1).bit_length()


def shape_bucket(assembly, config) -> ShapeBucket:
    """Derive the ShapeBucket of one (assembly, config) pair. Cached on
    the assembly (keyed by the config's field tuple): admission-time
    bucketing and a later precompile of the same pair must not re-pay the
    selector-tree walk."""
    from .stages import chunk_columns

    cfg_key = (
        config.fri_lde_factor, config.merkle_tree_cap_size,
        config.num_queries, config.pow_bits, config.fri_final_degree,
        tuple(config.fri_folding_schedule or ()), config.quotient_degree,
        config.transcript,
    )
    cache = getattr(assembly, "_shape_bucket_cache", None)
    if cache is None:
        cache = {}
        try:
            assembly._shape_bucket_cache = cache
        except Exception:
            cache = None
    if cache is not None and cfg_key in cache:
        return cache[cfg_key]

    geometry = assembly.geometry
    lookups = assembly.lookups_enabled
    lk_mode = assembly.lookup_mode if lookups else None
    lp = assembly.lookup_params
    Cg = assembly.copy_placement.shape[0]
    LC = assembly.num_lookup_cols
    chunks = chunk_columns(Cg + LC, geometry.max_allowed_constraint_degree)
    bucket = ShapeBucket(
        trace_len=int(assembly.trace_len),
        lde_factor=int(config.fri_lde_factor),
        cap_size=int(config.merkle_tree_cap_size),
        quotient_degree=derived_quotient_degree(assembly, config),
        num_queries=int(config.num_queries),
        fri_final_degree=int(config.fri_final_degree),
        fri_schedule=tuple(
            int(k) for k in (config.fri_folding_schedule or ())
        ),
        transcript=config.transcript,
        num_copy_cols=int(Cg),
        num_lookup_cols=int(LC),
        num_wit_cols=int(assembly.wit_placement.shape[0]),
        num_constant_cols=int(
            geometry.num_constant_columns
            + (1 if (lookups and lk_mode == "specialized") else 0)
        ),
        num_public_inputs=len(assembly.public_inputs),
        lookups=bool(lookups),
        lookup_mode=lk_mode,
        lookup_subargs=int(assembly.num_lookup_subargs if lookups else 0),
        lookup_width=int(lp.width if lookups else 0),
        gates_fp=_gates_fingerprint(assembly.gates),
        num_chunks=len(chunks),
        chunks=tuple(tuple(c) for c in chunks),
        max_degree=int(geometry.max_allowed_constraint_degree),
    )
    if cache is not None:
        cache[cfg_key] = bucket
    return bucket


def bucket_key(assembly, config) -> str:
    """The canonical shape-bucket key string (the admission-queue and
    compile-ledger tag)."""
    return shape_bucket(assembly, config).key
