"""Proof-of-work grinding (reference `PoWRunner`, pow.rs:7).

Algebraic Poseidon2 PoW: seed = 4 transcript challenges; find a u64 nonce
such that hash(seed ‖ nonce)[0] has `pow_bits` low zero bits. The nonce is
absorbed back into the transcript before query-index sampling so queries are
grinding-bound. (The reference's Blake2s/Keccak256 byte-oriented runners are
an alternative backend to add alongside.)
"""

from ..hashes.poseidon2 import Poseidon2SpongeHost


def pow_grind(transcript, pow_bits: int) -> int:
    if pow_bits == 0:
        return 0
    assert pow_bits <= 32, "unreasonable pow difficulty"
    seed = transcript.get_multiple_challenges(4)
    mask = (1 << pow_bits) - 1
    nonce = 0
    while True:
        h = Poseidon2SpongeHost.hash_leaf(seed + [nonce])
        if h[0] & mask == 0:
            break
        nonce += 1
    transcript.witness_field_elements([nonce])
    return nonce


def pow_verify(transcript, pow_bits: int, nonce: int) -> bool:
    if pow_bits == 0:
        return True
    seed = transcript.get_multiple_challenges(4)
    h = Poseidon2SpongeHost.hash_leaf(seed + [int(nonce)])
    if h[0] & ((1 << pow_bits) - 1) != 0:
        return False
    transcript.witness_field_elements([nonce])
    return True
