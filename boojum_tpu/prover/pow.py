"""Proof-of-work grinding (reference `PoWRunner`, pow.rs:7,51,140).

Algebraic Poseidon2 PoW (recursion-friendly: the recursive verifier replays
it with one flattened-gate sponge call): seed = 4 transcript challenges; find
a u64 nonce such that hash(seed ‖ nonce)[0] has `pow_bits` low zero bits. The
nonce is absorbed back into the transcript before query-index sampling so
queries are grinding-bound.

Byte-oriented Blake2s / Keccak256 runners mirror the reference's alternative
backends: seed = 4 challenges as LE bytes, digest's first LE u64 must have
`pow_bits` low zero bits.
"""

from ..hashes.poseidon2 import Poseidon2SpongeHost


def pow_grind(transcript, pow_bits: int) -> int:
    if pow_bits == 0:
        return 0
    assert pow_bits <= 32, "unreasonable pow difficulty"
    seed = transcript.get_multiple_challenges(4)
    mask = (1 << pow_bits) - 1
    nonce = 0
    while True:
        h = Poseidon2SpongeHost.hash_leaf(seed + [nonce])
        if h[0] & mask == 0:
            break
        nonce += 1
    transcript.witness_field_elements([nonce])
    return nonce


def pow_verify(transcript, pow_bits: int, nonce: int) -> bool:
    if pow_bits == 0:
        return True
    seed = transcript.get_multiple_challenges(4)
    h = Poseidon2SpongeHost.hash_leaf(seed + [int(nonce)])
    if h[0] & ((1 << pow_bits) - 1) != 0:
        return False
    transcript.witness_field_elements([nonce])
    return True


def _byte_pow_grind(transcript, pow_bits: int, hasher) -> int:
    if pow_bits == 0:
        return 0
    assert pow_bits <= 32, "unreasonable pow difficulty"
    seed = b"".join(
        c.to_bytes(8, "little")
        for c in transcript.get_multiple_challenges(4)
    )
    mask = (1 << pow_bits) - 1
    nonce = 0
    while True:
        h = hasher(seed + nonce.to_bytes(8, "little"))
        if int.from_bytes(h[:8], "little") & mask == 0:
            break
        nonce += 1
    transcript.witness_field_elements([nonce])
    return nonce


def _byte_pow_verify(transcript, pow_bits: int, nonce: int, hasher) -> bool:
    if pow_bits == 0:
        return True
    seed = b"".join(
        c.to_bytes(8, "little")
        for c in transcript.get_multiple_challenges(4)
    )
    mask = (1 << pow_bits) - 1
    h = hasher(seed + int(nonce).to_bytes(8, "little"))
    if int.from_bytes(h[:8], "little") & mask != 0:
        return False
    transcript.witness_field_elements([nonce])
    return True


def blake2s_pow_grind(transcript, pow_bits: int) -> int:
    """Blake2s nonce search (reference pow.rs:51)."""
    import hashlib

    return _byte_pow_grind(
        transcript, pow_bits, lambda d: hashlib.blake2s(d).digest()
    )


def blake2s_pow_verify(transcript, pow_bits: int, nonce: int) -> bool:
    import hashlib

    return _byte_pow_verify(
        transcript, pow_bits, nonce, lambda d: hashlib.blake2s(d).digest()
    )


def keccak256_pow_grind(transcript, pow_bits: int) -> int:
    """Keccak-256 nonce search (reference pow.rs:140)."""
    from ..hashes.keccak_host import keccak256

    return _byte_pow_grind(transcript, pow_bits, keccak256)


def keccak256_pow_verify(transcript, pow_bits: int, nonce: int) -> bool:
    from ..hashes.keccak_host import keccak256

    return _byte_pow_verify(transcript, pow_bits, nonce, keccak256)
