from .config import ProofConfig
from .setup import SetupData, VerificationKey, generate_setup
from .prover import prove
from .verifier import verify
from .proof import Proof
