from .config import ProofConfig
from .setup import SetupData, VerificationKey, generate_setup
from .prover import prove
from .verifier import verify
from .proof import Proof
from .convenience import (
    prove_one_shot,
    prepare_setup_and_vk,
    prove_from_precomputations,
    verify_circuit,
)
from .precompile import enumerate_kernels, precompile
from .shape_key import ShapeBucket, bucket_key, shape_bucket
