"""Parallel precompilation of the prover's kernel library (ISSUE 1).

A cold process used to pay the remote compile bill SERIALLY: each fused
round graph compiled at first dispatch, one at a time, 160-250 s each on
the tunneled compile service — ~35-45 minutes before the first prove
(BASELINE.md round 4). With the round graphs split into shape-keyed
top-level kernels (prover.py / stages.py / merkle.py / streaming.py /
fri.py), the bill becomes a LIBRARY of small modules that can compile
concurrently:

- `enumerate_kernels(assembly, config)` derives every shape-keyed
  executable a fused prove of this (CSGeometry, ProofConfig) will
  dispatch — the commit pipelines for each oracle, the stage-2 chunk
  scan/prefix/stack graphs, the per-coset evaluation + terms sweep, the
  round-4/5 evaluation and DEEP graphs and the FRI schedule — as
  (name, jitted_fn, ShapeDtypeStruct args) specs. No device memory is
  allocated.
- `precompile(...)` lowers the specs serially (tracing is Python/GIL
  work) and runs `.compile()` on a thread pool: under JAX_PLATFORMS=axon
  each compile is a blocking RPC that releases the GIL, so the
  round-trips overlap instead of queueing. Compiled executables land in
  the fingerprint-salted persistent cache (bench.py,
  boojum_tpu/__init__.py), which both this process's first prove and
  every later process read back — re-dispatch pays re-tracing plus a
  cache load, never the remote compile.

Every lower/compile is timed into a `utils.profiling.CompileLedger`;
bench.py emits the ledger JSON so compile-bill regressions show up in
round artifacts.
"""

from __future__ import annotations

import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass

import jax
import jax.numpy as jnp

from ..utils import metrics as _metrics
from ..utils.profiling import CompileLedger, current_compile_ledger
from ..utils.spans import span as _span


def _sds(*shape):
    return jax.ShapeDtypeStruct(shape, jnp.uint64)


def _sdsp(*shape):
    """A (lo, hi) u32 plane-pair ShapeDtypeStruct (the limb-resident
    kernel set's argument unit, ISSUE 10)."""
    s = jax.ShapeDtypeStruct(shape, jnp.uint32)
    return (s, s)


def _u32(*shape):
    return jax.ShapeDtypeStruct(shape, jnp.uint32)


def _i32():
    return jax.ShapeDtypeStruct((), jnp.int32)


@dataclass
class KernelSpec:
    name: str
    fn: object  # a jitted callable supporting .lower(*args)
    args: tuple


def _next_pow2(x: int) -> int:
    c = 1
    while c < max(x, 1):
        c *= 2
    return c


def enumerate_kernels(assembly, config, mesh_shape=None) -> list[KernelSpec]:
    """The shape-keyed kernel library for a fused prove of `assembly`
    under `config` — meshless, or per-chip shard_map when a shard_map
    mesh is active (parallel/shard_sweep.py) or `mesh_shape` names one.

    `mesh_shape`: a ('col','row') device-count pair like (2, 4), or an
    already-built Mesh — enumerates the `_sm` kernel variants (per-chip
    iNTT/LDE + pivot + leaf sponge, coset_sweep_terms[_limb]_sm,
    fri_fold[_limb]_k*_sm) for that mesh without one being active. Only
    the variant this process will dispatch is enumerated, so the compile
    ledger records exactly the dispatched set.

    Derivations mirror prover._prove_impl / setup.generate_setup; only
    circuit STRUCTURE is read (placements, gates, geometry, lookup
    params) — the witness values and the setup's sigma columns are never
    touched, so this runs before generate_setup. Deliberately skipped
    (cheap, query-dependent shapes): the fused query gather, streamed
    single-column opens, the replicated Merkle tail after the cap
    all_gather, and the PoW grind (host-side)."""
    from ..merkle import leaf_digests_device, node_layers_device
    from ..field import extension as ext_f
    from ..ntt.ntt import _ext_powers_jit, ntt_kernel_specs
    from .fri import fri_kernel_specs
    from .setup import build_selector_tree, non_residues_for_copy_permutation
    from .shape_key import shape_bucket
    from .stages import (
        _all_chunk_num_den,
        _lookup_denominators,
        _z_and_partials,
        num_gate_sweep_terms,
    )
    from .streaming import (
        COL_BLOCK,
        _absorb_cols,
        _absorb_lde_block,
        _lde_block_cols,
        use_streamed_lde,
    )
    from . import prover as P
    from ..parallel import shard_sweep as SS
    from ..parallel.sharding import shard_map_mesh
    from ..utils import transfer as _transfer

    if mesh_shape is None:
        smm = shard_map_mesh()
    elif isinstance(mesh_shape, (tuple, list)):
        smm = SS.mesh_from_shape(mesh_shape)
    else:
        smm = mesh_shape  # an already-built Mesh
    D = SS.mesh_devices(smm) if smm is not None else 1

    # field backend (ISSUE 19): BOOJUM_TPU_FIELD=babybear dispatches the
    # plane-free `_bb` kernel set (prover/bb_kernels.py) — a third
    # DISJOINT variant beside u64 and limb-resident, selected before
    # either (the field also rides prover/aot.py's variant fingerprint)
    from ..field.spec import is_babybear

    if is_babybear():
        return _enumerate_babybear(assembly, config)

    # limb residency (ISSUE 10): the resident prove dispatches a DISJOINT
    # plane-kernel set (`*_limbres` ledger names) — enumerate exactly that
    # set, never both (the variant also rides prover/aot.py's bundle key)
    from .pallas_sweep import limb_resident_enabled

    if limb_resident_enabled():
        return _enumerate_resident(assembly, config, smm, D)

    # ONE derivation of every shape-keyed quantity, shared with the
    # service admission queue and the compile-ledger tags (shape_key.py)
    sb = shape_bucket(assembly, config)
    n = sb.trace_len
    log_n = sb.log_n
    L = sb.lde_factor
    N = sb.domain_len
    cap = sb.cap_size
    Cg, LC, Ct, W = sb.num_copy_cols, sb.num_lookup_cols, sb.Ct, sb.num_wit_cols
    lookups = sb.lookups
    lk_mode = assembly.lookup_mode
    R_args = sb.lookup_subargs
    M, K, TW, width = sb.M, sb.num_constant_cols, sb.TW, sb.lookup_width

    chunks = list(sb.chunks)
    num_chunks = sb.num_chunks
    num_partials = num_chunks - 1
    S, B_wit, B_setup = sb.S, sb.B_wit, sb.B_setup

    # selector paths are structure, not shape — still derived here, exactly
    # as generate_setup derives them (shape_key resolves Q the same way)
    _tree, selector_paths = build_selector_tree(assembly.gates)
    Q = sb.quotient_degree
    B_q = sb.B_q
    B_all = sb.B_all
    non_residues = non_residues_for_copy_permutation(Ct)

    total_cols = B_all
    stream = use_streamed_lde(total_cols, N)
    stream_setup = use_streamed_lde(B_setup, N)

    specs: list[KernelSpec] = []

    def add(name, fn, *args):
        specs.append(KernelSpec(name, fn, args))

    # ---- commit pipelines (witness / stage-2 / quotient / setup) ---------
    absorb_blocks: set[int] = set()

    def commit_specs(tag, B, streamed, mono=True):
        if smm is not None:
            return commit_specs_sm(tag, B, streamed, mono)
        for nm, fn, args in ntt_kernel_specs(
            B, log_n, None if streamed else L, mono=mono
        ):
            add(f"{tag}:{nm}", fn, *args)
        if streamed:
            for i in range(0, B, COL_BLOCK):
                absorb_blocks.add(min(COL_BLOCK, B - i))
        else:
            add(f"{tag}:leaf_digests", leaf_digests_device, _sds(B, L, n))

    def commit_specs_sm(tag, B, streamed, mono=True):
        # the per-chip pipeline (shard_sweep.commit_pipeline_sm): local
        # iNTT of the column stripe, then — materialized — the fused
        # LDE + all_to_all pivot + leaf-sponge graph, or — streamed —
        # the per-block LDE+pivot feeding the carried local sponge
        Bp = SS.padded_cols(B, D)
        if mono:
            add(f"{tag}:mono_sm", SS._mono_fn(smm), _sds(Bp, n))
        if streamed:
            # block widths only — the per-width lde_pivot_cols spec is
            # added ONCE per width in the shared absorb_blocks loop below
            # (oracles share block shapes, and each lower() is a full
            # retrace: duplicate specs would re-pay the trace bill)
            for i in range(0, B, COL_BLOCK):
                absorb_blocks.add(min(COL_BLOCK, B - i))
        else:
            use_limb = SS.leaf_limb_ok(B, N // D)
            add(
                f"{tag}:lde_pivot_leaf_sm",
                SS._lde_pivot_leaf_fn(smm, L, B, use_limb), _sds(Bp, n),
            )

    commit_specs("wit", B_wit, stream)
    commit_specs("s2", S, stream)
    # the quotient LDE is always materialized, and its monomials come from
    # _quotient_interp rather than monomial_from_values — no imono kernel
    commit_specs("q", B_q, False, mono=False)
    commit_specs("setup", B_setup, stream_setup)
    # streamed-commit kernels follow the dispatch mode this process will
    # actually use: the double-buffered split pair with BOOJUM_TPU_OVERLAP
    # on (the default), the fused block graph with it off — compiling the
    # other mode's variant would be minutes of pure waste on the tunnel
    # compiler. The shard_map streamed commit always absorbs through the
    # split _absorb_cols (streaming.double_buffered_absorb).
    overlap = _transfer.overlap_enabled()
    for b in sorted(absorb_blocks):
        if smm is not None:
            add(
                f"lde_pivot_cols_b{b}_sm",
                SS._lde_pivot_cols_fn(smm, L, b),
                _sds(SS.padded_cols(b, D), n),
            )
            add(f"absorb_cols_b{b}", _absorb_cols, _sds(N, 12), _sds(N, b))
        elif overlap:
            add(f"lde_block_cols_b{b}", _lde_block_cols, _sds(b, n), L)
            add(f"absorb_cols_b{b}", _absorb_cols, _sds(N, 12), _sds(N, b))
        else:
            add(
                f"absorb_lde_block_b{b}",
                _absorb_lde_block, _sds(N, 12), _sds(b, n), L,
            )
    if smm is None:
        add("node_layers", node_layers_device, _sds(N, 4), cap)
    else:
        # per-chip node layers while digest pairs stay shard-local
        # (shard_sweep.node_layers_sm; the replicated tail past the
        # all_gather is cheap and compiles at dispatch)
        steps, gather = SS.node_plan(N, cap, D)
        for cur in steps:
            add("node_step_sm", SS._node_step_fn(smm), _sds(cur, 4))
        if gather is not None:
            add(
                "node_gather_sm", SS._all_gather_fn(smm, 2), _sds(gather, 4)
            )

    if overlap:
        # the chunked witness upload's on-device concatenate
        wit_groups = [Cg] + ([LC] if LC else []) + ([W] if W else []) \
            + ([1] if M else [])
        upload_parts = _transfer.upload_chunk_shapes(wit_groups, n)
        if len(upload_parts) > 1:
            add(
                "witness_upload_concat", _transfer._concat_jit(),
                *[_sds(b, n) for b in upload_parts],
            )

    # ---- round 2: chunk products, inversions, prefix product, stack ------
    sc = (_sds(), _sds())
    chunks_t = tuple(tuple(c) for c in chunks)
    add(
        "chunk_num_den", _all_chunk_num_den,
        _sds(Ct, n), _sds(Ct, n), _sds(Ct), _sds(n), sc, sc, chunks_t,
    )
    pair = lambda *shape: (_sds(*shape), _sds(*shape))  # noqa: E731
    add("ext_binv_chunks", ext_f.batch_inverse, pair(num_chunks, n))
    if lookups:
        lk_cols = _sds(LC, n) if lk_mode == "specialized" else _sds(Cg, n)
        add(
            "lookup_denominators", _lookup_denominators,
            lk_cols, _sds(n), _sds(width + 1, n), sc, sc, R_args, width,
        )
        add("ext_binv_lookup", ext_f.batch_inverse, pair(R_args + 1, n))
    add("z_and_partials", _z_and_partials, pair(num_chunks, n),
        pair(num_chunks, n))
    stack_fn = P._stage2_stack_fn(assembly, selector_paths)
    lk_inv = pair(R_args + 1, n) if lookups else None
    mult = _sds(n) if lookups else None
    consts = _sds(K, n) if (lookups and lk_mode == "general") else None
    add("stage2_stack", stack_fn, pair(n), pair(num_partials, n),
        lk_inv, mult, consts)

    # ---- round 3: per-coset evaluations + terms sweep + quotient tail ----
    total_alpha_terms = (
        num_gate_sweep_terms(assembly)
        + 1 + num_chunks
        + ((R_args + 1) if lookups else 0)
    )
    capA = _next_pow2(total_alpha_terms)
    add("zshift", P._zshift_fused, _sds(2, n), _sds())
    for tag, B in (
        ("wit", B_wit), ("setup", B_setup), ("s2", S), ("zs", 2)
    ):
        if smm is None:
            add(f"coset_eval_{tag}", P._coset_eval_q,
                _sds(B, n), _sds(Q, n), _i32())
        else:
            add(f"coset_eval_{tag}_sm", SS._coset_eval_fn(smm, B),
                _sds(SS.padded_cols(B, D), n), _sds(Q, n), _i32())
    mk_path = None
    if lookups and lk_mode == "general":
        mk_path = selector_paths[assembly.lookup_marker_gid()]
    lk_ctx = (
        lookups, lk_mode, R_args, width, num_partials, chunks_t,
        total_alpha_terms, Cg, Ct, W, K, M,
        tuple(mk_path) if mk_path is not None else None,
    )
    # the sweep factory dispatches the representation this process will
    # actually use (u64 XLA body vs the fused u32-limb Pallas kernel —
    # BOOJUM_TPU_LIMB_SWEEP); the ledger name carries the variant so a
    # compile-bill regression is attributable to the right kernel
    from .pallas_sweep import limb_sweep_enabled

    sweep = P._coset_sweep_fn(
        assembly, selector_paths, non_residues, lk_ctx, sm_mesh=smm
    )
    sweep_name = (
        "coset_sweep_terms_limb" if limb_sweep_enabled()
        else "coset_sweep_terms"
    ) + ("_sm" if smm is not None else "")
    add(
        sweep_name, sweep,
        _sds(B_wit, n), _sds(B_setup, n), _sds(S, n), _sds(2, n), _i32(),
        _sds(Q * n), _sds(Q * n), _sds(Q * n), _sds(capA), _sds(capA),
        _sds(2), _sds(2), _sds(2), _sds(2),
    )
    add(
        "quotient_interp", P._quotient_interp,
        tuple(_sds(n) for _ in range(Q)), tuple(_sds(n) for _ in range(Q)),
        Q, n,
    )

    # ---- rounds 4-5: openings, DEEP, FRI ---------------------------------
    num_lk = (R_args + 1) if lookups else 0
    num_pi = len(assembly.public_inputs)
    add("alpha_powers", _ext_powers_jit, _sds(2), capA)
    capD = _next_pow2(B_all + 2 + num_lk + num_pi)
    add("deep_powers", _ext_powers_jit, _sds(2), capD)
    add("evals_fused", P._evals_fused, _sds(B_all, n), _sds(S, n),
        _sds(2), _sds(2))
    add("deep_denoms", P._deep_denoms_fused, _sds(N), _sds(2), _sds(2))
    add("ext_binv_deep", ext_f.batch_inverse, pair(2, N))
    deep_blocks: set[int] = set()
    from ..ntt.ntt import chunk_shapes

    # the setup oracle streams in the DEEP phase iff it was COMMITTED
    # streamed (prover follows setup.setup_lde, decided per-setup by
    # generate_setup), independently of the prove-wide stream flag
    for B, streamed_src in (
        (B_wit, stream), (B_setup, stream_setup), (S, stream)
    ):
        if streamed_src:
            for i in range(0, B, COL_BLOCK):
                b32 = min(COL_BLOCK, B - i)
                deep_blocks.add(b32)
                # streamed DEEP blocks regenerate their rate-L values
                for nm, fn, args in ntt_kernel_specs(
                    b32, log_n, L, mono=False
                ):
                    add(f"deep_regen:{nm}", fn, *args)
        else:
            per = max(1, P._DEEP_BLOCK_BUDGET // (N * 8))
            for i in range(0, B, per):
                deep_blocks.add(min(per, B - i))
    per = max(1, P._DEEP_BLOCK_BUDGET // (N * 8))
    for i in range(0, B_q, per):
        deep_blocks.add(min(per, B_q - i))
    if smm is not None and not (stream or stream_setup):
        # the sm round 5: ONE shard_map graph for main sum + extras
        # (shard_sweep.deep_codeword_sm) — the per-block meshless deep
        # graphs are never dispatched
        capE = 2 + num_lk + num_pi
        add(
            "deep_codeword_sm", SS._deep_fn(smm, 4, 2, num_lk, num_pi),
            (_sds(B_wit, N), _sds(B_setup, N), _sds(S, N), _sds(B_q, N)),
            _sds(B_all), _sds(B_all), _sds(B_all), _sds(B_all),
            pair(N), pair(N), _sds(2, N), _sds(2 * num_lk, N),
            _sds(N) if lookups else _sds(1), _sds(num_pi, N),
            _sds(num_pi, N), _sds(num_pi), pair(2), pair(num_lk),
            _sds(capE), _sds(capE),
        )
    else:
        for b in sorted(deep_blocks):
            add(
                f"deep_block_b{b}", P._deep_block,
                _sds(b, N), _sds(b), _sds(b),
            )
        add("deep_combine", P._deep_combine, _sds(N), _sds(N),
            _sds(B_all), _sds(B_all), _sds(B_all), _sds(B_all), pair(N))
        extras = P._deep_extras_fn(2, num_lk, num_pi)
        add(
            "deep_extras", extras,
            pair(N), _sds(2, N), _sds(2 * num_lk, N), _sds(num_pi, N),
            pair(N), _sds(N) if lookups else _sds(1), _sds(num_pi, N),
            pair(2), pair(num_lk), _sds(num_pi), _sds(2 + num_lk + num_pi),
            _sds(2 + num_lk + num_pi),
        )
    for nm, fn, args in fri_kernel_specs(n, config, mesh=smm):
        add(nm, fn, *args)

    # ---- cached domain tables (built once per geometry, but their batch
    # inversions are real compiles on a cold cache) ------------------------
    from ..field import goldilocks as gf
    from .fri import fold_schedule

    add("gf_binv_domain", gf.batch_inverse_xla, _sds(N))
    num_folds = sum(
        fold_schedule(
            n, config.fri_final_degree,
            getattr(config, "fri_folding_schedule", None),
        )
    )
    log_full = N.bit_length() - 1
    for r in range(num_folds):
        add(
            f"gf_binv_fold_r{r}", gf.batch_inverse_xla,
            _sds(1 << (log_full - r - 1)),
        )
    if num_pi:
        add("gf_binv_pi", gf.batch_inverse_xla, _sds(num_pi, N))

    # dedupe identical (fn, args) pairs surfaced under several tags — one
    # executable serves them all, compiling it twice is pure waste
    seen = set()
    out = []
    for s in specs:
        key = (id(s.fn), repr(s.args))
        if key in seen:
            continue
        seen.add(key)
        out.append(s)
    return out


def _enumerate_babybear(assembly, config) -> list[KernelSpec]:
    """The BabyBear plane-free kernel library (enumerate_kernels' `_bb`
    twin, ISSUE 19): every top-level executable the self-contained
    BabyBear prover leg (prover/bb_prover.py) dispatches at this shape
    bucket's domain — single u32-lane args throughout, no (lo, hi)
    plane pairs anywhere in the set."""
    from .bb_kernels import bb_kernel_specs
    from .shape_key import shape_bucket

    sb = shape_bucket(assembly, config)
    specs = [
        KernelSpec(name, fn, args)
        for name, fn, args in bb_kernel_specs(
            sb.log_n, sb.lde_factor, sb.cap_size
        )
    ]
    specs += _enumerate_babybear_full(sb)
    return specs


def _enumerate_babybear_full(sb) -> list[KernelSpec]:
    """The FULL BabyBear prover's assembly-independent executables
    (ISSUE 20, prover/prover_bb.py): batched u32 iNTT/LDE at the
    bucket's oracle widths, paired-leaf commits at every oracle's
    (2B, N/2) stack, and the factor-2 FRI fold chain. The fused gate
    sweep jit is assembly-shaped (gate evaluators are baked into the
    graph) and warms on first prove instead."""
    import jax
    import jax.numpy as jnp

    from ..ntt.bb_ntt import lde_from_monomial_bb, monomial_from_values_bb
    from .bb_kernels import leaf_digests_bb, node_layers_bb, fri_fold_bb

    def u32(*shape):
        return jax.ShapeDtypeStruct(shape, jnp.uint32)

    n, L, cap = sb.trace_len, sb.lde_factor, sb.cap_size
    log_n = sb.log_n
    N = n * L
    half = N // 2
    Q = sb.quotient_degree
    shift = 31
    specs: list[KernelSpec] = []

    def add(name, fn, *args):
        specs.append(KernelSpec(name, fn, args))

    zs_rows = 4  # the z poly's base columns (omega-shifted monomials)
    oracle_widths = sorted(
        {sb.B_wit, sb.S, sb.B_q, zs_rows, 4}  # 4 = DEEP/FRI codeword
    )
    for B in oracle_widths:
        if B <= 0:
            continue
        add(f"imono_bb_n{n}x{B}", monomial_from_values_bb,
            u32(B, n), log_n)
        add(f"lde_bb_L{L}_n{n}x{B}", lde_from_monomial_bb,
            u32(B, n), log_n, L, shift)
        add(f"leaf_digests_bb_n{half}x{2 * B}", leaf_digests_bb,
            u32(2 * B, half))
    add(f"node_layers_bb_n{half}", node_layers_bb, u32(half, 8),
        min(cap, half))
    # rate-Q sweep-domain evals of every committed oracle group
    for B in sorted({sb.B_wit, sb.B_setup, sb.S, zs_rows}):
        if B > 0:
            add(f"lde_bb_Q{Q}_n{n}x{B}", lde_from_monomial_bb,
                u32(B, n), log_n, Q, shift)
    # quotient interpolation over the rate-Q accumulator
    add(f"imono_bb_n{Q * n}x4", monomial_from_values_bb,
        u32(4, Q * n), (Q * n).bit_length() - 1)
    return specs


def _enumerate_resident(assembly, config, smm, D) -> list[KernelSpec]:
    """The limb-RESIDENT kernel library (enumerate_kernels' plane twin):
    every executable a resident prove dispatches, with `_limbres`-tagged
    ledger names and (lo, hi) u32 plane-pair argument specs. Mirrors the
    derivations of prover._prove_impl's resident branches exactly."""
    from ..field import limb_ops as lop
    from ..merkle import leaf_digests_planes, node_layers_planes
    from ..ntt.limb_ntt import plane_ntt_kernel_specs
    from .fri import fri_kernel_specs
    from .setup import build_selector_tree, non_residues_for_copy_permutation
    from .shape_key import shape_bucket
    from .streaming import (
        COL_BLOCK,
        _absorb_cols_p,
        _lde_block_cols_p,
        use_streamed_lde,
    )
    from . import prover as P
    from . import resident as RES
    from ..parallel import shard_sweep as SS
    from ..utils import transfer as _transfer

    sb = shape_bucket(assembly, config)
    n, log_n, L, N, cap = (
        sb.trace_len, sb.log_n, sb.lde_factor, sb.domain_len, sb.cap_size
    )
    Cg, LC, Ct, W = sb.num_copy_cols, sb.num_lookup_cols, sb.Ct, sb.num_wit_cols
    lookups = sb.lookups
    lk_mode = assembly.lookup_mode
    R_args = sb.lookup_subargs
    M, K, TW, width = sb.M, sb.num_constant_cols, sb.TW, sb.lookup_width
    chunks = list(sb.chunks)
    num_chunks = sb.num_chunks
    num_partials = num_chunks - 1
    S, B_wit, B_setup = sb.S, sb.B_wit, sb.B_setup
    _tree, selector_paths = build_selector_tree(assembly.gates)
    Q = sb.quotient_degree
    B_q = sb.B_q
    B_all = sb.B_all
    non_residues = non_residues_for_copy_permutation(Ct)
    stream = use_streamed_lde(B_all, N)
    stream_setup = use_streamed_lde(B_setup, N)

    specs: list[KernelSpec] = []

    def add(name, fn, *args):
        specs.append(KernelSpec(name, fn, args))

    # ---- commit pipelines (plane NTT + plane sponges) --------------------
    absorb_blocks: set[int] = set()

    def commit_specs(tag, B, streamed, mono=True):
        if smm is not None:
            Bp = SS.padded_cols(B, D)
            if mono:
                add(
                    f"{tag}:mono_limbres_sm", SS._mono_fn_p(smm),
                    _sdsp(Bp, n),
                )
            if streamed:
                for i in range(0, B, COL_BLOCK):
                    absorb_blocks.add(min(COL_BLOCK, B - i))
            else:
                add(
                    f"{tag}:lde_pivot_leaf_limbres_sm",
                    SS._lde_pivot_leaf_fn_p(smm, L, B), _sdsp(Bp, n),
                )
            return
        for nm, fn, args in plane_ntt_kernel_specs(
            B, log_n, None if streamed else L, mono=mono
        ):
            add(f"{tag}:{nm}", fn, *args)
        if streamed:
            for i in range(0, B, COL_BLOCK):
                absorb_blocks.add(min(COL_BLOCK, B - i))
        else:
            add(
                f"{tag}:leaf_digests_limbres", leaf_digests_planes,
                _sdsp(B, L, n),
            )

    commit_specs("wit", B_wit, stream)
    commit_specs("s2", S, stream)
    commit_specs("q", B_q, False, mono=False)
    commit_specs("setup", B_setup, stream_setup)
    for b in sorted(absorb_blocks):
        if smm is not None:
            add(
                f"lde_pivot_cols_limbres_b{b}_sm",
                SS._lde_pivot_cols_fn_p(smm, L, b),
                _sdsp(SS.padded_cols(b, D), n),
            )
        else:
            # the resident streamed commit dispatches the split pair in
            # BOTH overlap modes (streaming.streamed_leaf_digests_blocks_p)
            add(
                f"lde_block_cols_limbres_b{b}", _lde_block_cols_p,
                _sdsp(b, n), L,
            )
        add(
            f"absorb_cols_limbres_b{b}", _absorb_cols_p,
            _sdsp(N, 12), _sdsp(N, b),
        )
    if smm is None:
        add("node_layers_limbres", node_layers_planes, _sdsp(N, 4), cap)
    else:
        steps, gather = SS.node_plan(N, cap, D)
        for cur in steps:
            add("node_step_limbres_sm", SS._node_step_fn_p(smm), _sdsp(cur, 4))
        if gather is not None:
            add(
                "node_gather_limbres_sm", SS._all_gather_fn(smm, 2),
                _u32(gather, 4),
            )
    if _transfer.overlap_enabled():
        wit_groups = [Cg] + ([LC] if LC else []) + ([W] if W else []) \
            + ([1] if M else [])
        upload_parts = _transfer.upload_chunk_shapes(wit_groups, n)
        if len(upload_parts) > 1:
            add(
                "witness_upload_concat_limbres", _transfer._concat_jit(),
                *[_u32(b, n) for b in upload_parts],
            )

    # ---- round 2 plane twins ---------------------------------------------
    chunks_t = tuple(tuple(c) for c in chunks)
    bg8 = _u32(8)
    pairp = lambda *shape: (_sdsp(*shape), _sdsp(*shape))  # noqa: E731
    add(
        "chunk_num_den_limbres", RES._all_chunk_num_den_p,
        _sdsp(Ct, n), _sdsp(Ct, n), _sdsp(Ct), (_sdsp(n), bg8), chunks_t,
    )
    add(
        "ext_binv_chunks_limbres", lop.ext_batch_inverse_jit,
        pairp(num_chunks, n),
    )
    if lookups:
        lk_cols = _sdsp(LC, n) if lk_mode == "specialized" else _sdsp(Cg, n)
        add(
            "lookup_denominators_limbres", RES._lookup_denominators_p,
            lk_cols, (_sdsp(n), _sdsp(width + 1, n)), bg8, R_args, width,
        )
        add(
            "ext_binv_lookup_limbres", lop.ext_batch_inverse_jit,
            pairp(R_args + 1, n),
        )
    add(
        "z_and_partials_limbres", RES._z_and_partials_p,
        pairp(num_chunks, n), pairp(num_chunks, n),
    )
    stack_fn = RES.stage2_stack_fn_p(assembly, selector_paths)
    lk_inv = pairp(R_args + 1, n) if lookups else None
    mult = _sdsp(n) if lookups else None
    consts = _sdsp(K, n) if (lookups and lk_mode == "general") else None
    add(
        "stage2_stack_limbres", stack_fn, pairp(n), pairp(num_partials, n),
        lk_inv, mult, consts,
    )

    # ---- round 3: plane evals + resident sweep + interp ------------------
    from .stages import num_gate_sweep_terms

    total_alpha_terms = (
        num_gate_sweep_terms(assembly)
        + 1 + num_chunks
        + ((R_args + 1) if lookups else 0)
    )
    capA = _next_pow2(total_alpha_terms)
    add("zshift_limbres", RES._zshift_p, _sdsp(2, n), _sdsp(n))
    for tag, B in (
        ("wit", B_wit), ("setup", B_setup), ("s2", S), ("zs", 2)
    ):
        if smm is None:
            add(
                f"coset_eval_{tag}_limbres", RES._coset_eval_q_p,
                _sdsp(B, n), _sdsp(Q, n), _i32(),
            )
        else:
            add(
                f"coset_eval_{tag}_limbres_sm", SS._coset_eval_fn_p(smm, B),
                _sdsp(SS.padded_cols(B, D), n), _sdsp(Q, n), _i32(),
            )
    mk_path = None
    if lookups and lk_mode == "general":
        mk_path = selector_paths[assembly.lookup_marker_gid()]
    lk_ctx = (
        lookups, lk_mode, R_args, width, num_partials, chunks_t,
        total_alpha_terms, Cg, Ct, W, K, M,
        tuple(mk_path) if mk_path is not None else None,
    )
    sweep = P._coset_sweep_fn(
        assembly, selector_paths, non_residues, lk_ctx, sm_mesh=smm
    )
    S_cols = capA + 4 + ((width + 2) if lookups else 0)
    add(
        "coset_sweep_terms_limbres" + ("_sm" if smm is not None else ""),
        sweep,
        _sdsp(B_wit, n), _sdsp(B_setup, n), _sdsp(S, n), _sdsp(2, n),
        _i32(), _sdsp(Q * n), _sdsp(Q * n), _sdsp(Q * n), _u32(4, S_cols),
    )
    add(
        "quotient_interp_limbres", RES._quotient_interp_p,
        tuple(_sdsp(n) for _ in range(Q)),
        tuple(_sdsp(n) for _ in range(Q)),
        Q, n,
    )

    # ---- rounds 4-5 plane twins ------------------------------------------
    num_lk = (R_args + 1) if lookups else 0
    num_pi = len(assembly.public_inputs)
    sc4 = _u32(4)
    add(
        "evals_limbres", RES._evals_p, _sdsp(B_all, n), _sdsp(S, n),
        sc4, sc4,
    )
    add("deep_denoms_limbres", RES._deep_denoms_p, _sdsp(N), sc4, sc4)
    add("ext_binv_deep_limbres", lop.ext_batch_inverse_jit, pairp(2, N))
    deep_blocks: set[int] = set()
    for B, streamed_src in (
        (B_wit, stream), (B_setup, stream_setup), (S, stream)
    ):
        if streamed_src:
            for i in range(0, B, COL_BLOCK):
                b32 = min(COL_BLOCK, B - i)
                deep_blocks.add(b32)
                for nm, fn, args in plane_ntt_kernel_specs(
                    b32, log_n, L, mono=False
                ):
                    add(f"deep_regen:{nm}", fn, *args)
        else:
            per = max(1, RES._DEEP_BLOCK_BUDGET // (N * 8))
            for i in range(0, B, per):
                deep_blocks.add(min(per, B - i))
    per = max(1, RES._DEEP_BLOCK_BUDGET // (N * 8))
    for i in range(0, B_q, per):
        deep_blocks.add(min(per, B_q - i))
    if smm is not None and not (stream or stream_setup):
        capE = 2 + num_lk + num_pi
        add(
            "deep_codeword_limbres_sm",
            SS._deep_fn_p(smm, 4, 2, num_lk, num_pi),
            (_sdsp(B_wit, N), _sdsp(B_setup, N), _sdsp(S, N), _sdsp(B_q, N)),
            _sdsp(B_all), _sdsp(B_all), _sdsp(B_all), _sdsp(B_all),
            pairp(N), pairp(N), _sdsp(2, N), _sdsp(2 * num_lk, N),
            _sdsp(N) if lookups else _sdsp(1), _sdsp(num_pi, N),
            _sdsp(num_pi, N), _sdsp(num_pi), pairp(2), pairp(num_lk),
            _sdsp(capE), _sdsp(capE),
        )
    else:
        for b in sorted(deep_blocks):
            add(
                f"deep_block_limbres_b{b}", RES._deep_block_p,
                _sdsp(b, N), _sdsp(b), _sdsp(b),
            )
        add(
            "deep_combine_limbres", RES._deep_combine_p,
            _sdsp(N), _sdsp(N), _sdsp(B_all), _sdsp(B_all),
            _sdsp(B_all), _sdsp(B_all), pairp(N),
        )
        extras = RES._deep_extras_fn_p(2, num_lk, num_pi)
        add(
            "deep_extras_limbres", extras,
            pairp(N), _sdsp(2, N), _sdsp(2 * num_lk, N), _sdsp(num_pi, N),
            pairp(N), _sdsp(N) if lookups else _sdsp(1), _sdsp(num_pi, N),
            pairp(2), pairp(num_lk), _sdsp(num_pi),
            _sdsp(2 + num_lk + num_pi), _sdsp(2 + num_lk + num_pi),
        )
    for nm, fn, args in fri_kernel_specs(n, config, mesh=smm):
        add(nm, fn, *args)

    # ---- cached plane domain tables' inversions --------------------------
    from .fri import fold_schedule

    add("binv_domain_limbres", lop.batch_inverse_jit, _sdsp(N))
    num_folds = sum(
        fold_schedule(
            n, config.fri_final_degree,
            getattr(config, "fri_folding_schedule", None),
        )
    )
    log_full = N.bit_length() - 1
    for r in range(num_folds):
        add(
            f"binv_fold_limbres_r{r}", lop.batch_inverse_jit,
            _sdsp(1 << (log_full - r - 1)),
        )
    if num_pi:
        add("binv_pi_limbres", lop.batch_inverse_jit, _sdsp(num_pi, N))

    seen = set()
    out = []
    for s in specs:
        key = (id(s.fn), repr(s.args))
        if key in seen:
            continue
        seen.add(key)
        out.append(s)
    return out


def precompile(
    assembly,
    config,
    max_workers: int = 8,
    ledger: CompileLedger | None = None,
    lower_only: bool = False,
    mesh_shape=None,
    specs=None,
) -> CompileLedger:
    """Lower + compile the whole kernel library, overlapping the backend
    compiles on a thread pool.

    Tracing/lowering runs on the calling thread (it is Python work and
    would only contend for the GIL); `.compile()` calls — blocking RPCs on
    a tunneled backend — run on up to `max_workers` threads. Failures are
    recorded per-kernel (ledger entry gains an "error" field) and never
    abort the sweep: a kernel that fails to precompile simply compiles at
    first dispatch like before. With `lower_only`, skips the backend
    compile — used by tier-1 tests to validate the enumeration on CPU,
    and still exercises every trace path. `specs` lets a caller that
    already enumerated (the aot.py bundle builder exports the same list)
    skip the second derivation."""
    from .shape_key import bucket_key

    if ledger is None:
        ledger = current_compile_ledger() or CompileLedger()
    # every ledger entry of this sweep carries the shape-bucket key —
    # the SAME key the service admission queue groups requests by
    shape = bucket_key(assembly, config)
    if specs is None:
        with _span("precompile_enumerate", shape=shape):
            specs = enumerate_kernels(
                assembly, config, mesh_shape=mesh_shape
            )
    _metrics.count("precompile.kernels", len(specs))
    # warm the analytic cost sheet from this enumeration so the first
    # recorded prove's cost seam never re-walks it inside its span
    from ..utils import costmodel as _costmodel

    _costmodel.prime_sheet(assembly, config, specs, mesh_shape=mesh_shape)

    lowered = []
    with _span("precompile_lower", kernels=len(specs)):
        for spec in specs:
            t0 = time.perf_counter()
            try:
                low = spec.fn.lower(*spec.args)
            except Exception as e:  # noqa: BLE001 - record and continue
                ledger.record(
                    spec.name, time.perf_counter() - t0, 0.0, error=repr(e),
                    shape_key=shape,
                )
                _metrics.count("precompile.lower_errors")
                continue
            lowered.append((spec, time.perf_counter() - t0, low))

    if lower_only:
        for spec, trace_s, _low in lowered:
            ledger.record(spec.name, trace_s, 0.0, cache_hit=None,
                          shape_key=shape)
        return ledger

    def _compile_one(item):
        spec, trace_s, low = item
        t0 = time.perf_counter()
        try:
            compiled = low.compile()
        except Exception as e:  # noqa: BLE001
            ledger.record(
                spec.name, trace_s, time.perf_counter() - t0, error=repr(e),
                shape_key=shape,
            )
            _metrics.count("precompile.compile_errors")
            return
        dt = time.perf_counter() - t0
        # compile-time cost actuals (ISSUE 12): the executable's own
        # flops / bytes-accessed — the analytic cost sheet's
        # cross-check axis, carried per kernel in the ledger
        from ..utils.costmodel import xla_cost_of

        # sub-100ms "compiles" are persistent-cache loads in practice —
        # a heuristic, but the ledger's monitoring counters carry the
        # authoritative process-wide hit/miss totals
        ledger.record(spec.name, trace_s, dt, cache_hit=dt < 0.1,
                      shape_key=shape, xla_cost=xla_cost_of(compiled))

    def _weight(item):
        # schedule the biggest modules first: with K workers and a handful
        # of minute-scale graphs among hundreds of second-scale ones, the
        # makespan is set by whatever big graph starts LAST. Total input
        # bytes (from the ShapeDtypeStruct args already in hand) is the
        # proxy — rendering every module's MLIR text (len(low.as_text()))
        # ranked similarly but cost multi-MB transient strings and seconds
        # of serial Python on the cold-start path this sweep exists to
        # shorten.
        spec, _t, _low = item

        def arg_bytes(a):
            if isinstance(a, (tuple, list)):
                return sum(arg_bytes(x) for x in a)
            shape = getattr(a, "shape", None)
            if shape is None:
                return 0
            n = 1
            for d in shape:
                n *= int(d)
            itemsize = getattr(getattr(a, "dtype", None), "itemsize", 8)
            return n * itemsize

        return -arg_bytes(spec.args)

    lowered.sort(key=_weight)
    workers = max(1, min(max_workers, len(lowered) or 1))
    # every sweep compile is already record()ed above — keep the ledger's
    # log capture from double-counting them into dispatch_compiles
    ledger.suppress_log_capture = True
    try:
        with _span("precompile_compile_pool", workers=workers):
            with ThreadPoolExecutor(max_workers=workers) as pool:
                list(pool.map(_compile_one, lowered))
    finally:
        ledger.suppress_log_capture = False
    return ledger
