"""BabyBear full-prover stage math (ISSUE 20): the plane-free twins of
prover/stages.py for the REAL PLONKish pipeline — stage-2 grand product and
partial products, lookup sum polynomials, the fused gate/copy-permutation/
lookup quotient sweep, and the DEEP accumulation — all in GF(p^4) over bare
u32 lanes.

Every computation here is written ONCE as a core parameterized over a tiny
`lib` namespace (base/ext field ops + the field-like gate-ops class) and
instantiated twice:

  - DEVICE: jitted `_bb` kernels over `babybear` jnp ops + `BBArrayOps`
    (the dispatch the cost ledger attributes via the `_bb` name suffix);
  - NUMPY:  the same core over `*_np` twins + `BBNpArrayOps` for the
    reference backend (compat/prove_reference_bb.py).

Both backends therefore consume gate terms — and alpha powers — in exactly
the same order; arithmetic is exact mod p on both sides, so proof parity is
by construction and any divergence localizes to one kernel twin.

No `field/limbs.py` import anywhere on this path (the plane-free claim,
`limb.splits == 0`, is structural).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..field import babybear as bb
from ..cs.field_like import BBArrayOps, BBNpArrayOps
from ..cs.gates.base import TermsCollector
from ..utils import metrics as _metrics
from . import bb_kernels as K


class _DevLib:
    """jnp instantiation: bb device ops + BBArrayOps."""

    ops = BBArrayOps
    add = staticmethod(bb.add)
    sub = staticmethod(bb.sub)
    mul = staticmethod(bb.mul)
    ext_add = staticmethod(bb.ext_add)
    ext_sub = staticmethod(bb.ext_sub)
    ext_mul = staticmethod(bb.ext_mul)
    ext_inv = staticmethod(bb.ext_inv)
    ext_prefix_product = staticmethod(bb.ext_prefix_product)

    @staticmethod
    def const(v: int):
        return jnp.uint32(int(v) % bb.P)

    @staticmethod
    def ones_like(x):
        return jnp.ones_like(x)

    @staticmethod
    def stack(xs):
        return jnp.stack(xs)

    @staticmethod
    def broadcast_to(x, shape):
        return jnp.broadcast_to(x, shape)


class _NpLib:
    """numpy instantiation: bb host twins + BBNpArrayOps."""

    ops = BBNpArrayOps
    add = staticmethod(bb.add_np)
    sub = staticmethod(bb.sub_np)
    mul = staticmethod(bb.mul_np)
    ext_add = staticmethod(bb.ext_add_np)

    @staticmethod
    def ext_sub(a, b):
        return tuple(bb.sub_np(x, y) for x, y in zip(a, b))

    ext_mul = staticmethod(bb.ext_mul_np)
    ext_inv = staticmethod(bb.ext_inv_np)
    ext_prefix_product = staticmethod(bb.ext_prefix_product_np)

    @staticmethod
    def const(v: int):
        return np.uint32(int(v) % bb.P)

    @staticmethod
    def ones_like(x):
        return np.ones_like(x)

    @staticmethod
    def stack(xs):
        return np.stack(xs)

    @staticmethod
    def broadcast_to(x, shape):
        return np.broadcast_to(x, shape)


def _ext4(stacked):
    """(4, ...) stacked -> 4-tuple of base arrays/scalars."""
    return tuple(stacked[k] for k in range(4))


def ext_powers_table_bb(e, count: int) -> np.ndarray:
    """(4, count) u32 host table of ext powers 1, e, e^2, ... (the BB
    AlphaPows supply: built on host, consumed as an array argument so new
    challenges never retrace the sweep)."""
    out = np.zeros((4, max(count, 1)), dtype=np.uint32)
    cur = bb.ONE_S
    for i in range(max(count, 1)):
        for k in range(4):
            out[k, i] = cur[k]
        cur = bb.ext_mul_s(cur, tuple(int(c) for c in e))
    return out


# ---------------------------------------------------------------------------
# Shared cores (lib-parameterized; see module docstring)
# ---------------------------------------------------------------------------


def _cp_num_den(lib, wcol, scol, kx, beta, gamma):
    """The copy-permutation rational's numerator (w + β·k·x + γ) and
    denominator (w + β·σ + γ) as ext 4-tuples over base arrays."""
    num = (
        lib.add(lib.add(wcol, lib.mul(kx, beta[0])), gamma[0]),
        lib.add(lib.mul(kx, beta[1]), gamma[1]),
        lib.add(lib.mul(kx, beta[2]), gamma[2]),
        lib.add(lib.mul(kx, beta[3]), gamma[3]),
    )
    den = (
        lib.add(lib.add(wcol, lib.mul(scol, beta[0])), gamma[0]),
        lib.add(lib.mul(scol, beta[1]), gamma[1]),
        lib.add(lib.mul(scol, beta[2]), gamma[2]),
        lib.add(lib.mul(scol, beta[3]), gamma[3]),
    )
    return num, den


def _stage2_core(lib, copy_vals, sigma_vals, ks, xs, beta, gamma, chunks):
    """z and partial products over H (stages.compute_copy_permutation_stage2
    twin): per-chunk num/den products, ONE stacked ext inversion, exclusive
    ext prefix product, cumulative partials. Returns a (1 + num_partials,
    4, n) stack [z; p_0; ...]."""
    n = copy_vals.shape[-1]
    num_ps, den_ps = [], []
    for chunk in chunks:
        num_p = den_p = None
        for col in chunk:
            kx = lib.mul(xs, lib.const(int(ks[col])))
            num, den = _cp_num_den(
                lib, copy_vals[col], sigma_vals[col], kx, beta, gamma
            )
            num_p = num if num_p is None else lib.ext_mul(num_p, num)
            den_p = den if den_p is None else lib.ext_mul(den_p, den)
        num_ps.append(num_p)
        den_ps.append(den_p)
    Kc = len(chunks)
    den_stack = tuple(lib.stack([d[k] for d in den_ps]) for k in range(4))
    den_inv = lib.ext_inv(den_stack)
    ratios = [
        lib.ext_mul(num_ps[j], tuple(den_inv[k][j] for k in range(4)))
        for j in range(Kc)
    ]
    full = ratios[0]
    for j in range(1, Kc):
        full = lib.ext_mul(full, ratios[j])
    incl = lib.ext_prefix_product(full)
    one = lib.ones_like(incl[0][..., :1])
    zero = lib.mul(one, lib.const(0))
    cat = jnp.concatenate if lib is _DevLib else np.concatenate
    z = tuple(
        cat([one if k == 0 else zero, incl[k][..., :-1]], axis=-1)
        for k in range(4)
    )
    rows = [lib.stack(z)]
    acc = z
    for j in range(Kc - 1):
        acc = lib.ext_mul(acc, ratios[j])
        rows.append(lib.stack(acc))
    return lib.stack(rows)


def _ext_powers_seq(lib, g, count: int):
    """[1, g, ..., g^(count-1)] as ext 4-tuples of scalars (host-loop of
    traced/np ext muls — the gamma-power ladder of the lookup aggregator)."""
    one = lib.const(1)
    zero = lib.const(0)
    pows = [(one, zero, zero, zero)]
    for _ in range(count - 1):
        pows.append(lib.ext_mul(pows[-1], g))
    return pows


def _aggregate_lookup(lib, cols, tid_col, gpow, beta, shape):
    """Σ_j γ^j·col_j (+ γ^w·table_id) + β -> ext 4-tuple over base arrays
    (stages.aggregate_lookup_columns twin)."""
    acc = tuple(lib.broadcast_to(beta[k], shape) for k in range(4))
    seq = list(cols) + ([tid_col] if tid_col is not None else [])
    for j, col in enumerate(seq):
        acc = tuple(
            lib.add(acc[k], lib.mul(col, gpow[j][k])) for k in range(4)
        )
    return acc


def _lookup_polys_core(
    lib, lookup_cols, tid_col, table_cols, mults, lkb, lkg, R, width
):
    """A_i and B over H (stages.compute_lookup_polys twin, SPECIALIZED
    columns mode): (R+1, 4, n) stack [A_0..A_{R-1}; B]."""
    shape = tid_col.shape
    gpow = _ext_powers_seq(lib, lkg, width + 1)
    dens = []
    for i in range(R):
        cols = [lookup_cols[i * width + j] for j in range(width)]
        dens.append(_aggregate_lookup(lib, cols, tid_col, gpow, lkb, shape))
    dens.append(
        _aggregate_lookup(
            lib,
            [table_cols[j] for j in range(width)],
            table_cols[width],
            gpow,
            lkb,
            shape,
        )
    )
    den_stack = tuple(lib.stack([d[k] for d in dens]) for k in range(4))
    inv = lib.ext_inv(den_stack)
    rows = [lib.stack([inv[k][i] for k in range(4)]) for i in range(R)]
    rows.append(lib.stack([lib.mul(inv[k][R], mults) for k in range(4)]))
    return lib.stack(rows)


class _ApCursor:
    """Sequential ext-challenge-power supply over a (4, T) table — the BB
    AlphaPows: over-consumption is a prover term-count bug, fail loudly."""

    def __init__(self, table, count: int):
        self.table = table
        self.count = count
        self.cursor = 0

    def take1(self):
        assert self.cursor < self.count, "BB alpha powers over-consumed"
        t = self.cursor
        self.cursor += 1
        return tuple(self.table[k][t] for k in range(4))


def _acc_base_term(lib, acc, term_base, ch):
    """acc += ch * term for a base-field term array, ext 4-tuple ch."""
    t = tuple(lib.mul(term_base, ch[k]) for k in range(4))
    if acc is None:
        return t
    return lib.ext_add(acc, t)


def _acc_ext_term(lib, acc, term_ext, ch):
    t = lib.ext_mul(term_ext, ch)
    if acc is None:
        return t
    return lib.ext_add(acc, t)


def _selector_poly(lib, const_cols, path):
    """Product over path bits of c_b or (1 - c_b)."""
    sel = None
    for b, bit in enumerate(path):
        col = const_cols[b]
        f = (
            col
            if bit
            else lib.sub(lib.mul(lib.ones_like(col), lib.const(1)), col)
        )
        sel = f if sel is None else lib.mul(sel, f)
    return sel


class _RowViewBB:
    """stages.LdeRowView twin over the flattened BB sweep stacks."""

    def __init__(self, copy_v, wit_v, const_v, vo, wo, ko):
        self._c, self._w, self._k = copy_v, wit_v, const_v
        self._vo, self._wo, self._ko = vo, wo, ko

    def v(self, i):
        return self._c[self._vo + i]

    def w(self, i):
        return self._w[self._wo + i]

    def c(self, i):
        return self._k[self._ko + i]


def _sweep_core(
    lib, gates, selector_paths, geometry, lk_ctx, non_residues,
    wit_v, setup_v, s2_v, zs_v, xs, l0, zh_inv,
    apows_tbl, total_alpha_terms, beta, gamma, lkb, lkg,
):
    """The fused quotient terms over the (rate-Q) sweep domain: gate sweep
    + copy-permutation terms + lookup terms, divided by Z_H. Term (and
    therefore alpha-power) order is the GL prover's: gates -> cp -> lookup
    (prover._u64_sweep_core). Returns the (4, Q*n) ext accumulator."""
    (lookups, R_args, width, num_partials, chunks, Cg, Ct, W, Kc, M) = lk_ctx
    ap = _ApCursor(apows_tbl, total_alpha_terms)
    copy_v = wit_v[:Ct]
    gate_wit_v = wit_v[Ct : Ct + W] if W else None
    sigma_v = setup_v[:Ct]
    const_v = setup_v[Ct : Ct + Kc]
    table_v = setup_v[Ct + Kc :]
    z_v = _ext4(s2_v[0:4])
    z_shift_v = _ext4(zs_v)
    partial_v = [
        _ext4(s2_v[4 + 4 * j : 8 + 4 * j]) for j in range(num_partials)
    ]
    acc = None
    # --- gate terms (selector-tree masked evaluation) ---
    for gid, gate in enumerate(gates):
        if gate.num_terms == 0:
            continue
        sel = _selector_poly(lib, const_v, selector_paths[gid])
        reps = gate.num_repetitions(geometry)
        gate_acc = None
        for inst in range(reps):
            row = _RowViewBB(
                copy_v[:Cg], gate_wit_v, const_v,
                inst * gate.principal_width,
                inst * gate.witness_width,
                len(selector_paths[gid]),
            )
            dst = TermsCollector()
            gate.evaluate(lib.ops, row, dst)
            assert len(dst.terms) == gate.num_terms, gate.name
            for term in dst.terms:
                gate_acc = _acc_base_term(lib, gate_acc, term, ap.take1())
        if gate_acc is not None:
            if sel is not None:
                gate_acc = tuple(lib.mul(c, sel) for c in gate_acc)
            acc = gate_acc if acc is None else lib.ext_add(acc, gate_acc)
    # --- copy-permutation terms ---
    zm1 = (lib.sub(z_v[0], lib.ones_like(z_v[0])),) + z_v[1:]
    t0 = tuple(lib.mul(c, l0) for c in zm1)
    acc = _acc_ext_term(lib, acc, t0, ap.take1())
    lhs_seq = list(partial_v) + [z_shift_v]
    rhs_seq = [z_v] + list(partial_v)
    for j, chunk in enumerate(chunks):
        num_p = den_p = None
        for col in chunk:
            kx = lib.mul(xs, lib.const(int(non_residues[col])))
            num, den = _cp_num_den(
                lib, copy_v[col], sigma_v[col], kx, beta, gamma
            )
            num_p = num if num_p is None else lib.ext_mul(num_p, num)
            den_p = den if den_p is None else lib.ext_mul(den_p, den)
        term = lib.ext_sub(
            lib.ext_mul(lhs_seq[j], den_p), lib.ext_mul(rhs_seq[j], num_p)
        )
        acc = _acc_ext_term(lib, acc, term, ap.take1())
    # --- lookup terms (specialized columns mode) ---
    if lookups:
        ab_off = 4 + 4 * num_partials
        a_v = [
            _ext4(s2_v[ab_off + 4 * i : ab_off + 4 * i + 4])
            for i in range(R_args)
        ]
        b_v = _ext4(s2_v[ab_off + 4 * R_args : ab_off + 4 * R_args + 4])
        gpow = _ext_powers_seq(lib, lkg, width + 1)
        tid_v = const_v[Kc - 1]
        for i in range(R_args):
            cols = [copy_v[Cg + i * width + j] for j in range(width)]
            den = _aggregate_lookup(lib, cols, tid_v, gpow, lkb, xs.shape)
            term = lib.ext_mul(a_v[i], den)
            term = (lib.sub(term[0], lib.ones_like(term[0])),) + term[1:]
            acc = _acc_ext_term(lib, acc, term, ap.take1())
        t_den = _aggregate_lookup(
            lib,
            [table_v[j] for j in range(width)],
            table_v[width],
            gpow,
            lkb,
            xs.shape,
        )
        term = lib.ext_mul(b_v, t_den)
        term = (lib.sub(term[0], wit_v[Ct + W]),) + term[1:]
        acc = _acc_ext_term(lib, acc, term, ap.take1())
    assert ap.cursor == total_alpha_terms, (ap.cursor, total_alpha_terms)
    return tuple(lib.mul(c, zh_inv) for c in acc)


def _modsum0(lib, a):
    """Exact mod-p sum along axis 0 (log-depth fold of lib.add)."""
    while a.shape[0] > 1:
        half = a.shape[0] // 2
        rest = a[2 * half :]
        a = lib.add(a[0:half], a[half : 2 * half])
        if rest.shape[0]:
            cat = jnp.concatenate if lib is _DevLib else np.concatenate
            a = cat([a, rest], axis=0)
    return a[0]


def _base_minus_ext(lib, base_arr, e):
    """(base - e) as an ext 4-tuple (bb_kernels twin over lib)."""
    shape = base_arr.shape
    return (
        lib.sub(base_arr, lib.broadcast_to(e[0], shape)),
        lib.broadcast_to(lib.sub(lib.const(0), e[1]), shape),
        lib.broadcast_to(lib.sub(lib.const(0), e[2]), shape),
        lib.broadcast_to(lib.sub(lib.const(0), e[3]), shape),
    )


def _deep_core(
    lib, all_lde, zw_cols, lk_cols, pi_cols, xs,
    z4, zw4, ch_tbl, at_z_const, y_zw, y_lk, pi_vals, pi_inv,
    num_lk, num_pi,
):
    """The BB DEEP codeword (4, N) — challenge-power order mirrors the GL
    prover exactly: all committed base columns at z (grouped: Σ ch_i·f_i
    minus the host-precomputed Σ ch_i·v_i constant), then the z-poly's 4
    base columns at z·omega, then each lookup A_i/B ext pair at 0, then the
    public-input opens."""
    B = all_lde.shape[0]
    # main at-z group: num_k = Σ_i ch_i[k]·f_i − const_k, ÷ (x − z)
    num = tuple(
        lib.sub(
            _modsum0(lib, lib.mul(all_lde, ch_tbl[k][:B][:, None])),
            lib.broadcast_to(at_z_const[k], xs.shape),
        )
        for k in range(4)
    )
    inv_xz = lib.ext_inv(_base_minus_ext(lib, xs, z4))
    h = lib.ext_mul(num, inv_xz)
    # z-poly base columns at z*omega (one challenge power per base column)
    inv_xzw = lib.ext_inv(_base_minus_ext(lib, xs, zw4))
    t = B
    for i in range(4):
        ch = tuple(ch_tbl[k][t] for k in range(4))
        num_i = _base_minus_ext(lib, zw_cols[i], _ext4(y_zw[:, i]))
        h = lib.ext_add(h, lib.ext_mul(lib.ext_mul(num_i, inv_xzw), ch))
        t += 1
    # lookup A_i/B at 0: ext numerator over the 4 base columns, ÷ x
    if num_lk:
        inv_x = lib.ext_inv(
            (xs, lib.mul(xs, lib.const(0)),
             lib.mul(xs, lib.const(0)), lib.mul(xs, lib.const(0)))
        )
        for i in range(num_lk):
            ch = tuple(ch_tbl[k][t] for k in range(4))
            num_i = tuple(
                lib.sub(
                    lk_cols[4 * i + k],
                    lib.broadcast_to(y_lk[i, k], xs.shape),
                )
                for k in range(4)
            )
            h = lib.ext_add(h, lib.ext_mul(lib.ext_mul(num_i, inv_x), ch))
            t += 1
    # public inputs: (w_col(x) − value) / (x − ω^row), base × ext ch
    for k_pi in range(num_pi):
        ch = tuple(ch_tbl[k][t] for k in range(4))
        num_b = lib.mul(
            lib.sub(pi_cols[k_pi], lib.broadcast_to(pi_vals[k_pi], xs.shape)),
            pi_inv[k_pi],
        )
        h = lib.ext_add(h, tuple(lib.mul(num_b, ch[k]) for k in range(4)))
        t += 1
    return lib.stack(h)


# ---------------------------------------------------------------------------
# Device kernels (the full-prover `_bb` ledger set)
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnums=(2, 6))
def stage2_z_partials_bb(copy_vals, sigma_vals, ks, xs, beta, gamma, chunks):
    """(1 + num_partials, 4, n) device stack [z; partials...]. `ks` (the
    non-residues) and `chunks` are static tuples."""
    return _stage2_core(
        _DevLib, copy_vals, sigma_vals, ks, xs,
        _ext4(beta), _ext4(gamma), chunks,
    )


@functools.partial(jax.jit, static_argnums=(6, 7))
def lookup_polys_bb(
    lookup_cols, tid_col, table_cols, mults, lkb, lkg, R: int, width: int
):
    """(R+1, 4, n) device stack [A_0..A_{R-1}; B]."""
    return _lookup_polys_core(
        _DevLib, lookup_cols, tid_col, table_cols, mults,
        _ext4(lkb), _ext4(lkg), R, width,
    )


def stage2_z_partials_np(copy_vals, sigma_vals, ks, xs, beta, gamma, chunks):
    """Numpy twin of stage2_z_partials_bb (reference backend)."""
    return np.asarray(
        _stage2_core(
            _NpLib, copy_vals, sigma_vals, ks, xs,
            _ext4(np.asarray(beta, dtype=np.uint32)),
            _ext4(np.asarray(gamma, dtype=np.uint32)), chunks,
        )
    )


def lookup_polys_np(
    lookup_cols, tid_col, table_cols, mults, lkb, lkg, R: int, width: int
):
    """Numpy twin of lookup_polys_bb (reference backend)."""
    return np.asarray(
        _lookup_polys_core(
            _NpLib, lookup_cols, tid_col, table_cols, mults,
            _ext4(np.asarray(lkb, dtype=np.uint32)),
            _ext4(np.asarray(lkg, dtype=np.uint32)), R, width,
        )
    )


def build_full_sweep_bb(gates, selector_paths, geometry, lk_ctx, non_residues):
    """Assembly-cached jitted quotient-terms graph over the whole rate-Q
    sweep domain (the BB twin of prover._coset_sweep_fn at 2^10-scale: one
    graph over Q·n points instead of Q per-coset dispatches)."""
    _metrics.count("gate_sweep.bb_builds")
    gates = tuple(gates)
    selector_paths = tuple(tuple(p) for p in selector_paths)
    non_residues = tuple(int(k) for k in non_residues)
    total = lk_ctx[-1]
    lk_core = lk_ctx[:-1]

    @jax.jit
    def fn(wit_v, setup_v, s2_v, zs_v, xs, l0, zh_inv, apows,
           beta, gamma, lkb, lkg):
        return jnp.stack(
            _sweep_core(
                _DevLib, gates, selector_paths, geometry, lk_core,
                non_residues, wit_v, setup_v, s2_v, zs_v, xs, l0, zh_inv,
                _ext4(apows), total, _ext4(beta), _ext4(gamma),
                _ext4(lkb), _ext4(lkg),
            )
        )

    return fn


def full_sweep_np(
    gates, selector_paths, geometry, lk_ctx, non_residues,
    wit_v, setup_v, s2_v, zs_v, xs, l0, zh_inv, apows,
    beta, gamma, lkb, lkg,
):
    """The numpy twin of build_full_sweep_bb's graph (same cores)."""
    total = lk_ctx[-1]
    return np.stack(
        _sweep_core(
            _NpLib, tuple(gates), tuple(tuple(p) for p in selector_paths),
            geometry, lk_ctx[:-1], tuple(int(k) for k in non_residues),
            wit_v, setup_v, s2_v, zs_v, xs, l0, zh_inv,
            _ext4(apows), total, _ext4(beta), _ext4(gamma),
            _ext4(lkb), _ext4(lkg),
        )
    )


@functools.partial(jax.jit, static_argnums=(13, 14))
def deep_full_bb(
    all_lde, zw_cols, lk_cols, pi_cols, xs, z4, zw4, ch_tbl,
    at_z_const, y_zw, y_lk, pi_vals, pi_inv, num_lk: int, num_pi: int,
):
    """The full-prover DEEP codeword (4, N), device."""
    return _deep_core(
        _DevLib, all_lde, zw_cols, lk_cols, pi_cols, xs,
        _ext4(z4), _ext4(zw4), _ext4(ch_tbl), _ext4(at_z_const),
        y_zw, y_lk, pi_vals, pi_inv, num_lk, num_pi,
    )


def deep_full_np(
    all_lde, zw_cols, lk_cols, pi_cols, xs, z4, zw4, ch_tbl,
    at_z_const, y_zw, y_lk, pi_vals, pi_inv, num_lk: int, num_pi: int,
):
    return _deep_core(
        _NpLib, all_lde, zw_cols, lk_cols, pi_cols, xs,
        _ext4(z4), _ext4(zw4), _ext4(ch_tbl), _ext4(at_z_const),
        y_zw, y_lk, pi_vals, pi_inv, num_lk, num_pi,
    )


# ---------------------------------------------------------------------------
# Host domain tables (witness-independent, cached per domain shape)
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def l0_lde_bb(log_n: int, rate: int, shift: int) -> np.ndarray:
    """L_0(x) = (x^n − 1)/(n·(x − 1)) over the natural-order rate-`rate`
    coset shift·<w_N> — the full-prover twin of prover._l0_brev."""
    n = 1 << log_n
    zh = bb.sub_np(
        np.tile(
            np.array(
                [
                    bb.mul_s(
                        bb.pow_s(shift % bb.P, n),
                        bb.pow_s(bb.omega(rate.bit_length() - 1), r),
                    )
                    for r in range(rate)
                ],
                dtype=np.uint32,
            ),
            n,
        ),
        np.uint32(1),
    )
    xs = K.domain_xs_bb(log_n, rate, shift)
    xm1_inv = K._host_batch_inv(bb.sub_np(xs, np.uint32(1)))
    return bb.mul_np(bb.mul_np(zh, np.uint32(bb.inv_s(n))), xm1_inv)
