"""BabyBear plane-free kernel twins of the prover hot path (ISSUE 19).

One u32 lane per field element end-to-end: the quotient sweep, the DEEP
codeword and the FRI fold chain below never touch `field/limbs.py` — there
are no (lo, hi) planes to split or join, so the interior-conversion
counters (`limb.splits`/`limb.joins`) stay at ZERO by construction and
every array moves HALF the HBM bytes of its limb-resident Goldilocks twin.

Layout contract: everything is NATURAL order over the coset
shift*<w_N>, N = n * lde_factor (ntt/bb_ntt.py). Extension values are
4-tuples of base u32 arrays stacked to (4, ...) at kernel boundaries.

Host-side tables (domain points, vanishing inverses, per-round fold
twiddles) are lru_cached python/numpy — they depend only on the domain
shape, never on witness data.

Ledger names follow the variant-keyed pattern PR 9 set up
(`coset_sweep_terms_bb`, `fri_fold_bb_k*`): prover/precompile.py
enumerates exactly this set when BOOJUM_TPU_FIELD=babybear, and
utils/costmodel.py prices the `_bb` names at 4 bytes/element.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..field import babybear as bb
from ..field.spec import BABYBEAR as SPEC
from ..hashes import poseidon2_bb as p2bb
from ..ntt import bb_ntt

INV2 = SPEC.half  # (p+1)/2 — satellite: read from the FieldSpec seam


# ---------------------------------------------------------------------------
# Host domain tables (witness-independent, cached per domain shape)
# ---------------------------------------------------------------------------


def _host_batch_inv(vals):
    """Batch inverse of a uint32 numpy vector via Montgomery's trick on
    python ints (one modular inversion total)."""
    xs = [int(v) for v in vals]
    pref = [1] * (len(xs) + 1)
    for i, x in enumerate(xs):
        pref[i + 1] = (pref[i] * x) % bb.P
    acc = bb.inv_s(pref[-1])
    out = [0] * len(xs)
    for i in range(len(xs) - 1, -1, -1):
        out[i] = (pref[i] * acc) % bb.P
        acc = (acc * xs[i]) % bb.P
    return np.array(out, dtype=np.uint32)


@functools.lru_cache(maxsize=None)
def domain_xs_bb(log_n: int, lde_factor: int, shift: int):
    """Natural-order coset points x_j = shift * w_N^j, j < N."""
    N = (1 << log_n) * lde_factor
    w = bb.omega(N.bit_length() - 1)
    return bb.mul_np(bb.powers_np(w, N), np.uint32(shift % bb.P))


@functools.lru_cache(maxsize=None)
def zh_inv_bb(log_n: int, lde_factor: int, shift: int):
    """1 / (x_j^n - 1) over the coset. Z_H(x_j) = shift^n * w_L^(j mod L)
    - 1 takes only L distinct values (w_N^n has order L), so the table is
    L inversions tiled to N."""
    n = 1 << log_n
    L = lde_factor
    sh_n = bb.pow_s(shift % bb.P, n)
    wl = bb.omega(L.bit_length() - 1)
    base = [
        bb.sub_s(bb.mul_s(sh_n, bb.pow_s(wl, r)), 1) for r in range(L)
    ]
    return np.tile(_host_batch_inv(np.array(base, dtype=np.uint32)),
                   n)


@functools.lru_cache(maxsize=None)
def last_row_term_bb(log_n: int, lde_factor: int, shift: int):
    """(x_j - g^(n-1)) over the coset — the transition constraint's
    excluded-row factor."""
    g_last = bb.pow_s(bb.omega(log_n), (1 << log_n) - 1)
    return bb.sub_np(domain_xs_bb(log_n, lde_factor, shift),
                     np.uint32(g_last))


@functools.lru_cache(maxsize=None)
def boundary_inv_bb(log_n: int, lde_factor: int, shift: int):
    """1 / (x_j - 1) over the coset (x = 1 is never on a proper coset,
    so the subtraction never hits zero)."""
    xs = domain_xs_bb(log_n, lde_factor, shift)
    return _host_batch_inv(bb.sub_np(xs, np.uint32(1)))


@functools.lru_cache(maxsize=None)
def fri_fold_tables_bb(log_N: int, shift: int, num_rounds: int):
    """Per-round odd-part twiddles: round r folds the length N_r = N>>r
    codeword over shift^(2^r)*<w_{N_r}> by pairing j with j + N_r/2;
    table[r][j] = 1 / (2 * x_j^(r)) for j < N_r/2 — the 1/2 of the even
    part is folded into INV2 at the kernel."""
    tables = []
    for r in range(num_rounds):
        log_r = log_N - r
        half = 1 << (log_r - 1)
        sh = bb.pow_s(shift % bb.P, 1 << r)
        w = bb.omega(log_r)
        xs = bb.mul_np(bb.powers_np(w, half), np.uint32(sh))
        tables.append(_host_batch_inv(bb.mul_np(xs, np.uint32(2))))
    return tuple(tables)


# ---------------------------------------------------------------------------
# Device kernels (the `_bb` ledger set)
# ---------------------------------------------------------------------------


def _ext_tuple(stacked):
    """(4, ...) stacked array -> 4-tuple of base arrays."""
    return tuple(stacked[k] for k in range(4))


def _base_minus_ext(base_arr, e):
    """(base - e) as an ext 4-tuple: coordinate 0 subtracts, coordinates
    1..3 are the broadcast negations of e's."""
    shape = base_arr.shape
    return (
        bb.sub(base_arr, jnp.broadcast_to(e[0], shape)),
        jnp.broadcast_to(bb.neg(e[1]), shape),
        jnp.broadcast_to(bb.neg(e[2]), shape),
        jnp.broadcast_to(bb.neg(e[3]), shape),
    )


@functools.partial(jax.jit, static_argnums=(6,))
def coset_sweep_terms_bb(
    w_lde, alpha, c_pub, last_tbl, zh_inv_tbl, bnd_inv_tbl, lde_factor: int
):
    """The fused BabyBear quotient sweep over the LDE coset: transition
    quotient (w(gx) - w(x)^2 - c) * (x - g_last) / Z_H(x) plus
    alpha * boundary quotient (w(x) - pub) / (x - 1), emitted as the
    ext quotient's 4 base coordinate columns (4, N).

    w(g*x) on the natural-order coset is a roll by -L (g*x_j = x_{j+L}
    mod N). `c_pub` is the (c, pub) public-parameter pair; the division
    tables arrive precomputed (witness-independent)."""
    wg = jnp.roll(w_lde, -lde_factor)
    trans = bb.sub(wg, bb.add(bb.sqr(w_lde), c_pub[0]))
    qt = bb.mul(bb.mul(trans, last_tbl), zh_inv_tbl)
    qb = bb.mul(bb.sub(w_lde, c_pub[1]), bnd_inv_tbl)
    out = [bb.add(qt, bb.mul(qb, alpha[0]))]
    out += [bb.mul(qb, alpha[k]) for k in range(1, 4)]
    return jnp.stack(out)


@jax.jit
def deep_accumulate_bb(
    w_lde, q_cols, xs, z, gz, wz, wgz, qz, gammas
):
    """The BabyBear DEEP codeword (4, N): gamma-combined out-of-domain
    quotients of every committed column, grouped by denominator —

      [g0*(w - w(z)) + sum_k g_{2+k}*(Q_k - Q_k(z))] / (x - z)
      + g1*(w - w(gz)) / (x - gz)

    Denominator inverses are the vectorized Frobenius/norm ext inverse
    (babybear.ext_inv) — no host round-trip, no limb planes."""
    zt = _ext_tuple(z)
    gzt = _ext_tuple(gz)
    num = bb.ext_mul(_ext_tuple(gammas[0]), _base_minus_ext(w_lde, _ext_tuple(wz)))
    for k in range(4):
        num = bb.ext_add(
            num,
            bb.ext_mul(
                _ext_tuple(gammas[2 + k]),
                _base_minus_ext(q_cols[k], _ext_tuple(qz[k])),
            ),
        )
    d1 = bb.ext_mul(num, bb.ext_inv(_base_minus_ext(xs, zt)))
    shifted = bb.ext_mul(
        _ext_tuple(gammas[1]), _base_minus_ext(w_lde, _ext_tuple(wgz))
    )
    d2 = bb.ext_mul(shifted, bb.ext_inv(_base_minus_ext(xs, gzt)))
    return jnp.stack(bb.ext_add(d1, d2))


@jax.jit
def fri_fold_bb(codeword, beta, inv2x):
    """One factor-2 natural-order fold of a (4, M) ext codeword:
    f'(x^2) = (f(x) + f(-x))/2 + beta * (f(x) - f(-x))/(2x), pairing
    j with j + M/2; `inv2x` is the precomputed base 1/(2x_j) table, so
    the odd part costs 4 base muls before the single ext mul by beta."""
    half = codeword.shape[-1] // 2
    a = _ext_tuple(codeword[:, :half])
    b = _ext_tuple(codeword[:, half:])
    even = tuple(bb.mul_const(bb.add(x, y), INV2) for x, y in zip(a, b))
    odd = tuple(bb.mul(bb.sub(x, y), inv2x) for x, y in zip(a, b))
    out = bb.ext_add(even, bb.ext_mul(_ext_tuple(beta), odd))
    return jnp.stack(out)


# --- Merkle commit twins (digest = 8 u32 lanes) ----------------------------


@jax.jit
def leaf_digests_bb(cols):
    """(B, N) committed columns -> (N, 8) BabyBear leaf digests; the
    leaf-major transpose happens inside the graph (merkle.py idiom)."""
    return p2bb._sponge_hash_bb(
        cols.reshape(cols.shape[0], -1).T, p2bb.poseidon2_permutation_bb_xla
    )


@functools.partial(jax.jit, static_argnums=(1,))
def node_layers_bb(digests, cap_size: int):
    """(N, 8) leaf digests -> all node layers up to the cap, one
    dispatch, keyed only on (N, cap)."""
    layers = [digests]
    while layers[-1].shape[0] > cap_size:
        cur = layers[-1]
        layers.append(p2bb.node_hash_bb_xla(cur[0::2], cur[1::2]))
    return tuple(layers)


class BBMerkleTree:
    """Cap-terminated Merkle tree over 8-lane BabyBear digests. Layers
    are held as host numpy (the BB demo domains are tiny: <= 2^12 x 8
    u32); the DEVICE work — leaf sponge + node stack — happened in the
    backend's commit kernels before construction."""

    def __init__(self, layers, cap_size: int):
        self.layers = [np.asarray(l) for l in layers]
        self.cap_size = cap_size
        self.num_leaves = int(self.layers[0].shape[0])

    def get_cap(self):
        return [tuple(int(x) for x in row) for row in self.layers[-1]]

    def get_path(self, leaf_idx: int):
        path = []
        idx = int(leaf_idx)
        for layer in self.layers[:-1]:
            path.append(tuple(int(x) for x in layer[idx ^ 1]))
            idx >>= 1
        return path


def verify_path_bb(leaf_values, path, cap, leaf_idx: int) -> bool:
    """Host-side BabyBear path verification against a cap."""
    digest = p2bb.leaf_hash_bb_host([int(v) for v in leaf_values])
    idx = int(leaf_idx)
    for sib in path:
        if idx & 1:
            digest = p2bb.node_hash_bb_host(sib, digest)
        else:
            digest = p2bb.node_hash_bb_host(digest, sib)
        idx >>= 1
    return tuple(digest) == tuple(cap[idx])


# ---------------------------------------------------------------------------
# Precompile enumeration: the `_bb` kernel library
# ---------------------------------------------------------------------------


def bb_kernel_specs(log_n: int, lde_factor: int, cap_size: int) -> list:
    """(name, jitted_fn, ShapeDtypeStruct args) triples for every
    top-level executable a BabyBear prove of this domain dispatches —
    the variant-keyed twin of fri_kernel_specs/enumerate_kernels, so
    prover/precompile.py can lower/compile the `_bb` set concurrently.
    No device memory is allocated."""

    def u32(*shape):
        return jax.ShapeDtypeStruct(shape, jnp.uint32)

    n = 1 << log_n
    N = n * lde_factor
    log_N = N.bit_length() - 1
    num_folds = log_N - 5  # fold to a 32-point final codeword
    specs = [
        (f"imono_bb_n{n}",
         bb_ntt.monomial_from_values_bb, (u32(n), log_n)),
        (f"lde_bb_L{lde_factor}_n{n}",
         bb_ntt.lde_from_monomial_bb,
         (u32(n), log_n, lde_factor,
          SPEC.multiplicative_generator)),
        (f"leaf_digests_bb_n{N}x1", leaf_digests_bb, (u32(1, N),)),
        (f"leaf_digests_bb_n{N}x4", leaf_digests_bb, (u32(4, N),)),
        (f"node_layers_bb_n{N}", node_layers_bb, (u32(N, 8), cap_size)),
        (f"coset_sweep_terms_bb_n{N}",
         coset_sweep_terms_bb,
         (u32(N), u32(4), u32(2), u32(N), u32(N), u32(N), lde_factor)),
        (f"deep_accumulate_bb_n{N}",
         deep_accumulate_bb,
         (u32(N), u32(4, N), u32(N), u32(4), u32(4), u32(4), u32(4),
          u32(4, 4), u32(6, 4))),
    ]
    cur = N
    for r in range(num_folds):
        specs.append(
            (f"fri_fold_bb_k1_m{cur}",
             fri_fold_bb, (u32(4, cur), u32(4), u32(cur // 2)))
        )
        if r + 1 < num_folds:
            # the paired-leaf commit of the next layer: (cur/2, 8) rows
            specs.append(
                (f"leaf_digests_bb_n{cur // 4}x8",
                 leaf_digests_bb, (u32(8, cur // 4),))
            )
            specs.append(
                (f"node_layers_bb_n{cur // 4}",
                 node_layers_bb, (u32(cur // 4, 8), min(cap_size,
                                                        cur // 4)))
            )
        cur //= 2
    return specs
