"""FRI: commit-and-fold low-degreeness argument over the quadratic extension.

Counterpart of `/root/reference/src/cs/implementations/fri/mod.rs` (do_fri
:49, fold_multiple :362, final monomial interpolation :476). The codeword is
an ext-valued array over the full LDE domain in bit-reversed enumeration, so
fold pairs (x, −x) are ADJACENT (even/odd lanes) and every fold round is two
strided slices + vectorized butterfly — no gather. Oracles follow the folding
schedule: each committed oracle groups 2^k brev-consecutive domain points
(its whole fold subtree) per Merkle leaf, interleaving (c0, c1) per point,
and answers k fold rounds with one drawn challenge (sub-challenges by
squaring).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..field import gl
from ..field import extension as ext_f
from ..field import goldilocks as gf
from ..merkle import MerkleTreeWithCap
from ..utils import metrics as _metrics
from ..utils.report import checkpoint as _checkpoint
from ..utils.spans import span as _span
from ..ntt import (
    bitreverse_indices,
    get_ntt_context,
    distribute_powers,
    ifft_bitreversed_to_natural,
    powers_device,
)
from .stages import ext_scalar
from ..field.spec import GOLDILOCKS as _GL_SPEC

INV2 = _GL_SPEC.half  # (p + 1) / 2 — the fold's 1/2 (field/spec.py seam)


from functools import lru_cache


@lru_cache(maxsize=4)
def fold_challenge_tables(log_full: int, num_rounds: int):
    """Per-round inverse-x tables: round r domain is the coset
    g^(2^r)·H_{N>>r}; table r holds 1/x at pair positions (even bit-reversed
    indices), length (N >> r)/2."""
    tables = []
    for r in range(num_rounds):
        log_nr = log_full - r
        n_r = 1 << log_nr
        shift = gl.pow_(gl.MULTIPLICATIVE_GENERATOR, 1 << r)
        omega = gl.omega(log_nr)
        xs_nat = powers_device(omega, n_r)
        xs_nat = gf.mul(xs_nat, jnp.uint64(shift))
        brev = bitreverse_indices(log_nr)
        xs_brev = xs_nat[jnp.asarray(brev)]
        xs_pairs = xs_brev[0::2]
        tables.append(gf.batch_inverse(xs_pairs))
    return tables


@jax.jit
def _fold_once_jit(values, ch, inv_x_pairs):
    a = (values[0][0::2], values[1][0::2])
    bm = (values[0][1::2], values[1][1::2])
    s = ext_f.add(a, bm)
    d = ext_f.sub(a, bm)
    d_over_x = (gf.mul(d[0], inv_x_pairs), gf.mul(d[1], inv_x_pairs))
    t = ext_f.add(s, ext_f.mul(d_over_x, ch))
    inv2 = jnp.uint64(INV2)
    return (gf.mul(t[0], inv2), gf.mul(t[1], inv2))


@jax.jit
def _fold_once_limb_jit(values, ch, inv_x_pairs):
    """The limb-domain fold kernel (pallas_sweep.fri_fold) under its own
    top-level jit — the unfused path's counterpart of _fold_once_jit."""
    from .pallas_sweep import fri_fold

    return fri_fold(values, ch, inv_x_pairs)


@lru_cache(maxsize=4)
def fold_challenge_tables_p(log_full: int, num_rounds: int):
    """Limb-resident twin of fold_challenge_tables: per-round 1/x PLANE
    pairs. Domain points are host-built numpy (split on host), the shift
    multiply and the Montgomery batch inversion run in the limb domain —
    no device u64 exists anywhere (values are identical: inverses are
    unique mod p and limb ops are exact)."""
    from ..field import limb_ops as lop
    from ..field import limbs
    from ..ntt.ntt import _powers_np

    tables = []
    for r in range(num_rounds):
        log_nr = log_full - r
        shift = gl.pow_(gl.MULTIPLICATIVE_GENERATOR, 1 << r)
        omega = gl.omega(log_nr)
        lo, hi = limbs.split_np(_powers_np(omega, 1 << log_nr))
        xs = (jnp.asarray(lo), jnp.asarray(hi))
        xs = limbs.mul_const(xs, limbs.const_pair(shift))
        brev = jnp.asarray(bitreverse_indices(log_nr))
        xs_pairs = (xs[0][brev][0::2], xs[1][brev][0::2])
        tables.append(lop.batch_inverse_jit(xs_pairs))
    return tables


def _ch_table_np(ch):
    """Host (c0, c1) ext challenge -> (4, 1) u32 scalar table (built on
    host: the resident fold's challenges never touch device u64)."""
    c0, c1 = int(ch[0]), int(ch[1])
    return np.array(
        [
            [c0 & 0xFFFFFFFF], [c0 >> 32],
            [c1 & 0xFFFFFFFF], [c1 >> 32],
        ],
        dtype=np.uint32,
    )


def fold_once(values, challenge, inv_x_pairs):
    """values: ext pair over round-r domain (brev layout); returns N/2 ext.

    f'(x^2) = (f(x)+f(-x))/2 + ch·(f(x)-f(-x))/(2x). Jitted core with the
    challenge as an array argument (new challenges never retrace). With the
    limb sweep on (BOOJUM_TPU_LIMB_SWEEP, prover/pallas_sweep.py) the
    butterfly runs on u32 limb planes — bit-identical output."""
    from .pallas_sweep import limb_sweep_enabled

    fn = _fold_once_limb_jit if limb_sweep_enabled() else _fold_once_jit
    return fn(values, ext_scalar(challenge), inv_x_pairs)


def commit_codeword(
    values, cap_size: int, elems_per_leaf: int = 2
) -> MerkleTreeWithCap:
    """Commit ext codeword: rows (N, 2) = [c0, c1]; `elems_per_leaf` domain
    points per Merkle leaf (leaf regrouping, reference fri/mod.rs:362,699 —
    one oracle then answers a whole 2^k fold subtree per query)."""
    arr = jnp.stack([values[0], values[1]], axis=-1)  # (N, 2)
    return MerkleTreeWithCap(arr, cap_size, num_elems_per_leaf=elems_per_leaf)


def fold_schedule(
    base_degree: int, final_degree: int, explicit=None
) -> list[int]:
    """Per-oracle fold counts (reference interpolation-log2 schedule,
    prover.rs:2281): each oracle folds 2^k-to-1 with one drawn challenge
    (sub-challenges by squaring). Greedy 3s then the remainder, unless an
    explicit schedule is configured."""
    num = 0
    deg = base_degree
    while deg > final_degree:
        deg //= 2
        num += 1
    assert num >= 1, "nothing to fold; lower fri_final_degree"
    if explicit is not None:
        explicit = [int(k) for k in explicit]
        assert sum(explicit) == num and all(k >= 1 for k in explicit), (
            f"folding schedule {explicit} must sum to {num}"
        )
        return explicit
    out = []
    rem = num
    while rem > 3:
        out.append(3)
        rem -= 3
    out.append(rem)
    return out


class FriOracles:
    def __init__(self):
        self.trees: list[MerkleTreeWithCap] = []
        self.values: list = []  # ext pairs per committed oracle (device)
        self.challenges: list = []  # one drawn ext challenge per oracle
        self.schedule: list[int] = []
        self.final_monomials = None  # host list of (c0, c1)


@lru_cache(maxsize=None)
def _fri_commit_fn(k: int, cap: int):
    """Fused oracle commit for one schedule entry: leaf regrouping + leaf
    hashing + every node layer in ONE dispatch."""
    from ..merkle import _tree_layers

    @jax.jit
    def fn(c0, c1):
        arr = jnp.stack([c0, c1], axis=-1)
        N = c0.shape[0]
        leaves = arr.reshape(N >> k, -1)
        return _tree_layers(leaves, cap)

    return fn


@lru_cache(maxsize=None)
def _fri_fold_fn(k: int, limb: bool = False, mesh=None):
    """Fused k-fold for one schedule entry (sub-challenges by squaring).
    With `limb`, each fold runs the u32-limb Pallas kernel
    (pallas_sweep.fri_fold) instead of the emulated-u64 butterfly —
    bit-identical outputs, so the two variants share nothing but math.
    With `mesh` (a shard_map mesh, parallel/shard_sweep.py) the whole
    k-fold chain runs per chip on row shards of the bit-reversed codeword:
    fold pairs are adjacent, so as long as every intermediate local size
    stays even (fri_prove guards divisibility) no fold ever communicates
    — the only collective in FRI is the cap gather at commit time."""

    if limb:
        from .pallas_sweep import fri_fold as fold
    else:
        fold = _fold_once_jit

    if mesh is not None:
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        spec = P(("col", "row"))

        def body(c0, c1, ch01, *tabs):
            cur = (c0, c1)
            sub = (ch01[0], ch01[1])
            for j in range(k):
                cur = fold(cur, sub, tabs[j])
                sub = ext_f.mul(sub, sub)
            return cur

        smf = shard_map(
            body, mesh=mesh,
            in_specs=(spec, spec, P(None)) + (spec,) * k,
            out_specs=(spec, spec), check_rep=False,
        )

        @jax.jit
        def fn(c0, c1, ch01, tables):
            return smf(c0, c1, ch01, *tables)

        return fn

    @jax.jit
    def fn(c0, c1, ch01, tables):
        cur = (c0, c1)
        sub = (ch01[0], ch01[1])
        for j in range(k):
            cur = fold(cur, sub, tables[j])
            sub = ext_f.mul(sub, sub)
        return cur

    return fn


from functools import partial as _partial


@_partial(jax.jit, static_argnums=(2,))
def _fri_final_fused(c0, c1, shift_inv: int):
    """Final-polynomial interpolation (2 iNTTs + coset unshift), fused."""
    m0 = distribute_powers(ifft_bitreversed_to_natural(c0), shift_inv)
    m1 = distribute_powers(ifft_bitreversed_to_natural(c1), shift_inv)
    return m0, m1


# ---------------------------------------------------------------------------
# Limb-resident FRI (ISSUE 10): commit, fold chain and final interpolation
# on (lo, hi) u32 plane pairs — the codeword arrives resident from DEEP and
# never converts; caps and final monomials join on HOST at the API edge.
# ---------------------------------------------------------------------------


@lru_cache(maxsize=None)
def _fri_commit_fn_p(k: int, cap: int):
    """Resident oracle commit: leaf regrouping + plane leaf sponge + plane
    node layers in ONE dispatch (the _fri_commit_fn twin)."""
    from ..hashes.poseidon2 import leaf_hash_planes
    from ..merkle import _node_layers_planes_body

    @jax.jit
    def fn(c0, c1):
        N = c0[0].shape[0]
        llo = jnp.stack([c0[0], c1[0]], axis=-1).reshape(N >> k, -1)
        lhi = jnp.stack([c0[1], c1[1]], axis=-1).reshape(N >> k, -1)
        dig = leaf_hash_planes((llo, lhi))
        return _node_layers_planes_body(dig, cap)

    return fn


@lru_cache(maxsize=None)
def _fri_fold_fn_p(k: int, mesh=None):
    """Resident k-fold for one schedule entry: the whole chain — including
    the squared sub-challenges — runs on planes (pallas_sweep.
    fri_fold_planes), so nothing converts between folds. `tb` is the
    (4, 1) u32 challenge table (host-built)."""
    from ..field import limb_ops as lop
    from .pallas_sweep import fri_fold_planes

    def body(c0, c1, tb, *tabs):
        cur = (c0, c1)
        sub = ((tb[0], tb[1]), (tb[2], tb[3]))
        for j in range(k):
            tbj = jnp.stack([sub[0][0], sub[0][1], sub[1][0], sub[1][1]])
            cur = fri_fold_planes(cur, tbj, tabs[j])
            sub = lop.ext_mul(sub, sub)
        return cur

    if mesh is not None:
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        spec = P(("col", "row"))
        smf = shard_map(
            body, mesh=mesh,
            in_specs=(spec, spec, P(None)) + (spec,) * k,
            out_specs=(spec, spec), check_rep=False,
        )

        @jax.jit
        def fn(c0, c1, tb, tables):
            return smf(c0, c1, tb, *tables)

        return fn

    @jax.jit
    def fn(c0, c1, tb, tables):
        return body(c0, c1, tb, *tables)

    return fn


@_partial(jax.jit, static_argnums=(2,))
def _fri_final_p(c0, c1, shift_inv: int):
    """Resident final interpolation: plane iNTTs + host-built unshift."""
    from ..ntt.limb_ntt import (
        distribute_powers_p,
        ifft_bitreversed_to_natural_p,
    )

    m0 = distribute_powers_p(ifft_bitreversed_to_natural_p(c0), shift_inv)
    m1 = distribute_powers_p(ifft_bitreversed_to_natural_p(c1), shift_inv)
    return m0, m1


def fri_kernel_specs(base_degree: int, config, mesh=None) -> list:
    """(name, jitted_fn, args) triples for every top-level executable a
    fused `fri_prove` dispatches for this (base_degree, config) — the
    per-schedule-entry commit and fold graphs plus the final
    interpolation — so prover/precompile.py can compile them concurrently
    before the first prove. Mirrors the schedule/shape walk of fri_prove;
    args are ShapeDtypeStructs (no device memory)."""

    from .pallas_sweep import limb_resident_enabled, limb_sweep_enabled

    def sds(*shape):
        return jax.ShapeDtypeStruct(shape, jnp.uint64)

    def sdsp(*shape):
        s = jax.ShapeDtypeStruct(shape, jnp.uint32)
        return (s, s)

    N = base_degree * config.fri_lde_factor
    log_full = N.bit_length() - 1
    schedule = fold_schedule(
        base_degree, config.fri_final_degree,
        getattr(config, "fri_folding_schedule", None),
    )
    num_folds = sum(schedule)
    specs = []
    cur = N
    fold_round = 0
    cap = config.merkle_tree_cap_size
    # enumerate the fold variant this process will actually dispatch (the
    # overlap-mode idiom in prover/precompile.py) — compiling the other
    # would be pure waste on the tunnel compiler. Under a shard_map mesh
    # that is the per-chip fold chain, ledger-tagged `_sm`; under limb
    # residency the PLANE chain, ledger-tagged `_limbres`.
    from ..parallel.sharding import shard_map_mesh
    from ..parallel.shard_sweep import fold_shards_ok

    limb = limb_sweep_enabled()
    resident = limb_resident_enabled()
    smm = mesh if mesh is not None else shard_map_mesh()
    fold_tag = "_limbres" if resident else ("_limb" if limb else "")
    for k in schedule:
        mesh_k = smm if smm is not None and fold_shards_ok(cur, k, smm) \
            else None
        if resident:
            ext_p = (sdsp(cur), sdsp(cur))
            if mesh_k is not None:
                from ..parallel.shard_sweep import _fri_leaf_fn_p

                specs.append((
                    f"fri_leaf_limbres_k{k}_n{cur}_sm",
                    _fri_leaf_fn_p(mesh_k, k),
                    ext_p,
                ))
            else:
                specs.append((
                    f"fri_commit_limbres_k{k}_n{cur}",
                    _fri_commit_fn_p(k, cap),
                    ext_p,
                ))
            tables = tuple(
                sdsp(1 << (log_full - fold_round - j - 1)) for j in range(k)
            )
            specs.append((
                f"fri_fold{fold_tag}_k{k}_n{cur}"
                + ("_sm" if mesh_k is not None else ""),
                _fri_fold_fn_p(k, mesh_k),
                ext_p + (jax.ShapeDtypeStruct((4, 1), jnp.uint32), tables),
            ))
            fold_round += k
            cur >>= k
            continue
        if mesh_k is not None:
            from ..parallel.shard_sweep import _fri_leaf_fn

            specs.append((
                f"fri_leaf_k{k}_n{cur}_sm",
                _fri_leaf_fn(mesh_k, k),
                (sds(cur), sds(cur)),
            ))
        else:
            specs.append((
                f"fri_commit_k{k}_n{cur}",
                _fri_commit_fn(k, cap),
                (sds(cur), sds(cur)),
            ))
        tables = tuple(
            sds(1 << (log_full - fold_round - j - 1)) for j in range(k)
        )
        specs.append((
            f"fri_fold{fold_tag}_k{k}_n{cur}"
            + ("_sm" if mesh_k is not None else ""),
            _fri_fold_fn(k, limb, mesh_k),
            (sds(cur), sds(cur), sds(2), tables),
        ))
        fold_round += k
        cur >>= k
    shift_inv = gl.inv(gl.pow_(gl.MULTIPLICATIVE_GENERATOR, 1 << num_folds))
    if resident:
        specs.append((
            f"fri_final_limbres_n{cur}", _fri_final_p,
            (sdsp(cur), sdsp(cur), shift_inv),
        ))
    else:
        specs.append((
            f"fri_final_n{cur}", _fri_final_fused,
            (sds(cur), sds(cur), shift_inv),
        ))
    return specs


def fri_prove(
    codeword, transcript, config, base_degree: int, fused: bool = False
) -> FriOracles:
    """codeword: ext pair over full LDE domain (brev layout).

    Protocol per schedule entry k: commit the current codeword with 2^k
    points per leaf -> absorb cap -> draw ONE challenge -> fold k times with
    challenges ch, ch^2, ch^4, ... -> next entry. Then interpolate the final
    monomials and absorb them. With `fused`, each entry is two dispatches
    (commit graph, then fold graph — the challenge only exists after the
    cap is absorbed).
    """
    from .pallas_sweep import limb_sweep_enabled

    out = FriOracles()
    # a resident codeword arrives as an ext PLANE pair ((lo,hi),(lo,hi))
    # straight from the DEEP accumulation (ISSUE 10) and stays planes
    # through every commit and fold; only the final monomials (and caps,
    # via the plane trees) join — on host, at the transcript edge
    resident = isinstance(codeword[0], tuple)
    _arr0 = codeword[0][0] if resident else codeword[0]
    N = int(_arr0.shape[0])
    log_full = N.bit_length() - 1
    schedule = fold_schedule(
        base_degree, config.fri_final_degree,
        getattr(config, "fri_folding_schedule", None),
    )
    out.schedule = schedule
    num_folds = sum(schedule)
    if resident:
        assert fused, "the resident codeword runs the fused FRI path"
        tables = fold_challenge_tables_p(log_full, num_folds)
    else:
        tables = fold_challenge_tables(log_full, num_folds)
    limb = limb_sweep_enabled()
    from ..parallel.sharding import shard_map_mesh
    from ..parallel.shard_sweep import fold_shards_ok

    smm = shard_map_mesh()
    if smm is not None and len(_arr0.devices()) <= 1:
        # streamed proves de-mesh their round-5 inputs (the DEEP sources
        # regenerate blocks inside plain jits), so the codeword arrives
        # on ONE device — the per-chip commit/fold graphs would reject
        # it. Run the whole FRI chain meshless; values are identical.
        smm = None

    cur = codeword
    fold_round = 0
    for r, k in enumerate(schedule):
        with _span(f"fri_oracle_{r}", k=k, limb=limb, resident=resident):
            # per-chip commit + fold chain while every intermediate local
            # size stays even; deep tails are pulled onto one device and
            # take the meshless graphs (the arrays are small there, and a
            # plain jit over a still-sharded operand would go through the
            # SPMD partitioner)
            cur_n = int((cur[0][0] if resident else cur[0]).shape[0])
            mesh_k = (
                smm
                if smm is not None and fold_shards_ok(cur_n, k, smm)
                else None
            )
            if smm is not None and mesh_k is None:
                from ..parallel.shard_sweep import demesh

                cur = demesh(cur)
            if resident:
                from ..merkle import PlaneMerkleTree

                if mesh_k is not None:
                    from ..parallel.shard_sweep import fri_commit_sm_p

                    layers = fri_commit_sm_p(
                        cur, k, config.merkle_tree_cap_size, mesh_k
                    )
                else:
                    layers = _fri_commit_fn_p(
                        k, config.merkle_tree_cap_size
                    )(cur[0], cur[1])
                tree = PlaneMerkleTree.from_layers(
                    list(layers), config.merkle_tree_cap_size
                )
            elif fused:
                if mesh_k is not None:
                    from ..parallel.shard_sweep import fri_commit_sm

                    layers = fri_commit_sm(
                        cur, k, config.merkle_tree_cap_size, mesh_k
                    )
                else:
                    layers = _fri_commit_fn(
                        k, config.merkle_tree_cap_size
                    )(*cur)
                tree = MerkleTreeWithCap.from_layers(
                    list(layers), config.merkle_tree_cap_size
                )
            else:
                tree = commit_codeword(
                    cur, config.merkle_tree_cap_size, elems_per_leaf=1 << k
                )
            _metrics.count("fri.oracle_commits")
            out.trees.append(tree)
            out.values.append(cur)
            transcript.witness_merkle_tree_cap(tree.get_cap())
            _checkpoint(5, f"fri_cap_{r}", tree.get_cap())
            ch = transcript.get_ext_challenge()
            _checkpoint(5, f"fri_challenge_{r}", ch)
            out.challenges.append(ch)
            _metrics.count("fri.folds", k)
            if limb:
                _metrics.count("fri.limb_folds", k)
            if resident:
                _metrics.count("fri.resident_folds", k)
                if mesh_k is not None:
                    _metrics.count("fri.sm_folds", k)
                tb = jnp.asarray(_ch_table_np(ch))
                cur = _fri_fold_fn_p(k, mesh_k)(
                    cur[0], cur[1], tb,
                    tuple(tables[fold_round : fold_round + k]),
                )
                fold_round += k
            elif fused:
                ch01 = jnp.asarray(np.array([ch[0], ch[1]], dtype=np.uint64))
                if mesh_k is not None:
                    _metrics.count("fri.sm_folds", k)
                cur = _fri_fold_fn(k, limb, mesh_k)(
                    cur[0], cur[1], ch01,
                    tuple(tables[fold_round : fold_round + k]),
                )
                fold_round += k
            else:
                sub = ch
                for _ in range(k):
                    cur = fold_once(cur, sub, tables[fold_round])
                    fold_round += 1
                    sub = ext_f.sqr_s(sub)
    # final interpolation over coset g^(2^R)·H_{N>>R}
    n_fin = N >> num_folds
    shift_inv = gl.inv(gl.pow_(gl.MULTIPLICATIVE_GENERATOR, 1 << num_folds))
    with _span("fri_final_interpolation"):
        if smm is not None:
            from ..parallel.shard_sweep import demesh

            cur = demesh(cur)
        if resident:
            mono0, mono1 = _fri_final_p(cur[0], cur[1], shift_inv)
        elif fused:
            mono0, mono1 = _fri_final_fused(cur[0], cur[1], shift_inv)
        else:
            mono0 = distribute_powers(
                ifft_bitreversed_to_natural(cur[0]), shift_inv
            )
            mono1 = distribute_powers(
                ifft_bitreversed_to_natural(cur[1]), shift_inv
            )
    # one batched pull for both coordinate arrays (sequenced: two
    # blocking host_np syncs; overlapped: one, started async)
    from ..utils.transfer import fetch_np

    if resident:
        # planes leave the device; u64 reassembles on HOST (the API edge)
        from ..field.limbs import join_np

        got = fetch_np(
            mono0[0], mono0[1], mono1[0], mono1[1],
            label="fri_final_monomials",
        )
        m0 = join_np(got[0], got[1])
        m1 = join_np(got[2], got[3])
    else:
        m0, m1 = fetch_np(mono0, mono1, label="fri_final_monomials")
    deg_bound = base_degree >> num_folds
    assert (m0[deg_bound:] == 0).all() and (m1[deg_bound:] == 0).all(), (
        "final FRI polynomial exceeds degree bound"
    )
    out.final_monomials = [(int(a), int(b)) for a, b in zip(m0[:deg_bound], m1[:deg_bound])]
    for c0, c1 in out.final_monomials:
        transcript.witness_field_elements([c0, c1])
    _checkpoint(5, "fri_final_monomials", out.final_monomials)
    out.num_folds = num_folds
    return out


def fri_verify_queries(
    schedule, challenges, final_monomials, query_index: int, leaves,
    log_full: int,
):
    """Check one query's grouped fold chain on host (python ints).

    schedule: per-oracle fold counts; challenges: the one drawn ext
    challenge per oracle; leaves: per oracle, the 2^k ext values of the
    Merkle leaf covering the query (brev-consecutive domain points).
    Returns True iff the chain folds into the final polynomial.
    """
    idx = query_index
    fold_round = 0
    cur_expected = None
    for r, k in enumerate(schedule):
        block = 1 << k
        sub_idx = idx % block
        leaf_idx = idx >> k
        vals = [tuple(v) for v in leaves[r]]
        if len(vals) != block:
            return False
        if cur_expected is not None and vals[sub_idx] != tuple(cur_expected):
            return False
        # fold the whole leaf down with ch, ch^2, ch^4, ...
        ch = challenges[r]
        base_global = leaf_idx * block
        for j in range(k):
            log_nr = log_full - fold_round
            shift = gl.pow_(gl.MULTIPLICATIVE_GENERATOR, 1 << fold_round)
            nxt = []
            for m in range(len(vals) // 2):
                gi = (base_global >> j) + 2 * m
                x = gl.mul(shift, gl.pow_(gl.omega(log_nr), _brev(gi, log_nr)))
                even, odd = vals[2 * m], vals[2 * m + 1]
                s = ext_f.add_s(even, odd)
                d = ext_f.sub_s(even, odd)
                dox = ext_f.mul_by_base_s(d, gl.inv(x))
                t = ext_f.add_s(s, ext_f.mul_s(dox, ch))
                nxt.append(ext_f.mul_by_base_s(t, INV2))
            vals = nxt
            fold_round += 1
            ch = ext_f.sqr_s(ch)
        cur_expected = vals[0]
        idx = leaf_idx
    # final check: evaluate final monomials at the folded domain point
    num_folds = sum(schedule)
    log_fin = log_full - num_folds
    nat = _brev(idx, log_fin)
    shift = gl.pow_(gl.MULTIPLICATIVE_GENERATOR, 1 << num_folds)
    x = gl.mul(shift, gl.pow_(gl.omega(log_fin), nat))
    acc = ext_f.ZERO_S
    xp = ext_f.ONE_S
    for c in final_monomials:
        acc = ext_f.add_s(acc, ext_f.mul_s(c, xp))
        xp = ext_f.mul_by_base_s(xp, x)
    return tuple(acc) == tuple(cur_expected)


def _brev(i: int, bits: int) -> int:
    out = 0
    for b in range(bits):
        out |= ((i >> b) & 1) << (bits - 1 - b)
    return out
