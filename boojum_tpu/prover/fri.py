"""FRI: commit-and-fold low-degreeness argument over the quadratic extension.

Counterpart of `/root/reference/src/cs/implementations/fri/mod.rs` (do_fri
:49, fold_multiple :362, final monomial interpolation :476). The codeword is
an ext-valued array over the full LDE domain in bit-reversed enumeration, so
fold pairs (x, −x) are ADJACENT (even/odd lanes) and every fold round is two
strided slices + vectorized butterfly — no gather. Each committed round
interleaves (c0, c1) with two domain points per Merkle leaf.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..field import gl
from ..field import extension as ext_f
from ..field import goldilocks as gf
from ..merkle import MerkleTreeWithCap
from ..ntt import (
    bitreverse_indices,
    get_ntt_context,
    distribute_powers,
    ifft_bitreversed_to_natural,
    powers_device,
)
from .stages import ext_scalar

INV2 = (gl.P + 1) // 2


def fold_challenge_tables(log_full: int, num_rounds: int):
    """Per-round inverse-x tables: round r domain is the coset
    g^(2^r)·H_{N>>r}; table r holds 1/x at pair positions (even bit-reversed
    indices), length (N >> r)/2."""
    tables = []
    for r in range(num_rounds):
        log_nr = log_full - r
        n_r = 1 << log_nr
        shift = gl.pow_(gl.MULTIPLICATIVE_GENERATOR, 1 << r)
        omega = gl.omega(log_nr)
        xs_nat = powers_device(omega, n_r)
        xs_nat = gf.mul(xs_nat, jnp.uint64(shift))
        brev = bitreverse_indices(log_nr)
        xs_brev = xs_nat[jnp.asarray(brev)]
        xs_pairs = xs_brev[0::2]
        tables.append(gf.batch_inverse(xs_pairs))
    return tables


@jax.jit
def _fold_once_jit(values, ch, inv_x_pairs):
    a = (values[0][0::2], values[1][0::2])
    bm = (values[0][1::2], values[1][1::2])
    s = ext_f.add(a, bm)
    d = ext_f.sub(a, bm)
    d_over_x = (gf.mul(d[0], inv_x_pairs), gf.mul(d[1], inv_x_pairs))
    t = ext_f.add(s, ext_f.mul(d_over_x, ch))
    inv2 = jnp.uint64(INV2)
    return (gf.mul(t[0], inv2), gf.mul(t[1], inv2))


def fold_once(values, challenge, inv_x_pairs):
    """values: ext pair over round-r domain (brev layout); returns N/2 ext.

    f'(x^2) = (f(x)+f(-x))/2 + ch·(f(x)-f(-x))/(2x). Jitted core with the
    challenge as an array argument (new challenges never retrace).
    """
    return _fold_once_jit(values, ext_scalar(challenge), inv_x_pairs)


def commit_codeword(values, cap_size: int) -> MerkleTreeWithCap:
    """Commit ext codeword: rows (N, 2) = [c0, c1], two points per leaf."""
    arr = jnp.stack([values[0], values[1]], axis=-1)  # (N, 2)
    return MerkleTreeWithCap(arr, cap_size, num_elems_per_leaf=2)


class FriOracles:
    def __init__(self):
        self.trees: list[MerkleTreeWithCap] = []
        self.values: list = []  # ext pairs per round (device)
        self.challenges: list = []
        self.final_monomials = None  # host list of (c0, c1)


def fri_prove(codeword, transcript, config, base_degree: int) -> FriOracles:
    """codeword: ext pair over full LDE domain (brev layout).

    Protocol: commit base oracle -> absorb cap -> repeat [draw challenge,
    fold; commit+absorb unless final] -> interpolate final monomials, absorb.
    """
    out = FriOracles()
    N = int(codeword[0].shape[0])
    log_full = N.bit_length() - 1
    deg = base_degree
    num_folds = 0
    while deg > config.fri_final_degree:
        deg //= 2
        num_folds += 1
    assert num_folds >= 1, "nothing to fold; lower fri_final_degree"
    tables = fold_challenge_tables(log_full, num_folds)

    cur = codeword
    tree = commit_codeword(cur, config.merkle_tree_cap_size)
    out.trees.append(tree)
    out.values.append(cur)
    transcript.witness_merkle_tree_cap(tree.get_cap())
    for r in range(num_folds):
        ch = transcript.get_ext_challenge()
        out.challenges.append(ch)
        cur = fold_once(cur, ch, tables[r])
        if r + 1 < num_folds:
            tree = commit_codeword(cur, config.merkle_tree_cap_size)
            out.trees.append(tree)
            out.values.append(cur)
            transcript.witness_merkle_tree_cap(tree.get_cap())
    # final interpolation over coset g^(2^R)·H_{N>>R}
    n_fin = N >> num_folds
    shift_inv = gl.inv(gl.pow_(gl.MULTIPLICATIVE_GENERATOR, 1 << num_folds))
    mono0 = distribute_powers(ifft_bitreversed_to_natural(cur[0]), shift_inv)
    mono1 = distribute_powers(ifft_bitreversed_to_natural(cur[1]), shift_inv)
    m0 = np.asarray(mono0)
    m1 = np.asarray(mono1)
    deg_bound = base_degree >> num_folds
    assert (m0[deg_bound:] == 0).all() and (m1[deg_bound:] == 0).all(), (
        "final FRI polynomial exceeds degree bound"
    )
    out.final_monomials = [(int(a), int(b)) for a, b in zip(m0[:deg_bound], m1[:deg_bound])]
    for c0, c1 in out.final_monomials:
        transcript.witness_field_elements([c0, c1])
    out.num_folds = num_folds
    return out


def fri_verify_queries(
    proof_fri, challenges, final_monomials, query_index: int, query_data,
    log_full: int, num_folds: int,
):
    """Check one query's fold chain on host (python ints).

    query_data: list over rounds of (pair_values) where pair_values =
    [(c0,c1) at even idx, (c0,c1) at odd idx] for the round's pair containing
    the query. Returns True iff the chain folds into the final polynomial.
    """
    idx = query_index
    cur_pair_expected = None
    for r in range(num_folds):
        log_nr = log_full - r
        pair = query_data[r]
        even, odd = pair
        if cur_pair_expected is not None:
            mine = even if (idx & 1) == 0 else odd
            if tuple(mine) != tuple(cur_pair_expected):
                return False
        # fold
        k = idx >> 1
        shift = gl.pow_(gl.MULTIPLICATIVE_GENERATOR, 1 << r)
        n_r = 1 << log_nr
        # x at brev position 2k: natural index brev(2k)
        nat = _brev(2 * k, log_nr)
        x = gl.mul(shift, gl.pow_(gl.omega(log_nr), nat))
        ch = challenges[r]
        s = ext_f.add_s(even, odd)
        d = ext_f.sub_s(even, odd)
        dox = ext_f.mul_by_base_s(d, gl.inv(x))
        t = ext_f.add_s(s, ext_f.mul_s(dox, ch))
        cur_pair_expected = ext_f.mul_by_base_s(t, INV2)
        idx = k
    # final check: evaluate final monomials at the folded domain point
    log_fin = log_full - num_folds
    nat = _brev(idx, log_fin)
    shift = gl.pow_(gl.MULTIPLICATIVE_GENERATOR, 1 << num_folds)
    x = gl.mul(shift, gl.pow_(gl.omega(log_fin), nat))
    acc = ext_f.ZERO_S
    xp = ext_f.ONE_S
    for c in final_monomials:
        acc = ext_f.add_s(acc, ext_f.mul_s(c, xp))
        xp = ext_f.mul_by_base_s(xp, x)
    return tuple(acc) == tuple(cur_pair_expected)


def _brev(i: int, bits: int) -> int:
    out = 0
    for b in range(bits):
        out |= ((i >> b) & 1) << (bits - 1 - b)
    return out
