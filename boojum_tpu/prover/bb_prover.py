"""End-to-end BabyBear prover (ISSUE 19): a self-contained mini-STARK on
bare u32 lanes, 2^10-scale, proved entirely through the `_bb` kernel twins.

The statement: a length-n trace of the public square map
w[i+1] = w[i]^2 + c with boundary w[0] = pub. One committed trace column,
one alpha-combined ext quotient (4 base coordinate columns), DEEP at an
ext point z, factor-2 natural-order FRI down to a raw final codeword,
blake2s PoW, transcript-sampled queries — every round absorbing into the
width-16 BabyBear Poseidon2 transcript and landing a Fiat–Shamir
checkpoint, so checkpoint-stream determinism and NumPy-reference parity
(compat/prove_reference_bb.py) are testable from day one.

The prover is written against a small BACKEND seam (intt/lde/sweep/deep/
fold/commit, numpy in, numpy out): the device backend dispatches the
jitted `_bb` kernels (prover/bb_kernels.py); the reference backend is the
same flow over pure-numpy twins. Transcript, challenge schedule, proof
assembly and checkpoints are SHARED — parity is by construction, so a
checkpoint mismatch always means a kernel bug, never a protocol drift.

No `field/limbs.py` import anywhere on this path: the zero
interior-conversion claim (`limb.splits == 0`) is structural.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ..field import babybear as bb
from ..field.spec import BABYBEAR as SPEC
from ..transcript import BitSource, Poseidon2BabyBearTranscript
from ..utils import metrics as _metrics
from ..utils.report import checkpoint as _checkpoint
from . import bb_kernels as K
from .pow import blake2s_pow_grind


@dataclasses.dataclass(frozen=True)
class BBProofConfig:
    log_n: int = 10
    lde_factor: int = 4
    shift: int = SPEC.multiplicative_generator
    square_c: int = 7  # the transition constant of the square map
    num_queries: int = 20
    pow_bits: int = 8
    cap_size: int = 8
    final_len: int = 32  # raw FRI final codeword length

    @property
    def n(self) -> int:
        return 1 << self.log_n

    @property
    def domain_len(self) -> int:
        return self.n * self.lde_factor

    @property
    def num_folds(self) -> int:
        return (self.domain_len // self.final_len).bit_length() - 1

    def params_list(self) -> list:
        return [
            self.log_n, self.lde_factor, self.shift, self.square_c,
            self.num_queries, self.pow_bits, self.cap_size, self.final_len,
        ]


@dataclasses.dataclass
class BBProof:
    config: BBProofConfig
    pub: int
    witness_cap: list
    quotient_cap: list
    evals: dict  # {"wz": ext, "wgz": ext, "qz": [ext x4]}
    fri_caps: list  # caps of layers 1..num_folds-1
    final_codeword: list  # final_len ext 4-tuples
    pow_nonce: int
    query_indices: list
    queries: list  # per-query opening dicts


# ---------------------------------------------------------------------------
# Device backend: the `_bb` kernel twins (numpy in, numpy out)
# ---------------------------------------------------------------------------


class DeviceBackendBB:
    """Dispatches the jitted plane-free kernels. All methods take and
    return host numpy so the shared prover core never branches on the
    backend; domains are 2^12-scale, transfers are noise."""

    def intt(self, values):
        import jax.numpy as jnp

        values = np.asarray(values, dtype=np.uint32)
        log_n = values.shape[-1].bit_length() - 1
        from ..ntt.bb_ntt import monomial_from_values_bb

        return np.asarray(monomial_from_values_bb(jnp.asarray(values), log_n))

    def lde(self, mono, log_n, lde_factor, shift):
        import jax.numpy as jnp

        from ..ntt.bb_ntt import lde_from_monomial_bb

        return np.asarray(
            lde_from_monomial_bb(jnp.asarray(mono), log_n, lde_factor, shift)
        )

    def coset_sweep(self, w_lde, alpha, cfg: BBProofConfig, pub: int):
        import jax.numpy as jnp

        _metrics.count("quotient.bb_coset_sweeps")
        args = (cfg.log_n, cfg.lde_factor, cfg.shift)
        return np.asarray(
            K.coset_sweep_terms_bb(
                jnp.asarray(w_lde),
                jnp.asarray(np.array(alpha, dtype=np.uint32)),
                jnp.asarray(
                    np.array([cfg.square_c, pub], dtype=np.uint32)
                ),
                jnp.asarray(K.last_row_term_bb(*args)),
                jnp.asarray(K.zh_inv_bb(*args)),
                jnp.asarray(K.boundary_inv_bb(*args)),
                cfg.lde_factor,
            )
        )

    def deep(self, w_lde, q_cols, xs, z, gz, wz, wgz, qz, gammas):
        import jax.numpy as jnp

        _metrics.count("deep.bb_accumulates")

        def a(v):
            return jnp.asarray(np.array(v, dtype=np.uint32))

        return np.asarray(
            K.deep_accumulate_bb(
                jnp.asarray(w_lde), jnp.asarray(q_cols), jnp.asarray(xs),
                a(z), a(gz), a(wz), a(wgz), a(qz), a(gammas),
            )
        )

    def fold(self, codeword, beta, inv2x):
        import jax.numpy as jnp

        _metrics.count("fri.bb_folds")
        return np.asarray(
            K.fri_fold_bb(
                jnp.asarray(codeword),
                jnp.asarray(np.array(beta, dtype=np.uint32)),
                jnp.asarray(inv2x),
            )
        )

    def commit(self, cols, cap_size: int) -> K.BBMerkleTree:
        import jax.numpy as jnp

        _metrics.count("merkle.bb_commits")
        digests = K.leaf_digests_bb(jnp.asarray(cols))
        layers = K.node_layers_bb(digests, cap_size)
        return K.BBMerkleTree([np.asarray(l) for l in layers], cap_size)


# ---------------------------------------------------------------------------
# Shared host helpers
# ---------------------------------------------------------------------------


def build_trace(pub: int, cfg: BBProofConfig):
    """w[0] = pub, w[i+1] = w[i]^2 + c — natural-order subgroup values."""
    w = [int(pub) % bb.P]
    for _ in range(cfg.n - 1):
        w.append((w[-1] * w[-1] + cfg.square_c) % bb.P)
    return np.array(w, dtype=np.uint32)


def ext_powers_table(z, count: int):
    """(4, count) u32 table of ext powers 1, z, z^2, ... (host ints)."""
    out = np.zeros((4, count), dtype=np.uint32)
    cur = bb.ONE_S
    for i in range(count):
        for k in range(4):
            out[k, i] = cur[k]
        cur = bb.ext_mul_s(cur, z)
    return out


def eval_base_at_ext(mono, zpows) -> tuple:
    """Evaluate a base-coefficient polynomial at the ext point whose
    power table is `zpows` ((4, >=len) u32)."""
    mono = np.asarray(mono, dtype=np.uint32)
    m = mono.shape[-1]
    return tuple(
        int(
            np.sum(
                bb.mul_np(mono, zpows[k, :m]).astype(np.uint64)
            ) % np.uint64(bb.P)
        )
        for k in range(4)
    )


def _flat_cap(cap) -> list:
    return [int(v) for digest in cap for v in digest]


def _flat_ext_list(vals) -> list:
    return [int(c) for e in vals for c in e]


def _fri_pair_cols(cur):
    """(4, M) layer -> (8, M/2) paired-leaf columns: leaf j holds the
    fold pair (f_j ‖ f_{j+M/2}), so one auth path serves both."""
    half = cur.shape[-1] // 2
    return np.vstack([cur[:, :half], cur[:, half:]])


def coset_descale(mono_like, shift: int):
    """Undo a coset: values over shift*<w_N> iNTT'd plainly give u with
    u_i = m_i * shift^i; multiply by shift^-i to recover m."""
    N = mono_like.shape[-1]
    tbl = bb.powers_np(bb.inv_s(shift % bb.P), N)
    return bb.mul_np(mono_like, tbl)


# ---------------------------------------------------------------------------
# The prover
# ---------------------------------------------------------------------------


def prove_babybear(
    pub: int, cfg: BBProofConfig | None = None, backend=None
) -> BBProof:
    cfg = cfg or BBProofConfig()
    backend = backend or DeviceBackendBB()
    pub = int(pub) % bb.P
    n, L, N = cfg.n, cfg.lde_factor, cfg.domain_len
    log_N = N.bit_length() - 1

    t = Poseidon2BabyBearTranscript()

    # round 0: bind the protocol parameters + public input
    params = cfg.params_list() + [pub]
    t.witness_field_elements(params)
    _checkpoint(0, "bb_params", params)

    # round 1: trace -> monomials -> LDE -> witness commitment
    w_vals = build_trace(pub, cfg)
    w_mono = backend.intt(w_vals)
    w_lde = backend.lde(w_mono, cfg.log_n, L, cfg.shift)
    w_tree = backend.commit(w_lde[None, :], cfg.cap_size)
    w_cap = w_tree.get_cap()
    t.witness_merkle_tree_cap(w_cap)
    _checkpoint(1, "witness_cap", _flat_cap(w_cap))

    # round 2: the constraint-combining challenge
    alpha = t.get_ext_challenge()
    _checkpoint(2, "alpha", list(alpha))

    # round 3: fused quotient sweep -> quotient commitment -> z
    q_cols = backend.coset_sweep(w_lde, alpha, cfg, pub)
    q_tree = backend.commit(q_cols, cfg.cap_size)
    q_cap = q_tree.get_cap()
    t.witness_merkle_tree_cap(q_cap)
    _checkpoint(3, "quotient_cap", _flat_cap(q_cap))
    z = t.get_ext_challenge()
    _checkpoint(3, "z", list(z))

    # round 4: out-of-domain evaluations at z and g*z
    g = bb.omega(cfg.log_n)
    gz = bb.ext_scale_s(z, g)
    zpows = ext_powers_table(z, N)
    gzpows = ext_powers_table(gz, n)
    wz = eval_base_at_ext(w_mono, zpows)
    wgz = eval_base_at_ext(w_mono, gzpows)
    q_monos = coset_descale(backend.intt(q_cols), cfg.shift)
    qz = [eval_base_at_ext(q_monos[k], zpows) for k in range(4)]
    evals_flat = _flat_ext_list([wz, wgz] + qz)
    t.witness_field_elements(evals_flat)
    _checkpoint(4, "evals", evals_flat)
    gammas = [t.get_ext_challenge() for _ in range(6)]
    _checkpoint(4, "deep_gammas", _flat_ext_list(gammas))

    # round 5: DEEP codeword -> FRI fold chain -> PoW -> queries
    xs = K.domain_xs_bb(cfg.log_n, L, cfg.shift)
    cur = backend.deep(
        w_lde, q_cols, xs, z, gz, wz, wgz, qz, gammas
    )
    fold_tables = K.fri_fold_tables_bb(log_N, cfg.shift, cfg.num_folds)
    fri_trees: list = []
    fri_caps: list = []
    betas: list = []
    layers = [cur]
    for r in range(cfg.num_folds):
        if r > 0:
            tree = backend.commit(_fri_pair_cols(cur), min(
                cfg.cap_size, cur.shape[-1] // 2))
            cap = tree.get_cap()
            t.witness_merkle_tree_cap(cap)
            _checkpoint(5, f"fri_cap_{r}", _flat_cap(cap))
            fri_trees.append(tree)
            fri_caps.append(cap)
        beta = t.get_ext_challenge()
        _checkpoint(5, f"fri_beta_{r}", list(beta))
        betas.append(beta)
        cur = backend.fold(cur, beta, fold_tables[r])
        layers.append(cur)
    final = [
        tuple(int(cur[k, j]) for k in range(4))
        for j in range(cfg.final_len)
    ]
    final_flat = _flat_ext_list(final)
    t.witness_field_elements(final_flat)
    _checkpoint(5, "fri_final", final_flat)

    nonce = blake2s_pow_grind(t, cfg.pow_bits)
    _checkpoint(5, "pow_nonce", [nonce])

    bits = BitSource(log_N, challenge_bits=SPEC.challenge_bits)
    idxs = [bits.get_index(t, log_N) for _ in range(cfg.num_queries)]
    _checkpoint(5, "query_indices", idxs)

    # query openings (host gathers over the stored trees/layers)
    w_host = np.asarray(w_lde)
    q_host = np.asarray(q_cols)
    queries = []
    for pos in idxs:
        j0 = pos % (N // 2)
        opens = {"pos": int(pos), "w": [], "q": [], "fri": []}
        for j in (j0, j0 + N // 2):
            opens["w"].append(
                ([int(w_host[j])], w_tree.get_path(j))
            )
            opens["q"].append(
                ([int(q_host[k, j]) for k in range(4)], q_tree.get_path(j))
            )
        p = j0
        for r in range(1, cfg.num_folds):
            M = N >> r
            leaf_idx = p % (M // 2)
            layer = layers[r]
            leaf_vals = (
                [int(layer[k, leaf_idx]) for k in range(4)]
                + [int(layer[k, leaf_idx + M // 2]) for k in range(4)]
            )
            opens["fri"].append(
                (leaf_vals, fri_trees[r - 1].get_path(leaf_idx))
            )
            p = p % (M // 2)
        queries.append(opens)

    return BBProof(
        config=cfg,
        pub=pub,
        witness_cap=w_cap,
        quotient_cap=q_cap,
        evals={"wz": wz, "wgz": wgz, "qz": qz},
        fri_caps=fri_caps,
        final_codeword=final,
        pow_nonce=int(nonce),
        query_indices=[int(i) for i in idxs],
        queries=queries,
    )
