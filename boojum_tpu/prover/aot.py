"""AOT executable artifact store — compilation as a build step (ISSUE 8).

Rounds 3 and 4 of the bench burned the whole 1500 s watchdog budget
inside cold-cache warm-up compiles: a fresh process pays the full XLA
bill for the kernel library before its first prove, which is fatal for a
production prover (ROADMAP item 1) and has kept every PR 3-5 perf win
unmeasured. ICICLE (PAPERS.md) ships precompiled device kernels as
deployment artifacts; DIZK's fleet amortization only works when
per-process startup is cheap. This module makes compilation a BUILD
step:

- `build_bundle(assembly, config, out_root)` compiles the whole
  enumerated kernel library (`precompile.enumerate_kernels`) with the
  persistent compilation cache redirected into a bundle directory, then
  runs `generate_setup` + one full `prove` under the same redirect so
  every graph a cold serve process will dispatch — including the setup
  pipeline and the query-phase graphs `enumerate_kernels` deliberately
  skips — lands in the bundle. Each kernel is additionally serialized as
  a `jax.export` StableHLO artifact where exportable (Pallas custom
  calls may refuse; those entries fall back to cache-bundle-only, which
  is recorded per kernel in the manifest). A `manifest.json` carries the
  bundle key, jax/jaxlib versions, platform fingerprint and a sha256
  per artifact file.

- `load_bundle(out_root, assembly, config)` finds the bundle for this
  (ShapeBucket.key, mesh shape, flag variant), validates versions /
  platform / integrity hashes, and copies the cache entries into the
  process's active persistent-cache directory — so every later compile
  of a bundled kernel is a cache DESERIALIZATION, not an XLA compile.
  A version-mismatched, corrupt or missing bundle logs a warning and
  returns None (graceful JIT fallback) unless BOOJUM_TPU_AOT_REQUIRE is
  set, in which case it raises — production deployments where silent
  JIT means an SLO breach opt into the hard failure.

- `warm_from_bundle(assembly, config)` re-lowers the enumerated library
  serially and `.compile()`s each kernel, classifying it `aot_hit`
  (persistent-cache deserialization, zero misses escaped to the
  compiler) or miss by diffing the jax.monitoring cache counters around
  each compile. Every kernel lands in the CompileLedger with an
  `aot_hit` field, and the `aot.*` metrics (hits / misses /
  deserialize_s) make the warm-up bill attributable to deserialization
  rather than compilation on every bench/report line.

Key identity: a bundle serves exactly one
``(ShapeBucket.key, mesh_shape, flag variant)`` triple — the same
bucket key the admission queue and compile ledger use
(prover/shape_key.py) plus the env-flag variant that decides WHICH
kernel set `enumerate_kernels` derives (overlap / limb-sweep /
stream-LDE threshold / mesh mode). jax+jaxlib versions and the platform
fingerprint are validated at LOAD time rather than folded into the
directory name, so a version bump reads as "stale bundle" in the logs
instead of a silent miss.

Honest scope note: `jax.export` artifacts carry lowered StableHLO —
portable and auditable, but re-compiled by XLA on any consumer. The
persistent-cache entries carry the COMPILED executable and are what
makes a matching process zero-compile; they are only valid on an
exactly-matching (jax, jaxlib, backend, device kind, device count,
host CPU) stack, which the manifest records and the loader enforces.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import time
from dataclasses import dataclass, field

from ..utils import metrics as _metrics
from ..utils.profiling import (
    CompileLedger,
    current_compile_ledger,
    log as _log,
)
from ..utils.spans import span as _span

AOT_KIND = "boojum_tpu.aot_bundle"
AOT_SCHEMA = 1
MANIFEST_NAME = "manifest.json"

# platform fields that must match EXACTLY between build and load for the
# compiled cache entries to be usable: the persistent-cache key covers
# jax/backend identity, and XLA:CPU AOT code additionally embeds the
# compile host's vector features (_hostfp.py — loading a mismatched
# entry SIGILLs rather than missing). Device topology is keyed on
# (process_count, per-host device count), NOT the global device count:
# every host of a multi-process run sees the same pair, so a bundle
# built on host 0 of a pod warms hosts 1..P-1, while a single-host run
# with the same TOTAL device count (which traces different local shapes)
# correctly misses. Pre-16 manifests carrying only num_devices are
# matched on that legacy key (see load_bundle).
_PLATFORM_FIELDS = (
    "jax", "jaxlib", "backend", "device_kind",
    "num_local_devices", "process_count", "host_fp",
)


class AotBundleError(RuntimeError):
    """A required artifact bundle is missing, stale or corrupt
    (BOOJUM_TPU_AOT_REQUIRE=1 turns the JIT fallback into this error)."""


def aot_dir() -> str | None:
    """BOOJUM_TPU_AOT_DIR: root directory of artifact bundles (None =
    the AOT layer is off and every consult is a no-op)."""
    return os.environ.get("BOOJUM_TPU_AOT_DIR", "").strip() or None


def aot_require() -> bool:
    """BOOJUM_TPU_AOT_REQUIRE: a missing/stale/corrupt bundle raises
    AotBundleError instead of falling back to JIT (default off)."""
    from ..utils.transfer import env_flag

    return env_flag("BOOJUM_TPU_AOT_REQUIRE", False)


def aot_warm_enabled() -> bool:
    """BOOJUM_TPU_AOT_WARM: after a bundle load, re-lower + compile the
    enumerated library so every kernel's cache deserialization happens
    up front WITH per-kernel aot_hit ledger attribution (default on;
    off = first dispatch of each kernel pays its own cache load)."""
    from ..utils.transfer import env_flag

    return env_flag("BOOJUM_TPU_AOT_WARM", True)


def aot_export_enabled() -> bool:
    """BOOJUM_TPU_AOT_EXPORT: also serialize a jax.export StableHLO
    artifact per kernel at build time (default on; the portable,
    auditable representation — the cache entries alone already make a
    matching process zero-compile)."""
    from ..utils.transfer import env_flag

    return env_flag("BOOJUM_TPU_AOT_EXPORT", True)


# ---------------------------------------------------------------------------
# Bundle identity
# ---------------------------------------------------------------------------


def _mesh_shape_list(mesh_shape) -> list | None:
    """Normalize a mesh spec — None, a (ncol, nrow) pair, or a built Mesh
    — to a JSON-stable [ncol, nrow] list (None = meshless)."""
    if mesh_shape is None:
        return None
    if isinstance(mesh_shape, (tuple, list)):
        return [int(mesh_shape[0]), int(mesh_shape[1])]
    sh = dict(mesh_shape.shape)
    return [int(sh.get("col", 1)), int(sh.get("row", 1))]


def variant_fingerprint(mesh_shape=None) -> dict:
    """The env-flag variant that decides WHICH kernel set
    `precompile.enumerate_kernels` derives — resolved the same way the
    enumeration resolves it, so build and load can never disagree by
    parsing flags differently."""
    from ..field.spec import active_field
    from ..utils import transfer as _transfer
    from .pallas_sweep import limb_resident_enabled, limb_sweep_enabled
    from .streaming import stream_threshold_bytes

    thresh = stream_threshold_bytes()
    return {
        # the field backend selects a DISJOINT kernel set (`_bb` names,
        # ISSUE 19) — a goldilocks bundle must never satisfy a babybear
        # load or vice versa
        "field": active_field(),
        "overlap": bool(_transfer.overlap_enabled()),
        "limb_sweep": bool(limb_sweep_enabled()),
        # the resident variant is a DISJOINT kernel set (`*_limbres`
        # ledger names); it must never share a bundle with the
        # converting set
        "limb_resident": bool(limb_resident_enabled()),
        "mesh_shape": _mesh_shape_list(mesh_shape),
        # inf is not JSON — the "streaming forced off" sentinel string is
        "stream_lde_bytes": (
            "off" if thresh == float("inf") else float(thresh)
        ),
    }


_PLATFORM_INFO: dict | None = None


def platform_info() -> dict:
    """The exact-match stack identity the compiled cache entries are
    valid on (manifest-recorded, load-validated). Computed once per
    process — it re-probes jax.devices() and hashes /proc/cpuinfo, and
    every report/bench line carries it — then copied per call so a
    caller mutating its manifest can't poison the cache."""
    global _PLATFORM_INFO
    if _PLATFORM_INFO is not None:
        return dict(_PLATFORM_INFO)
    import jax
    import jaxlib

    from .._hostfp import host_fingerprint

    try:
        dev = jax.devices()[0]
        kind = getattr(dev, "device_kind", "unknown")
    except Exception:
        kind = "unknown"
    try:
        ndev = int(jax.device_count())
    except Exception:
        ndev = 0
    try:
        nloc = int(jax.local_device_count())
    except Exception:
        nloc = 0
    try:
        nproc = int(jax.process_count())
    except Exception:
        nproc = 1
    info = {
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "backend": jax.default_backend(),
        "device_kind": kind,
        "num_devices": ndev,
        "num_local_devices": nloc,
        "process_count": nproc,
        "host_fp": host_fingerprint(),
    }
    # memoize SUCCESSFUL probes only: a first call racing device
    # availability (backend not up yet, pre-distributed-init worker)
    # must not pin kind='unknown' for the process lifetime — that would
    # reject every bundle load and mis-identify every report line
    if kind != "unknown" and ndev > 0:
        _PLATFORM_INFO = info
    return dict(info)


def bundle_name(bucket_key: str, variant: dict) -> str:
    """Directory name of the bundle serving one (bucket, variant) pair:
    the bucket's short fingerprint (shape_key.key_fingerprint — the one
    fs-safe short form of "same shape", greppable back to a bucket)
    plus a digest of the full identity."""
    from .shape_key import key_fingerprint

    ident = json.dumps([bucket_key, variant], sort_keys=True)
    digest = hashlib.sha256(ident.encode()).hexdigest()[:16]
    return f"bundle-{key_fingerprint(bucket_key)}-{digest}"


def bundle_dir_for(
    out_root: str, assembly, config, mesh_shape=None
) -> str:
    from .shape_key import bucket_key

    return os.path.join(
        out_root,
        bundle_name(
            bucket_key(assembly, config), variant_fingerprint(mesh_shape)
        ),
    )


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _safe_kernel_filename(name: str) -> str:
    """Kernel names carry shape/oracle punctuation (wit:mono_sm,
    fri_fold_limb_k2) — map to a fs-safe unique filename."""
    stem = "".join(c if c.isalnum() or c in "._-" else "_" for c in name)
    tag = hashlib.blake2s(name.encode(), digest_size=4).hexdigest()
    return f"{stem}-{tag}.jaxexport"


# ---------------------------------------------------------------------------
# Persistent-cache plumbing
# ---------------------------------------------------------------------------


def _strip_path_keyed_options():
    """Make compiled cache entries PORTABLE across cache directories.

    jax 0.4.36+ injects the persistent-cache DIRECTORY PATH into every
    compile's options (jax_persistent_cache_enable_xla_caches enables
    the GPU autotune/kernel caches at `<cache_dir>/...`, and that path
    lands in debug_options, which the cache key hashes) — so an
    executable compiled under the bundle's cache dir could never be a
    hit under a consumer's cache dir. Every AOT flow — build, load,
    warm — forces the injection off, on BOTH sides of the bundle;
    the GPU-only caches it would enable are irrelevant on the CPU/TPU
    backends this prover targets. Deliberately sticky (not restored):
    the consumer's later setup/prove lowerings must keep producing
    bundle-portable keys, and flipping mid-process would split the
    process's own cache in two."""
    try:
        import jax

        jax.config.update("jax_persistent_cache_enable_xla_caches", "none")
    except Exception:
        pass


def _reset_persistent_cache():
    """Drop jax's process-wide persistent-cache singleton so the next
    compile re-reads jax_compilation_cache_dir (the documented way to
    repoint the cache mid-process)."""
    try:
        from jax._src import compilation_cache as _cc

        _cc.reset_cache()
    except Exception:
        pass


class _redirected_cache:
    """Context manager: point the persistent compilation cache at
    `cache_dir` with persist-everything thresholds, restoring the
    previous configuration (and cache singleton) on exit."""

    def __init__(self, cache_dir: str):
        self.cache_dir = cache_dir

    def __enter__(self):
        import jax

        self._prev = {
            "jax_compilation_cache_dir":
                jax.config.jax_compilation_cache_dir,
            "jax_persistent_cache_min_compile_time_secs":
                jax.config.jax_persistent_cache_min_compile_time_secs,
            "jax_persistent_cache_min_entry_size_bytes":
                jax.config.jax_persistent_cache_min_entry_size_bytes,
        }
        jax.config.update("jax_compilation_cache_dir", self.cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        _strip_path_keyed_options()
        _reset_persistent_cache()
        return self

    def __exit__(self, *exc):
        import jax

        for k, v in self._prev.items():
            jax.config.update(k, v)
        _reset_persistent_cache()
        return False


def _active_cache_dir() -> str | None:
    """The process's persistent-cache directory, configuring the
    package default when nothing pinned one yet (a loader without a
    destination cache has nowhere to put the compiled artifacts)."""
    import jax

    d = jax.config.jax_compilation_cache_dir
    if d:
        os.makedirs(d, exist_ok=True)
        return d
    if os.environ.get("BOOJUM_TPU_NO_COMPILE_CACHE"):
        return None
    from .._hostfp import host_fingerprint

    plat = (
        os.environ.get("JAX_PLATFORMS", "").strip().replace(",", "-")
        or "default"
    )
    d = os.environ.get(
        "BOOJUM_TPU_COMPILE_CACHE",
        os.path.expanduser(
            f"~/.cache/boojum_tpu_xla-{plat}-{host_fingerprint()}"
        ),
    )
    jax.config.update("jax_compilation_cache_dir", d)
    _reset_persistent_cache()
    os.makedirs(d, exist_ok=True)
    return d


# monitoring-fed cache hit/miss counters for per-kernel warm attribution
# (jax.monitoring offers registration but no deregistration, so ONE
# module-lifetime listener feeds a pair of counters the warm loop diffs
# around each serial compile)
_CACHE_EVENTS = {"hits": 0, "misses": 0}
_LISTENER_INSTALLED = False


def _install_cache_listener():
    global _LISTENER_INSTALLED
    if _LISTENER_INSTALLED:
        return
    try:
        from jax import monitoring as _mon

        def _on_event(event, **kw):
            if event == "/jax/compilation_cache/cache_hits":
                _CACHE_EVENTS["hits"] += 1
            elif event == "/jax/compilation_cache/cache_misses":
                _CACHE_EVENTS["misses"] += 1

        _mon.register_event_listener(_on_event)
        _LISTENER_INSTALLED = True
    except Exception:
        pass


# ---------------------------------------------------------------------------
# Build
# ---------------------------------------------------------------------------


# True while build_bundle is capturing its own setup+prove: prove()'s
# AOT consult (maybe_load_for_prove) is suppressed for the duration so a
# previous bundle can never leak entries into the one being built
_BUILDING = [False]


def build_bundle(
    assembly,
    config,
    out_root: str,
    mesh_shape=None,
    ledger: CompileLedger | None = None,
    max_workers: int = 8,
    include_prove: bool = True,
) -> dict:
    """Build one artifact bundle for (assembly, config, mesh_shape) under
    `out_root` and return its manifest (with a "dir" key added).

    The whole compile surface runs with the persistent cache redirected
    into the bundle: the parallel `precompile` sweep of the enumerated
    library first (per-kernel ledger attribution), then — with
    `include_prove` — `generate_setup` and one full `prove`, which
    captures the setup pipeline and the query-phase graphs the
    enumeration deliberately skips, so a cold consumer process compiles
    NOTHING. The bundle is built in a temp directory and atomically
    renamed into place; a torn build never shadows a good bundle."""
    from .precompile import enumerate_kernels, precompile
    from .shape_key import shape_bucket

    if ledger is None:
        ledger = current_compile_ledger() or CompileLedger()
    sb = shape_bucket(assembly, config)
    variant = variant_fingerprint(mesh_shape)
    final_dir = os.path.join(out_root, bundle_name(sb.key, variant))
    tmp_dir = f"{final_dir}.tmp{os.getpid()}"
    shutil.rmtree(tmp_dir, ignore_errors=True)
    cache_dir = os.path.join(tmp_dir, "cache")
    exports_dir = os.path.join(tmp_dir, "exports")
    os.makedirs(cache_dir)
    os.makedirs(exports_dir)

    t0 = time.perf_counter()
    _BUILDING[0] = True
    try:
        with _span("aot_build", shape=sb.key):
            specs = enumerate_kernels(
                assembly, config, mesh_shape=mesh_shape
            )
            with _redirected_cache(cache_dir):
                precompile(
                    assembly, config, max_workers=max_workers,
                    ledger=ledger, mesh_shape=mesh_shape, specs=specs,
                )
                if include_prove:
                    # the setup + prove graphs NOT in the enumeration
                    # (setup pipeline, fused query gather, streamed
                    # single-column opens, Merkle tail) — run once so
                    # they land in the bundle too; witness values ride
                    # on the assembly
                    from . import prover as P
                    from .setup import generate_setup

                    with _span("aot_build_prove", shape=sb.key):
                        setup = generate_setup(assembly, config)
                        if mesh_shape is not None:
                            from ..parallel.shard_sweep import (
                                mesh_from_shape,
                            )

                            mesh = (
                                mesh_shape
                                if not isinstance(
                                    mesh_shape, (tuple, list)
                                )
                                else mesh_from_shape(mesh_shape)
                            )
                            P.prove(assembly, setup, config, mesh=mesh)
                        else:
                            P.prove(assembly, setup, config)

            kernels = []
            export_ok = 0
            # compile-time cost actuals (ISSUE 12): the sweep above
            # recorded each kernel's cost_analysis()/memory_analysis()
            # into the ledger — persist them in the manifest so a
            # zero-compile cold consumer still carries actuals even
            # when its deserialized executables refuse the analysis
            ledger_costs = ledger.kernel_costs(shape_key=sb.key)
            for spec in specs:
                ent: dict = {"name": spec.name}
                cost = ledger_costs.get(spec.name)
                if cost:
                    ent["cost"] = cost
                if aot_export_enabled():
                    try:
                        from jax import export as _export

                        exp = _export.export(spec.fn)(*spec.args)
                        data = exp.serialize()
                        fname = _safe_kernel_filename(spec.name)
                        fpath = os.path.join(exports_dir, fname)
                        with open(fpath, "wb") as f:
                            f.write(data)
                        ent.update(
                            kind="export",
                            file=f"exports/{fname}",
                            sha256=hashlib.sha256(data).hexdigest(),
                            bytes=len(data),
                        )
                        export_ok += 1
                    except Exception as e:  # noqa: BLE001 — Pallas
                        # custom calls (and anything else jax.export
                        # refuses) fall back to cache-bundle-only,
                        # recorded per kernel
                        ent.update(
                            kind="cache_only", export_error=repr(e)[:200]
                        )
                else:
                    ent["kind"] = "cache_only"
                kernels.append(ent)

            cache_entries = []
            total_bytes = 0
            for base, _dirs, files in os.walk(cache_dir):
                for fname in sorted(files):
                    p = os.path.join(base, fname)
                    rel = os.path.relpath(p, tmp_dir)
                    size = os.path.getsize(p)
                    cache_entries.append(
                        {
                            "file": rel,
                            "sha256": _sha256_file(p),
                            "bytes": size,
                        }
                    )
                    total_bytes += size

            manifest = {
                "kind": AOT_KIND,
                "schema": AOT_SCHEMA,
                "created_unix": round(time.time(), 3),
                "bucket": sb.key,
                "variant": variant,
                "platform": platform_info(),
                "num_kernels": len(specs),
                "num_exports": export_ok,
                "kernels": kernels,
                "cache_entries": cache_entries,
                "cache_bytes": total_bytes,
                "build_wall_s": round(time.perf_counter() - t0, 3),
            }
            with open(os.path.join(tmp_dir, MANIFEST_NAME), "w") as f:
                json.dump(manifest, f, indent=1)
    except BaseException:
        # a failed build must not litter multi-GiB bundle-*.tmp<pid>
        # dirs next to live bundles (repeat failures would accumulate)
        shutil.rmtree(tmp_dir, ignore_errors=True)
        raise
    finally:
        _BUILDING[0] = False

    os.makedirs(out_root, exist_ok=True)
    shutil.rmtree(final_dir, ignore_errors=True)
    os.replace(tmp_dir, final_dir)
    _metrics.count_aot("builds")
    _log(
        f"aot: built {final_dir} — {len(specs)} kernels "
        f"({export_ok} exported), {len(cache_entries)} cache entries, "
        f"{total_bytes / 2**20:.1f} MiB, "
        f"{manifest['build_wall_s']:.1f}s"
    )
    manifest["dir"] = final_dir
    return manifest


# ---------------------------------------------------------------------------
# Load
# ---------------------------------------------------------------------------


@dataclass
class LoadedBundle:
    """One successfully loaded bundle: where it came from, which cache
    files were installed into the process cache dir, and what was
    skipped as corrupt."""

    dir: str
    manifest: dict
    cache_files: list[str] = field(default_factory=list)
    skipped: int = 0
    load_s: float = 0.0


# cache-entry basenames installed by any load this process performed —
# bench.py's size-capped prune consults this so artifact-backed entries
# are never evicted out from under the run that loaded them
_LOADED_CACHE_FILES: set[str] = set()


def loaded_cache_files() -> set[str]:
    return set(_LOADED_CACHE_FILES)


def load_bundle(
    out_root: str,
    assembly,
    config,
    mesh_shape=None,
    require: bool | None = None,
) -> LoadedBundle | None:
    """Find, validate and install the bundle for (assembly, config,
    mesh_shape). Returns None — after a logged warning — when the bundle
    is missing, version/platform-stale or has a corrupt manifest, so the
    caller falls back to plain JIT; BOOJUM_TPU_AOT_REQUIRE (or
    `require=True`) raises AotBundleError instead. Individually corrupt
    cache entries are skipped (their kernels JIT-compile) rather than
    rejecting the whole bundle."""
    from .shape_key import bucket_key

    if require is None:
        require = aot_require()

    def _fail(event: str, msg: str):
        _metrics.count_aot(event)
        if require:
            raise AotBundleError(msg)
        _log(f"aot: {msg} — falling back to JIT compilation")
        return None

    key = bucket_key(assembly, config)
    variant = variant_fingerprint(mesh_shape)
    bdir = os.path.join(out_root, bundle_name(key, variant))
    mpath = os.path.join(bdir, MANIFEST_NAME)
    if not os.path.isfile(mpath):
        return _fail(
            "bundle_misses",
            f"no artifact bundle for bucket {key} "
            f"(variant {variant}) under {out_root}",
        )
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except Exception as e:  # noqa: BLE001
        return _fail(
            "corrupt_bundles", f"unreadable manifest {mpath}: {e!r}"
        )
    if (
        manifest.get("kind") != AOT_KIND
        or manifest.get("schema") != AOT_SCHEMA
    ):
        return _fail(
            "corrupt_bundles",
            f"{mpath}: kind/schema mismatch "
            f"({manifest.get('kind')!r}/{manifest.get('schema')!r})",
        )
    plat = platform_info()
    mplat = manifest.get("platform") or {}
    fields = _PLATFORM_FIELDS
    if "num_local_devices" not in mplat:
        # pre-16 manifest: no process-topology keys — match on the
        # legacy global device count instead
        fields = tuple(
            k for k in fields
            if k not in ("num_local_devices", "process_count")
        ) + ("num_devices",)
    stale = [
        f"{k}: bundle {mplat.get(k)!r} vs process {plat.get(k)!r}"
        for k in fields
        if mplat.get(k) != plat.get(k)
    ]
    if stale:
        return _fail(
            "stale_bundles",
            f"stale bundle {bdir} ({'; '.join(stale)})",
        )
    dest = _active_cache_dir()
    if dest is None:
        return _fail(
            "bundle_misses",
            "no persistent compilation cache available "
            "(BOOJUM_TPU_NO_COMPILE_CACHE set?) — artifact cache "
            "entries have nowhere to install",
        )

    # from here on this process is consuming the bundle: its own
    # lowerings must produce bundle-portable cache keys
    _strip_path_keyed_options()
    t0 = time.perf_counter()
    installed: list[str] = []
    skipped = 0
    total_bytes = 0
    with _span("aot_load", bundle=os.path.basename(bdir)):
        for ent in manifest.get("cache_entries", ()):
            src = os.path.join(bdir, ent["file"])
            try:
                if _sha256_file(src) != ent["sha256"]:
                    raise ValueError("sha256 mismatch")
            except Exception as e:  # noqa: BLE001
                skipped += 1
                _metrics.count_aot("corrupt_entries")
                _log(
                    f"aot: skipping corrupt artifact {ent['file']} "
                    f"({e!r}) — its kernel will JIT-compile"
                )
                continue
            base = os.path.basename(ent["file"])
            dst = os.path.join(dest, base)
            try:
                if not os.path.exists(dst):
                    tmp = f"{dst}.aot{os.getpid()}"
                    shutil.copyfile(src, tmp)
                    os.replace(tmp, dst)  # atomic: concurrent readers
                    # never see a torn entry
            except OSError as e:
                # unwritable/full cache dir: the entry's kernel JITs;
                # never turn a bundle install into a prove() crash
                skipped += 1
                _metrics.count_aot("install_errors")
                _log(
                    f"aot: could not install {base} into {dest} "
                    f"({e!r}) — its kernel will JIT-compile"
                )
                continue
            installed.append(base)
            total_bytes += int(ent.get("bytes", 0))
    load_s = time.perf_counter() - t0
    _LOADED_CACHE_FILES.update(installed)
    _metrics.count_aot("bundles_loaded")
    _metrics.gauge_aot_add("load_s", load_s)
    _metrics.gauge_aot_add("bundle_bytes", float(total_bytes))
    _log(
        f"aot: loaded {bdir} — {len(installed)} cache entries "
        f"({total_bytes / 2**20:.1f} MiB) into {dest} in {load_s:.2f}s"
        + (f", {skipped} corrupt skipped" if skipped else "")
    )
    return LoadedBundle(
        dir=bdir, manifest=manifest, cache_files=installed,
        skipped=skipped, load_s=round(load_s, 4),
    )


# ---------------------------------------------------------------------------
# Warm (per-kernel aot_hit attribution)
# ---------------------------------------------------------------------------


def warm_from_bundle(
    assembly,
    config,
    mesh_shape=None,
    ledger: CompileLedger | None = None,
    specs=None,
    manifest_costs: dict | None = None,
) -> dict:
    """Lower + compile the enumerated kernel library SERIALLY, so each
    kernel's persistent-cache hit/miss is attributable: the monitoring
    cache counters are diffed around every `.compile()`, and the ledger
    entry records `aot_hit` (deserialized from an artifact, zero misses
    escaped to the compiler) or not. Serial is the right shape here —
    lowering is GIL-bound Python either way and a warmed compile is a
    local cache read, so there are no slow RPCs left to overlap.

    `manifest_costs` ({kernel_name: xla_cost dict}, from the bundle
    manifest) backfills cost actuals for kernels whose deserialized
    executables refuse `cost_analysis()` — a zero-compile cold process
    still attributes per-kernel flops/bytes without recompiling
    anything (ISSUE 12).

    Returns {"kernels", "aot_hits", "aot_misses", "deserialize_s"}."""
    import jax

    from .precompile import enumerate_kernels
    from .shape_key import bucket_key

    if ledger is None:
        ledger = current_compile_ledger() or CompileLedger()
    _install_cache_listener()
    _strip_path_keyed_options()
    shape = bucket_key(assembly, config)
    if specs is None:
        with _span("aot_warm_enumerate", shape=shape):
            specs = enumerate_kernels(
                assembly, config, mesh_shape=mesh_shape
            )
    cache_on = bool(jax.config.jax_compilation_cache_dir)

    hits = misses = 0
    aborted = False
    # a couple of misses = a stale entry or two; once misses exceed
    # this, the bundle's keys systematically mismatch and finishing the
    # SERIAL loop would reproduce the cold-compile wall that killed
    # BENCH_r03/r04 — bail out so the caller falls back to the
    # PARALLEL precompile sweep (already-warmed kernels re-hit there)
    miss_budget = max(2, len(specs) // 8)
    deserialize_s = 0.0
    # the warm compiles emit their own "Finished XLA compilation" log
    # lines; suppress ledger log capture so dispatch_compiles keeps
    # meaning "graphs that ESCAPED the artifact store"
    ledger.suppress_log_capture = True
    try:
        with _span("aot_warm", kernels=len(specs), shape=shape):
            for spec in specs:
                t0 = time.perf_counter()
                try:
                    low = spec.fn.lower(*spec.args)
                except Exception as e:  # noqa: BLE001
                    ledger.record(
                        spec.name, time.perf_counter() - t0, 0.0,
                        error=repr(e), shape_key=shape,
                    )
                    continue
                trace_s = time.perf_counter() - t0
                m0 = _CACHE_EVENTS["misses"]
                t1 = time.perf_counter()
                try:
                    compiled = low.compile()
                except Exception as e:  # noqa: BLE001
                    ledger.record(
                        spec.name, trace_s, time.perf_counter() - t1,
                        error=repr(e), shape_key=shape,
                    )
                    continue
                dt = time.perf_counter() - t1
                from ..utils.costmodel import xla_cost_of

                # MERGE manifest actuals under whatever the deserialized
                # executable still reports: memory_analysis() can
                # succeed while cost_analysis() refuses, and a partial
                # capture must not mask the manifest's flops/bytes
                xc = dict((manifest_costs or {}).get(spec.name) or {})
                xc.update(xla_cost_of(compiled) or {})
                xc = xc or None
                # hit = no persistent-cache MISS escaped to the
                # compiler during this kernel's compile. A compile that
                # raised neither event was deduplicated against an
                # in-process executable (jax's in-memory compilation
                # cache — e.g. two specs lowering to identical HLO),
                # which also paid no XLA compile; the miss counter is
                # the authoritative did-a-compile-escape signal, and
                # the report validator cross-checks the process-wide
                # ledger miss total against the all-hits claim.
                hit = cache_on and _CACHE_EVENTS["misses"] == m0
                ledger.record(
                    spec.name, trace_s, dt, cache_hit=hit,
                    shape_key=shape, aot_hit=hit, xla_cost=xc,
                )
                if hit:
                    hits += 1
                    deserialize_s += dt
                    _metrics.count_aot("hits")
                    _metrics.gauge_aot_add("deserialize_s", dt)
                else:
                    misses += 1
                    _metrics.count_aot("misses")
                    # a miss here still needs the deserialize gauge
                    # present for the report validator's schema
                    _metrics.gauge_aot_add("deserialize_s", 0.0)
                    if misses > miss_budget:
                        aborted = True
                        _log(
                            f"aot: {misses} misses in {len(specs)} "
                            f"kernels — bundle keys mismatch, aborting "
                            f"the serial warm (caller falls back to "
                            f"the parallel precompile sweep)"
                        )
                        break
    finally:
        ledger.suppress_log_capture = False
    _log(
        f"aot: warmed {len(specs)} kernels for {shape}: "
        f"{hits} artifact hits, {misses} misses, "
        f"deserialize {deserialize_s:.2f}s"
    )
    return {
        "kernels": len(specs),
        "aot_hits": hits,
        "aot_misses": misses,
        "aborted": aborted,
        "deserialize_s": round(deserialize_s, 4),
    }


def load_and_warm(
    out_root: str,
    assembly,
    config,
    mesh_shape=None,
    ledger: CompileLedger | None = None,
) -> dict | None:
    """The consumer entry: install the bundle's cache entries, then (per
    BOOJUM_TPU_AOT_WARM) run the attributing warm pass. None = no usable
    bundle, caller falls back to its JIT/precompile path.

    Marks the (root, bucket, variant) triple as attempted: a later
    prove() of the same bucket skips its own consult instead of paying
    a SECOND full load + serial warm (bench.py and the service warmer
    call this directly, then prove)."""
    _mark_attempted(out_root, assembly, config, mesh_shape)
    bundle = load_bundle(
        out_root, assembly, config, mesh_shape=mesh_shape
    )
    if bundle is None:
        return None
    stats: dict = {"bundle": bundle.dir, "load_s": bundle.load_s,
                   "skipped_entries": bundle.skipped}
    if aot_warm_enabled():
        manifest_costs = {
            k["name"]: k["cost"]
            for k in bundle.manifest.get("kernels", ())
            if isinstance(k, dict) and k.get("cost")
        }
        stats.update(
            warm_from_bundle(
                assembly, config, mesh_shape=mesh_shape, ledger=ledger,
                manifest_costs=manifest_costs,
            )
        )
    return stats


# ---------------------------------------------------------------------------
# prove() consult
# ---------------------------------------------------------------------------


def _would_shard_map(mesh) -> bool:
    """Whether `prove(mesh=...)` will execute via shard_map — replicated
    from parallel.sharding.mesh_mode WITHOUT needing the mesh active.
    shard_map is the default on every topology (including multi-process
    jax.distributed meshes); gspmd only when forced by env."""
    if mesh is None:
        return False
    v = os.environ.get("BOOJUM_TPU_MESH_MODE", "").strip().lower()
    if v == "gspmd":
        return False
    return True


_PROVE_ATTEMPTED: set[tuple] = set()


def _attempt_key(out_root, assembly, config, mesh_shape) -> tuple:
    from .shape_key import bucket_key

    return (
        out_root, bucket_key(assembly, config),
        json.dumps(variant_fingerprint(mesh_shape), sort_keys=True),
    )


def _mark_attempted(out_root, assembly, config, mesh_shape) -> bool:
    """Record one consult of (root, bucket, variant); True if it was
    already attempted this process (success or failure — a failed
    bundle stays failed, re-warning every prove helps nobody)."""
    key = _attempt_key(out_root, assembly, config, mesh_shape)
    if key in _PROVE_ATTEMPTED:
        return True
    _PROVE_ATTEMPTED.add(key)
    return False


def maybe_load_for_prove(assembly, config, mesh=None) -> dict | None:
    """prove()'s pre-trace consult: when BOOJUM_TPU_AOT_DIR is set, load
    (and warm) the bundle for this bucket/variant ONCE per process.
    No-op-cheap without the env var; a missing/stale bundle logs once
    and lets the prove JIT (unless BOOJUM_TPU_AOT_REQUIRE)."""
    if _BUILDING[0]:
        # the build step's own capture prove must never pull a PREVIOUS
        # bundle's entries into the redirected cache it is populating
        return None
    root = aot_dir()
    if root is None:
        return None
    if mesh is not None and not _would_shard_map(mesh):
        # the legacy GSPMD path partitions its own sequenced graphs —
        # not the enumerated kernel set a bundle holds; nothing to load
        return None
    mesh_shape = _mesh_shape_list(mesh) if mesh is not None else None
    if _attempt_key(root, assembly, config, mesh_shape) in _PROVE_ATTEMPTED:
        # already consulted — by an earlier prove, or by a direct
        # load_and_warm caller (bench.py / service warmer)
        return None
    try:
        return load_and_warm(root, assembly, config, mesh_shape=mesh_shape)
    except AotBundleError:
        raise  # BOOJUM_TPU_AOT_REQUIRE: surface, don't JIT
    except Exception as e:  # noqa: BLE001 — an unexpected loader bug
        # must degrade this prove to plain JIT, not fail it
        _log(f"aot: consult failed ({e!r}) — proving via JIT")
        return None
