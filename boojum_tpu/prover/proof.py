"""Proof object (reference `Proof`, proof.rs:121, queries proof.rs:11)."""

from __future__ import annotations

import json
from dataclasses import dataclass, field


@dataclass
class OracleQuery:
    """Leaf values + Merkle path for one oracle at one query index."""

    leaf_values: list  # flat list of ints (column values at the point)
    path: list  # list of 4-tuples


@dataclass
class SingleRoundQueries:
    witness: OracleQuery
    stage2: OracleQuery
    quotient: OracleQuery
    setup: OracleQuery
    fri: list  # OracleQuery per committed FRI round (pair leaves)


@dataclass
class Proof:
    public_inputs: list
    witness_cap: list
    stage2_cap: list
    quotient_cap: list
    values_at_z: list  # [(c0, c1)] in canonical column order
    values_at_z_omega: list  # [(c0, c1)] for the grand-product poly cols
    values_at_0: list  # [(c0, c1)] for lookup A/B polys
    fri_caps: list  # caps per committed FRI round
    final_fri_monomials: list  # [(c0, c1)]
    queries: list  # SingleRoundQueries per query
    pow_challenge: int = 0
    config: dict = field(default_factory=dict)

    def to_json(self) -> str:
        def enc(o):
            if isinstance(o, (OracleQuery, SingleRoundQueries)):
                return o.__dict__
            if isinstance(o, tuple):
                return list(o)
            raise TypeError(type(o))

        return json.dumps(self.__dict__, default=enc)

    @staticmethod
    def from_json(s: str) -> "Proof":
        d = json.loads(s)

        def dec_q(q):
            return OracleQuery(
                leaf_values=[int(v) for v in q["leaf_values"]],
                path=[tuple(int(x) for x in p) for p in q["path"]],
            )

        queries = [
            SingleRoundQueries(
                witness=dec_q(r["witness"]),
                stage2=dec_q(r["stage2"]),
                quotient=dec_q(r["quotient"]),
                setup=dec_q(r["setup"]),
                fri=[dec_q(f) for f in r["fri"]],
            )
            for r in d["queries"]
        ]
        caps = lambda c: [tuple(int(x) for x in t) for t in c]
        return Proof(
            public_inputs=[int(v) for v in d["public_inputs"]],
            witness_cap=caps(d["witness_cap"]),
            stage2_cap=caps(d["stage2_cap"]),
            quotient_cap=caps(d["quotient_cap"]),
            values_at_z=[tuple(int(x) for x in v) for v in d["values_at_z"]],
            values_at_z_omega=[
                tuple(int(x) for x in v) for v in d["values_at_z_omega"]
            ],
            values_at_0=[tuple(int(x) for x in v) for v in d["values_at_0"]],
            fri_caps=[caps(c) for c in d["fri_caps"]],
            final_fri_monomials=[
                tuple(int(x) for x in v) for v in d["final_fri_monomials"]
            ],
            queries=queries,
            pow_challenge=int(d.get("pow_challenge", 0)),
            config=d.get("config", {}),
        )
