"""Proof configuration (reference ProofConfig, prover.rs:55)."""

from dataclasses import dataclass


@dataclass
class ProofConfig:
    fri_lde_factor: int = 8
    merkle_tree_cap_size: int = 16
    num_queries: int = 50
    pow_bits: int = 0
    fri_final_degree: int = 64  # stop folding when poly degree <= this
    # optional explicit FRI folding schedule: list of per-oracle fold counts
    # (2^k-to-1 per oracle, reference fri/mod.rs interpolation schedule);
    # None derives the reference-style greedy [3,3,...,rem] schedule
    fri_folding_schedule: list | None = None
    # quotient evaluation rate (number of size-n cosets the quotient sweep
    # runs over = number of degree-<n quotient chunks). None derives it from
    # the circuit's constraint degrees at setup time — DECOUPLED from
    # fri_lde_factor, as in the reference (prover.rs:259 quotient_degree vs
    # :313 used_lde_degree): oracles commit at fri_lde_factor while the
    # sweep streams per-coset at this rate, so e.g. the Era main-VM config
    # (LDE 2, degree-8 quotient) neither inflates proofs nor HBM.
    quotient_degree: int | None = None
    # Fiat-Shamir transcript kind: poseidon2 (default, recursion-compatible)
    # | poseidon (legacy round function) | blake2s | keccak256 (reference
    # transcript.rs:48,155,264 — the tree hasher stays Poseidon2)
    transcript: str = "poseidon2"

    def __post_init__(self):
        assert self.fri_lde_factor & (self.fri_lde_factor - 1) == 0
        assert self.merkle_tree_cap_size & (self.merkle_tree_cap_size - 1) == 0
        if self.fri_folding_schedule is not None:
            assert all(int(k) >= 1 for k in self.fri_folding_schedule)
        from ..transcript import TRANSCRIPTS

        assert self.transcript in TRANSCRIPTS, self.transcript
        if self.quotient_degree is not None:
            assert self.quotient_degree >= 1
            assert self.quotient_degree & (self.quotient_degree - 1) == 0
