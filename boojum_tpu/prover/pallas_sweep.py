"""Limb-domain quotient sweep + FRI fold as fused Pallas TPU kernels.

ISSUE 4 tentpole. The quotient-stage cores (`stages._build_gate_sweep`,
`_cp_quotient_core`, `_lookup_quotient_core` / `_lookup_quotient_core_general`)
and the FRI fold (`fri._fold_once_jit`) historically computed in
`field/goldilocks.py`'s XLA-emulated uint64 — the representation Mosaic
rejects and XLA cannot fuse across kernel boundaries. This module evaluates
the SAME math on `(lo, hi)` uint32 limb pairs (`field/limbs.py` +
`field/limb_ops.py`), tiled over VMEM column blocks:

- `build_coset_terms(...)`: ONE fused kernel per assembly structure that
  evaluates, per quotient-coset block, the gate-terms contribution, the
  copy-permutation terms, the lookup terms and the 1/Z_H multiply — the
  limb counterpart of `prover._coset_sweep_fn`'s body. Trace columns and
  challenges are array arguments (new challenges never retrace); challenge
  scalars and alpha/γ-power tables ride SMEM; packed gate programs replay
  from SMEM op tables under `fori_loop` (constant graph size).
- `fri_fold(...)`: one fold round f'(x^2) = (f(x)+f(-x))/2 + ch·(f(x)-f(-x))/(2x)
  on deinterleaved even/odd limb planes.
- standalone `cp_quotient` / `lookup_quotient` / `lookup_quotient_general`
  / `gate_terms_fn` wrappers over the same in-kernel cores, for per-kernel
  parity tests and `bench_micro.py`'s u64-vs-limb sweep section.

Layout: a `(B, n)` uint64 column stack becomes two `(B, R, 128)` uint32
planes (R = n/128); the grid walks R in sublane tiles, so every field op is
an elementwise VPU op over `(B, T, 128)` tiles resident in VMEM. u64↔limb
conversion happens ONLY at these call boundaries — field ops are exact
mod p and keep values canonical, so outputs (and therefore digests,
checkpoints and proof bytes) are bit-identical to the u64 path
(`BOOJUM_TPU_LIMB_SWEEP=0` restores it; tests/test_limb_sweep.py pins
parity per kernel and end-to-end).

Dispatch: default ON where the kernels are native (TPU backend, no active
prover mesh — pallas_call cannot partition under a NamedSharding); on other
backends `BOOJUM_TPU_LIMB_SWEEP=1` opts in via interpret mode (how the CPU
tier-1 parity tests run). Shapes whose domain is not a multiple of 128
lanes (deep FRI fold tails) run the same limb cores as plain XLA ops.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..cs.field_like import LimbOps
from ..cs.gates.base import RowView, TermsCollector
from ..field import gl
from ..field import limb_ops as lop
from ..field import limbs
from ..utils import metrics as _metrics
from ..utils.pallas_util import (
    _FORCE_XLA,
    imap32,
    pick_tile,
    tpu_compiler_params,
)

_LANE = 128
_INV2_PAIR = limbs.const_pair((gl.P + 1) // 2)

# sweep tiles carry every oracle's column block at once; the default
# 16 MiB scoped-vmem budget is too tight for wide geometries
_CP = tpu_compiler_params(128 * 1024 * 1024)


def limb_sweep_enabled() -> bool:
    """True when the limb-domain sweep kernels should be dispatched.

    Default ON where they are native: TPU backend, no GSPMD-mode prover
    mesh, no BOOJUM_TPU_LIMB_SWEEP opt-out / force_xla override. Under an
    active mesh the answer depends on HOW the mesh executes
    (parallel/sharding.mesh_mode): the shard_map path hands each chip its
    local block, so pallas_call never sees a sharded operand and the limb
    kernels stay on; the legacy GSPMD path cannot partition a pallas_call
    and keeps them off. On non-TPU backends the kernels run in interpret
    mode and are OPT-IN (truthy BOOJUM_TPU_LIMB_SWEEP) — the u64 path
    stays the CPU default so tier-1 wall-clock is unchanged. The knob
    parses through transfer.env_flag_opt's spelling set (0/false/off/no,
    1/true/on/yes; junk raises — a typo must never silently pick a
    mode)."""
    from ..utils.transfer import env_flag_opt

    try:
        backend = jax.default_backend()
    except Exception:
        return False
    # the backend-dependent default makes the knob tri-state: unset means
    # "native backends only"
    explicit = env_flag_opt("BOOJUM_TPU_LIMB_SWEEP")
    if explicit is False:
        return False
    if _FORCE_XLA[0]:
        return False
    from ..parallel.sharding import active_mesh, mesh_mode

    if active_mesh() is not None and mesh_mode() != "shard_map":
        return False
    if backend == "tpu":
        return True
    # an explicit limb-RESIDENT opt-in implies the limb kernels: the
    # resident pipeline has no u64 kernel set to fall back to
    return explicit is True or env_flag_opt("BOOJUM_TPU_LIMB_RESIDENT") is True


def limb_resident_enabled() -> bool:
    """True when (lo, hi) u32 limb planes are the CANONICAL on-device
    representation for the whole prove (ISSUE 10): witness columns enter
    as planes at H2D, stay planes through iNTT/LDE, sponges, the quotient
    sweep, DEEP and FRI, and `limbs.join` survives only at the API edge
    (transcript absorbs, query openings, proof serialization).

    BOOJUM_TPU_LIMB_RESIDENT: default ON where the limb sweep is native
    (TPU backend — meshless or shard_map); `=0` restores the u64-resident
    path bit-for-bit; `=1` opts in elsewhere (CPU runs the same plane
    pipeline with interpret-mode/XLA limb kernels — how the tier-1 parity
    tests run). Residency requires the limb kernel family, so every
    limb_sweep_enabled() veto (GSPMD mesh, force_xla, LIMB_SWEEP=0)
    also disables it.

    BOOJUM_TPU_FIELD=babybear vetoes residency unconditionally (ISSUE
    19): the (lo, hi) planes ARE the Goldilocks 64-bit representation —
    a 31-bit BabyBear element is one bare u32 lane with no planes to be
    resident in, and the dispatcher routes to the disjoint `_bb` kernel
    set instead (prover/bb_kernels.py)."""
    from ..field.spec import is_babybear
    from ..utils.transfer import env_flag_opt

    if is_babybear():
        return False
    explicit = env_flag_opt("BOOJUM_TPU_LIMB_RESIDENT")
    if explicit is False:
        return False
    if not limb_sweep_enabled():
        return False
    if explicit is True:
        return True
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def _interpret() -> bool:
    try:
        return jax.default_backend() != "tpu"
    except Exception:
        return True


# ---------------------------------------------------------------------------
# Generic tiled dispatch: u64 stacks in, u64 ext columns out
# ---------------------------------------------------------------------------


def _pack_table(c0s, c1s):
    """Ext scalar columns (two (S,) uint64 arrays) -> (4, S) uint32 SMEM
    table, rows [c0_lo, c0_hi, c1_lo, c1_hi]."""
    l0, h0 = limbs.split(c0s)
    l1, h1 = limbs.split(c1s)
    return jnp.stack([l0, h0, l1, h1])


def _row(p, j):
    """Row j of a (B, ...) limb-plane pair as a base limb pair."""
    return p[0][j], p[1][j]


def _sc_ext(tb, j, like):
    """Scalar-table column j as an ext limb element broadcast to `like`
    (the poseidon2 _rc_row idiom: Mosaic broadcasts SMEM scalars via
    full_like, and the same indexing works on a plain array in the
    direct/interpret path)."""
    return (
        (jnp.full_like(like, tb[0, j]), jnp.full_like(like, tb[1, j])),
        (jnp.full_like(like, tb[2, j]), jnp.full_like(like, tb[3, j])),
    )


def _in_planes(x, shape):
    """An input stack as reshaped planes: a (lo, hi) plane pair passes
    through (the resident path — NO conversion), a u64 array splits at
    this call boundary (the converting path)."""
    if isinstance(x, tuple):
        return x[0].reshape(shape), x[1].reshape(shape)
    return limbs.split(x.reshape(shape))


def _in_rows(x) -> int:
    return int((x[0] if isinstance(x, tuple) else x).shape[0])


def _in_n(x) -> int:
    return int((x[0] if isinstance(x, tuple) else x).shape[-1])


def _tiled_ext_call(
    body, ins, table, extra_tables=(), num_ext_out=1, interpret=None,
    planes_out=False,
):
    """Run `body` over limb planes of the column stacks `ins`.

    ins: list of (B_i, n) uint64 arrays OR (lo, hi) u32 plane pairs (the
    limb-resident path — plane inputs enter the kernel with no conversion
    at all). table: (4, S) uint32 scalar table (SMEM). extra_tables: int32
    2-D tables (SMEM; packed gate programs). body(table, tables, pairs)
    receives pairs[i] = (lo, hi) uint32 arrays of block shape (B_i, T, 128)
    and returns `num_ext_out` ext limb elements of shape (T, 128). Returns
    that many (c0, c1) uint64 (n,) pairs — or, with `planes_out`, ext limb
    pairs ((lo, hi), (lo, hi)) of (n,) planes (resident callers keep the
    output resident; `limbs.join` never runs).

    Domains that don't tile (n % 128 != 0) run `body` directly on
    (B_i, 1, n) planes — same code, plain XLA."""
    n = _in_n(ins[0])
    if interpret is None:
        interpret = _interpret()
    extra_tables = tuple(jnp.asarray(t) for t in extra_tables)
    if n % _LANE != 0:
        pairs = [_in_planes(x, (_in_rows(x), 1, n)) for x in ins]
        outs = body(table, extra_tables, pairs)
        if planes_out:
            return tuple(
                (
                    (c0[0].reshape(n), c0[1].reshape(n)),
                    (c1[0].reshape(n), c1[1].reshape(n)),
                )
                for (c0, c1) in outs
            )
        return tuple(
            (limbs.join(c0).reshape(n), limbs.join(c1).reshape(n))
            for (c0, c1) in outs
        )
    R = n // _LANE
    total_rows = sum(_in_rows(x) for x in ins) + 2 * num_ext_out
    budget_rows = max(8, (4 << 20) // max(total_rows * _LANE * 8, 1))
    tile = pick_tile(R, budget_rows)
    grid = (R // tile,)

    def _smem_spec(t):
        return pl.BlockSpec(
            t.shape, imap32(lambda *_: (0,) * t.ndim), memory_space=pltpu.SMEM
        )

    in_specs = [_smem_spec(table)]
    args = [table]
    for t in extra_tables:
        in_specs.append(_smem_spec(t))
        args.append(t)
    for x in ins:
        B = _in_rows(x)
        lo, hi = _in_planes(x, (B, R, _LANE))
        spec = pl.BlockSpec(
            (B, tile, _LANE),
            imap32(lambda r: (0, r, 0)),
            memory_space=pltpu.VMEM,
        )
        in_specs += [spec, spec]
        args += [lo, hi]
    out_spec = pl.BlockSpec(
        (tile, _LANE), imap32(lambda r: (r, 0)), memory_space=pltpu.VMEM
    )
    out_shape = [
        jax.ShapeDtypeStruct((R, _LANE), jnp.uint32)
    ] * (4 * num_ext_out)
    n_tab = 1 + len(extra_tables)
    n_in = len(ins)

    def kernel(*refs):
        tb = refs[0]
        tabs = refs[1:n_tab]
        in_refs = refs[n_tab : n_tab + 2 * n_in]
        out_refs = refs[n_tab + 2 * n_in :]
        pairs = [
            (in_refs[2 * i][:], in_refs[2 * i + 1][:]) for i in range(n_in)
        ]
        outs = body(tb, tabs, pairs)
        for k, (c0, c1) in enumerate(outs):
            out_refs[4 * k][:] = c0[0]
            out_refs[4 * k + 1][:] = c0[1]
            out_refs[4 * k + 2][:] = c1[0]
            out_refs[4 * k + 3][:] = c1[1]

    planes = pl.pallas_call(
        kernel,
        grid=grid,
        out_shape=out_shape,
        in_specs=in_specs,
        out_specs=[out_spec] * (4 * num_ext_out),
        interpret=interpret,
        compiler_params=None if interpret else _CP,
    )(*args)
    outs = []
    for k in range(num_ext_out):
        if planes_out:
            outs.append(
                (
                    (planes[4 * k].reshape(n), planes[4 * k + 1].reshape(n)),
                    (
                        planes[4 * k + 2].reshape(n),
                        planes[4 * k + 3].reshape(n),
                    ),
                )
            )
            continue
        c0 = limbs.join((planes[4 * k], planes[4 * k + 1])).reshape(n)
        c1 = limbs.join((planes[4 * k + 2], planes[4 * k + 3])).reshape(n)
        outs.append((c0, c1))
    return tuple(outs)


# ---------------------------------------------------------------------------
# In-kernel cores (limb mirrors of prover/stages.py)
# ---------------------------------------------------------------------------


def _cp_terms(
    tb, like, s2_p, zs_p, copy_p, sigma_p, xs, l0,
    a_col, beta_col, gamma_col, chunks, non_residues, num_partials,
):
    """Copy-permutation quotient terms (stages._cp_quotient_core), alpha
    powers at scalar-table columns a_col.."""
    b = _sc_ext(tb, beta_col, like)
    g = _sc_ext(tb, gamma_col, like)
    z = (_row(s2_p, 0), _row(s2_p, 1))
    z_shift = (_row(zs_p, 0), _row(zs_p, 1))
    partials = [
        (_row(s2_p, 2 + 2 * j), _row(s2_p, 3 + 2 * j))
        for j in range(num_partials)
    ]
    acc = None
    zm1 = (limbs.sub(z[0], lop.ones_like(z[0])), z[1])
    t0 = (limbs.mul(zm1[0], l0), limbs.mul(zm1[1], l0))
    acc = lop.ext_accumulate(acc, t0, _sc_ext(tb, a_col, like))
    lhs_seq = partials + [z_shift]
    rhs_seq = [z] + partials
    for j, chunk in enumerate(chunks):
        num_p = den_p = None
        for col in chunk:
            w = _row(copy_p, col)
            kx = limbs.mul_const(xs, limbs.const_pair(non_residues[col]))
            num = (
                limbs.add(limbs.add(w, limbs.mul(kx, b[0])), g[0]),
                limbs.add(limbs.mul(kx, b[1]), g[1]),
            )
            s = _row(sigma_p, col)
            den = (
                limbs.add(limbs.add(w, limbs.mul(s, b[0])), g[0]),
                limbs.add(limbs.mul(s, b[1]), g[1]),
            )
            num_p = num if num_p is None else limbs.ext_mul(num_p, num)
            den_p = den if den_p is None else limbs.ext_mul(den_p, den)
        term = lop.ext_sub(
            limbs.ext_mul(lhs_seq[j], den_p), limbs.ext_mul(rhs_seq[j], num_p)
        )
        acc = lop.ext_accumulate(acc, term, _sc_ext(tb, a_col + 1 + j, like))
    return acc


def _lookup_terms(
    tb, like, s2_p, lk_cols_p, tid, table_p, mult, sel,
    a_col, gpow_col, ab_off, num_subargs, width, general,
):
    """Lookup quotient terms (stages._lookup_quotient_core and its
    general-columns twin — `sel` is the marker selector in general mode,
    None in specialized mode where the subtrahend is the constant 1)."""
    gpow = [_sc_ext(tb, gpow_col + j, like) for j in range(width + 1)]
    beta = _sc_ext(tb, gpow_col + width + 1, like)
    acc = None
    for i in range(num_subargs):
        a_i = (
            _row(s2_p, ab_off + 2 * i),
            _row(s2_p, ab_off + 2 * i + 1),
        )
        cols = [_row(lk_cols_p, i * width + j) for j in range(width)]
        den = lop.aggregate_columns(cols, tid, gpow, beta)
        term = limbs.ext_mul(a_i, den)
        if general:
            term = (limbs.sub(term[0], sel), term[1])
        else:
            term = (limbs.sub(term[0], lop.ones_like(term[0])), term[1])
        acc = lop.ext_accumulate(acc, term, _sc_ext(tb, a_col + i, like))
    b_poly = (
        _row(s2_p, ab_off + 2 * num_subargs),
        _row(s2_p, ab_off + 2 * num_subargs + 1),
    )
    t_den = lop.aggregate_columns(
        [_row(table_p, j) for j in range(width)],
        _row(table_p, width),
        gpow,
        beta,
    )
    term = limbs.ext_mul(b_poly, t_den)
    term = (limbs.sub(term[0], mult), term[1])
    return lop.ext_accumulate(
        acc, term, _sc_ext(tb, a_col + num_subargs, like)
    )


def _selector_from_consts(const_p, path):
    """Product over path bits of c_b or (1 - c_b) (stages.selector_poly_lde);
    None = constant 1 (single-gate circuits / empty marker path)."""
    sel = None
    for b, bit in enumerate(path):
        col = _row(const_p, b)
        f = col if bit else limbs.sub(lop.ones_like(col), col)
        sel = f if sel is None else limbs.mul(sel, f)
    return sel


def _scan_replay(packed, ops_ref, row):
    """Replay a PackedGateProgram over limb-pair row values: the limb twin
    of gate_capture.scan_evaluate — regs are two stacked uint32 planes and
    the op table streams from SMEM under one fori_loop (constant graph
    size for permutation-sized gates)."""
    loads = []
    sample = None
    for idx, reg, getter in (
        [(i, r, row.v) for i, r in zip(packed.v_idx, packed.v_regs)]
        + [(i, r, row.w) for i, r in zip(packed.w_idx, packed.w_regs)]
        + [(i, r, row.c) for i, r in zip(packed.c_idx, packed.c_regs)]
    ):
        val = getter(idx)
        sample = val
        loads.append((reg, val))
    assert sample is not None, packed.gate_name
    shape = sample[0].shape
    regs_lo = jnp.zeros((packed.num_regs,) + shape, jnp.uint32)
    regs_hi = jnp.zeros((packed.num_regs,) + shape, jnp.uint32)
    for reg, (vlo, vhi) in loads:
        regs_lo = regs_lo.at[reg].set(jnp.broadcast_to(vlo, shape))
        regs_hi = regs_hi.at[reg].set(jnp.broadcast_to(vhi, shape))
    for val, reg in zip(packed.const_vals, packed.const_regs):
        clo, chi = limbs.const_pair(val)
        regs_lo = regs_lo.at[reg].set(jnp.full(shape, clo, jnp.uint32))
        regs_hi = regs_hi.at[reg].set(jnp.full(shape, chi, jnp.uint32))

    def step(i, carry):
        rl, rh = carry
        a = (
            jax.lax.dynamic_index_in_dim(rl, ops_ref[i, 2], 0, keepdims=False),
            jax.lax.dynamic_index_in_dim(rh, ops_ref[i, 2], 0, keepdims=False),
        )
        b = (
            jax.lax.dynamic_index_in_dim(rl, ops_ref[i, 3], 0, keepdims=False),
            jax.lax.dynamic_index_in_dim(rh, ops_ref[i, 3], 0, keepdims=False),
        )
        res = jax.lax.switch(
            ops_ref[i, 0],
            (
                lambda x, y: limbs.add(x, y),
                lambda x, y: limbs.sub(x, y),
                lambda x, y: limbs.mul(x, y),
            ),
            a,
            b,
        )
        rl = jax.lax.dynamic_update_index_in_dim(rl, res[0], ops_ref[i, 1], 0)
        rh = jax.lax.dynamic_update_index_in_dim(rh, res[1], ops_ref[i, 1], 0)
        return rl, rh

    regs_lo, regs_hi = jax.lax.fori_loop(
        jnp.int32(0), jnp.int32(packed.num_ops), step, (regs_lo, regs_hi)
    )
    return [(regs_lo[r], regs_hi[r]) for r in packed.term_regs]


def _gate_terms(tb, tabs, like, copy_p, wit_p, const_p, plan, a_col):
    """Gate-terms contribution (stages._build_gate_sweep core): per gate,
    selector-masked sum over instances/terms of alpha^t·term. Consumes one
    SMEM op table from `tabs` per packed gate, in plan order. Returns
    (acc_ext_or_None, alpha powers consumed)."""
    t = 0
    tab_i = 0
    acc = None
    for gate, path, reps, packed in plan:
        sel = _selector_from_consts(const_p, path)
        ops_ref = None
        if packed is not None:
            ops_ref = tabs[tab_i]
            tab_i += 1
        gate_acc = None
        for inst in range(reps):
            row = RowView(
                lambda i, o=inst * gate.principal_width: _row(copy_p, o + i),
                lambda i, o=inst * gate.witness_width: _row(wit_p, o + i),
                lambda i, o=len(path): _row(const_p, o + i),
            )
            if packed is not None:
                terms = _scan_replay(packed, ops_ref, row)
            else:
                dst = TermsCollector()
                gate.evaluate(LimbOps, row, dst)
                terms = dst.terms
            assert len(terms) == gate.num_terms, gate.name
            for term in terms:
                gate_acc = lop.accumulate(
                    gate_acc, term, _sc_ext(tb, a_col + t, like)
                )
                t += 1
        if gate_acc is not None:
            if sel is not None:
                gate_acc = (
                    limbs.mul(gate_acc[0], sel),
                    limbs.mul(gate_acc[1], sel),
                )
            acc = gate_acc if acc is None else lop.ext_add(acc, gate_acc)
    return acc, t


def _packed_tables(plan):
    """The SMEM int32 op tables of the plan's packed gates, in plan order."""
    return tuple(
        np.asarray(packed.ops_arr, dtype=np.int32)
        for _gate, _path, _reps, packed in plan
        if packed is not None
    )


def _ext_scalar_cols(s):
    """Ext scalar as two (1,) uint64 arrays (table columns)."""
    return (
        jnp.asarray(s[0], jnp.uint64).reshape(1),
        jnp.asarray(s[1], jnp.uint64).reshape(1),
    )


# ---------------------------------------------------------------------------
# The fused per-coset terms kernel (prover._coset_sweep_fn's limb body)
# ---------------------------------------------------------------------------


def build_coset_terms(gates, selector_paths, geometry, lk_ctx, non_residues):
    """One fused sweep kernel per assembly structure: gate terms +
    copy-permutation terms + lookup terms + 1/Z_H, per quotient-coset
    block. Alpha-power consumption order matches the u64 body exactly
    (gates, then cp, then lookups) — same per-TERM challenge sequence the
    verifier replays. Returns call(wit_v, setup_v, s2_v, zs_v, xs_sl,
    l0_sl, zhinv_sl, ap0, ap1, beta01, gamma01, lkb01, lkg01) -> (t0, t1)
    uint64 arrays, traceable inside the outer per-coset jit."""
    from .stages import gate_sweep_plan

    (
        lookups, lk_mode, R_args, width, num_partials, chunks,
        total_alpha_terms, Cg, Ct, W, K, M, mk_path,
    ) = lk_ctx
    non_residues = tuple(int(k) for k in non_residues)
    plan = gate_sweep_plan(gates, selector_paths, geometry)
    total_gate_terms = sum(
        reps * gate.num_terms for gate, _path, reps, _packed in plan
    )
    expected = (
        total_gate_terms + 1 + len(chunks) + ((R_args + 1) if lookups else 0)
    )
    assert expected == total_alpha_terms, (expected, total_alpha_terms)
    tabs_static = _packed_tables(plan)
    ab_off = 2 + 2 * num_partials
    _metrics.count("pallas_sweep.builds")

    def body(tb, tabs, pairs, A):
        wit_p, setup_p, s2_p, zs_p, xs_p, l0_p, zh_p = pairs
        like = xs_p[0][0]
        xs = _row(xs_p, 0)
        l0 = _row(l0_p, 0)
        zh = _row(zh_p, 0)
        copy_p = (wit_p[0][:Ct], wit_p[1][:Ct])
        gate_wit_p = (
            (wit_p[0][Ct : Ct + W], wit_p[1][Ct : Ct + W]) if W else None
        )
        sigma_p = (setup_p[0][:Ct], setup_p[1][:Ct])
        const_p = (setup_p[0][Ct : Ct + K], setup_p[1][Ct : Ct + K])
        table_p = (setup_p[0][Ct + K :], setup_p[1][Ct + K :])
        t = 0
        acc = None
        if total_gate_terms:
            gcopy_p = (copy_p[0][:Cg], copy_p[1][:Cg])
            acc, t = _gate_terms(
                tb, tabs, like, gcopy_p, gate_wit_p, const_p, plan, a_col=0
            )
            assert t == total_gate_terms
        cp = _cp_terms(
            tb, like, s2_p, zs_p, copy_p, sigma_p, xs, l0,
            a_col=t, beta_col=A, gamma_col=A + 1,
            chunks=chunks, non_residues=non_residues,
            num_partials=num_partials,
        )
        acc = cp if acc is None else lop.ext_add(acc, cp)
        t += 1 + len(chunks)
        if lookups:
            mult = _row(wit_p, Ct + W)
            if lk_mode == "specialized":
                lk_cols_p = (copy_p[0][Cg:], copy_p[1][Cg:])
                tid = _row(const_p, K - 1)
                sel = None
            else:
                lk_cols_p = (copy_p[0][:Cg], copy_p[1][:Cg])
                tid = _row(const_p, len(mk_path))
                sel = _selector_from_consts(const_p, mk_path)
                if sel is None:
                    sel = lop.ones_like(like)
            lk = _lookup_terms(
                tb, like, s2_p, lk_cols_p, tid, table_p, mult, sel,
                a_col=t, gpow_col=A + 4, ab_off=ab_off,
                num_subargs=R_args, width=width,
                general=(lk_mode != "specialized"),
            )
            acc = lop.ext_add(acc, lk)
        return ((limbs.mul(acc[0], zh), limbs.mul(acc[1], zh)),)

    def call(
        wit_v, setup_v, s2_v, zs_v, xs_sl, l0_sl, zhinv_sl,
        ap0, ap1, beta01, gamma01, lkb01, lkg01,
    ):
        A = int(ap0.shape[0])
        cols0 = [ap0, beta01[:1], gamma01[:1], lkb01[:1], lkg01[:1]]
        cols1 = [ap1, beta01[1:], gamma01[1:], lkb01[1:], lkg01[1:]]
        if lookups:
            from .stages import _ext_powers_traced

            gpow = _ext_powers_traced((lkg01[0], lkg01[1]), width + 1)
            cols0.append(jnp.stack([p[0] for p in gpow]))
            cols1.append(jnp.stack([p[1] for p in gpow]))
            # beta' rides right after the γ powers (see _lookup_terms)
            cols0.append(lkb01[:1])
            cols1.append(lkb01[1:])
        table = _pack_table(
            jnp.concatenate(cols0), jnp.concatenate(cols1)
        )
        (out,) = _tiled_ext_call(
            partial(body, A=A),
            [
                wit_v, setup_v, s2_v, zs_v,
                xs_sl[None], l0_sl[None], zhinv_sl[None],
            ],
            table,
            extra_tables=tabs_static,
        )
        return out

    # scalar-table column count past the alpha block (call's layout):
    # [beta, gamma, lkb, lkg] + with lookups [gpow(width+1), beta']
    _extra_cols = 4 + ((width + 2) if lookups else 0)

    def call_planes(
        wit_p, setup_p, s2_p, zs_p, xs_p, l0_p, zh_p, table
    ):
        """The RESIDENT entry (ISSUE 10): every oracle stack arrives as a
        (lo, hi) u32 plane pair and the terms come back as an ext plane
        pair — no u64 exists anywhere in the round. `table` is the (4, S)
        u32 scalar table prebuilt on HOST from the transcript challenges
        (prover/resident.py builds it in `call`'s exact column layout)."""
        A = int(table.shape[1]) - _extra_cols
        (out,) = _tiled_ext_call(
            partial(body, A=A),
            [
                wit_p, setup_p, s2_p, zs_p,
                (xs_p[0][None], xs_p[1][None]),
                (l0_p[0][None], l0_p[1][None]),
                (zh_p[0][None], zh_p[1][None]),
            ],
            table,
            extra_tables=tabs_static,
            planes_out=True,
        )
        return out

    call.planes = call_planes
    return call


# ---------------------------------------------------------------------------
# Standalone per-family wrappers (parity tests + bench_micro sweep section)
# ---------------------------------------------------------------------------


def cp_quotient(
    z_lde, z_shift_lde, partial_ldes, copy_lde, sigma_lde, xs_lde, l0_lde,
    b, g, a0, a1, chunks, non_residues, interpret=None,
):
    """Limb twin of stages._cp_quotient_core (same args, uint64 in/out)."""
    num_partials = len(partial_ldes)
    s2_rows = [z_lde[0], z_lde[1]]
    for p in partial_ldes:
        s2_rows += [p[0], p[1]]
    s2_stack = jnp.stack(s2_rows)
    zs_stack = jnp.stack([z_shift_lde[0], z_shift_lde[1]])
    A = int(a0.shape[0])
    bc0, bc1 = _ext_scalar_cols(b)
    gc0, gc1 = _ext_scalar_cols(g)
    table = _pack_table(
        jnp.concatenate([a0, bc0, gc0]), jnp.concatenate([a1, bc1, gc1])
    )
    chunks = tuple(tuple(c) for c in chunks)
    non_residues = tuple(int(k) for k in non_residues)

    def body(tb, _tabs, pairs):
        s2_p, zs_p, copy_p, sigma_p, xs_p, l0_p = pairs
        like = xs_p[0][0]
        acc = _cp_terms(
            tb, like, s2_p, zs_p, copy_p, sigma_p,
            _row(xs_p, 0), _row(l0_p, 0),
            a_col=0, beta_col=A, gamma_col=A + 1,
            chunks=chunks, non_residues=non_residues,
            num_partials=num_partials,
        )
        return (acc,)

    (out,) = _tiled_ext_call(
        body,
        [s2_stack, zs_stack, copy_lde, sigma_lde, xs_lde[None], l0_lde[None]],
        table,
        interpret=interpret,
    )
    return out


def _lookup_quotient_shared(
    a_ldes, b_lde, cols_lde, tid_lde, table_ldes, mult_lde, sel_lde,
    b, g, a0, a1, num_subargs, width, general, interpret,
):
    s2_rows = []
    for a in a_ldes:
        s2_rows += [a[0], a[1]]
    s2_rows += [b_lde[0], b_lde[1]]
    s2_stack = jnp.stack(s2_rows)
    gpow = None
    from .stages import _ext_powers_traced

    gpow = _ext_powers_traced(g, width + 1)
    bc0, bc1 = _ext_scalar_cols(b)
    A = int(a0.shape[0])
    table = _pack_table(
        jnp.concatenate([a0] + [jnp.reshape(p[0], (1,)) for p in gpow] + [bc0]),
        jnp.concatenate([a1] + [jnp.reshape(p[1], (1,)) for p in gpow] + [bc1]),
    )
    ins = [s2_stack, cols_lde, tid_lde[None], table_ldes, mult_lde[None]]
    if general:
        ins.append(sel_lde[None])

    def body(tb, _tabs, pairs):
        if general:
            s2_p, cols_p, tid_p, table_p, mult_p, sel_p = pairs
            sel = _row(sel_p, 0)
        else:
            s2_p, cols_p, tid_p, table_p, mult_p = pairs
            sel = None
        like = tid_p[0][0]
        acc = _lookup_terms(
            tb, like, s2_p, cols_p, _row(tid_p, 0), table_p,
            _row(mult_p, 0), sel,
            a_col=0, gpow_col=A, ab_off=0,
            num_subargs=num_subargs, width=width, general=general,
        )
        return (acc,)

    (out,) = _tiled_ext_call(body, ins, table, interpret=interpret)
    return out


def lookup_quotient(
    a_ldes, b_lde, lookup_lde_cols, table_id_lde, table_ldes, mult_lde,
    b, g, a0, a1, num_repetitions, width, interpret=None,
):
    """Limb twin of stages._lookup_quotient_core."""
    return _lookup_quotient_shared(
        a_ldes, b_lde, lookup_lde_cols, table_id_lde, table_ldes, mult_lde,
        None, b, g, a0, a1, int(num_repetitions), int(width),
        general=False, interpret=interpret,
    )


def lookup_quotient_general(
    a_ldes, b_lde, gen_lde_cols, tid_lde, table_ldes, mult_lde, sel_lde,
    b, g, a0, a1, num_subargs, width, interpret=None,
):
    """Limb twin of stages._lookup_quotient_core_general."""
    return _lookup_quotient_shared(
        a_ldes, b_lde, gen_lde_cols, tid_lde, table_ldes, mult_lde,
        sel_lde, b, g, a0, a1, int(num_subargs), int(width),
        general=True, interpret=interpret,
    )


def gate_terms_fn(gates, selector_paths, geometry, interpret=None):
    """Limb twin of stages._build_gate_sweep: returns fn(copy_lde_flat,
    wit_lde_flat, const_lde_flat, a0, a1) -> ext pair."""
    from .stages import gate_sweep_plan

    plan = gate_sweep_plan(
        tuple(gates), tuple(tuple(p) for p in selector_paths), geometry
    )
    tabs_static = _packed_tables(plan)

    def fn(copy_lde_flat, wit_lde_flat, const_lde_flat, a0, a1):
        table = _pack_table(a0, a1)
        ins = [copy_lde_flat]
        has_wit = wit_lde_flat is not None
        if has_wit:
            ins.append(wit_lde_flat)
        ins.append(const_lde_flat)

        def body(tb, tabs, pairs):
            if has_wit:
                copy_p, wit_p, const_p = pairs
            else:
                copy_p, const_p = pairs
                wit_p = None
            like = copy_p[0][0]
            acc, _t = _gate_terms(
                tb, tabs, like, copy_p, wit_p, const_p, plan, a_col=0
            )
            return (acc,)

        (out,) = _tiled_ext_call(
            body, ins, table, extra_tables=tabs_static, interpret=interpret
        )
        return out

    def fn_planes(copy_p, wit_p, const_p, table):
        """Resident entry: plane stacks + a prebuilt (4, S) u32 table."""
        ins = [copy_p]
        has_wit = wit_p is not None
        if has_wit:
            ins.append(wit_p)
        ins.append(const_p)

        def body(tb, tabs, pairs):
            if has_wit:
                copy_pp, wit_pp, const_pp = pairs
            else:
                copy_pp, const_pp = pairs
                wit_pp = None
            like = copy_pp[0][0]
            acc, _t = _gate_terms(
                tb, tabs, like, copy_pp, wit_pp, const_pp, plan, a_col=0
            )
            return (acc,)

        (out,) = _tiled_ext_call(
            body, ins, table, extra_tables=tabs_static,
            interpret=interpret, planes_out=True,
        )
        return out

    fn.planes = fn_planes
    return fn


# ---------------------------------------------------------------------------
# FRI fold
# ---------------------------------------------------------------------------


def _fold_body(tb, _tabs, pairs):
    quad, inv = pairs
    like = quad[0][0]
    a = (_row(quad, 0), _row(quad, 1))
    bm = (_row(quad, 2), _row(quad, 3))
    invx = _row(inv, 0)
    s = lop.ext_add(a, bm)
    d = lop.ext_sub(a, bm)
    d_over_x = (limbs.mul(d[0], invx), limbs.mul(d[1], invx))
    ch = _sc_ext(tb, 0, like)
    t = lop.ext_add(s, limbs.ext_mul(d_over_x, ch))
    return (
        (
            limbs.mul_const(t[0], _INV2_PAIR),
            limbs.mul_const(t[1], _INV2_PAIR),
        ),
    )


def fri_fold(values, ch, inv_x_pairs, interpret=None):
    """Limb twin of fri._fold_once_jit: one fold round over the
    bit-reversed codeword (pairs adjacent). `values` is an ext pair over
    the round domain, `ch` an ext pair of uint64 scalars; returns the
    half-size ext pair. The even/odd deinterleave happens outside the
    kernel (one strided XLA slice) so the kernel body is fully
    elementwise."""
    quad = jnp.stack(
        [
            values[0][0::2], values[1][0::2],
            values[0][1::2], values[1][1::2],
        ]
    )
    c0, c1 = _ext_scalar_cols(ch)
    table = _pack_table(c0, c1)
    (out,) = _tiled_ext_call(
        _fold_body, [quad, inv_x_pairs[None]], table, interpret=interpret
    )
    return out


def fri_fold_planes(values_p, table, inv_x_p, interpret=None):
    """Resident FRI fold (ISSUE 10): `values_p` is an ext plane pair over
    the round domain, `table` the (4, 1) u32 challenge table, `inv_x_p` the
    1/x plane pair at pair positions. Returns the half-size ext plane pair
    — the fold CHAIN stays resident across rounds, where the converting
    `fri_fold` paid a split+join per fold."""
    c0p, c1p = values_p
    quad = (
        jnp.stack([c0p[0][0::2], c1p[0][0::2], c0p[0][1::2], c1p[0][1::2]]),
        jnp.stack([c0p[1][0::2], c1p[1][0::2], c0p[1][1::2], c1p[1][1::2]]),
    )
    (out,) = _tiled_ext_call(
        _fold_body,
        [quad, (inv_x_p[0][None], inv_x_p[1][None])],
        table,
        interpret=interpret,
        planes_out=True,
    )
    return out
