"""Per-row satisfiability checker (debug aid; reference
satisfiability_test.rs:15 `check_if_satisfied`): re-evaluates every placed
gate over scalar field values. Runs the SAME evaluators as prover/verifier via
ScalarOps — a three-way cross-check of the field-like contract.
"""

from __future__ import annotations

import numpy as np

from ..cs.field_like import ScalarOps
from ..cs.gates.base import RowView, TermsCollector


def check_if_satisfied(assembly, verbose: bool = False) -> bool:
    n = assembly.trace_len
    geometry = assembly.geometry
    copy_vals = assembly.copy_cols_values
    wit_vals = assembly.wit_cols_values
    for row in range(n):
        gate = assembly.gates[int(assembly.row_gate[row])]
        if gate.num_terms == 0:
            continue
        consts = assembly.gate_constants.get(row, ())
        reps = gate.num_repetitions(geometry)
        for inst in range(reps):
            voff = inst * gate.principal_width
            woff = inst * gate.witness_width

            row_view = RowView(
                lambda i, row=row, voff=voff: int(copy_vals[voff + i, row]),
                lambda i, row=row, woff=woff: int(wit_vals[woff + i, row]),
                lambda i, consts=consts: consts[i] if i < len(consts) else 0,
            )
            dst = TermsCollector()
            gate.evaluate(ScalarOps, row_view, dst)
            for ti, term in enumerate(dst.terms):
                if term != 0:
                    if verbose:
                        print(
                            f"UNSATISFIED: row {row} gate {gate.name} "
                            f"instance {inst} term {ti} = {term}"
                        )
                    return False
    if assembly.lookups_enabled:
        if not _check_lookups(assembly, verbose):
            return False
    return True


def _check_lookups(assembly, verbose: bool) -> bool:
    """Every placed lookup tuple is a table row and the multiplicity column
    counts exactly the placed tuples (reference satisfiability_test.rs lookup
    spot checks). Rows are deduplicated first (np.unique over stacked
    [table-id; lookup columns]) so the padding-dominated tail of large traces
    costs one check, not n."""
    lp = assembly.lookup_params
    R, w = lp.num_repetitions, lp.width
    vals = assembly.lookup_cols_values
    tid_col = assembly.lookup_table_id_col
    stacked = np.vstack([np.asarray(tid_col, dtype=np.uint64)[None, :], vals])
    uniq, ucounts = np.unique(stacked, axis=1, return_counts=True)
    counts = {}
    for u in range(uniq.shape[1]):
        tid = int(uniq[0, u])
        times = int(ucounts[u])
        if tid == 0:
            if verbose:
                print("LOOKUP: row(s) with no table id")
            return False
        table = assembly.lookup_tables[tid - 1]
        col = uniq[1:, u]
        for s in range(R):
            tup = tuple(int(col[s * w + j]) for j in range(table.width))
            try:
                ridx = table.row_index(tup)
            except (KeyError, AssertionError):
                if verbose:
                    print(
                        f"LOOKUP UNSATISFIED: sub-arg {s} tuple "
                        f"{tup} not in table {table.name}"
                    )
                return False
            for j in range(table.width, w):
                if int(col[s * w + j]) != 0:
                    if verbose:
                        print(f"LOOKUP: sub-arg {s} pad not zero")
                    return False
            key = (tid, ridx)
            counts[key] = counts.get(key, 0) + times
    # compare the FULL multiplicity vector (zeros included): a spurious
    # nonzero multiplicity on a never-looked-up row breaks the B(0) = ΣA_i(0)
    # sum check in the real argument and must fail here too
    expected = np.zeros(assembly.trace_len, dtype=np.uint64)
    for (tid, ridx), cnt in counts.items():
        expected[assembly.table_offsets[tid] + ridx] = cnt
    bad = np.nonzero(expected != np.asarray(assembly.multiplicities))[0]
    if bad.size:
        if verbose:
            g = int(bad[0])
            print(
                f"LOOKUP UNSATISFIED: multiplicity at stacked row {g}: "
                f"column says {int(assembly.multiplicities[g])}, trace has "
                f"{int(expected[g])}"
            )
        return False
    return True
