"""Per-row satisfiability checker (debug aid; reference
satisfiability_test.rs:15 `check_if_satisfied`): re-evaluates every placed
gate over scalar field values. Runs the SAME evaluators as prover/verifier via
ScalarOps — a three-way cross-check of the field-like contract.
"""

from __future__ import annotations

from ..cs.field_like import ScalarOps
from ..cs.gates.base import RowView, TermsCollector


def check_if_satisfied(assembly, verbose: bool = False) -> bool:
    n = assembly.trace_len
    geometry = assembly.geometry
    copy_vals = assembly.copy_cols_values
    wit_vals = assembly.wit_cols_values
    for row in range(n):
        gate = assembly.gates[int(assembly.row_gate[row])]
        if gate.num_terms == 0:
            continue
        consts = assembly.gate_constants.get(row, ())
        reps = gate.num_repetitions(geometry)
        for inst in range(reps):
            voff = inst * gate.principal_width
            woff = inst * gate.witness_width

            row_view = RowView(
                lambda i, row=row, voff=voff: int(copy_vals[voff + i, row]),
                lambda i, row=row, woff=woff: int(wit_vals[woff + i, row]),
                lambda i, consts=consts: consts[i] if i < len(consts) else 0,
            )
            dst = TermsCollector()
            gate.evaluate(ScalarOps, row_view, dst)
            for ti, term in enumerate(dst.terms):
                if term != 0:
                    if verbose:
                        print(
                            f"UNSATISFIED: row {row} gate {gate.name} "
                            f"instance {inst} term {ti} = {term}"
                        )
                    return False
    return True
