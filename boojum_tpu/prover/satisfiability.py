"""Per-row satisfiability checker (debug aid; reference
satisfiability_test.rs:15 `check_if_satisfied`): re-evaluates every placed
gate over scalar field values. Runs the SAME evaluators as prover/verifier via
ScalarOps — a three-way cross-check of the field-like contract.
"""

from __future__ import annotations

from ..cs.field_like import ScalarOps
from ..cs.gates.base import RowView, TermsCollector


def check_if_satisfied(assembly, verbose: bool = False) -> bool:
    n = assembly.trace_len
    geometry = assembly.geometry
    copy_vals = assembly.copy_cols_values
    wit_vals = assembly.wit_cols_values
    for row in range(n):
        gate = assembly.gates[int(assembly.row_gate[row])]
        if gate.num_terms == 0:
            continue
        consts = assembly.gate_constants.get(row, ())
        reps = gate.num_repetitions(geometry)
        for inst in range(reps):
            voff = inst * gate.principal_width
            woff = inst * gate.witness_width

            row_view = RowView(
                lambda i, row=row, voff=voff: int(copy_vals[voff + i, row]),
                lambda i, row=row, woff=woff: int(wit_vals[woff + i, row]),
                lambda i, consts=consts: consts[i] if i < len(consts) else 0,
            )
            dst = TermsCollector()
            gate.evaluate(ScalarOps, row_view, dst)
            for ti, term in enumerate(dst.terms):
                if term != 0:
                    if verbose:
                        print(
                            f"UNSATISFIED: row {row} gate {gate.name} "
                            f"instance {inst} term {ti} = {term}"
                        )
                    return False
    if assembly.lookups_enabled:
        if not _check_lookups(assembly, verbose):
            return False
    return True


def _check_lookups(assembly, verbose: bool) -> bool:
    """Every placed lookup tuple is a table row and the multiplicity column
    counts exactly the placed tuples (reference satisfiability_test.rs lookup
    spot checks)."""
    lp = assembly.lookup_params
    R, w = lp.num_repetitions, lp.width
    n = assembly.trace_len
    vals = assembly.lookup_cols_values
    tid_col = assembly.lookup_table_id_col
    counts = {}
    for row in range(n):
        tid = int(tid_col[row])
        if tid == 0:
            if verbose:
                print(f"LOOKUP: row {row} has no table id")
            return False
        table = assembly.lookup_tables[tid - 1]
        for s in range(R):
            tup = tuple(int(vals[s * w + j, row]) for j in range(table.width))
            try:
                ridx = table.row_index(tup)
            except (KeyError, AssertionError):
                if verbose:
                    print(
                        f"LOOKUP UNSATISFIED: row {row} sub-arg {s} tuple "
                        f"{tup} not in table {table.name}"
                    )
                return False
            for j in range(table.width, w):
                if int(vals[s * w + j, row]) != 0:
                    if verbose:
                        print(f"LOOKUP: row {row} sub-arg {s} pad not zero")
                    return False
            key = (tid, ridx)
            counts[key] = counts.get(key, 0) + 1
    for (tid, ridx), cnt in counts.items():
        gidx = assembly.table_offsets[tid] + ridx
        if int(assembly.multiplicities[gidx]) != cnt:
            if verbose:
                print(
                    f"LOOKUP UNSATISFIED: multiplicity of table {tid} row "
                    f"{ridx}: column says {int(assembly.multiplicities[gidx])},"
                    f" trace has {cnt}"
                )
            return False
    return True
