"""Per-row satisfiability checker (debug aid; reference
satisfiability_test.rs:15 `check_if_satisfied`): re-evaluates every placed
gate over scalar field values. Runs the SAME evaluators as prover/verifier via
ScalarOps — a three-way cross-check of the field-like contract.
"""

from __future__ import annotations

import numpy as np

from ..cs.field_like import BBScalarOps, ScalarOps
from ..cs.gates.base import RowView, TermsCollector


def _scalar_ops_for(assembly):
    """The scalar ops context matching the field the assembly was
    synthesized over (ISSUE 20): gate evaluators must reduce mod the same
    prime the witness resolver used or every row looks unsatisfied."""
    if getattr(assembly, "field", "goldilocks") == "babybear":
        return BBScalarOps
    return ScalarOps


def check_if_satisfied(assembly, verbose: bool = False) -> bool:
    ops = _scalar_ops_for(assembly)
    n = assembly.trace_len
    geometry = assembly.geometry
    copy_vals = assembly.copy_cols_values
    wit_vals = assembly.wit_cols_values
    for row in range(n):
        gate = assembly.gates[int(assembly.row_gate[row])]
        if gate.num_terms == 0:
            continue
        consts = assembly.gate_constants.get(row, ())
        reps = gate.num_repetitions(geometry)
        for inst in range(reps):
            voff = inst * gate.principal_width
            woff = inst * gate.witness_width

            row_view = RowView(
                lambda i, row=row, voff=voff: int(copy_vals[voff + i, row]),
                lambda i, row=row, woff=woff: int(wit_vals[woff + i, row]),
                lambda i, consts=consts: consts[i] if i < len(consts) else 0,
            )
            dst = TermsCollector()
            gate.evaluate(ops, row_view, dst)
            for ti, term in enumerate(dst.terms):
                if term != 0:
                    if verbose:
                        print(
                            f"UNSATISFIED: row {row} gate {gate.name} "
                            f"instance {inst} term {ti} = {term}"
                        )
                    return False
    if assembly.lookups_enabled:
        if not _check_lookups(assembly, verbose):
            return False
    return True


def _check_lookups_general(assembly, verbose: bool) -> bool:
    """General-purpose-columns mode: tuples live on lookup-marker rows in
    the general copy columns; the row's gate constant is the table id."""
    lp = assembly.lookup_params
    w = lp.width
    mk_gid = assembly.lookup_marker_gid()
    if mk_gid is None:
        if verbose:
            print("LOOKUP: general mode but no marker gate registered")
        return False
    marker = assembly.gates[mk_gid]
    reps = marker.num_repetitions(assembly.geometry)
    counts: dict = {}
    rows = np.nonzero(assembly.row_gate == mk_gid)[0]
    if rows.size == 0:
        return True
    tids = np.zeros(rows.size, dtype=np.uint64)
    for k, row in enumerate(rows):
        consts = assembly.gate_constants.get(int(row), ())
        tids[k] = int(consts[0]) if consts else 0
    # dedup whole marker rows (same trick as the specialized checker): one
    # check per unique (tid, all-slot tuples) combination, not per row
    stacked = np.vstack(
        [tids[None, :]]
        + [
            assembly.copy_cols_values[s * w : (s + 1) * w, rows]
            for s in range(reps)
        ]
    )
    uniq, ucounts = np.unique(stacked, axis=1, return_counts=True)
    for u in range(uniq.shape[1]):
        tid = int(uniq[0, u])
        times = int(ucounts[u])
        if tid == 0:
            if verbose:
                print("LOOKUP: marker row(s) with no table id")
            return False
        table = assembly.lookup_tables[tid - 1]
        col = uniq[1:, u]
        for s in range(reps):
            tup = tuple(int(col[s * w + j]) for j in range(table.width))
            try:
                ridx = table.row_index(tup)
            except (KeyError, AssertionError):
                if verbose:
                    print(
                        f"LOOKUP UNSATISFIED: slot {s} tuple {tup} "
                        f"not in table {table.name}"
                    )
                return False
            for j in range(table.width, w):
                if int(col[s * w + j]) != 0:
                    if verbose:
                        print(f"LOOKUP: slot {s} pad not zero")
                    return False
            key = (tid, ridx)
            counts[key] = counts.get(key, 0) + times
    expected = np.zeros(assembly.trace_len, dtype=np.uint64)
    for (tid, ridx), cnt in counts.items():
        expected[assembly.table_offsets[tid] + ridx] = cnt
    bad = np.nonzero(expected != np.asarray(assembly.multiplicities))[0]
    if bad.size:
        if verbose:
            print(
                f"LOOKUP: multiplicity mismatch at stacked rows "
                f"{bad[:5].tolist()}"
            )
        return False
    return True


def _check_lookups(assembly, verbose: bool) -> bool:
    """Every placed lookup tuple is a table row and the multiplicity column
    counts exactly the placed tuples (reference satisfiability_test.rs lookup
    spot checks). Rows are deduplicated first (np.unique over stacked
    [table-id; lookup columns]) so the padding-dominated tail of large traces
    costs one check, not n."""
    if assembly.lookup_mode == "general":
        return _check_lookups_general(assembly, verbose)
    lp = assembly.lookup_params
    R, w = lp.num_repetitions, lp.width
    vals = assembly.lookup_cols_values
    tid_col = assembly.lookup_table_id_col
    stacked = np.vstack([np.asarray(tid_col, dtype=np.uint64)[None, :], vals])
    uniq, ucounts = np.unique(stacked, axis=1, return_counts=True)
    counts = {}
    for u in range(uniq.shape[1]):
        tid = int(uniq[0, u])
        times = int(ucounts[u])
        if tid == 0:
            if verbose:
                print("LOOKUP: row(s) with no table id")
            return False
        table = assembly.lookup_tables[tid - 1]
        col = uniq[1:, u]
        for s in range(R):
            tup = tuple(int(col[s * w + j]) for j in range(table.width))
            try:
                ridx = table.row_index(tup)
            except (KeyError, AssertionError):
                if verbose:
                    print(
                        f"LOOKUP UNSATISFIED: sub-arg {s} tuple "
                        f"{tup} not in table {table.name}"
                    )
                return False
            for j in range(table.width, w):
                if int(col[s * w + j]) != 0:
                    if verbose:
                        print(f"LOOKUP: sub-arg {s} pad not zero")
                    return False
            key = (tid, ridx)
            counts[key] = counts.get(key, 0) + times
    # compare the FULL multiplicity vector (zeros included): a spurious
    # nonzero multiplicity on a never-looked-up row breaks the B(0) = ΣA_i(0)
    # sum check in the real argument and must fail here too
    expected = np.zeros(assembly.trace_len, dtype=np.uint64)
    for (tid, ridx), cnt in counts.items():
        expected[assembly.table_offsets[tid] + ridx] = cnt
    bad = np.nonzero(expected != np.asarray(assembly.multiplicities))[0]
    if bad.size:
        if verbose:
            g = int(bad[0])
            print(
                f"LOOKUP UNSATISFIED: multiplicity at stacked row {g}: "
                f"column says {int(assembly.multiplicities[g])}, trace has "
                f"{int(expected[g])}"
            )
        return False
    return True
