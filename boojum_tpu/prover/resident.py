"""Limb-resident prove pipeline (ISSUE 10 tentpole).

PR 4 put the quotient sweep and the FRI folds on (lo, hi) u32 limb planes
but converted u64<->limb "ONLY at call boundaries" — so every kernel call
still paid a split on entry and a join on exit, and each conversion fenced
XLA fusion at the seam. This module makes the PLANES the canonical
on-device representation for the whole prove (ICICLE's conclusion,
PAPERS.md): witness columns enter as planes at H2D upload
(`utils/transfer.chunked_upload(planes=True)` splits once on host), stay
planes through iNTT/LDE (`ntt/limb_ntt.py`), the stage-2 grand product,
Poseidon2 leaf/node sponges, the fused quotient sweep, DEEP accumulation,
streamed commits and the FRI chain, and `limbs.join` survives only at the
API edge — transcript absorbs, query openings and proof serialization all
reassemble u64 ON HOST (`limbs.join_np`).

Everything here is a `_p`-suffixed twin of a fused-round graph in
prover.py/stages.py, computing the SAME exact mod-p values on planes
(limb ops are exact and canonical, inverses unique), so proof bytes and
the Fiat–Shamir checkpoint stream are bit-identical to the u64 path —
pinned by tests/test_limb_resident.py, which also pins ZERO interior
`limb.splits`/`limb.joins` during a resident prove (the metrics counters
charged inside field/limbs.py; the allowlisted edges are the host-side
conversions plus the per-setup-object `limbs.edge("ingest:*")` splits of
data that was born u64 before residency — sigma/setup oracles and their
committed tree).

Dispatch: `pallas_sweep.limb_resident_enabled()` — BOOJUM_TPU_LIMB_RESIDENT
default ON where the limb sweep is native (TPU), `=0` restores the
u64-resident path bit-for-bit, `=1` opts in on CPU (tier-1 parity tests).

Field note (ISSUE 19): limb residency is a Goldilocks-only concern — the
planes exist because Goldilocks elements are 64-bit and Mosaic has no
64-bit integer datapath. Under `BOOJUM_TPU_FIELD=babybear` every element
already fits one u32 lane, so there is nothing to split: the dispatcher
(`precompile.enumerate_kernels`) selects the plane-free `_bb` kernel twins
(prover/bb_kernels.py) before the limb-residency check, and
`limb_resident_enabled()` itself returns False under babybear. No module
here participates in a BabyBear prove.
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np

from ..field import extension as ext_f
from ..field import gl
from ..field import limb_ops as lop
from ..field import limbs
from ..ntt import limb_ntt as LN
from ..ntt.ntt import _powers_np, bitreverse_indices
from ..utils import metrics as _metrics
from ..utils.spans import span as _span

_MASK = 0xFFFFFFFF


# ---------------------------------------------------------------------------
# Host-side builders: scalars/tables enter the device already as planes
# ---------------------------------------------------------------------------


def host_planes(arr):
    """Host uint64 numpy -> device (lo, hi) planes (host split: an edge
    by construction — no device conversion exists)."""
    lo, hi = limbs.split_np(np.asarray(arr, dtype=np.uint64))
    return jnp.asarray(lo), jnp.asarray(hi)


def sc_table_np(cols0, cols1) -> np.ndarray:
    """Two int lists (c0s, c1s) -> (4, S) u32 scalar table in the kernel
    layout of pallas_sweep._pack_table, built entirely on host."""
    c0 = np.array([int(v) % gl.P for v in cols0], dtype=np.uint64)
    c1 = np.array([int(v) % gl.P for v in cols1], dtype=np.uint64)
    return np.stack(
        [
            (c0 & _MASK).astype(np.uint32),
            (c0 >> np.uint64(32)).astype(np.uint32),
            (c1 & _MASK).astype(np.uint32),
            (c1 >> np.uint64(32)).astype(np.uint32),
        ]
    )


def ext_sc_np(s) -> np.ndarray:
    """One host ext scalar -> (4,) u32 [c0lo, c0hi, c1lo, c1hi]."""
    c0, c1 = int(s[0]) % gl.P, int(s[1]) % gl.P
    return np.array(
        [c0 & _MASK, c0 >> 32, c1 & _MASK, c1 >> 32], dtype=np.uint32
    )


def bg_np(b, g) -> np.ndarray:
    """Two host ext scalars -> (8,) u32 [b0lo,b0hi,b1lo,b1hi,g0..]."""
    return np.concatenate([ext_sc_np(b), ext_sc_np(g)])


def _next_pow2(x: int) -> int:
    c = 1
    while c < max(x, 1):
        c *= 2
    return c


def sweep_table_np(alpha, total_alpha_terms, beta, gamma, lkb, lkg,
                   lookups: bool, width: int) -> np.ndarray:
    """The (4, S) u32 scalar table of the resident sweep, in EXACTLY the
    column layout of pallas_sweep.build_coset_terms' u64 `call` ([alpha
    powers (pow2 cap) | beta | gamma | lkb | lkg | gpow(width+1) | lkb']),
    built from the host transcript challenges — the alpha/γ-power tables
    never exist as device u64."""
    capA = _next_pow2(total_alpha_terms)
    ap = ext_f.powers_s(tuple(int(v) for v in alpha), capA)
    cols0 = [p[0] for p in ap] + [beta[0], gamma[0], lkb[0], lkg[0]]
    cols1 = [p[1] for p in ap] + [beta[1], gamma[1], lkb[1], lkg[1]]
    if lookups:
        gpow = ext_f.powers_s(tuple(int(v) for v in lkg), width + 1)
        cols0 += [p[0] for p in gpow] + [lkb[0]]
        cols1 += [p[1] for p in gpow] + [lkb[1]]
    return sc_table_np(cols0, cols1)


# ---------------------------------------------------------------------------
# Cached plane domain tables (challenge-independent, per geometry)
# ---------------------------------------------------------------------------


_mul_gen_jit = jax.jit(
    lambda p: limbs.mul_const(
        p, limbs.const_pair(int(gl.MULTIPLICATIVE_GENERATOR))
    )
)


@lru_cache(maxsize=4)
def domain_xs_brev_p(log_n: int, lde_factor: int):
    """Plane twin of prover._domain_xs_brev (host powers + limb scale)."""
    log_full = log_n + (lde_factor.bit_length() - 1)
    xs = host_planes(_powers_np(gl.omega(log_full), 1 << log_full))
    xs = _mul_gen_jit(xs)
    brev = jnp.asarray(bitreverse_indices(log_full))
    return xs[0][brev], xs[1][brev]


@jax.jit
def _sub_ones_jit(p):
    return limbs.sub(p, lop.ones_like(p[0]))


@partial(jax.jit, static_argnums=(2,))
def _l0_scale_jit(zh_p, binv_p, log_n: int):
    t = limbs.mul_const(zh_p, limbs.const_pair(gl.inv(1 << log_n)))
    return limbs.mul(t, binv_p)


@lru_cache(maxsize=4)
def l0_brev_p(log_n: int, lde_factor: int):
    """Plane twin of prover._l0_brev."""
    n = 1 << log_n
    log_full = log_n + (lde_factor.bit_length() - 1)
    zh_vals = np.array(
        [
            gl.sub(
                gl.pow_(
                    gl.mul(
                        gl.MULTIPLICATIVE_GENERATOR,
                        gl.pow_(gl.omega(log_full), int(jb)),
                    ),
                    n,
                ),
                1,
            )
            for jb in bitreverse_indices(lde_factor.bit_length() - 1)
        ],
        dtype=np.uint64,
    )
    zh = host_planes(np.repeat(zh_vals, n))
    xs = domain_xs_brev_p(log_n, lde_factor)
    binv = lop.batch_inverse_jit(_sub_ones_jit(xs))
    return _l0_scale_jit(zh, binv, log_n)


@lru_cache(maxsize=4)
def inv_xs_brev_p(log_n: int, lde_factor: int):
    return lop.batch_inverse_jit(domain_xs_brev_p(log_n, lde_factor))


@lru_cache(maxsize=4)
def vanishing_inv_brev_p(log_n: int, lde_factor: int):
    """Plane twin of prover._vanishing_inv_brev (fully host-built)."""
    n = 1 << log_n
    log_lde = lde_factor.bit_length() - 1
    w_full = gl.omega(log_n + log_lde)
    vals = []
    for jb in bitreverse_indices(log_lde):
        shift = gl.mul(gl.MULTIPLICATIVE_GENERATOR, gl.pow_(w_full, int(jb)))
        vals.append(gl.inv(gl.sub(gl.pow_(shift, n), 1)))
    return host_planes(np.repeat(np.array(vals, dtype=np.uint64), n))


@lru_cache(maxsize=8)
def omega_powers_p(log_n: int):
    """[1, w, w^2, ...] planes for the z-shift (host-built)."""
    return host_planes(_powers_np(gl.omega(log_n), 1 << log_n))


def clear_plane_caches():
    """Resident counterpart of prover.clear_domain_caches."""
    from .fri import fold_challenge_tables_p

    for fn in (
        domain_xs_brev_p,
        l0_brev_p,
        inv_xs_brev_p,
        vanishing_inv_brev_p,
        omega_powers_p,
        fold_challenge_tables_p,
    ):
        fn.cache_clear()


# ---------------------------------------------------------------------------
# Round 2: grand product / lookup twins (stages.py on planes)
# ---------------------------------------------------------------------------


def _bg(bg_arr):
    """(8,) u32 -> (beta_ext, gamma_ext) scalar plane elements."""
    b = ((bg_arr[0], bg_arr[1]), (bg_arr[2], bg_arr[3]))
    g = ((bg_arr[4], bg_arr[5]), (bg_arr[6], bg_arr[7]))
    return b, g


@partial(jax.jit, static_argnums=(4,))
def _all_chunk_num_den_p(copy_p, sigma_p, ks_p, xs_bg, chunks):
    """Plane twin of stages._all_chunk_num_den (same scan structure).
    `xs_bg` bundles (xs planes, (8,) challenge table)."""
    xs_p, bg_arr = xs_bg
    b, g = _bg(bg_arr)
    n = copy_p[0].shape[-1]
    flat = [col for c in chunks for col in c]
    assert flat == list(range(len(flat))), chunks
    w = len(chunks[0])
    K_full = sum(1 for c in chunks if len(c) == w)
    assert all(len(c) == w for c in chunks[:K_full]), chunks
    assert len(chunks) - K_full <= 1, chunks

    def _prod_terms(cv, sv, kv):
        num_p = den_p = None
        for j in range(cv[0].shape[0]):
            wcol = (cv[0][j], cv[1][j])
            kx = limbs.mul(xs_p, (kv[0][j], kv[1][j]))
            num = (
                limbs.add(limbs.add(wcol, limbs.mul(kx, b[0])), g[0]),
                limbs.add(limbs.mul(kx, b[1]), g[1]),
            )
            s = (sv[0][j], sv[1][j])
            den = (
                limbs.add(limbs.add(wcol, limbs.mul(s, b[0])), g[0]),
                limbs.add(limbs.mul(s, b[1]), g[1]),
            )
            num_p = num if num_p is None else limbs.ext_mul(num_p, num)
            den_p = den if den_p is None else limbs.ext_mul(den_p, den)
        return num_p, den_p

    def body(carry, blk):
        cvl, cvh, svl, svh, kvl, kvh = blk
        num_p, den_p = _prod_terms((cvl, cvh), (svl, svh), (kvl, kvh))
        return carry, (
            num_p[0][0], num_p[0][1], num_p[1][0], num_p[1][1],
            den_p[0][0], den_p[0][1], den_p[1][0], den_p[1][1],
        )

    Cw = K_full * w
    _, scanned = jax.lax.scan(
        body,
        None,
        (
            copy_p[0][:Cw].reshape(K_full, w, n),
            copy_p[1][:Cw].reshape(K_full, w, n),
            sigma_p[0][:Cw].reshape(K_full, w, n),
            sigma_p[1][:Cw].reshape(K_full, w, n),
            ks_p[0][:Cw].reshape(K_full, w),
            ks_p[1][:Cw].reshape(K_full, w),
        ),
    )
    n00, n01, n10, n11, d00, d01, d10, d11 = scanned
    if len(chunks) > K_full:
        num_p, den_p = _prod_terms(
            (copy_p[0][Cw:], copy_p[1][Cw:]),
            (sigma_p[0][Cw:], sigma_p[1][Cw:]),
            (ks_p[0][Cw:], ks_p[1][Cw:]),
        )
        n00 = jnp.concatenate([n00, num_p[0][0][None]])
        n01 = jnp.concatenate([n01, num_p[0][1][None]])
        n10 = jnp.concatenate([n10, num_p[1][0][None]])
        n11 = jnp.concatenate([n11, num_p[1][1][None]])
        d00 = jnp.concatenate([d00, den_p[0][0][None]])
        d01 = jnp.concatenate([d01, den_p[0][1][None]])
        d10 = jnp.concatenate([d10, den_p[1][0][None]])
        d11 = jnp.concatenate([d11, den_p[1][1][None]])
    return ((n00, n01), (n10, n11)), ((d00, d01), (d10, d11))


def _ext_prefix_prod_p(a):
    """Inclusive ext prefix product along the last axis on planes
    (stages._ext_prefix_prod_xla twin)."""
    n = a[0][0].shape[-1]
    shift = 1
    while shift < n:
        ones = jnp.ones((shift,), jnp.uint32)
        zeros = jnp.zeros((shift,), jnp.uint32)
        shifted = (
            (
                jnp.concatenate([ones, a[0][0][:-shift]]),
                jnp.concatenate([zeros, a[0][1][:-shift]]),
            ),
            (
                jnp.concatenate([zeros, a[1][0][:-shift]]),
                jnp.concatenate([zeros, a[1][1][:-shift]]),
            ),
        )
        a = limbs.ext_mul(a, shifted)
        shift *= 2
    return a


@jax.jit
def _z_and_partials_p(num_all, den_inv_all):
    """Plane twin of stages._z_and_partials."""
    K = num_all[0][0].shape[0]
    ratios = limbs.ext_mul(num_all, den_inv_all)

    def row(j):
        return (
            (ratios[0][0][j], ratios[0][1][j]),
            (ratios[1][0][j], ratios[1][1][j]),
        )

    full = row(0)
    for j in range(1, K):
        full = limbs.ext_mul(full, row(j))
    incl = _ext_prefix_prod_p(full)
    one = jnp.ones((1,), jnp.uint32)
    zero = jnp.zeros((1,), jnp.uint32)
    z = (
        (
            jnp.concatenate([one, incl[0][0][:-1]]),
            jnp.concatenate([zero, incl[0][1][:-1]]),
        ),
        (
            jnp.concatenate([zero, incl[1][0][:-1]]),
            jnp.concatenate([zero, incl[1][1][:-1]]),
        ),
    )
    parts = []
    acc = z
    for j in range(K - 1):
        acc = limbs.ext_mul(acc, row(j))
        parts.append(acc)
    if parts:
        stacked = (
            (
                jnp.stack([p[0][0] for p in parts]),
                jnp.stack([p[0][1] for p in parts]),
            ),
            (
                jnp.stack([p[1][0] for p in parts]),
                jnp.stack([p[1][1] for p in parts]),
            ),
        )
        return z, stacked
    e = jnp.zeros((0,) + z[0][0].shape, jnp.uint32)
    return z, ((e, e), (e, e))


@partial(jax.jit, static_argnums=(3, 4))
def _lookup_denominators_p(
    lk_cols_p, tid_table_p, bg_arr, num_repetitions, width
):
    """Plane twin of stages._lookup_denominators. `tid_table_p` bundles
    (table_id planes, stacked table planes)."""
    tid_p, table_p = tid_table_p
    b, g = _bg(bg_arr)
    gpow = lop.ext_powers(g, width + 1)
    dens = []
    for i in range(num_repetitions):
        cols = [
            (lk_cols_p[0][i * width + j], lk_cols_p[1][i * width + j])
            for j in range(width)
        ]
        dens.append(lop.aggregate_columns(cols, tid_p, gpow, b))
    dens.append(
        lop.aggregate_columns(
            [(table_p[0][j], table_p[1][j]) for j in range(width)],
            (table_p[0][width], table_p[1][width]),
            gpow,
            b,
        )
    )
    return (
        (
            jnp.stack([d[0][0] for d in dens]),
            jnp.stack([d[0][1] for d in dens]),
        ),
        (
            jnp.stack([d[1][0] for d in dens]),
            jnp.stack([d[1][1] for d in dens]),
        ),
    )


def stage2_stack_fn_p(assembly, selector_paths):
    """Plane twin of prover._stage2_stack_fn, cached per assembly."""
    cached = getattr(assembly, "_stage2_stack_p_jit", None)
    if cached is not None:
        return cached

    from .stages import chunk_columns

    lookups = assembly.lookups_enabled
    lk_mode = assembly.lookup_mode
    R_args = assembly.num_lookup_subargs
    num_chunks = len(
        chunk_columns(
            assembly.copy_placement.shape[0] + assembly.num_lookup_cols,
            assembly.geometry.max_allowed_constraint_degree,
        )
    )
    if lookups and lk_mode == "general":
        mk_path = tuple(selector_paths[assembly.lookup_marker_gid()])
    else:
        mk_path = None

    @jax.jit
    def fn(z, partials_stacked, lk_inv, multiplicities, consts_dev):
        lo_rows = [z[0][0], z[1][0]]
        hi_rows = [z[0][1], z[1][1]]
        for j in range(num_chunks - 1):
            lo_rows += [partials_stacked[0][0][j], partials_stacked[1][0][j]]
            hi_rows += [partials_stacked[0][1][j], partials_stacked[1][1][j]]
        if lookups:
            sel_h = None
            if lk_mode == "general":
                for bdx, bit in enumerate(mk_path):
                    col = (consts_dev[0][bdx], consts_dev[1][bdx])
                    f = col if bit else limbs.sub(lop.ones_like(col[0]), col)
                    sel_h = f if sel_h is None else limbs.mul(sel_h, f)
            for i in range(R_args):
                a0 = (lk_inv[0][0][i], lk_inv[0][1][i])
                a1 = (lk_inv[1][0][i], lk_inv[1][1][i])
                if sel_h is not None:
                    a0 = limbs.mul(a0, sel_h)
                    a1 = limbs.mul(a1, sel_h)
                lo_rows += [a0[0], a1[0]]
                hi_rows += [a0[1], a1[1]]
            t0 = limbs.mul(
                (lk_inv[0][0][R_args], lk_inv[0][1][R_args]), multiplicities
            )
            t1 = limbs.mul(
                (lk_inv[1][0][R_args], lk_inv[1][1][R_args]), multiplicities
            )
            lo_rows += [t0[0], t1[0]]
            hi_rows += [t0[1], t1[1]]
        return jnp.stack(lo_rows), jnp.stack(hi_rows)

    assembly._stage2_stack_p_jit = fn
    return fn


# ---------------------------------------------------------------------------
# Round 3: z-shift, coset evaluation, quotient tail (on planes)
# ---------------------------------------------------------------------------


@jax.jit
def _zshift_p(s2_mono2_p, pows_p):
    """(2, n) z monomial planes -> z(w·x) monomial planes (host powers)."""
    return limbs.mul(s2_mono2_p, (pows_p[0][None], pows_p[1][None]))


_SWEEP_EVAL_CHUNK = 128 << 20


@jax.jit
def _coset_eval_p(mono_p, scale_row_p):
    """Plane twin of prover._coset_eval (same chunked barrier posture)."""
    B, n = mono_p[0].shape
    per = max(1, _SWEEP_EVAL_CHUNK // (n * 8))
    if B <= per:
        scaled = limbs.mul(
            mono_p, (scale_row_p[0][None], scale_row_p[1][None])
        )
        return _fft_dispatch(scaled)
    out_lo = jnp.zeros((B, n), jnp.uint32)
    out_hi = jnp.zeros((B, n), jnp.uint32)
    mlo, mhi = mono_p
    for i in range(0, B, per):
        mlo, mhi, out_lo, out_hi = jax.lax.optimization_barrier(
            (mlo, mhi, out_lo, out_hi)
        )
        chunk = limbs.mul(
            (mlo[i : i + per], mhi[i : i + per]),
            (scale_row_p[0][None], scale_row_p[1][None]),
        )
        clo, chi = _fft_dispatch(chunk)
        out_lo = jax.lax.dynamic_update_slice_in_dim(out_lo, clo, i, axis=0)
        out_hi = jax.lax.dynamic_update_slice_in_dim(out_hi, chi, i, axis=0)
    return out_lo, out_hi


def _fft_dispatch(p):
    return LN.fft_natural_to_bitreversed_p(p)


@jax.jit
def _coset_eval_q_p(mono_p, scale_q_p, c_arr):
    """Plane twin of prover._coset_eval_q."""
    row = (
        jax.lax.dynamic_index_in_dim(scale_q_p[0], c_arr, 0, keepdims=False),
        jax.lax.dynamic_index_in_dim(scale_q_p[1], c_arr, 0, keepdims=False),
    )
    return _coset_eval_p(mono_p, row)


@partial(jax.jit, static_argnums=(2, 3))
def _quotient_interp_p(T0_parts, T1_parts, Q: int, n: int):
    """Plane twin of prover._quotient_interp."""
    g_inv = gl.inv(gl.MULTIPLICATIVE_GENERATOR)
    T0 = (
        jnp.concatenate([t[0] for t in T0_parts]),
        jnp.concatenate([t[1] for t in T0_parts]),
    )
    T1 = (
        jnp.concatenate([t[0] for t in T1_parts]),
        jnp.concatenate([t[1] for t in T1_parts]),
    )
    T_mono = tuple(
        LN.distribute_powers_p(LN.ifft_bitreversed_to_natural_p(t), g_inv)
        for t in (T0, T1)
    )
    lo_rows, hi_rows = [], []
    for i in range(Q):
        for comp in (0, 1):
            lo_rows.append(T_mono[comp][0][i * n : (i + 1) * n])
            hi_rows.append(T_mono[comp][1][i * n : (i + 1) * n])
    return jnp.stack(lo_rows), jnp.stack(hi_rows)


def _quotient_tail_p(T0_parts, T1_parts, Q: int, n: int, L: int, cap: int):
    """Plane twin of prover._quotient_tail_fused (same dispatch split)."""
    from ..merkle import commit_layers_planes

    q_mono = _quotient_interp_p(tuple(T0_parts), tuple(T1_parts), Q, n)
    q_lde = LN.lde_from_monomial_p(q_mono, L)
    return q_mono, q_lde, commit_layers_planes(q_lde, cap)


# ---------------------------------------------------------------------------
# Round 4: evaluations at z (on planes)
# ---------------------------------------------------------------------------


def _modsum_p(p):
    """Modular sum along the last axis on planes (ntt._modsum twin)."""
    lo, hi = p
    n = lo.shape[-1]
    while n > 1:
        if n % 2 == 1:
            z = jnp.zeros(lo.shape[:-1] + (1,), jnp.uint32)
            lo = jnp.concatenate([lo, z], axis=-1)
            hi = jnp.concatenate([hi, z], axis=-1)
            n += 1
        lo, hi = limbs.add(
            (lo[..., : n // 2], hi[..., : n // 2]),
            (lo[..., n // 2 :], hi[..., n // 2 :]),
        )
        n //= 2
    return lo[..., 0], hi[..., 0]


def _modsum_axis0_p(p):
    return _modsum_p((jnp.moveaxis(p[0], 0, -1), jnp.moveaxis(p[1], 0, -1)))


@partial(jax.jit, static_argnums=(1,))
def _ext_powers_p_jit(z_tb, count: int):
    """Plane twin of ntt._ext_powers_jit (log-doubling; `z_tb` is the (4,)
    u32 host-built challenge)."""
    p0 = (jnp.ones((1,), jnp.uint32), jnp.zeros((1,), jnp.uint32))
    p1 = (jnp.zeros((1,), jnp.uint32), jnp.zeros((1,), jnp.uint32))
    step = ((z_tb[0], z_tb[1]), (z_tb[2], z_tb[3]))
    cur = 1
    while cur < count:
        n0, n1 = limbs.ext_mul((p0, p1), step)
        p0 = (
            jnp.concatenate([p0[0], n0[0]]),
            jnp.concatenate([p0[1], n0[1]]),
        )
        p1 = (
            jnp.concatenate([p1[0], n1[0]]),
            jnp.concatenate([p1[1], n1[1]]),
        )
        step = limbs.ext_mul(step, step)
        cur *= 2
    return p0, p1


def _eval_with_pows_p(coeffs_p, p0, p1):
    c0 = _modsum_p(limbs.mul(coeffs_p, p0))
    c1 = _modsum_p(limbs.mul(coeffs_p, p1))
    return c0, c1


@jax.jit
def _evals_p(all_mono_p, s2_mono_p, z_tb, zw_tb):
    """Plane twin of prover._evals_fused; outputs stay planes (the caller
    pulls them to host and joins at the transcript edge)."""
    n = all_mono_p[0].shape[-1]
    zp = _ext_powers_p_jit(z_tb, n)
    ev0, ev1 = _eval_with_pows_p(all_mono_p, zp[0], zp[1])
    zwp = _ext_powers_p_jit(zw_tb, n)
    evw0, evw1 = _eval_with_pows_p(
        (s2_mono_p[0][:2], s2_mono_p[1][:2]), zwp[0], zwp[1]
    )
    return ev0, ev1, evw0, evw1


# ---------------------------------------------------------------------------
# Round 5: DEEP on planes
# ---------------------------------------------------------------------------


@jax.jit
def _deep_denoms_p(xs_lde_p, z_tb, zw_tb):
    """Plane twin of prover._deep_denoms_fused."""
    shape = xs_lde_p[0].shape

    def _sub_sc(tb_lo, tb_hi):
        return limbs.sub(xs_lde_p, (tb_lo, tb_hi))

    a = _sub_sc(z_tb[0], z_tb[1])
    b = _sub_sc(zw_tb[0], zw_tb[1])
    c0 = (jnp.stack([a[0], b[0]]), jnp.stack([a[1], b[1]]))
    nz = limbs.neg((z_tb[2], z_tb[3]))
    nzw = limbs.neg((zw_tb[2], zw_tb[3]))
    c1 = (
        jnp.stack(
            [
                jnp.broadcast_to(nz[0], shape),
                jnp.broadcast_to(nzw[0], shape),
            ]
        ),
        jnp.stack(
            [
                jnp.broadcast_to(nz[1], shape),
                jnp.broadcast_to(nzw[1], shape),
            ]
        ),
    )
    return c0, c1


_DEEP_BLOCK_BUDGET = 128 << 20


@jax.jit
def _deep_block_p(blk_p, c0s_p, c1s_p):
    return (
        _modsum_axis0_p(
            limbs.mul(blk_p, (c0s_p[0][:, None], c0s_p[1][:, None]))
        ),
        _modsum_axis0_p(
            limbs.mul(blk_p, (c1s_p[0][:, None], c1s_p[1][:, None]))
        ),
    )


@jax.jit
def _deep_combine_p(t0, t1, y0s_p, y1s_p, c0s_p, c1s_p, inv_xz):
    s = limbs.ext_mul((c0s_p, c1s_p), (y0s_p, y1s_p))
    num = (
        limbs.sub(t0, _modsum_axis0_p(s[0])),
        limbs.sub(t1, _modsum_axis0_p(s[1])),
    )
    return limbs.ext_mul(num, inv_xz)


def deep_source_blocks_p(sources, per_bytes: int):
    """Plane twin of streaming.deep_source_blocks."""
    from .streaming import MonomialPlanesSource

    off = 0
    for src in sources:
        if isinstance(src, MonomialPlanesSource):
            for i, flat in src.blocks():
                yield flat, off + i
            off += src.shape[0]
        else:
            B, N = src[0].shape
            per = max(1, per_bytes // (N * 8))
            for i in range(0, B, per):
                yield (src[0][i : i + per], src[1][i : i + per]), off + i
            off += B


def _deep_main_sum_p(sources, y0s_p, y1s_p, c0s_p, c1s_p, inv_xz):
    """Plane twin of prover._deep_main_sum."""
    t0 = t1 = None
    for blk, off in deep_source_blocks_p(sources, _DEEP_BLOCK_BUDGET):
        _metrics.count("deep.blocks")
        j = off + blk[0].shape[0]
        b0, b1 = _deep_block_p(
            blk,
            (c0s_p[0][off:j], c0s_p[1][off:j]),
            (c1s_p[0][off:j], c1s_p[1][off:j]),
        )
        t0 = b0 if t0 is None else limbs.add(t0, b0)
        t1 = b1 if t1 is None else limbs.add(t1, b1)
    return _deep_combine_p(t0, t1, y0s_p, y1s_p, c0s_p, c1s_p, inv_xz)


@lru_cache(maxsize=8)
def _deep_extras_fn_p(num_zw: int, num_lk: int, num_pi: int):
    """Plane twin of prover._deep_extras_fn."""

    @jax.jit
    def fn(h, cols_zw, cols_lk, cols_pi, inv_xzw, inv_x, pi_denoms,
           y_zw, y_lk0, pi_vals, ch0, ch1):
        shape = h[0][0].shape
        t = 0
        for i in range(num_zw):
            ch = ((ch0[0][t], ch0[1][t]), (ch1[0][t], ch1[1][t]))
            ny = limbs.neg((y_zw[1][0][i], y_zw[1][1][i]))
            num = (
                limbs.sub(
                    (cols_zw[0][i], cols_zw[1][i]),
                    (y_zw[0][0][i], y_zw[0][1][i]),
                ),
                (
                    jnp.broadcast_to(ny[0], shape),
                    jnp.broadcast_to(ny[1], shape),
                ),
            )
            h = lop.ext_add(h, limbs.ext_mul(limbs.ext_mul(num, inv_xzw), ch))
            t += 1
        for i in range(num_lk):
            ch = ((ch0[0][t], ch0[1][t]), (ch1[0][t], ch1[1][t]))
            num = (
                limbs.sub(
                    (cols_lk[0][2 * i], cols_lk[1][2 * i]),
                    (y_lk0[0][0][i], y_lk0[0][1][i]),
                ),
                limbs.sub(
                    (cols_lk[0][2 * i + 1], cols_lk[1][2 * i + 1]),
                    (y_lk0[1][0][i], y_lk0[1][1][i]),
                ),
            )
            term = limbs.ext_mul(
                (limbs.mul(num[0], inv_x), limbs.mul(num[1], inv_x)), ch
            )
            h = lop.ext_add(h, term)
            t += 1
        for k in range(num_pi):
            ch = ((ch0[0][t], ch0[1][t]), (ch1[0][t], ch1[1][t]))
            num = limbs.sub(
                (cols_pi[0][k], cols_pi[1][k]),
                (pi_vals[0][k], pi_vals[1][k]),
            )
            term_base = limbs.mul(num, (pi_denoms[0][k], pi_denoms[1][k]))
            h = lop.ext_add(
                h,
                (
                    limbs.mul(term_base, ch[0]),
                    limbs.mul(term_base, ch[1]),
                ),
            )
            t += 1
        return h

    return fn


@partial(jax.jit, static_argnums=(1, 2))
def _cols_from_mono_p(mono_p, idxs: tuple, L: int):
    """Plane twin of prover._cols_from_mono."""
    sel_idx = jnp.asarray(np.array(idxs, dtype=np.int64))
    sel = (mono_p[0][sel_idx], mono_p[1][sel_idx])
    lde = LN.lde_from_monomial_p(sel, L)
    return (
        lde[0].reshape(len(idxs), -1),
        lde[1].reshape(len(idxs), -1),
    )


@partial(jax.jit, static_argnums=(2,))
def _stream_gather_p(mono_p, idx_dev, L: int):
    from .streaming import MonomialPlanesSource

    return MonomialPlanesSource(mono_p, L).gather_rows(idx_dev)


def deep_round5_prep_p(
    assembly, *, log_n, L, N, lookups, num_partials, R_args,
    s2_mono_p, wit_mono_p, s2_lde_flat_p, wit_lde_all_p, xs_lde_p,
    z_tb, zw_tb, omega,
):
    """Plane twin of prover._deep_round5_prep."""
    from .streaming import MonomialPlanesSource

    num_lk = (R_args + 1) if lookups else 0
    num_pi = len(assembly.public_inputs)
    d = _deep_denoms_p(xs_lde_p, z_tb, zw_tb)
    dinv = lop.ext_batch_inverse_jit(d)
    ab_off = 2 + 2 * num_partials
    s2_idxs = [0, 1] + [ab_off + j for j in range(2 * num_lk)]
    if isinstance(s2_lde_flat_p, MonomialPlanesSource):
        s2_cols = _cols_from_mono_p(s2_mono_p, tuple(s2_idxs), L)
    else:
        sel = jnp.asarray(np.array(s2_idxs))
        s2_cols = (s2_lde_flat_p[0][sel], s2_lde_flat_p[1][sel])
    if lookups:
        inv_x = inv_xs_brev_p(log_n, L)
    else:
        z1 = jnp.zeros((1,), jnp.uint32)
        inv_x = (z1, z1)
    if num_pi:
        pi_cols_idx = [c_ for (c_, _r, _v) in assembly.public_inputs]
        if isinstance(wit_lde_all_p, MonomialPlanesSource):
            cols_pi = _cols_from_mono_p(wit_mono_p, tuple(pi_cols_idx), L)
        else:
            sel = jnp.asarray(np.array(pi_cols_idx))
            cols_pi = (wit_lde_all_p[0][sel], wit_lde_all_p[1][sel])
        pi_points = host_planes(
            np.array(
                [gl.pow_(omega, r) for (_c, r, _v) in assembly.public_inputs],
                dtype=np.uint64,
            )
        )
        pi_denoms = lop.batch_inverse_jit(
            _pi_denom_sub_jit(xs_lde_p, pi_points)
        )
        pi_vals = host_planes(
            np.array(
                [v for (_c, _r, v) in assembly.public_inputs],
                dtype=np.uint64,
            )
        )
    else:
        e = jnp.zeros((0, N), jnp.uint32)
        cols_pi = (e, e)
        pi_denoms = (e, e)
        ze = jnp.zeros((0,), jnp.uint32)
        pi_vals = (ze, ze)
    return {
        "inv_xz": (
            (dinv[0][0][0], dinv[0][1][0]),
            (dinv[1][0][0], dinv[1][1][0]),
        ),
        "inv_xzw": (
            (dinv[0][0][1], dinv[0][1][1]),
            (dinv[1][0][1], dinv[1][1][1]),
        ),
        "s2_cols": s2_cols,
        "inv_x": inv_x,
        "cols_pi": cols_pi,
        "pi_denoms": pi_denoms,
        "pi_vals": pi_vals,
    }


@jax.jit
def _pi_denom_sub_jit(xs_lde_p, pi_points_p):
    return limbs.sub(
        (xs_lde_p[0][None, :], xs_lde_p[1][None, :]),
        (pi_points_p[0][:, None], pi_points_p[1][:, None]),
    )


# ---------------------------------------------------------------------------
# Commit pipeline (on planes)
# ---------------------------------------------------------------------------


def commit_pipeline_p(values_p, L: int, cap: int, stream: bool, sm_mesh=None):
    """Plane twin of prover._commit_pipeline: values over H (B, n) planes
    -> (mono planes, lde planes | None, plane tree layers)."""
    from ..merkle import commit_layers_planes, node_layers_planes
    from .streaming import streamed_leaf_digests_blocks_p

    if sm_mesh is not None:
        from ..parallel.shard_sweep import commit_pipeline_sm_p

        with _span("commit_pipeline", stream=stream, sm=True, resident=True):
            return commit_pipeline_sm_p(values_p, L, cap, stream, sm_mesh)
    with _span("commit_pipeline", stream=stream, resident=True):
        mono = LN.monomial_from_values_p(values_p)
        _metrics.count("ntt.monomial_from_values")
        _metrics.count("ntt.resident_transforms")
        if stream:
            digests = streamed_leaf_digests_blocks_p(mono, L)
            _metrics.count("merkle.streamed_commits")
            _metrics.count("merkle.resident_commits")
            return mono, None, node_layers_planes(digests, cap)
        lde = LN.lde_from_monomial_p(mono, L)
        _metrics.count("ntt.lde_from_monomial")
        _metrics.count("merkle.commits")
        _metrics.count("merkle.resident_commits")
        return mono, lde, commit_layers_planes(lde, cap)


# ---------------------------------------------------------------------------
# Ingest edges: data born u64 before residency enters planes ONCE per
# holder object (cached), inside an explicit limbs.edge() allowlist scope
# ---------------------------------------------------------------------------


def ingest_planes(arr, label: str):
    """Device u64 -> planes at an allowlisted ingest edge (setup oracles,
    committed trees — built u64 by generate_setup before residency)."""
    with limbs.edge(f"ingest:{label}"):
        return limbs.split(arr)


def setup_tree_planes(setup):
    """The setup's committed Merkle tree as a PlaneMerkleTree (cached on
    the setup object; cap values identical)."""
    from ..merkle import PlaneMerkleTree

    cached = getattr(setup, "_tree_planes", None)
    if cached is not None:
        return cached
    layers = [
        ingest_planes(layer, "setup_tree") for layer in setup.setup_tree.layers
    ]
    tree = PlaneMerkleTree.from_layers(layers, setup.setup_tree.cap_size)
    setup._tree_planes = tree
    return tree


# ---------------------------------------------------------------------------
# Prefetch (round-0 overlap): the plane-table half of
# prover._prefetch_challenge_independent
# ---------------------------------------------------------------------------


def prefetch_plane_tables(config, *, log_n, L, Q, n, lookups):
    from .fri import fold_challenge_tables_p, fold_schedule

    LN.PlaneNTTContext(log_n)
    log_full = log_n + (L.bit_length() - 1)
    LN.PlaneNTTContext(log_full)
    LN._lde_scale_planes(log_n, L, int(gl.MULTIPLICATIVE_GENERATOR))
    LN._lde_scale_planes(log_n, Q, int(gl.MULTIPLICATIVE_GENERATOR))
    domain_xs_brev_p(log_n, L)
    domain_xs_brev_p(log_n, Q)
    l0_brev_p(log_n, Q)
    vanishing_inv_brev_p(log_n, Q)
    omega_powers_p(log_n)
    if lookups:
        inv_xs_brev_p(log_n, L)
    num_folds = sum(
        fold_schedule(
            n, config.fri_final_degree,
            getattr(config, "fri_folding_schedule", None),
        )
    )
    fold_challenge_tables_p(log_full, num_folds)
