"""Plain (host) verifier.

Counterpart of `/root/reference/src/cs/implementations/verifier.rs:888`:
transcript replay, quotient reconstruction at z via the same gate evaluators
(over ExtScalarOps — the verifier-side face of the field-like contract),
copy-permutation and log-derivative lookup relations at z, the lookup
sum check over the openings at 0 (verifier.rs:1242), and DEEP/FRI query
checking against Merkle caps. Pure python ints: the verifier is tiny compared
to proving and needs no device.
"""

from __future__ import annotations

from ..field import gl
from ..field import extension as ext_f
from ..merkle import verify_proof_over_cap
from ..transcript import BitSource, make_transcript
from ..cs.field_like import ExtScalarOps
from ..cs.gates.base import RowView, TermsCollector
from .fri import fri_verify_queries, INV2
from .pow import pow_verify
from .stages import chunk_columns
from .setup import non_residues_for_copy_permutation

W_EXT = (0, 1)  # the extension generator (sqrt of 7)


class _ZRowView:
    """RowView over values-at-z for one gate instance chunk."""

    def __init__(self, wit_vals, const_vals, var_off, wit_off, const_off, num_copy):
        self.wit_vals = wit_vals
        self.const_vals = const_vals
        self.var_off = var_off
        self.wit_off = wit_off
        self.const_off = const_off
        self.num_copy = num_copy

    def v(self, i):
        return self.wit_vals[self.var_off + i]

    def w(self, i):
        return self.wit_vals[self.num_copy + self.wit_off + i]

    def c(self, i):
        return self.const_vals[self.const_off + i]


def ext_from_pair(c0, c1):
    """Value of an ext-coefficient poly from its two base-poly openings."""
    return ext_f.add_s(c0, ext_f.mul_s(c1, W_EXT))


def verify(vk, proof, gates) -> bool:
    geometry = vk.geometry
    n = vk.trace_len
    log_n = n.bit_length() - 1
    L = vk.fri_lde_factor
    Q = vk.effective_quotient_degree()
    log_full = log_n + (L.bit_length() - 1)
    N = n * L
    Ct = vk.num_copy_cols  # ALL columns under copy permutation
    Cg = geometry.num_columns_under_copy_permutation
    W = vk.num_wit_cols
    lp = vk.lookup_params
    lookups = lp is not None and lp.is_enabled
    lk_specialized = lookups and lp.use_specialized_columns
    M = 1 if lookups else 0
    wdt = lp.width if lookups else 0
    if lk_specialized:
        R = lp.num_repetitions
    elif lookups:
        R = Cg // wdt  # general mode: sub-arguments tile the general columns
    else:
        R = 0
    K = geometry.num_constant_columns + (1 if lk_specialized else 0)
    TW = (wdt + 1) if lookups else 0
    if not lk_specialized and Ct != Cg:
        return False
    if lk_specialized and Ct != Cg + R * wdt:
        return False
    if [g.name for g in gates] != list(vk.gate_names):
        return False
    if len(proof.public_inputs) != len(vk.public_input_locations):
        return False

    num_chunks = len(chunk_columns(Ct, geometry.max_allowed_constraint_degree))
    S = 2 * (1 + (num_chunks - 1)) + 2 * R + 2 * M  # z, partials, A_i, B
    B = (Ct + W + M) + (Ct + K + TW) + S + 2 * Q
    if len(proof.values_at_z) != B or len(proof.values_at_z_omega) != 2:
        return False
    if len(proof.values_at_0) != R + M:
        return False

    # ---- transcript replay ------------------------------------------------
    t = make_transcript(getattr(vk, 'transcript', 'poseidon2'))
    t.witness_merkle_tree_cap(vk.setup_merkle_cap)
    t.witness_field_elements(proof.public_inputs)
    t.witness_merkle_tree_cap(proof.witness_cap)
    beta = t.get_ext_challenge()
    gamma = t.get_ext_challenge()
    if lookups:
        lookup_beta = t.get_ext_challenge()
        lookup_gamma = t.get_ext_challenge()
    t.witness_merkle_tree_cap(proof.stage2_cap)
    alpha = t.get_ext_challenge()
    t.witness_merkle_tree_cap(proof.quotient_cap)
    z_chal = t.get_ext_challenge()
    for v in proof.values_at_z:
        t.witness_field_elements(v)
    for v in proof.values_at_z_omega:
        t.witness_field_elements(v)
    for v in proof.values_at_0:
        t.witness_field_elements(v)
    deep_ch = t.get_ext_challenge()
    # FRI replay — ALL security parameters come from the VK, never the proof
    from .fri import fold_schedule

    try:
        schedule = fold_schedule(
            n, vk.fri_final_degree, getattr(vk, "fri_folding_schedule", None)
        )
    except AssertionError:
        return False
    num_folds = sum(schedule)
    if len(proof.fri_caps) != len(schedule):
        return False
    fri_challenges = []
    for r in range(len(schedule)):
        t.witness_merkle_tree_cap(proof.fri_caps[r])
        fri_challenges.append(t.get_ext_challenge())
    if len(proof.final_fri_monomials) != (n >> num_folds):
        return False
    for c0, c1 in proof.final_fri_monomials:
        t.witness_field_elements([c0, c1])

    # ---- split openings ---------------------------------------------------
    vals = [tuple(v) for v in proof.values_at_z]
    wit_vals = vals[: Ct + W + M]
    sigma_vals = vals[Ct + W + M : 2 * Ct + W + M]
    const_vals = vals[2 * Ct + W + M : 2 * Ct + W + M + K]
    table_vals = vals[2 * Ct + W + M + K : 2 * Ct + W + M + K + TW]
    s2_vals = vals[2 * Ct + W + M + K + TW : 2 * Ct + W + M + K + TW + S]
    q_vals = vals[2 * Ct + W + M + K + TW + S :]

    # ---- quotient identity at z ------------------------------------------
    alpha_pows = _powers_iter(alpha)
    total = ExtScalarOps.zero()
    for gid, gate in enumerate(gates):
        if gate.num_terms == 0:
            continue
        path = vk.selector_paths[gid]
        sel = ExtScalarOps.one()
        for b, bit in enumerate(path):
            cb = const_vals[b]
            sel = ext_f.mul_s(sel, cb if bit else ext_f.sub_s((1, 0), cb))
        reps = gate.num_repetitions(geometry)
        gate_acc = ExtScalarOps.zero()
        for inst in range(reps):
            row = _ZRowView(
                wit_vals, const_vals, inst * gate.principal_width,
                inst * gate.witness_width, len(path), Ct,
            )
            dst = TermsCollector()
            gate.evaluate(ExtScalarOps, row, dst)
            if len(dst.terms) != gate.num_terms:
                return False
            for term in dst.terms:
                gate_acc = ext_f.add_s(
                    gate_acc, ext_f.mul_s(term, next(alpha_pows))
                )
        total = ext_f.add_s(total, ext_f.mul_s(sel, gate_acc))

    # copy-permutation terms at z
    z_at_z = ext_from_pair(s2_vals[0], s2_vals[1])
    z_at_zw = ext_from_pair(
        tuple(proof.values_at_z_omega[0]), tuple(proof.values_at_z_omega[1])
    )
    partial_at_z = [
        ext_from_pair(s2_vals[2 + 2 * j], s2_vals[3 + 2 * j])
        for j in range(num_chunks - 1)
    ]
    non_residues = non_residues_for_copy_permutation(Ct)
    chunks = chunk_columns(Ct, geometry.max_allowed_constraint_degree)
    # L_0(z) = (z^n - 1)/(n (z - 1))
    z_pow_n = ext_f.pow_s(z_chal, n)
    zh_at_z = ext_f.sub_s(z_pow_n, ext_f.ONE_S)
    l0_at_z = ext_f.mul_s(
        ext_f.mul_s(zh_at_z, (gl.inv(n), 0)),
        ext_f.inv_s(ext_f.sub_s(z_chal, ext_f.ONE_S)),
    )
    term = ext_f.mul_s(l0_at_z, ext_f.sub_s(z_at_z, ext_f.ONE_S))
    total = ext_f.add_s(total, ext_f.mul_s(term, next(alpha_pows)))
    lhs_seq = partial_at_z + [z_at_zw]
    rhs_seq = [z_at_z] + partial_at_z
    for j, chunk in enumerate(chunks):
        num_p = ext_f.ONE_S
        den_p = ext_f.ONE_S
        for col in chunk:
            w = wit_vals[col]
            kx = ext_f.mul_by_base_s(z_chal, non_residues[col])
            num = ext_f.add_s(ext_f.add_s(w, ext_f.mul_s(beta, kx)), gamma)
            den = ext_f.add_s(
                ext_f.add_s(w, ext_f.mul_s(beta, sigma_vals[col])), gamma
            )
            num_p = ext_f.mul_s(num_p, num)
            den_p = ext_f.mul_s(den_p, den)
        rel = ext_f.sub_s(
            ext_f.mul_s(lhs_seq[j], den_p), ext_f.mul_s(rhs_seq[j], num_p)
        )
        total = ext_f.add_s(total, ext_f.mul_s(rel, next(alpha_pows)))

    # lookup terms at z (A_i·den − 1, B·den_t − M) + the sum check at 0
    if lookups:
        ab_off = 2 * (1 + (num_chunks - 1))
        gpow = ext_f.powers_s(lookup_gamma, wdt + 1)
        if lk_specialized:
            tid_at_z = const_vals[K - 1]
            a_numerator = ext_f.ONE_S
            col_base = Cg
        else:
            # general mode: the table id is the marker row's constant and
            # each A relation is gated by the marker's SELECTOR at z
            mk_gid = next(
                (
                    i for i, g in enumerate(gates)
                    if getattr(g, "is_lookup_marker", False)
                ),
                None,
            )
            if mk_gid is None:
                return False  # general-mode VK but no marker gate supplied
            mk_path = vk.selector_paths[mk_gid]
            tid_at_z = const_vals[len(mk_path)]
            sel_at_z = ext_f.ONE_S
            for bdx, bit in enumerate(mk_path):
                cb = const_vals[bdx]
                sel_at_z = ext_f.mul_s(
                    sel_at_z,
                    cb if bit else ext_f.sub_s((1, 0), cb),
                )
            a_numerator = sel_at_z
            col_base = 0
        for i in range(R):
            a_i = ext_from_pair(
                s2_vals[ab_off + 2 * i], s2_vals[ab_off + 2 * i + 1]
            )
            den = lookup_beta
            for j in range(wdt):
                wv = wit_vals[col_base + i * wdt + j]
                den = ext_f.add_s(den, ext_f.mul_s(gpow[j], wv))
            den = ext_f.add_s(den, ext_f.mul_s(gpow[wdt], tid_at_z))
            rel = ext_f.sub_s(ext_f.mul_s(a_i, den), a_numerator)
            total = ext_f.add_s(total, ext_f.mul_s(rel, next(alpha_pows)))
        b_at_z = ext_from_pair(
            s2_vals[ab_off + 2 * R], s2_vals[ab_off + 2 * R + 1]
        )
        den = lookup_beta
        for j in range(wdt + 1):
            den = ext_f.add_s(den, ext_f.mul_s(gpow[j], table_vals[j]))
        m_at_z = wit_vals[Ct + W]
        rel = ext_f.sub_s(ext_f.mul_s(b_at_z, den), m_at_z)
        total = ext_f.add_s(total, ext_f.mul_s(rel, next(alpha_pows)))
        # sum over H of (sum_i A_i - B) must vanish:  sum_i A_i(0) == B(0)
        a_sum = ext_f.ZERO_S
        for i in range(R):
            a_sum = ext_f.add_s(a_sum, tuple(proof.values_at_0[i]))
        if tuple(a_sum) != tuple(proof.values_at_0[R]):
            return False

    # T(z) from quotient chunks: sum z^{i n} * q_i(z)
    t_at_z = ext_f.ZERO_S
    for i in range(Q):
        q_i = ext_from_pair(q_vals[2 * i], q_vals[2 * i + 1])
        t_at_z = ext_f.add_s(
            t_at_z, ext_f.mul_s(q_i, ext_f.pow_s(z_chal, i * n))
        )
    if total != ext_f.mul_s(t_at_z, zh_at_z):
        return False

    # ---- PoW + queries ----------------------------------------------------
    if not pow_verify(t, vk.pow_bits, proof.pow_challenge):
        return False
    if len(proof.queries) != vk.num_queries:
        return False
    omega = gl.omega(log_n)
    zw = ext_f.mul_by_base_s(z_chal, omega)
    pi_locs = vk.public_input_locations
    bs = BitSource(log_full)
    for q in proof.queries:
        idx = bs.get_index(t, log_full)
        # oracle membership
        if not verify_proof_over_cap(
            q.witness.leaf_values, q.witness.path, proof.witness_cap, idx
        ):
            return False
        if not verify_proof_over_cap(
            q.stage2.leaf_values, q.stage2.path, proof.stage2_cap, idx
        ):
            return False
        if not verify_proof_over_cap(
            q.quotient.leaf_values, q.quotient.path, proof.quotient_cap, idx
        ):
            return False
        if not verify_proof_over_cap(
            q.setup.leaf_values, q.setup.path, vk.setup_merkle_cap, idx
        ):
            return False
        if (
            len(q.witness.leaf_values) != Ct + W + M
            or len(q.setup.leaf_values) != Ct + K + TW
            or len(q.stage2.leaf_values) != S
            or len(q.quotient.leaf_values) != 2 * Q
        ):
            return False
        # recompute the DEEP codeword value h(x) at the queried point
        x = gl.mul(
            gl.MULTIPLICATIVE_GENERATOR, gl.pow_(gl.omega(log_full), _brev(idx, log_full))
        )
        f_all = (
            [ (v, 0) for v in q.witness.leaf_values ]
            + [ (v, 0) for v in q.setup.leaf_values ]
            + [ (v, 0) for v in q.stage2.leaf_values ]
            + [ (v, 0) for v in q.quotient.leaf_values ]
        )
        inv_xz = ext_f.inv_s(ext_f.sub_s((x, 0), z_chal))
        inv_xzw = ext_f.inv_s(ext_f.sub_s((x, 0), zw))
        h = ext_f.ZERO_S
        ch_iter = _powers_iter(deep_ch)
        for i in range(B):
            diff = ext_f.sub_s(f_all[i], vals[i])
            h = ext_f.add_s(
                h, ext_f.mul_s(ext_f.mul_s(diff, inv_xz), next(ch_iter))
            )
        for i in range(2):
            f = (q.stage2.leaf_values[i], 0)
            diff = ext_f.sub_s(f, tuple(proof.values_at_z_omega[i]))
            h = ext_f.add_s(
                h, ext_f.mul_s(ext_f.mul_s(diff, inv_xzw), next(ch_iter))
            )
        if lookups:
            inv_x = gl.inv(x)
            ab_off = 2 * (1 + (num_chunks - 1))
            for i in range(R + 1):
                ch = next(ch_iter)
                f_pair = (
                    q.stage2.leaf_values[ab_off + 2 * i],
                    q.stage2.leaf_values[ab_off + 2 * i + 1],
                )
                diff = ext_f.sub_s(f_pair, tuple(proof.values_at_0[i]))
                h = ext_f.add_s(
                    h, ext_f.mul_s(ext_f.mul_by_base_s(diff, inv_x), ch)
                )
        for k, (col, row) in enumerate(pi_locs):
            ch = next(ch_iter)
            pt = gl.pow_(omega, row)
            diff = gl.sub(q.witness.leaf_values[col], proof.public_inputs[k])
            tb = gl.mul(diff, gl.inv(gl.sub(x, pt)))
            h = ext_f.add_s(h, ext_f.mul_by_base_s(ch, tb))
        # FRI chain (grouped oracles per the folding schedule)
        if len(q.fri) != len(schedule):
            return False
        leaves = []
        fidx = idx
        for r, (k, oq) in enumerate(zip(schedule, q.fri)):
            block = 1 << k
            leaf_idx = fidx >> k
            if len(oq.leaf_values) != 2 * block:
                return False
            if not verify_proof_over_cap(
                oq.leaf_values, oq.path, proof.fri_caps[r], leaf_idx
            ):
                return False
            leaves.append(
                [
                    (oq.leaf_values[2 * j], oq.leaf_values[2 * j + 1])
                    for j in range(block)
                ]
            )
            fidx = leaf_idx
        # base oracle value must equal recomputed h
        if tuple(leaves[0][idx % (1 << schedule[0])]) != tuple(h):
            return False
        if not fri_verify_queries(
            schedule, fri_challenges,
            [tuple(c) for c in proof.final_fri_monomials],
            idx, leaves, log_full,
        ):
            return False
    return True


def _powers_iter(a):
    cur = ext_f.ONE_S
    aa = (int(a[0]), int(a[1]))
    while True:
        yield cur
        cur = ext_f.mul_s(cur, aa)


def _brev(i: int, bits: int) -> int:
    out = 0
    for b in range(bits):
        out |= ((i >> b) & 1) << (bits - 1 - b)
    return out
