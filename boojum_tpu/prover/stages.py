"""Prover stage computations: copy-permutation grand product and the
gate-constraint quotient sweep.

Counterparts: `/root/reference/src/cs/implementations/copy_permutation.rs`
(pointwise rational accumulation :30, shifted grand product :367, partial
products chunked by degree :525, quotient terms :1000) and the general-purpose
gate sweep of `prover.rs:813-1130`.

TPU-first shape: everything is computed on whole (…, n) or (…, lde·n) arrays;
the grand product is ONE `jax.lax.associative_scan` over the row axis (the
scan counterpart of the reference's chunked sequential products), and the gate
sweep evaluates every allowed gate's evaluator over the entire LDE domain at
once, masked by its selector-path polynomial — the "static masked evaluation"
form that suits SIMD/MXU hardware.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..field import gl
from ..field import extension as ext_f
from ..field import goldilocks as gf
from ..ntt import (
    bitreverse_indices,
    get_ntt_context,
    lde_from_monomial,
    monomial_from_values,
    powers_device,
)
from ..cs.field_like import ArrayOps
from ..cs.gates.base import RowView, TermsCollector


def ext_scalar(s):
    return (jnp.uint64(int(s[0])), jnp.uint64(int(s[1])))


def chunk_columns(num_cols: int, max_degree: int):
    """Split copy columns into chunks of size <= max_degree (the relation
    degree cap; reference copy_permutation.rs:525)."""
    cs = max(1, max_degree)
    return [list(range(i, min(i + cs, num_cols))) for i in range(0, num_cols, cs)]


def compute_copy_permutation_stage2(
    copy_vals, sigma_vals, non_residues, beta, gamma, max_degree
):
    """Grand product z and partial products over H.

    copy_vals/sigma_vals: (C, n) device base arrays (natural row order);
    beta/gamma host ext scalars. Returns (z_pair, partial_pairs, chunks)
    where z(w^0)=1 and for the last chunk relation
    z(w*x)·prod_den_last = p_last·prod_num_last holds.
    """
    C, n = copy_vals.shape
    ctx = get_ntt_context(n.bit_length() - 1)
    xs = powers_device(ctx.omega, n)  # w^r natural order
    b = ext_scalar(beta)
    g = ext_scalar(gamma)
    chunks = chunk_columns(C, max_degree)
    ks = [jnp.uint64(k) for k in non_residues]

    def num_den_for_col(j):
        w = copy_vals[j]
        kx = gf.mul(xs, ks[j])
        num = (
            gf.add(gf.add(w, gf.mul(kx, b[0])), g[0]),
            gf.add(gf.mul(kx, b[1]), g[1]),
        )
        s = sigma_vals[j]
        den = (
            gf.add(gf.add(w, gf.mul(s, b[0])), g[0]),
            gf.add(gf.mul(s, b[1]), g[1]),
        )
        return num, den

    chunk_ratios = []
    for chunk in chunks:
        num_p = None
        den_p = None
        for j in chunk:
            num, den = num_den_for_col(j)
            num_p = num if num_p is None else ext_f.mul(num_p, num)
            den_p = den if den_p is None else ext_f.mul(den_p, den)
        ratio = ext_f.mul(num_p, ext_f.batch_inverse(den_p))
        chunk_ratios.append(ratio)

    full_ratio = chunk_ratios[0]
    for r in chunk_ratios[1:]:
        full_ratio = ext_f.mul(full_ratio, r)

    # z = exclusive prefix product of full_ratio along rows
    def emul(a, b):
        return ext_f.mul(a, b)

    incl = jax.lax.associative_scan(emul, full_ratio, axis=-1)
    one = jnp.ones((1,), jnp.uint64)
    zero = jnp.zeros((1,), jnp.uint64)
    z = (
        jnp.concatenate([one, incl[0][:-1]]),
        jnp.concatenate([zero, incl[1][:-1]]),
    )
    # partial products p_j = z * prod_{k<=j} chunk_ratio_k (pointwise row r)
    partials = []
    acc = z
    for r in chunk_ratios[:-1]:
        acc = ext_f.mul(acc, r)
        partials.append(acc)
    return z, partials, chunks


class LdeRowView:
    """RowView over flattened LDE arrays for one gate-instance chunk."""

    def __init__(self, copy_lde_flat, wit_lde_flat, const_lde_flat, var_off, wit_off, const_off):
        self._c = copy_lde_flat
        self._w = wit_lde_flat
        self._k = const_lde_flat
        self._vo = var_off
        self._wo = wit_off
        self._ko = const_off

    def v(self, i):
        return self._c[self._vo + i]

    def w(self, i):
        return self._w[self._wo + i]

    def c(self, i):
        return self._k[self._ko + i]


def selector_poly_lde(const_lde_flat, path):
    """Product over path bits of c_b or (1 - c_b), over the LDE domain."""
    sel = None
    one = jnp.uint64(1)
    for b, bit in enumerate(path):
        col = const_lde_flat[b]
        f = col if bit else gf.sub(jnp.broadcast_to(one, col.shape), col)
        sel = f if sel is None else gf.mul(sel, f)
    return sel  # None = constant 1 (single-gate circuits)


def alpha_powers_iter(alpha):
    """Infinite iterator of host ext powers 1, a, a^2, ..."""
    cur = ext_f.ONE_S
    a = (int(alpha[0]), int(alpha[1]))
    while True:
        yield cur
        cur = ext_f.mul_s(cur, a)


def accumulate_ext(acc, term_base, challenge):
    """acc += challenge * term for base-field term arrays, ext challenge."""
    ch = ext_scalar(challenge)
    t0 = gf.mul(term_base, ch[0])
    t1 = gf.mul(term_base, ch[1])
    if acc is None:
        return (t0, t1)
    return (gf.add(acc[0], t0), gf.add(acc[1], t1))


def accumulate_ext_ext(acc, term_ext, challenge):
    ch = ext_scalar(challenge)
    t = ext_f.mul(term_ext, ch)
    if acc is None:
        return t
    return ext_f.add(acc, t)


def gate_terms_contribution(
    assembly, selector_paths, copy_lde_flat, wit_lde_flat, const_lde_flat,
    selector_depth, alpha_iter, domain_shape,
):
    """Sum over gates/instances/terms of alpha^t * selector_g * term."""
    geometry = assembly.geometry
    acc = None
    for gid, gate in enumerate(assembly.gates):
        if gate.num_terms == 0:
            continue
        path = selector_paths[gid]
        sel = selector_poly_lde(const_lde_flat, path)
        reps = gate.num_repetitions(geometry)
        gate_acc = None
        for inst in range(reps):
            row = LdeRowView(
                copy_lde_flat,
                wit_lde_flat,
                const_lde_flat,
                inst * gate.principal_width,
                inst * gate.witness_width,
                selector_depth,
            )
            dst = TermsCollector()
            gate.evaluate(ArrayOps, row, dst)
            assert len(dst.terms) == gate.num_terms, gate.name
            for term in dst.terms:
                gate_acc = accumulate_ext(gate_acc, term, next(alpha_iter))
        if gate_acc is not None:
            if sel is not None:
                gate_acc = (gf.mul(gate_acc[0], sel), gf.mul(gate_acc[1], sel))
            acc = gate_acc if acc is None else ext_f.add(acc, gate_acc)
    return acc


def aggregate_lookup_columns(cols, table_id_col, gamma, beta):
    """Σ_j γ^j·col_j (+ γ^w·table_id) + β over whole base arrays -> ext pair.

    cols: list of (n,)-or-(N,) base arrays; table_id_col: same-shape base
    array or None; returns the log-derivative denominator before inversion
    (reference lookup_argument_in_ext.rs:424 'aggregated_lookup_columns').
    """
    total = len(cols) + (1 if table_id_col is not None else 0)
    gpow = ext_f.powers_s(gamma, total)
    b = ext_scalar(beta)
    acc0 = jnp.broadcast_to(b[0], cols[0].shape)
    acc1 = jnp.broadcast_to(b[1], cols[0].shape)
    seq = list(cols) + ([table_id_col] if table_id_col is not None else [])
    for j, col in enumerate(seq):
        g0, g1 = jnp.uint64(gpow[j][0]), jnp.uint64(gpow[j][1])
        acc0 = gf.add(acc0, gf.mul(col, g0))
        acc1 = gf.add(acc1, gf.mul(col, g1))
    return (acc0, acc1)


def compute_lookup_polys(
    lookup_cols, table_id_col, table_cols, multiplicities,
    lookup_beta, lookup_gamma, num_repetitions, width,
):
    """A_i and B polys over H (reference compute_lookup_poly_pairs_specialized,
    lookup_argument_in_ext.rs:320).

    lookup_cols: (R*w, n) base device array of the specialized columns;
    table_id_col: (n,) base; table_cols: (w+1, n) stacked tables incl. id;
    multiplicities: (n,). Returns (a_polys list of ext pairs, b_poly ext pair):
      A_i(x) = 1 / (Σ_j γ^j·w_{i,j}(x) + γ^w·table_id(x) + β)
      B(x)   = M(x) / (Σ_j γ^j·t_j(x) + γ^w·t_id(x) + β)
    """
    a_polys = []
    for i in range(num_repetitions):
        cols = [lookup_cols[i * width + j] for j in range(width)]
        den = aggregate_lookup_columns(cols, table_id_col, lookup_gamma, lookup_beta)
        a_polys.append(ext_f.batch_inverse(den))
    t_den = aggregate_lookup_columns(
        [table_cols[j] for j in range(width)], table_cols[width],
        lookup_gamma, lookup_beta,
    )
    t_inv = ext_f.batch_inverse(t_den)
    b_poly = (gf.mul(t_inv[0], multiplicities), gf.mul(t_inv[1], multiplicities))
    return a_polys, b_poly


def lookup_quotient_terms(
    a_ldes, b_lde, lookup_lde_cols, table_id_lde, table_ldes, mult_lde,
    lookup_beta, lookup_gamma, num_repetitions, width, alpha_iter,
):
    """Quotient contributions over the LDE domain (reference
    compute_quotient_terms_for_lookup_specialized,
    lookup_argument_in_ext.rs:949):

      per sub-arg i: A_i(x)·(Σ γ^j·w_{i,j}(x) + γ^w·tid(x) + β) − 1
      for B:         B(x)·(Σ γ^j·t_j(x) + γ^w·t_id(x) + β) − M(x)
    """
    acc = None
    one = jnp.uint64(1)
    for i in range(num_repetitions):
        cols = [lookup_lde_cols[i * width + j] for j in range(width)]
        den = aggregate_lookup_columns(cols, table_id_lde, lookup_gamma, lookup_beta)
        term = ext_f.mul(a_ldes[i], den)
        term = (gf.sub(term[0], jnp.broadcast_to(one, term[0].shape)), term[1])
        acc = accumulate_ext_ext(acc, term, next(alpha_iter))
    t_den = aggregate_lookup_columns(
        [table_ldes[j] for j in range(width)], table_ldes[width],
        lookup_gamma, lookup_beta,
    )
    term = ext_f.mul(b_lde, t_den)
    term = (gf.sub(term[0], mult_lde), term[1])
    acc = accumulate_ext_ext(acc, term, next(alpha_iter))
    return acc


def copy_permutation_quotient_terms(
    z_lde, z_shift_lde, partial_ldes, chunks, copy_lde, sigma_lde,
    non_residues, xs_lde, l0_lde, beta, gamma, alpha_iter,
):
    """Quotient contributions of the copy-permutation argument over the LDE
    domain (reference copy_permutation.rs:1000):

      t0: L_0(x) · (z(x) − 1)
      per chunk j:  lhs_j(x)·prod_den_j(x) − rhs_j(x)·prod_num_j(x)
        where (lhs, rhs) walk z, p_0, …, p_last, z(w·x).
    """
    b = ext_scalar(beta)
    g = ext_scalar(gamma)
    one = jnp.uint64(1)
    acc = None
    # L_0(x)(z(x)-1)
    zm1 = (gf.sub(z_lde[0], jnp.broadcast_to(one, z_lde[0].shape)), z_lde[1])
    t0 = (gf.mul(zm1[0], l0_lde), gf.mul(zm1[1], l0_lde))
    acc = accumulate_ext_ext(acc, t0, next(alpha_iter))
    lhs_seq = partial_ldes + [z_shift_lde]
    rhs_seq = [z_lde] + partial_ldes
    ks = non_residues
    for j, chunk in enumerate(chunks):
        num_p = None
        den_p = None
        for col in chunk:
            w = copy_lde[col]
            kx = gf.mul(xs_lde, jnp.uint64(ks[col]))
            num = (
                gf.add(gf.add(w, gf.mul(kx, b[0])), g[0]),
                gf.add(gf.mul(kx, b[1]), g[1]),
            )
            s = sigma_lde[col]
            den = (
                gf.add(gf.add(w, gf.mul(s, b[0])), g[0]),
                gf.add(gf.mul(s, b[1]), g[1]),
            )
            num_p = num if num_p is None else ext_f.mul(num_p, num)
            den_p = den if den_p is None else ext_f.mul(den_p, den)
        term = ext_f.sub(
            ext_f.mul(lhs_seq[j], den_p), ext_f.mul(rhs_seq[j], num_p)
        )
        acc = accumulate_ext_ext(acc, term, next(alpha_iter))
    return acc
