"""Prover stage computations: copy-permutation grand product and the
gate-constraint quotient sweep.

Counterparts: `/root/reference/src/cs/implementations/copy_permutation.rs`
(pointwise rational accumulation :30, shifted grand product :367, partial
products chunked by degree :525, quotient terms :1000) and the general-purpose
gate sweep of `prover.rs:813-1130`.

TPU-first shape: everything is computed on whole (…, n) or (…, lde·n) arrays;
the grand product is ONE `jax.lax.associative_scan` over the row axis (the
scan counterpart of the reference's chunked sequential products), and the gate
sweep evaluates every allowed gate's evaluator over the entire LDE domain at
once, masked by its selector-path polynomial — the "static masked evaluation"
form that suits SIMD/MXU hardware.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..field import gl
from ..field import extension as ext_f
from ..field import goldilocks as gf
from ..ntt import (
    bitreverse_indices,
    get_ntt_context,
    lde_from_monomial,
    monomial_from_values,
    powers_device,
)
from ..cs.field_like import ArrayOps
from ..cs.gates.base import RowView, TermsCollector
from ..utils import metrics as _metrics
from ..utils.spans import span as _span


def ext_scalar(s):
    """Host (int, int) ext scalar -> pair of u64 array scalars; jax-array
    components (fused-round tracing) pass through unchanged."""
    a, b = s[0], s[1]
    if isinstance(a, jax.Array):
        return (a, b)
    return (jnp.uint64(int(a)), jnp.uint64(int(b)))


def chunk_columns(num_cols: int, max_degree: int):
    """Split copy columns into chunks of size <= max_degree (the relation
    degree cap; reference copy_permutation.rs:525)."""
    cs = max(1, max_degree)
    return [list(range(i, min(i + cs, num_cols))) for i in range(0, num_cols, cs)]


@partial(jax.jit, static_argnums=(6,))
def _all_chunk_num_den(copy_vals, sigma_vals, ks, xs, b, g, chunks):
    """Per-chunk products of numerator (w + β·k·x + γ) and denominator
    (w + β·σ + γ), ALL chunks in one dispatch -> (num_chunks, n) stacked
    ext pairs.

    The loop over the uniform-width chunk prefix runs under `lax.scan`, so
    the traced module holds ONE chunk's field ops instead of every chunk's
    (the fully unrolled form's remote compile was 251 s on the 2^16 SHA
    geometry — BASELINE.md round 4); a trailing ragged chunk unrolls into
    the same graph. chunk_columns' chunks are contiguous column ranges, so
    the blocked view is a reshape, never a gather. The denominator
    inversion happens OUTSIDE this jit: batch_inverse must stay a
    top-level jit boundary — inlining its Fermat-chain into larger
    XLA:CPU modules has produced never-terminating executables on this
    backend (miscompile class, not a slowness issue)."""
    n = copy_vals.shape[-1]
    flat = [col for c in chunks for col in c]
    assert flat == list(range(len(flat))), chunks
    w = len(chunks[0])
    K_full = sum(1 for c in chunks if len(c) == w)
    assert all(len(c) == w for c in chunks[:K_full]), chunks
    assert len(chunks) - K_full <= 1, chunks

    def _prod_terms(cv, sv, kv):
        # cv/sv: (w', n) column blocks; kv: (w',) non-residues
        num_p = den_p = None
        for j in range(cv.shape[0]):
            wcol = cv[j]
            kx = gf.mul(xs, kv[j])
            num = (
                gf.add(gf.add(wcol, gf.mul(kx, b[0])), g[0]),
                gf.add(gf.mul(kx, b[1]), g[1]),
            )
            s = sv[j]
            den = (
                gf.add(gf.add(wcol, gf.mul(s, b[0])), g[0]),
                gf.add(gf.mul(s, b[1]), g[1]),
            )
            num_p = num if num_p is None else ext_f.mul(num_p, num)
            den_p = den if den_p is None else ext_f.mul(den_p, den)
        return num_p, den_p

    def body(carry, blk):
        num_p, den_p = _prod_terms(*blk)
        return carry, (num_p[0], num_p[1], den_p[0], den_p[1])

    Cw = K_full * w
    _, (n0, n1, d0, d1) = jax.lax.scan(
        body,
        None,
        (
            copy_vals[:Cw].reshape(K_full, w, n),
            sigma_vals[:Cw].reshape(K_full, w, n),
            ks[:Cw].reshape(K_full, w),
        ),
    )
    if len(chunks) > K_full:
        num_p, den_p = _prod_terms(copy_vals[Cw:], sigma_vals[Cw:], ks[Cw:])
        n0 = jnp.concatenate([n0, num_p[0][None]])
        n1 = jnp.concatenate([n1, num_p[1][None]])
        d0 = jnp.concatenate([d0, den_p[0][None]])
        d1 = jnp.concatenate([d1, den_p[1][None]])
    return (n0, n1), (d0, d1)


@jax.jit
def _z_and_partials(num_all, den_inv_all):
    """Chunk ratios -> full-row ratio -> exclusive prefix product z ->
    cumulative partial products, one compiled graph. Inputs are
    (num_chunks, n) stacked ext pairs (den already inverted)."""
    K = num_all[0].shape[0]
    ratios = ext_f.mul(num_all, den_inv_all)
    full = (ratios[0][0], ratios[1][0])
    for j in range(1, K):
        full = ext_f.mul(full, (ratios[0][j], ratios[1][j]))
    incl = _ext_prefix_prod(full)
    one = jnp.ones((1,), jnp.uint64)
    zero = jnp.zeros((1,), jnp.uint64)
    z = (
        jnp.concatenate([one, incl[0][:-1]]),
        jnp.concatenate([zero, incl[1][:-1]]),
    )
    parts0, parts1 = [], []
    acc = z
    for j in range(K - 1):
        acc = ext_f.mul(acc, (ratios[0][j], ratios[1][j]))
        parts0.append(acc[0])
        parts1.append(acc[1])
    if parts0:
        return z, (jnp.stack(parts0), jnp.stack(parts1))
    return z, (jnp.zeros((0,) + z[0].shape, jnp.uint64),) * 2


def _ext_prefix_prod(a):
    """Inclusive ext prefix product along the last axis (log-doubling XLA;
    see goldilocks.batch_inverse for why the Pallas block-scan was
    retired)."""
    return _ext_prefix_prod_xla(a)


@jax.jit
def _ext_prefix_prod_xla(a):
    """Inclusive ext prefix product along the last axis (log-doubling; same
    rationale as gf.prefix_product — associative_scan's graph explodes XLA
    compile time for wide combine fns)."""
    n = a[0].shape[-1]
    shift = 1
    while shift < n:
        shifted = (
            jnp.concatenate([jnp.ones((shift,), jnp.uint64), a[0][:-shift]]),
            jnp.concatenate([jnp.zeros((shift,), jnp.uint64), a[1][:-shift]]),
        )
        a = ext_f.mul(a, shifted)
        shift *= 2
    return a


def compute_copy_permutation_stage2(
    copy_vals, sigma_vals, non_residues, beta, gamma, max_degree
):
    """Grand product z and partial products over H.

    copy_vals/sigma_vals: (C, n) device base arrays (natural row order);
    beta/gamma host ext scalars. Returns (z_pair, partial_pairs, chunks)
    where z(w^0)=1 and for the last chunk relation
    z(w*x)·prod_den_last = p_last·prod_num_last holds.

    Deliberately NOT one fused jit: XLA:CPU optimization time is superlinear
    in module size, so this sequences a handful of small jitted kernels
    (per-chunk ratio, batch inverse, prefix product) instead.
    """
    C, n = copy_vals.shape
    ctx = get_ntt_context(n.bit_length() - 1)
    xs = powers_device(ctx.omega, n)  # w^r natural order
    b = ext_scalar(beta)
    g = ext_scalar(gamma)
    chunks = chunk_columns(C, max_degree)
    # a real h2d upload seam (the fused path's equivalent rides
    # prover._dev_cached): keep the transfer ledger complete
    ks = _metrics.count_upload(
        jnp.asarray(np.array([int(k) for k in non_residues], dtype=np.uint64))
    )

    _metrics.count("stage2.chunk_scans")
    with _span("stage2_grand_product"):
        num_all, den_all = _all_chunk_num_den(
            copy_vals, sigma_vals, ks, xs, b, g,
            tuple(tuple(c) for c in chunks),
        )
        # ONE stacked inversion for every chunk denominator
        den_inv_all = ext_f.batch_inverse(den_all)
        z, partials_stacked = _z_and_partials(num_all, den_inv_all)
    partials = [
        (partials_stacked[0][j], partials_stacked[1][j])
        for j in range(len(chunks) - 1)
    ]
    return z, partials, chunks


class LdeRowView:
    """RowView over flattened LDE arrays for one gate-instance chunk."""

    def __init__(self, copy_lde_flat, wit_lde_flat, const_lde_flat, var_off, wit_off, const_off):
        self._c = copy_lde_flat
        self._w = wit_lde_flat
        self._k = const_lde_flat
        self._vo = var_off
        self._wo = wit_off
        self._ko = const_off

    def v(self, i):
        return self._c[self._vo + i]

    def w(self, i):
        return self._w[self._wo + i]

    def c(self, i):
        return self._k[self._ko + i]


def selector_poly_lde(const_lde_flat, path):
    """Product over path bits of c_b or (1 - c_b), over the LDE domain."""
    sel = None
    one = jnp.uint64(1)
    for b, bit in enumerate(path):
        col = const_lde_flat[b]
        f = col if bit else gf.sub(jnp.broadcast_to(one, col.shape), col)
        sel = f if sel is None else gf.mul(sel, f)
    return sel  # None = constant 1 (single-gate circuits)


class AlphaPows:
    """Challenge-power supply for the quotient sweep: a device array of ext
    powers consumed sequentially (so jitted stages take them as array
    arguments and new challenges never retrace)."""

    def __init__(self, alpha, count: int):
        from ..ntt import ext_powers_device

        cap = 1
        while cap < max(count, 1):
            cap *= 2
        self.p0, self.p1 = ext_powers_device(alpha, cap)
        self.count = count
        self.cursor = 0

    @classmethod
    def from_arrays(cls, p0, p1, count: int) -> "AlphaPows":
        """Wrap an existing device power table (fused-round tracing: the
        table is built once outside and passed as an array argument)."""
        self = cls.__new__(cls)
        self.p0, self.p1 = p0, p1
        self.count = count
        self.cursor = 0
        return self

    def take(self, k: int):
        """(k,)-shaped ext power pair slice. Over-consumption is a prover
        term-count bug; fail loudly (a silent short slice would corrupt the
        challenge combination into an invalid proof)."""
        assert self.cursor + k <= self.count, (
            f"AlphaPows over-consumed: {self.cursor}+{k} > {self.count}"
        )
        s = slice(self.cursor, self.cursor + k)
        self.cursor += k
        return (self.p0[s], self.p1[s])


def accumulate_ext(acc, term_base, ch):
    """acc += ch * term for base-field term arrays, ext array scalar ch."""
    t0 = gf.mul(term_base, ch[0])
    t1 = gf.mul(term_base, ch[1])
    if acc is None:
        return (t0, t1)
    return (gf.add(acc[0], t0), gf.add(acc[1], t1))


def accumulate_ext_ext(acc, term_ext, ch):
    t = ext_f.mul(term_ext, ch)
    if acc is None:
        return t
    return ext_f.add(acc, t)


def num_gate_sweep_terms(assembly) -> int:
    return sum(
        g.num_repetitions(assembly.geometry) * g.num_terms
        for g in assembly.gates
        if g.num_terms
    )


def gate_terms_contribution(
    assembly, selector_paths, copy_lde_flat, wit_lde_flat, const_lde_flat,
    alpha_pows: AlphaPows,
):
    """Sum over gates/instances/terms of alpha^t * selector_g * term.

    One jitted graph per assembly structure (cached on the assembly object);
    the trace columns and alpha powers are array arguments.
    """
    total = num_gate_sweep_terms(assembly)
    if total == 0:
        return None
    a0, a1 = alpha_pows.take(total)
    fn = getattr(assembly, "_gate_sweep_jit", None)
    if fn is None:
        fn = _build_gate_sweep(
            tuple(assembly.gates), tuple(tuple(p) for p in selector_paths),
            assembly.geometry,
        )
        assembly._gate_sweep_jit = fn
    return fn(copy_lde_flat, wit_lde_flat, const_lde_flat, a0, a1)


def gate_sweep_plan(gates, selector_paths, geometry):
    """Static per-gate sweep schedule shared by the u64 sweep trace and the
    limb-domain Pallas kernel builder (prover/pallas_sweep.py): one
    (gate, selector_path, repetitions, packed_program) tuple per gate with
    quotient terms, in gate order — both backends MUST consume terms (and
    therefore alpha powers) in exactly this order or challenges desync."""
    from ..cs.gate_capture import packed_program_for

    plan = []
    for gid, gate in enumerate(gates):
        if gate.num_terms == 0:
            continue
        plan.append(
            (
                gate,
                tuple(selector_paths[gid]),
                gate.num_repetitions(geometry),
                packed_program_for(gate),
            )
        )
    return plan


def _build_gate_sweep(gates, selector_paths, geometry):
    from ..cs.gate_capture import packed_program_for, scan_evaluate

    _metrics.count("gate_sweep.builds")

    def core(copy_lde_flat, wit_lde_flat, const_lde_flat, a0, a1):
        t = 0
        acc = None
        for gid, gate in enumerate(gates):
            if gate.num_terms == 0:
                continue
            sel = selector_poly_lde(const_lde_flat, selector_paths[gid])
            reps = gate.num_repetitions(geometry)
            # permutation-sized gate programs replay under ONE lax.scan
            # (constant graph size) instead of unrolling thousands of field
            # ops into the trace — the recursion circuit's flattened
            # Poseidon2 gate made the unrolled sweep uncompilable
            packed = packed_program_for(gate)
            gate_acc = None
            for inst in range(reps):
                row = LdeRowView(
                    copy_lde_flat,
                    wit_lde_flat,
                    const_lde_flat,
                    inst * gate.principal_width,
                    inst * gate.witness_width,
                    # variable-depth selectors: a gate's constants start
                    # right after ITS OWN path bits
                    len(selector_paths[gid]),
                )
                if packed is not None:
                    terms = scan_evaluate(packed, row)
                else:
                    dst = TermsCollector()
                    gate.evaluate(ArrayOps, row, dst)
                    terms = dst.terms
                assert len(terms) == gate.num_terms, gate.name
                for term in terms:
                    gate_acc = accumulate_ext(gate_acc, term, (a0[t], a1[t]))
                    t += 1
            if gate_acc is not None:
                if sel is not None:
                    gate_acc = (
                        gf.mul(gate_acc[0], sel), gf.mul(gate_acc[1], sel)
                    )
                acc = gate_acc if acc is None else ext_f.add(acc, gate_acc)
        return acc

    return jax.jit(core)


def _ext_powers_traced(g, count: int):
    """[1, g, ..., g^(count-1)] as host-loop of traced ext scalar muls."""
    pows = [(jnp.uint64(1), jnp.uint64(0))]
    for _ in range(count - 1):
        pows.append(ext_f.mul(pows[-1], g))
    return pows


def aggregate_lookup_columns(cols, table_id_col, gpow, beta):
    """Σ_j γ^j·col_j (+ γ^w·table_id) + β over whole base arrays -> ext pair.

    cols: list of (n,)-or-(N,) base arrays; table_id_col: same-shape base
    array or None; gpow: list of ext array scalars [1, γ, γ², …]; beta: ext
    array scalar. Returns the log-derivative denominator before inversion
    (reference lookup_argument_in_ext.rs:424 'aggregated_lookup_columns').
    """
    acc0 = jnp.broadcast_to(beta[0], cols[0].shape)
    acc1 = jnp.broadcast_to(beta[1], cols[0].shape)
    seq = list(cols) + ([table_id_col] if table_id_col is not None else [])
    for j, col in enumerate(seq):
        acc0 = gf.add(acc0, gf.mul(col, gpow[j][0]))
        acc1 = gf.add(acc1, gf.mul(col, gpow[j][1]))
    return (acc0, acc1)


def compute_lookup_polys(
    lookup_cols, table_id_col, table_cols, multiplicities,
    lookup_beta, lookup_gamma, num_repetitions, width,
):
    """A_i and B polys over H (reference compute_lookup_poly_pairs_specialized,
    lookup_argument_in_ext.rs:320).

    lookup_cols: (R*w, n) base device array of the specialized columns;
    table_id_col: (n,) base; table_cols: (w+1, n) stacked tables incl. id;
    multiplicities: (n,). Returns (a_polys list of ext pairs, b_poly ext pair):
      A_i(x) = 1 / (Σ_j γ^j·w_{i,j}(x) + γ^w·table_id(x) + β)
      B(x)   = M(x) / (Σ_j γ^j·t_j(x) + γ^w·t_id(x) + β)
    """
    b = ext_scalar(lookup_beta)
    g = ext_scalar(lookup_gamma)
    R = int(num_repetitions)
    _metrics.count("stage2.lookup_denominator_builds")
    dens = _lookup_denominators(
        lookup_cols, table_id_col, table_cols, b, g, R, int(width),
    )
    # ONE stacked inversion for all R+1 denominators (batch_inverse stays a
    # top-level jit boundary; see _all_chunk_num_den)
    inv = ext_f.batch_inverse(dens)
    a_polys = [(inv[0][i], inv[1][i]) for i in range(R)]
    t_inv = (inv[0][R], inv[1][R])
    b_poly = (gf.mul(t_inv[0], multiplicities), gf.mul(t_inv[1], multiplicities))
    return a_polys, b_poly


@partial(jax.jit, static_argnums=(5, 6))
def _lookup_denominators(
    lookup_cols, table_id_col, table_cols, b, g, num_repetitions, width
):
    """(R+1, n) stacked ext pairs: the R sub-argument denominators plus the
    table denominator, ready for one batched inversion."""
    gpow = _ext_powers_traced(g, width + 1)
    dens = []
    for i in range(num_repetitions):
        cols = [lookup_cols[i * width + j] for j in range(width)]
        dens.append(aggregate_lookup_columns(cols, table_id_col, gpow, b))
    dens.append(
        aggregate_lookup_columns(
            [table_cols[j] for j in range(width)], table_cols[width], gpow, b
        )
    )
    return (
        jnp.stack([d[0] for d in dens]),
        jnp.stack([d[1] for d in dens]),
    )


def compute_lookup_polys_general(
    gen_cols, tid_col, table_cols, multiplicities, sel_h,
    lookup_beta, lookup_gamma, num_subargs, width,
):
    """A_i and B polys over H for the GENERAL-PURPOSE-columns mode
    (reference lookup_argument.rs / lookup_placement.rs:21): sub-arguments
    tile the general copy columns, the table id is the marker row's gate
    constant column, and A_i = selector(x)/agg_i(x) — zero off the marker
    rows, where agg_i may be arbitrary (Fermat inversion maps 0 to 0)."""
    b = ext_scalar(lookup_beta)
    g = ext_scalar(lookup_gamma)
    R = int(num_subargs)
    dens = _lookup_denominators(
        gen_cols, tid_col, table_cols, b, g, R, int(width),
    )
    inv = ext_f.batch_inverse(dens)
    a_polys = [
        (gf.mul(inv[0][i], sel_h), gf.mul(inv[1][i], sel_h))
        for i in range(R)
    ]
    t_inv = (inv[0][R], inv[1][R])
    b_poly = (
        gf.mul(t_inv[0], multiplicities),
        gf.mul(t_inv[1], multiplicities),
    )
    return a_polys, b_poly


def lookup_quotient_terms_general(
    a_ldes, b_lde, gen_lde_cols, tid_lde, table_ldes, mult_lde, sel_lde,
    lookup_beta, lookup_gamma, num_subargs, width, alpha_pows: AlphaPows,
):
    """General-mode quotient contributions: per sub-arg
    A_i(x)·agg_i(x) − selector(x); for B: B(x)·t_agg(x) − M(x)
    (reference lookup_argument.rs quotient terms over general columns)."""
    a0, a1 = alpha_pows.take(num_subargs + 1)
    return _lookup_quotient_core_general(
        a_ldes, b_lde, gen_lde_cols, tid_lde, table_ldes, mult_lde, sel_lde,
        ext_scalar(lookup_beta), ext_scalar(lookup_gamma), a0, a1,
        int(num_subargs), int(width),
    )


@partial(jax.jit, static_argnums=(11, 12))
def _lookup_quotient_core_general(
    a_ldes, b_lde, gen_lde_cols, tid_lde, table_ldes, mult_lde, sel_lde,
    b, g, a0, a1, num_subargs, width,
):
    gpow = _ext_powers_traced(g, width + 1)
    acc = None
    for i in range(num_subargs):
        cols = [gen_lde_cols[i * width + j] for j in range(width)]
        den = aggregate_lookup_columns(cols, tid_lde, gpow, b)
        term = ext_f.mul(a_ldes[i], den)
        term = (gf.sub(term[0], sel_lde), term[1])
        acc = accumulate_ext_ext(acc, term, (a0[i], a1[i]))
    t_den = aggregate_lookup_columns(
        [table_ldes[j] for j in range(width)], table_ldes[width], gpow, b
    )
    term = ext_f.mul(b_lde, t_den)
    term = (gf.sub(term[0], mult_lde), term[1])
    acc = accumulate_ext_ext(
        acc, term, (a0[num_subargs], a1[num_subargs])
    )
    return acc


def lookup_quotient_terms(
    a_ldes, b_lde, lookup_lde_cols, table_id_lde, table_ldes, mult_lde,
    lookup_beta, lookup_gamma, num_repetitions, width, alpha_pows: AlphaPows,
):
    """Quotient contributions over the LDE domain (reference
    compute_quotient_terms_for_lookup_specialized,
    lookup_argument_in_ext.rs:949):

      per sub-arg i: A_i(x)·(Σ γ^j·w_{i,j}(x) + γ^w·tid(x) + β) − 1
      for B:         B(x)·(Σ γ^j·t_j(x) + γ^w·t_id(x) + β) − M(x)
    """
    a0, a1 = alpha_pows.take(num_repetitions + 1)
    return _lookup_quotient_core(
        a_ldes, b_lde, lookup_lde_cols, table_id_lde, table_ldes, mult_lde,
        ext_scalar(lookup_beta), ext_scalar(lookup_gamma), a0, a1,
        int(num_repetitions), int(width),
    )


@partial(jax.jit, static_argnums=(10, 11))
def _lookup_quotient_core(
    a_ldes, b_lde, lookup_lde_cols, table_id_lde, table_ldes, mult_lde,
    b, g, a0, a1, num_repetitions, width,
):
    gpow = _ext_powers_traced(g, width + 1)
    acc = None
    one = jnp.uint64(1)
    for i in range(num_repetitions):
        cols = [lookup_lde_cols[i * width + j] for j in range(width)]
        den = aggregate_lookup_columns(cols, table_id_lde, gpow, b)
        term = ext_f.mul(a_ldes[i], den)
        term = (gf.sub(term[0], jnp.broadcast_to(one, term[0].shape)), term[1])
        acc = accumulate_ext_ext(acc, term, (a0[i], a1[i]))
    t_den = aggregate_lookup_columns(
        [table_ldes[j] for j in range(width)], table_ldes[width], gpow, b
    )
    term = ext_f.mul(b_lde, t_den)
    term = (gf.sub(term[0], mult_lde), term[1])
    acc = accumulate_ext_ext(
        acc, term, (a0[num_repetitions], a1[num_repetitions])
    )
    return acc


def copy_permutation_quotient_terms(
    z_lde, z_shift_lde, partial_ldes, chunks, copy_lde, sigma_lde,
    non_residues, xs_lde, l0_lde, beta, gamma, alpha_pows: AlphaPows,
):
    """Quotient contributions of the copy-permutation argument over the LDE
    domain (reference copy_permutation.rs:1000):

      t0: L_0(x) · (z(x) − 1)
      per chunk j:  lhs_j(x)·prod_den_j(x) − rhs_j(x)·prod_num_j(x)
        where (lhs, rhs) walk z, p_0, …, p_last, z(w·x).
    """
    a0, a1 = alpha_pows.take(1 + len(chunks))
    return _cp_quotient_core(
        z_lde, z_shift_lde, partial_ldes, copy_lde, sigma_lde, xs_lde,
        l0_lde, ext_scalar(beta), ext_scalar(gamma), a0, a1,
        tuple(tuple(c) for c in chunks),
        tuple(int(k) for k in non_residues),
    )


@partial(jax.jit, static_argnums=(11, 12))
def _cp_quotient_core(
    z_lde, z_shift_lde, partial_ldes, copy_lde, sigma_lde, xs_lde, l0_lde,
    b, g, a0, a1, chunks, non_residues,
):
    one = jnp.uint64(1)
    acc = None
    # L_0(x)(z(x)-1)
    zm1 = (gf.sub(z_lde[0], jnp.broadcast_to(one, z_lde[0].shape)), z_lde[1])
    t0 = (gf.mul(zm1[0], l0_lde), gf.mul(zm1[1], l0_lde))
    acc = accumulate_ext_ext(acc, t0, (a0[0], a1[0]))
    lhs_seq = list(partial_ldes) + [z_shift_lde]
    rhs_seq = [z_lde] + list(partial_ldes)
    ks = non_residues
    for j, chunk in enumerate(chunks):
        num_p = None
        den_p = None
        for col in chunk:
            w = copy_lde[col]
            kx = gf.mul(xs_lde, jnp.uint64(ks[col]))
            num = (
                gf.add(gf.add(w, gf.mul(kx, b[0])), g[0]),
                gf.add(gf.mul(kx, b[1]), g[1]),
            )
            s = sigma_lde[col]
            den = (
                gf.add(gf.add(w, gf.mul(s, b[0])), g[0]),
                gf.add(gf.mul(s, b[1]), g[1]),
            )
            num_p = num if num_p is None else ext_f.mul(num_p, num)
            den_p = den if den_p is None else ext_f.mul(den_p, den)
        term = ext_f.sub(
            ext_f.mul(lhs_seq[j], den_p), ext_f.mul(rhs_seq[j], num_p)
        )
        acc = accumulate_ext_ext(acc, term, (a0[1 + j], a1[1 + j]))
    return acc
