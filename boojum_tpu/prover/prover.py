"""The 5-round IOP prover (reference `prove_cpu_basic`, prover.rs:153).

Round structure (transcript order is the protocol; the verifier replays it):
  0. absorb setup cap + public inputs
  1. commit witness columns (monomial -> coset LDE -> Merkle) ... draw beta,
     gamma (+ lookup beta', gamma' when lookups are on)
  2. commit stage-2 (copy-permutation z + partial products, lookup A_i/B)
     ... draw alpha
  3. commit quotient chunks                                   ... draw z
  4. absorb evaluations at z (z*omega for the grand product; 0 for the
     lookup sum polys)                                        ... draw DEEP
  5. DEEP quotening -> FRI fold rounds -> queries

Witness oracle column order: [general copy | lookup copy | witness |
multiplicities]; setup oracle: [sigma (all copy cols) | constants (+table-id)
| stacked table columns]; stage-2 oracle: [z | partials | A_i | B], every ext
poly as its (c0, c1) base column pair.

Every polynomial op in rounds 1-3 and 5 is a whole-array device computation;
the host only sequences rounds, runs the transcript, and gathers query paths.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..field import gl
from ..field import extension as ext_f
from ..field import goldilocks as gf
from ..merkle import MerkleTreeWithCap
from ..ntt import (
    bitreverse_indices,
    ext_powers_device,
    eval_monomial_at_ext_point,
    distribute_powers,
    fft_natural_to_bitreversed,
    lde_scale_rows,
    get_ntt_context,
    ifft_bitreversed_to_natural,
    lde_from_monomial,
    monomial_from_values,
    powers_device,
)
from ..transcript import BitSource, make_transcript
from .config import ProofConfig
from .fri import fri_prove
from .pow import pow_grind
from .proof import OracleQuery, Proof, SingleRoundQueries
from ..utils import metrics as _metrics
from ..utils import transfer as _transfer
from ..utils.report import checkpoint as _checkpoint
from ..utils.spans import span as _span
from ..utils.spans import sync_point as _sync_point


class _StageClock:
    """Sequential stage spans with guaranteed cleanup: prove() wraps its
    body in try/finally so an exception mid-stage still closes the open
    span (incl. any jax.profiler annotation), recording the partial stage
    with an `error` field instead of dropping it. Each stage start also
    takes a metrics boundary snapshot (live-buffer census + device memory
    high water) when a registry is installed."""

    def __init__(self):
        self._cm = None

    def start(self, name):
        self.stop()
        _metrics.stage_boundary(name)
        import os

        if os.environ.get("BOOJUM_TPU_MEMLOG"):
            import sys

            census = _metrics.live_buffer_census()
            if census is not None:
                num, total = census
                print(
                    f"[boojum_tpu mem] before {name}: "
                    f"{total / 2**30:.2f} GiB ({num} arrays)",
                    file=sys.stderr,
                    flush=True,
                )
        self._cm = _span(name, stage=True)
        self._cm.__enter__()

    def stop(self, error: BaseException | None = None):
        if self._cm is None:
            return
        cm, self._cm = self._cm, None
        if error is None:
            cm.__exit__(None, None, None)
            return
        try:
            cm.__exit__(type(error), error, error.__traceback__)
        except BaseException:
            pass  # span recorded the error; the caller re-raises it
from .streaming import (
    MonomialSource,
    deep_source_blocks,
    use_streamed_lde,
)
from .stages import (
    AlphaPows,
    chunk_columns,
    compute_copy_permutation_stage2,
    compute_lookup_polys,
    copy_permutation_quotient_terms,
    gate_terms_contribution,
    lookup_quotient_terms,
    num_gate_sweep_terms,
)


def _modsum_axis0(a):
    """Modular sum along axis 0 (the ntt log-depth fold, axis-moved)."""
    from ..ntt.ntt import _modsum

    return _modsum(jnp.moveaxis(a, 0, -1))


@jax.jit
def _deep_block(block_lde, c0s, c1s):
    return (
        _modsum_axis0(gf.mul(block_lde, c0s[:, None])),
        _modsum_axis0(gf.mul(block_lde, c1s[:, None])),
    )


@jax.jit
def _deep_combine(t0, t1, y0s, y1s, c0s, c1s, inv_xz):
    s = ext_f.mul((c0s, c1s), (y0s, y1s))
    num = (gf.sub(t0, _modsum_axis0(s[0])), gf.sub(t1, _modsum_axis0(s[1])))
    return ext_f.mul(num, inv_xz)


_DEEP_BLOCK_BUDGET = 128 << 20  # bytes of columns per contraction block


def _deep_main_sum(lde_sources, y0s, y1s, c0s, c1s, inv_xz):
    """Σ_i ch_i·(f_i − y_i)/(x − z) over all opened columns.

    `lde_sources` mixes (B_k, N) arrays and MonomialSource oracles consumed
    in order (witness, setup, stage-2, quotient) — iterating blocks avoids
    materializing the multi-GB concatenation, and MonomialSource blocks
    regenerate streamed oracles from monomials on the fly. One batched
    contraction per column BLOCK: Σ ch_i·f_i is two base-field log-tree
    reductions (fully parallel on the VPU; the sequential lax.scan this
    replaced serialized B device steps and dominated round 5)."""
    t0 = None
    t1 = None
    for blk, off in deep_source_blocks(lde_sources, _DEEP_BLOCK_BUDGET):
        _metrics.count("deep.blocks")
        j = off + blk.shape[0]
        b0, b1 = _deep_block(blk, c0s[off:j], c1s[off:j])
        t0 = b0 if t0 is None else gf.add(t0, b0)
        t1 = b1 if t1 is None else gf.add(t1, b1)
    return _deep_combine(t0, t1, y0s, y1s, c0s, c1s, inv_xz)


def _commit_columns(lde, cap_size):
    """lde: (B, L, n) -> Merkle tree over (L*n, B) leaves.

    Under an active prover mesh the transpose is the col->row layout pivot:
    leaves re-shard across both mesh axes (one all-to-all over ICI) so leaf
    hashing is row-parallel."""
    from ..parallel.sharding import shard_leaves

    B = lde.shape[0]
    leaves = shard_leaves(lde.reshape(B, -1).T)
    return MerkleTreeWithCap(leaves, cap_size), leaves


from functools import lru_cache


def clear_domain_caches():
    """Drop the cached per-geometry device tables (challenge-independent
    LDE-domain constants). They pin a few full-domain buffers per geometry;
    long-lived processes switching between large geometries can reclaim the
    HBM here."""
    from .fri import fold_challenge_tables

    for fn in (
        _domain_xs_brev,
        _l0_brev,
        _inv_xs_brev,
        _vanishing_inv_brev,
        fold_challenge_tables,
    ):
        fn.cache_clear()
    from .resident import clear_plane_caches

    clear_plane_caches()


@lru_cache(maxsize=4)
def _domain_xs_brev(log_n, lde_factor):
    """Full LDE domain values g·w_N^i in bit-reversed enumeration (cached:
    identical across proves of the same geometry)."""
    log_full = log_n + (lde_factor.bit_length() - 1)
    N = 1 << log_full
    xs = powers_device(gl.omega(log_full), N)
    xs = gf.mul(xs, jnp.uint64(gl.MULTIPLICATIVE_GENERATOR))
    return xs[jnp.asarray(bitreverse_indices(log_full))]


@lru_cache(maxsize=4)
def _l0_brev(log_n, lde_factor):
    """L_0(x) = (x^n - 1) / (n (x - 1)) over the LDE domain, brev order
    (cached: challenge-independent)."""
    n = 1 << log_n
    log_full = log_n + (lde_factor.bit_length() - 1)
    xs_lde = _domain_xs_brev(log_n, lde_factor)
    zh = gf.sub(
        jnp.repeat(
            jnp.asarray(
                np.array(
                    [
                        gl.pow_(
                            gl.mul(
                                gl.MULTIPLICATIVE_GENERATOR,
                                gl.pow_(gl.omega(log_full), int(jb)),
                            ),
                            n,
                        )
                        for jb in bitreverse_indices(lde_factor.bit_length() - 1)
                    ],
                    dtype=np.uint64,
                )
            ),
            n,
        ),
        jnp.uint64(1),
    )
    return gf.mul(
        gf.mul(zh, jnp.uint64(gl.inv(n))),
        gf.batch_inverse(gf.sub(xs_lde, jnp.uint64(1))),
    )


# input bytes per chunk of the in-graph coset evaluation: at 2^20 rows a
# whole oracle group is 700+ MB and the transform's transient working set is
# a small multiple of its input, which is what exhausted HBM in the round-3
# sweep; sequential dynamic-update-slice chunks bound it
_SWEEP_EVAL_CHUNK = 128 << 20


@jax.jit
def _coset_eval(mono_stack, scale_row):
    """Evaluate a (B, n) monomial stack over ONE LDE coset: the scale row is
    shift_c^i (ntt._lde_scale_cached row c), then a forward NTT. One
    compiled graph reused for every coset of the streamed quotient sweep.
    Column batches are transformed in sequentially-chained chunks so the
    peak transient stays bounded regardless of B."""
    B, n = mono_stack.shape
    per = max(1, _SWEEP_EVAL_CHUNK // (n * 8))
    if B <= per:
        scaled = gf.mul(mono_stack, scale_row[None, :])
        return fft_natural_to_bitreversed(scaled)
    out = jnp.zeros((B, n), jnp.uint64)
    for i in range(0, B, per):
        # derive each chunk's input THROUGH the accumulated output (an
        # optimization_barrier ties them): the chunks are otherwise
        # data-independent and nothing would stop XLA's scheduler from
        # materializing several chunk transients concurrently — the memory
        # bound must be enforced by dataflow, not scheduler luck
        mono_stack, out = jax.lax.optimization_barrier((mono_stack, out))
        chunk = gf.mul(mono_stack[i : i + per], scale_row[None, :])
        chunk = fft_natural_to_bitreversed(chunk)
        out = jax.lax.dynamic_update_slice_in_dim(out, chunk, i, axis=0)
    return out


@lru_cache(maxsize=4)
def _inv_xs_brev(log_n, lde_factor):
    """1/x over the LDE domain, brev order (cached: challenge-independent)."""
    return gf.batch_inverse(_domain_xs_brev(log_n, lde_factor))


@lru_cache(maxsize=4)
def _vanishing_inv_brev(log_n, lde_factor):
    """1/(x^n - 1) over the LDE domain (per-coset constants, brev order)."""
    n = 1 << log_n
    log_lde = lde_factor.bit_length() - 1
    brev_lde = bitreverse_indices(log_lde)
    w_full = gl.omega(log_n + log_lde)
    vals = []
    for jb in brev_lde:
        shift = gl.mul(gl.MULTIPLICATIVE_GENERATOR, gl.pow_(w_full, int(jb)))
        vals.append(gl.inv(gl.sub(gl.pow_(shift, n), 1)))
    per_coset = jnp.asarray(np.array(vals, dtype=np.uint64))
    return jnp.repeat(per_coset, n)


# ---------------------------------------------------------------------------
# Fused stage graphs
# ---------------------------------------------------------------------------
# Every executable launch on a network-tunneled device costs a full round
# trip (~10 ms measured on the axon v5e), and EAGER jnp ops dispatch one
# executable per primitive — a single eager gf.mul is ~25 round trips. The
# prover therefore fuses each round's device work into one (or a handful of)
# jitted graphs; nested @jax.jit functions inline into the outer trace, so
# the existing stage helpers are reused unchanged. Two deliberate seams
# remain: batch_inverse stays a top-level jit boundary (see
# stages._all_chunk_num_den's miscompile note), and transcript absorbs
# happen on host between rounds (protocol order). Under an active mesh the
# legacy sequenced path is kept — GSPMD partitions its smaller jits, and
# pallas kernels cannot split under a NamedSharding.


def _dev_cached(obj, name: str, build):
    """Device-upload cache on a host object (assembly/setup): re-proving the
    same circuit reuses resident buffers instead of re-paying H2D transfers
    (the reference prover likewise starts with the witness resident in RAM).

    The cached stacks stay pinned in HBM between proves (~1 GB at 2^20
    rows for witness+sigma); BOOJUM_TPU_CACHE_DEVICE_INPUTS=0 disables the
    cache when that residency matters more than the re-upload cost."""
    import os

    if os.environ.get("BOOJUM_TPU_CACHE_DEVICE_INPUTS", "").strip() == "0":
        return _metrics.count_upload(build())
    cache = getattr(obj, "_dev_cache", None)
    if cache is None:
        cache = {}
        try:
            obj._dev_cache = cache
        except Exception:
            return _metrics.count_upload(build())
    if name not in cache:
        cache[name] = _metrics.count_upload(build())
    return cache[name]


def _commit_pipeline(values, L: int, cap: int, stream: bool):
    """values over H (B, n) -> (mono, lde | None, tree layers).

    (Flight recorder: one `commit_pipeline` span per oracle, NTT/Merkle
    invocation counters — no-ops unless recording.)

    The round-3 one-graph-per-commit form (`_commit_fused`) paid a 200 s+
    remote compile per oracle SHAPE because the inverse NTT, the rate-L
    forward NTTs, the leaf sponge and every node layer all landed in one
    module. This issues the same math as a short pipeline of shape-keyed
    top-level dispatches — inverse NTT keyed (B, n), LDE keyed (B, n, L),
    leaf sponge keyed (B, L·n), node stack keyed only (L·n, cap) — each of
    which compiles in well under a minute, precompiles concurrently
    (prover/precompile.py), and is shared wherever the shape recurs (the
    node stack is one executable for ALL oracles of a domain size).
    Streamed mode never materializes the rate-L storage: leaf digests are
    absorbed per column block (streaming.streamed_leaf_digests_blocks),
    one reusable (COL_BLOCK, n) graph for every block of every oracle.

    Under a shard_map mesh the whole pipeline delegates to
    parallel/shard_sweep.commit_pipeline_sm: per-chip iNTT/LDE, the
    explicit all_to_all layout pivot, per-chip leaf sponges (the fused
    limb kernel where native) and an explicit cap all_gather — same
    return contract, bit-identical digests."""
    from ..merkle import commit_layers_device, node_layers_device
    from ..parallel.sharding import shard_map_mesh
    from .streaming import streamed_leaf_digests_blocks

    sm_mesh = shard_map_mesh()
    if sm_mesh is not None:
        from ..parallel.shard_sweep import commit_pipeline_sm

        with _span("commit_pipeline", stream=stream, sm=True):
            return commit_pipeline_sm(values, L, cap, stream, sm_mesh)
    with _span("commit_pipeline", stream=stream):
        mono = monomial_from_values(values)
        _metrics.count("ntt.monomial_from_values")
        if stream:
            digests = streamed_leaf_digests_blocks(mono, L)
            _metrics.count("merkle.streamed_commits")
            return mono, None, node_layers_device(digests, cap)
        lde = lde_from_monomial(mono, L)
        _metrics.count("ntt.lde_from_monomial")
        _metrics.count("merkle.commits")
        return mono, lde, commit_layers_device(lde, cap)


def _tree_from_layers(layers, cap):
    return MerkleTreeWithCap.from_layers(list(layers), cap)


def _stage2_stack_fn(assembly, selector_paths):
    """Assembly-cached round-2 STACK graph: assemble the stage-2 column
    stack [z | partials | lookup A_i | B] from the already-computed
    z/partials and inverted lookup denominators — elementwise muls plus
    one stack, a deliberately small compile. The round-3 form fused this
    with `_z_and_partials` AND the full commit into one 163 s-compile
    mega-graph; split, the prefix product, the stack and the commit
    pipeline are separate shape-keyed dispatches (inversions happen
    outside as ever)."""
    cached = getattr(assembly, "_stage2_stack_jit", None)
    if cached is not None:
        return cached

    lookups = assembly.lookups_enabled
    lk_mode = assembly.lookup_mode
    R_args = assembly.num_lookup_subargs
    num_chunks = len(
        chunk_columns(
            assembly.copy_placement.shape[0] + assembly.num_lookup_cols,
            assembly.geometry.max_allowed_constraint_degree,
        )
    )
    if lookups and lk_mode == "general":
        mk_path = tuple(selector_paths[assembly.lookup_marker_gid()])
    else:
        mk_path = None

    @jax.jit
    def fn(z, partials_stacked, lk_inv, multiplicities, consts_dev):
        stage2_list = [z[0], z[1]]
        for j in range(num_chunks - 1):
            stage2_list += [partials_stacked[0][j], partials_stacked[1][j]]
        if lookups:
            sel_h = None
            if lk_mode == "general":
                one = jnp.uint64(1)
                for bdx, bit in enumerate(mk_path):
                    col = consts_dev[bdx]
                    f = (
                        col
                        if bit
                        else gf.sub(jnp.broadcast_to(one, col.shape), col)
                    )
                    sel_h = f if sel_h is None else gf.mul(sel_h, f)
            for i in range(R_args):
                a0, a1 = lk_inv[0][i], lk_inv[1][i]
                if sel_h is not None:
                    a0, a1 = gf.mul(a0, sel_h), gf.mul(a1, sel_h)
                stage2_list += [a0, a1]
            t_inv = (lk_inv[0][R_args], lk_inv[1][R_args])
            stage2_list += [
                gf.mul(t_inv[0], multiplicities),
                gf.mul(t_inv[1], multiplicities),
            ]
        return jnp.stack(stage2_list)

    assembly._stage2_stack_jit = fn
    return fn


@jax.jit
def _zshift_fused(s2_mono2, omega_arr):
    """(2, n) z monomials -> stacked z(w·x) monomials (one dispatch)."""
    n = s2_mono2.shape[-1]
    pows = powers_device_base(omega_arr, n)
    return gf.mul(s2_mono2, pows[None, :])


def powers_device_base(base_arr, count: int):
    """powers_device with a traced scalar base (log-doubling)."""
    pows = jnp.ones((1,), jnp.uint64)
    step = base_arr
    cur = 1
    while cur < count:
        pows = jnp.concatenate([pows, gf.mul(pows, step)])
        step = gf.mul(step, step) if 2 * cur < count else step
        cur *= 2
    return pows[:count]


@jax.jit
def _coset_eval_q(mono_stack, scale_q, c_arr):
    """One group's coset evaluation: scale row c of scale_q, forward NTT.

    A TOP-LEVEL executable on purpose: inlining the four group evaluations
    into the terms graph quadrupled that graph's NTT content and pushed
    its remote compile alone to ~440s (plus minutes of tracing) — split,
    each shape compiles once in tens of seconds and is reused across all
    cosets and proofs."""
    scale_row = jax.lax.dynamic_index_in_dim(
        scale_q, c_arr, 0, keepdims=False
    )
    return _coset_eval(mono_stack, scale_row)


def _coset_sweep_fn(
    assembly, selector_paths, non_residues, lk_ctx, sm_mesh=None
):
    """Assembly-cached fused per-coset quotient TERMS graph: gate sweep +
    copy-permutation + lookup terms + 1/Z_H over already-evaluated coset
    values (the 4 group evaluations run as separate _coset_eval_q
    dispatches). Reused across cosets AND proofs (challenges are array
    args). Takes selector paths + non-residues rather than the SetupData
    so precompile.py can build (and warm) the very same assembly-cached
    graph before the setup's sigma columns exist.

    The closure captures only structural data (gate sweep fn, counts,
    paths) — never the assembly/setup objects, so re-witnessed clones can
    inherit it without pinning the original's witness buffers.

    Variants, cached separately per assembly keyed (limb, shard_map mesh)
    — the flags can flip between proves in one process; parity tests do
    exactly that. The per-coset CORE (everything after the xs/L0/1-Z_H
    coset slicing) is one function with one signature for both
    representations: the u64 XLA body or the fused u32-limb Pallas kernel
    (pallas_sweep.build_coset_terms, BOOJUM_TPU_LIMB_SWEEP). Meshless, the
    core runs under a plain jit; under a shard_map mesh it runs per chip
    on row shards (parallel/shard_sweep.sweep_shard_map — the terms are
    pointwise across the domain, so sharding rows changes no value)."""
    from .pallas_sweep import (
        build_coset_terms,
        limb_resident_enabled,
        limb_sweep_enabled,
    )
    from ..parallel.sharding import shard_map_mesh

    limb = limb_sweep_enabled()
    resident = limb_resident_enabled()
    if sm_mesh is None:
        sm_mesh = shard_map_mesh()
    cache = getattr(assembly, "_coset_sweep_cache", None)
    if not isinstance(cache, dict):
        cache = {}
        assembly._coset_sweep_cache = cache
    key = (limb, resident, sm_mesh)
    if key in cache:
        return cache[key]

    (lookups, lk_mode, R_args, width, num_partials, chunks,
     total_alpha_terms, Cg, Ct, W, K, M, mk_path) = lk_ctx
    non_residues = tuple(int(k) for k in non_residues)

    if limb:
        core = build_coset_terms(
            tuple(assembly.gates),
            tuple(tuple(p) for p in selector_paths),
            assembly.geometry, lk_ctx, non_residues,
        )
    else:
        core = _u64_sweep_core(
            assembly, selector_paths, non_residues, lk_ctx
        )

    if resident:
        # the RESIDENT sweep: plane stacks in, plane terms out, the
        # challenge/alpha scalar table host-built (resident.sweep_table_np)
        core_p = core.planes
        if sm_mesh is not None:
            from ..parallel.shard_sweep import sweep_shard_map_p

            fn = sweep_shard_map_p(core_p, sm_mesh)
        else:

            def body_p(
                wit_p, setup_p, s2_p, zs_p, c_arr,
                xs_q_p, l0_q_p, zhinv_q_p, table,
            ):
                n = wit_p[0].shape[-1]
                start = c_arr * n

                def _sl(p):
                    return (
                        jax.lax.dynamic_slice_in_dim(p[0], start, n),
                        jax.lax.dynamic_slice_in_dim(p[1], start, n),
                    )

                return core_p(
                    wit_p, setup_p, s2_p, zs_p,
                    _sl(xs_q_p), _sl(l0_q_p), _sl(zhinv_q_p), table,
                )

            fn = jax.jit(body_p)
    elif sm_mesh is not None:
        from ..parallel.shard_sweep import sweep_shard_map

        fn = sweep_shard_map(core, sm_mesh)
    else:

        def body(
            wit_v, setup_v, s2_v, zs_v, c_arr,
            xs_q, l0_q, zhinv_q, ap0, ap1, beta01, gamma01, lkb01, lkg01,
        ):
            n = wit_v.shape[-1]
            start = c_arr * n
            xs_sl = jax.lax.dynamic_slice_in_dim(xs_q, start, n)
            l0_sl = jax.lax.dynamic_slice_in_dim(l0_q, start, n)
            zhinv_sl = jax.lax.dynamic_slice_in_dim(zhinv_q, start, n)
            return core(
                wit_v, setup_v, s2_v, zs_v, xs_sl, l0_sl, zhinv_sl,
                ap0, ap1, beta01, gamma01, lkb01, lkg01,
            )

        fn = jax.jit(body)
    cache[key] = fn
    return fn


def _u64_sweep_core(assembly, selector_paths, non_residues, lk_ctx):
    """The emulated-u64 per-coset terms core, signature-identical to the
    limb kernel (pallas_sweep.build_coset_terms): consumes pre-sliced
    xs/L0/1-Z_H coset rows so the same core serves the meshless jit and
    the per-chip shard_map body."""
    from .stages import _build_gate_sweep

    (lookups, lk_mode, R_args, width, num_partials, chunks,
     total_alpha_terms, Cg, Ct, W, K, M, mk_path) = lk_ctx
    non_residues = tuple(int(k) for k in non_residues)

    total_gate_terms = num_gate_sweep_terms(assembly)
    gate_fn = getattr(assembly, "_gate_sweep_jit", None)
    if gate_fn is None and total_gate_terms:
        gate_fn = _build_gate_sweep(
            tuple(assembly.gates), tuple(tuple(p) for p in selector_paths),
            assembly.geometry,
        )
        assembly._gate_sweep_jit = gate_fn

    def core(
        wit_v, setup_v, s2_v, zs_v, xs_sl, l0_sl, zhinv_sl,
        ap0, ap1, beta01, gamma01, lkb01, lkg01,
    ):
        from .stages import AlphaPows as AP

        copy_v = wit_v[:Ct]
        gate_wit_v = wit_v[Ct : Ct + W] if W else None
        sigma_v = setup_v[:Ct]
        const_v = setup_v[Ct : Ct + K]
        table_v = setup_v[Ct + K :]
        z_v = (s2_v[0], s2_v[1])
        z_shift_v = (zs_v[0], zs_v[1])
        partial_v = [
            (s2_v[2 + 2 * j], s2_v[3 + 2 * j]) for j in range(num_partials)
        ]
        beta = (beta01[0], beta01[1])
        gamma = (gamma01[0], gamma01[1])
        alpha_pows = AP.from_arrays(ap0, ap1, total_alpha_terms)
        acc = None
        if total_gate_terms:
            a0, a1 = alpha_pows.take(total_gate_terms)
            acc = gate_fn(copy_v[:Cg], gate_wit_v, const_v, a0, a1)
        cp_acc = copy_permutation_quotient_terms(
            z_v, z_shift_v, partial_v, chunks, copy_v, sigma_v,
            non_residues, xs_sl, l0_sl, beta, gamma, alpha_pows,
        )
        acc = cp_acc if acc is None else ext_f.add(acc, cp_acc)
        if lookups:
            lkb = (lkb01[0], lkb01[1])
            lkg = (lkg01[0], lkg01[1])
            ab_off = 2 + 2 * num_partials
            a_v = [
                (s2_v[ab_off + 2 * i], s2_v[ab_off + 2 * i + 1])
                for i in range(R_args)
            ]
            b_v = (
                s2_v[ab_off + 2 * R_args],
                s2_v[ab_off + 2 * R_args + 1],
            )
            if lk_mode == "specialized":
                lk_acc = lookup_quotient_terms(
                    a_v, b_v, copy_v[Cg:], const_v[K - 1], table_v,
                    wit_v[Ct + W], lkb, lkg, R_args, width, alpha_pows,
                )
            else:
                from .stages import (
                    lookup_quotient_terms_general,
                    selector_poly_lde,
                )

                sel_v = selector_poly_lde(const_v, mk_path)
                if sel_v is None:
                    sel_v = jnp.ones_like(zhinv_sl)
                lk_acc = lookup_quotient_terms_general(
                    a_v, b_v, copy_v[:Cg], const_v[len(mk_path)], table_v,
                    wit_v[Ct + W], sel_v, lkb, lkg, R_args, width,
                    alpha_pows,
                )
            acc = ext_f.add(acc, lk_acc)
        return gf.mul(acc[0], zhinv_sl), gf.mul(acc[1], zhinv_sl)

    return core


def _gspmd_demesh_ok() -> bool:
    """Whether the GSPMD u64-miscompile hardening (rounds 4-5 de-mesh,
    replicated query gathers) can apply: always, on every topology.
    PR 5 gated this to single-process meshes because the de-mesh pull
    onto one device needed every mesh device addressable; shard_sweep's
    demesh is now addressable-safe (non-addressable arrays gather to
    every host via multihost_utils.process_allgather, billed to the
    dcn.* gauges, then land on the local device), so the hardening
    holds across jax.distributed too — each host runs the identical
    single-device rounds 4-5 graph over the identical gathered data."""
    return True


@partial(jax.jit, static_argnums=(2, 3))
def _quotient_interp(T0_parts, T1_parts, Q: int, n: int):
    """Quotient interpolation + chunk split (one dispatch)."""
    g_inv = gl.inv(gl.MULTIPLICATIVE_GENERATOR)
    T0 = jnp.concatenate(list(T0_parts))
    T1 = jnp.concatenate(list(T1_parts))
    T_mono = tuple(
        distribute_powers(ifft_bitreversed_to_natural(t), g_inv)
        for t in (T0, T1)
    )
    q_cols = []
    for i in range(Q):
        for comp in (0, 1):
            q_cols.append(T_mono[comp][i * n : (i + 1) * n])
    return jnp.stack(q_cols)


def _quotient_tail_fused(T0_parts, T1_parts, Q: int, n: int, L: int, cap: int):
    """Quotient interpolation + chunk split + LDE + commit.

    Deliberately SEPARATE dispatches (interp / LDE / leaf sponge / node
    stack): at 2^20 rows one fused graph's working set — the size-Q*n
    inverse transform, the rate-L LDE, the leaf-major transpose and the
    tree layers with no dead-buffer reuse between them — landed right at
    the device's memory ceiling, and the merged module's remote compile
    was part of the round-4 cold-start bill. The extra launches cost tens
    of ms; the freed intermediates are GBs and the node stack shares its
    executable with every other oracle (merkle.commit_layers_device)."""
    from ..merkle import commit_layers_device

    q_mono = _quotient_interp(tuple(T0_parts), tuple(T1_parts), Q, n)
    q_lde = lde_from_monomial(q_mono, L)
    return q_mono, q_lde, commit_layers_device(q_lde, cap)


@jax.jit
def _evals_fused(all_mono, s2_mono, z01, zw01):
    """Round-4 openings: everything at z plus z(z*omega), one dispatch."""
    from ..ntt.ntt import _eval_with_pows, _ext_powers_jit

    n = all_mono.shape[-1]
    zp = _ext_powers_jit(z01, n)
    ev0, ev1 = _eval_with_pows(all_mono, zp[0], zp[1])
    zwp = _ext_powers_jit(zw01, n)
    evw0, evw1 = _eval_with_pows(s2_mono[:2], zwp[0], zwp[1])
    return ev0, ev1, evw0, evw1


@jax.jit
def _deep_denoms_fused(xs_lde, z01, zw01):
    """Stacked (2, N) ext denominators [x - z; x - z*omega] (one dispatch;
    the batched inversion stays a top-level boundary outside)."""
    c0 = jnp.stack([gf.sub(xs_lde, z01[0]), gf.sub(xs_lde, zw01[0])])
    neg1 = jnp.stack(
        [
            jnp.broadcast_to(gf.neg(z01[1]), xs_lde.shape),
            jnp.broadcast_to(gf.neg(zw01[1]), xs_lde.shape),
        ]
    )
    return c0, neg1


@partial(jax.jit, static_argnums=(1, 2))
def _cols_from_mono(mono, idxs: tuple, L: int):
    """Regenerate a handful of rate-L columns from monomials (streamed
    oracles' round-5 single-column opens), one dispatch."""
    sel = mono[jnp.asarray(np.array(idxs, dtype=np.int64))]
    lde = lde_from_monomial(sel, L)
    return lde.reshape(len(idxs), -1)


@lru_cache(maxsize=8)
def _deep_extras_fn(num_zw: int, num_lk: int, num_pi: int):
    """Fused round-5 'extra term' accumulation: z at z*omega, lookup A/B at
    0, public-input opens — all in one graph. Static shape key only."""

    @jax.jit
    def fn(h, cols_zw, cols_lk, cols_pi, inv_xzw, inv_x, pi_denoms,
           y_zw, y_lk0, pi_vals, ch0, ch1):
        t = 0
        for i in range(num_zw):
            ch = (ch0[t], ch1[t])
            num = (
                gf.sub(cols_zw[i], y_zw[0][i]),
                jnp.broadcast_to(gf.neg(y_zw[1][i]), cols_zw[i].shape),
            )
            term = ext_f.mul(ext_f.mul(num, inv_xzw), ch)
            h = ext_f.add(h, term)
            t += 1
        for i in range(num_lk):
            ch = (ch0[t], ch1[t])
            num = (
                gf.sub(cols_lk[2 * i], y_lk0[0][i]),
                gf.sub(cols_lk[2 * i + 1], y_lk0[1][i]),
            )
            term = ext_f.mul(
                (gf.mul(num[0], inv_x), gf.mul(num[1], inv_x)), ch
            )
            h = ext_f.add(h, term)
            t += 1
        for k in range(num_pi):
            ch = (ch0[t], ch1[t])
            num = gf.sub(cols_pi[k], pi_vals[k])
            term_base = gf.mul(num, pi_denoms[k])
            h = ext_f.add(
                h, (gf.mul(term_base, ch[0]), gf.mul(term_base, ch[1]))
            )
            t += 1
        return h

    return fn


@partial(jax.jit, static_argnums=(2,))
def _gather_flat_fused(arrs, idxs, axes: tuple):
    """All query-phase gathers (oracle leaves, tree path levels, FRI leaf
    rows) in ONE dispatch, concatenated flat for a single host transfer.
    Axis tags: 0 = row gather, 1 = column gather, 2 = take whole array."""
    parts = []
    for arr, ix, ax in zip(arrs, idxs, axes):
        if ax == 2:
            g = arr
        elif ax == 1:
            g = arr[:, ix]
        else:
            g = arr[ix]
        parts.append(g.reshape(-1))
    return jnp.concatenate(parts)


@partial(jax.jit, static_argnums=(2,))
def _stream_gather_fused(mono, idx_dev, L: int):
    """Streamed-oracle leaf-value gather (MonomialSource.gather_rows traced
    into one dispatch — block order must match the streamed commit, so the
    single implementation lives there)."""
    return MonomialSource(mono, L).gather_rows(idx_dev)


def _prefetch_challenge_independent(
    assembly, setup, config, *, log_n, L, Q, n, lookups, lk_mode,
    resident=False,
):
    """Round-0 prefetch (BOOJUM_TPU_OVERLAP): every device input and
    cached domain/twiddle table that rounds 2-5 consume and that depends
    on NO transcript challenge is enqueued here, while the setup-cap
    absorb and the witness commit keep the host busy. Pure enqueue +
    cache population — nothing blocks, nothing is absorbed, so the
    transcript (and therefore proof bytes) are untouched; the later
    rounds simply hit the _dev_cached / lru caches instead of paying
    their builds at a transcript barrier."""
    import os

    from ..ntt.ntt import warm_domain_caches
    from .fri import fold_challenge_tables, fold_schedule

    if resident:
        # the plane twins of everything below (prover/resident.py) —
        # same enqueue-only posture, nothing absorbed
        from . import resident as _RES

        _RES.prefetch_plane_tables(
            config, log_n=log_n, L=L, Q=Q, n=n, lookups=lookups
        )
        if (
            os.environ.get("BOOJUM_TPU_CACHE_DEVICE_INPUTS", "").strip()
            == "0"
        ):
            return
        ctx_n = get_ntt_context(log_n)
        _dev_cached(
            setup, "sigma_planes",
            lambda: _RES.host_planes(setup.sigma_cols),
        )
        _dev_cached(
            setup, "xs_h_planes",
            lambda: _RES.host_planes(gl.powers_np(int(ctx_n.omega), n)),
        )
        _dev_cached(
            setup, "ks_planes",
            lambda: _RES.host_planes(
                np.array(
                    [int(k) for k in setup.non_residues], dtype=np.uint64
                )
            ),
        )
        _dev_cached(
            setup, "setup_mono_planes",
            lambda: _RES.ingest_planes(setup.setup_monomials, "setup_mono"),
        )
        if lookups:
            lp = assembly.lookup_params
            _dev_cached(
                assembly, "table_stack_planes",
                lambda: _RES.host_planes(
                    assembly.stacked_table_columns(lp.width)
                ),
            )
            _dev_cached(
                assembly, "mult_planes",
                lambda: _RES.host_planes(assembly.multiplicities),
            )
            if lk_mode == "specialized":
                _dev_cached(
                    setup, "tid_planes",
                    lambda: _RES.host_planes(setup.constant_cols[-1]),
                )
            else:
                _dev_cached(
                    setup, "consts_planes",
                    lambda: _RES.host_planes(setup.constant_cols),
                )
        return

    # twiddle/scale tables: commit rate L, quotient sweep rate Q, and the
    # full-domain brev constants rounds 3/5 read
    warm_domain_caches(log_n, L)
    warm_domain_caches(log_n, Q)
    _domain_xs_brev(log_n, L)
    _domain_xs_brev(log_n, Q)
    _l0_brev(log_n, Q)
    _vanishing_inv_brev(log_n, Q)
    if lookups:
        _inv_xs_brev(log_n, L)
    # FRI per-round 1/x tables (round 5)
    log_full = log_n + (L.bit_length() - 1)
    num_folds = sum(
        fold_schedule(
            n, config.fri_final_degree,
            getattr(config, "fri_folding_schedule", None),
        )
    )
    fold_challenge_tables(log_full, num_folds)
    if os.environ.get("BOOJUM_TPU_CACHE_DEVICE_INPUTS", "").strip() == "0":
        return  # uncached uploads here would be built twice — skip
    # round-2 device inputs: sigma columns, grand-product x powers and
    # non-residues, lookup tables — witness- and challenge-independent
    ctx_n = get_ntt_context(log_n)
    _dev_cached(setup, "sigma", lambda: jnp.asarray(setup.sigma_cols))
    _dev_cached(setup, "xs_h", lambda: powers_device(ctx_n.omega, n))
    _dev_cached(
        setup,
        "ks",
        lambda: jnp.asarray(
            np.array([int(k) for k in setup.non_residues], dtype=np.uint64)
        ),
    )
    if lookups:
        lp = assembly.lookup_params
        _dev_cached(
            assembly,
            "table_stack",
            lambda: jnp.asarray(assembly.stacked_table_columns(lp.width)),
        )
        _dev_cached(
            assembly, "mult", lambda: jnp.asarray(assembly.multiplicities)
        )
        if lk_mode == "specialized":
            _dev_cached(
                setup,
                "tid_col",
                lambda: jnp.asarray(setup.constant_cols[-1]),
            )
        else:
            _dev_cached(
                setup, "consts", lambda: jnp.asarray(setup.constant_cols)
            )


def _deep_round5_prep(
    assembly, *, log_n, L, N, lookups, num_partials, R_args,
    s2_mono, wit_mono, s2_lde_flat, wit_lde_all, xs_lde, z01, zw01, omega,
):
    """The DEEP-challenge-INDEPENDENT half of round 5: the 1/(x-z),
    1/(x-z*omega) denominator inversion, the shifted/lookup single-column
    regens, and the public-input denominators all depend only on z (drawn
    at the end of round 3) and on committed data — so with overlap on the
    prover dispatches them DURING the round-4 evaluation pull's flight
    window instead of serially after the DEEP challenge. Returns the prep
    dict the round-5 body consumes; issuing it earlier or later changes
    nothing that crosses the transcript."""
    num_lk = (R_args + 1) if lookups else 0
    num_pi = len(assembly.public_inputs)
    d0, d1 = _deep_denoms_fused(xs_lde, z01, zw01)
    dinv = ext_f.batch_inverse((d0, d1))
    ab_off = 2 + 2 * num_partials
    s2_idxs = [0, 1] + [ab_off + j for j in range(2 * num_lk)]
    if isinstance(s2_lde_flat, MonomialSource):
        s2_cols = _cols_from_mono(s2_mono, tuple(s2_idxs), L)
    else:
        s2_cols = s2_lde_flat[jnp.asarray(np.array(s2_idxs))]
    inv_x = (
        _inv_xs_brev(log_n, L) if lookups else jnp.zeros((1,), jnp.uint64)
    )
    if num_pi:
        pi_cols_idx = [c_ for (c_, _r, _v) in assembly.public_inputs]
        if isinstance(wit_lde_all, MonomialSource):
            cols_pi = _cols_from_mono(wit_mono, tuple(pi_cols_idx), L)
        else:
            cols_pi = wit_lde_all[jnp.asarray(np.array(pi_cols_idx))]
        pi_points = np.array(
            [gl.pow_(omega, r) for (_c, r, _v) in assembly.public_inputs],
            dtype=np.uint64,
        )
        pi_denoms = gf.batch_inverse(
            gf.sub(xs_lde[None, :], jnp.asarray(pi_points)[:, None])
        )
        pi_vals = jnp.asarray(
            np.array(
                [v for (_c, _r, v) in assembly.public_inputs],
                dtype=np.uint64,
            )
        )
    else:
        cols_pi = jnp.zeros((0, N), jnp.uint64)
        pi_denoms = cols_pi
        pi_vals = jnp.zeros((0,), jnp.uint64)
    return {
        "inv_xz": (dinv[0][0], dinv[1][0]),
        "inv_xzw": (dinv[0][1], dinv[1][1]),
        "s2_cols": s2_cols,
        "inv_x": inv_x,
        "cols_pi": cols_pi,
        "pi_denoms": pi_denoms,
        "pi_vals": pi_vals,
    }


def prove(assembly, setup, config: ProofConfig, mesh=None) -> Proof:
    """Prove; with `mesh` (a jax.sharding.Mesh from parallel.make_mesh) the
    polynomial work shards over the mesh ('col' axis for per-column phases,
    both axes for leaf hashing) and produces a byte-identical proof.

    Flight recorder: with BOOJUM_TPU_REPORT=<path> each prove records
    hierarchical spans, metrics and Fiat–Shamir digest checkpoints and
    appends one ProveReport JSONL line to <path> (utils/report.py). A
    caller that already installed a FlightRecorder (bench.py labels its
    reps) keeps ownership — no double emission.

    Trace context (ISSUE 17): the auto-installed recorder adopts
    whatever inbound trace the execution context carries — the proving
    service binds a gateway-minted context before dispatch, and a bare
    CLI/bench prove honors BOOJUM_TPU_TRACE="<trace_id>[:<span_id>]"
    (utils/spans.py inbound_trace) — so the emitted line's `trace_ctx`
    and every span id stitch into the caller's distributed timeline;
    without either, the recorder mints a fresh root trace.

    AOT artifacts: with BOOJUM_TPU_AOT_DIR=<dir> the prove consults the
    artifact store (prover/aot.py) BEFORE tracing — once per process per
    (shape bucket, variant) the pre-built executable bundle is installed
    into the persistent cache and warmed, so a cold process pays
    deserialization instead of XLA compilation. A missing/stale bundle
    logs a warning and the prove JIT-compiles as before
    (BOOJUM_TPU_AOT_REQUIRE=1 makes that a hard error).

    On-demand device profiles: BOOJUM_TPU_XPROF=<dir>[:N] arms a
    process-wide budget — the next N proves each capture a jax.profiler
    trace into a fresh subdirectory, recorded as the report line's
    `trace` record (and skipped silently when a caller — the proving
    service honoring a request's capture_trace flag — already holds the
    capture)."""
    import os

    from ..utils import blackbox as _blackbox
    from ..utils import profiling as _prof
    from ..utils import report as _report

    label = f"prove_n{assembly.trace_len}"
    path = os.environ.get("BOOJUM_TPU_REPORT")
    # black-box forensics (utils/blackbox.py): with BOOJUM_TPU_BLACKBOX
    # or BOOJUM_TPU_STALL_S armed, a heartbeat thread stamps a crash-safe
    # sidecar and a stall/SIGTERM dump lands in the report artifact
    _blackbox.ensure_started(label=label, report_path=path)
    _blackbox.set_phase(label)
    with _prof.maybe_trace_capture(label) as trace_dir:
        if trace_dir:
            # attribute the capture to whoever is recording this prove
            # (a caller-owned flight recorder, or the one below)
            rec_owner = _report.current_flight_recorder()
            if rec_owner is not None:
                rec_owner.trace_dir = trace_dir
        if path and _report.current_flight_recorder() is None:
            with _report.flight_recording(label=label) as rec:
                rec.trace_dir = trace_dir
                try:
                    return _prove_entry(assembly, setup, config, mesh)
                finally:
                    # emit even when the prove raised — the partial span
                    # tree (with its error field) and the checkpoints up
                    # to the failure are exactly what a post-mortem needs
                    try:
                        _report.append_jsonl(
                            path, _report.build_report(rec)
                        )
                    except Exception as e:  # noqa: BLE001 — the recorder
                        # must never turn a successful prove into a crash
                        from ..utils.profiling import log

                        log(f"ProveReport write to {path!r} failed: {e!r}")
        return _prove_entry(assembly, setup, config, mesh)


def _prove_entry(assembly, setup, config: ProofConfig, mesh) -> Proof:
    import os

    from ..parallel.sharding import prover_mesh

    clock = _StageClock()
    _metrics.count("prover.proves")
    with _span("prove", trace_len=assembly.trace_len):
        # measured-traffic baseline BEFORE any of this prove's work: on
        # a long-lived registry (bench multi-rep) the ici./transfer.
        # families are cumulative, and the cost record must carry this
        # prove's bytes only
        from ..utils import costmodel as _costmodel

        cost_baseline = _costmodel.measured_baseline()
        # AOT consult INSIDE the recorded region (flight recorder is
        # installed by now), so aot.* counters/gauges and the
        # aot_load/aot_warm spans land on this prove's report line;
        # once per process per (bucket, variant) — no-op-cheap after
        if os.environ.get("BOOJUM_TPU_AOT_DIR", "").strip():
            from . import aot as _aot

            _aot.maybe_load_for_prove(assembly, config, mesh)
        try:
            from ..field.spec import is_babybear

            if is_babybear():
                # ISSUE 20: the BabyBear field backend drives the REAL
                # prover pipeline — same rounds, checkpoints and clock
                # stages, every kernel the plane-free u32 twin
                from .prover_bb import prove_full_babybear

                proof = prove_full_babybear(assembly, setup, config, clock)
            elif mesh is not None:
                with prover_mesh(mesh):
                    proof = _prove_impl(assembly, setup, config, clock)
            else:
                proof = _prove_impl(assembly, setup, config, clock)
            clock.stop()
            # roofline attribution (ISSUE 12): every stage span is
            # closed now — join the analytic cost model with this
            # prove's walls/gauges/ledger actuals and stamp the `cost`
            # record on the report line (fails soft inside)
            _costmodel.attach_cost_record(
                assembly, config, mesh=mesh, baseline=cost_baseline
            )
            return proof
        except BaseException as e:
            clock.stop(error=e)
            raise
        finally:
            clock.stop()


def _prove_impl(assembly, setup, config: ProofConfig, clock) -> Proof:
    n = assembly.trace_len
    log_n = n.bit_length() - 1
    L = config.fri_lde_factor
    log_full = log_n + (L.bit_length() - 1)
    N = n * L
    cap = config.merkle_tree_cap_size
    geometry = assembly.geometry
    Cg = assembly.copy_placement.shape[0]
    LC = assembly.num_lookup_cols
    Ct = Cg + LC
    W = assembly.wit_placement.shape[0]
    lookups = assembly.lookups_enabled
    lk_mode = assembly.lookup_mode
    R_args = assembly.num_lookup_subargs
    M = 1 if lookups else 0
    # the dedicated table-id constant column exists only in specialized mode
    K = geometry.num_constant_columns + (
        1 if lk_mode == "specialized" else 0
    )
    lp = assembly.lookup_params
    TW = (lp.width + 1) if lookups else 0  # table setup columns

    from ..parallel.sharding import active_mesh, shard_cols, shard_map_mesh

    # Mesh execution comes in two flavors (parallel/sharding.mesh_mode):
    # the shard_map path runs the FUSED round graphs with per-chip native
    # kernels and explicit collectives (parallel/shard_sweep.py), so it
    # shares the fused control flow below; the legacy GSPMD path keeps the
    # sequenced branches (its smaller jits are what GSPMD partitions).
    sm_mesh = shard_map_mesh()
    fused = active_mesh() is None or sm_mesh is not None
    # Limb residency (ISSUE 10): with BOOJUM_TPU_LIMB_RESIDENT on, every
    # fused-round graph below runs its plane twin (prover/resident.py) —
    # (lo, hi) u32 planes are the canonical device representation from the
    # H2D witness split to the query-phase host joins, and the interior
    # u64<->limb conversions of the converting path never trace
    # (limb.splits/limb.joins stay 0; tests/test_limb_resident.py).
    from .pallas_sweep import limb_resident_enabled
    from . import resident as RES

    res = fused and limb_resident_enabled()
    _wit_key = "witness_planes" if res else "witness_cols"

    def _shard_cols_r(x):
        if isinstance(x, tuple):
            return (shard_cols(x[0]), shard_cols(x[1]))
        return shard_cols(x)

    def _prefetch_r(x):
        if isinstance(x, tuple):
            _transfer.prefetch_async(x[0])
            _transfer.prefetch_async(x[1])
        else:
            _transfer.prefetch_async(x)

    def _tree_r(layers):
        if res:
            from ..merkle import PlaneMerkleTree

            return PlaneMerkleTree.from_layers(list(layers), cap)
        return _tree_from_layers(layers, cap)

    def _upload_witness():
        host_cols = [np.asarray(assembly.copy_cols_values)]
        if LC:
            host_cols.append(np.asarray(assembly.lookup_cols_values))
        if W:
            host_cols.append(np.asarray(assembly.wit_cols_values))
        if M:
            host_cols.append(np.asarray(assembly.multiplicities)[None, :])
        # chunked async device_put with overlap on, one synchronous
        # jnp.asarray(np.concatenate) with it off — identical bytes.
        # Resident mode splits once on HOST and uploads u32 planes (the
        # residency contract's H2D edge).
        return _transfer.chunked_upload(host_cols, planes=res)

    # streamed commit-rate mode: above the footprint threshold the rate-L
    # storages are never materialized — commits absorb column blocks into a
    # carried sponge state, DEEP/queries regenerate blocks from monomials
    # (see prover/streaming.py). GSPMD mesh runs keep the materialized path
    # (its sharding constraints pool HBM across chips); shard_map mesh runs
    # stream per chip — each chip absorbs its own row range
    # (shard_sweep.streamed_leaf_digests_sm).
    num_chunks_est = len(
        chunk_columns(Ct, geometry.max_allowed_constraint_degree)
    )
    S_est = 2 * num_chunks_est + 2 * R_args + 2 * M
    Q_est = setup.vk.effective_quotient_degree()
    total_cols = (Ct + W + M) + (Ct + K + TW) + S_est + 2 * Q_est
    stream = fused and use_streamed_lde(total_cols, N)
    overlap = fused and _transfer.overlap_enabled()
    if overlap:
        # dispatch everything challenge-independent — witness H2D chunks,
        # the sigma/table uploads, domain/twiddle/FRI caches — while the
        # setup-cap absorb below runs on host. Enqueue-only: transcript
        # order (and every byte absorbed) is exactly the sequenced order.
        import os as _os0

        with _span("overlap_prefetch"):
            # with the device-input cache disabled a prefetch upload would
            # be discarded and re-paid in round 1 — skip it then
            if (
                _os0.environ.get(
                    "BOOJUM_TPU_CACHE_DEVICE_INPUTS", ""
                ).strip()
                != "0"
            ):
                _dev_cached(assembly, _wit_key, _upload_witness)
            _prefetch_challenge_independent(
                assembly, setup, config,
                log_n=log_n, L=L, Q=Q_est, n=n,
                lookups=lookups, lk_mode=lk_mode, resident=res,
            )

    t = make_transcript(setup.vk.transcript)
    t.witness_merkle_tree_cap(setup.vk.setup_merkle_cap)
    _checkpoint(0, "setup_cap", setup.vk.setup_merkle_cap)
    pi_values = [v for (_c, _r, v) in assembly.public_inputs]
    t.witness_field_elements(pi_values)
    _checkpoint(0, "public_inputs", pi_values)

    # ---- round 1: witness commitment -------------------------------------
    clock.start("round1_witness_commit")
    witness_cols = _dev_cached(assembly, _wit_key, _upload_witness)
    if res:
        copy_vals = (witness_cols[0][:Ct], witness_cols[1][:Ct])
    else:
        copy_vals = witness_cols[:Ct]
    witness_cols = _shard_cols_r(witness_cols)
    # round 2 consumes copy_vals directly: shard it too or the heaviest
    # column phase (grand product + lookup polys) stays replicated
    copy_vals = _shard_cols_r(copy_vals)
    if fused:
        if res:
            wit_mono, wit_lde, layers = RES.commit_pipeline_p(
                witness_cols, L, cap, stream, sm_mesh
            )
        else:
            wit_mono, wit_lde, layers = _commit_pipeline(
                witness_cols, L, cap, stream
            )
        if overlap:
            _prefetch_r(layers[-1])  # cap d2h rides the queue
        wit_tree = _tree_r(layers)
    else:
        wit_mono = monomial_from_values(witness_cols)
        wit_lde = lde_from_monomial(wit_mono, L)  # (Ct+W+M, L, n)
        wit_tree, _ = _commit_columns(wit_lde, cap)
    del witness_cols  # values over H: monomials carry them from here
    t.witness_merkle_tree_cap(wit_tree.get_cap())
    _checkpoint(1, "witness_cap", wit_tree.get_cap())
    beta = t.get_ext_challenge()
    gamma = t.get_ext_challenge()
    r1_challenges = [beta, gamma]
    if lookups:
        lookup_beta = t.get_ext_challenge()
        lookup_gamma = t.get_ext_challenge()
        r1_challenges += [lookup_beta, lookup_gamma]
    _checkpoint(1, "challenges", r1_challenges)

    # ---- round 2: copy-permutation + lookup stage 2 ----------------------
    clock.start("round2_stage2_commit")
    chunks = chunk_columns(Ct, geometry.max_allowed_constraint_degree)
    num_partials = len(chunks) - 1
    s2_lde = None
    if res:
        # the plane twins of the fused round-2 graphs (prover/resident.py):
        # sigma/tables/x-powers enter as HOST-split planes, the chunk scan,
        # inversions, prefix product and the stage-2 stack all compute in
        # the limb domain, and the commit pipeline hashes planes
        from ..field import limb_ops as lop

        ctx_n = get_ntt_context(log_n)
        sigma_dev = _shard_cols_r(
            _dev_cached(
                setup, "sigma_planes",
                lambda: RES.host_planes(setup.sigma_cols),
            )
        )
        xs_h = _dev_cached(
            setup, "xs_h_planes",
            lambda: RES.host_planes(gl.powers_np(int(ctx_n.omega), n)),
        )
        ks = _dev_cached(
            setup, "ks_planes",
            lambda: RES.host_planes(
                np.array(
                    [int(k) for k in setup.non_residues], dtype=np.uint64
                )
            ),
        )
        bg_arr = jnp.asarray(RES.bg_np(beta, gamma))
        with _span("stage2_chunk_num_den"):
            num_all, den_all = RES._all_chunk_num_den_p(
                copy_vals, sigma_dev, ks, (xs_h, bg_arr),
                tuple(tuple(c) for c in chunks),
            )
            den_inv_all = lop.ext_batch_inverse_jit(den_all)
        _metrics.count("stage2.chunk_scans")
        lk_inv = mult_dev = consts_dev = None
        if lookups:
            table_stack = _dev_cached(
                assembly, "table_stack_planes",
                lambda: RES.host_planes(
                    assembly.stacked_table_columns(lp.width)
                ),
            )
            mult_dev = _dev_cached(
                assembly, "mult_planes",
                lambda: RES.host_planes(assembly.multiplicities),
            )
            if lk_mode == "specialized":
                lkcols = (copy_vals[0][Cg:], copy_vals[1][Cg:])
                tid_col = _dev_cached(
                    setup, "tid_planes",
                    lambda: RES.host_planes(setup.constant_cols[-1]),
                )
            else:
                consts_dev = _dev_cached(
                    setup, "consts_planes",
                    lambda: RES.host_planes(setup.constant_cols),
                )
                mk_path_r2 = setup.selector_paths[assembly.lookup_marker_gid()]
                lkcols = (copy_vals[0][:Cg], copy_vals[1][:Cg])
                tid_col = (
                    consts_dev[0][len(mk_path_r2)],
                    consts_dev[1][len(mk_path_r2)],
                )
            lkbg_arr = jnp.asarray(RES.bg_np(lookup_beta, lookup_gamma))
            dens = RES._lookup_denominators_p(
                lkcols, (tid_col, table_stack), lkbg_arr, R_args, lp.width
            )
            lk_inv = lop.ext_batch_inverse_jit(dens)
        z_pp = RES._z_and_partials_p(num_all, den_inv_all)
        stack = RES.stage2_stack_fn_p(assembly, setup.selector_paths)
        s2_vals = stack(z_pp[0], z_pp[1], lk_inv, mult_dev, consts_dev)
        s2_mono, s2_lde, layers = RES.commit_pipeline_p(
            s2_vals, L, cap, stream, sm_mesh
        )
        del s2_vals
        if overlap:
            _prefetch_r(layers[-1])
        s2_tree = _tree_r(layers)
        num_all = den_all = den_inv_all = lk_inv = dens = mult_dev = None
        z_pp = None
        if stream:
            for _obj, _keys in (
                (
                    assembly,
                    ("witness_planes", "table_stack_planes", "mult_planes"),
                ),
                (setup, ("sigma_planes",)),
            ):
                _c = getattr(_obj, "_dev_cache", None)
                if _c is not None:
                    for _k in _keys:
                        _c.pop(_k, None)
    elif fused:
        sigma_dev = shard_cols(
            _dev_cached(setup, "sigma", lambda: jnp.asarray(setup.sigma_cols))
        )
        from .stages import _all_chunk_num_den, _lookup_denominators

        ctx_n = get_ntt_context(log_n)
        xs_h = _dev_cached(
            setup, "xs_h", lambda: powers_device(ctx_n.omega, n)
        )
        ks = _dev_cached(
            setup,
            "ks",
            lambda: jnp.asarray(
                np.array([int(k) for k in setup.non_residues], dtype=np.uint64)
            ),
        )

        def _pair(s):
            return jnp.asarray(np.array([s[0], s[1]], dtype=np.uint64))

        beta01, gamma01 = _pair(beta), _pair(gamma)
        with _span("stage2_chunk_num_den"):
            num_all, den_all = _all_chunk_num_den(
                copy_vals, sigma_dev, ks, xs_h,
                (beta01[0], beta01[1]), (gamma01[0], gamma01[1]),
                tuple(tuple(c) for c in chunks),
            )
            den_inv_all = ext_f.batch_inverse(den_all)
        _metrics.count("stage2.chunk_scans")
        lk_inv = mult_dev = consts_dev = None
        lkb01 = lkg01 = None
        if lookups:
            lkb01, lkg01 = _pair(lookup_beta), _pair(lookup_gamma)
            table_stack = _dev_cached(
                assembly,
                "table_stack",
                lambda: jnp.asarray(assembly.stacked_table_columns(lp.width)),
            )
            mult_dev = _dev_cached(
                assembly, "mult", lambda: jnp.asarray(assembly.multiplicities)
            )
            if lk_mode == "specialized":
                lkcols = copy_vals[Cg:]
                tid_col = _dev_cached(
                    setup,
                    "tid_col",
                    lambda: jnp.asarray(setup.constant_cols[-1]),
                )
            else:
                consts_dev = _dev_cached(
                    setup,
                    "consts",
                    lambda: jnp.asarray(setup.constant_cols),
                )
                mk_path_r2 = setup.selector_paths[assembly.lookup_marker_gid()]
                lkcols = copy_vals[:Cg]
                tid_col = consts_dev[len(mk_path_r2)]
            dens = _lookup_denominators(
                lkcols, tid_col, table_stack,
                (lkb01[0], lkb01[1]), (lkg01[0], lkg01[1]),
                R_args, lp.width,
            )
            lk_inv = ext_f.batch_inverse(dens)
        from .stages import _z_and_partials

        z_pp = _z_and_partials(num_all, den_inv_all)
        stack = _stage2_stack_fn(assembly, setup.selector_paths)
        s2_vals = stack(z_pp[0], z_pp[1], lk_inv, mult_dev, consts_dev)
        s2_mono, s2_lde, layers = _commit_pipeline(s2_vals, L, cap, stream)
        del s2_vals
        if overlap:
            _transfer.prefetch_async(layers[-1])
        s2_tree = _tree_from_layers(layers, cap)
        # the chunk numerator/denominator ext stacks, the z/partials and
        # the lookup denominators total ~2 GB at 2^20 rows and are dead
        # after the commit — rebind so the buffers free before the
        # round-3 sweep
        num_all = den_all = den_inv_all = lk_inv = dens = mult_dev = None
        z_pp = None
        if stream:
            # streamed proves regenerate everything from monomials; the
            # values-form device-input caches (witness columns, sigmas,
            # table stack — ~1.5 GB at 2^20) only save warm-rep H2D time
            # and that residency is what the big-trace mode cannot afford
            for _obj, _keys in (
                (assembly, ("witness_cols", "table_stack", "mult")),
                (setup, ("sigma",)),
            ):
                _c = getattr(_obj, "_dev_cache", None)
                if _c is not None:
                    for _k in _keys:
                        _c.pop(_k, None)
    else:
        sigma_dev = shard_cols(
            _dev_cached(setup, "sigma", lambda: jnp.asarray(setup.sigma_cols))
        )
        z, partials, chunks = compute_copy_permutation_stage2(
            copy_vals, sigma_dev, setup.non_residues, beta, gamma,
            geometry.max_allowed_constraint_degree,
        )
        stage2_list = [z[0], z[1]] + [
            c for p in partials for c in (p[0], p[1])
        ]
        num_partials = len(partials)
        if lk_mode == "specialized":
            table_cols_dev = jnp.asarray(setup.constant_cols[-1])
            a_polys, b_poly = compute_lookup_polys(
                copy_vals[Cg:], table_cols_dev,
                jnp.asarray(assembly.stacked_table_columns(lp.width)),
                jnp.asarray(assembly.multiplicities),
                lookup_beta, lookup_gamma, R_args, lp.width,
            )
            for a in a_polys:
                stage2_list += [a[0], a[1]]
            stage2_list += [b_poly[0], b_poly[1]]
        elif lk_mode == "general":
            from .stages import compute_lookup_polys_general

            mk_gid = assembly.lookup_marker_gid()
            mk_path_r2 = setup.selector_paths[mk_gid]
            tid_idx = len(mk_path_r2)
            # marker selector over H from the base constant columns
            sel_h = None
            one = jnp.uint64(1)
            consts_dev = jnp.asarray(setup.constant_cols)
            for bdx, bit in enumerate(mk_path_r2):
                col = consts_dev[bdx]
                f = col if bit else gf.sub(jnp.broadcast_to(one, col.shape), col)
                sel_h = f if sel_h is None else gf.mul(sel_h, f)
            if sel_h is None:
                sel_h = jnp.ones((n,), jnp.uint64)
            a_polys, b_poly = compute_lookup_polys_general(
                copy_vals[:Cg], consts_dev[tid_idx],
                jnp.asarray(assembly.stacked_table_columns(lp.width)),
                jnp.asarray(assembly.multiplicities), sel_h,
                lookup_beta, lookup_gamma, R_args, lp.width,
            )
            for a in a_polys:
                stage2_list += [a[0], a[1]]
            stage2_list += [b_poly[0], b_poly[1]]
        stage2_cols = shard_cols(jnp.stack(stage2_list))
        del stage2_list
        s2_mono = monomial_from_values(stage2_cols)
        del stage2_cols
        s2_lde = lde_from_monomial(s2_mono, L)
        s2_tree, _ = _commit_columns(s2_lde, cap)
    del copy_vals, sigma_dev  # round 3 reads sigmas from the setup monomials
    t.witness_merkle_tree_cap(s2_tree.get_cap())
    _checkpoint(2, "stage2_cap", s2_tree.get_cap())
    alpha = t.get_ext_challenge()
    _checkpoint(2, "alpha", alpha)

    # ---- round 3: quotient (streamed per coset at rate Q) ----------------
    # The sweep runs over Q = vk.quotient_degree cosets while every oracle
    # commits at rate L — the reference's used_lde_degree vs fri_lde_factor
    # split (prover.rs:313, setup.rs:1187 subset_for_degree). Streaming one
    # coset at a time bounds transient HBM to (columns, n) regardless of Q,
    # which is what lets 2^20-row traces prove at the Era commit rate L=2.
    clock.start("round3_quotient")
    Q = setup.vk.effective_quotient_degree()
    if res:
        from .streaming import MonomialPlanesSource

        _setup_mono_p = _dev_cached(
            setup, "setup_mono_planes",
            lambda: RES.ingest_planes(setup.setup_monomials, "setup_mono"),
        )
        if stream:
            wit_lde_all = MonomialPlanesSource(wit_mono, L)
            s2_lde_flat = MonomialPlanesSource(s2_mono, L)
        else:
            wit_lde_all = (
                wit_lde[0].reshape(Ct + W + M, N),
                wit_lde[1].reshape(Ct + W + M, N),
            )
            s2_lde_flat = (
                s2_lde[0].reshape(-1, N), s2_lde[1].reshape(-1, N)
            )
        if setup.setup_lde is None:
            setup_lde_flat = MonomialPlanesSource(_setup_mono_p, L)
        else:
            setup_lde_flat = _shard_cols_r(
                _dev_cached(
                    setup, "setup_lde_planes",
                    lambda: RES.ingest_planes(
                        setup.setup_lde.reshape(Ct + K + TW, N), "setup_lde"
                    ),
                )
            )
        xs_lde = RES.domain_xs_brev_p(log_n, L)
        omega = gl.omega(log_n)
        zs_mono = RES._zshift_p(
            (s2_mono[0][:2], s2_mono[1][:2]), RES.omega_powers_p(log_n)
        )
        xs_q = RES.domain_xs_brev_p(log_n, Q)
        l0_q = RES.l0_brev_p(log_n, Q)
        zh_inv_q = RES.vanishing_inv_brev_p(log_n, Q)
        from ..ntt.limb_ntt import _lde_scale_planes

        scale_q = _lde_scale_planes(
            log_n, Q, int(gl.MULTIPLICATIVE_GENERATOR)
        )
    else:
        if stream:
            wit_lde_all = MonomialSource(wit_mono, L)
            s2_lde_flat = MonomialSource(s2_mono, L)
        else:
            wit_lde_all = wit_lde.reshape(Ct + W + M, N)
            s2_lde_flat = s2_lde.reshape(-1, N)
        # the setup oracle follows HOW IT WAS COMMITTED: a materialized
        # setup_lde is already resident (and shardable under a mesh) —
        # never regenerate it; only a streamed-committed setup (setup_lde
        # None) streams here too
        if setup.setup_lde is None:
            setup_lde_flat = MonomialSource(setup.setup_monomials, L)
        else:
            setup_lde_flat = shard_cols(
                setup.setup_lde.reshape(Ct + K + TW, N)
            )
        xs_lde = _domain_xs_brev(log_n, L)
        omega = gl.omega(log_n)
        # per-coset evaluation happens per GROUP (witness / setup /
        # stage-2 / shifted-z) straight from the existing monomial stacks
        # — concatenating them would duplicate every committed
        # polynomial's monomials (~1.5 GB at 2^20 rows) purely for
        # indexing convenience
        if fused:
            zs_mono = _zshift_fused(s2_mono[:2], jnp.uint64(omega))
        else:
            z_shift_mono = (
                distribute_powers(s2_mono[0], omega),
                distribute_powers(s2_mono[1], omega),
            )
            zs_mono = jnp.stack([z_shift_mono[0], z_shift_mono[1]])

        xs_q = _domain_xs_brev(log_n, Q)
        l0_q = _l0_brev(log_n, Q)
        zh_inv_q = _vanishing_inv_brev(log_n, Q)
        scale_q = lde_scale_rows(log_n, Q)

    total_alpha_terms = (
        num_gate_sweep_terms(assembly)
        + 1 + len(chunks)
        + ((R_args + 1) if lookups else 0)
    )
    mk_path = None
    if lookups and lk_mode == "general":
        from .stages import (
            lookup_quotient_terms_general,
            selector_poly_lde,
        )

        mk_path = setup.selector_paths[assembly.lookup_marker_gid()]

    if fused:
        # five dispatches per coset (4 group evals + 1 terms graph, ~10 ms
        # RTT each) — deliberately NOT one fused graph: the fused form's
        # remote compile alone was ~440s (see _coset_eval_q)
        lk_ctx = (
            lookups, lk_mode, R_args, (lp.width if lookups else 0),
            num_partials, tuple(tuple(c) for c in chunks),
            total_alpha_terms, Cg, Ct, W, K, M,
            tuple(mk_path) if mk_path is not None else None,
        )
        from .pallas_sweep import limb_sweep_enabled

        _limb_sweep = limb_sweep_enabled()
        if res:
            # the alpha/γ-power scalar table is host-built; no device u64
            # challenge arrays exist in the resident round
            sweep_tb = jnp.asarray(
                RES.sweep_table_np(
                    alpha, total_alpha_terms, beta, gamma,
                    lookup_beta if lookups else (0, 0),
                    lookup_gamma if lookups else (0, 0),
                    lookups, (lp.width if lookups else 0),
                )
            )
        else:
            ap = AlphaPows(alpha, total_alpha_terms)
            zero2 = jnp.zeros((2,), jnp.uint64)
        sweep = _coset_sweep_fn(
            assembly, setup.selector_paths, setup.non_residues, lk_ctx
        )
        # No default host barrier here (the old code block_until_ready'd
        # every sweep at n >= 2^19): the dependent dispatches already
        # order the work — each sweep consumes its own coset's four group
        # evaluations and the quotient tail consumes every sweep output,
        # so the device runs them in queue order with zero host stalls.
        # BOOJUM_TPU_SYNC_SWEEPS=1 restores a per-coset barrier for
        # HBM-constrained geometries where bounding the number of
        # concurrently ENQUEUED sweep working sets matters more than
        # keeping the host ahead of the device (the entry points that
        # drive the 2^20 ceiling — bench.py at large traces,
        # scripts/sha2_20_driver.py — set it themselves).
        _sync_sweeps = _transfer.env_flag("BOOJUM_TPU_SYNC_SWEEPS", False)
        _setup_eval_mono = _setup_mono_p if res else setup.setup_monomials
        if sm_mesh is not None:
            # pad + column-shard the four monomial groups ONCE per round
            # (not per coset); each coset evaluation then runs the
            # per-chip scale+NTT and pivots to row sharding with one
            # explicit all_to_all (parallel/shard_sweep.py)
            if res:
                from ..parallel.shard_sweep import (
                    coset_eval_q_sm_p,
                    pad_cols_sharded_p,
                )

                _eval_groups = {
                    "wit": pad_cols_sharded_p(wit_mono, sm_mesh),
                    "setup": pad_cols_sharded_p(_setup_eval_mono, sm_mesh),
                    "s2": pad_cols_sharded_p(s2_mono, sm_mesh),
                    "zs": pad_cols_sharded_p(zs_mono, sm_mesh),
                }

                def _eval_group(tag, mono_stack, ci):
                    return coset_eval_q_sm_p(
                        _eval_groups[tag], scale_q, ci,
                        int(mono_stack[0].shape[0]), sm_mesh,
                    )

            else:
                from ..parallel.shard_sweep import (
                    coset_eval_q_sm,
                    pad_cols_sharded,
                )

                _eval_groups = {
                    "wit": pad_cols_sharded(wit_mono, sm_mesh),
                    "setup": pad_cols_sharded(_setup_eval_mono, sm_mesh),
                    "s2": pad_cols_sharded(s2_mono, sm_mesh),
                    "zs": pad_cols_sharded(zs_mono, sm_mesh),
                }

                def _eval_group(tag, mono_stack, ci):
                    return coset_eval_q_sm(
                        _eval_groups[tag], scale_q, ci,
                        int(mono_stack.shape[0]), sm_mesh,
                    )

        elif res:

            def _eval_group(tag, mono_p, ci):
                return RES._coset_eval_q_p(mono_p, scale_q, ci)

        else:

            def _eval_group(tag, mono_stack, ci):
                return _coset_eval_q(mono_stack, scale_q, ci)

        T_parts0, T_parts1 = [], []
        with _span(
            "round3_coset_sweeps", cosets=Q, limb=_limb_sweep,
            resident=res, sm=sm_mesh is not None,
        ):
            for c in range(Q):
                ci = jnp.int32(c)
                _metrics.count("ntt.coset_evals", 4)
                _metrics.count("quotient.coset_sweeps")
                if _limb_sweep:
                    # flight-recorder surface: the limb-kernel dispatch
                    # count makes "which representation ran" auditable
                    # per report
                    _metrics.count("quotient.limb_coset_sweeps")
                if res:
                    _metrics.count("quotient.resident_coset_sweeps")
                wit_v = _eval_group("wit", wit_mono, ci)
                setup_v = _eval_group("setup", _setup_eval_mono, ci)
                s2_v = _eval_group("s2", s2_mono, ci)
                zs_v = _eval_group("zs", zs_mono, ci)
                if res:
                    t0c, t1c = sweep(
                        wit_v, setup_v, s2_v, zs_v,
                        ci, xs_q, l0_q, zh_inv_q, sweep_tb,
                    )
                else:
                    t0c, t1c = sweep(
                        wit_v, setup_v, s2_v, zs_v,
                        ci, xs_q, l0_q, zh_inv_q,
                        ap.p0, ap.p1, beta01, gamma01,
                        lkb01 if lkb01 is not None else zero2,
                        lkg01 if lkg01 is not None else zero2,
                    )
                if _sync_sweeps:
                    _metrics.count("host.blocking_syncs")
                    jax.block_until_ready(t1c)
                T_parts0.append(t0c)
                T_parts1.append(t1c)
            _sync_point(T_parts1, "round3_sweeps")
        if sm_mesh is not None:
            del _eval_groups
            if res:
                from ..parallel.shard_sweep import commit_from_mono_sm_p

                q_mono = RES._quotient_interp_p(
                    tuple(T_parts0), tuple(T_parts1), Q, n
                )
                q_lde, layers = commit_from_mono_sm_p(
                    q_mono, L, cap, sm_mesh
                )
            else:
                from ..parallel.shard_sweep import commit_from_mono_sm

                q_mono = _quotient_interp(
                    tuple(T_parts0), tuple(T_parts1), Q, n
                )
                q_lde, layers = commit_from_mono_sm(q_mono, L, cap, sm_mesh)
        elif res:
            q_mono, q_lde, layers = RES._quotient_tail_p(
                tuple(T_parts0), tuple(T_parts1), Q, n, L, cap
            )
        else:
            q_mono, q_lde, layers = _quotient_tail_fused(
                tuple(T_parts0), tuple(T_parts1), Q, n, L, cap
            )
        del T_parts0, T_parts1
        if overlap:
            _prefetch_r(layers[-1])
        q_tree = _tree_r(layers)
    else:
        T_parts0, T_parts1 = [], []
        for c in range(Q):
            row = scale_q[c]
            wit_v = _coset_eval(wit_mono, row)
            setup_v = _coset_eval(setup.setup_monomials, row)
            s2_v = _coset_eval(s2_mono, row)
            zs_v = _coset_eval(zs_mono, row)
            copy_v = wit_v[:Ct]
            gate_wit_v = wit_v[Ct : Ct + W] if W else None
            sigma_v = setup_v[:Ct]
            const_v = setup_v[Ct : Ct + K]
            table_v = setup_v[Ct + K :]
            z_v = (s2_v[0], s2_v[1])
            z_shift_v = (zs_v[0], zs_v[1])
            partial_v = [
                (s2_v[2 + 2 * j], s2_v[3 + 2 * j])
                for j in range(num_partials)
            ]
            sl = slice(c * n, (c + 1) * n)
            # fresh per coset: the per-TERM challenge sequence is identical
            # on every coset (same order the verifier replays)
            alpha_pows = AlphaPows(alpha, total_alpha_terms)
            acc = gate_terms_contribution(
                assembly, setup.selector_paths, copy_v[:Cg], gate_wit_v,
                const_v, alpha_pows,
            )
            cp_acc = copy_permutation_quotient_terms(
                z_v, z_shift_v, partial_v, chunks, copy_v, sigma_v,
                setup.non_residues, xs_q[sl], l0_q[sl], beta, gamma,
                alpha_pows,
            )
            acc = cp_acc if acc is None else ext_f.add(acc, cp_acc)
            if lookups:
                ab_off = 2 + 2 * num_partials
                a_v = [
                    (s2_v[ab_off + 2 * i], s2_v[ab_off + 2 * i + 1])
                    for i in range(R_args)
                ]
                b_v = (
                    s2_v[ab_off + 2 * R_args],
                    s2_v[ab_off + 2 * R_args + 1],
                )
                if lk_mode == "specialized":
                    lk_acc = lookup_quotient_terms(
                        a_v, b_v, copy_v[Cg:], const_v[K - 1], table_v,
                        wit_v[Ct + W], lookup_beta, lookup_gamma, R_args,
                        lp.width, alpha_pows,
                    )
                else:
                    sel_v = selector_poly_lde(const_v, mk_path)
                    if sel_v is None:
                        sel_v = jnp.ones((n,), jnp.uint64)
                    lk_acc = lookup_quotient_terms_general(
                        a_v, b_v, copy_v[:Cg], const_v[len(mk_path)], table_v,
                        wit_v[Ct + W], sel_v, lookup_beta, lookup_gamma,
                        R_args, lp.width, alpha_pows,
                    )
                acc = ext_f.add(acc, lk_acc)
            T_parts0.append(gf.mul(acc[0], zh_inv_q[sl]))
            T_parts1.append(gf.mul(acc[1], zh_inv_q[sl]))
        # the last coset's group evaluations (~2 GB at 2^20) are dead here;
        # free them before the N_Q-size interpolation allocates its stages
        del wit_v, setup_v, s2_v, zs_v, copy_v, gate_wit_v, sigma_v, const_v
        del table_v, z_v, z_shift_v, partial_v, acc, cp_acc
        T = (jnp.concatenate(T_parts0), jnp.concatenate(T_parts1))
        del T_parts0, T_parts1
        # interpolate over the full rate-Q domain to monomial form
        g_inv = gl.inv(gl.MULTIPLICATIVE_GENERATOR)
        T_mono = tuple(
            distribute_powers(ifft_bitreversed_to_natural(T[i]), g_inv)
            for i in (0, 1)
        )
        del T
        # split into Q chunks of degree < n, interleave (c0, c1); COMMIT at L
        q_cols = []
        for i in range(Q):
            for comp in (0, 1):
                q_cols.append(T_mono[comp][i * n : (i + 1) * n])
        q_mono = shard_cols(jnp.stack(q_cols))  # (2Q, n) already monomial
        q_lde = lde_from_monomial(q_mono, L)
        q_tree, _ = _commit_columns(q_lde, cap)
    t.witness_merkle_tree_cap(q_tree.get_cap())
    _checkpoint(3, "quotient_cap", q_tree.get_cap())
    z_chal = t.get_ext_challenge()
    _checkpoint(3, "z", z_chal)

    # ---- round 4: evaluations at z (and z*omega, 0) ----------------------
    clock.start("round4_evaluations")
    _setup_mono = setup.setup_monomials
    if active_mesh() is not None and sm_mesh is None and _gspmd_demesh_ok():
        # GSPMD only: the partitioner's u64 miscompile (see the round-5
        # de-mesh below) can also land on the z-evaluation contraction
        # over the sharded monomial stacks — pull them onto one device
        # BEFORE the concat so rounds 4-5 run the single-device graphs.
        # The committed heavy phases (rounds 1-3) keep their GSPMD
        # sharding; their caps are transcript-checked bit-exact.
        from ..parallel.shard_sweep import demesh as _demesh

        wit_mono = _demesh(wit_mono)
        s2_mono = _demesh(s2_mono)
        q_mono = _demesh(q_mono)
        _setup_mono = _demesh(_setup_mono)
    if res:
        all_mono = (
            jnp.concatenate(
                [wit_mono[0], _setup_mono_p[0], s2_mono[0], q_mono[0]]
            ),
            jnp.concatenate(
                [wit_mono[1], _setup_mono_p[1], s2_mono[1], q_mono[1]]
            ),
        )
        B = all_mono[0].shape[0]
    else:
        all_mono = jnp.concatenate([wit_mono, _setup_mono, s2_mono, q_mono])
        B = all_mono.shape[0]
    zw = ext_f.mul_by_base_s(z_chal, omega)
    deep_prep = None
    if res:
        # evaluations compute on planes; the pull fetches u32 planes and
        # u64 reassembles ON HOST (the transcript absorb edge)
        z_tb = jnp.asarray(RES.ext_sc_np(z_chal))
        zw_tb = jnp.asarray(RES.ext_sc_np(zw))
        ev0p, ev1p, evw0p, evw1p = RES._evals_p(
            all_mono, s2_mono, z_tb, zw_tb
        )
        pulls = [
            ev0p[0], ev0p[1], ev1p[0], ev1p[1],
            evw0p[0], evw0p[1], evw1p[0], evw1p[1],
        ]
        if lookups:
            pulls += [s2_mono[0][:, 0], s2_mono[1][:, 0]]
        fetch = _transfer.start_fetch(pulls, label="round4_evals")
        if overlap:
            with _span("deep_prep_overlap"):
                deep_prep = RES.deep_round5_prep_p(
                    assembly, log_n=log_n, L=L, N=N, lookups=lookups,
                    num_partials=num_partials, R_args=R_args,
                    s2_mono_p=s2_mono, wit_mono_p=wit_mono,
                    s2_lde_flat_p=s2_lde_flat, wit_lde_all_p=wit_lde_all,
                    xs_lde_p=xs_lde, z_tb=z_tb, zw_tb=zw_tb, omega=omega,
                )
        got = fetch.wait()
        from ..field.limbs import join_np as _join_np

        ev0 = _join_np(got[0], got[1])
        ev1 = _join_np(got[2], got[3])
        evw0 = _join_np(got[4], got[5])
        evw1 = _join_np(got[6], got[7])
        s2_mono_host = _join_np(got[8], got[9]) if lookups else None
    elif fused:
        z01 = jnp.asarray(np.array([z_chal[0], z_chal[1]], dtype=np.uint64))
        zw01 = jnp.asarray(np.array([zw[0], zw[1]], dtype=np.uint64))
        ev0, ev1, evw0, evw1 = _evals_fused(all_mono, s2_mono, z01, zw01)
        # ONE batched, prefetched d2h for the whole evaluation round
        # (the sequenced path pays four-plus separate blocking pulls);
        # the lookup sums at 0 are the constant monomial coefficients,
        # so their gather rides the same batch
        pulls = [ev0, ev1, evw0, evw1]
        if lookups:
            pulls.append(s2_mono[:, 0])
        fetch = _transfer.start_fetch(pulls, label="round4_evals")
        if overlap:
            # the DEEP-challenge-independent half of round 5 (denominator
            # inversions, single-column regens, public-input denoms)
            # dispatches inside the pull's flight window
            with _span("deep_prep_overlap"):
                deep_prep = _deep_round5_prep(
                    assembly, log_n=log_n, L=L, N=N, lookups=lookups,
                    num_partials=num_partials, R_args=R_args,
                    s2_mono=s2_mono, wit_mono=wit_mono,
                    s2_lde_flat=s2_lde_flat, wit_lde_all=wit_lde_all,
                    xs_lde=xs_lde, z01=z01, zw01=zw01, omega=omega,
                )
        got = fetch.wait()
        ev0, ev1, evw0, evw1 = got[:4]
        s2_mono_host = got[4] if lookups else None
    else:
        z_pows = ext_powers_device(z_chal, n)
        ev0, ev1 = eval_monomial_at_ext_point(all_mono, z_chal, z_pows)
        zw_pows = ext_powers_device(zw, n)
        evw0, evw1 = eval_monomial_at_ext_point(s2_mono[:2], zw, zw_pows)
        s2_mono_host = None
    from ..parallel.sharding import host_np

    values_at_z = [
        (int(a), int(b)) for a, b in zip(host_np(ev0), host_np(ev1))
    ]
    values_at_z_omega = [
        (int(a), int(b)) for a, b in zip(host_np(evw0), host_np(evw1))
    ]
    # lookup sum openings at 0: ext value of each A_i/B pair is the pair of
    # constant monomial coefficients
    values_at_0 = []
    if lookups:
        if s2_mono_host is None:
            s2_mono_host = host_np(s2_mono[:, 0])
        ab_off = 2 + 2 * num_partials
        for i in range(R_args + 1):
            values_at_0.append(
                (int(s2_mono_host[ab_off + 2 * i]),
                 int(s2_mono_host[ab_off + 2 * i + 1]))
            )
    for v in values_at_z:
        t.witness_field_elements(v)
    for v in values_at_z_omega:
        t.witness_field_elements(v)
    for v in values_at_0:
        t.witness_field_elements(v)
    _checkpoint(
        4, "evaluations", [values_at_z, values_at_z_omega, values_at_0]
    )
    deep_ch = t.get_ext_challenge()
    _checkpoint(4, "deep_challenge", deep_ch)

    # ---- round 5: DEEP + FRI ---------------------------------------------
    clock.start("round5_deep_fri")

    def _col(src, i):
        return src.column(i) if isinstance(src, MonomialSource) else src[i]

    if (
        active_mesh() is not None
        and shard_map_mesh() is None
        and _gspmd_demesh_ok()
    ):
        # GSPMD only: XLA's SPMD partitioner miscompiles the u64 round-5
        # math over mesh-sharded operands (first divergence of the whole
        # prove lands on fri_cap_0 — the h/t codeword itself comes out
        # wrong on the forced-8-device CPU mesh; rounds 1-4, whose caps
        # hash the SAME LDE arrays, match bit-for-bit, and replicating
        # the operands is NOT enough — the partitioned batch-inverse scan
        # still diverges). Pull every round-5 input onto one device so
        # DEEP + FRI run the single-device graphs — correctness over
        # speed on the legacy path; the shard_map mode is the performant
        # mesh path.
        from ..parallel.shard_sweep import demesh as _demesh

        wit_lde_all = _demesh(wit_lde_all)
        setup_lde_flat = _demesh(setup_lde_flat)
        s2_lde_flat = _demesh(s2_lde_flat)
        q_lde = _demesh(q_lde)
        xs_lde = _demesh(xs_lde)
        if deep_prep is not None:
            deep_prep = {k: _demesh(v) for k, v in deep_prep.items()}

    deep_sources = [
        wit_lde_all,
        setup_lde_flat,
        s2_lde_flat,
        (
            (q_lde[0].reshape(2 * Q, N), q_lde[1].reshape(2 * Q, N))
            if res
            else q_lde.reshape(2 * Q, N)
        ),
    ]
    num_deep_terms = (
        B + 2
        + ((R_args + 1) if lookups else 0)
        + len(assembly.public_inputs)
    )
    num_lk = (R_args + 1) if lookups else 0
    num_pi = len(assembly.public_inputs)
    if res:
        # DEEP challenge powers + opened values enter as HOST-built planes
        from .streaming import MonomialPlanesSource

        dp = ext_f.powers_s(
            (int(deep_ch[0]), int(deep_ch[1])), RES._next_pow2(num_deep_terms)
        )
        dp0 = np.array([p[0] for p in dp], dtype=np.uint64)
        dp1 = np.array([p[1] for p in dp], dtype=np.uint64)
        c0s = RES.host_planes(dp0[:B])
        c1s = RES.host_planes(dp1[:B])
        y0s = RES.host_planes(
            np.array([v[0] for v in values_at_z], dtype=np.uint64)
        )
        y1s = RES.host_planes(
            np.array([v[1] for v in values_at_z], dtype=np.uint64)
        )
        if deep_prep is None:
            deep_prep = RES.deep_round5_prep_p(
                assembly, log_n=log_n, L=L, N=N, lookups=lookups,
                num_partials=num_partials, R_args=R_args,
                s2_mono_p=s2_mono, wit_mono_p=wit_mono,
                s2_lde_flat_p=s2_lde_flat, wit_lde_all_p=wit_lde_all,
                xs_lde_p=xs_lde, z_tb=z_tb, zw_tb=zw_tb, omega=omega,
            )
        inv_xz = deep_prep["inv_xz"]
        inv_xzw = deep_prep["inv_xzw"]
        E = 2 + num_lk + num_pi
        ch0e = RES.host_planes(dp0[B : B + E])
        ch1e = RES.host_planes(dp1[B : B + E])
        y_zw = (
            RES.host_planes(
                np.array([v[0] for v in values_at_z_omega], dtype=np.uint64)
            ),
            RES.host_planes(
                np.array([v[1] for v in values_at_z_omega], dtype=np.uint64)
            ),
        )
        y_lk0 = (
            RES.host_planes(
                np.array([v[0] for v in values_at_0], dtype=np.uint64)
            ),
            RES.host_planes(
                np.array([v[1] for v in values_at_0], dtype=np.uint64)
            ),
        )
        _streamed_deep = any(
            isinstance(s, MonomialPlanesSource) for s in deep_sources
        )
        if sm_mesh is not None and not _streamed_deep:
            from ..parallel.shard_sweep import deep_codeword_sm_p

            h = deep_codeword_sm_p(
                sm_mesh, deep_sources, y0s, y1s, c0s, c1s, inv_xz,
                deep_prep, y_zw, y_lk0, ch0e, ch1e, 2, num_lk, num_pi,
            )
        else:
            if sm_mesh is not None:
                from ..parallel.shard_sweep import demesh as _demesh

                deep_sources = [_demesh(s) for s in deep_sources]
                deep_prep = {k: _demesh(v) for k, v in deep_prep.items()}
                inv_xz = deep_prep["inv_xz"]
                inv_xzw = deep_prep["inv_xzw"]
            h = RES._deep_main_sum_p(
                deep_sources, y0s, y1s, c0s, c1s, inv_xz
            )
            s2_cols = deep_prep["s2_cols"]
            cols_zw = (s2_cols[0][:2], s2_cols[1][:2])
            cols_lk = (s2_cols[0][2:], s2_cols[1][2:])
            extras = RES._deep_extras_fn_p(2, num_lk, num_pi)
            h = extras(
                h, cols_zw, cols_lk, deep_prep["cols_pi"], inv_xzw,
                deep_prep["inv_x"], deep_prep["pi_denoms"],
                y_zw, y_lk0, deep_prep["pi_vals"], ch0e, ch1e,
            )
        _metrics.count("deep.resident_codewords")
    elif fused:
        deep_pows = AlphaPows(deep_ch, num_deep_terms)
        c0s, c1s = deep_pows.take(B)
        y0s = jnp.asarray(
            np.array([v[0] for v in values_at_z], dtype=np.uint64)
        )
        y1s = jnp.asarray(
            np.array([v[1] for v in values_at_z], dtype=np.uint64)
        )
        # the challenge-independent prep — 1/(x-z), 1/(x-z*omega) (one
        # build + ONE batched inversion), single-column regens for the
        # remaining terms, public-input denominators — was dispatched
        # during the round-4 evaluation pull with overlap on; compute it
        # here (the sequenced order) otherwise
        if deep_prep is None:
            deep_prep = _deep_round5_prep(
                assembly, log_n=log_n, L=L, N=N, lookups=lookups,
                num_partials=num_partials, R_args=R_args,
                s2_mono=s2_mono, wit_mono=wit_mono,
                s2_lde_flat=s2_lde_flat, wit_lde_all=wit_lde_all,
                xs_lde=xs_lde, z01=z01, zw01=zw01, omega=omega,
            )
        inv_xz = deep_prep["inv_xz"]
        inv_xzw = deep_prep["inv_xzw"]
        ch0e, ch1e = deep_pows.take(2 + num_lk + num_pi)
        y_zw = (
            jnp.asarray(np.array([v[0] for v in values_at_z_omega], dtype=np.uint64)),
            jnp.asarray(np.array([v[1] for v in values_at_z_omega], dtype=np.uint64)),
        )
        y_lk0 = (
            jnp.asarray(np.array([v[0] for v in values_at_0], dtype=np.uint64)),
            jnp.asarray(np.array([v[1] for v in values_at_0], dtype=np.uint64)),
        )
        _streamed_deep = any(
            isinstance(s, MonomialSource) for s in deep_sources
        )
        if sm_mesh is not None and not _streamed_deep:
            # the whole DEEP accumulation is pointwise across the domain:
            # one shard_map graph computes main sum + extras per chip on
            # its N/D slice (the col->row re-layout of the sources at its
            # boundary is charged to ici.*), and h comes out row-sharded
            # — the layout the per-chip FRI commit/fold
            # graphs consume (shard_sweep.deep_codeword_sm; also dodges
            # the SPMD-partitioner u64 miscompile a plain jit over the
            # sharded LDE operands hits)
            from ..parallel.shard_sweep import deep_codeword_sm

            h = deep_codeword_sm(
                sm_mesh, deep_sources, y0s, y1s, c0s, c1s, inv_xz,
                deep_prep, y_zw, y_lk0, ch0e, ch1e, 2, num_lk, num_pi,
            )
        else:
            if sm_mesh is not None:
                # streamed oracles regenerate their blocks inside plain
                # jits — de-mesh the round-5 inputs so those jits stay
                # off the partitioner (correctness fallback; the commit/
                # sweep/fold phases already ran per chip)
                from ..parallel.shard_sweep import demesh as _demesh

                deep_sources = [_demesh(s) for s in deep_sources]
                deep_prep = {k: _demesh(v) for k, v in deep_prep.items()}
                inv_xz = deep_prep["inv_xz"]
                inv_xzw = deep_prep["inv_xzw"]
            h = _deep_main_sum(deep_sources, y0s, y1s, c0s, c1s, inv_xz)
            # the remaining terms (z at z*omega, lookup sums at 0, public
            # inputs): the gathered columns, then ONE fused accumulation
            s2_cols = deep_prep["s2_cols"]
            cols_zw = s2_cols[:2]
            cols_lk = s2_cols[2:]
            inv_x = deep_prep["inv_x"]
            cols_pi = deep_prep["cols_pi"]
            pi_denoms = deep_prep["pi_denoms"]
            pi_vals = deep_prep["pi_vals"]
            extras = _deep_extras_fn(2, num_lk, num_pi)
            h = extras(
                h, cols_zw, cols_lk, cols_pi, inv_xzw, inv_x, pi_denoms,
                y_zw, y_lk0, pi_vals, ch0e, ch1e,
            )
    else:
        deep_pows = AlphaPows(deep_ch, num_deep_terms)
        c0s, c1s = deep_pows.take(B)
        y0s = jnp.asarray(
            np.array([v[0] for v in values_at_z], dtype=np.uint64)
        )
        y1s = jnp.asarray(
            np.array([v[1] for v in values_at_z], dtype=np.uint64)
        )
        # 1/(x - z), 1/(x - z*omega) over the domain (ext)
        x_minus_z = (gf.sub(xs_lde, jnp.uint64(z_chal[0])),
                     jnp.broadcast_to(jnp.uint64(gl.neg(z_chal[1])), xs_lde.shape))
        inv_xz = ext_f.batch_inverse(x_minus_z)
        x_minus_zw = (gf.sub(xs_lde, jnp.uint64(zw[0])),
                      jnp.broadcast_to(jnp.uint64(gl.neg(zw[1])), xs_lde.shape))
        inv_xzw = ext_f.batch_inverse(x_minus_zw)
        h = _deep_main_sum(deep_sources, y0s, y1s, c0s, c1s, inv_xz)
        # z-poly at z*omega
        for i in range(2):
            c0, c1 = deep_pows.take(1)
            ch = (c0[0], c1[0])
            y = values_at_z_omega[i]
            num = (
                gf.sub(_col(s2_lde_flat, i), jnp.uint64(y[0])),
                jnp.broadcast_to(jnp.uint64(gl.neg(y[1])), xs_lde.shape),
            )
            term = ext_f.mul(ext_f.mul(num, inv_xzw), ch)
            h = ext_f.add(h, term)
        # lookup A_i/B at 0: (f(x) - f(0)) / x with f as ext coordinate pair
        if lookups:
            inv_x = _inv_xs_brev(log_n, L)
            ab_off = 2 + 2 * num_partials
            for i in range(R_args + 1):
                c0, c1 = deep_pows.take(1)
                ch = (c0[0], c1[0])
                v0, v1 = values_at_0[i]
                num = (
                    gf.sub(_col(s2_lde_flat, ab_off + 2 * i), jnp.uint64(v0)),
                    gf.sub(_col(s2_lde_flat, ab_off + 2 * i + 1), jnp.uint64(v1)),
                )
                term = ext_f.mul((gf.mul(num[0], inv_x), gf.mul(num[1], inv_x)), ch)
                h = ext_f.add(h, term)
        # public input openings: (w_col(x) - value) / (x - w^row)
        if assembly.public_inputs:
            pi_points = [gl.pow_(omega, r) for (_c, r, _v) in assembly.public_inputs]
            denoms = gf.batch_inverse(
                jnp.stack([gf.sub(xs_lde, jnp.uint64(p)) for p in pi_points])
            )
            for k, (col, _row, value) in enumerate(assembly.public_inputs):
                c0, c1 = deep_pows.take(1)
                ch = (c0[0], c1[0])
                num = gf.sub(_col(wit_lde_all, col), jnp.uint64(value))
                term_base = gf.mul(num, denoms[k])
                h = ext_f.add(h, (gf.mul(term_base, ch[0]), gf.mul(term_base, ch[1])))

    _sync_point(h, "deep_codeword")
    fri = fri_prove(h, t, config, base_degree=n, fused=fused)
    pow_nonce = pow_grind(t, config.pow_bits)
    _checkpoint(5, "pow_nonce", [pow_nonce])

    # ---- queries ----------------------------------------------------------
    clock.start("queries")
    bs = BitSource(log_full)
    # draw ALL query indices first (same transcript sequence the verifier
    # replays), then extract every oracle batched: one device gather per
    # storage / per tree level instead of per-query element reads — the
    # round-trips dominate when the device sits behind a network tunnel
    idxs = [bs.get_index(t, log_full) for _ in range(config.num_queries)]
    _checkpoint(5, "query_indices", idxs)
    idx_dev = jnp.asarray(np.array(idxs, dtype=np.int64))

    # PLAN every query gather (leaf rows + all tree path levels, all
    # oracles), execute them in ONE fused dispatch, and pay ONE host
    # transfer — behind a network tunnel per-op round trips otherwise
    # dominate the whole query phase.
    plans: list = []  # (array, index array, axis tag)
    plan_shapes: list = []  # result shape per plan (single source of truth)
    _dummy_idx = jnp.zeros((0,), jnp.int64)

    def _defer(arr, ix, axis):
        if axis == 2:
            shape = tuple(arr.shape)
            ix = _dummy_idx
        elif axis == 1:
            shape = (int(arr.shape[0]), int(ix.shape[0]))
        else:
            shape = (int(ix.shape[0]),) + tuple(arr.shape[1:])
        plans.append((arr, ix, axis))
        plan_shapes.append(shape)
        return len(plans) - 1, shape

    def _defer_vals(leaves_cols):
        """Leaf-value gather handle: ("one", h) for u64 storages, or
        ("pair", h_lo, h_hi) for resident plane pairs — the pair joins on
        HOST in _take_vals (the query-opening edge of the residency
        contract; no device u64 ever exists)."""
        from .streaming import MonomialPlanesSource

        if isinstance(leaves_cols, MonomialSource):
            vals = _stream_gather_fused(
                leaves_cols.mono, idx_dev, leaves_cols.L
            )
            return ("one", _defer(vals, None, 2))
        if isinstance(leaves_cols, MonomialPlanesSource):
            vlo, vhi = RES._stream_gather_p(
                leaves_cols.mono, idx_dev, leaves_cols.L
            )
            return ("pair", _defer(vlo, None, 2), _defer(vhi, None, 2))
        if isinstance(leaves_cols, tuple):
            return (
                "pair",
                _defer(leaves_cols[0], idx_dev, 1),
                _defer(leaves_cols[1], idx_dev, 1),
            )
        return ("one", _defer(leaves_cols, idx_dev, 1))

    def _defer_oracle(leaves_cols, tree):
        vals_h = _defer_vals(leaves_cols)
        gplans, assemble = tree.proof_gather_plans(idxs)
        level_hs = [
            _defer(layer, jnp.asarray(ix), 0) for layer, ix in gplans
        ]
        return vals_h, level_hs, assemble

    if res:
        _q_flat = (q_lde[0].reshape(2 * Q, N), q_lde[1].reshape(2 * Q, N))
        _setup_tree = RES.setup_tree_planes(setup)
    else:
        _q_flat = q_lde.reshape(2 * Q, N)
        _setup_tree = setup.setup_tree
    oracle_handles = [
        _defer_oracle(wit_lde_all, wit_tree),
        _defer_oracle(s2_lde_flat, s2_tree),
        _defer_oracle(_q_flat, q_tree),
        _defer_oracle(setup_lde_flat, _setup_tree),
    ]
    fri_handles = []
    fidxs = np.array(idxs, dtype=np.int64)
    for r, tree in enumerate(fri.trees):
        k = fri.schedule[r]
        block = 1 << k
        leaf_idx = fidxs >> k
        v0, v1 = fri.values[r]
        rows = (
            leaf_idx[:, None] * block + np.arange(block)[None, :]
        ).reshape(-1)
        rows_dev = jnp.asarray(rows)
        if res:
            g0_h = ("pair", _defer(v0[0], rows_dev, 0),
                    _defer(v0[1], rows_dev, 0))
            g1_h = ("pair", _defer(v1[0], rows_dev, 0),
                    _defer(v1[1], rows_dev, 0))
        else:
            g0_h = ("one", _defer(v0, rows_dev, 0))
            g1_h = ("one", _defer(v1, rows_dev, 0))
        gplans, assemble = tree.proof_gather_plans(
            [int(p) for p in leaf_idx]
        )
        level_hs = [
            _defer(layer, jnp.asarray(ix), 0) for layer, ix in gplans
        ]
        fri_handles.append((g0_h, g1_h, level_hs, assemble, block))
        fidxs = leaf_idx

    # ONE fused gather dispatch + ONE host transfer
    arrs_, idxs_, axes_ = zip(*plans)
    if (
        active_mesh() is not None
        and shard_map_mesh() is None
        and _gspmd_demesh_ok()
    ):
        # GSPMD only: XLA's SPMD partitioner miscompiles u64 gathers over
        # partially-replicated operands (replica values get SUMMED — 2x
        # leaf values observed on the forced-8-device CPU mesh, alongside
        # its "involuntary full rematerialization" warning). Gather from
        # explicitly replicated copies instead; the shard_map path keeps
        # its layouts (its gathers came out bit-exact). Across
        # jax.distributed a replicated device_put of a non-addressable
        # array is illegal — demesh those (per-host gather + local
        # device), which removes the partially-replicated layouts just
        # as thoroughly.
        from jax.sharding import NamedSharding, PartitionSpec

        if any(
            not getattr(a, "is_fully_addressable", True) for a in arrs_
        ):
            from ..parallel.shard_sweep import demesh as _demesh_g

            arrs_ = tuple(_demesh_g(a) for a in arrs_)
        else:
            _rep = NamedSharding(active_mesh(), PartitionSpec())
            arrs_ = tuple(jax.device_put(a, _rep) for a in arrs_)
    elif shard_map_mesh() is not None and any(
        len(a.devices()) <= 1 for a in arrs_
    ):
        # streamed sm proves mix placements here: commit-phase node
        # layers live on the mesh while the de-meshed round-5/FRI chain
        # left its layers on one device — one jit cannot take both.
        # These are the small node/cap layers (the big leaf gathers went
        # through the MonomialSource path above), so pull them all onto
        # one device and gather there.
        from ..parallel.shard_sweep import demesh as _demesh

        arrs_ = tuple(_demesh(a) for a in arrs_)
    _metrics.count("query.gather_plans", len(plans))
    with _span("query_gather"):
        flat = host_np(
            _gather_flat_fused(tuple(arrs_), tuple(idxs_), tuple(axes_))
        )
    _plan_offsets = np.concatenate(
        [[0], np.cumsum([int(np.prod(s)) for s in plan_shapes])]
    )

    def _take(handle):
        i, shape = handle
        return flat[_plan_offsets[i] : _plan_offsets[i + 1]].reshape(shape)

    def _take_vals(handle):
        if handle[0] == "one":
            return _take(handle[1])
        from ..field.limbs import join_np as _join_np

        return _join_np(_take(handle[1]), _take(handle[2]))

    def _oracle_queries(handle):
        vals_h, level_hs, assemble = handle
        vals = _take_vals(vals_h)
        paths = assemble([_take(h) for h in level_hs])
        return [
            OracleQuery(
                leaf_values=[int(x) for x in vals[:, q]], path=paths[q]
            )
            for q in range(len(idxs))
        ]

    wit_qs, s2_qs, q_qs, setup_qs = map(_oracle_queries, oracle_handles)
    fri_qs_per_round = []
    num_q = len(idxs)
    for g0_h, g1_h, level_hs, assemble, block in fri_handles:
        gathered = np.stack([_take_vals(g0_h), _take_vals(g1_h)])
        paths = assemble([_take(h) for h in level_hs])
        fri_qs_per_round.append(
            [
                OracleQuery(
                    leaf_values=[
                        int(gathered[c, q * block + j])
                        for j in range(block)
                        for c in (0, 1)
                    ],
                    path=paths[q],
                )
                for q in range(num_q)
            ]
        )
    queries = [
        SingleRoundQueries(
            witness=wit_qs[q],
            stage2=s2_qs[q],
            quotient=q_qs[q],
            setup=setup_qs[q],
            fri=[fri_qs_per_round[r][q] for r in range(len(fri.trees))],
        )
        for q in range(len(idxs))
    ]

    return Proof(
        public_inputs=pi_values,
        witness_cap=wit_tree.get_cap(),
        stage2_cap=s2_tree.get_cap(),
        quotient_cap=q_tree.get_cap(),
        values_at_z=values_at_z,
        values_at_z_omega=values_at_z_omega,
        values_at_0=values_at_0,
        fri_caps=[tr.get_cap() for tr in fri.trees],
        final_fri_monomials=fri.final_monomials,
        queries=queries,
        pow_challenge=pow_nonce,
        config={
            "fri_lde_factor": L,
            "quotient_degree": Q,
            "merkle_tree_cap_size": cap,
            "num_queries": config.num_queries,
            "pow_bits": config.pow_bits,
            "fri_final_degree": config.fri_final_degree,
        },
    )
