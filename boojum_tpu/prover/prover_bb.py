"""The full PLONKish prover over BabyBear (ISSUE 20 tentpole).

`prove_full_babybear` runs the REAL gate/CS pipeline — the same 5-round
IOP, checkpoint labels, and clock stages as `prover._prove_impl` — with
every polynomial phase on bare u32 lanes: witness/setup ingestion is one
`.astype(uint32)` (no `limbs.split` anywhere; the plane-free claim is
structural), stage 2 runs the GF(p^4) grand-product/lookup kernels, the
quotient is ONE fused sweep over the whole rate-Q coset, DEEP opens at a
GF(p^4) z, and the FRI chain folds factor-2 over Poseidon2-BB oracles.

The prover core is backend-agnostic (np-in/np-out kernel seam, exactly
the mini-STARK's `bb_prover` discipline): `DeviceBackendBBFull`
dispatches the jitted `_bb` kernels; the numpy twin lives in
`compat/prove_reference_bb.NumpyBackendBBFull`. Both run THIS function,
so transcript bytes, challenge schedule, checkpoint stream and proof
assembly are shared — parity reduces to per-kernel mod-p exactness.

Protocol deltas vs the Goldilocks prover, all forced by the field:
- ext degree 4: the z poly / partials / lookup sums are 4 base columns
  each; values-at-z entries are 4-tuples; DEEP spends one challenge
  power per base column of the z-poly at z*omega (4, not 2).
- commits use PAIRED leaves — leaf j of a (B, N) oracle holds columns'
  values at j AND j + N/2, so one auth path serves both FRI halves.
- FRI folds factor-2 per round over the natural-order coset (no 2^k
  leaf grouping), committing every layer including the DEEP codeword.
- PoW grinds blake2s over the 31-bit challenge stream.
"""

from __future__ import annotations

import numpy as np

from ..field import babybear as bb
from ..field.spec import BABYBEAR as BB_SPEC
from ..transcript import BitSource, make_transcript
from ..utils import metrics as _metrics
from ..utils.spans import span as _span
from ..utils.report import checkpoint as _checkpoint
from . import bb_kernels as K
from . import stages_bb as S
from .bb_prover import (
    coset_descale,
    eval_base_at_ext,
    ext_powers_table,
    _fri_pair_cols,
)
from .config import ProofConfig
from .pow import blake2s_pow_grind
from .proof import OracleQuery, Proof, SingleRoundQueries
from .stages import chunk_columns, num_gate_sweep_terms

SHIFT = int(BB_SPEC.multiplicative_generator)  # coset shift = 31


class _NoClock:
    def start(self, name):
        pass

    def stop(self, error=None):
        pass


class DeviceBackendBBFull:
    """Dispatches the jitted full-prover `_bb` kernels; numpy in, numpy
    out (2^10-class domains — transfers are noise), every dispatch
    counted so the zero-limb acceptance can also assert the `_bb`
    counters MOVED."""

    name = "device"

    def intt(self, values):
        import jax.numpy as jnp

        from ..ntt.bb_ntt import monomial_from_values_bb

        _metrics.count("ntt.bb_dispatches")
        values = np.asarray(values, dtype=np.uint32)
        log_m = values.shape[-1].bit_length() - 1
        return np.asarray(
            monomial_from_values_bb(jnp.asarray(values), log_m)
        )

    def lde(self, mono, rate, shift=SHIFT):
        import jax.numpy as jnp

        from ..ntt.bb_ntt import lde_from_monomial_bb

        _metrics.count("lde.bb_dispatches")
        mono = np.asarray(mono, dtype=np.uint32)
        log_m = mono.shape[-1].bit_length() - 1
        return np.asarray(
            lde_from_monomial_bb(jnp.asarray(mono), log_m, rate, shift)
        )

    def commit(self, cols, cap_size):
        import jax.numpy as jnp

        _metrics.count("merkle.bb_commits")
        digests = K.leaf_digests_bb(jnp.asarray(np.asarray(cols, np.uint32)))
        layers = K.node_layers_bb(digests, cap_size)
        return K.BBMerkleTree([np.asarray(l) for l in layers], cap_size)

    def stage2(self, copy_vals, sigma_vals, ks, xs, beta, gamma, chunks):
        import jax.numpy as jnp

        _metrics.count("stage2.bb_scans")
        return np.asarray(
            S.stage2_z_partials_bb(
                jnp.asarray(copy_vals), jnp.asarray(sigma_vals),
                tuple(int(k) for k in ks), jnp.asarray(xs),
                jnp.asarray(beta), jnp.asarray(gamma),
                tuple(tuple(c) for c in chunks),
            )
        )

    def lookup_polys(
        self, lookup_cols, tid_col, table_cols, mults, lkb, lkg, R, width
    ):
        import jax.numpy as jnp

        _metrics.count("lookup.bb_polys")
        return np.asarray(
            S.lookup_polys_bb(
                jnp.asarray(lookup_cols), jnp.asarray(tid_col),
                jnp.asarray(table_cols), jnp.asarray(mults),
                jnp.asarray(lkb), jnp.asarray(lkg), R, width,
            )
        )

    def sweep(self, assembly, sweep_ctx, arrays):
        import jax.numpy as jnp

        _metrics.count("quotient.bb_full_sweeps")
        gates, selector_paths, geometry, lk_ctx, non_residues = sweep_ctx
        fn = getattr(assembly, "_bb_sweep_jit", None)
        if fn is None:
            fn = S.build_full_sweep_bb(
                gates, selector_paths, geometry, lk_ctx, non_residues
            )
            assembly._bb_sweep_jit = fn
        return np.asarray(fn(*[jnp.asarray(a) for a in arrays]))

    def deep(self, all_lde, zw_cols, lk_cols, pi_cols, xs, z4, zw4,
             ch_tbl, at_z_const, y_zw, y_lk, pi_vals, pi_inv,
             num_lk, num_pi):
        import jax.numpy as jnp

        _metrics.count("deep.bb_accumulates")
        return np.asarray(
            S.deep_full_bb(
                jnp.asarray(all_lde), jnp.asarray(zw_cols),
                jnp.asarray(lk_cols), jnp.asarray(pi_cols),
                jnp.asarray(xs), jnp.asarray(z4), jnp.asarray(zw4),
                jnp.asarray(ch_tbl), jnp.asarray(at_z_const),
                jnp.asarray(y_zw), jnp.asarray(y_lk),
                jnp.asarray(pi_vals), jnp.asarray(pi_inv),
                num_lk, num_pi,
            )
        )

    def fri_fold(self, codeword, beta4, inv2x):
        import jax.numpy as jnp

        _metrics.count("fri.bb_folds")
        return np.asarray(
            K.fri_fold_bb(
                jnp.asarray(np.asarray(codeword, np.uint32)),
                jnp.asarray(np.asarray(beta4, np.uint32)),
                jnp.asarray(inv2x),
            )
        )


def _u32_cols(arr):
    a = np.asarray(arr)
    assert a.dtype != np.uint32 or True
    return a.astype(np.uint32)


def _ext_np(e):
    return np.array([int(c) % bb.P for c in e], dtype=np.uint32)


def _abs_ext(t, e):
    t.witness_field_elements([int(c) for c in e])


def prove_full_babybear(
    assembly, setup, config: ProofConfig, clock=None, backend=None
) -> Proof:
    """The shared full-prover core; see module docstring. `setup` must
    come from `generate_setup` under the babybear field (its VK carries
    the poseidon2_babybear transcript and the host-committed setup
    oracle both backends share)."""
    clock = clock or _NoClock()
    backend = backend or DeviceBackendBBFull()
    n = assembly.trace_len
    log_n = n.bit_length() - 1
    L = config.fri_lde_factor
    log_full = log_n + (L.bit_length() - 1)
    N = n * L
    half = N // 2
    cap = config.merkle_tree_cap_size
    geometry = assembly.geometry
    Cg = assembly.copy_placement.shape[0]
    LC = assembly.num_lookup_cols
    Ct = Cg + LC
    W = assembly.wit_placement.shape[0]
    lookups = assembly.lookups_enabled
    R_args = assembly.num_lookup_subargs
    M = 1 if lookups else 0
    Kc = geometry.num_constant_columns + (1 if lookups else 0)
    lp = assembly.lookup_params
    width = lp.width if lookups else 0
    TW = (width + 1) if lookups else 0
    assert not lookups or assembly.lookup_mode == "specialized", (
        "babybear full prover supports specialized lookup columns only"
    )
    assert setup.vk.transcript.endswith("babybear"), setup.vk.transcript
    Q = setup.vk.effective_quotient_degree()
    num_pi = len(assembly.public_inputs)
    num_lk = (R_args + 1) if lookups else 0
    omega = bb.omega(log_n)

    t = make_transcript(setup.vk.transcript)
    t.witness_merkle_tree_cap(setup.vk.setup_merkle_cap)
    _checkpoint(0, "setup_cap", setup.vk.setup_merkle_cap)
    pi_values = [int(v) for (_c, _r, v) in assembly.public_inputs]
    t.witness_field_elements(pi_values)
    _checkpoint(0, "public_inputs", pi_values)

    # ---- round 1: witness commitment -------------------------------------
    clock.start("round1_witness_commit")
    host_cols = [_u32_cols(assembly.copy_cols_values)]
    if LC:
        host_cols.append(_u32_cols(assembly.lookup_cols_values))
    if W:
        host_cols.append(_u32_cols(assembly.wit_cols_values))
    if M:
        host_cols.append(_u32_cols(assembly.multiplicities)[None, :])
    wit_vals = np.concatenate(host_cols, axis=0)  # (Ct+W+M, n) u32
    with _span("bb_witness_commit"):
        wit_mono = backend.intt(wit_vals)
        wit_lde = backend.lde(wit_mono, L)
        wit_tree = backend.commit(
            np.concatenate([wit_lde[:, :half], wit_lde[:, half:]]), cap
        )
    t.witness_merkle_tree_cap(wit_tree.get_cap())
    _checkpoint(1, "witness_cap", wit_tree.get_cap())
    beta = t.get_ext_challenge()
    gamma = t.get_ext_challenge()
    r1_challenges = [beta, gamma]
    if lookups:
        lookup_beta = t.get_ext_challenge()
        lookup_gamma = t.get_ext_challenge()
        r1_challenges += [lookup_beta, lookup_gamma]
    else:
        lookup_beta = lookup_gamma = bb.ZERO_S
    _checkpoint(1, "challenges", r1_challenges)

    # ---- round 2: copy-permutation + lookup stage 2 ----------------------
    clock.start("round2_stage2_commit")
    chunks = chunk_columns(Ct, geometry.max_allowed_constraint_degree)
    num_partials = len(chunks) - 1
    sigma_u32 = _u32_cols(setup.sigma_cols)
    consts_u32 = _u32_cols(setup.constant_cols)
    xs_h = bb.powers_np(omega, n)
    with _span("bb_stage2"):
        zp = backend.stage2(
            wit_vals[:Ct], sigma_u32, setup.non_residues, xs_h,
            _ext_np(beta), _ext_np(gamma), chunks,
        )  # (1 + num_partials, 4, n)
        s2_rows = [zp[j, k] for j in range(1 + num_partials)
                   for k in range(4)]
        if lookups:
            ab = backend.lookup_polys(
                wit_vals[Cg:Cg + R_args * width], consts_u32[Kc - 1],
                _u32_cols(
                    assembly.stacked_table_columns(width)
                ),
                wit_vals[Ct + W], _ext_np(lookup_beta),
                _ext_np(lookup_gamma), R_args, width,
            )  # (R_args + 1, 4, n)
            s2_rows += [ab[i, k] for i in range(R_args + 1)
                        for k in range(4)]
        s2_vals = np.stack(s2_rows)  # (S, n)
        s2_mono = backend.intt(s2_vals)
        s2_lde = backend.lde(s2_mono, L)
        s2_tree = backend.commit(
            np.concatenate([s2_lde[:, :half], s2_lde[:, half:]]), cap
        )
    t.witness_merkle_tree_cap(s2_tree.get_cap())
    _checkpoint(2, "stage2_cap", s2_tree.get_cap())
    alpha = t.get_ext_challenge()
    _checkpoint(2, "alpha", alpha)

    # ---- round 3: quotient (ONE fused sweep over the rate-Q coset) -------
    clock.start("round3_quotient")
    total_alpha_terms = (
        num_gate_sweep_terms(assembly)
        + 1 + len(chunks)
        + ((R_args + 1) if lookups else 0)
    )
    setup_mono = np.asarray(setup.setup_monomials, dtype=np.uint32)
    setup_lde = np.asarray(setup.setup_lde, dtype=np.uint32)
    with _span("bb_quotient"):
        wit_q = backend.lde(wit_mono, Q)
        setup_q = backend.lde(setup_mono, Q)
        s2_q = backend.lde(s2_mono, Q)
        # z(omega*x): the z poly's 4 base monomial rows scaled by omega^i
        zs_mono = bb.mul_np(
            s2_mono[:4], bb.powers_np(omega, n)[None, :]
        )
        zs_q = backend.lde(zs_mono, Q)
        xs_q = K.domain_xs_bb(log_n, Q, SHIFT)
        zh_inv_q = K.zh_inv_bb(log_n, Q, SHIFT)
        l0_q = S.l0_lde_bb(log_n, Q, SHIFT)
        apows = ext_powers_table(alpha, total_alpha_terms)
        lk_ctx = (
            lookups, R_args, width, num_partials,
            tuple(tuple(c) for c in chunks),
            Cg, Ct, W, Kc, M, total_alpha_terms,
        )
        sweep_ctx = (
            tuple(assembly.gates),
            tuple(tuple(p) for p in setup.selector_paths),
            geometry, lk_ctx,
            tuple(int(k) for k in setup.non_residues),
        )
        acc = backend.sweep(
            assembly, sweep_ctx,
            (wit_q, setup_q, s2_q, zs_q, xs_q, l0_q, zh_inv_q, apows,
             _ext_np(beta), _ext_np(gamma), _ext_np(lookup_beta),
             _ext_np(lookup_gamma)),
        )  # (4, Q*n) — the quotient T over the sweep domain
        t_mono = coset_descale(backend.intt(acc), SHIFT)
        q_mono = np.stack(
            [t_mono[k][i * n:(i + 1) * n]
             for i in range(Q) for k in range(4)]
        )  # (4Q, n)
        q_lde = backend.lde(q_mono, L)
        q_tree = backend.commit(
            np.concatenate([q_lde[:, :half], q_lde[:, half:]]), cap
        )
    t.witness_merkle_tree_cap(q_tree.get_cap())
    _checkpoint(3, "quotient_cap", q_tree.get_cap())
    z_chal = t.get_ext_challenge()
    _checkpoint(3, "z", z_chal)

    # ---- round 4: evaluations at z (and z*omega, 0) ----------------------
    clock.start("round4_evaluations")
    all_mono = np.concatenate([wit_mono, setup_mono, s2_mono, q_mono])
    B_all = all_mono.shape[0]
    zpows = ext_powers_table(z_chal, n)
    values_at_z = [eval_base_at_ext(all_mono[i], zpows)
                   for i in range(B_all)]
    zw = tuple(bb.mul_s(int(c), omega) for c in z_chal)
    zwpows = ext_powers_table(zw, n)
    values_at_z_omega = [eval_base_at_ext(s2_mono[i], zwpows)
                         for i in range(4)]
    ab4_off = 4 + 4 * num_partials
    values_at_0 = [
        tuple(int(s2_mono[ab4_off + 4 * i + k][0]) for k in range(4))
        for i in range(num_lk)
    ]
    for v in values_at_z:
        _abs_ext(t, v)
    for v in values_at_z_omega:
        _abs_ext(t, v)
    for v in values_at_0:
        _abs_ext(t, v)
    _checkpoint(
        4, "evaluations", [values_at_z, values_at_z_omega, values_at_0]
    )
    deep_ch = t.get_ext_challenge()
    _checkpoint(4, "deep_challenge", deep_ch)

    # ---- round 5: DEEP + FRI ---------------------------------------------
    clock.start("round5_deep_fri")
    num_deep_terms = B_all + 4 + num_lk + num_pi
    ch_tbl = ext_powers_table(deep_ch, num_deep_terms)
    at_z = bb.ZERO_S
    for i in range(B_all):
        ch = tuple(int(ch_tbl[k, i]) for k in range(4))
        at_z = bb.ext_add_s(at_z, bb.ext_mul_s(ch, values_at_z[i]))
    xs_lde = K.domain_xs_bb(log_n, L, SHIFT)
    all_lde = np.concatenate([wit_lde, setup_lde, s2_lde, q_lde])
    pi_rows = [r for (_c, r, _v) in assembly.public_inputs]
    pi_cols = (
        np.stack([wit_lde[c] for (c, _r, _v) in assembly.public_inputs])
        if num_pi else np.zeros((0, N), dtype=np.uint32)
    )
    pi_inv = (
        np.stack([
            K._host_batch_inv(
                bb.sub_np(xs_lde, np.uint32(bb.pow_s(omega, r)))
            )
            for r in pi_rows
        ])
        if num_pi else np.zeros((0, N), dtype=np.uint32)
    )
    lk_cols = (
        s2_lde[ab4_off:ab4_off + 4 * num_lk]
        if num_lk else np.zeros((0, N), dtype=np.uint32)
    )
    y_zw = np.array(values_at_z_omega, dtype=np.uint32).T  # (4 comps, 4)
    y_lk = (
        np.array(values_at_0, dtype=np.uint32)
        if num_lk else np.zeros((0, 4), dtype=np.uint32)
    )
    with _span("bb_deep"):
        h = backend.deep(
            all_lde, s2_lde[:4], lk_cols, pi_cols, xs_lde,
            _ext_np(z_chal), _ext_np(zw), ch_tbl, _ext_np(at_z),
            y_zw, y_lk,
            np.array(pi_values, dtype=np.uint32), pi_inv,
            num_lk, num_pi,
        )  # (4, N)

    num_fri_rounds = (n // config.fri_final_degree).bit_length() - 1
    assert num_fri_rounds >= 1, "fri_final_degree leaves nothing to fold"
    fold_tables = K.fri_fold_tables_bb(log_full, SHIFT, num_fri_rounds)
    fri_trees, fri_layers, cur = [], [], h
    with _span("bb_fri"):
        for r in range(num_fri_rounds):
            fri_layers.append(cur)
            tree = backend.commit(
                _fri_pair_cols(cur), min(cap, cur.shape[-1] // 2)
            )
            fri_trees.append(tree)
            t.witness_merkle_tree_cap(tree.get_cap())
            _checkpoint(5, f"fri_cap_{r}", tree.get_cap())
            ch = t.get_ext_challenge()
            _checkpoint(5, f"fri_challenge_{r}", ch)
            cur = backend.fri_fold(cur, _ext_np(ch), fold_tables[r])
        final_mono = coset_descale(
            backend.intt(cur), bb.pow_s(SHIFT, 1 << num_fri_rounds)
        )
    final_fri_monomials = [
        tuple(int(final_mono[k][i]) for k in range(4))
        for i in range(config.fri_final_degree)
    ]
    for c in final_fri_monomials:
        _abs_ext(t, c)
    _checkpoint(5, "fri_final_monomials", final_fri_monomials)
    pow_nonce = blake2s_pow_grind(t, config.pow_bits)
    _checkpoint(5, "pow_nonce", [pow_nonce])

    # ---- queries ----------------------------------------------------------
    clock.start("queries")
    bs = BitSource(log_full, challenge_bits=BB_SPEC.challenge_bits)
    idxs = [bs.get_index(t, log_full) for _ in range(config.num_queries)]
    _checkpoint(5, "query_indices", idxs)

    paired = {
        "witness": np.concatenate([wit_lde[:, :half], wit_lde[:, half:]]),
        "stage2": np.concatenate([s2_lde[:, :half], s2_lde[:, half:]]),
        "quotient": np.concatenate([q_lde[:, :half], q_lde[:, half:]]),
        "setup": np.concatenate([setup_lde[:, :half],
                                 setup_lde[:, half:]]),
    }
    trees = {
        "witness": wit_tree, "stage2": s2_tree,
        "quotient": q_tree, "setup": setup.setup_tree,
    }

    def _oracle_query(name, j0):
        cols = paired[name]
        return OracleQuery(
            leaf_values=[int(x) for x in cols[:, j0]],
            path=trees[name].get_path(j0),
        )

    queries = []
    for pos in idxs:
        j0 = pos % half
        fri_qs = []
        p = pos
        for r in range(num_fri_rounds):
            layer = fri_layers[r]
            h_r = layer.shape[-1] // 2
            leaf = p % h_r
            fri_qs.append(
                OracleQuery(
                    leaf_values=[
                        int(layer[k][leaf + off])
                        for off in (0, h_r) for k in range(4)
                    ],
                    path=fri_trees[r].get_path(leaf),
                )
            )
            p %= h_r
        queries.append(
            SingleRoundQueries(
                witness=_oracle_query("witness", j0),
                stage2=_oracle_query("stage2", j0),
                quotient=_oracle_query("quotient", j0),
                setup=_oracle_query("setup", j0),
                fri=fri_qs,
            )
        )

    return Proof(
        public_inputs=pi_values,
        witness_cap=wit_tree.get_cap(),
        stage2_cap=s2_tree.get_cap(),
        quotient_cap=q_tree.get_cap(),
        values_at_z=values_at_z,
        values_at_z_omega=values_at_z_omega,
        values_at_0=values_at_0,
        fri_caps=[tr.get_cap() for tr in fri_trees],
        final_fri_monomials=final_fri_monomials,
        queries=queries,
        pow_challenge=pow_nonce,
        config={
            "fri_lde_factor": L,
            "quotient_degree": Q,
            "merkle_tree_cap_size": cap,
            "num_queries": config.num_queries,
            "pow_bits": config.pow_bits,
            "fri_final_degree": config.fri_final_degree,
            "field": "babybear",
        },
    )


# ---------------------------------------------------------------------------
# Quotient identity self-check at z (the mini-verifier acceptance leg)
# ---------------------------------------------------------------------------


def _replay_challenges(assembly, setup, proof):
    """Re-derive every drawn challenge by replaying the transcript from
    the proof's own contents (exactly what a verifier does)."""
    cfg = proof.config
    t = make_transcript(setup.vk.transcript)
    t.witness_merkle_tree_cap(setup.vk.setup_merkle_cap)
    t.witness_field_elements([int(v) for v in proof.public_inputs])
    t.witness_merkle_tree_cap(proof.witness_cap)
    out = {"beta": t.get_ext_challenge(), "gamma": t.get_ext_challenge()}
    if assembly.lookups_enabled:
        out["lookup_beta"] = t.get_ext_challenge()
        out["lookup_gamma"] = t.get_ext_challenge()
    t.witness_merkle_tree_cap(proof.stage2_cap)
    out["alpha"] = t.get_ext_challenge()
    t.witness_merkle_tree_cap(proof.quotient_cap)
    out["z"] = t.get_ext_challenge()
    for v in proof.values_at_z:
        _abs_ext(t, v)
    for v in proof.values_at_z_omega:
        _abs_ext(t, v)
    for v in proof.values_at_0:
        _abs_ext(t, v)
    out["deep"] = t.get_ext_challenge()
    return out


def quotient_identity_at_z(assembly, setup, proof) -> bool:
    """acc(z) == T(z) * (z^n - 1): reconstruct the alpha-weighted
    constraint accumulator at z from the proof's openings via
    `BBExtScalarOps` (the SAME gate evaluators the sweep ran, now over
    GF(p^4) scalars) and compare against the committed quotient
    recombined at z. This is the verifier-side half of the quotient
    protocol, used as the full-prover self-check."""
    from ..cs.field_like import BBExtScalarOps as E
    from ..cs.gates.base import TermsCollector

    n = assembly.trace_len
    log_n = n.bit_length() - 1
    geometry = assembly.geometry
    Cg = assembly.copy_placement.shape[0]
    Ct = Cg + assembly.num_lookup_cols
    W = assembly.wit_placement.shape[0]
    lookups = assembly.lookups_enabled
    R_args = assembly.num_lookup_subargs
    Kc = geometry.num_constant_columns + (1 if lookups else 0)
    width = assembly.lookup_params.width if lookups else 0
    Q = setup.vk.effective_quotient_degree()
    M = 1 if lookups else 0
    omega = bb.omega(log_n)
    chs = _replay_challenges(assembly, setup, proof)
    z = tuple(int(c) for c in chs["z"])
    vz = [tuple(int(c) for c in v) for v in proof.values_at_z]
    B_wit = Ct + W + M
    B_setup = Ct + Kc + ((width + 1) if lookups else 0)
    wit_z = vz[:B_wit]
    setup_z = vz[B_wit:B_wit + B_setup]
    s2_z = vz[B_wit + B_setup:len(vz) - 4 * Q]
    q_z = vz[len(vz) - 4 * Q:]
    sigma_z = setup_z[:Ct]
    const_z = setup_z[Ct:Ct + Kc]
    table_z = setup_z[Ct + Kc:]
    # ext helpers over the opened 4-tuples
    z_pow_n = bb.ext_pow_s(z, n)
    zh_z = bb.ext_sub_s(z_pow_n, bb.ONE_S)
    chunks = chunk_columns(Ct, geometry.max_allowed_constraint_degree)
    num_partials = len(chunks) - 1
    z_v = _group_ext(s2_z, 0)
    partial_v = [_group_ext(s2_z, 1 + j) for j in range(num_partials)]
    zw_v = [tuple(int(c) for c in v) for v in proof.values_at_z_omega]
    z_shift_v = _recombine_ext_cols(zw_v)
    total_alpha_terms = (
        num_gate_sweep_terms(assembly) + 1 + len(chunks)
        + ((R_args + 1) if lookups else 0)
    )
    apows = [bb.ONE_S]
    alpha = tuple(int(c) for c in chs["alpha"])
    for _ in range(total_alpha_terms - 1):
        apows.append(bb.ext_mul_s(apows[-1], alpha))
    ap_it = iter(apows)
    acc = bb.ZERO_S

    class _Row:
        def __init__(self, vo, wo, ko):
            self.vo, self.wo, self.ko = vo, wo, ko

        def v(self, i):
            return wit_z[self.vo + i]

        def w(self, i):
            return wit_z[Ct + self.wo + i]

        def c(self, i):
            return const_z[self.ko + i]

    for gid, gate in enumerate(assembly.gates):
        if gate.num_terms == 0:
            continue
        path = setup.selector_paths[gid]
        sel = bb.ONE_S
        for b, bit in enumerate(path):
            f = (const_z[b] if bit
                 else bb.ext_sub_s(bb.ONE_S, const_z[b]))
            sel = bb.ext_mul_s(sel, f)
        gate_acc = bb.ZERO_S
        for inst in range(gate.num_repetitions(geometry)):
            row = _Row(
                inst * gate.principal_width,
                inst * gate.witness_width, len(path),
            )
            dst = TermsCollector()
            gate.evaluate(E, row, dst)
            for term in dst.terms:
                gate_acc = bb.ext_add_s(
                    gate_acc, bb.ext_mul_s(term, next(ap_it))
                )
        acc = bb.ext_add_s(acc, bb.ext_mul_s(gate_acc, sel))
    # copy permutation
    l0_z = bb.ext_mul_s(
        zh_z,
        bb.ext_inv_s(
            bb.ext_scale_s(bb.ext_sub_s(z, bb.ONE_S), n)
        ),
    )
    t0 = bb.ext_mul_s(l0_z, bb.ext_sub_s(z_v, bb.ONE_S))
    acc = bb.ext_add_s(acc, bb.ext_mul_s(t0, next(ap_it)))
    lhs_seq = partial_v + [z_shift_v]
    rhs_seq = [z_v] + partial_v
    for j, chunk in enumerate(chunks):
        num_p = den_p = bb.ONE_S
        for col in chunk:
            kx = bb.ext_scale_s(z, int(setup.non_residues[col]))
            num = bb.ext_add_s(
                bb.ext_add_s(
                    wit_z[col],
                    bb.ext_mul_s(tuple(int(c) for c in chs["beta"]), kx),
                ),
                tuple(int(c) for c in chs["gamma"]),
            )
            den = bb.ext_add_s(
                bb.ext_add_s(
                    wit_z[col],
                    bb.ext_mul_s(
                        tuple(int(c) for c in chs["beta"]), sigma_z[col]
                    ),
                ),
                tuple(int(c) for c in chs["gamma"]),
            )
            num_p = bb.ext_mul_s(num_p, num)
            den_p = bb.ext_mul_s(den_p, den)
        term = bb.ext_sub_s(
            bb.ext_mul_s(lhs_seq[j], den_p),
            bb.ext_mul_s(rhs_seq[j], num_p),
        )
        acc = bb.ext_add_s(acc, bb.ext_mul_s(term, next(ap_it)))
    if lookups:
        lkb = tuple(int(c) for c in chs["lookup_beta"])
        lkg = tuple(int(c) for c in chs["lookup_gamma"])
        gpow = [bb.ONE_S]
        for _ in range(width):
            gpow.append(bb.ext_mul_s(gpow[-1], lkg))
        ab_off = 1 + num_partials
        tid_z = const_z[Kc - 1]
        for i in range(R_args):
            den = lkb
            for j in range(width):
                den = bb.ext_add_s(
                    den,
                    bb.ext_mul_s(wit_z[Cg + i * width + j], gpow[j]),
                )
            den = bb.ext_add_s(den, bb.ext_mul_s(tid_z, gpow[width]))
            a_i = _group_ext(s2_z, ab_off + i)
            term = bb.ext_sub_s(bb.ext_mul_s(a_i, den), bb.ONE_S)
            acc = bb.ext_add_s(acc, bb.ext_mul_s(term, next(ap_it)))
        t_den = lkb
        for j in range(width):
            t_den = bb.ext_add_s(
                t_den, bb.ext_mul_s(table_z[j], gpow[j])
            )
        t_den = bb.ext_add_s(
            t_den, bb.ext_mul_s(table_z[width], gpow[width])
        )
        b_v = _group_ext(s2_z, ab_off + R_args)
        term = bb.ext_sub_s(
            bb.ext_mul_s(b_v, t_den), wit_z[Ct + W]
        )
        acc = bb.ext_add_s(acc, bb.ext_mul_s(term, next(ap_it)))
    # T(z): recombine the 4Q committed base columns
    w_basis = [
        tuple(1 if k == i else 0 for k in range(4)) for i in range(4)
    ]
    t_z = bb.ZERO_S
    zn_pow = bb.ONE_S
    for i in range(Q):
        chunk_v = bb.ZERO_S
        for k in range(4):
            chunk_v = bb.ext_add_s(
                chunk_v, bb.ext_mul_s(w_basis[k], q_z[4 * i + k])
            )
        t_z = bb.ext_add_s(t_z, bb.ext_mul_s(chunk_v, zn_pow))
        zn_pow = bb.ext_mul_s(zn_pow, z_pow_n)
    return acc == bb.ext_mul_s(t_z, zh_z)


def _group_ext(vals, idx):
    """4 consecutive opened base-column values (each a 4-tuple at z) of
    ext poly `idx` -> the poly's ext value: sum_k w^k * col_k(z)."""
    out = bb.ZERO_S
    for k in range(4):
        basis = tuple(1 if j == k else 0 for j in range(4))
        out = bb.ext_add_s(out, bb.ext_mul_s(basis, vals[4 * idx + k]))
    return out


def _recombine_ext_cols(cols4):
    out = bb.ZERO_S
    for k in range(4):
        basis = tuple(1 if j == k else 0 for j in range(4))
        out = bb.ext_add_s(out, bb.ext_mul_s(basis, cols4[k]))
    return out
