"""Limb-domain algebra for the quotient sweep: GF(p^2), powers, Horner.

`field/limbs.py` is the core Goldilocks algebra on `(lo, hi)` uint32 pairs —
the representation Mosaic accepts and XLA can fuse. This module is the
limb-domain ALGEBRA SURFACE layered on top of it (ISSUE 4): extension-field
helpers, power/horner supplies, boundary conversions, and the accumulate /
aggregate term combinators mirroring `prover/stages.py` — all in uint32 so
the SAME code runs inside Pallas kernels and as plain XLA. The sweep
kernels (`prover/pallas_sweep.py`) consume the combinators and broadcast
helpers directly; the power/horner/conversion primitives are the
kernel-side toolkit for stages that move limb-domain later (challenge
tables currently ride SMEM, computed outside the kernels) — every op here,
consumed or not yet, is pinned u64<->limb bit-exact by
tests/test_limb_sweep.py, so the surface cannot drift from goldilocks.py.

Conventions: a BASE element is a `(lo, hi)` pair of same-shape uint32
arrays; an EXT element of GF(p^2) = GF(p)[w]/(w^2 - 7) is a `(c0, c1)`
pair of base elements. Field ops are exact mod p and keep values
canonical, so any evaluation order produces bit-identical results to the
u64 path — parity is pinned per-op in tests/test_limb_sweep.py.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from . import gl
from . import limbs
from .limbs import add, double, ext_add, ext_mul, ext_sub, mul, neg, sqr, sub

NON_RESIDUE = 7


# ---------------------------------------------------------------------------
# Broadcast helpers
# ---------------------------------------------------------------------------


def zeros_like(a):
    """Base-field zero with `a`'s shape (`a` a limb pair or uint32 array)."""
    ref = a[0] if isinstance(a, tuple) else a
    z = jnp.zeros_like(ref)
    return z, z


def ones_like(a):
    ref = a[0] if isinstance(a, tuple) else a
    return jnp.ones_like(ref), jnp.zeros_like(ref)


def full_like(a, value: int):
    """A python-int field constant broadcast to `a`'s shape."""
    ref = a[0] if isinstance(a, tuple) else a
    clo, chi = limbs.const_pair(value)
    return jnp.full_like(ref, clo), jnp.full_like(ref, chi)


# ---------------------------------------------------------------------------
# Base-field extras
# ---------------------------------------------------------------------------


def mul_small(a, k: int):
    """Multiply by a small constant via modular double-and-add (mirrors
    goldilocks.mul_small; cheap on the VPU — no 16-bit product split)."""
    assert 0 <= k
    if k == 0:
        return zeros_like(a)
    acc = None
    addend = a
    while k:
        if k & 1:
            acc = addend if acc is None else add(acc, addend)
        k >>= 1
        if k:
            addend = double(addend)
    return acc


def powers(base, count: int):
    """[1, b, ..., b^(count-1)] as a python list of limb pairs (traced
    scalar chain — the limb counterpart of stages._ext_powers_traced's
    base-field half)."""
    assert count >= 1
    out = [ones_like(base)]
    for _ in range(count - 1):
        out.append(mul(out[-1], base))
    return out


def horner(coeffs, x):
    """Σ_j coeffs[j]·x^j by Horner's rule over limb pairs (coeffs[0] is the
    constant term). Exact mod p, so it matches the powers-table form
    bit-for-bit."""
    acc = coeffs[-1]
    for c in reversed(coeffs[:-1]):
        acc = add(mul(acc, x), c)
    return acc


# ---------------------------------------------------------------------------
# GF(p^2) extras (ext_add / ext_sub / ext_mul live in limbs.py)
# ---------------------------------------------------------------------------


def ext_neg(a):
    return neg(a[0]), neg(a[1])


def ext_sqr(a):
    return ext_mul(a, a)


def ext_mul_by_base(a, b):
    """Ext element `a` times base element `b`."""
    return mul(a[0], b), mul(a[1], b)


def ext_powers(base, count: int):
    """[1, g, ..., g^(count-1)] as a python list of ext limb elements."""
    assert count >= 1
    out = [(ones_like(base[0]), zeros_like(base[0]))]
    for _ in range(count - 1):
        out.append(ext_mul(out[-1], base))
    return out


def ext_horner(coeffs, x):
    """Σ_j coeffs[j]·x^j over ext limb elements."""
    acc = coeffs[-1]
    for c in reversed(coeffs[:-1]):
        acc = ext_add(ext_mul(acc, x), c)
    return acc


# ---------------------------------------------------------------------------
# Quotient-sweep combinators (stages.py counterparts, limb domain)
# ---------------------------------------------------------------------------


def accumulate(acc, term_base, ch):
    """acc += ch * term for a BASE-field term and ext challenge ch
    (stages.accumulate_ext)."""
    t0 = mul(term_base, ch[0])
    t1 = mul(term_base, ch[1])
    if acc is None:
        return (t0, t1)
    return add(acc[0], t0), add(acc[1], t1)


def ext_accumulate(acc, term_ext, ch):
    """acc += ch * term for an EXT term (stages.accumulate_ext_ext)."""
    t = ext_mul(term_ext, ch)
    if acc is None:
        return t
    return ext_add(acc, t)


def aggregate_columns(cols, table_id_col, gpow, beta):
    """Σ_j γ^j·col_j (+ γ^w·table_id) + β over base limb columns -> ext
    (stages.aggregate_lookup_columns). `gpow` is a list of ext elements
    [1, γ, γ², …] (broadcastable), `beta` an ext element."""
    like = cols[0][0] if isinstance(cols[0], tuple) else cols[0]
    acc0 = (
        jnp.broadcast_to(beta[0][0], like.shape),
        jnp.broadcast_to(beta[0][1], like.shape),
    )
    acc1 = (
        jnp.broadcast_to(beta[1][0], like.shape),
        jnp.broadcast_to(beta[1][1], like.shape),
    )
    seq = list(cols) + ([table_id_col] if table_id_col is not None else [])
    for j, col in enumerate(seq):
        acc0 = add(acc0, mul(col, gpow[j][0]))
        acc1 = add(acc1, mul(col, gpow[j][1]))
    return acc0, acc1


# ---------------------------------------------------------------------------
# Inversion (ISSUE 10: the resident prover's denominators/fold tables stay
# in limb planes end-to-end, so the Montgomery trick needs a limb form).
# Inverses are unique mod p and every op here is exact+canonical, so values
# are bit-identical to the u64 goldilocks.batch_inverse family.
# ---------------------------------------------------------------------------


def pow_int(a, e: int):
    """a ** e for a python-int exponent (square-and-multiply chain)."""
    e = int(e)
    assert e >= 0
    result = None
    base = a
    while e:
        if e & 1:
            result = base if result is None else mul(result, base)
        e >>= 1
        if e:
            base = sqr(base)
    if result is None:
        return ones_like(a)
    return result


def inv(a):
    """Fermat inverse a^(p-2) on a limb pair; inverse of 0 is 0."""
    return pow_int(a, gl.P - 2)


def prefix_product(a):
    """Inclusive modular prefix product along the last axis (log-doubling
    Hillis–Steele, the goldilocks.prefix_product twin on planes)."""
    lo, hi = a
    n = lo.shape[-1]
    shift = 1
    while shift < n:
        pad_lo = jnp.ones(lo.shape[:-1] + (shift,), jnp.uint32)
        pad_hi = jnp.zeros(hi.shape[:-1] + (shift,), jnp.uint32)
        shifted = (
            jnp.concatenate([pad_lo, lo[..., :-shift]], axis=-1),
            jnp.concatenate([pad_hi, hi[..., :-shift]], axis=-1),
        )
        lo, hi = mul((lo, hi), shifted)
        shift *= 2
    return lo, hi


def batch_inverse(a):
    """Montgomery batch inversion along the last axis on limb planes
    (two prefix-product passes + ONE Fermat inversion)."""
    lo, hi = a
    prefix = prefix_product(a)
    total_inv = inv((prefix[0][..., -1:], prefix[1][..., -1:]))
    rev = (jnp.flip(lo, axis=-1), jnp.flip(hi, axis=-1))
    rev_prefix = prefix_product(rev)
    suffix = (
        jnp.concatenate(
            [jnp.flip(rev_prefix[0][..., :-1], axis=-1),
             jnp.ones_like(lo[..., :1])], axis=-1,
        ),
        jnp.concatenate(
            [jnp.flip(rev_prefix[1][..., :-1], axis=-1),
             jnp.zeros_like(hi[..., :1])], axis=-1,
        ),
    )
    shifted_prefix = (
        jnp.concatenate(
            [jnp.ones_like(lo[..., :1]), prefix[0][..., :-1]], axis=-1
        ),
        jnp.concatenate(
            [jnp.zeros_like(hi[..., :1]), prefix[1][..., :-1]], axis=-1
        ),
    )
    return mul(mul(total_inv, suffix), shifted_prefix)


def ext_batch_inverse(a):
    """GF(p^2) batch inversion on ext limb elements (extension.batch_inverse
    twin): 1/(c0 + c1 w) = (c0 - c1 w) / (c0² - 7 c1²)."""
    d = sub(sqr(a[0]), mul_small(sqr(a[1]), NON_RESIDUE))
    dinv = batch_inverse(d)
    return mul(a[0], dinv), neg(mul(a[1], dinv))


# top-level jit boundaries for the inversions (same posture as
# goldilocks.batch_inverse / extension.batch_inverse: the Fermat chain
# inlined into large XLA:CPU modules has miscompiled — keep it separate)
batch_inverse_jit = jax.jit(batch_inverse)
ext_batch_inverse_jit = jax.jit(ext_batch_inverse)


# ---------------------------------------------------------------------------
# u64-boundary conversions for ext pairs (stage seams only)
# ---------------------------------------------------------------------------


def ext_split(a_u64_pair):
    """(c0, c1) uint64 arrays -> ext limb element."""
    return limbs.split(a_u64_pair[0]), limbs.split(a_u64_pair[1])


def ext_join(a_limb_ext):
    """Ext limb element -> (c0, c1) uint64 arrays."""
    return limbs.join(a_limb_ext[0]), limbs.join(a_limb_ext[1])


def const_ext(c0: int, c1: int = 0):
    """Host ints -> ext element of numpy uint32 scalar pairs (bakeable)."""
    return limbs.const_pair(c0 % gl.P), limbs.const_pair(c1 % gl.P)
